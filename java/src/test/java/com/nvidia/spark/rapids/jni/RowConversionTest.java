/*
 * Device-path round trip of the 8-column reference table through the
 * restored reference signatures — the JUnit shape of the reference's
 * RowConversionTest (reference RowConversionTest.java:28-59), retargeted
 * at the TPU runtime bridge. The same table and assertions also run
 * without a JVM via src/native/src/rt_selftest.cpp (the CI gate in images
 * without a JDK; this test is wired for environments that have one).
 *
 * Run with: ai.rapids.tpudf.python.path pointing at the runtime package
 * (or TPUDF_PY_PATH env), libtpudf_rt.so on java.library.path.
 */

package com.nvidia.spark.rapids.jni;

import static org.junit.jupiter.api.Assertions.assertArrayEquals;
import static org.junit.jupiter.api.Assertions.assertEquals;

import ai.rapids.cudf.ColumnVector;
import ai.rapids.cudf.DType;
import ai.rapids.cudf.Table;
import java.nio.ByteBuffer;
import java.nio.ByteOrder;
import org.junit.jupiter.api.Test;

public class RowConversionTest {

  private static byte[] longs(long... vals) {
    ByteBuffer b = ByteBuffer.allocate(vals.length * 8)
        .order(ByteOrder.LITTLE_ENDIAN);
    for (long v : vals) {
      b.putLong(v);
    }
    return b.array();
  }

  private static byte[] doubles(double... vals) {
    ByteBuffer b = ByteBuffer.allocate(vals.length * 8)
        .order(ByteOrder.LITTLE_ENDIAN);
    for (double v : vals) {
      b.putDouble(v);
    }
    return b.array();
  }

  private static byte[] ints(int... vals) {
    ByteBuffer b = ByteBuffer.allocate(vals.length * 4)
        .order(ByteOrder.LITTLE_ENDIAN);
    for (int v : vals) {
      b.putInt(v);
    }
    return b.array();
  }

  private static byte[] floats(float... vals) {
    ByteBuffer b = ByteBuffer.allocate(vals.length * 4)
        .order(ByteOrder.LITTLE_ENDIAN);
    for (float v : vals) {
      b.putFloat(v);
    }
    return b.array();
  }

  @Test
  void fixedWidthRowsRoundTrip() {
    byte[] tailNull = new byte[] {1, 1, 1, 1, 1, 0};
    byte[][] inputData = new byte[][] {
        longs(3, 9, 4, 2, 20, 0),
        doubles(5.0, 9.5, 0.9, 7.23, 2.8, 0.0),
        ints(5, 1, 0, 2, 7, 0),
        new byte[] {1, 0, 0, 1, 0, 0},
        floats(1.0f, 3.5f, 5.9f, 7.1f, 9.8f, 0.0f),
        new byte[] {2, 3, 4, 5, 9, 0},
        ints(5000, 9500, 900, 7230, 2800, 0),
        longs(300000000L, 900000000L, 400000000L, 200000000L, 2000000000L, 0),
    };
    ColumnVector[] cols = new ColumnVector[] {
        ColumnVector.fromHost(DType.INT64, 6, longs(3, 9, 4, 2, 20, 0),
            tailNull),
        ColumnVector.fromHost(DType.FLOAT64, 6,
            doubles(5.0, 9.5, 0.9, 7.23, 2.8, 0.0), tailNull),
        ColumnVector.fromHost(DType.INT32, 6, ints(5, 1, 0, 2, 7, 0),
            tailNull),
        ColumnVector.fromHost(DType.BOOL8, 6,
            new byte[] {1, 0, 0, 1, 0, 0}, tailNull),
        ColumnVector.fromHost(DType.FLOAT32, 6,
            floats(1.0f, 3.5f, 5.9f, 7.1f, 9.8f, 0.0f), tailNull),
        ColumnVector.fromHost(DType.INT8, 6,
            new byte[] {2, 3, 4, 5, 9, 0}, tailNull),
        ColumnVector.fromHost(DType.create(DType.DTypeEnum.DECIMAL32, -3), 6,
            ints(5000, 9500, 900, 7230, 2800, 0), tailNull),
        ColumnVector.fromHost(DType.create(DType.DTypeEnum.DECIMAL64, -8), 6,
            longs(300000000L, 900000000L, 400000000L, 200000000L,
                2000000000L, 0),
            tailNull),
    };
    try (Table t = new Table(cols)) {
      ColumnVector[] rows = RowConversion.convertToRows(t);
      try {
        // We didn't overflow
        assertEquals(1, rows.length);
        assertEquals(t.getRowCount(), rows[0].getRowCount());
        DType[] types = new DType[t.getNumberOfColumns()];
        for (int i = 0; i < t.getNumberOfColumns(); i++) {
          types[i] = t.getColumn(i).getType();
        }
        try (Table backAgain = RowConversion.convertFromRows(rows[0], types)) {
          assertEquals(t.getRowCount(), backAgain.getRowCount());
          for (int i = 0; i < t.getNumberOfColumns(); i++) {
            ColumnVector back = backAgain.getColumn(i);
            assertEquals(t.getColumn(i).getType(), back.getType());
            byte[] validity = new byte[6];
            int elem = 8;
            DType.DTypeEnum id = back.getType().getTypeId();
            if (id == DType.DTypeEnum.INT32 || id == DType.DTypeEnum.FLOAT32
                || id == DType.DTypeEnum.DECIMAL32) {
              elem = 4;
            } else if (id == DType.DTypeEnum.BOOL8
                || id == DType.DTypeEnum.INT8) {
              elem = 1;
            }
            byte[] data = new byte[6 * elem];
            back.copyToHost(data, validity);
            assertArrayEquals(tailNull, validity, "column " + i);
            // valid rows' bytes must survive exactly (row 5 is null:
            // its payload is unspecified, cuDF semantics)
            for (int r = 0; r < 5; r++) {
              for (int b = 0; b < elem; b++) {
                assertEquals(inputData[i][r * elem + b], data[r * elem + b],
                    "column " + i + " row " + r + " byte " + b);
              }
            }
          }
        }
      } finally {
        for (ColumnVector cv : rows) {
          cv.close();
        }
      }
    } finally {
      for (ColumnVector cv : cols) {
        cv.close();
      }
    }
  }
}
