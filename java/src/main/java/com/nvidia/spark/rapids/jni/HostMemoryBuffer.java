/*
 * Minimal off-heap host buffer — the role ai.rapids.cudf.HostMemoryBuffer
 * plays in the reference's API signatures (reference ParquetFooter.java:19,
 * 82-95 takes one as the footer byte source). Address + length + explicit
 * close, nothing more; allocation is native so the address is stable for
 * JNI calls.
 */

package com.nvidia.spark.rapids.jni;

public class HostMemoryBuffer implements AutoCloseable {
  static {
    NativeDepsLoader.loadNativeDeps();
  }

  private long address;
  private final long length;

  private HostMemoryBuffer(long address, long length) {
    this.address = address;
    this.length = length;
  }

  public static HostMemoryBuffer allocate(long bytes) {
    long addr = hostAlloc(bytes);
    if (addr == 0) {
      throw new OutOfMemoryError("host allocation of " + bytes + " bytes failed");
    }
    return new HostMemoryBuffer(addr, bytes);
  }

  public long getAddress() {
    if (address == 0) {
      throw new IllegalStateException("buffer is closed");
    }
    return address;
  }

  public long getLength() {
    return length;
  }

  public void setBytes(long offset, byte[] src) {
    if (offset < 0 || offset + src.length > length) {
      throw new IndexOutOfBoundsException();
    }
    copyIn(getAddress() + offset, src);
  }

  public byte[] getBytes(long offset, int count) {
    if (offset < 0 || offset + count > length) {
      throw new IndexOutOfBoundsException();
    }
    return copyOut(getAddress() + offset, count);
  }

  @Override
  public synchronized void close() {
    if (address != 0) {
      hostFree(address);
      address = 0;
    }
  }

  private static native long hostAlloc(long bytes);
  private static native void hostFree(long address);
  private static native void copyIn(long address, byte[] src);
  private static native byte[] copyOut(long address, int count);
}
