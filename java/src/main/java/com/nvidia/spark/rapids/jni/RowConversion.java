/*
 * Device row-major <-> column-major table conversion — signature-compatible
 * with the reference (reference RowConversion.java:101-121) over the same
 * packed-row byte contract (reference RowConversion.java:40-99): columns
 * size-aligned in schema order, validity bytes (bit col%8 of byte col//8)
 * after the last column, rows padded to 8 bytes, output batched under 2^31
 * bytes with 32-row-multiple batch sizes. All fixed-width types pack,
 * including DECIMAL128: a 16-byte little-endian two's-complement element
 * aligned to 16 bytes — the generic alignment-equals-size rule the
 * reference applies to every cudf::size_of type (reference
 * row_conversion.cu:439-443,462-468) — supported by BOTH the device
 * (Python/JAX) codec and the host-buffer C codec, cross-validated
 * byte-for-byte.
 *
 * The conversion runs ON DEVICE through the embedded TPU runtime
 * (libtpudf_rt -> spark_rapids_jni_tpu.ops.row_conversion), crossing JNI as
 * jlong handles exactly like the reference's CUDA path (reference
 * RowConversionJni.cpp:24-41). The host-buffer codec variant lives in
 * HostRowConversion (the Spark UnsafeRow handoff).
 */

package com.nvidia.spark.rapids.jni;

import ai.rapids.cudf.ColumnVector;
import ai.rapids.cudf.ColumnView;
import ai.rapids.cudf.DType;
import ai.rapids.cudf.Table;
import ai.rapids.cudf.TpuRuntime;

public final class RowConversion {
  static {
    TpuRuntime.ensureInitialized();
  }

  private RowConversion() {}

  /**
   * Convert a device table to packed rows: one or more LIST<INT8>-shaped
   * row columns, each under 2GB (reference RowConversion.java:101-108).
   */
  public static ColumnVector[] convertToRows(Table table) {
    long[] ptrs = convertToRows(table.getNativeView());
    ColumnVector[] ret = new ColumnVector[ptrs.length];
    for (int i = 0; i < ptrs.length; i++) {
      ret[i] = new ColumnVector(ptrs[i]);
    }
    return ret;
  }

  /**
   * Convert packed rows back to a device table with the given column types
   * (reference RowConversion.java:110-121).
   */
  public static Table convertFromRows(ColumnView vec, DType... schema) {
    int[] types = new int[schema.length];
    int[] scale = new int[schema.length];
    for (int i = 0; i < schema.length; i++) {
      types[i] = schema[i].getTypeId().getNativeId();
      scale[i] = schema[i].getScale();
    }
    return new Table(convertFromRows(vec.getNativeView(), types, scale));
  }

  private static native long[] convertToRows(long nativeHandle);

  private static native long[] convertFromRows(long nativeColumnView,
      int[] types, int[] scale);
}
