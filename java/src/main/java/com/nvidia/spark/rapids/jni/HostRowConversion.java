/*
 * Row-major <-> column-major conversion, host side — API parity with the
 * reference's RowConversion (reference RowConversion.java:101-121) over the
 * same packed-row byte contract (reference RowConversion.java:40-99):
 * size-aligned columns in schema order, validity bytes (bit col%8 of byte
 * col//8) after the last column, rows padded to 8 bytes.
 *
 * This JVM surface packs/unpacks HOST buffers through the native codec
 * (src/native/src/row_conversion.cpp) — the Spark-side UnsafeRow handoff.
 * The device-resident conversion runs in the TPU runtime
 * (spark_rapids_jni_tpu/ops/row_conversion.py) over the identical layout;
 * the two are cross-validated byte-for-byte in the Python test suite.
 */

package com.nvidia.spark.rapids.jni;

public final class HostRowConversion {
  static {
    NativeDepsLoader.loadNativeDeps();
  }

  private HostRowConversion() {}

  /** One fixed-width column resident in host buffers. */
  public static final class HostColumn {
    final HostMemoryBuffer data;
    final HostMemoryBuffer validity;  // one byte per row, 1 = valid; or null
    final int elementSize;            // 1, 2, 4 or 8

    public HostColumn(HostMemoryBuffer data, HostMemoryBuffer validity,
        int elementSize) {
      this.data = data;
      this.validity = validity;
      this.elementSize = elementSize;
    }
  }

  /** Row size in bytes for a schema of element sizes (layout probe). */
  public static int rowSize(int[] elementSizes) {
    return rowSizeNative(elementSizes);
  }

  /**
   * Pack columns into rows. Returns a buffer of numRows * rowSize bytes.
   * Fixed-width columns only, matching the reference's restriction
   * (reference row_conversion.cu:515).
   */
  public static HostMemoryBuffer convertToRows(HostColumn[] columns,
      long numRows) {
    int n = columns.length;
    long[] data = new long[n];
    long[] valid = new long[n];
    int[] sizes = new int[n];
    for (int i = 0; i < n; i++) {
      data[i] = columns[i].data.getAddress();
      valid[i] = columns[i].validity == null ? 0
          : columns[i].validity.getAddress();
      sizes[i] = columns[i].elementSize;
    }
    long rowSize = rowSizeNative(sizes);
    HostMemoryBuffer out = HostMemoryBuffer.allocate(numRows * rowSize);
    toRowsNative(data, valid, sizes, numRows, out.getAddress());
    return out;
  }

  /**
   * Unpack rows into caller-allocated columns (data and validity buffers
   * must be sized numRows*elementSize and numRows respectively; the packed
   * form always carries validity, reference row_conversion.cu:551-555).
   */
  public static void convertFromRows(HostMemoryBuffer rows, long numRows,
      HostColumn[] columns) {
    int n = columns.length;
    long[] data = new long[n];
    long[] valid = new long[n];
    int[] sizes = new int[n];
    for (int i = 0; i < n; i++) {
      data[i] = columns[i].data.getAddress();
      valid[i] = columns[i].validity.getAddress();
      sizes[i] = columns[i].elementSize;
    }
    fromRowsNative(rows.getAddress(), numRows, sizes, data, valid);
  }

  private static native int rowSizeNative(int[] elementSizes);

  private static native void toRowsNative(long[] data, long[] valid,
      int[] sizes, long numRows, long outAddress);

  private static native void fromRowsNative(long rowsAddress, long numRows,
      int[] sizes, long[] data, long[] valid);
}
