/*
 * Parquet footer prune/filter — API parity with the reference's
 * ParquetFooter (reference ParquetFooter.java:40-113): an AutoCloseable
 * wrapper over a native footer handle with the same depth-first flattened
 * (names, numChildren) schema-request contract
 * (reference ParquetFooter.java:66-95). The native side is
 * src/native/src/parquet_footer.cpp via the tpudf C ABI.
 */

package com.nvidia.spark.rapids.jni;

public class ParquetFooter implements AutoCloseable {
  static {
    NativeDepsLoader.loadNativeDeps();
  }

  private long handle;

  private ParquetFooter(long handle) {
    this.handle = handle;
  }

  /**
   * Parse a footer from host memory, prune it to the requested column tree,
   * and filter row groups to the partition split [partOffset,
   * partOffset+partLength). Column names and child counts are flattened
   * depth-first, root excluded — the reference's request encoding
   * (reference ParquetFooter.java:66-81).
   */
  public static ParquetFooter readAndFilter(HostMemoryBuffer buffer,
      long partOffset, long partLength, String[] names, int[] numChildren,
      int parentNumChildren, boolean ignoreCase) {
    long h = readAndFilterNative(buffer.getAddress(), buffer.getLength(),
        partOffset, partLength, names, numChildren, parentNumChildren,
        ignoreCase);
    return new ParquetFooter(h);
  }

  /** Re-serialize as a PAR1-framed thrift file into a fresh host buffer. */
  public HostMemoryBuffer serializeThriftFile() {
    byte[] bytes = serializeNative(checkHandle());
    HostMemoryBuffer out = HostMemoryBuffer.allocate(bytes.length);
    out.setBytes(0, bytes);
    return out;
  }

  public long getNumRows() {
    return numRowsNative(checkHandle());
  }

  public int getNumColumns() {
    return numColumnsNative(checkHandle());
  }

  @Override
  public synchronized void close() {
    if (handle != 0) {
      closeNative(handle);
      handle = 0;
    }
  }

  private long checkHandle() {
    if (handle == 0) {
      throw new IllegalStateException("footer is closed");
    }
    return handle;
  }

  private static native long readAndFilterNative(long address, long length,
      long partOffset, long partLength, String[] names, int[] numChildren,
      int parentNumChildren, boolean ignoreCase);

  private static native byte[] serializeNative(long handle);

  private static native long numRowsNative(long handle);

  private static native int numColumnsNative(long handle);

  private static native void closeNative(long handle);
}
