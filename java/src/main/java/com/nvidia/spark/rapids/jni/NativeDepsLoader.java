/*
 * Native library bootstrap for the TPU build.
 *
 * Role parity with the loader the reference classes invoke in their static
 * initializers (reference RowConversion.java:23-25, ParquetFooter.java:25-27;
 * per-platform .so packaging scheme at reference pom.xml:385-421): find
 * libtpudf.so — explicit path, jar resource, or build tree — extract if
 * needed, System.load once.
 */

package com.nvidia.spark.rapids.jni;

import java.io.File;
import java.io.IOException;
import java.io.InputStream;
import java.nio.file.Files;
import java.nio.file.Path;
import java.nio.file.StandardCopyOption;

public final class NativeDepsLoader {
  private static final String LIB_NAME = "tpudf_jni";
  private static boolean loaded = false;

  private NativeDepsLoader() {}

  public static synchronized void loadNativeDeps() {
    if (loaded) {
      return;
    }
    String explicit = System.getProperty("spark.rapids.tpu.nativeLib");
    if (explicit == null) {
      explicit = System.getenv("SPARK_RAPIDS_TPU_JNI_LIB");
    }
    if (explicit != null) {
      System.load(explicit);
      loaded = true;
      return;
    }
    String resource = "/" + System.getProperty("os.arch") + "/"
        + System.getProperty("os.name") + "/lib" + LIB_NAME + ".so";
    try (InputStream in = NativeDepsLoader.class.getResourceAsStream(resource)) {
      if (in != null) {
        Path tmp = Files.createTempFile("lib" + LIB_NAME, ".so");
        tmp.toFile().deleteOnExit();
        Files.copy(in, tmp, StandardCopyOption.REPLACE_EXISTING);
        System.load(tmp.toAbsolutePath().toString());
        loaded = true;
        return;
      }
    } catch (IOException e) {
      throw new ExceptionInInitializerError(e);
    }
    // dev fallback: repo build tree
    File dev = new File("build/native/lib" + LIB_NAME + ".so");
    if (dev.exists()) {
      System.load(dev.getAbsolutePath());
      loaded = true;
      return;
    }
    System.loadLibrary(LIB_NAME);
    loaded = true;
  }
}
