/*
 * Non-owning view of a device column — the ai.rapids.cudf.ColumnView role:
 * the handle an API call reads without taking ownership (reference
 * RowConversion.java:110 takes a ColumnView for convertFromRows). Handles
 * are int64 keys into the native runtime's registry (libtpudf_rt), the
 * same jlong-pointer convention as the reference JNI layer
 * (reference RowConversionJni.cpp:31,36).
 */

package ai.rapids.cudf;

public class ColumnView implements AutoCloseable {
  protected long handle;

  ColumnView(long handle) {
    this.handle = handle;
  }

  public final long getNativeView() {
    return handle;
  }

  public final long getRowCount() {
    return getRowCountNative(handle);
  }

  public final DType getType() {
    return DType.fromNative(getTypeIdNative(handle), getScaleNative(handle));
  }

  @Override
  public void close() {
    if (handle != 0) {
      freeNative(handle);
      handle = 0;
    }
  }

  static native long getRowCountNative(long handle);

  static native int getTypeIdNative(long handle);

  static native int getScaleNative(long handle);

  static native void freeNative(long handle);
}
