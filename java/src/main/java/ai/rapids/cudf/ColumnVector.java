/*
 * Owning device column — the ai.rapids.cudf.ColumnVector subset the row
 * conversion path needs: constructed from a native handle released by a
 * native call (reference RowConversion.java:103-107 wraps the jlong array
 * returned by convertToRows), AutoCloseable ownership, host round-trip
 * helpers for tests.
 */

package ai.rapids.cudf;

public final class ColumnVector extends ColumnView {
  static {
    TpuRuntime.ensureInitialized();
  }

  /** Takes ownership of a handle released by a native call. */
  public ColumnVector(long nativeHandle) {
    super(nativeHandle);
  }

  /**
   * Build a fixed-width device column from host bytes (little-endian data,
   * one validity byte per row or null for all-valid) — the TestBuilder-
   * style entry tests use.
   */
  public static ColumnVector fromHost(DType type, long rows, byte[] data,
      byte[] validity) {
    long h = fromHostNative(type.getTypeId().getNativeId(), type.getScale(),
        rows, data, validity);
    return new ColumnVector(h);
  }

  /** Copy the column back to host: data bytes and per-row validity bytes. */
  public void copyToHost(byte[] dataOut, byte[] validityOut) {
    copyToHostNative(handle, dataOut, validityOut);
  }

  static native long fromHostNative(int typeId, int scale, long rows,
      byte[] data, byte[] validity);

  static native void copyToHostNative(long handle, byte[] dataOut,
      byte[] validityOut);
}
