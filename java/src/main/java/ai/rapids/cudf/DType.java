/*
 * Column data type — the ai.rapids.cudf.DType subset the Spark plugin's
 * row-conversion path touches (reference RowConversion.java:110-121
 * marshals each column as (typeId.getNativeId(), getScale()) pairs).
 * Native ids follow the cuDF type_id enum (branch-22.06 ordering), the
 * same table as the Python side's types.TypeId.
 */

package ai.rapids.cudf;

public final class DType {
  public enum DTypeEnum {
    EMPTY(0), INT8(1), INT16(2), INT32(3), INT64(4),
    UINT8(5), UINT16(6), UINT32(7), UINT64(8),
    FLOAT32(9), FLOAT64(10), BOOL8(11),
    TIMESTAMP_DAYS(12), TIMESTAMP_SECONDS(13), TIMESTAMP_MILLISECONDS(14),
    TIMESTAMP_MICROSECONDS(15), TIMESTAMP_NANOSECONDS(16),
    DURATION_DAYS(17), DURATION_SECONDS(18), DURATION_MILLISECONDS(19),
    DURATION_MICROSECONDS(20), DURATION_NANOSECONDS(21),
    DICTIONARY32(22), STRING(23), LIST(24),
    DECIMAL32(25), DECIMAL64(26), DECIMAL128(27), STRUCT(28);

    private final int nativeId;

    DTypeEnum(int nativeId) {
      this.nativeId = nativeId;
    }

    public int getNativeId() {
      return nativeId;
    }
  }

  public static final DType INT8 = new DType(DTypeEnum.INT8, 0);
  public static final DType INT16 = new DType(DTypeEnum.INT16, 0);
  public static final DType INT32 = new DType(DTypeEnum.INT32, 0);
  public static final DType INT64 = new DType(DTypeEnum.INT64, 0);
  public static final DType FLOAT32 = new DType(DTypeEnum.FLOAT32, 0);
  public static final DType FLOAT64 = new DType(DTypeEnum.FLOAT64, 0);
  public static final DType BOOL8 = new DType(DTypeEnum.BOOL8, 0);
  public static final DType STRING = new DType(DTypeEnum.STRING, 0);
  public static final DType TIMESTAMP_DAYS =
      new DType(DTypeEnum.TIMESTAMP_DAYS, 0);

  private final DTypeEnum typeId;
  private final int scale;

  private DType(DTypeEnum typeId, int scale) {
    this.typeId = typeId;
    this.scale = scale;
  }

  public DTypeEnum getTypeId() {
    return typeId;
  }

  /** cuDF convention: value = unscaled * 10^scale (usually negative). */
  public int getScale() {
    return scale;
  }

  public static DType create(DTypeEnum id) {
    return new DType(id, 0);
  }

  public static DType create(DTypeEnum id, int scale) {
    return new DType(id, scale);
  }

  public static DType fromNative(int nativeId, int scale) {
    for (DTypeEnum e : DTypeEnum.values()) {
      if (e.getNativeId() == nativeId) {
        return new DType(e, scale);
      }
    }
    throw new IllegalArgumentException("unknown native type id " + nativeId);
  }

  @Override
  public boolean equals(Object o) {
    if (!(o instanceof DType)) {
      return false;
    }
    DType d = (DType) o;
    return d.typeId == typeId && d.scale == scale;
  }

  @Override
  public int hashCode() {
    return typeId.ordinal() * 31 + scale;
  }

  @Override
  public String toString() {
    return typeId + (scale != 0 ? "(scale=" + scale + ")" : "");
  }
}
