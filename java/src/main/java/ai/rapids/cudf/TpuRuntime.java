/*
 * Device-runtime bootstrap — the NativeDepsLoader + CUDA-context-init role
 * of the reference (reference RowConversion.java:23-25: loadNativeDeps in a
 * static initializer; first cudf call initializes the CUDA context). Here
 * the first API touch loads libtpudf_rt.so and initializes the embedded
 * CPython/JAX runtime that owns the TPU (architecture decision documented
 * in spark_rapids_jni_tpu/runtime/bridge.py).
 *
 * Configuration (system properties, the reference's config idiom,
 * reference pom.xml:435-438):
 *   ai.rapids.tpudf.python.path — ':'-separated sys.path entries for the
 *       runtime package (defaults to TPUDF_PY_PATH env).
 *   ai.rapids.tpudf.platform    — "" (default: TPU when present) or "cpu".
 */

package ai.rapids.cudf;

import com.nvidia.spark.rapids.jni.NativeDepsLoader;

public final class TpuRuntime {
  private static volatile boolean initialized = false;

  private TpuRuntime() {}

  public static void ensureInitialized() {
    if (!initialized) {
      synchronized (TpuRuntime.class) {
        if (!initialized) {
          NativeDepsLoader.loadNativeDeps();
          String path = System.getProperty("ai.rapids.tpudf.python.path",
              System.getenv().getOrDefault("TPUDF_PY_PATH", ""));
          String platform = System.getProperty("ai.rapids.tpudf.platform", "");
          initNative(path, platform);
          initialized = true;
        }
      }
    }
  }

  static native void initNative(String sysPath, String platform);
}
