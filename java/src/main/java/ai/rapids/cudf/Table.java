/*
 * Device table — the ai.rapids.cudf.Table subset the Spark plugin's JNI
 * kernels consume: an ordered set of equal-length device columns behind a
 * jlong native view (reference RowConversion.java:101-108 passes
 * table.getNativeView() across JNI; RowConversionJni.cpp:31 reinterprets
 * it). Constructed from column handles the way the reference builds a
 * Table from the jlong array a native call returns
 * (reference RowConversion.java:120 `new Table(handles)`).
 */

package ai.rapids.cudf;

public final class Table implements AutoCloseable {
  static {
    TpuRuntime.ensureInitialized();
  }

  private long handle;
  private final ColumnVector[] columns;
  private final boolean ownsColumns;

  /** Takes ownership of column handles released by a native call. */
  public Table(long[] columnHandles) {
    this.columns = new ColumnVector[columnHandles.length];
    this.ownsColumns = true;
    for (int i = 0; i < columnHandles.length; i++) {
      this.columns[i] = new ColumnVector(columnHandles[i]);
    }
    try {
      this.handle = createTable(columnHandles);
    } catch (RuntimeException e) {
      for (ColumnVector c : this.columns) {
        c.close();
      }
      throw e;
    }
  }

  /**
   * Build from caller-owned columns. cuDF convention: the caller keeps
   * ownership of its vectors and closes them itself; this table's close()
   * only releases the table handle.
   */
  public Table(ColumnVector[] columns) {
    this.columns = columns.clone();
    this.ownsColumns = false;
    long[] handles = new long[columns.length];
    for (int i = 0; i < columns.length; i++) {
      handles[i] = columns[i].getNativeView();
    }
    this.handle = createTable(handles);
  }

  public long getNativeView() {
    return handle;
  }

  public long getRowCount() {
    return getRowCountNative(handle);
  }

  public int getNumberOfColumns() {
    return columns.length;
  }

  public ColumnVector getColumn(int index) {
    return columns[index];
  }

  @Override
  public void close() {
    if (handle != 0) {
      freeNative(handle);
      handle = 0;
    }
    if (ownsColumns) {
      for (ColumnVector c : columns) {
        c.close();
      }
    }
  }

  static native long createTable(long[] columnHandles);

  static native long getRowCountNative(long handle);

  static native void freeNative(long handle);
}
