"""Compatibility shim: the q1 kernel moved into the maintained Pallas
kernel tier (ops/pallas/q1.py), where it is registered with its XLA
bit-identity oracle and routed through dispatch.call. Import from
``spark_rapids_jni_tpu.ops.pallas.q1`` in new code."""

from spark_rapids_jni_tpu.ops.pallas.q1 import (  # noqa: F401
    _q1_kernel,
    _q1_pallas_partials,
    tpch_q1_pallas,
)

__all__ = ["tpch_q1_pallas"]
