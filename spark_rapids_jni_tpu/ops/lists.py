"""LIST-column operators: explode/posexplode and collect_list/collect_set.

cuDF ships ``explode``/``explode_position`` and the ``collect_list``/
``collect_set`` groupby aggregations as part of the vendored capability
surface (SURVEY.md section 2.2 — libcudf columnar engine; Spark lowers
``explode()``, ``posexplode()``, ``collect_list()``, ``collect_set()``
straight onto them). The TPU designs here are scatter-free:

- ``explode``: each output slot finds its parent row with ONE searchsorted
  against the per-row start positions, then gathers. Inner and outer
  explode share the mechanism — outer adds one slot for every empty/null
  list (start = offsets + running empty count), which reproduces Spark's
  exact interleaved row order with static shapes (output padded to the
  worst case, ``row_valid`` reports the live slots).
- ``groupby_collect``: stable key sort + one boolean argsort compacts each
  group's kept values into a dense child in input order; list offsets are
  a cumsum of per-group keep counts. ``distinct=True`` re-sorts by
  (keys, value) and keeps first occurrences — set semantics with
  value-ordered output (Spark's collect_set leaves order unspecified).

Null semantics are Spark's: collect_list/collect_set SKIP null values and
return EMPTY lists (never null) for groups with no kept values; explode
drops null/empty lists, explode_outer emits one all-null row for them.
"""

from __future__ import annotations

from typing import NamedTuple, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from spark_rapids_jni_tpu.columnar import Column, Table
from spark_rapids_jni_tpu.ops.groupby import (
    _dense_group_bounds,
    _gather_group_keys,
    _rows_equal_prev,
    _col_values_equal_prev,
)
from spark_rapids_jni_tpu.ops.sort import gather, sort_order
from spark_rapids_jni_tpu.types import DType, TypeId
from spark_rapids_jni_tpu.utils.tracing import func_range


def make_list_column(values: Sequence, element_dtype: DType) -> Column:
    """Host-side LIST builder from ``[[...], None, [...]]`` pylists (the
    test/ingest convenience mirroring ``Column.from_pylist``)."""
    import numpy as np

    offsets = np.zeros(len(values) + 1, dtype=np.int32)
    flat: list = []
    valid = np.ones(len(values), dtype=bool)
    for i, v in enumerate(values):
        if v is None:
            valid[i] = False
            offsets[i + 1] = offsets[i]
        else:
            flat.extend(v)
            offsets[i + 1] = offsets[i] + len(v)
    child = Column.from_pylist(flat, element_dtype)
    return Column(
        DType(TypeId.LIST), jnp.asarray(offsets),
        None if valid.all() else jnp.asarray(valid),
        children=[child],
    )


class ExplodeResult(NamedTuple):
    table: Table              # exploded rows, padded to the static bound
    row_valid: jnp.ndarray    # bool[out_n]: live output slots
    num_rows: jnp.ndarray     # scalar int64 true output row count


def _gather_any(c: Column, idx: jnp.ndarray, extra_valid) -> Column:
    """Gather a non-LIST column at ``idx`` with extra invalidation."""
    valid = c.valid_mask()[idx] & extra_valid
    if c.dtype.is_string:
        from spark_rapids_jni_tpu.ops import strings as s

        g = s.gather_strings(c, idx)
        return Column(c.dtype, g.data, valid, chars=g.chars)
    return Column(c.dtype, c.data[idx], valid)


@func_range("explode")
def explode(table: Table, col_idx: int, *, outer: bool = False,
            position: bool = False) -> ExplodeResult:
    """Explode the LIST column ``col_idx``: one output row per element,
    the other columns repeated, in Spark's exact interleaved order.

    ``outer=True`` (Spark ``explode_outer``) keeps rows whose list is
    empty or null as a single row with a null element. ``position=True``
    (Spark ``posexplode``) inserts an INT32 0-based position column just
    before the element column. Output is padded to the static worst case
    (child length, + row count when outer); ``row_valid`` marks live
    slots and ``num_rows`` is the true count.
    """
    lc = table.column(col_idx)
    if lc.dtype.type_id != TypeId.LIST:
        raise TypeError(f"explode needs a LIST column, got {lc.dtype}")
    child = lc.children[0]
    if child.dtype.type_id == TypeId.LIST:
        raise NotImplementedError("explode of nested LIST-of-LIST")
    n = lc.size
    offsets = lc.data.astype(jnp.int64)
    list_valid = lc.valid_mask()
    # treat null lists as length 0 (they contribute rows only under outer)
    lens = jnp.where(list_valid, offsets[1:] - offsets[:-1], 0)
    starts_inner = jnp.concatenate(
        [jnp.zeros((1,), jnp.int64), jnp.cumsum(lens)])
    if outer:
        empty = (lens == 0).astype(jnp.int64)
        starts = starts_inner + jnp.concatenate(
            [jnp.zeros((1,), jnp.int64), jnp.cumsum(empty)])
    else:
        starts = starts_inner
    total = starts[-1]
    out_n = int(child.size) + (n if outer else 0)
    k = jnp.arange(out_n, dtype=jnp.int64)
    parent = jnp.clip(
        jnp.searchsorted(starts, k, side="right") - 1, 0, max(n - 1, 0)
    ).astype(jnp.int32)
    j = k - starts[parent]
    live = k < total
    has_elem = live & (j < lens[parent])
    # element index into the ORIGINAL child buffer (null lists have
    # lens == 0, so has_elem is False and the clipped index is unused)
    eidx = jnp.clip(offsets[parent] + j, 0,
                    max(int(child.size) - 1, 0)).astype(jnp.int32)
    out_cols: list[Column] = []
    for ci in range(table.num_columns):
        if ci == col_idx:
            if position:
                out_cols.append(Column(
                    DType(TypeId.INT32), j.astype(jnp.int32), has_elem))
            out_cols.append(_gather_any(child, eidx, has_elem))
        else:
            c = table.column(ci)
            if c.dtype.type_id in (TypeId.LIST, TypeId.STRUCT):
                raise NotImplementedError(
                    "explode alongside other nested columns")
            out_cols.append(_gather_any(c, parent, live))
    return ExplodeResult(Table(out_cols), live, total)


class CollectResult(NamedTuple):
    table: Table              # keys then ONE LIST column, padded to m rows
    num_groups: jnp.ndarray   # scalar int32


@func_range("groupby_collect")
def groupby_collect(table: Table, keys: Sequence[int], value_col: int,
                    *, distinct: bool = False) -> CollectResult:
    """collect_list (``distinct=False``) / collect_set (``distinct=True``)
    of ``value_col`` grouped by ``keys``.

    The LIST child holds every kept value, groups concatenated in key
    order; offsets are the cumsum of per-group keep counts. Groups with
    no kept values get EMPTY lists (Spark returns [] here, not null).
    Output is padded to n rows like groupby_aggregate; callers trim with
    ``num_groups`` (the child is likewise padded — ``to_pylist`` only
    reads below each list's offsets).
    """
    c_check = table.column(value_col)
    if c_check.dtype.type_id in (TypeId.LIST, TypeId.STRUCT):
        raise NotImplementedError("collect of nested columns")
    n = table.num_rows
    m = n
    sub = Table([table.column(k) for k in keys] + [table.column(value_col)])
    kix = list(range(len(keys)))
    vix = len(keys)
    if distinct:
        order = sort_order(sub, kix + [vix],
                           nulls_first=[True] * len(keys) + [False])
    else:
        order = sort_order(sub, kix)
    ssub = gather(sub, order)
    same = _rows_equal_prev(ssub, kix)
    if n:
        gid = (jnp.cumsum(~same) - 1).astype(jnp.int32)
    else:
        gid = None
    num_groups, g_lo, g_hi = _dense_group_bounds(gid, n, m)
    first_idx = jnp.where(g_hi > g_lo, g_lo, n)
    out_cols = _gather_group_keys(ssub, kix, first_idx, m, n)

    vc = ssub.column(vix)
    keep = vc.valid_mask()
    if distinct and n:
        # drop repeats of the same value within a group (values are
        # adjacent after the secondary sort — the nunique flag idiom)
        eqv = _col_values_equal_prev(vc)
        prev_same_valid = jnp.concatenate(
            [jnp.zeros((1,), jnp.bool_), eqv & keep[:-1]])
        keep = keep & (~same | ~prev_same_valid)
    if n:
        pref = jnp.cumsum(keep.astype(jnp.int64))
        pref0 = jnp.concatenate([jnp.zeros((1,), jnp.int64), pref])
        counts = pref0[g_hi] - pref0[g_lo]
        # kept rows first (stable) — their sorted order IS group order,
        # so the compacted prefix is exactly the dense child
        comp = jnp.argsort(~keep, stable=True).astype(jnp.int32)
        child = _gather_any(vc, comp, jnp.bool_(True))
    else:
        counts = jnp.zeros((m,), jnp.int64)
        child = vc
    offsets = jnp.concatenate(
        [jnp.zeros((1,), jnp.int64), jnp.cumsum(counts)]
    ).astype(jnp.int32)
    garange = jnp.arange(m, dtype=jnp.int32)
    out_cols.append(Column(
        DType(TypeId.LIST), offsets, garange < num_groups,
        children=[child],
    ))
    return CollectResult(Table(out_cols), num_groups)


@func_range("array_size")
def array_size(col: Column) -> Column:
    """Spark ``size``/``cardinality``: element count per list; null
    lists give null (ANSI) — the caller can map null->-1 for legacy."""
    if col.dtype.type_id != TypeId.LIST:
        raise TypeError(f"array_size needs a LIST column, got {col.dtype}")
    lens = (col.data[1:] - col.data[:-1]).astype(jnp.int32)
    return Column(DType(TypeId.INT32), lens,
                  col.valid_mask() if col.validity is not None else None)


@func_range("array_contains")
def array_contains(col: Column, value) -> Column:
    """Spark ``array_contains(list, value)``: per-row ANY over the
    child — a prefix-difference count over the flat child matches, no
    per-row loops. Three-valued logic matches Spark's ArrayContains:
    TRUE when found; NULL when not found but the list has a null
    element (the null might have been the value); FALSE otherwise; a
    null list is null."""
    if col.dtype.type_id != TypeId.LIST:
        raise TypeError(
            f"array_contains needs a LIST column, got {col.dtype}")
    child = col.children[0]
    if child.dtype.is_decimal128:
        hit = _scalar_d128_hit(child, value)
    elif child.dtype.is_string:
        hit = _scalar_string_hit(child, value)
    else:
        hit = (child.data == value) & child.valid_mask()

    found = _range_any(hit, col.data)
    has_null_elem = _range_any(~child.valid_mask(), col.data)
    from spark_rapids_jni_tpu.types import BOOL8

    validity = col.valid_mask() & (found | ~has_null_elem)
    return Column(BOOL8, found.astype(jnp.uint8), validity)


@func_range("element_at")
def element_at(col: Column, k: int) -> Column:
    """Spark ``element_at(list, k)``: 1-based; negative k counts from
    the end; out-of-bounds gives null (non-ANSI posture)."""
    if col.dtype.type_id != TypeId.LIST:
        raise TypeError(f"element_at needs a LIST column, got {col.dtype}")
    if k == 0:
        raise ValueError("element_at index is 1-based (k != 0)")
    child = col.children[0]
    off = col.data.astype(jnp.int32)
    lens = off[1:] - off[:-1]
    if k > 0:
        pos = off[:-1] + (k - 1)
        in_b = k <= lens
    else:
        pos = off[1:] + k
        in_b = -k <= lens
    valid = in_b & col.valid_mask()
    src = jnp.clip(pos, 0, max(int(child.size) - 1, 0))
    return _gather_any(child, src, valid)


@func_range("array_join")
def array_join(col: Column, sep: str,
               null_replacement: str | None = None) -> Column:
    """Spark ``array_join``: concatenate STRING list elements with
    ``sep``; null elements are skipped unless ``null_replacement``."""
    if col.dtype.type_id != TypeId.LIST:
        raise TypeError(f"array_join needs a LIST column, got {col.dtype}")
    child = col.children[0]
    if not child.dtype.is_string:
        raise TypeError("array_join needs LIST<STRING>")
    # host-assembled (ragged concatenation has no fixed-width form that
    # beats the explode->concat_ws chain; columns needing device joins
    # should explode + groupby_collect instead)
    vals = col.to_pylist()
    out = []
    for lst in vals:
        if lst is None:
            out.append(None)
            continue
        parts = []
        for v in lst:
            if v is None:
                if null_replacement is not None:
                    parts.append(null_replacement)
            else:
                parts.append(v)
        out.append(sep.join(parts))
    from spark_rapids_jni_tpu import types as t

    return Column.from_pylist(out, t.STRING)


def _scalar_string_hit(child: Column, value) -> jnp.ndarray:
    """bool[child_n]: child string elements equal to the scalar value
    (padded compare; absent when longer than the padded width)."""
    from spark_rapids_jni_tpu.ops import strings as s

    p = s.pad_strings(child)
    vb = str(value).encode()
    w = p.chars.shape[1]
    if len(vb) > w:
        return jnp.zeros((int(child.size),), jnp.bool_)
    target = jnp.zeros((w,), jnp.uint8).at[:len(vb)].set(
        jnp.asarray(bytearray(vb), dtype=jnp.uint8))
    return ((p.data == len(vb))
            & jnp.all(p.chars == target[None, :], axis=1)
            & p.valid_mask())


def _scalar_d128_hit(child: Column, value) -> jnp.ndarray:
    """bool[child_n]: DECIMAL128 elements equal to the Python-int
    unscaled ``value`` (two's-complement limb split)."""
    v = int(value)
    lo = jnp.int64(np.int64(np.uint64(v & 0xFFFFFFFFFFFFFFFF)))
    hi = jnp.int64(v >> 64)
    return ((child.data[:, 0] == lo) & (child.data[:, 1] == hi)
            & child.valid_mask())


def _range_any(flags: jnp.ndarray, offsets: jnp.ndarray) -> jnp.ndarray:
    """bool[n]: ANY of ``flags`` within each [offsets[i], offsets[i+1])
    — one cumsum + prefix difference, the shared list-predicate idiom."""
    pref = jnp.concatenate(
        [jnp.zeros((1,), jnp.int64),
         jnp.cumsum(flags.astype(jnp.int64))])
    off = offsets.astype(jnp.int32)
    return (pref[off[1:]] - pref[off[:-1]]) > 0


def _parent_ids(col: Column) -> jnp.ndarray:
    """int32 parent row per child element (searchsorted over offsets —
    the explode idiom). Child slots BEYOND offsets[-1] (the padded tail
    array_distinct/groupby_collect leave behind) get the sentinel parent
    ``n`` so they sort after every real row and match no range query —
    clipping them into the last row would corrupt it."""
    child_n = int(col.children[0].size)
    n = col.size
    off = col.data.astype(jnp.int64)
    k = jnp.arange(child_n, dtype=jnp.int64)
    real = jnp.clip(
        jnp.searchsorted(off, k, side="right") - 1, 0,
        max(n - 1, 0)).astype(jnp.int32)
    return jnp.where(k < off[-1], real, jnp.int32(n))


@func_range("sort_array")
def sort_array(col: Column, ascending: bool = True) -> Column:
    """Spark ``sort_array``: elements sorted within each list (offsets
    unchanged — one segmented sort of (parent, value)). Null elements
    first when ascending, last when descending (Spark's rule)."""
    if col.dtype.type_id != TypeId.LIST:
        raise TypeError(f"sort_array needs a LIST column, got {col.dtype}")
    child = col.children[0]
    parent = _parent_ids(col)
    from spark_rapids_jni_tpu.types import DType as _D, TypeId as _T

    ptbl = Table([
        Column(_D(_T.INT32), parent, None),
        child,
    ])
    order = sort_order(ptbl, [0, 1], ascending=[True, ascending],
                       nulls_first=[True, ascending])
    schild = gather(Table([child]), order).column(0)
    return Column(col.dtype, col.data, col.validity, children=[schild])


@func_range("array_position")
def array_position(col: Column, value) -> Column:
    """Spark ``array_position``: 1-based index of the first element equal
    to ``value``, 0 when absent, null for null lists. Null elements never
    match (no 3VL here — Spark's ArrayPosition returns a position, and
    absent-with-nulls is still 0... matching Spark's non-ANSI behavior:
    it returns null only for null inputs)."""
    if col.dtype.type_id != TypeId.LIST:
        raise TypeError(
            f"array_position needs a LIST column, got {col.dtype}")
    child = col.children[0]
    if child.dtype.is_decimal128:
        hit = _scalar_d128_hit(child, value)
    elif child.dtype.is_string:
        hit = _scalar_string_hit(child, value)
    else:
        hit = (child.data == value) & child.valid_mask()
    child_n = int(child.size)
    k = jnp.arange(child_n, dtype=jnp.int64)
    first_global = jnp.where(hit, k, child_n)
    # per-list min of the hit positions via a cummin prefix difference:
    # positions are globally increasing, so the first hit in [lo, hi) is
    # the min over that range — use a suffix-min then gather at lo
    if child_n:
        suffix_min = jax.lax.cummin(first_global[::-1])[::-1]
        off = col.data.astype(jnp.int32)
        lo = jnp.clip(off[:-1], 0, child_n - 1)
        first_in = jnp.minimum(
            suffix_min[lo],
            jnp.int64(child_n))
        # clamp to the row's own range: a hit belonging to a LATER row
        # must not leak backwards
        in_range = first_in < off[1:]
        pos = jnp.where(in_range & (first_in >= off[:-1]),
                        first_in - off[:-1] + 1, 0)
    else:
        pos = jnp.zeros((col.size,), jnp.int64)
    return Column(DType(TypeId.INT64), pos.astype(jnp.int64),
                  col.valid_mask() if col.validity is not None else None)


@func_range("array_distinct")
def array_distinct(col: Column) -> Column:
    """Spark ``array_distinct``: duplicates removed, FIRST occurrences
    kept in order. Two sorts: (parent, value) marks first occurrences,
    (parent, position) restores order; the kept elements compact into a
    dense child with prefix-sum offsets."""
    if col.dtype.type_id != TypeId.LIST:
        raise TypeError(
            f"array_distinct needs a LIST column, got {col.dtype}")
    child = col.children[0]
    n = col.size
    child_n = int(child.size)
    if child_n == 0:
        return col
    parent = _parent_ids(col)
    from spark_rapids_jni_tpu.types import DType as _D, TypeId as _T

    pcol = Column(_D(_T.INT32), parent, None)
    ptbl = Table([pcol, child])
    order = sort_order(ptbl, [0, 1], nulls_first=[True, True])
    svals = gather(ptbl, order)
    same_parent = svals.column(0).data[1:] == svals.column(0).data[:-1]
    sc = svals.column(1)
    eqv = _col_values_equal_prev(sc)
    v1 = sc.valid_mask()
    both_null = ~v1[1:] & ~v1[:-1]
    same_val = (eqv & v1[1:] & v1[:-1]) | both_null
    dup = jnp.concatenate(
        [jnp.zeros((1,), jnp.bool_), same_parent & same_val])
    # keep flag back in ORIGINAL child positions: keep[order[i]] = ~dup[i]
    # (a gather-free formulation: sort (order) is a permutation, use
    # argsort to invert — one more sort, no scatter)
    inv = jnp.argsort(order).astype(jnp.int32)
    keep = (~dup)[inv]
    counts_pref = jnp.concatenate(
        [jnp.zeros((1,), jnp.int64), jnp.cumsum(keep.astype(jnp.int64))])
    off = col.data.astype(jnp.int32)
    new_off = (counts_pref[off] ).astype(jnp.int32)
    comp = jnp.argsort(~keep, stable=True).astype(jnp.int32)
    new_child = _gather_any(child, comp, jnp.bool_(True))
    return Column(col.dtype, new_off, col.validity, children=[new_child])


@func_range("arrays_overlap")
def arrays_overlap(a: Column, b: Column) -> Column:
    """Spark ``arrays_overlap``: TRUE when the rows' lists share a
    non-null element; NULL when they don't but either side has a null
    element (3VL); FALSE otherwise; null lists give null."""
    for c in (a, b):
        if c.dtype.type_id != TypeId.LIST:
            raise TypeError(
                f"arrays_overlap needs LIST columns, got {c.dtype}")
    ca, cb = a.children[0], b.children[0]
    if ca.dtype != cb.dtype:
        raise TypeError("arrays_overlap needs matching element dtypes")
    if a.size != b.size:
        raise ValueError(
            f"arrays_overlap needs equal row counts, got {a.size} vs "
            f"{b.size}")
    # DECIMAL128 children work unchanged: limb-pair sort keys and the
    # limb-wise equal-prev compare are the same machinery sort/groupby use
    n = a.size
    pa, pb = _parent_ids(a), _parent_ids(b)
    from spark_rapids_jni_tpu.ops.table_ops import concatenate
    from spark_rapids_jni_tpu.types import DType as _D, TypeId as _T

    side_a = Column(_D(_T.INT8),
                    jnp.zeros((int(ca.size),), jnp.int8), None)
    side_b = Column(_D(_T.INT8),
                    jnp.ones((int(cb.size),), jnp.int8), None)
    ta = Table([Column(_D(_T.INT32), pa, None), ca, side_a])
    tb = Table([Column(_D(_T.INT32), pb, None), cb, side_b])
    allt = concatenate([ta, tb])
    order = sort_order(allt, [0, 1, 2], nulls_first=[True, False, True])
    sv = gather(allt, order)
    same_parent = sv.column(0).data[1:] == sv.column(0).data[:-1]
    sc = sv.column(1)
    v1 = sc.valid_mask()
    eqv = _col_values_equal_prev(sc)
    same_valid_val = eqv & v1[1:] & v1[:-1]
    diff_side = sv.column(2).data[1:] != sv.column(2).data[:-1]
    pairhit = same_parent & same_valid_val & diff_side
    # per-parent ANY over adjacent pair hits (prefix-difference count
    # indexed by the sorted parent runs)
    hit_parent = sv.column(0).data[1:]
    total = int(ca.size) + int(cb.size)
    cnt = jnp.zeros((n,), jnp.int64)
    if total > 1:
        pref = jnp.concatenate(
            [jnp.zeros((1,), jnp.int64),
             jnp.cumsum(pairhit.astype(jnp.int64))])
        pr = jnp.arange(n, dtype=jnp.int32)
        lo = jnp.searchsorted(hit_parent, pr, side="left")
        hi = jnp.searchsorted(hit_parent, pr, side="right")
        cnt = pref[hi] - pref[lo]
    overlap = cnt > 0

    # 3VL per Spark's ArraysOverlap: NULL only when there is no common
    # element, BOTH arrays are non-empty, and either contains a null
    def _range_any_nulls(col_l):
        c = col_l.children[0]
        if c.validity is None:
            return jnp.zeros((n,), jnp.bool_)
        return _range_any(~c.valid_mask(), col_l.data)

    def _nonempty(col_l):
        off_ = col_l.data.astype(jnp.int32)
        return off_[1:] > off_[:-1]

    has_null = ((_range_any_nulls(a) | _range_any_nulls(b))
                & _nonempty(a) & _nonempty(b))
    from spark_rapids_jni_tpu.types import BOOL8

    validity = a.valid_mask() & b.valid_mask() & (overlap | ~has_null)
    return Column(BOOL8, overlap.astype(jnp.uint8), validity)


@func_range("sequence")
def sequence(start: Column, stop: Column, step: Column | int = 1,
             max_length: int = 1024) -> Column:
    """Spark ``sequence(start, stop, step)``: one inclusive arithmetic
    range per row as LIST<INT64>.

    HOST-LEVEL generator (not jit-composable: the static child budget
    and Spark's error semantics both need host checks). A row whose
    range exceeds ``max_length`` raises; a step moving AWAY from stop
    raises like Spark's ILLEGAL_SEQUENCE_BOUNDARIES; step 0 is rejected
    up front; null operands give a null row (Spark null propagation)."""
    if isinstance(step, int):
        step_data = jnp.full((start.size,), step, jnp.int64)
        step_valid = jnp.ones((start.size,), jnp.bool_)
    else:
        step_data = step.data.astype(jnp.int64)
        step_valid = step.valid_mask()
    a = start.data.astype(jnp.int64)
    b = stop.data.astype(jnp.int64)
    ok = start.valid_mask() & stop.valid_mask() & step_valid
    # Spark's rule: a zero step is legal ONLY when start == stop (the
    # single-element sequence); otherwise, and for steps moving away
    # from stop, ILLEGAL_SEQUENCE_BOUNDARIES
    zero_ok = (step_data == 0) & (a == b)
    right_dir = jnp.where(step_data > 0, b >= a,
                          jnp.where(step_data < 0, b <= a, a == b))
    if bool(jnp.any(ok & ~right_dir)):
        raise ValueError(
            "sequence step moves away from stop (or is zero with "
            "start != stop) — Spark ILLEGAL_SEQUENCE_BOUNDARIES")
    safe_step = jnp.where(step_data == 0, jnp.int64(1), step_data)
    lens = jnp.where(
        ok & right_dir,
        jnp.where(zero_ok, jnp.int64(1),
                  jnp.floor_divide(b - a, safe_step) + 1),
        jnp.int64(0))
    too_long = bool(jnp.any(lens > max_length))
    if too_long:
        raise ValueError(
            f"sequence row exceeds max_length={max_length} elements; "
            "raise max_length (static child budget)")
    n = start.size
    offsets = jnp.concatenate(
        [jnp.zeros((1,), jnp.int64), jnp.cumsum(lens)]).astype(jnp.int32)
    child_n = n * max_length
    k = jnp.arange(child_n, dtype=jnp.int64)
    parent = jnp.clip(
        jnp.searchsorted(offsets.astype(jnp.int64), k, side="right") - 1,
        0, max(n - 1, 0)).astype(jnp.int32)
    j = k - offsets[parent]
    live = k < offsets[-1]
    vals = a[parent] + j * step_data[parent]
    child = Column(DType(TypeId.INT64),
                   jnp.where(live, vals, 0).astype(jnp.int64),
                   live)
    validity = None if (start.validity is None
                        and stop.validity is None
                        and not isinstance(step, Column)) else ok
    return Column(DType(TypeId.LIST), offsets, validity, children=[child])


def _list_ranges(col: Column):
    off = col.data.astype(jnp.int32)
    return off[:-1], off[1:]


@func_range("array_sum")
def array_sum(col: Column) -> Column:
    """Per-list SUM of numeric elements (nulls skipped; empty/all-null
    lists null — the aggregate posture)."""
    if col.dtype.type_id != TypeId.LIST:
        raise TypeError(f"array_sum needs a LIST column, got {col.dtype}")
    child = col.children[0]
    if child.dtype.is_string or child.dtype.is_decimal128:
        raise TypeError("array_sum needs numeric elements")
    valid = child.valid_mask()
    vv = jnp.where(valid, child.data, jnp.zeros_like(child.data))
    from spark_rapids_jni_tpu.ops.groupby import _sum_dtype

    acc_dt = _sum_dtype(child.dtype)
    acc = vv.astype(jnp.int64) if acc_dt.storage_dtype.kind in ("i", "u") \
        else vv.astype(jnp.float64)
    pref = jnp.concatenate(
        [jnp.zeros((1,), acc.dtype), jnp.cumsum(acc)])
    cpref = jnp.concatenate(
        [jnp.zeros((1,), jnp.int64),
         jnp.cumsum(valid.astype(jnp.int64))])
    lo, hi = _list_ranges(col)
    total = (pref[hi] - pref[lo]).astype(acc_dt.jnp_dtype)
    cnt = cpref[hi] - cpref[lo]
    return Column(acc_dt, total, col.valid_mask() & (cnt > 0))


def _array_extremum(col: Column, op: str) -> Column:
    if col.dtype.type_id != TypeId.LIST:
        raise TypeError(f"array_{op} needs a LIST column, got {col.dtype}")
    child = col.children[0]
    if child.dtype.is_string or child.dtype.is_decimal128:
        raise NotImplementedError(f"array_{op} on non-fixed-width elements")
    child_n = int(child.size)
    n = col.size
    lo, hi = _list_ranges(col)
    if child_n == 0:
        return Column(child.dtype,
                      jnp.zeros((n,), child.dtype.jnp_dtype),
                      jnp.zeros((n,), jnp.bool_))
    import numpy as _np

    dt = child.dtype.storage_dtype
    if dt.kind == "f":
        sentinel = jnp.inf if op == "min" else -jnp.inf
    else:
        info = _np.iinfo(dt)
        sentinel = info.max if op == "min" else info.min
    vv = jnp.where(child.valid_mask(), child.data,
                   jnp.asarray(sentinel, child.data.dtype))
    if dt.kind == "f":
        # Spark orders NaN greatest: array_max with any NaN is NaN,
        # array_min skips NaNs (unless every element is NaN). Map NaN
        # to +inf for the scan, then restore NaN where +inf won
        # (documented ambiguity with a genuine +inf element).
        vv = jnp.where(jnp.isnan(vv), jnp.inf, vv)
    pick = jnp.minimum if op == "min" else jnp.maximum
    # suffix-scan sparse table over the flat child (the rolling-extremum
    # idiom at list granularity): levels cover the longest list
    max_len = int(jnp.max(hi - lo)) if n else 1
    nlev = max(1, max(max_len, 1).bit_length())
    idx = jnp.arange(child_n, dtype=jnp.int32)
    levels = [vv]
    for lev in range(nlev - 1):
        off = 1 << lev
        levels.append(pick(
            levels[-1],
            levels[-1][jnp.clip(idx + off, 0, child_n - 1)]))
    stacked = jnp.stack(levels)
    length = jnp.maximum(hi - lo, 1)
    k = jnp.zeros((n,), jnp.int32)
    for lev in range(1, nlev):
        k = k + (length >= (1 << lev)).astype(jnp.int32)
    span = jnp.left_shift(jnp.int32(1), k)
    c32 = lambda i: jnp.clip(i, 0, child_n - 1).astype(jnp.int32)
    at_lo = stacked[:, c32(lo)]
    at_hi = stacked[:, c32(hi - span)]
    a = jnp.take_along_axis(at_lo, k[None, :], axis=0)[0]
    b = jnp.take_along_axis(at_hi, k[None, :], axis=0)[0]
    out = pick(a, b)
    if dt.kind == "f":
        out = jnp.where(jnp.isinf(out) & (out > 0), jnp.nan, out)
    cnt = _range_any(child.valid_mask(), col.data)
    return Column(child.dtype, out, col.valid_mask() & cnt)


@func_range("array_min")
def array_min(col: Column) -> Column:
    """Per-list MIN (nulls skipped; empty/all-null lists null)."""
    return _array_extremum(col, "min")


@func_range("array_max")
def array_max(col: Column) -> Column:
    return _array_extremum(col, "max")


@func_range("array_slice")
def array_slice(col: Column, start: int, length: int) -> Column:
    """Spark ``slice(arr, start, length)``: 1-based start (negative
    counts from the end — a start beyond the head gives an EMPTY list),
    ``length`` elements. Builds a dense compacted child via the
    explode-style parent mapping (new offsets + one gather)."""
    if col.dtype.type_id != TypeId.LIST:
        raise TypeError(f"array_slice needs a LIST column, got {col.dtype}")
    if start == 0:
        raise ValueError("slice start is 1-based (non-zero)")
    if length < 0:
        raise ValueError("slice length must be >= 0")
    lo, hi = _list_ranges(col)
    lens = hi - lo
    if start > 0:
        s0 = lo + (start - 1)
    else:
        # Spark: a negative start beyond the list head yields an EMPTY
        # slice, not a clamped one
        cand = hi + start
        s0 = jnp.where(cand >= lo, cand, hi)
    s0 = jnp.minimum(s0, hi)
    e0 = jnp.minimum(s0 + length, hi)
    new_lens = jnp.maximum(e0 - s0, 0)
    # rebuild offsets for a COMPACT child: gather kept elements densely
    # (explode-style parent mapping over the kept ranges)
    n = col.size
    new_off = jnp.concatenate(
        [jnp.zeros((1,), jnp.int64),
         jnp.cumsum(new_lens.astype(jnp.int64))])
    child = col.children[0]
    child_n = int(child.size)
    out_n = child_n  # static bound
    k = jnp.arange(out_n, dtype=jnp.int64)
    parent = jnp.clip(
        jnp.searchsorted(new_off, k, side="right") - 1, 0,
        max(n - 1, 0)).astype(jnp.int32)
    j = k - new_off[parent]
    live = k < new_off[-1]
    src = jnp.clip(s0[parent] + j.astype(jnp.int32), 0,
                   max(child_n - 1, 0))
    new_child = _gather_any(child, src, live)
    return Column(col.dtype, new_off.astype(jnp.int32), col.validity,
                  children=[new_child])


# ---------------------------------------------------------------------------
# Padded wire layout for LIST columns (the padded-strings trick
# generalized): data = int32 per-row lengths, children[0] = an element
# column whose data is an (n, L) matrix with (n, L) element validity.
# This is the layout the ICI shuffle ships (every lane is a dense
# row-aligned buffer); offsets-layout lists convert at the boundary.
# ---------------------------------------------------------------------------


def is_padded_list(col: Column) -> bool:
    """Delegates to the Column property (single source of truth: the
    mandatory 2-D element validity is the layout marker)."""
    return col.is_padded_list


def max_list_length(col: Column) -> int:
    """Host-side max list length (0-safe). Only valid outside jit."""
    import numpy as np

    off = np.asarray(col.data)
    if off.shape[0] <= 1:
        return 0
    return int(np.max(off[1:] - off[:-1]))


@func_range("pad_lists")
def pad_lists(col: Column, max_len: int | None = None) -> Column:
    """Offsets layout -> padded wire layout. ``max_len`` must bound every
    row's length (host-computed by default; pass it statically inside
    jit). Plain fixed-width elements only (DECIMAL128 limb pairs would
    need a rank-3 matrix the Column invariants reject; strings-in-lists
    are not wire-supported — explode them instead).

    The (n, L) element validity is MANDATORY in this layout — it is the
    layout marker (see Column.is_padded_list) and carries the element
    null mask; for null-free children it costs one bool lane on the
    wire that could in principle be derived from the lengths, a
    documented trade-off for unambiguous layout detection."""
    if col.dtype.type_id != TypeId.LIST:
        raise TypeError(f"pad_lists needs a LIST column, got {col.dtype}")
    if is_padded_list(col):
        return col
    child = col.children[0]
    if not child.dtype.is_fixed_width or child.dtype.is_string:
        raise NotImplementedError(
            "pad_lists supports plain fixed-width elements only")
    if max_len is None:
        max_len = max_list_length(col)
    L = max(int(max_len), 1)
    off = col.data.astype(jnp.int32)
    lens = off[1:] - off[:-1]
    n = col.size
    child_n = int(child.size)
    j = jnp.arange(L, dtype=jnp.int32)[None, :]
    src = jnp.clip(off[:-1][:, None] + j, 0, max(child_n - 1, 0))
    in_row = j < lens[:, None]
    if child_n:
        mat = child.data[src]
        evalid = child.valid_mask()[src] & in_row
    else:
        shape = (n, L) + child.data.shape[1:]
        mat = jnp.zeros(shape, child.data.dtype)
        evalid = jnp.zeros((n, L), jnp.bool_)
    mat = jnp.where(in_row, mat, jnp.zeros_like(mat))
    elem = Column(child.dtype, mat, evalid)
    return Column(col.dtype, lens.astype(jnp.int32), col.validity,
                  children=[elem])


@func_range("unpad_lists")
def unpad_lists(col: Column) -> Column:
    """Padded wire layout -> offsets layout (dense compacted child via
    the explode-style parent mapping)."""
    if not is_padded_list(col):
        return col
    lens = col.data.astype(jnp.int64)
    elem = col.children[0]
    n, L = int(elem.data.shape[0]), int(elem.data.shape[1])
    offsets = jnp.concatenate(
        [jnp.zeros((1,), jnp.int64), jnp.cumsum(lens)])
    cap = max(n * L, 1)
    k = jnp.arange(cap, dtype=jnp.int64)
    parent = jnp.clip(
        jnp.searchsorted(offsets, k, side="right") - 1, 0,
        max(n - 1, 0)).astype(jnp.int32)
    j = jnp.clip(k - offsets[parent], 0, L - 1).astype(jnp.int32)
    live = k < offsets[-1]
    flatv = elem.data[parent, j]
    flat_valid = elem.valid_mask()[parent, j] & live
    flatv = jnp.where(live, flatv, jnp.zeros_like(flatv))
    child = Column(elem.dtype, flatv, flat_valid)
    return Column(col.dtype, offsets.astype(jnp.int32), col.validity,
                  children=[child])
