"""LIST-column operators: explode/posexplode and collect_list/collect_set.

cuDF ships ``explode``/``explode_position`` and the ``collect_list``/
``collect_set`` groupby aggregations as part of the vendored capability
surface (SURVEY.md section 2.2 — libcudf columnar engine; Spark lowers
``explode()``, ``posexplode()``, ``collect_list()``, ``collect_set()``
straight onto them). The TPU designs here are scatter-free:

- ``explode``: each output slot finds its parent row with ONE searchsorted
  against the per-row start positions, then gathers. Inner and outer
  explode share the mechanism — outer adds one slot for every empty/null
  list (start = offsets + running empty count), which reproduces Spark's
  exact interleaved row order with static shapes (output padded to the
  worst case, ``row_valid`` reports the live slots).
- ``groupby_collect``: stable key sort + one boolean argsort compacts each
  group's kept values into a dense child in input order; list offsets are
  a cumsum of per-group keep counts. ``distinct=True`` re-sorts by
  (keys, value) and keeps first occurrences — set semantics with
  value-ordered output (Spark's collect_set leaves order unspecified).

Null semantics are Spark's: collect_list/collect_set SKIP null values and
return EMPTY lists (never null) for groups with no kept values; explode
drops null/empty lists, explode_outer emits one all-null row for them.
"""

from __future__ import annotations

from typing import NamedTuple, Sequence

import jax.numpy as jnp

from spark_rapids_jni_tpu.columnar import Column, Table
from spark_rapids_jni_tpu.ops.groupby import (
    _dense_group_bounds,
    _gather_group_keys,
    _rows_equal_prev,
    _col_values_equal_prev,
)
from spark_rapids_jni_tpu.ops.sort import gather, sort_order
from spark_rapids_jni_tpu.types import DType, TypeId
from spark_rapids_jni_tpu.utils.tracing import func_range


def make_list_column(values: Sequence, element_dtype: DType) -> Column:
    """Host-side LIST builder from ``[[...], None, [...]]`` pylists (the
    test/ingest convenience mirroring ``Column.from_pylist``)."""
    import numpy as np

    offsets = np.zeros(len(values) + 1, dtype=np.int32)
    flat: list = []
    valid = np.ones(len(values), dtype=bool)
    for i, v in enumerate(values):
        if v is None:
            valid[i] = False
            offsets[i + 1] = offsets[i]
        else:
            flat.extend(v)
            offsets[i + 1] = offsets[i] + len(v)
    child = Column.from_pylist(flat, element_dtype)
    return Column(
        DType(TypeId.LIST), jnp.asarray(offsets),
        None if valid.all() else jnp.asarray(valid),
        children=[child],
    )


class ExplodeResult(NamedTuple):
    table: Table              # exploded rows, padded to the static bound
    row_valid: jnp.ndarray    # bool[out_n]: live output slots
    num_rows: jnp.ndarray     # scalar int64 true output row count


def _gather_any(c: Column, idx: jnp.ndarray, extra_valid) -> Column:
    """Gather a non-LIST column at ``idx`` with extra invalidation."""
    valid = c.valid_mask()[idx] & extra_valid
    if c.dtype.is_string:
        from spark_rapids_jni_tpu.ops import strings as s

        g = s.gather_strings(c, idx)
        return Column(c.dtype, g.data, valid, chars=g.chars)
    return Column(c.dtype, c.data[idx], valid)


@func_range("explode")
def explode(table: Table, col_idx: int, *, outer: bool = False,
            position: bool = False) -> ExplodeResult:
    """Explode the LIST column ``col_idx``: one output row per element,
    the other columns repeated, in Spark's exact interleaved order.

    ``outer=True`` (Spark ``explode_outer``) keeps rows whose list is
    empty or null as a single row with a null element. ``position=True``
    (Spark ``posexplode``) inserts an INT32 0-based position column just
    before the element column. Output is padded to the static worst case
    (child length, + row count when outer); ``row_valid`` marks live
    slots and ``num_rows`` is the true count.
    """
    lc = table.column(col_idx)
    if lc.dtype.type_id != TypeId.LIST:
        raise TypeError(f"explode needs a LIST column, got {lc.dtype}")
    child = lc.children[0]
    if child.dtype.type_id == TypeId.LIST:
        raise NotImplementedError("explode of nested LIST-of-LIST")
    n = lc.size
    offsets = lc.data.astype(jnp.int64)
    list_valid = lc.valid_mask()
    # treat null lists as length 0 (they contribute rows only under outer)
    lens = jnp.where(list_valid, offsets[1:] - offsets[:-1], 0)
    starts_inner = jnp.concatenate(
        [jnp.zeros((1,), jnp.int64), jnp.cumsum(lens)])
    if outer:
        empty = (lens == 0).astype(jnp.int64)
        starts = starts_inner + jnp.concatenate(
            [jnp.zeros((1,), jnp.int64), jnp.cumsum(empty)])
    else:
        starts = starts_inner
    total = starts[-1]
    out_n = int(child.size) + (n if outer else 0)
    k = jnp.arange(out_n, dtype=jnp.int64)
    parent = jnp.clip(
        jnp.searchsorted(starts, k, side="right") - 1, 0, max(n - 1, 0)
    ).astype(jnp.int32)
    j = k - starts[parent]
    live = k < total
    has_elem = live & (j < lens[parent])
    # element index into the ORIGINAL child buffer (null lists have
    # lens == 0, so has_elem is False and the clipped index is unused)
    eidx = jnp.clip(offsets[parent] + j, 0,
                    max(int(child.size) - 1, 0)).astype(jnp.int32)
    out_cols: list[Column] = []
    for ci in range(table.num_columns):
        if ci == col_idx:
            if position:
                out_cols.append(Column(
                    DType(TypeId.INT32), j.astype(jnp.int32), has_elem))
            out_cols.append(_gather_any(child, eidx, has_elem))
        else:
            c = table.column(ci)
            if c.dtype.type_id in (TypeId.LIST, TypeId.STRUCT):
                raise NotImplementedError(
                    "explode alongside other nested columns")
            out_cols.append(_gather_any(c, parent, live))
    return ExplodeResult(Table(out_cols), live, total)


class CollectResult(NamedTuple):
    table: Table              # keys then ONE LIST column, padded to m rows
    num_groups: jnp.ndarray   # scalar int32


@func_range("groupby_collect")
def groupby_collect(table: Table, keys: Sequence[int], value_col: int,
                    *, distinct: bool = False) -> CollectResult:
    """collect_list (``distinct=False``) / collect_set (``distinct=True``)
    of ``value_col`` grouped by ``keys``.

    The LIST child holds every kept value, groups concatenated in key
    order; offsets are the cumsum of per-group keep counts. Groups with
    no kept values get EMPTY lists (Spark returns [] here, not null).
    Output is padded to n rows like groupby_aggregate; callers trim with
    ``num_groups`` (the child is likewise padded — ``to_pylist`` only
    reads below each list's offsets).
    """
    c_check = table.column(value_col)
    if c_check.dtype.type_id in (TypeId.LIST, TypeId.STRUCT):
        raise NotImplementedError("collect of nested columns")
    n = table.num_rows
    m = n
    sub = Table([table.column(k) for k in keys] + [table.column(value_col)])
    kix = list(range(len(keys)))
    vix = len(keys)
    if distinct:
        order = sort_order(sub, kix + [vix],
                           nulls_first=[True] * len(keys) + [False])
    else:
        order = sort_order(sub, kix)
    ssub = gather(sub, order)
    same = _rows_equal_prev(ssub, kix)
    if n:
        gid = (jnp.cumsum(~same) - 1).astype(jnp.int32)
    else:
        gid = None
    num_groups, g_lo, g_hi = _dense_group_bounds(gid, n, m)
    first_idx = jnp.where(g_hi > g_lo, g_lo, n)
    out_cols = _gather_group_keys(ssub, kix, first_idx, m, n)

    vc = ssub.column(vix)
    keep = vc.valid_mask()
    if distinct and n:
        # drop repeats of the same value within a group (values are
        # adjacent after the secondary sort — the nunique flag idiom)
        eqv = _col_values_equal_prev(vc)
        prev_same_valid = jnp.concatenate(
            [jnp.zeros((1,), jnp.bool_), eqv & keep[:-1]])
        keep = keep & (~same | ~prev_same_valid)
    if n:
        pref = jnp.cumsum(keep.astype(jnp.int64))
        pref0 = jnp.concatenate([jnp.zeros((1,), jnp.int64), pref])
        counts = pref0[g_hi] - pref0[g_lo]
        # kept rows first (stable) — their sorted order IS group order,
        # so the compacted prefix is exactly the dense child
        comp = jnp.argsort(~keep, stable=True).astype(jnp.int32)
        child = _gather_any(vc, comp, jnp.bool_(True))
    else:
        counts = jnp.zeros((m,), jnp.int64)
        child = vc
    offsets = jnp.concatenate(
        [jnp.zeros((1,), jnp.int64), jnp.cumsum(counts)]
    ).astype(jnp.int32)
    garange = jnp.arange(m, dtype=jnp.int32)
    out_cols.append(Column(
        DType(TypeId.LIST), offsets, garange < num_groups,
        children=[child],
    ))
    return CollectResult(Table(out_cols), num_groups)


@func_range("array_size")
def array_size(col: Column) -> Column:
    """Spark ``size``/``cardinality``: element count per list; null
    lists give null (ANSI) — the caller can map null->-1 for legacy."""
    if col.dtype.type_id != TypeId.LIST:
        raise TypeError(f"array_size needs a LIST column, got {col.dtype}")
    lens = (col.data[1:] - col.data[:-1]).astype(jnp.int32)
    return Column(DType(TypeId.INT32), lens,
                  col.valid_mask() if col.validity is not None else None)


@func_range("array_contains")
def array_contains(col: Column, value) -> Column:
    """Spark ``array_contains(list, value)``: per-row ANY over the
    child — a prefix-difference count over the flat child matches, no
    per-row loops. Three-valued logic matches Spark's ArrayContains:
    TRUE when found; NULL when not found but the list has a null
    element (the null might have been the value); FALSE otherwise; a
    null list is null."""
    if col.dtype.type_id != TypeId.LIST:
        raise TypeError(
            f"array_contains needs a LIST column, got {col.dtype}")
    child = col.children[0]
    if child.dtype.is_decimal128:
        raise NotImplementedError(
            "array_contains on DECIMAL128 children")
    if child.dtype.is_string:
        from spark_rapids_jni_tpu.ops import strings as s

        p = s.pad_strings(child)
        vb = str(value).encode()
        w = p.chars.shape[1]
        if len(vb) > w:
            hit = jnp.zeros((p.chars.shape[0],), jnp.bool_)
        else:
            target = jnp.zeros((w,), jnp.uint8).at[:len(vb)].set(
                jnp.asarray(bytearray(vb), dtype=jnp.uint8))
            hit = (p.data == len(vb)) & jnp.all(
                p.chars == target[None, :], axis=1)
        hit = hit & p.valid_mask()
    else:
        hit = (child.data == value) & child.valid_mask()

    def _range_any(flags):
        pref = jnp.concatenate(
            [jnp.zeros((1,), jnp.int64),
             jnp.cumsum(flags.astype(jnp.int64))])
        off = col.data.astype(jnp.int32)
        return (pref[off[1:]] - pref[off[:-1]]) > 0

    found = _range_any(hit)
    has_null_elem = _range_any(~child.valid_mask())
    from spark_rapids_jni_tpu.types import BOOL8

    validity = col.valid_mask() & (found | ~has_null_elem)
    return Column(BOOL8, found.astype(jnp.uint8), validity)


@func_range("element_at")
def element_at(col: Column, k: int) -> Column:
    """Spark ``element_at(list, k)``: 1-based; negative k counts from
    the end; out-of-bounds gives null (non-ANSI posture)."""
    if col.dtype.type_id != TypeId.LIST:
        raise TypeError(f"element_at needs a LIST column, got {col.dtype}")
    if k == 0:
        raise ValueError("element_at index is 1-based (k != 0)")
    child = col.children[0]
    off = col.data.astype(jnp.int32)
    lens = off[1:] - off[:-1]
    if k > 0:
        pos = off[:-1] + (k - 1)
        in_b = k <= lens
    else:
        pos = off[1:] + k
        in_b = -k <= lens
    valid = in_b & col.valid_mask()
    src = jnp.clip(pos, 0, max(int(child.size) - 1, 0))
    return _gather_any(child, src, valid)


@func_range("array_join")
def array_join(col: Column, sep: str,
               null_replacement: str | None = None) -> Column:
    """Spark ``array_join``: concatenate STRING list elements with
    ``sep``; null elements are skipped unless ``null_replacement``."""
    if col.dtype.type_id != TypeId.LIST:
        raise TypeError(f"array_join needs a LIST column, got {col.dtype}")
    child = col.children[0]
    if not child.dtype.is_string:
        raise TypeError("array_join needs LIST<STRING>")
    # host-assembled (ragged concatenation has no fixed-width form that
    # beats the explode->concat_ws chain; columns needing device joins
    # should explode + groupby_collect instead)
    vals = col.to_pylist()
    out = []
    for lst in vals:
        if lst is None:
            out.append(None)
            continue
        parts = []
        for v in lst:
            if v is None:
                if null_replacement is not None:
                    parts.append(null_replacement)
            else:
                parts.append(v)
        out.append(sep.join(parts))
    from spark_rapids_jni_tpu import types as t

    return Column.from_pylist(out, t.STRING)
