"""Equi-join — the cuDF hash-join equivalent (vendored capability surface,
SURVEY.md section 2.2; exercised by TPC-DS q64/q72, BASELINE.json config #4).

TPU-first design: no device hash table (SURVEY.md section 7: partitioned/
sort designs instead of chaining hash maps). This is a sort + binary-search
join: sort the build side once, then for every probe row locate its match
run with vectorized ``searchsorted`` (lower/upper bound), lay output pairs
out with a prefix sum, and resolve pair j -> (probe row, match ordinal) with
one more searchsorted over the offsets. Everything is static-shape; the
caller supplies ``out_size`` (capacity) and gets back gather maps plus the
true match count — the bucketed-padding discipline XLA wants. SQL semantics:
NULL keys never match; left join emits unmatched probe rows with an invalid
right index.

Multi-column and string/float keys are **exact**, not hashed: both sides'
key tuples are dense-rank encoded over their union (one sort of the
concatenated key columns + boundary scan — the same machinery groupby
uses), after which the join runs on a single collision-free int32 rank
column. cuDF's hash join is exact on composite keys; rank encoding is the
sort-based TPU equivalent (no collision-at-hash wrong answers, unlike the
round-1 "pre-hash into one column" recipe this replaces).
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from spark_rapids_jni_tpu.columnar import Column, Table
from spark_rapids_jni_tpu.ops.hash import probe_sorted_lo_hi
from spark_rapids_jni_tpu.ops.sort import gather, sort_order
from spark_rapids_jni_tpu.utils.tracing import func_range


class JoinMaps(NamedTuple):
    """Gather maps describing join output rows (padded to out_size)."""

    left_index: jnp.ndarray   # int32[out_size] into the left table
    right_index: jnp.ndarray  # int32[out_size] into the right table
    right_valid: jnp.ndarray  # bool: False on left-join unmatched rows
    row_valid: jnp.ndarray    # bool: False on padding rows
    total: jnp.ndarray        # scalar int64: true number of output rows
    # bool: False on right/full-join rows with no left match (null left)
    left_valid: jnp.ndarray


def _sorted_valid_keys(
    key: jnp.ndarray, valid: jnp.ndarray
) -> tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Sort one side with nulls banished past the valid prefix (null_rank
    is the primary lexsort key), then overwrite the tail with the dtype's
    max so a binary search over it stays sound even though null rows carry
    arbitrary key bytes. Returns (sorted_key, n_valid, perm)."""
    n = key.shape[0]
    null_rank = (~valid).astype(jnp.uint8)
    perm = jnp.lexsort((key, null_rank)).astype(jnp.int32)
    n_valid = jnp.sum(valid.astype(jnp.int64))
    info = np.iinfo(np.dtype(key.dtype.name))
    sorted_key = jnp.where(
        jnp.arange(n, dtype=jnp.int64) < n_valid,
        key[perm],
        jnp.asarray(info.max, dtype=key.dtype),
    )
    return sorted_key, n_valid, perm


def _join_maps_impl(
    left_key: jnp.ndarray,
    left_valid: jnp.ndarray,
    right_key: jnp.ndarray,
    right_valid: jnp.ndarray,
    out_size: int,
    how: str,
    left_row_valid: jnp.ndarray | None = None,
    right_row_valid: jnp.ndarray | None = None,
) -> JoinMaps:
    n_left = left_key.shape[0]
    n_right = right_key.shape[0]
    # Rows that are not rows at all (padding/phantom shuffle slots) must
    # never match, regardless of what their key bytes and key validity
    # happen to hold — fold row existence into key validity up front.
    if left_row_valid is not None:
        left_valid = left_valid & left_row_valid
    if right_row_valid is not None:
        right_valid = right_valid & right_row_valid
    sorted_key, n_valid_right, perm = _sorted_valid_keys(
        right_key, right_valid)

    # Match runs per probe row (empty when the probe key is null).
    # probe_sorted_lo_hi is the kernel-tier seam: searchsorted pair on
    # the XLA tier, the streaming Pallas probe kernel otherwise.
    lo, hi = probe_sorted_lo_hi(sorted_key, left_key)
    hi = jnp.minimum(hi, n_valid_right)  # the sentinel tail never matches
    lo = jnp.minimum(lo, hi)
    counts = jnp.where(left_valid, hi - lo, 0)
    if how in ("left", "full"):
        out_per_row = jnp.maximum(counts, 1)  # unmatched probe row emits one
    elif how == "left_semi":
        out_per_row = (counts > 0).astype(counts.dtype)
    elif how == "left_anti":
        # no match at all — a NULL probe key matches nothing, so it
        # qualifies (Spark NOT EXISTS / cuDF left_anti semantics)
        out_per_row = (counts == 0).astype(counts.dtype)
    else:  # inner, right
        out_per_row = counts
    if left_row_valid is not None and how != "inner" and how != "right":
        # phantom probe rows must emit nothing — only real probe rows get
        # the unmatched-row / semi / anti treatment (a real row with a
        # NULL key still counts). inner/right emission is already 0 for
        # phantom rows: left_valid was masked above, so counts == 0.
        out_per_row = jnp.where(left_row_valid, out_per_row, 0)
    offsets = jnp.cumsum(out_per_row)
    probe_total = offsets[-1] if n_left else jnp.int64(0)

    j = jnp.arange(out_size, dtype=jnp.int64)
    left_row = jnp.searchsorted(offsets, j, side="right").astype(jnp.int32)
    left_row = jnp.clip(left_row, 0, max(n_left - 1, 0))
    base = jnp.where(left_row > 0, offsets[jnp.maximum(left_row - 1, 0)], 0)
    ordinal = j - base
    matched = counts[left_row] > 0
    right_pos = jnp.clip(
        lo[left_row] + ordinal, 0, max(n_right - 1, 0)
    ).astype(jnp.int32)
    right_row = perm[right_pos] if n_right else jnp.zeros_like(right_pos)

    if how not in ("right", "full"):
        row_valid = j < probe_total
        right_ok = matched & row_valid & (how != "left_anti")
        return JoinMaps(
            left_index=left_row,
            right_index=right_row,
            right_valid=right_ok,
            row_valid=row_valid,
            total=probe_total,
            left_valid=row_valid,
        )

    # right/full outer: append build rows no valid probe row matched, with
    # a null left side. A build row is matched iff its key is valid and
    # appears among the valid probe keys — one more sort + binary search,
    # the mirror of the probe phase (scatter-free).
    sorted_left, n_valid_left, _ = _sorted_valid_keys(left_key, left_valid)
    l_lo, l_hi = probe_sorted_lo_hi(sorted_left, right_key)
    l_hi = jnp.minimum(l_hi, n_valid_left)
    exists_in_left = jnp.minimum(l_lo, l_hi) < l_hi
    unmatched = ~(right_valid & exists_in_left)
    if right_row_valid is not None:
        unmatched = unmatched & right_row_valid  # phantom slots emit nothing
    r_off = jnp.cumsum(unmatched.astype(jnp.int64))
    extra_total = r_off[-1] if n_right else jnp.int64(0)
    total = probe_total + extra_total

    is_extra = (j >= probe_total) & (j < total)
    k = jnp.clip(j - probe_total, 0, None)
    extra_right = jnp.searchsorted(r_off, k, side="right").astype(jnp.int32)
    extra_right = jnp.clip(extra_right, 0, max(n_right - 1, 0))
    row_valid = j < total
    return JoinMaps(
        left_index=left_row,
        right_index=jnp.where(is_extra, extra_right, right_row),
        right_valid=(matched | is_extra) & row_valid,
        row_valid=row_valid,
        total=total,
        left_valid=row_valid & ~is_extra,
    )


def _concat_key_columns(lc: Column, rc: Column) -> Column:
    """Stack one key column from both tables into a combined column (left
    rows first) for union rank encoding."""
    if lc.dtype.is_string != rc.dtype.is_string:
        raise TypeError("join key types must match (string vs non-string)")
    lv, rv = lc.valid_mask(), rc.valid_mask()
    validity = jnp.concatenate([lv, rv])
    if lc.dtype.is_decimal or rc.dtype.is_decimal:
        # unscaled storage comparison is only sound at equal scales
        if lc.dtype != rc.dtype:
            raise TypeError(
                f"decimal join keys must have identical type+scale, got "
                f"{lc.dtype} vs {rc.dtype} (rescale first)"
            )
    if lc.dtype.is_string:
        from spark_rapids_jni_tpu.ops import strings as s

        lp, rp = s.pad_strings(lc), s.pad_strings(rc)
        width = max(int(lp.chars.shape[1]), int(rp.chars.shape[1]))

        def widen(p):
            w = int(p.chars.shape[1])
            if w == width:
                return p.chars
            return jnp.pad(p.chars, ((0, 0), (0, width - w)))

        return Column(
            lc.dtype,
            jnp.concatenate([lp.data, rp.data]),
            validity,
            chars=jnp.concatenate([widen(lp), widen(rp)]),
        )
    if lc.dtype.is_decimal128:
        # limb-pair storage concatenates along the row axis like any other
        return Column(lc.dtype, jnp.concatenate([lc.data, rc.data]), validity)
    if lc.dtype.storage_dtype != rc.dtype.storage_dtype:
        raise TypeError("join key storage types must match")
    return Column(lc.dtype, jnp.concatenate([lc.data, rc.data]), validity)


@func_range("rank_encode_keys")
def rank_encode_keys(
    left: Table, right: Table,
    left_on: Sequence[int], right_on: Sequence[int],
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Exact join-key encoding: dense ranks of the key tuples over the union
    of both tables. ``lkey[i] == rkey[j]`` iff the tuples are equal (nulls
    compare equal to nulls here; null-match exclusion stays in the join's
    validity masks). One lexsort of nl+nr rows — collision-free, unlike
    hashing."""
    from spark_rapids_jni_tpu.ops.groupby import _rows_equal_prev

    nl = left.num_rows
    combined = Table([
        _concat_key_columns(left.column(i), right.column(j))
        for i, j in zip(left_on, right_on)
    ])
    n = combined.num_rows
    ks = list(range(combined.num_columns))
    order = sort_order(combined, ks)
    sorted_tbl = gather(combined, order)
    same = _rows_equal_prev(sorted_tbl, ks)
    gid = (jnp.cumsum(~same) - 1).astype(jnp.int32)
    # scatter-free permutation inverse: ranks[order[i]] = gid[i] is the
    # gather ranks = gid[argsort(order)] (argsort of a permutation is its
    # inverse; scatters serialize on TPU)
    ranks = gid[jnp.argsort(order)]
    return ranks[:nl], ranks[nl:]


_JOIN_TYPES = ("inner", "left", "left_semi", "left_anti", "right", "full")


def _join_impl(row_args, aux_args, row_valids, *, lkeys, rkeys,
               out_size, how) -> JoinMaps:
    ((left, left_row_valid), (right, right_row_valid)) = row_args
    if row_valids is not None:
        # Row-dim padding happened: a caller-supplied row_valid was padded
        # with False (phantom rows already excluded); with no caller mask
        # the bucket mask itself marks the phantoms.
        lrv, rrv = row_valids
        if left_row_valid is None:
            left_row_valid = lrv
        if right_row_valid is None:
            right_row_valid = rrv

    lvalid = left.column(lkeys[0]).valid_mask()
    for k in lkeys[1:]:
        lvalid = lvalid & left.column(k).valid_mask()
    rvalid = right.column(rkeys[0]).valid_mask()
    for k in rkeys[1:]:
        rvalid = rvalid & right.column(k).valid_mask()

    lc = left.column(lkeys[0])
    rc0 = right.column(rkeys[0])
    single_integral = (
        len(lkeys) == 1
        and lc.dtype == rc0.dtype  # incl. decimal scale — unscaled values
        and not lc.dtype.is_string  # only compare at identical scales
        and not lc.dtype.is_decimal128  # limb pairs go via rank encoding
        and lc.dtype.storage_dtype.kind in ("i", "u")
    )
    if single_integral:
        # fast path: integral values are their own exact encoding
        lkey, rkey = lc.data, rc0.data
    else:
        lkey, rkey = rank_encode_keys(left, right, list(lkeys), list(rkeys))
    return _join_maps_impl(
        lkey, lvalid, rkey, rvalid, out_size, how, left_row_valid,
        right_row_valid,
    )


@func_range("join")
def join(
    left: Table,
    right: Table,
    left_on: int | Sequence[int],
    right_on: int | Sequence[int],
    out_size: int,
    how: str = "inner",
    left_row_valid: jnp.ndarray | None = None,
    right_row_valid: jnp.ndarray | None = None,
) -> JoinMaps:
    """Equi-join returning gather maps; single- or multi-column keys of any
    supported type (integral, float, decimal, string). ``out_size`` caps the
    output (check ``total`` <= out_size on host if exactness matters, or use
    ``join_auto``). ``left_row_valid`` / ``right_row_valid`` mark which rows
    exist at all (False = padding/shuffle phantom, emits nothing even under
    an outer join).

    Join types (the cuDF surface, reference build-libcudf.xml:34-60
    capability): ``inner``, ``left``, ``left_semi`` (one row per probe row
    with >=1 match; right side = first match), ``left_anti`` (one row per
    probe row with NO match — null keys qualify; right side null),
    ``right`` (inner + unmatched build rows with null left), ``full``
    (left + unmatched build rows with null left).

    Runs through the shape-bucketed dispatch cache: each side's row count
    is padded up to its own bucket, so nearby (n_left, n_right) pairs share
    one executable per (out_size, how) instead of compiling per exact
    shape. Phantom pad rows ride the existing ``*_row_valid`` contract and
    emit nothing. The ``JoinMaps`` output is sized by ``out_size`` (a
    static), never by the buckets, so no output slicing is needed; index
    values in the ``~row_valid`` region are unspecified either way.

    SQL semantics: a NULL in ANY key column makes the row match nothing."""
    if how not in _JOIN_TYPES:
        raise ValueError(
            f"unsupported join type {how!r}; valid: {_JOIN_TYPES}")
    left_keys = [left_on] if isinstance(left_on, int) else list(left_on)
    right_keys = [right_on] if isinstance(right_on, int) else list(right_on)
    if len(left_keys) != len(right_keys) or not left_keys:
        raise ValueError("left_on and right_on must be equal-length, non-empty")
    lkeys_t = tuple(int(k) for k in left_keys)
    rkeys_t = tuple(int(k) for k in right_keys)
    out_size = int(out_size)

    from spark_rapids_jni_tpu.runtime import dispatch

    return dispatch.call(
        "join",
        partial(_join_impl, lkeys=lkeys_t, rkeys=rkeys_t,
                out_size=out_size, how=how),
        ((left, left_row_valid), (right, right_row_valid)),
        statics=(lkeys_t, rkeys_t, out_size, how),
        slice_rows=False,
    )


def _gather_out(c: Column, idx: jnp.ndarray, validity: jnp.ndarray) -> Column:
    if c.dtype.is_string:
        from spark_rapids_jni_tpu.ops import strings as s

        g = s.gather_strings(c, idx)
        return Column(c.dtype, g.data, validity, chars=g.chars)
    return Column(c.dtype, c.data[idx], validity)


def apply_join_maps(
    left: Table, right: Table, maps: JoinMaps
) -> Table:
    """Materialize the joined table: left columns then right columns.
    Padding rows carry validity False everywhere; unmatched right sides
    (left/full join) and unmatched left sides (right/full join) are null.
    String columns come back in the padded device layout
    (ops.strings.unpad_strings restores Arrow)."""
    cols: list[Column] = []
    for c in left.columns:
        validity = (
            c.valid_mask()[maps.left_index] & maps.left_valid & maps.row_valid
        )
        cols.append(_gather_out(c, maps.left_index, validity))
    for c in right.columns:
        validity = (
            c.valid_mask()[maps.right_index] & maps.right_valid & maps.row_valid
        )
        cols.append(_gather_out(c, maps.right_index, validity))
    return Table(cols)


def join_auto(
    left: Table,
    right: Table,
    left_on: int | Sequence[int],
    right_on: int | Sequence[int],
    initial_out_size: int | None = None,
    how: str = "inner",
    growth: int = 4,
) -> tuple[JoinMaps, Table]:
    """Host-level grow-and-retry around the output capacity: run with a
    guessed ``out_size``, and if ``total`` exceeded it, grow by ``growth``
    and rerun until exact. Each retry recompiles for the new static bound —
    output capacity is a planning parameter on TPU, and this wrapper is the
    planner's feedback loop. Growth runs through the shared resilience
    ladder (``runtime/resilience.escalate``): the overflowed attempt
    reports its exact requirement (``total``), so the schedule —
    max(total, out_size·growth) — converges on the second attempt exactly
    as the pre-resilience loop did. Returns (maps, materialized table)."""
    from spark_rapids_jni_tpu.runtime import resilience

    n = max(left.num_rows, 1)
    out_size = int(initial_out_size) if initial_out_size else n
    if not resilience.enabled():
        while True:
            maps = join(left, right, left_on, right_on, out_size, how=how)
            total = int(maps.total)
            if total <= out_size:
                return maps, apply_join_maps(left, right, maps)
            out_size = max(total, out_size * growth)

    def _attempt(cap):
        maps = join(left, right, left_on, right_on, cap, how=how)
        total = int(maps.total)
        if total <= cap:
            return (maps, apply_join_maps(left, right, maps)), False, None
        return None, True, total

    return resilience.escalate(
        "join_auto", _attempt, seam="dispatch.execute",
        initial=out_size, growth=growth, rows=n)
