"""Equi-join — the cuDF hash-join equivalent (vendored capability surface,
SURVEY.md section 2.2; exercised by TPC-DS q64/q72, BASELINE.json config #4).

TPU-first design: no device hash table (SURVEY.md section 7: partitioned/
sort designs instead of chaining hash maps). This is a sort + binary-search
join: sort the build side once, then for every probe row locate its match
run with vectorized ``searchsorted`` (lower/upper bound), lay output pairs
out with a prefix sum, and resolve pair j -> (probe row, match ordinal) with
one more searchsorted over the offsets. Everything is static-shape; the
caller supplies ``out_size`` (capacity) and gets back gather maps plus the
true match count — the bucketed-padding discipline XLA wants. SQL semantics:
NULL keys never match; left join emits unmatched probe rows with an invalid
right index.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from spark_rapids_jni_tpu.columnar import Column, Table
from spark_rapids_jni_tpu.ops.sort import gather
from spark_rapids_jni_tpu.utils.tracing import func_range


class JoinMaps(NamedTuple):
    """Gather maps describing join output rows (padded to out_size)."""

    left_index: jnp.ndarray   # int32[out_size] into the left table
    right_index: jnp.ndarray  # int32[out_size] into the right table
    right_valid: jnp.ndarray  # bool: False on left-join unmatched rows
    row_valid: jnp.ndarray    # bool: False on padding rows
    total: jnp.ndarray        # scalar int64: true number of output rows


def _join_maps_impl(
    left_key: jnp.ndarray,
    left_valid: jnp.ndarray,
    right_key: jnp.ndarray,
    right_valid: jnp.ndarray,
    out_size: int,
    how: str,
    left_row_valid: jnp.ndarray | None = None,
) -> JoinMaps:
    n_right = right_key.shape[0]
    # Sort the build side with nulls banished past the valid prefix
    # (null_rank is the primary lexsort key), then overwrite the tail with
    # the dtype's max so the array binary-search over it stays sound even
    # though null rows carry arbitrary key bytes.
    null_rank = (~right_valid).astype(jnp.uint8)
    perm = jnp.lexsort((right_key, null_rank)).astype(jnp.int32)
    n_valid_right = jnp.sum(right_valid.astype(jnp.int64))
    info = np.iinfo(np.dtype(right_key.dtype.name))
    sorted_key = jnp.where(
        jnp.arange(n_right, dtype=jnp.int64) < n_valid_right,
        right_key[perm],
        jnp.asarray(info.max, dtype=right_key.dtype),
    )

    # Match runs per probe row (empty when the probe key is null).
    lo = jnp.searchsorted(sorted_key, left_key, side="left")
    hi = jnp.searchsorted(sorted_key, left_key, side="right")
    hi = jnp.minimum(hi, n_valid_right)  # the sentinel tail never matches
    lo = jnp.minimum(lo, hi)
    counts = jnp.where(left_valid, hi - lo, 0)
    if how == "left":
        out_per_row = jnp.maximum(counts, 1)  # unmatched probe row emits one
        if left_row_valid is not None:
            # rows that are not rows at all (padding/phantom shuffle slots)
            # must emit nothing — only real probe rows get the unmatched-row
            # treatment (a real row with a NULL key still emits one).
            out_per_row = jnp.where(left_row_valid, out_per_row, 0)
    else:
        out_per_row = counts
    offsets = jnp.cumsum(out_per_row)
    total = offsets[-1] if left_key.shape[0] else jnp.int64(0)

    j = jnp.arange(out_size, dtype=jnp.int64)
    row_valid = j < total
    left_row = jnp.searchsorted(offsets, j, side="right").astype(jnp.int32)
    left_row = jnp.clip(left_row, 0, max(left_key.shape[0] - 1, 0))
    base = jnp.where(left_row > 0, offsets[jnp.maximum(left_row - 1, 0)], 0)
    ordinal = j - base
    matched = counts[left_row] > 0
    right_pos = jnp.clip(
        lo[left_row] + ordinal, 0, max(n_right - 1, 0)
    ).astype(jnp.int32)
    right_row = perm[right_pos] if n_right else jnp.zeros_like(right_pos)
    right_ok = matched & row_valid
    return JoinMaps(
        left_index=left_row,
        right_index=right_row,
        right_valid=right_ok,
        row_valid=row_valid,
        total=total,
    )


@func_range("join")
def join(
    left: Table,
    right: Table,
    left_on: int,
    right_on: int,
    out_size: int,
    how: str = "inner",
    left_row_valid: jnp.ndarray | None = None,
) -> JoinMaps:
    """Single-key equi-join returning gather maps. ``out_size`` caps the
    output (check ``total`` <= out_size on host if exactness matters);
    multi-key joins compose by pre-hashing keys into one column.
    ``left_row_valid`` marks which probe rows exist at all (False =
    padding/shuffle phantom, emits nothing even under a left join)."""
    if how not in ("inner", "left"):
        raise ValueError(f"unsupported join type {how!r}")
    lc, rc = left.column(left_on), right.column(right_on)
    if lc.dtype.storage_dtype != rc.dtype.storage_dtype:
        raise TypeError("join key storage types must match")
    if lc.dtype.storage_dtype.kind not in ("i", "u"):
        raise TypeError(
            "join keys must be integral this round (hash or encode other "
            "types into an integer column first)"
        )
    return _join_maps_impl(
        lc.data, lc.valid_mask(), rc.data, rc.valid_mask(), out_size, how,
        left_row_valid,
    )


def apply_join_maps(
    left: Table, right: Table, maps: JoinMaps
) -> Table:
    """Materialize the joined table: left columns then right columns.
    Padding rows carry validity False everywhere; unmatched right sides
    (left join) are null."""
    cols: list[Column] = []
    for c in left.columns:
        validity = c.valid_mask()[maps.left_index] & maps.row_valid
        cols.append(Column(c.dtype, c.data[maps.left_index], validity))
    for c in right.columns:
        validity = (
            c.valid_mask()[maps.right_index] & maps.right_valid & maps.row_valid
        )
        cols.append(Column(c.dtype, c.data[maps.right_index], validity))
    return Table(cols)
