"""Column-level scalar reductions — cuDF ``reduce`` parity (SUM/MIN/MAX/
MEAN/COUNT with SQL null semantics: nulls skipped; an all-null column's
SUM/MIN/MAX/MEAN is null). Fully jittable; each op returns
(value, valid) device scalars so callers compose without host syncs.

Reductions route through ``runtime.dispatch`` with padded tail rows as
NULL rows — every path already neutralizes nulls (sums add 0, min/max
see sentinels, the string/decimal128 sort path ranks nulls last, counts
skip them), so a bucketed reduction is bit-identical to the exact-shape
one. Outputs are scalars (or (2,) limb pairs), so ``slice_rows=False``.
"""

from __future__ import annotations

from functools import partial

import jax.numpy as jnp
import numpy as np

from spark_rapids_jni_tpu.columnar import Column
from spark_rapids_jni_tpu.runtime import dispatch
from spark_rapids_jni_tpu.utils.tracing import func_range


def _masked(col: Column, neutral):
    valid = col.valid_mask()
    return jnp.where(valid, col.data, jnp.asarray(neutral, col.data.dtype)), valid


def _count_impl(row_args, aux, rvs):
    ((col,),) = row_args
    return jnp.sum(col.valid_mask()).astype(jnp.int64)


@func_range("reduce_count")
def count(col: Column) -> jnp.ndarray:
    """Non-null count (always valid)."""
    return dispatch.rowwise("reduce_count", _count_impl, (col,),
                            slice_rows=False)


def _sum_impl(row_args, aux, rvs):
    ((col,),) = row_args
    valid = col.valid_mask()
    has_any = jnp.any(valid)
    if col.dtype.is_decimal128:
        from spark_rapids_jni_tpu.ops.groupby import (
            recombine_sum128,
            split_sum128_lanes,
        )

        lo = jnp.where(valid, col.data[:, 0], jnp.int64(0))
        hi = jnp.where(valid, col.data[:, 1], jnp.int64(0))
        lanes = [jnp.sum(l) for l in split_sum128_lanes(lo, hi)]
        # totals past signed 128 bits null the result instead of wrapping
        # (the groupby sum_overflow posture, reference: Spark ANSI)
        lo_t, hi_t, ovf = recombine_sum128(*lanes)
        return jnp.stack([lo_t, hi_t]), has_any & ~ovf
    vals, _ = _masked(col, 0)
    kind = col.dtype.storage_dtype.kind
    if kind == "u":
        # unsigned accumulates unsigned: values >= 2^63 must not wrap
        return jnp.sum(vals.astype(jnp.uint64)), has_any
    if kind in ("i", "b"):
        return jnp.sum(vals.astype(jnp.int64)), has_any
    return jnp.sum(vals), has_any


@func_range("reduce_sum")
def sum_(col: Column):
    """(sum, valid): int/decimal accumulate in int64 (exact); floats in
    their own dtype. DECIMAL128 sums limb-exactly (carry recombination)."""
    return dispatch.rowwise("reduce_sum", _sum_impl, (col,),
                            slice_rows=False)


def _minmax_impl(row_args, aux, rvs, *, op: str):
    ((col,),) = row_args
    if col.dtype.is_string or col.dtype.is_decimal128:
        # order statistics via one sort: the winner is row 0 / row n-1 of
        # the nulls-last order (rank trick without the groupby machinery)
        from spark_rapids_jni_tpu.columnar import Table
        from spark_rapids_jni_tpu.ops.sort import gather, sort_order

        order = sort_order(Table([col]), [0], nulls_first=[False])
        valid = col.valid_mask()
        has_any = jnp.any(valid)
        pos = jnp.where(
            jnp.asarray(op == "min"), 0,
            jnp.maximum(jnp.sum(valid).astype(jnp.int32) - 1, 0),
        )
        winner = gather(Table([col]), order[pos][None])
        return winner.column(0), has_any
    np_dt = col.dtype.storage_dtype
    if np_dt.kind == "f":
        neutral = np.inf if op == "min" else -np.inf
    else:
        info = np.iinfo(np_dt)
        neutral = info.max if op == "min" else info.min
    vals, valid = _masked(col, neutral)
    red = jnp.min(vals) if op == "min" else jnp.max(vals)
    return red, jnp.any(valid)


def _minmax(col: Column, op: str):
    return dispatch.rowwise(
        f"reduce_{op}", partial(_minmax_impl, op=op), (col,),
        statics=(op,), slice_rows=False)


@func_range("reduce_min")
def min_(col: Column):
    return _minmax(col, "min")


@func_range("reduce_max")
def max_(col: Column):
    return _minmax(col, "max")


def _mean_impl(row_args, aux, rvs):
    (group,) = row_args
    (col,) = group
    if col.dtype.is_decimal128:
        from spark_rapids_jni_tpu.ops.groupby import _mean128_exact

        total, has_any = _sum_impl(row_args, aux, rvs)  # (2,) limbs, exact
        cnt = _count_impl(row_args, aux, rvs)
        limbs, overflow = _mean128_exact(
            total[0:1], total[1:2], cnt.reshape(1))
        return limbs[0], has_any & ~overflow[0]
    total, has_any = _sum_impl(row_args, aux, rvs)
    denom = jnp.maximum(_count_impl(row_args, aux, rvs), 1).astype(
        jnp.float64)
    m = total.astype(jnp.float64) / denom
    if col.dtype.is_decimal:
        m = m * (10.0 ** col.dtype.scale)
    return m, has_any


@func_range("reduce_mean")
def mean(col: Column):
    """(mean, valid). Floats/ints/decimal64 return FLOAT64 rescaled to the
    true value (the groupby mean contract); DECIMAL128 returns EXACT
    (2,)-limb unscaled value at 4 extra fractional digits via the same
    integer long-division path the groupby uses — no f64 anywhere."""
    return dispatch.rowwise("reduce_mean", _mean_impl, (col,),
                            slice_rows=False)
