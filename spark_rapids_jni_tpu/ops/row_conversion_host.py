"""Host-side packed-row codec — the native (C++) half of component C1'.

Same byte contract as ops/row_conversion (which runs on device): the JNI
surface uses this for Spark's host-side UnsafeRow handoff, and the tests
cross-validate the two implementations byte-for-byte — an independent
check of the reference layout contract (row_conversion.cu:432-456).
"""

from __future__ import annotations

import ctypes

import numpy as np

from spark_rapids_jni_tpu.columnar import Column, Table
from spark_rapids_jni_tpu.parquet.footer import NativeError
from spark_rapids_jni_tpu.runtime.native import load_native
from spark_rapids_jni_tpu.types import DType


def _sizes(schema: list[DType]) -> np.ndarray:
    return np.array([dt.size_bytes for dt in schema], dtype=np.int32)


def host_layout(schema: list[DType]) -> tuple[np.ndarray, int]:
    """(column_start[n], row_size) from the native layout engine."""
    lib = load_native()
    sizes = _sizes(schema)
    starts = np.empty(len(schema), dtype=np.int32)
    row_size = lib.tpudf_rows_layout(
        sizes.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        len(schema),
        starts.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
    )
    if row_size < 0:
        raise NativeError(lib.last_error())
    return starts, int(row_size)


def host_to_rows(table: Table) -> np.ndarray:
    """Pack a host copy of the table into uint8[n, row_size]."""
    lib = load_native()
    schema = table.schema()
    sizes = _sizes(schema)
    n = table.num_rows
    _, row_size = host_layout(schema)

    datas = []
    valids = []
    for c in table.columns:
        datas.append(np.ascontiguousarray(np.asarray(c.data)))
        valids.append(
            None if c.validity is None
            else np.ascontiguousarray(np.asarray(c.validity), dtype=np.uint8)
        )
    data_ptrs = (ctypes.c_void_p * len(datas))(
        *[d.ctypes.data_as(ctypes.c_void_p).value for d in datas]
    )
    valid_ptrs = (ctypes.c_void_p * len(valids))(
        *[None if v is None else v.ctypes.data_as(ctypes.c_void_p).value
          for v in valids]
    )
    out = np.zeros((n, row_size), dtype=np.uint8)
    rc = lib.tpudf_to_rows(
        data_ptrs, valid_ptrs,
        sizes.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        len(schema), n, out.ctypes.data_as(ctypes.c_void_p),
    )
    if rc != 0:
        raise NativeError(lib.last_error())
    return out


def host_from_rows(rows: np.ndarray, schema: list[DType]) -> Table:
    """Unpack uint8[n, row_size] into a host-backed Table."""
    import jax.numpy as jnp

    lib = load_native()
    sizes = _sizes(schema)
    _, row_size = host_layout(schema)
    if rows.ndim != 2 or rows.shape[1] != row_size:
        raise ValueError("The layout of the data appears to be off")
    n = rows.shape[0]
    rows = np.ascontiguousarray(rows, dtype=np.uint8)

    # DECIMAL128 unpacks straight into its int64[n, 2] limb-pair storage
    # (16 contiguous little-endian bytes per row — the same image the
    # device codec writes)
    datas = [np.empty((n, 2), dtype=np.int64) if dt.is_decimal128
             else np.empty(n, dtype=dt.storage_dtype) for dt in schema]
    valids = [np.empty(n, dtype=np.uint8) for _ in schema]
    data_ptrs = (ctypes.c_void_p * len(datas))(
        *[d.ctypes.data_as(ctypes.c_void_p).value for d in datas]
    )
    valid_ptrs = (ctypes.c_void_p * len(valids))(
        *[v.ctypes.data_as(ctypes.c_void_p).value for v in valids]
    )
    rc = lib.tpudf_from_rows(
        rows.ctypes.data_as(ctypes.c_void_p), n,
        sizes.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        len(schema), data_ptrs, valid_ptrs,
    )
    if rc != 0:
        raise NativeError(lib.last_error())
    return Table(
        [
            Column(dt, jnp.asarray(d), jnp.asarray(v.astype(bool)))
            for dt, d, v in zip(schema, datas, valids)
        ]
    )
