"""Ragged-row transpose kernel: column->row byte interleave (the
``row_conversion`` hot path) as one streaming Pallas pass.

The XLA path builds the fixed-width row image by a wide lane
concatenation of per-column byte pieces (+ alignment zero-pads + packed
validity bytes). This kernel replaces the interleave: each grid step
takes a 256-row slice of every byte piece (pre-cast to int32 lanes on
the XLA side — byte values are exact in int32) and assembles the
(256, row_width) output tile by broadcasted_iota where-selects, one
static output byte column at a time. Alignment gaps and the trailing
64-bit row pad fall out of the zero-initialized accumulator, so the
result is byte-for-byte ``jnp.concatenate(pieces, axis=1)``.

Rows are "ragged" across schemas, not within a batch: the kernel closure
is specialized per (starts, widths) layout — exactly the static schema
information ``compute_fixed_width_layout`` derives — and dispatch caches
one executable per schema x bucket like every other row-wise op.

Wide rows fall back to the oracle with reason ``row_too_wide``: the
select-assembly unrolls one op per row byte, so the tier caps the row
image at MAX_ROW_BYTES (two 128-lane tiles; the reference's shared-
memory row limit lives in the same order of magnitude).
"""

from __future__ import annotations

from typing import Sequence

import jax
import jax.numpy as jnp

from spark_rapids_jni_tpu.ops.pallas import register_kernel

_ROWS = 256          # rows per grid step (32 int32 sublane tiles)
MAX_ROW_BYTES = 256  # row-image cap (select-assembly unrolls per byte)

register_kernel(
    "row_conversion.to_rows",
    oracle="spark_rapids_jni_tpu.ops.row_conversion._to_rows_impl "
           "(tier=xla lane concatenation of byte pieces)",
    doc="column->row byte interleave of fixed-width pieces + packed "
        "validity into the uint8 row image, 256 rows per grid step",
)


def unsupported_reason(n: int, size_per_row: int) -> str | None:
    """Static (trace-time) eligibility; non-None routes to the oracle."""
    if n == 0:
        return "empty_input"
    if size_per_row > MAX_ROW_BYTES:
        return "row_too_wide"
    return None


def _round_up(x: int, mult: int) -> int:
    return -(-x // mult) * mult


def _make_kernel(starts_widths: tuple[tuple[int, int], ...], total: int):
    """Kernel closure over the static row layout: piece ``pi`` lands at
    byte offset ``starts_widths[pi][0]``; untouched columns stay zero
    (alignment gaps, trailing row pad)."""

    def kernel(*refs):
        out_ref = refs[-1]
        col_ids = jax.lax.broadcasted_iota(jnp.int32, (_ROWS, total), 1)
        acc = jnp.zeros((_ROWS, total), jnp.int32)
        for pi, (start, width) in enumerate(starts_widths):
            piece = refs[pi][0]                # (_ROWS, width)
            for k in range(width):
                col = piece[:, k:k + 1]        # (_ROWS, 1) keepdims slice
                acc = jnp.where(col_ids == start + k, col, acc)
        out_ref[0] = acc

    return kernel


def assemble_rows(
    pieces: Sequence[jnp.ndarray],
    starts: Sequence[int],
    size_per_row: int,
    *,
    interpret: bool,
) -> jnp.ndarray:
    """Interleave uint8 ``pieces`` (each (n, w_i)) into the row image
    uint8[n, size_per_row], piece i starting at byte ``starts[i]``.
    Byte-identical to concatenating the pieces with zero-fill gaps."""
    from jax.experimental import pallas as pl

    n = pieces[0].shape[0]
    total = _round_up(size_per_row, 128)
    pad = (-n) % _ROWS
    nb = (n + pad) // _ROWS
    ins = []
    starts_widths = []
    for start, piece in zip(starts, pieces):
        a = piece.astype(jnp.int32)            # bytes are exact in int32
        if pad:
            a = jnp.concatenate(
                [a, jnp.zeros((pad, a.shape[1]), jnp.int32)])
        ins.append(a.reshape(nb, _ROWS, a.shape[1]))
        starts_widths.append((int(start), int(piece.shape[1])))
    out = pl.pallas_call(
        _make_kernel(tuple(starts_widths), total),
        out_shape=jax.ShapeDtypeStruct((nb, _ROWS, total), jnp.int32),
        grid=(nb,),
        in_specs=[
            pl.BlockSpec((1, _ROWS, w), lambda i: (i, 0, 0))
            for _, w in starts_widths
        ],
        out_specs=pl.BlockSpec((1, _ROWS, total), lambda i: (i, 0, 0)),
        interpret=interpret,
    )(*ins)
    rows = out.astype(jnp.uint8).reshape(nb * _ROWS, total)
    return rows[:n, :size_per_row]
