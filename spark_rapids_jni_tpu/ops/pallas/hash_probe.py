"""Hash-probe kernel: the join/groupby probe loop over a bucketed build
table as a streaming comparison-count pass.

The XLA probe (ops/join.py ``_join_maps_impl``) is a pair of binary
searches over the sorted build keys::

    lo = searchsorted(sorted_key, probe, side="left")   # #(build <  p)
    hi = searchsorted(sorted_key, probe, side="right")  # #(build <= p)

Counting comparisons over the build MULTISET is the same function —
including the sentinel tail ``_sorted_valid_keys`` parks past the valid
prefix (dtype max never compares below a probe, and the downstream
``min(hi, n_valid)`` clamp is shared) — so the kernel streams the build
keys from SMEM (scalar prefetch, the Ragged Paged Attention idiom for
small per-block tables) past each 2048-row probe tile and accumulates
the two counts per probe element. Bit-identity with searchsorted holds
for every probe value by construction, not by tolerance.

The brute-force stream is O(build) per probe tile, so the tier caps the
build side (``MAX_BUILD``); larger builds fall back to the oracle with
reason ``build_too_large`` — the planner's bucketed-table sweet spot
(dimension-side joins) is exactly the small-build case.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from spark_rapids_jni_tpu.ops.pallas import register_kernel

_BLOCK = 2048      # probe rows per grid step
_SUB = 256
_SUBS = _BLOCK // _SUB
MAX_BUILD = 2048   # build keys held in SMEM per grid step (8 KiB int32)

register_kernel(
    "join.hash_probe",
    oracle="spark_rapids_jni_tpu.ops.join._join_maps_impl "
           "(tier=xla jnp.searchsorted left/right pair)",
    doc="per-probe-row match-run bounds [lo, hi) counted by streaming "
        "the SMEM-resident build keys past each probe tile",
)

# int32-representable key dtypes: the cast to the kernel's int32 lanes
# must preserve order and value (rank-encoded keys are int32 already)
_OK_KINDS = ("i",)
_OK_ITEMSIZE = 4


def unsupported_reason(build_rows: int, key_dtype) -> str | None:
    """Static (trace-time) eligibility; non-None routes to the oracle."""
    dt = jnp.dtype(key_dtype)
    if dt.kind not in _OK_KINDS or dt.itemsize > _OK_ITEMSIZE:
        return "key_width"
    if build_rows > MAX_BUILD:
        return "build_too_large"
    return None


def _probe_kernel(build_ref, probe_ref, lt_ref, le_ref):
    """One grid step: stream every build key (SMEM scalar) past the
    (SUBS, SUB) probe tile, counting strictly-less and less-or-equal
    matches per probe element. Static loop bound (the padded build
    length); sentinel-tail elements count exactly like searchsorted's."""
    p = probe_ref[0]                           # (SUBS, SUB) int32
    zero = jnp.zeros((_SUBS, _SUB), jnp.int32)

    def body(j, carry):
        lt, le = carry
        b = build_ref[j]                       # scalar from SMEM
        lt = lt + jnp.where(b < p, 1, 0).astype(jnp.int32)
        le = le + jnp.where(b <= p, 1, 0).astype(jnp.int32)
        return lt, le

    lt, le = jax.lax.fori_loop(
        0, build_ref.shape[0], body, (zero, zero))
    lt_ref[0] = lt
    le_ref[0] = le


def probe_lo_hi(
    sorted_key: jnp.ndarray,
    probe_key: jnp.ndarray,
    *,
    interpret: bool,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Drop-in twin of the searchsorted left/right pair over the
    sentinel-padded sorted build keys. Returns (lo, hi) with the same
    values AND dtype searchsorted would produce."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    # searchsorted's result dtype is backend/x64 policy, not ours to
    # guess: read it off a degenerate call (dead code once traced)
    out_dt = jnp.searchsorted(sorted_key[:1], probe_key[:1]).dtype

    n = probe_key.shape[0]
    pad = (-n) % _BLOCK
    probe = probe_key.astype(jnp.int32)
    if pad:
        probe = jnp.concatenate([probe, jnp.zeros((pad,), jnp.int32)])
    nb = (n + pad) // _BLOCK
    probe3 = probe.reshape(nb, _SUBS, _SUB)
    build = sorted_key.astype(jnp.int32)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(nb,),
        in_specs=[pl.BlockSpec((1, _SUBS, _SUB), lambda i, b: (i, 0, 0))],
        out_specs=[
            pl.BlockSpec((1, _SUBS, _SUB), lambda i, b: (i, 0, 0)),
        ] * 2,
    )
    lt, le = pl.pallas_call(
        _probe_kernel,
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct((nb, _SUBS, _SUB), jnp.int32),
        ] * 2,
        interpret=interpret,
    )(build, probe3)
    lo = lt.reshape(-1)[:n].astype(out_dt)
    hi = le.reshape(-1)[:n].astype(out_dt)
    return lo, hi
