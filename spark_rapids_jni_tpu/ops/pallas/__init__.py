"""Maintained Pallas kernel tier for the hot inner loops.

``ops/pallas_q1.py`` proved the headroom for q1 empirically (one fused
streaming pass, no int64 in the hot loop) but was a one-off outside the
dispatch/fusion machinery. This package promotes it to a pattern: each
kernel here is a drop-in per-op device function that an XLA call site
swaps in at TRACE time, so a Pallas kernel inherits shape bucketing, the
executable cache and donation exactly like its XLA twin (the tier
decision rides every dispatch cache key via ``kernels_digest``, so a
tier flip can never reuse a stale executable).

Contract, enforced by tpulint rule 19 (``pallas-kernel-must-have-oracle``)
and tests/test_pallas.py:

- every kernel registers here with its XLA **bit-identity oracle** twin
  declared; forcing ``kernels.tier=xla`` must reproduce the pre-tier
  path byte-for-byte at every bucket size;
- on backends without Mosaic support (CPU tier-1) kernels run in the
  Pallas interpreter or fall back to XLA with a recorded reason —
  never a silent behavior change (``record_kernel_tier``);
- unsupported shapes/dtypes/aggregates fall back to the oracle with a
  recorded reason via :func:`fall_back`.

Tier selection: ``kernels.tier`` config (``xla`` | ``pallas`` | ``auto``,
short env var SPARK_RAPIDS_TPU_KERNEL_TIER checked first) with per-op
``kernels.tier_overrides`` ("op=tier,op=tier").
"""

from __future__ import annotations

import os
from typing import NamedTuple

from spark_rapids_jni_tpu.telemetry.events import record_kernel_tier
from spark_rapids_jni_tpu.utils.config import get_option

__all__ = [
    "KernelSpec",
    "TierDecision",
    "register_kernel",
    "registered",
    "decide",
    "fall_back",
    "resolved_tier",
    "kernels_digest",
]

_TIERS = ("xla", "pallas", "auto")


class KernelSpec(NamedTuple):
    """One registered kernel: the op name its call site decides under,
    the dotted path of its XLA bit-identity oracle (kept reachable by
    forcing ``kernels.tier=xla``), and a one-line description."""

    name: str
    oracle: str
    doc: str


class TierDecision(NamedTuple):
    """A trace-time tier pick for one op. ``tier`` is what actually
    traces ("pallas" | "xla"); ``mode`` is how ("native" | "interpret"
    | "oracle"); ``reason`` says why (recorded in telemetry)."""

    tier: str
    mode: str
    reason: str

    @property
    def use_pallas(self) -> bool:
        return self.tier == "pallas"

    @property
    def interpret(self) -> bool:
        return self.mode == "interpret"


_registry: dict[str, KernelSpec] = {}


def register_kernel(name: str, *, oracle: str, doc: str = "") -> KernelSpec:
    """Register a Pallas kernel with its declared XLA oracle twin.

    ``oracle`` is the dotted path of the XLA implementation that
    ``kernels.tier=xla`` routes to — non-empty by contract (tpulint
    rule 19 lints the call site; this validates at import)."""
    if not oracle or not str(oracle).strip():
        raise ValueError(
            f"register_kernel({name!r}): every pallas kernel must declare "
            f"its XLA bit-identity oracle twin"
        )
    spec = KernelSpec(str(name), str(oracle), str(doc))
    _registry[spec.name] = spec
    return spec


def registered() -> dict[str, KernelSpec]:
    """Snapshot of registered kernels (name -> spec)."""
    return dict(_registry)


def _backend() -> str:
    import jax

    try:
        return str(jax.default_backend())
    except Exception:
        return "unknown"


def resolved_tier(op: str) -> str:
    """The configured tier for ``op``: per-op override, else the global
    ``kernels.tier`` (short env var SPARK_RAPIDS_TPU_KERNEL_TIER first)."""
    raw = os.environ.get("SPARK_RAPIDS_TPU_KERNEL_TIER")
    tier = (raw or get_option("kernels.tier") or "xla").strip().lower()
    for entry in str(get_option("kernels.tier_overrides")).split(","):
        entry = entry.strip()
        if not entry:
            continue
        key, _, value = entry.partition("=")
        if key.strip() == op:
            tier = value.strip().lower()
    if tier not in _TIERS:
        raise ValueError(
            f"kernels.tier for {op!r} must be one of {_TIERS}, got {tier!r}"
        )
    return tier


def decide(op: str) -> TierDecision:
    """Pick the tier for one op at trace time and record the decision.

    ``xla`` always wins when configured (the oracle stays reachable at
    every bucket size); ``pallas`` off-TPU runs the interpreter (tier-1
    CPU testing); ``auto`` is pallas on TPU and a recorded xla fallback
    elsewhere."""
    tier = resolved_tier(op)
    if tier == "xla":
        decision = TierDecision("xla", "oracle", "config")
    elif tier == "pallas":
        if _backend() == "tpu":
            decision = TierDecision("pallas", "native", "config")
        else:
            decision = TierDecision("pallas", "interpret", "no_pallas_backend")
    else:  # auto
        if _backend() == "tpu":
            decision = TierDecision("pallas", "native", "auto")
        else:
            decision = TierDecision("xla", "oracle", "no_pallas_backend")
    record_kernel_tier(
        op, tier=decision.tier, mode=decision.mode, reason=decision.reason)
    return decision


def fall_back(op: str, reason: str) -> TierDecision:
    """A pallas-decided op cannot run this trace (unsupported dtype /
    shape / aggregate...): hand it to the XLA oracle, recorded."""
    decision = TierDecision("xla", "oracle", reason)
    record_kernel_tier(op, tier="xla", mode="oracle", reason=reason)
    return decision


def kernels_digest() -> tuple:
    """The tier configuration as a hashable cache-key component.

    runtime/dispatch.py folds this into every executable-cache key (and
    fusion fingerprints inherit it through dispatch), so flipping
    ``kernels.tier`` or an override can never replay an executable
    traced under the other tier."""
    raw = os.environ.get("SPARK_RAPIDS_TPU_KERNEL_TIER")
    return (
        (raw or str(get_option("kernels.tier"))).strip().lower(),
        str(get_option("kernels.tier_overrides")).strip(),
    )


# kernel modules self-register on import; q1 (which pulls in the TPC-H
# model constants) registers when ops.pallas.q1 / ops.pallas_q1 loads
from spark_rapids_jni_tpu.ops.pallas import (  # noqa: E402  (registration)
    groupby_accumulate as groupby_accumulate,
    hash_probe as hash_probe,
    row_transpose as row_transpose,
)
