"""Bounded-groupby accumulate kernel: the masked per-group reduction
loop of ``groupby_aggregate_bounded`` as ONE streaming Pallas pass.

Generalizes ops/pallas/q1.py's sub-block int32-limb scheme to arbitrary
bounded domains (any ``m`` slots) and arbitrary aggregate lane sets:

- the caller (ops/groupby.py) turns each aggregate into int32 LANES —
  a row-count lane, a valid-count lane per column, 16-bit limb lanes
  for integer sums (a 64-bit value splits into four limbs, each exact:
  ``v = sum_k limb_k << 16k`` with the top limb arithmetic-shifted),
  and a sentinel-masked value lane per min/max;
- each 2048-row grid block reduces in 256-row sub-blocks so every int32
  partial provably fits (|limb| < 2^16, x256 < 2^24 << 2^31);
- the tiny (blocks*subs, m*L) partial tensor is combined OUTSIDE the
  kernel by XLA in int64 — limb recombination is exact mod 2^64, which
  is exactly the oracle's wrapping int64 sum, so integer aggregates are
  bit-identical to ``per_group`` under any row count. Float aggregates
  are never kernelized (summation-order sensitivity would break the
  bit-identity contract): the call site falls back with reason
  ``float_agg``.

Mosaic-conformance posture inherited from q1's round-5 rewrite: every
intermediate keeps (sublane, lane) structure, blocks are pre-shaped on
the XLA side to (SUBS, SUB) = (8, 256), reductions keep dims, and the
output tile assembles by broadcasted_iota where-selects — no rank
changes, no 1-D vectors, no lane concatenation.
"""

from __future__ import annotations

from typing import Sequence

import jax
import jax.numpy as jnp

from spark_rapids_jni_tpu.ops.pallas import register_kernel

_BLOCK = 2048      # rows per grid step (16 x 128 int32 tile)
_SUB = 256         # rows per int32-safe partial (2^16 * 256 < 2^31)
_SUBS = _BLOCK // _SUB
_LIMB = 16         # limb width: |limb| < 2^16 keeps sub-block sums exact
_MAX_COLS = 2048   # cap on m*L lanes (16 KiB int32 output tile per sub)

register_kernel(
    "groupby.bounded_accumulate",
    oracle="spark_rapids_jni_tpu.ops.groupby.groupby_aggregate_bounded "
           "(tier=xla per_group masked reductions)",
    doc="per-group partial sums / counts / min / max over planner-"
        "declared bounded key domains, int32 limbs in-kernel, int64 "
        "recombination outside",
)


def unsupported_reason(
    n: int, m: int, lane_count: int
) -> str | None:
    """Static (trace-time) eligibility of one accumulate launch; a
    non-None reason routes the op to the XLA oracle, recorded."""
    if n == 0:
        return "empty_input"
    if m * lane_count > _MAX_COLS:
        return "too_many_lanes"
    return None


def limb_count(itemsize: int) -> int:
    """How many 16-bit limb lanes an integer column of ``itemsize``
    bytes needs. 1- and 2-byte values ride as a single int32 lane
    (|v| <= 2^15 keeps the 256-row partial exact without splitting)."""
    return max(1, (int(itemsize) * 8) // _LIMB)


def split_limbs(values: jnp.ndarray, itemsize: int) -> list[jnp.ndarray]:
    """Exact 16-bit limb decomposition of an integer column (XLA side).

    ``v = sum_k limbs[k] << 16k``: low limbs are masked (in [0, 2^16)),
    the top limb is arithmetic-shifted (signed), so recombination in
    wrapping int64 reproduces the oracle's int64 sum bit-for-bit."""
    k = limb_count(itemsize)
    if k == 1:
        return [values.astype(jnp.int32)]
    limbs = []
    for i in range(k - 1):
        limbs.append(
            ((values >> (_LIMB * i)) & ((1 << _LIMB) - 1)).astype(jnp.int32))
    limbs.append((values >> (_LIMB * (k - 1))).astype(jnp.int32))
    return limbs


def combine_limbs(limb_totals: Sequence[jnp.ndarray]) -> jnp.ndarray:
    """int64 recombination of per-limb totals — exact mod 2^64."""
    total = limb_totals[0].astype(jnp.int64)
    for i, t in enumerate(limb_totals[1:], start=1):
        total = total + (t.astype(jnp.int64) << (_LIMB * i))
    return total


def _round_up(x: int, mult: int) -> int:
    return -(-x // mult) * mult


def _make_kernel(m: int, lane_meta: tuple[tuple[str, int], ...], total: int):
    """Kernel closure over the static layout: one grid step turns
    (1, SUBS, SUB) gid + lane slices into a (1, SUBS, total) int32
    partial tile, column g*L+li = group g's partial of lane li."""
    lane_n = len(lane_meta)

    def kernel(gid_ref, *refs):
        out_ref = refs[-1]
        lane_refs = refs[:-1]
        gid = gid_ref[0]                       # (SUBS, SUB)
        col_ids = jax.lax.broadcasted_iota(
            jnp.int32, (_SUBS, total), 1)
        acc = jnp.zeros((_SUBS, total), jnp.int32)
        for g in range(m):
            mask = gid == g
            for li, (op, neutral) in enumerate(lane_meta):
                lane = lane_refs[li][0]        # (SUBS, SUB)
                masked = jnp.where(mask, lane, jnp.int32(neutral))
                if op == "sum":
                    p = jnp.sum(masked, axis=1, keepdims=True,
                                dtype=jnp.int32)
                elif op == "min":
                    p = jnp.min(masked, axis=1, keepdims=True)
                else:  # max
                    p = jnp.max(masked, axis=1, keepdims=True)
                # each (group, lane) column is written exactly once, so a
                # where-select needs no accumulation read-modify-write
                acc = jnp.where(col_ids == g * lane_n + li, p, acc)
        out_ref[0] = acc

    return kernel


def accumulate(
    gid: jnp.ndarray,
    lanes: Sequence[jnp.ndarray],
    lane_meta: tuple[tuple[str, int], ...],
    m: int,
    *,
    interpret: bool,
) -> tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """One kernel launch over all lanes.

    ``gid``: int32[n] dense group ids in [0, m]; m = "no group" (shard
    padding / domain-missed rows — matches no in-kernel mask, exactly
    like the oracle's phantom-row contract). ``lanes``: int32[n] arrays,
    one per ``lane_meta`` entry ``(op, neutral)`` with op in
    sum|min|max and a static int32 neutral (0 for sums, the oracle's
    minmax_sentinel for min/max, so empty groups reproduce the oracle's
    sentinel fill).

    Returns ``(sums, mins, maxs)``, each (m, L): int64 totals for sum
    lanes, int32 reductions for min/max lanes (read only the columns
    whose op matches).
    """
    from jax.experimental import pallas as pl

    lane_n = len(lane_meta)
    total = _round_up(max(m * lane_n, 1), 128)
    n = gid.shape[0]
    pad = (-n) % _BLOCK
    if pad:
        # padding rows join NO group (gid = m); lane fill is the lane's
        # neutral so even an unmasked bug could not bend a reduction
        gid = jnp.concatenate([gid, jnp.full((pad,), m, jnp.int32)])
        lanes = [
            jnp.concatenate(
                [lane, jnp.full((pad,), jnp.int32(neutral))])
            for lane, (_, neutral) in zip(lanes, lane_meta)
        ]
    nb = (n + pad) // _BLOCK
    # blocks pre-shaped on the XLA side to the kernel's (SUBS, SUB)
    # layout — in-kernel rank-changing reshapes are what Mosaic rejects
    gid3 = gid.reshape(nb, _SUBS, _SUB)
    lanes3 = [lane.reshape(nb, _SUBS, _SUB) for lane in lanes]
    spec = pl.BlockSpec((1, _SUBS, _SUB), lambda i: (i, 0, 0))
    out = pl.pallas_call(
        _make_kernel(m, tuple(lane_meta), total),
        out_shape=jax.ShapeDtypeStruct((nb, _SUBS, total), jnp.int32),
        grid=(nb,),
        in_specs=[spec] * (1 + lane_n),
        out_specs=pl.BlockSpec((1, _SUBS, total), lambda i: (i, 0, 0)),
        interpret=interpret,
    )(gid3, *lanes3)
    # tiny combine outside the kernel: (nb*SUBS, m*L) partials -> (m, L)
    flat = out.reshape(nb * _SUBS, total)[:, : m * lane_n]
    sums = jnp.sum(flat.astype(jnp.int64), axis=0).reshape(m, lane_n)
    mins = jnp.min(flat, axis=0).reshape(m, lane_n)
    maxs = jnp.max(flat, axis=0).reshape(m, lane_n)
    return sums, mins, maxs
