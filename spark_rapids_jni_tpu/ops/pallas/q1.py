"""Fused TPC-H q1 Pallas kernel: the whole query as ONE streaming pass.

Folded into the maintained kernel tier from ops/pallas_q1.py (the
VERDICT r3 one-off that proved the headroom empirically). It fuses the
q1 pipeline (filter + decimal derives + per-group partial sums) into
one pass with NO int64 arithmetic anywhere in the hot loop:

- inputs are int32 (the planner knows q1's money columns fit int32 per
  row: price < 1.05e7, disc_price = price*(100-disc) < 1.05e9 < 2^31);
- charge (disc_price * (100+tax), up to ~1.1e11) never materializes per
  row: disc_price splits into 16-bit halves A,B and the kernel sums
  A*(100+tax) and B*(100+tax) lanes, recombined as 2^16*sum_A + sum_B
  AFTER the reduction (exact int32 limb arithmetic);
- group ids come from the planner-declared TPC-H flag domains (like
  groupby_aggregate_bounded) — no sort, no gather;
- each 2048-row grid block reduces in 256-row sub-blocks so every int32
  partial provably fits (max lane value 7.1e6 * 256 < 2^31), and the
  tiny (blocks, sub, m, lanes) partial tensor is combined in int64 by
  XLA outside the kernel.

The partials run through ``dispatch.call`` (bucket_rows=False: inputs
are already _BLOCK-quantized by the caller, so row counts collapse to
block multiples and the Pallas grid is specialized per shape anyway) —
one cached executable per block-multiple x interpret flag x tier
digest, single-flight compiled like every other op.

Result layout matches tpch_q1 (keys + 8 aggregates), real groups first
in lexicographic order (static — no output sort).

Reference perf-design analogue: the reference's row_conversion.cu grid/
block discipline (:315-367) — saturate the chip with a 1-D grid of
fixed-size blocks and do all reduction work in fast memory.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from spark_rapids_jni_tpu import types as t
from spark_rapids_jni_tpu.columnar import Column, Table
from spark_rapids_jni_tpu.models.tpch import (
    _Q1_CUTOFF_DAYS,
    _Q1_LS_DOMAIN,
    _Q1_RF_DOMAIN,
    L_DISCOUNT,
    L_EXTENDEDPRICE,
    L_LINESTATUS,
    L_QUANTITY,
    L_RETURNFLAG,
    L_SHIPDATE,
    L_TAX,
)
from spark_rapids_jni_tpu.ops.pallas import register_kernel

_BLOCK = 2048      # rows per grid step (16 x 128 int32 tile)
_SUB = 256         # rows per int32-safe partial (7.1e6 * 256 < 2^31)
_M = 8             # 3*2 real groups + dropped-row slot 6 + domain-miss 7
_LANES = 16        # 9 used lanes padded to a tile-friendly width

# lane indices
_L_COUNT, _L_QTY, _L_PHI, _L_PLO, _L_DISC = 0, 1, 2, 3, 4
_L_DPA, _L_DPB, _L_CHA, _L_CHB = 5, 6, 7, 8

_P_SPLIT = 12      # price = p_hi * 2^12 + p_lo  (p_hi < 2^12 at 1.05e7)
_DP_SPLIT = 16     # disc_price = A * 2^16 + B   (A < 2^15 at 1.05e9)

register_kernel(
    "tpch_q1.fused",
    oracle="spark_rapids_jni_tpu.models.tpch.tpch_q1_planned_result "
           "(bounded-domain plan through fusion/groupby, tier=xla)",
    doc="whole-query q1: filter + decimal derives + bounded-domain "
        "partial sums in one pass, int32 limbs in the hot loop",
)


def _q1_kernel(qty_ref, price_ref, disc_ref, tax_ref, ship_ref, rf_ref,
               ls_ref, out_ref):
    """One grid step: (1, SUBS, SUB) int32 column slices -> (1, SUBS,
    M*LANES) int32 partial sums. Zero int64 ops.

    Round-5 Mosaic-conformance rewrite (the r04 kernel crashed at
    runtime on the real chip after interpret-only development): every
    intermediate now keeps a (sublane, lane) structure the TPU layout
    system supports — the host pre-shapes blocks to (SUBS, SUB) =
    (8, 256), two int32 tiles, instead of in-kernel (2048,) -> (8, 256)
    layout-changing reshapes; reductions keep dims ((8, 1) per group
    lane, never 1-D (8,) vectors); and the output assembles by lane
    concatenation into EXACTLY one (8, 128) int32 tile — no flattening
    store."""
    qty = qty_ref[0]      # (SUBS, SUB) = (8, 256)
    price = price_ref[0]
    disc = disc_ref[0]
    tax = tax_ref[0]
    ship = ship_ref[0]
    rf = rf_ref[0]
    ls = ls_ref[0]

    keep = ship <= _Q1_CUTOFF_DAYS
    # flag codes via the declared domains (planner facts, not data sort)
    rfc = jnp.where(rf == _Q1_RF_DOMAIN[0], 0,
                    jnp.where(rf == _Q1_RF_DOMAIN[1], 1,
                              jnp.where(rf == _Q1_RF_DOMAIN[2], 2, -1)))
    lsc = jnp.where(ls == _Q1_LS_DOMAIN[0], 0,
                    jnp.where(ls == _Q1_LS_DOMAIN[1], 1, -1))
    miss = (rfc < 0) | (lsc < 0)
    gid = jnp.where(keep & ~miss, rfc * 2 + lsc,
                    jnp.where(keep, 7, 6)).astype(jnp.int32)

    w = 100 - disc
    dp = price * w                      # < 1.05e9, int32-exact
    w2 = 100 + tax
    a = dp >> _DP_SPLIT                 # < 2^15
    b = dp & ((1 << _DP_SPLIT) - 1)     # < 2^16

    lanes = [
        jnp.ones_like(qty),             # count
        qty,                            # sum_qty
        price >> _P_SPLIT,              # price high limb
        price & ((1 << _P_SPLIT) - 1),  # price low limb
        disc,                           # sum_disc (avg_disc numerator)
        a,                              # disc_price high limb
        b,                              # disc_price low limb
        a * w2,                         # charge high limb  (< 2^22)
        b * w2,                         # charge low limb   (< 2^23)
    ]
    subs = _BLOCK // _SUB
    # assemble the (SUBS, M*LANES) = (8, 128) int32 output tile by
    # broadcast-select accumulation: each (group, lane) partial is a
    # keepdims (8, 1) sum placed at column g*LANES+li via a
    # broadcasted_iota mask — only documented-safe Mosaic constructs
    # (no rank changes, no 1-D vectors, no many-operand lane concat)
    col_ids = jax.lax.broadcasted_iota(
        jnp.int32, (subs, _M * _LANES), 1)
    acc = jnp.zeros((subs, _M * _LANES), jnp.int32)
    for g in range(_M):
        mask = gid == g
        for li, lane in enumerate(lanes):
            # dtype pinned: under x64 jnp.sum would promote the int32
            # partial to int64, which Mosaic rejects at the int32 out_ref
            # swap — every partial is int32-exact by the limb bounds above
            p = jnp.sum(jnp.where(mask, lane, 0), axis=1,
                        keepdims=True, dtype=jnp.int32)   # (SUBS, 1)
            acc = acc + jnp.where(
                col_ids == g * _LANES + li, p, 0)
    out_ref[0] = acc


def _q1_partials_fn(row_args, aux_args, row_valids, *, interpret: bool):
    """dispatch.call body (rule-8 route — the jit and its executable
    cache now come from dispatch, not a module-local jax.jit). The
    row_valids mask is unused by design: bucket_rows=False means
    dispatch never pads here, and the caller's own padding rows are
    filter-failing by construction (ship parked past the cutoff), so
    no padding row can reach slots 0-5."""
    from jax.experimental import pallas as pl

    ((qty, price, disc, tax, ship, rf, ls),) = row_args
    n = qty.shape[0]
    nb = n // _BLOCK
    subs = _BLOCK // _SUB
    # blocks pre-shaped on the XLA side to the kernel's (SUBS, SUB)
    # layout — in-kernel rank-changing reshapes are what Mosaic rejects
    cols = [c.reshape(nb, subs, _SUB) for c in
            (qty, price, disc, tax, ship, rf, ls)]
    spec = pl.BlockSpec((1, subs, _SUB), lambda i: (i, 0, 0))
    out = pl.pallas_call(
        _q1_kernel,
        out_shape=jax.ShapeDtypeStruct(
            (nb, subs, _M * _LANES), jnp.int32),
        grid=(nb,),
        in_specs=[spec] * 7,
        out_specs=pl.BlockSpec((1, subs, _M * _LANES),
                               lambda i: (i, 0, 0)),
        interpret=interpret,
    )(*cols)
    # tiny int64 combine outside the kernel: (nb, subs, m, lanes) -> (m, lanes)
    return jnp.sum(
        out.reshape(nb * subs, _M, _LANES).astype(jnp.int64), axis=0)


def _q1_pallas_partials(qty, price, disc, tax, ship, rf, ls,
                        interpret: bool = False):
    from functools import partial

    from spark_rapids_jni_tpu.runtime import dispatch

    # bucket_rows=False: the caller already quantized rows to _BLOCK
    # multiples (a dispatch bucket need not be), so dispatch memoizes
    # one executable per exact block-multiple shape — the same collapse
    # the old module-local jit relied on, now in the shared cache
    return dispatch.call(
        "pallas_q1.partials",
        partial(_q1_partials_fn, interpret=interpret),
        ((qty, price, disc, tax, ship, rf, ls),),
        statics=("interpret", bool(interpret)),
        slice_rows=False,
        bucket_rows=False,
    )


def tpch_q1_pallas(lineitem: Table, interpret: bool = False) -> Table:
    """q1 through the fused kernel. Same output schema and ordering as
    ``tpch_q1_planned`` (keys + 8 aggregates; real groups lexicographic
    first; domain-missed/filtered rows excluded). ``interpret=True`` runs
    the Pallas interpreter (CPU testing).

    Planner contract: NON-NULLABLE measure and key columns (the kernel
    zero-fills would otherwise silently break SQL null-skipping
    aggregates). Nullability is static schema information, so the guard
    below works under jit — a nullable input raises at trace time and the
    planner keeps the general pipeline for that batch shape."""
    for idx in (L_QUANTITY, L_EXTENDEDPRICE, L_DISCOUNT, L_TAX,
                L_RETURNFLAG, L_LINESTATUS, L_SHIPDATE):
        if lineitem.column(idx).validity is not None:
            raise NotImplementedError(
                "tpch_q1_pallas requires non-nullable inputs (planner "
                "contract); a nullable column routes the batch to "
                "tpch_q1/tpch_q1_planned, whose aggregates skip nulls"
            )
    n = lineitem.num_rows
    pad = (-n) % _BLOCK

    def as_i32(col_idx, fill):
        c = lineitem.column(col_idx)
        v = c.data.astype(jnp.int32)
        if pad:
            v = jnp.concatenate(
                [v, jnp.full((pad,), jnp.int32(fill))])
        return v

    # null/padding rows must fail the filter: park them past the cutoff
    drop = _Q1_CUTOFF_DAYS + 1
    qty = as_i32(L_QUANTITY, 0)
    price = as_i32(L_EXTENDEDPRICE, 0)
    disc = as_i32(L_DISCOUNT, 0)
    tax = as_i32(L_TAX, 0)
    ship = as_i32(L_SHIPDATE, drop)
    rf = as_i32(L_RETURNFLAG, 0)
    ls = as_i32(L_LINESTATUS, 0)

    agg = _q1_pallas_partials(qty, price, disc, tax, ship, rf, ls,
                              interpret=interpret)

    counts = agg[:6, _L_COUNT]
    present = counts > 0
    sum_qty = agg[:6, _L_QTY]
    sum_price = (agg[:6, _L_PHI] << _P_SPLIT) + agg[:6, _L_PLO]
    sum_disc = agg[:6, _L_DISC]
    sum_dp = (agg[:6, _L_DPA] << _DP_SPLIT) + agg[:6, _L_DPB]
    sum_ch = (agg[:6, _L_CHA] << _DP_SPLIT) + agg[:6, _L_CHB]

    denom = jnp.maximum(counts, 1).astype(jnp.float64)

    def avg(total, scale):
        return total.astype(jnp.float64) / denom * (10.0 ** scale)

    keys_rf = np.repeat(np.asarray(_Q1_RF_DOMAIN, np.int8), 2)
    keys_ls = np.tile(np.asarray(_Q1_LS_DOMAIN, np.int8), 3)
    return Table([
        Column(t.INT8, jnp.asarray(keys_rf), present),
        Column(t.INT8, jnp.asarray(keys_ls), present),
        Column(t.decimal64(-2), sum_qty, present),
        Column(t.decimal64(-2), sum_price, present),
        Column(t.decimal64(-4), sum_dp, present),
        Column(t.decimal64(-6), sum_ch, present),
        Column(t.FLOAT64, avg(sum_qty, -2), present),
        Column(t.FLOAT64, avg(sum_price, -2), present),
        Column(t.FLOAT64, avg(sum_disc, -2), present),
        Column(t.INT64, counts, present),
    ])
