"""Device regexp_extract / regexp_replace: capture tracking over the
byte-DFA machinery (VERDICT r4 item 7).

``regexp_contains`` needs one DFA verdict per row; EXTRACT needs the
capture-group BOUNDARIES of the first match, which a single DFA cannot
produce. The classic answer is a tagged automaton; the TPU-shaped answer
here is the two-pass scheme the verdict sketched, specialized to LINEAR
patterns (a concatenation of literals and quantified byte-classes, with
non-nested capture groups — which covers the bulk of practical extraction
patterns: ``(\\d+)``, ``id=(\\w+);``, ``([a-z]+)-(\\d+)``, ...):

1. **Suffix feasibility (reverse DFA passes).** For each element index k,
   a DFA for the REVERSED suffix pattern ``rev(E_m)..rev(E_k)`` runs once
   over the reversed padded char matrix, yielding ``feas_k[i]`` = "can
   elements k..m match starting at byte i" for ALL i in one O(n*W) scan
   (state-table gathers, zero scatters — the regexp_contains cost model).
2. **Greedy boundary walk (forward, one masked reduction per element).**
   The match start is the smallest feasible i (Java's leftmost rule).
   Element k's end is then the LARGEST (greedy; smallest for lazy ``?``)
   t with ``t - p`` in the quantifier range, all bytes in ``[p, t)``
   inside the class (one reverse-cummin "next non-class byte" pass), and
   ``feas_{k+1}[t]`` — exactly Java's backtracking priority, computed
   without backtracking because feasibility already encodes "the rest
   can still match".

Group values are substring gathers over the recorded boundaries.
``regexp_replace`` iterates the same first-match engine from a moving
cursor (bounded rounds, Java's empty-match advance rule) and rebuilds
rows with a piece-table gather.

Correctness scope (dispatcher-enforced): linear patterns only (no
alternation, no nesting), ASCII-only classes/literals, and all-ASCII
input rows (checked at runtime — ``.`` and negated classes are byte-level
here, which equals char-level exactly on ASCII data). Everything else
takes the host java.util.regex emulation — the two-engine posture of
regexp_contains/get_json_object. cuDF analogue: the vendored device regex
engine (SURVEY.md section 2.2).
"""

from __future__ import annotations

import functools
from functools import partial
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np

from spark_rapids_jni_tpu.ops.regex_device import (
    MAX_DFA_STATES,
    MAX_EXPANSION,
    RegexUnsupported,
    _closure,
    _Nfa,
)
from spark_rapids_jni_tpu.utils.tracing import func_range

_MAX_ELEMENTS = 24
_ANY_NO_NUL = frozenset(range(1, 256))

_D = frozenset(range(0x30, 0x3A))
_W_SET = (frozenset(range(0x30, 0x3A)) | frozenset(range(0x41, 0x5B))
          | frozenset(range(0x61, 0x7B)) | {0x5F})
_S = frozenset(b" \t\n\x0b\f\r")
_ASCII = frozenset(range(1, 128))
_ASCII_NO_NL = _ASCII - {0x0A}


class LinearElement(NamedTuple):
    byteset: frozenset  # candidate bytes (single-byte steps)
    lo: int             # min repetitions
    hi: Optional[int]   # max repetitions, None = unbounded
    lazy: bool


class LinearPattern(NamedTuple):
    elements: tuple            # of LinearElement
    groups: tuple              # group g (1-based) -> (first_el, last_el+1)
    anchored_start: bool
    anchored_end: bool


class _LinParser:
    """Linear-subset parser: concatenation of quantified single-byte
    atoms and flat capture groups. Anything outside the subset raises
    RegexUnsupported (the dispatcher's host-fallback signal)."""

    def __init__(self, pattern: str):
        self.p = pattern
        self.i = 0

    def _peek(self):
        return self.p[self.i] if self.i < len(self.p) else None

    def _take(self):
        c = self._peek()
        if c is None:
            raise RegexUnsupported("unexpected end of pattern")
        self.i += 1
        return c

    def parse(self) -> LinearPattern:
        anchored_start = anchored_end = False
        if self._peek() == "^":
            self._take()
            anchored_start = True
        elements: list[LinearElement] = []
        groups: list[tuple[int, int]] = []
        while self._peek() is not None:
            c = self._peek()
            if c == "$":
                self._take()
                if self._peek() is not None:
                    raise RegexUnsupported("mid-pattern $")
                anchored_end = True
                break
            if c == "|":
                raise RegexUnsupported("alternation")
            if c == ")":
                raise RegexUnsupported("unbalanced )")
            if c == "(":
                self._take()
                capturing = True
                if self._peek() == "?":
                    self._take()
                    if self._peek() != ":":
                        raise RegexUnsupported("(?...) construct")
                    self._take()
                    capturing = False
                first = len(elements)
                while self._peek() not in (")", None):
                    if self._peek() in ("(",):
                        raise RegexUnsupported("nested group")
                    if self._peek() == "|":
                        raise RegexUnsupported("alternation")
                    elements.append(self._quantified_atom())
                if self._take() != ")":
                    raise RegexUnsupported("unbalanced (")
                if self._peek() in ("*", "+", "?", "{"):
                    raise RegexUnsupported("quantified group")
                if capturing:
                    groups.append((first, len(elements)))
                continue
            elements.append(self._quantified_atom())
        if not elements:
            raise RegexUnsupported("empty pattern")
        if len(elements) > _MAX_ELEMENTS:
            raise RegexUnsupported(f"more than {_MAX_ELEMENTS} elements")
        return LinearPattern(tuple(elements), tuple(groups),
                             anchored_start, anchored_end)

    def _quantified_atom(self) -> LinearElement:
        byteset = self._atom()
        lo, hi = 1, 1
        c = self._peek()
        if c == "*":
            self._take()
            lo, hi = 0, None
        elif c == "+":
            self._take()
            lo, hi = 1, None
        elif c == "?":
            self._take()
            lo, hi = 0, 1
        elif c == "{":
            self._take()
            digs = ""
            while self._peek() and self._peek().isdigit():
                digs += self._take()
            if not digs:
                raise RegexUnsupported("bad {} quantifier")
            lo = int(digs)
            if self._peek() == ",":
                self._take()
                digs2 = ""
                while self._peek() and self._peek().isdigit():
                    digs2 += self._take()
                hi = int(digs2) if digs2 else None
            else:
                hi = lo
            if self._take() != "}":
                raise RegexUnsupported("bad {} quantifier")
            if hi is not None and hi < lo:
                raise RegexUnsupported("bad {} range")
            if lo > MAX_EXPANSION or (hi or 0) > MAX_EXPANSION:
                raise RegexUnsupported("quantifier too large")
        lazy = False
        if self._peek() == "?" and (lo, hi) != (1, 1):
            self._take()
            lazy = True
        if self._peek() in ("*", "+", "?", "{") and (lo, hi) != (1, 1):
            raise RegexUnsupported("double quantifier")
        return LinearElement(byteset, lo, hi, lazy)

    def _atom(self) -> frozenset:
        c = self._take()
        if c == ".":
            byteset = _ASCII_NO_NL
        elif c == "[":
            byteset = self._char_class()
        elif c == "\\":
            byteset = self._escape()
        elif c in "*+?{":
            raise RegexUnsupported("dangling quantifier")
        elif ord(c) > 0x7F:
            raise RegexUnsupported("non-ASCII literal")
        else:
            byteset = frozenset([ord(c)])
        if 0 in byteset:
            # byte 0 is the row padding byte of the padded char matrix:
            # an atom that can match NUL would match padding and run
            # across row boundaries — host engine territory
            raise RegexUnsupported("NUL byte in pattern")
        return byteset

    def _escape(self) -> frozenset:
        c = self._take()
        table = {"d": _D, "D": _ASCII - _D, "w": _W_SET,
                 "W": _ASCII - _W_SET, "s": _S, "S": _ASCII - _S,
                 "n": frozenset(b"\n"), "t": frozenset(b"\t"),
                 "r": frozenset(b"\r")}
        if c in table:
            return table[c]
        # ord(c) == 0 (an escaped literal NUL) is excluded with the
        # non-ASCII range: its byteset would contain the padding byte
        if not c.isalnum() and 0 < ord(c) <= 0x7F:
            return frozenset([ord(c)])
        # alnum escapes are Java metasyntax; >0x7F would index past the
        # 256-entry byte transition rows — both are host-engine territory
        raise RegexUnsupported(f"escape \\{c}")

    def _char_class(self) -> frozenset:
        negated = False
        if self._peek() == "^":
            self._take()
            negated = True
        members: set[int] = set()
        first = True
        while True:
            c = self._peek()
            if c is None:
                raise RegexUnsupported("unterminated class")
            if c == "]" and not first:
                self._take()
                break
            first = False
            if c == "\\":
                self._take()
                members |= self._escape()
                continue
            self._take()
            if ord(c) > 0x7F:
                raise RegexUnsupported("non-ASCII class member")
            if self._peek() == "-" and self.i + 1 < len(self.p) \
                    and self.p[self.i + 1] != "]":
                self._take()
                d = self._take()
                if d == "\\" or ord(d) > 0x7F or ord(d) < ord(c):
                    raise RegexUnsupported("complex class range")
                members |= set(range(ord(c), ord(d) + 1))
            else:
                members.add(ord(c))
        if negated:
            return _ASCII - frozenset(members)
        if not members:
            raise RegexUnsupported("empty class")
        return frozenset(members)


def parse_linear(pattern: str) -> LinearPattern:
    return _LinParser(pattern).parse()


# ---------------------------------------------------------------------------
# suffix feasibility DFAs
# ---------------------------------------------------------------------------


def _append_element_rev(nfa: _Nfa, cur: int, el: LinearElement) -> int:
    """Chain one element (class semantics are order-free, so the reversed
    element is itself) onto ``cur``; returns the new chain end."""
    for _ in range(el.lo):
        s = nfa.new_state()
        nfa.add(cur, el.byteset, s)
        cur = s
    if el.hi is None:
        s = nfa.new_state()
        nfa.add(cur, None, s)
        nfa.add(s, el.byteset, s)
        cur = s
    else:
        end = nfa.new_state()
        nfa.add(cur, None, end)
        for _ in range(el.hi - el.lo):
            s = nfa.new_state()
            nfa.add(cur, el.byteset, s)
            nfa.add(s, None, end)
            cur = s
        cur = end
    return cur


def _subset_construct(nfa: _Nfa, start: int, final: int):
    """NFA -> DFA transition table + accept vector (the regexp_contains
    construction, parameterized for reuse)."""
    d0 = _closure(nfa, frozenset([start]))
    ids = {d0: 0}
    order = [d0]
    trans: list[np.ndarray] = []
    qi = 0
    while qi < len(order):
        cur = order[qi]
        qi += 1
        row = np.full(256, -1, dtype=np.int32)
        move: dict[int, set] = {}
        for s in cur:
            for byteset, tgt in nfa.edges[s]:
                if byteset is None:
                    continue
                for b in byteset:
                    move.setdefault(b, set()).add(tgt)
        cache: dict[frozenset, int] = {}
        for b, tgts in move.items():
            key = frozenset(tgts)
            if key in cache:
                row[b] = cache[key]
                continue
            nxt = _closure(nfa, key)
            if nxt not in ids:
                if len(ids) >= MAX_DFA_STATES:
                    raise RegexUnsupported(
                        f"DFA exceeds {MAX_DFA_STATES} states")
                ids[nxt] = len(ids)
                order.append(nxt)
            row[b] = ids[nxt]
            cache[key] = ids[nxt]
        trans.append(row)
    dead = len(order)
    table = np.concatenate(trans).astype(np.int32)
    table[table < 0] = dead
    table = np.concatenate([table, np.full(256, dead, dtype=np.int32)])
    # host-side DFA compile path, not device execution
    # tpulint: disable=no-host-transfer-in-device-path
    accept = np.array([final in st for st in order] + [False], dtype=bool)
    return table, accept


class CompiledLinear(NamedTuple):
    pattern: LinearPattern
    # per suffix k in 0..m: (table, accept) of the reversed-suffix DFA
    suffix_dfas: tuple


def compile_linear(pattern: str) -> CompiledLinear:
    """Host compile: the linear pattern + one reversed-suffix DFA per
    element boundary. LRU-cached per pattern string; hits/misses are
    recorded as telemetry compile_cache events (rejected patterns raise
    out of the cache — counted as misses)."""
    from spark_rapids_jni_tpu import telemetry

    if telemetry.enabled():
        before = _compile_linear_cached.cache_info().hits
        out = _compile_linear_cached(pattern)
        hit = _compile_linear_cached.cache_info().hits > before
        telemetry.record_compile_cache("regex_linear", hit=hit)
        return out
    return _compile_linear_cached(pattern)


@functools.lru_cache(maxsize=256)
def _compile_linear_cached(pattern: str) -> CompiledLinear:
    lin = parse_linear(pattern)
    m = len(lin.elements)
    dfas = []
    for k in range(m + 1):
        nfa = _Nfa()
        q0 = nfa.new_state()
        # reversed padding prefix: the reverse scan consumes the row's
        # 0x00 tail first, by design  # tpulint: disable=padding-byte-invariant
        nfa.add(q0, frozenset([0]), q0)
        cur = nfa.new_state()
        nfa.add(q0, None, cur)
        if not lin.anchored_end:
            # bytes AFTER the match end (reversed: consumed first)
            nfa.add(cur, _ANY_NO_NUL, cur)
        for el in reversed(lin.elements[k:]):
            cur = _append_element_rev(nfa, cur, el)
        dfas.append(_subset_construct(nfa, q0, cur))
    return CompiledLinear(lin, tuple(dfas))


# ---------------------------------------------------------------------------
# device passes
# ---------------------------------------------------------------------------


def _feasibility(chars: jnp.ndarray, table: np.ndarray,
                 accept: np.ndarray) -> jnp.ndarray:
    """(n, W) padded chars -> (n, W+1) bool: feas[:, t] = the reversed
    DFA accepts after consuming the reversed row down to byte t (i.e.
    the suffix pattern can match starting at t)."""
    n, w = chars.shape
    tbl = jnp.asarray(table)
    acc = jnp.asarray(accept)
    rev_cols = chars[:, ::-1].T  # (W, n)

    def step(state, col):
        nxt = tbl[state * 256 + col.astype(jnp.int32)]
        return nxt, nxt

    init = jnp.zeros((n,), jnp.int32)
    _, states = jax.lax.scan(step, init, rev_cols)  # (W, n)
    all_states = jnp.concatenate([init[None, :], states], axis=0)
    # position t consumed W-t reversed bytes -> state all_states[W-t]
    return acc[all_states[::-1]].T  # (n, W+1)


def _next_nonclass(chars: jnp.ndarray, byteset: frozenset) -> jnp.ndarray:
    """(n, W) -> (n, W+1) int32: nxt[:, i] = smallest j >= i with
    chars[:, j] outside the class (W if the run reaches the pad; byte 0
    is never in a class, so runs always stop at the row end)."""
    n, w = chars.shape
    lut = np.zeros(256, bool)
    lut[list(byteset)] = True
    inclass = jnp.asarray(lut)[chars.astype(jnp.int32)]  # (n, W)
    pos = jnp.arange(w, dtype=jnp.int32)[None, :]
    stop = jnp.where(inclass, jnp.int32(w), pos)  # (n, W)
    # reverse cumulative min: nxt[i] = min(stop[i:], default W)
    rev_min = jax.lax.cummin(stop[:, ::-1], axis=1)[:, ::-1]
    return jnp.concatenate(
        [rev_min, jnp.full((n, 1), w, jnp.int32)], axis=1)


class MatchBounds(NamedTuple):
    matched: jnp.ndarray        # bool[n]
    starts: jnp.ndarray         # int32[n, m] element starts
    ends: jnp.ndarray           # int32[n, m] element ends


def _first_match(chars: jnp.ndarray, comp: CompiledLinear,
                 feas: list[jnp.ndarray],
                 cursor: jnp.ndarray) -> MatchBounds:
    """Boundaries of the leftmost match starting at or after ``cursor``
    (int32[n]), via the greedy walk. All O(n*W) masked reductions."""
    lin = comp.pattern
    n, w = chars.shape
    m = len(lin.elements)
    t_idx = jnp.arange(w + 1, dtype=jnp.int32)[None, :]

    # leftmost feasible start
    start_ok = feas[0] & (t_idx >= cursor[:, None])
    if lin.anchored_start:
        start_ok = start_ok & (t_idx == 0)
    any_start = jnp.any(start_ok, axis=1)
    s = jnp.where(
        any_start,
        jnp.argmax(start_ok, axis=1).astype(jnp.int32),
        jnp.int32(w))

    starts, ends = [], []
    p = s
    for k, el in enumerate(lin.elements):
        nxt = _next_nonclass(chars, el.byteset)
        run_end = jnp.take_along_axis(
            nxt, jnp.clip(p, 0, w)[:, None], axis=1)[:, 0]
        hi_eff = w if el.hi is None else el.hi
        upper = jnp.minimum(p + hi_eff, run_end)
        lower = p + el.lo
        mask = ((t_idx >= lower[:, None]) & (t_idx <= upper[:, None])
                & feas[k + 1])
        if el.lazy:
            j = jnp.min(jnp.where(mask, t_idx, w + 1), axis=1)
        else:
            j = jnp.max(jnp.where(mask, t_idx, -1), axis=1)
        # feasibility guarantees a masked candidate when feas[k][p] holds;
        # unmatched rows just carry harmless clipped positions
        j = jnp.clip(j, 0, w).astype(jnp.int32)
        starts.append(p)
        ends.append(j)
        p = j
    return MatchBounds(any_start, jnp.stack(starts, axis=1),
                       jnp.stack(ends, axis=1))


def _extract_impl(row_args, aux, rvs, *, comp: CompiledLinear, group: int):
    ((chars,),) = row_args
    lin = comp.pattern
    n, w = chars.shape
    feas = [_feasibility(chars, tbl, acc) for tbl, acc in comp.suffix_dfas]
    mb = _first_match(chars, comp, feas, jnp.zeros((n,), jnp.int32))
    if group == 0:
        b = mb.starts[:, 0]
        e = mb.ends[:, -1]
    else:
        first_el, end_el = lin.groups[group - 1]
        if first_el == end_el:  # empty group body: zero-width capture
            b = e = (mb.starts[:, first_el] if first_el < len(lin.elements)
                     else mb.ends[:, -1])
        else:
            b = mb.starts[:, first_el]
            e = mb.ends[:, end_el - 1]
    b = jnp.where(mb.matched, b, 0)
    e = jnp.where(mb.matched, e, 0)
    lengths = (e - b).astype(jnp.int32)
    pos = jnp.arange(w, dtype=jnp.int32)[None, :]
    src = jnp.clip(b[:, None] + pos, 0, w - 1)
    out = jnp.where(pos < lengths[:, None],
                    jnp.take_along_axis(chars, src, axis=1),
                    jnp.uint8(0))
    return lengths, out


@func_range("regexp_extract_device")
def extract_device(chars: jnp.ndarray, comp: CompiledLinear,
                   group: int, dispatch_key: str | None = None
                   ) -> tuple[jnp.ndarray, jnp.ndarray]:
    """(lengths int32[n], out_chars uint8[n, W]) for Spark
    regexp_extract semantics: group'th capture of the first match, ''
    on no-match. ``group`` 0 = the whole match.

    ``dispatch_key`` (the source pattern string) routes the pass through
    the bucketed executable cache: the suffix-DFA tables are baked into
    the trace as constants, so the pattern's identity — which ``comp``
    itself cannot provide stably — must key the executable. None skips
    dispatch (direct trace, for callers already inside a jit)."""
    if dispatch_key is None:
        return _extract_impl(((chars,),), (), None, comp=comp, group=group)
    from spark_rapids_jni_tpu.runtime import dispatch

    return dispatch.rowwise(
        "regexp_extract",
        partial(_extract_impl, comp=comp, group=group),
        (chars,), statics=("extract", dispatch_key, group))


def _replace_impl(row_args, aux, rvs, *, comp: CompiledLinear,
                  replacement: bytes, max_matches: int):
    ((chars, lengths),) = row_args
    lin = comp.pattern
    n, w = chars.shape
    feas = [_feasibility(chars, tbl, acc) for tbl, acc in comp.suffix_dfas]
    rep = np.frombuffer(replacement, np.uint8)
    rl = len(rep)

    # (b, e, hit) per round; non-hit rows park the span at the row end so
    # the piece loop's keep-segment arithmetic degenerates harmlessly
    spans: list[tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]] = []
    cursor = jnp.zeros((n,), jnp.int32)
    active = jnp.ones((n,), jnp.bool_)
    for _ in range(max_matches):
        mb = _first_match(chars, comp, feas, cursor)
        hit = active & mb.matched & (mb.starts[:, 0] <= lengths)
        b = jnp.where(hit, mb.starts[:, 0], lengths)
        e = jnp.where(hit, mb.ends[:, -1], lengths)
        spans.append((b, e, hit))
        # Java empty-match rule: advance at least one byte
        cursor = jnp.where(hit, jnp.maximum(e, b + 1), jnp.int32(w + 1))
        active = hit
    # a row overflows when another match still starts inside the row
    # after the final cursor — the dispatcher recomputes those on host
    t_idx = jnp.arange(w + 1, dtype=jnp.int32)[None, :]
    more = jnp.any(feas[0] & (t_idx >= cursor[:, None])
                   & (t_idx <= lengths[:, None]), axis=1)
    overflowed = jnp.any(more & active)

    # piece-table rebuild: per round, keep [prev_e, b) then the literal
    # replacement; one final tail segment — all masked gathers. Bound:
    # an EMPTY match consumes 0 bytes and inserts rl, so growth per
    # round is rl, not rl-1.
    w_out = w + max_matches * rl + 1
    out = jnp.zeros((n, w_out), jnp.uint8)
    out_pos = jnp.zeros((n,), jnp.int32)
    opos = jnp.arange(w_out, dtype=jnp.int32)[None, :]
    prev_e = jnp.zeros((n,), jnp.int32)
    rep_arr = jnp.asarray(rep) if rl else jnp.zeros((1,), jnp.uint8)

    def paste_input(out, out_pos, seg_start, seg_len):
        src = jnp.clip(seg_start[:, None] + (opos - out_pos[:, None]),
                       0, w - 1)
        seg = jnp.take_along_axis(chars, src, axis=1)
        sel = (opos >= out_pos[:, None]) \
            & (opos < (out_pos + seg_len)[:, None])
        return jnp.where(sel, seg, out), out_pos + seg_len

    for b, e, hit in spans:
        out, out_pos = paste_input(out, out_pos,
                                   prev_e, (b - prev_e).astype(jnp.int32))
        if rl:
            ins = jnp.where(hit, jnp.int32(rl), jnp.int32(0))
            rsel = (opos >= out_pos[:, None]) \
                & (opos < (out_pos + ins)[:, None])
            ridx = jnp.clip(opos - out_pos[:, None], 0, rl - 1)
            out = jnp.where(rsel, rep_arr[ridx], out)
            out_pos = out_pos + ins
        prev_e = e
    out, out_pos = paste_input(out, out_pos, prev_e,
                               (lengths - prev_e).astype(jnp.int32))
    return out_pos, out, overflowed


@func_range("regexp_replace_device")
def replace_device(chars: jnp.ndarray, lengths: jnp.ndarray,
                   comp: CompiledLinear, replacement: bytes,
                   max_matches: int = 8,
                   dispatch_key: str | None = None):
    """Replace ALL matches with a literal replacement, Java semantics
    (left-to-right non-overlapping; an empty match advances the cursor
    by one). Returns (out_lengths, out_chars, overflowed) —
    ``overflowed`` True for any row with matches beyond ``max_matches``
    rounds (the dispatcher's host-recompute signal).

    ``dispatch_key`` (the source pattern string) keys the bucketed
    executable cache, same contract as ``extract_device``. Padded tail
    rows have zero chars/lengths: their first empty match parks the
    cursor past the row, so they can neither overflow nor affect real
    rows, and their output slots are sliced off."""
    if dispatch_key is None:
        return _replace_impl(
            ((chars, lengths),), (), None, comp=comp,
            replacement=replacement, max_matches=max_matches)
    from spark_rapids_jni_tpu.runtime import dispatch

    return dispatch.call(
        "regexp_replace",
        partial(_replace_impl, comp=comp, replacement=replacement,
                max_matches=max_matches),
        ((chars, lengths),),
        statics=("replace", dispatch_key, replacement, max_matches),
        slice_rows=True)
