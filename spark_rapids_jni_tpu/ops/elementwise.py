"""Elementwise/scalar SQL function family (cuDF unary/binary ops +
Spark conditional expressions — vendored capability surface, SURVEY.md
section 2.2): coalesce, nullif, greatest/least, abs, ceil/floor, round
(decimal-exact HALF_UP), and pmod.

All pure ``jnp.where`` lattices — XLA fuses them into whatever consumer
follows, so there is no standalone kernel cost. Decimal ``round`` stays
in integer arithmetic end to end (the package's exactness posture: TPU
f64 is f32-pair emulated, so float round-tripping a DECIMAL would
silently lose digits).

Null semantics are Spark's per function: coalesce takes the first
non-null; nullif(a, b) nulls where equal; greatest/least SKIP nulls
(null only when every operand is null); unary math propagates nulls;
pmod is null when the divisor is 0 (non-ANSI posture) or either side
is null.

Every public function here validates host-side, then routes its pure
compute through ``runtime.dispatch`` (shape-bucketed executable cache):
the ``_*_impl`` functions are the traced bodies. Padded tail rows
arrive as NULL rows and are sliced off the output, so the impls never
need the row_valid mask — elementwise ops are row-local.
"""

from __future__ import annotations

from functools import partial
from typing import Sequence

import jax.numpy as jnp

from spark_rapids_jni_tpu.columnar import Column
from spark_rapids_jni_tpu.runtime import dispatch
from spark_rapids_jni_tpu.types import DType, TypeId
from spark_rapids_jni_tpu.utils.tracing import func_range


def _check_numeric(c: Column, op: str) -> None:
    if c.dtype.is_string or c.dtype.is_decimal128 or \
            c.dtype.type_id in (TypeId.LIST, TypeId.STRUCT):
        raise TypeError(f"{op} needs a fixed-width numeric column, "
                        f"got {c.dtype}")


def _same_dtypes(cols: Sequence[Column], op: str) -> None:
    for c in cols[1:]:
        if c.dtype != cols[0].dtype:
            raise TypeError(
                f"{op} needs matching dtypes, got {c.dtype} vs "
                f"{cols[0].dtype}")


def _coalesce_impl(row_args, aux, rvs):
    (cols,) = row_args
    first = cols[0]
    if first.dtype.is_string:
        from spark_rapids_jni_tpu.ops.strings import pad_to_common_width

        ps = pad_to_common_width(cols)
        data = ps[0].data
        chars = ps[0].chars
        taken = ps[0].valid_mask()
        for p in ps[1:]:
            use = ~taken & p.valid_mask()
            data = jnp.where(use, p.data, data)
            chars = jnp.where(use[:, None], p.chars, chars)
            taken = taken | p.valid_mask()
        return Column(first.dtype, data, taken, chars=chars)
    data = cols[0].data
    taken = cols[0].valid_mask()
    for c in cols[1:]:
        use = ~taken & c.valid_mask()
        if first.dtype.is_decimal128:
            data = jnp.where(use[:, None], c.data, data)
        else:
            data = jnp.where(use, c.data, data)
        taken = taken | c.valid_mask()
    return Column(first.dtype, data, taken)


@func_range("coalesce")
def coalesce(cols: Sequence[Column]) -> Column:
    """Spark ``coalesce``: per row, the first non-null operand."""
    if not cols:
        raise ValueError("coalesce needs at least one column")
    _same_dtypes(cols, "coalesce")
    return dispatch.rowwise("coalesce", _coalesce_impl, tuple(cols))


def _nullif_impl(row_args, aux, rvs):
    ((a, b),) = row_args
    if a.dtype.is_string:
        from spark_rapids_jni_tpu.ops.strings import pad_to_common_width

        pa, pb = pad_to_common_width([a, b])
        eq_val = (pa.data == pb.data) & jnp.all(
            pa.chars == pb.chars, axis=1)
        eq = eq_val & pa.valid_mask() & pb.valid_mask()
        return Column(pa.dtype, pa.data, pa.valid_mask() & ~eq,
                      chars=pa.chars)
    if a.dtype.is_decimal128:
        eq_val = jnp.all(a.data == b.data, axis=-1)
        eq = eq_val & a.valid_mask() & b.valid_mask()
        return Column(a.dtype, a.data, a.valid_mask() & ~eq)
    eq = (a.data == b.data) & a.valid_mask() & b.valid_mask()
    return Column(a.dtype, a.data, a.valid_mask() & ~eq)


@func_range("nullif")
def nullif(a: Column, b: Column) -> Column:
    """Spark ``nullif(a, b)``: a, nulled where a == b (null-safe: a null
    pair does NOT null — Spark's NullIf uses EqualTo, null == null is
    unknown, so a stays null anyway). Strings compare by padded bytes,
    DECIMAL128 by limb pairs."""
    _same_dtypes([a, b], "nullif")
    return dispatch.rowwise("nullif", _nullif_impl, (a, b))


def _extremum_impl(row_args, aux, rvs, *, pick_max: bool):
    (cols,) = row_args
    is_float = cols[0].dtype.storage_dtype.kind == "f"

    def key(x):
        # Spark orders NaN ABOVE every value for greatest/least
        if not is_float:
            return x
        return jnp.where(jnp.isnan(x), jnp.inf, x)

    acc = cols[0].data
    have = cols[0].valid_mask()
    for c in cols[1:]:
        v = c.valid_mask()
        better = jnp.where(pick_max, key(c.data) > key(acc),
                           key(c.data) < key(acc))
        use = v & (~have | better)
        acc = jnp.where(use, c.data, acc)
        have = have | v
    return Column(cols[0].dtype, acc, have)


def _nary_extremum(cols: Sequence[Column], op: str) -> Column:
    if len(cols) < 2:
        raise ValueError(f"{op} needs at least two columns")
    _same_dtypes(cols, op)
    for c in cols:
        _check_numeric(c, op)
    pick_max = op == "greatest"
    return dispatch.rowwise(
        op, partial(_extremum_impl, pick_max=pick_max), tuple(cols),
        statics=(pick_max,))


@func_range("greatest")
def greatest(cols: Sequence[Column]) -> Column:
    """Spark ``greatest``: row-wise max, SKIPPING nulls (null only when
    all operands are null)."""
    return _nary_extremum(cols, "greatest")


@func_range("least")
def least(cols: Sequence[Column]) -> Column:
    return _nary_extremum(cols, "least")


def _abs_impl(row_args, aux, rvs):
    ((col,),) = row_args
    return Column(col.dtype, jnp.abs(col.data), col.validity)


@func_range("abs_")
def abs_(col: Column) -> Column:
    _check_numeric(col, "abs")
    return dispatch.rowwise("abs", _abs_impl, (col,))


@func_range("ceil")
def ceil(col: Column) -> Column:
    """Spark ``ceil``: BIGINT for floats; decimals round toward +inf in
    integer arithmetic (result scale 0, kept in the same storage)."""
    return _round_directed(col, up=True)


@func_range("floor")
def floor(col: Column) -> Column:
    return _round_directed(col, up=False)


def _round_directed_impl(row_args, aux, rvs, *, up: bool):
    ((col,),) = row_args
    dt = col.dtype
    if dt.is_decimal:
        s = -dt.scale
        if s <= 0:
            # scale >= 0: already integral; BIGINT value is
            # unscaled * 10^scale
            mul = 10 ** dt.scale
            return Column(DType(TypeId.INT64),
                          col.data.astype(jnp.int64) * mul, col.validity)
        pow10 = 10 ** s
        q = jnp.floor_divide(col.data, pow10)
        if up:
            q = q + (jnp.remainder(col.data, pow10) != 0).astype(q.dtype)
        return Column(DType(TypeId.INT64), q.astype(jnp.int64),
                      col.validity)
    if dt.storage_dtype.kind == "f":
        v = jnp.ceil(col.data) if up else jnp.floor(col.data)
        return Column(DType(TypeId.INT64), v.astype(jnp.int64),
                      col.validity)
    return Column(DType(TypeId.INT64), col.data.astype(jnp.int64),
                  col.validity)


def _round_directed(col: Column, up: bool) -> Column:
    _check_numeric(col, "ceil/floor")
    return dispatch.rowwise(
        "ceil" if up else "floor",
        partial(_round_directed_impl, up=up), (col,), statics=(up,))


def _round_decimal_impl(row_args, aux, rvs, *, d: int):
    ((col,),) = row_args
    dt = col.dtype
    frac = -dt.scale
    pow10 = 10 ** (frac - d)
    v = col.data
    q = jnp.floor_divide(v, pow10)
    r = v - q * pow10                     # in [0, pow10)
    # HALF_UP is away from zero: for negative values the floor division
    # already moved down, so a remainder STRICTLY ABOVE half rounds the
    # magnitude... spelled out via the sign-split:
    neg = v < 0
    round_up_pos = (~neg) & (r * 2 >= pow10)
    round_up_neg = neg & (r * 2 > pow10)
    q = q + (round_up_pos | round_up_neg).astype(q.dtype)
    from spark_rapids_jni_tpu.types import decimal32, decimal64

    out_dt = decimal64(-d) if dt.type_id == TypeId.DECIMAL64 \
        else decimal32(-d)
    return Column(out_dt, q.astype(dt.jnp_dtype), col.validity)


@func_range("round_decimal")
def round_decimal(col: Column, d: int = 0) -> Column:
    """Spark ``round(decimal, d)`` with HALF_UP, EXACT integer
    arithmetic: the unscaled value is divided by 10^(frac-d) with
    away-from-zero tie rounding; the result keeps scale -d (Spark
    narrows the scale). Non-decimal inputs are rejected — float round
    belongs to jnp directly."""
    dt = col.dtype
    if not dt.is_decimal or dt.is_decimal128:
        raise TypeError(
            f"round_decimal needs a DECIMAL32/64 column, got {dt}")
    if d >= -dt.scale:
        return col  # nothing to drop
    return dispatch.rowwise(
        "round_decimal", partial(_round_decimal_impl, d=d), (col,),
        statics=(d,))


def _pmod_impl(row_args, aux, rvs):
    ((a, b),) = row_args
    zero = b.data == 0
    safe_b = jnp.where(zero, jnp.ones_like(b.data), b.data)

    def _trunc_mod(x, nn):
        # Java truncated % from floor %: t = m - n when m != 0 and the
        # operand signs differ — no abs() anywhere, so INT64_MIN is safe
        fm = jnp.remainder(x, nn)
        flip = (fm != 0) & ((x < 0) != (nn < 0))
        return fm - jnp.where(flip, nn, jnp.zeros_like(nn))

    jt = _trunc_mod(a.data, safe_b)
    srn = jt + safe_b          # |jt| < |n| so this cannot overflow
    adj = _trunc_mod(srn, safe_b)
    m = jnp.where(jt < 0, adj, jt)
    validity = a.valid_mask() & b.valid_mask() & ~zero
    return Column(a.dtype, m.astype(a.dtype.jnp_dtype), validity)


@func_range("pmod")
def pmod(a: Column, b: Column) -> Column:
    """Spark ``pmod(a, b)``, bit-exact to its Java formula
    ``r = a % n; if (r < 0) (r + n) % n else r`` with JAVA's
    truncated-% (dividend sign) — for positive divisors that is the
    usual [0, b) modulus; for negative divisors Spark's result keeps
    the dividend-sign quirk, reproduced here rather than idealized.
    Division by zero gives null (non-ANSI posture)."""
    _same_dtypes([a, b], "pmod")
    _check_numeric(a, "pmod")
    return dispatch.rowwise("pmod", _pmod_impl, (a, b))
