"""Table-level cuDF operator parity: concatenate, boolean-mask stream
compaction, and distinct (cuDF ``concatenate`` / ``apply_boolean_mask`` /
``distinct`` — vendored capability surface, SURVEY.md section 2.2).

TPU-first shape discipline throughout: compaction-style ops cannot return
data-dependent shapes under jit, so they follow the framework-wide
padded-plus-count contract (rows compacted to the front, ``num_rows``
reported; callers slice on host) — the same contract groupby and the
shuffle use. No scatters: compaction is a stable argsort on the keep flag
(kept rows first, input order preserved), which XLA sorts as one pass.
"""

from __future__ import annotations

from typing import NamedTuple, Optional, Sequence

import jax.numpy as jnp
import numpy as np

from spark_rapids_jni_tpu.columnar import Column, Table
from spark_rapids_jni_tpu.ops.sort import gather, sort_order
from spark_rapids_jni_tpu.types import TypeId
from spark_rapids_jni_tpu.utils.tracing import func_range


def _slice_child(c: Column, lo: int, hi: int) -> Column:
    """Row slice of a LIST child (any non-nested layout)."""
    return _slice_rows(Table([c]), lo, hi).column(0)


def _slice_rows(table: Table, lo: int, hi: int) -> Table:
    """Host-level row slice [lo, hi) handling every column layout
    (fixed-width, limb-pair, padded string, Arrow string — whose offsets
    need hi-lo+1 entries re-based to the slice's first char)."""
    cols = []
    for c in table.columns:
        validity = None if c.validity is None else c.validity[lo:hi]
        if c.dtype.type_id == TypeId.STRUCT:
            cols.append(Column(
                c.dtype, c.data[lo:hi], validity,
                children=[_slice_rows(Table([k]), lo, hi).column(0)
                          for k in c.children]))
        elif c.dtype.type_id == TypeId.LIST:
            # slice-and-rebase: cut the child to this window's element
            # range [offsets[lo], offsets[hi]) and shift the offsets so
            # they index the cut child from 0
            base = c.data[lo]
            cols.append(Column(
                c.dtype, (c.data[lo:hi + 1] - base).astype(jnp.int32),
                validity,
                children=[_slice_child(c.children[0], int(base),
                                       int(c.data[hi]))],
            ))
        elif c.dtype.is_string and c.is_padded_string:
            cols.append(Column(c.dtype, c.data[lo:hi], validity,
                               chars=c.chars[lo:hi]))
        elif c.dtype.is_string:
            base_lo = int(c.data[lo])
            base_hi = int(c.data[hi])
            cols.append(Column(
                c.dtype,
                (c.data[lo:hi + 1] - base_lo).astype(jnp.int32),
                validity,
                chars=c.chars[base_lo:base_hi],
            ))
        else:
            cols.append(Column(c.dtype, c.data[lo:hi], validity))
    return Table(cols)


def trim_table(table: Table, k: int) -> Table:
    """Host-side trim of a padded result to its first ``k`` real rows —
    the shared tail of every padded-plus-count contract (groupby,
    compaction)."""
    return _slice_rows(table, 0, k)


class CompactResult(NamedTuple):
    table: Table             # kept rows first, padded to the input size
    num_rows: jnp.ndarray    # scalar int32: real row count

    def compact(self) -> Table:
        """Host-side trim to the real row count."""
        return trim_table(self.table, int(self.num_rows))


def _concat_columns(cols: Sequence[Column]) -> Column:
    dtype = cols[0].dtype
    for c in cols[1:]:
        if c.dtype != dtype:
            raise TypeError(
                f"concatenate: column dtypes differ ({c.dtype} vs {dtype})"
            )
    if all(c.validity is None for c in cols):
        validity = None  # keep the no-null-mask fast path alive
    else:
        validity = jnp.concatenate([c.valid_mask() for c in cols])
    if dtype.type_id == TypeId.STRUCT:
        return Column(
            dtype,
            jnp.concatenate([c.data for c in cols]),
            validity,
            children=[
                _concat_columns([c.children[i] for c in cols])
                for i in range(len(cols[0].children))
            ],
        )
    if dtype.type_id == TypeId.LIST:
        # host-level: trim each child to its live element range (padded
        # tails would corrupt the offset re-base), shift offsets by the
        # running child total, concat children recursively
        offs, base = [], 0
        kids = []
        for c in cols:
            live = int(c.data[-1]) if c.size else 0
            offs.append(c.data[:-1].astype(jnp.int64) + base)
            kids.append(_slice_child(c.children[0], 0, live))
            base += live
        if base > np.iinfo(np.int32).max:
            raise ValueError(
                f"concatenated LIST child holds {base} elements, over the "
                "int32 Arrow offset bound (2^31-1); concatenate in batches")
        offs.append(jnp.asarray([base], jnp.int64))
        child = _concat_columns(kids)
        return Column(
            dtype,
            jnp.concatenate(offs).astype(jnp.int32),
            validity,
            children=[child],
        )
    if dtype.is_string:
        if any(c.is_padded_string for c in cols):
            # normalize to the padded device layout at the widest width
            from spark_rapids_jni_tpu.ops.strings import pad_to_common_width

            padded = pad_to_common_width(cols)
            return Column(
                dtype,
                jnp.concatenate([p.data for p in padded]),
                validity,
                chars=jnp.concatenate([p.chars for p in padded]),
            )
        # Arrow layout: shift each table's offsets by the chars written so far
        parts, offs, base = [], [], 0
        for c in cols:
            offs.append(c.data[:-1] + base if c.size else c.data[:0])
            parts.append(c.chars)
            base = base + c.data[-1] if c.size else base
        offs.append(jnp.asarray([base], jnp.int32).reshape(1))
        return Column(
            dtype,
            jnp.concatenate(offs).astype(jnp.int32),
            validity,
            chars=jnp.concatenate(parts) if parts else jnp.zeros(0, jnp.uint8),
        )
    return Column(dtype, jnp.concatenate([c.data for c in cols]), validity)


@func_range("concatenate")
def concatenate(tables: Sequence[Table]) -> Table:
    """Row-wise concatenation (cuDF ``concatenate``): schemas must match;
    string columns concat in either layout (Arrow offsets re-based on
    device; padded layouts widened to the max width)."""
    tables = list(tables)
    if not tables:
        raise ValueError("concatenate needs at least one table")
    ncols = tables[0].num_columns
    for tb in tables[1:]:
        if tb.num_columns != ncols:
            raise TypeError("concatenate: column counts differ")
    return Table([
        _concat_columns([tb.column(i) for tb in tables])
        for i in range(ncols)
    ])


@func_range("apply_boolean_mask")
def apply_boolean_mask(table: Table, mask: jnp.ndarray) -> CompactResult:
    """Stream compaction (cuDF ``apply_boolean_mask``): keep rows where
    ``mask`` is True, preserving input order. Output is padded to the
    input size with ``num_rows`` alongside (slice on host)."""
    n = table.num_rows
    if mask.shape != (n,):
        raise ValueError(f"mask shape {mask.shape} != ({n},)")
    keep = mask.astype(jnp.bool_)
    # stable argsort on the drop flag: kept rows first, original order kept
    order = jnp.argsort(~keep, stable=True).astype(jnp.int32)
    num = jnp.sum(keep).astype(jnp.int32)
    return CompactResult(_gather_mask_tail(table, order, num), num)


def _gather_mask_tail(table: Table, order: jnp.ndarray,
                      num: jnp.ndarray) -> Table:
    """One gather by ``order`` with rows past ``num`` forced null (padding
    must not read as stale duplicates)."""
    out = gather(table, order)
    j = jnp.arange(table.num_rows, dtype=jnp.int32)
    cols = []
    for c in out.columns:
        validity = c.valid_mask() & (j < num)
        if c.dtype.is_string:
            cols.append(Column(c.dtype, c.data, validity, chars=c.chars))
        else:
            cols.append(Column(c.dtype, c.data, validity))
    return Table(cols)


@func_range("distinct")
def distinct(table: Table, keys: Optional[Sequence[int]] = None) -> CompactResult:
    """Distinct key tuples (cuDF ``distinct`` / Spark dropDuplicates):
    keeps one row per distinct tuple over ``keys`` (default: all columns);
    null tuples count as equal (one null group). Output rows arrive in
    key-sorted order, padded, with the distinct count alongside."""
    ks = list(range(table.num_columns)) if keys is None else list(keys)
    from spark_rapids_jni_tpu.ops.groupby import _rows_equal_prev

    order = sort_order(table, ks)
    # adjacency only needs the KEY columns sorted; the full table is
    # gathered once, through the composed permutation
    key_sorted = gather(Table([table.column(k) for k in ks]), order)
    same = _rows_equal_prev(key_sorted, list(range(len(ks))))
    keep = ~same
    perm = jnp.argsort(same, stable=True).astype(jnp.int32)
    num = jnp.sum(keep).astype(jnp.int32)
    return CompactResult(_gather_mask_tail(table, order[perm], num), num)


@func_range("contiguous_split")
def contiguous_split(table: Table, splits: Sequence[int]) -> list[Table]:
    """Split rows at the given indices (cuDF ``contiguous_split``, the
    primitive the Spark plugin uses to carve shuffle partitions):
    ``splits=[a, b]`` -> three tables covering [0,a), [a,b), [b,n).
    Host-level API (static row counts per piece); each piece's buffers
    are device slices of the parent."""
    n = table.num_rows
    bounds = [0] + [int(x) for x in splits] + [n]
    for lo, hi in zip(bounds, bounds[1:]):
        if lo > hi or lo < 0 or hi > n:
            raise ValueError(f"bad split bounds {splits} for {n} rows")
    return [_slice_rows(table, lo, hi) for lo, hi in zip(bounds, bounds[1:])]


def _set_op(left: Table, right: Table, keep_matched: bool) -> CompactResult:
    """Shared EXCEPT/INTERSECT scaffold: distinct left tuples, marked and
    concatenated with right rows, one sort over all columns, then a
    per-tuple-group ANY over the side flag — SQL set-op null semantics
    (NULL tuples compare equal) come from _rows_equal_prev's both-null
    rule, unlike an equi-join which would drop them."""
    from spark_rapids_jni_tpu.ops.groupby import _rows_equal_prev
    from spark_rapids_jni_tpu.types import DType as _D, TypeId as _T

    if left.num_columns != right.num_columns:
        raise ValueError("set ops need matching column counts")
    for i in range(left.num_columns):
        if left.column(i).dtype != right.column(i).dtype:
            raise TypeError(
                f"set ops need matching dtypes at column {i}: "
                f"{left.column(i).dtype} vs {right.column(i).dtype}")
    l0 = distinct(left).compact()

    def _with_side(tbl: Table, side: int) -> Table:
        flag = Column(_D(_T.INT8),
                      jnp.full((tbl.num_rows,), side, jnp.int8), None)
        return Table(list(tbl.columns) + [flag])

    allt = concatenate([_with_side(l0, 0), _with_side(right, 1)])
    nk = left.num_columns
    ks = list(range(nk))
    # side as the trailing sort key: left tuples are DISTINCT, so each
    # group's single side-0 row sorts immediately before its side-1
    # rows — membership is one neighbor compare, no group-id machinery
    order = sort_order(allt, ks + [nk])
    sall = gather(allt, order)
    same = _rows_equal_prev(sall, ks)
    side_sorted = sall.column(nk).data
    next_same = jnp.concatenate(
        [same[1:], jnp.zeros((1,), jnp.bool_)])
    matched = next_same  # the only same-key follower can be side 1
    mask = (side_sorted == 0) & (matched == keep_matched)
    perm = jnp.argsort(~mask, stable=True).astype(jnp.int32)
    num = jnp.sum(mask).astype(jnp.int32)
    # _gather_mask_tail nulls the padding rows — the module's contract
    # (padding must not read as stale duplicates)
    return CompactResult(
        _gather_mask_tail(Table([sall.column(i) for i in ks]), perm, num),
        num)


@func_range("except_rows")
def except_rows(left: Table, right: Table) -> CompactResult:
    """SQL EXCEPT (DISTINCT): distinct left tuples with no equal tuple
    in right; NULLs compare equal (set semantics)."""
    return _set_op(left, right, keep_matched=False)


@func_range("intersect_rows")
def intersect_rows(left: Table, right: Table) -> CompactResult:
    """SQL INTERSECT (DISTINCT): distinct left tuples that also appear
    in right; NULLs compare equal."""
    return _set_op(left, right, keep_matched=True)
