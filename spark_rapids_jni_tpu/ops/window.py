"""Window functions over sorted partitions — the cuDF rolling/window
surface Spark's window expressions lower to (vendored capability family,
SURVEY.md section 2.2).

TPU-first design: one sort by (partition keys, order keys), per-row
results computed with the groupby module's scatter-free segmented
machinery (log-depth segmented scans, cummax boundary tracking — no
segment_* scatters, which serialize on TPU), then one gather through the
sort's inverse permutation so every result column aligns with the INPUT
row order (Spark window semantics: results join back to their rows).

Null order keys sort by the sort module's null rules and otherwise
behave as values; null partition keys form their own partition (Spark).
"""

from __future__ import annotations

from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np

from spark_rapids_jni_tpu.columnar import Column, Table
from spark_rapids_jni_tpu.ops.groupby import (
    _rows_equal_prev,
    _segmented_extremum,
    _segmented_sum_scan,
)
from spark_rapids_jni_tpu.ops.sort import gather, sort_order
from spark_rapids_jni_tpu.types import DType, TypeId
from spark_rapids_jni_tpu.utils.tracing import func_range


class Window:
    """Shared precompute for one PARTITION BY / ORDER BY spec: the sort,
    its inverse, and the partition/peer boundary flags. Build once, call
    any number of window functions against it."""

    def __init__(
        self,
        table: Table,
        partition_by: Sequence[int],
        order_by: Sequence[int],
        ascending: Sequence[bool] | None = None,
        nulls_first: Sequence[bool] | None = None,
    ):
        self._table = table
        n = table.num_rows
        self._n = n
        keys = list(partition_by) + list(order_by)
        asc = ([True] * len(partition_by) + list(ascending)
               if ascending is not None else None)
        nf = ([True] * len(partition_by) + list(nulls_first)
              if nulls_first is not None else None)
        self._order_by = list(order_by)
        self._order_asc = (list(ascending) if ascending is not None
                           else [True] * len(self._order_by))
        self._order_nf = (list(nulls_first) if nulls_first is not None
                          else [True] * len(self._order_by))
        self._order = sort_order(table, keys, ascending=asc, nulls_first=nf)
        self._sorted = gather(table, self._order)
        # inverse permutation via argsort — a sort, never a scatter
        self._inv = jnp.argsort(self._order).astype(jnp.int32)
        # same_p[i]: sorted row i continues row i-1's partition;
        # same_peer[i]: ... AND has an equal order-key tuple (rank peers)
        self._same_p = _rows_equal_prev(self._sorted, list(
            range(len(partition_by))))
        self._same_peer = _rows_equal_prev(
            self._sorted, list(range(len(keys))))
        self._idx = jnp.arange(n, dtype=jnp.int64)
        # position of each sorted row's partition start (cummax of starts)
        self._p_start = jax.lax.associative_scan(
            jnp.maximum, jnp.where(~self._same_p, self._idx, -1))
        self._p_end = self._segment_end(self._same_p)
        self._peer_end_cache: jnp.ndarray | None = None

    def _segment_end(self, same_prev: jnp.ndarray) -> jnp.ndarray:
        """Sorted position of the last row of each row's segment, where a
        segment starts wherever ``same_prev`` is False: reverse cummin of
        start positions, shifted to 'earliest start strictly after i',
        minus one."""
        n = self._n
        start_pos = jnp.where(~same_prev, self._idx, n)
        nxt = jnp.flip(jax.lax.associative_scan(
            jnp.minimum, jnp.flip(start_pos)))
        nxt_after = jnp.concatenate(
            [nxt[1:], jnp.full((1,), n, dtype=nxt.dtype)]) if n else nxt
        return nxt_after - 1

    @property
    def _peer_end(self) -> jnp.ndarray:
        """Sorted position of the last row in each row's peer group (same
        partition AND equal order keys) — the frame end of Spark's default
        RANGE BETWEEN UNBOUNDED PRECEDING AND CURRENT ROW window."""
        if self._peer_end_cache is None:
            self._peer_end_cache = self._segment_end(self._same_peer)
        return self._peer_end_cache

    def _unsort(self, sorted_vals: jnp.ndarray) -> jnp.ndarray:
        return sorted_vals[self._inv]

    def _int_col(self, sorted_vals: jnp.ndarray) -> Column:
        return Column(DType(TypeId.INT64),
                      self._unsort(sorted_vals.astype(jnp.int64)), None)

    @func_range("window_row_number")
    def row_number(self) -> Column:
        """1-based position within the partition (ROW_NUMBER)."""
        return self._int_col(self._idx - self._p_start + 1)

    def _first_peer(self) -> jnp.ndarray:
        """Sorted position of the first row of each row's peer group
        (cummax of peer-group starts)."""
        return jax.lax.associative_scan(
            jnp.maximum, jnp.where(~self._same_peer, self._idx, -1))

    @func_range("window_rank")
    def rank(self) -> Column:
        """RANK: 1 + rows strictly before the first peer (gaps on ties)."""
        return self._int_col(self._first_peer() - self._p_start + 1)

    @func_range("window_dense_rank")
    def dense_rank(self) -> Column:
        """DENSE_RANK: distinct order-key values seen so far (no gaps)."""
        new_val = (~self._same_peer).astype(jnp.int64)
        dr = _segmented_sum_scan(new_val[:, None], ~self._same_p)[:, 0]
        return self._int_col(dr)

    def _shifted(self, col_idx: int, k: int) -> Column:
        pos = self._idx - k
        src = jnp.clip(pos, 0, max(self._n - 1, 0)).astype(jnp.int32)
        in_bounds = (pos >= 0) & (pos < self._n)
        # same partition iff the partition start did not change
        same_part = self._p_start[src] == self._p_start
        return self._gather_at(self._sorted.column(col_idx), pos,
                               in_bounds & same_part)

    @func_range("window_lag")
    def lag(self, col_idx: int, k: int = 1) -> Column:
        """Value k rows earlier in the partition, null past the edge."""
        if k < 0:
            raise ValueError("lag offset must be >= 0 (use lead)")
        return self._shifted(col_idx, k)

    @func_range("window_lead")
    def lead(self, col_idx: int, k: int = 1) -> Column:
        """Value k rows later in the partition, null past the edge."""
        if k < 0:
            raise ValueError("lead offset must be >= 0 (use lag)")
        return self._shifted(col_idx, -k)

    @staticmethod
    def _sentinel(np_dt, op: str):
        """Neutral element for min/max over possibly-null values."""
        if np_dt.kind == "f":
            return jnp.inf if op == "min" else -jnp.inf
        info = np.iinfo(np_dt)
        return info.max if op == "min" else info.min

    def _running(self, col_idx: int, op: str) -> Column:
        c = self._sorted.column(col_idx)
        if c.dtype.is_string or c.dtype.is_decimal128:
            raise NotImplementedError(
                f"running {op} needs fixed-width numeric columns"
            )
        valid = c.valid_mask()
        if op == "sum":
            from spark_rapids_jni_tpu.ops.groupby import _sum_dtype

            acc_dt = _sum_dtype(c.dtype)
            zero = jnp.zeros_like(c.data)
            vv = jnp.where(valid, c.data, zero)
            if acc_dt.storage_dtype.kind in ("i", "u"):
                vv = vv.astype(jnp.int64)
            else:
                vv = vv.astype(jnp.float64)
            run = _segmented_sum_scan(vv[:, None], ~self._same_p)[:, 0]
            # running count of valid values: all-null-so-far stays null
            cnt = _segmented_sum_scan(
                valid.astype(jnp.int64)[:, None], ~self._same_p)[:, 0]
            return Column(acc_dt,
                          self._unsort(run.astype(acc_dt.jnp_dtype)),
                          self._unsort(cnt > 0))
        sentinel = self._sentinel(c.dtype.storage_dtype, op)
        vv = jnp.where(valid, c.data, jnp.asarray(sentinel, c.data.dtype))
        run = _segmented_extremum(vv, ~self._same_p, op)
        cnt = _segmented_sum_scan(
            valid.astype(jnp.int64)[:, None], ~self._same_p)[:, 0]
        return Column(c.dtype, self._unsort(run), self._unsort(cnt > 0))

    def _frame_bounds(self, preceding: int, following: int):
        """Sorted-position [lo, hi] of each row's ROWS frame, clamped to
        its partition."""
        if preceding < 0 or following < 0:
            raise ValueError("rolling bounds must be >= 0")
        lo = jnp.clip(self._idx - preceding, self._p_start, self._p_end)
        hi = jnp.clip(self._idx + following, self._p_start, self._p_end)
        return lo, hi

    def _bounds(self, preceding, following, frame: str):
        if frame == "rows":
            return self._frame_bounds(preceding, following)
        if frame == "range":
            return self._range_frame_bounds(preceding, following)
        raise ValueError(f"frame must be 'rows' or 'range', got {frame!r}")

    def _bounded_search(self, v: jnp.ndarray, target: jnp.ndarray,
                        lo0: jnp.ndarray, hi0: jnp.ndarray,
                        side_left: bool) -> jnp.ndarray:
        """Per-row binary search of ``target`` inside [lo0, hi0) over the
        partition-sorted values ``v`` — log2(n) vectorized halving steps
        (jnp.searchsorted has no per-row bounds)."""
        import numpy as _np

        n = self._n
        lo_b, hi_b = lo0.astype(jnp.int64), hi0.astype(jnp.int64)
        steps = int(_np.ceil(_np.log2(max(n, 2)))) + 1
        for _ in range(steps):
            active = lo_b < hi_b
            mid = (lo_b + hi_b) >> 1
            mv = v[jnp.clip(mid, 0, max(n - 1, 0))]
            go_right = (mv < target) if side_left else (mv <= target)
            lo_b = jnp.where(active & go_right, mid + 1, lo_b)
            hi_b = jnp.where(active & ~go_right, mid, hi_b)
        return lo_b

    def _range_frame_bounds(self, preceding, following):
        """Sorted-position [lo, hi] of each row's RANGE frame: rows of
        the same partition whose ORDER BY value lies in
        [v - preceding, v + following]. Requirements (raise otherwise):
        exactly ONE numeric ORDER BY key, ascending, nulls first (the
        defaults). Rows with a NULL order value frame over the
        partition's null run (Spark: nulls are peers only of nulls)."""
        if len(self._order_by) != 1:
            raise ValueError(
                "RANGE frames need exactly one ORDER BY key")
        if not self._order_asc[0] or not self._order_nf[0]:
            raise NotImplementedError(
                "RANGE frames need an ascending, nulls-first ORDER BY "
                "key (the defaults)")
        if preceding < 0 or following < 0:
            raise ValueError("RANGE bounds must be >= 0")
        oc = self._sorted.column(self._order_by[0])
        if oc.dtype.is_string or oc.dtype.is_decimal128 or \
                oc.dtype.storage_dtype.kind not in ("i", "u", "f"):
            raise TypeError(
                f"RANGE frames need a numeric ORDER BY key, got "
                f"{oc.dtype}")
        if oc.dtype.is_decimal:
            # bounds are VALUE distances: rescale to unscaled units
            # exactly (via Fraction — float multiply would falsely
            # reject exactly-representable bounds like 0.29 at scale
            # -2), or refuse
            from fractions import Fraction

            factor = 10 ** (-oc.dtype.scale)
            scaled = []
            for name, b in (("preceding", preceding),
                            ("following", following)):
                fb = Fraction(str(b)) * factor
                if fb.denominator != 1:
                    raise ValueError(
                        f"RANGE {name}={b} is not representable at "
                        f"{oc.dtype} scale")
                scaled.append(int(fb))
            preceding, following = scaled
        v = oc.data
        if oc.dtype.storage_dtype.kind == "u":
            if oc.dtype.storage_dtype.itemsize == 8:
                raise NotImplementedError(
                    "RANGE frames on uint64 ORDER BY keys (bound "
                    "arithmetic would wrap)")
            v = v.astype(jnp.int64)
        elif oc.dtype.storage_dtype.kind == "i" and \
                oc.dtype.storage_dtype.itemsize < 8:
            v = v.astype(jnp.int64)  # headroom for v ± bound
        is_null = ~oc.valid_mask()
        # per-partition null-run length (nulls sort first)
        nrun = _segmented_sum_scan(
            is_null.astype(jnp.int64)[:, None], ~self._same_p)[:, 0]
        nc = nrun[jnp.clip(self._p_end, 0, max(self._n - 1, 0))]
        valid_start = self._p_start + nc
        valid_end = self._p_end + 1
        is_nan = jnp.zeros((self._n,), jnp.bool_)
        if oc.dtype.storage_dtype.kind == "f":
            # NaN orders greatest (the sort posture), so the NaN run
            # sits at the partition END; NaN rows frame over their NaN
            # peers (NaN == NaN) and value searches exclude the run
            is_nan = jnp.isnan(v) & ~is_null
            nanrun = _segmented_sum_scan(
                is_nan.astype(jnp.int64)[:, None], ~self._same_p)[:, 0]
            nanc = nanrun[jnp.clip(self._p_end, 0,
                                   max(self._n - 1, 0))]
            valid_end = valid_end - nanc
        # saturating bound arithmetic: int64 keys near the dtype edge
        # must not wrap (narrow ints were widened above; uint64 is
        # rejected)
        lo_t = v - preceding
        hi_t = v + following
        if oc.dtype.storage_dtype.kind in ("i", "u"):
            lo_t = jnp.where((preceding > 0) & (lo_t > v),
                             jnp.iinfo(jnp.int64).min, lo_t)
            hi_t = jnp.where(
                (following > 0) & (hi_t < v),
                # saturation bound for the search, not a data sentinel
                # tpulint: disable=sentinel-safety
                jnp.iinfo(jnp.int64).max, hi_t)
        lo = self._bounded_search(v, lo_t, valid_start,
                                  valid_end, side_left=True)
        hi = self._bounded_search(v, hi_t, valid_start,
                                  valid_end, side_left=False) - 1
        # null-order rows frame over the null run; NaN rows over theirs
        lo = jnp.where(is_null, self._p_start, lo)
        hi = jnp.where(is_null, self._p_start + nc - 1, hi)
        if oc.dtype.storage_dtype.kind == "f":
            lo = jnp.where(is_nan, valid_end, lo)
            hi = jnp.where(is_nan, self._p_end, hi)
        return lo, hi

    def _frame_diff(self, running: jnp.ndarray, lo: jnp.ndarray,
                    hi: jnp.ndarray) -> jnp.ndarray:
        """Per-frame total of a segmented running sum via prefix
        differences (the base at lo-1 is zero at a partition start, so
        cross-partition terms never enter)."""
        n = self._n
        safe = lambda a, i: a[jnp.clip(i, 0, max(n - 1, 0))]
        upper = safe(running, hi)
        base = jnp.where(lo > self._p_start, safe(running, lo - 1), 0)
        return upper - base

    def _frame_valid_count(self, valid: jnp.ndarray, lo: jnp.ndarray,
                           hi: jnp.ndarray) -> jnp.ndarray:
        cnt = _segmented_sum_scan(
            valid.astype(jnp.int64)[:, None], ~self._same_p)[:, 0]
        return self._frame_diff(cnt, lo, hi)

    def _rolling_parts(self, col_idx: int, preceding: int, following: int,
                       frame: str = "rows"):
        """Shared rolling-frame machinery: per-row frame sums and counts
        over ROWS BETWEEN preceding PRECEDING AND following FOLLOWING,
        clamped to the partition — prefix differences of the SEGMENTED
        running sum (resets each partition, so int lanes are exact and
        float error stays partition-local)."""
        lo, hi = self._bounds(preceding, following, frame)
        c = self._sorted.column(col_idx)
        if c.dtype.is_string or c.dtype.is_decimal128:
            raise NotImplementedError(
                "rolling aggregates need fixed-width numeric columns")
        valid = c.valid_mask()
        vv = jnp.where(valid, c.data, jnp.zeros_like(c.data))
        if c.dtype.storage_dtype.kind in ("i", "u", "b"):
            vv = vv.astype(jnp.int64)
        else:
            vv = vv.astype(jnp.float64)
        run = _segmented_sum_scan(vv[:, None], ~self._same_p)[:, 0]
        return (c, self._frame_diff(run, lo, hi),
                self._frame_valid_count(valid, lo, hi))

    def _rolling_sum128(self, col_idx: int, preceding: int,
                        following: int, frame: str) -> Column:
        """Exact DECIMAL128 rolling SUM: four 32-bit limb lanes through
        the segmented scan, frame prefix-differences per lane, carry
        recombination with 128-bit overflow DETECTION — an overflowing
        frame's sum is NULL, never a wrapped value (the groupby sum128
        posture; the window API has no flag channel, documented)."""
        from spark_rapids_jni_tpu.ops.groupby import (
            recombine_sum128,
            split_sum128_lanes,
        )

        lo_b, hi_b = self._bounds(preceding, following, frame)
        c = self._sorted.column(col_idx)
        valid = c.valid_mask()
        vlo = jnp.where(valid, c.data[:, 0], jnp.int64(0))
        vhi = jnp.where(valid, c.data[:, 1], jnp.int64(0))
        # validity rides the scan as a fifth lane — one pass, not two
        lanes = jnp.stack(
            split_sum128_lanes(vlo, vhi)
            + [valid.astype(jnp.int64)], axis=1)
        runs = _segmented_sum_scan(lanes, ~self._same_p)
        segs = [self._frame_diff(runs[:, i], lo_b, hi_b)
                for i in range(5)]
        lo_out, hi_out, ovf = recombine_sum128(*segs[:4])
        wcnt = segs[4]
        out = jnp.stack([lo_out, hi_out], axis=-1)
        return Column(c.dtype, self._unsort(out),
                      self._unsort((wcnt > 0) & ~ovf))

    @func_range("window_rolling_sum")
    def rolling_sum(self, col_idx: int, preceding: int,
                    following: int = 0, frame: str = "rows") -> Column:
        """SUM over ROWS BETWEEN preceding PRECEDING AND following
        FOLLOWING (the cuDF rolling-window op). Exact for int/decimal
        lanes; float frames difference partition-local running sums
        (documented float-rounding posture)."""
        from spark_rapids_jni_tpu.ops.groupby import _sum_dtype

        if self._sorted.column(col_idx).dtype.is_decimal128:
            return self._rolling_sum128(col_idx, preceding, following,
                                        frame)
        c, wsum, wcnt = self._rolling_parts(col_idx, preceding,
                                            following, frame)
        acc_dt = _sum_dtype(c.dtype)
        return Column(acc_dt,
                      self._unsort(wsum.astype(acc_dt.jnp_dtype)),
                      self._unsort(wcnt > 0))

    @func_range("window_rolling_count")
    def rolling_count(self, col_idx: int, preceding: int,
                      following: int = 0, frame: str = "rows") -> Column:
        """COUNT of non-null values in the rolling frame — needs only the
        validity mask, so every dtype (strings, DECIMAL128) is counted."""
        lo, hi = self._bounds(preceding, following, frame)
        valid = self._sorted.column(col_idx).valid_mask()
        wcnt = self._frame_valid_count(valid, lo, hi)
        return Column(DType(TypeId.INT64), self._unsort(wcnt), None)

    @func_range("window_rolling_mean")
    def rolling_mean(self, col_idx: int, preceding: int,
                     following: int = 0, frame: str = "rows") -> Column:
        """AVG over the rolling frame (FLOAT64, decimal-rescaled like the
        groupby mean contract)."""
        c, wsum, wcnt = self._rolling_parts(col_idx, preceding,
                                            following, frame)
        denom = jnp.maximum(wcnt, 1).astype(jnp.float64)
        m = wsum.astype(jnp.float64) / denom
        if c.dtype.is_decimal:
            m = m * (10.0 ** c.dtype.scale)
        return Column(DType(TypeId.FLOAT64), self._unsort(m),
                      self._unsort(wcnt > 0))

    @func_range("window_rolling_var")
    def rolling_var(self, col_idx: int, preceding: int,
                    following: int = 0, ddof: int = 1,
                    frame: str = "rows") -> Column:
        """VARIANCE over the ROWS frame (cuDF rolling VAR; Spark windowed
        var_samp at ddof=1, var_pop at ddof=0). Frames are centered
        around the PARTITION mean before squaring, so the
        prefix-difference form subtracts sums of small deviations rather
        than raw magnitudes — the shift theorem keeps the result
        identical while removing the classic Σx² cancellation blowup.
        Residual noise floor: ~eps · (partition-accumulated cx²), i.e.
        near-zero variances of a frame inside a high-variance partition
        carry absolute noise at that floor (the same caveat cuDF's
        prefix-sum rolling VAR has; groupby var does a true per-group
        two-pass instead). FLOAT64 output (f32-pair emulation posture)."""
        if ddof not in (0, 1):
            raise ValueError("ddof must be 0 (population) or 1 (sample)")
        lo, hi = self._bounds(preceding, following, frame)
        c = self._sorted.column(col_idx)
        if c.dtype.is_string or c.dtype.is_decimal128 or \
                c.dtype.storage_dtype.kind not in ("i", "u", "f"):
            raise TypeError(
                f"rolling var/std need a numeric column, got {c.dtype}")
        valid = c.valid_mask()
        scale_f = (10.0 ** c.dtype.scale) if c.dtype.is_decimal else 1.0
        x = c.data.astype(jnp.float64) * scale_f
        x0 = jnp.where(valid, x, 0.0)
        # partition mean, broadcast per row: segmented totals read at
        # each row's partition end
        runs = _segmented_sum_scan(
            jnp.stack([x0, valid.astype(jnp.float64)], axis=1),
            ~self._same_p)
        tot = runs[self._p_end, 0]
        cntp = runs[self._p_end, 1]
        mean_p = tot / jnp.maximum(cntp, 1.0)
        cx = jnp.where(valid, x - mean_p, 0.0)
        runs2 = _segmented_sum_scan(
            jnp.stack([cx, cx * cx], axis=1), ~self._same_p)
        s1 = self._frame_diff(runs2[:, 0], lo, hi)
        s2 = self._frame_diff(runs2[:, 1], lo, hi)
        # runs[:, 1] is already the segmented running count of valids —
        # reuse it rather than paying _frame_valid_count's extra scan
        cnt = self._frame_diff(runs[:, 1], lo, hi).astype(jnp.int64)
        m = cnt.astype(jnp.float64)
        num = jnp.maximum(s2 - s1 * s1 / jnp.maximum(m, 1.0), 0.0)
        var = num / jnp.maximum(m - ddof, 1.0)
        return Column(DType(TypeId.FLOAT64), self._unsort(var),
                      self._unsort(cnt > ddof))

    @func_range("window_rolling_std")
    def rolling_std(self, col_idx: int, preceding: int,
                    following: int = 0, ddof: int = 1,
                    frame: str = "rows") -> Column:
        """STDDEV over the frame (sqrt of rolling_var)."""
        v = self.rolling_var(col_idx, preceding, following, ddof, frame)
        return Column(v.dtype, jnp.sqrt(v.data), v.validity)

    @func_range("window_rolling_min")
    def rolling_min(self, col_idx: int, preceding: int,
                    following: int = 0, frame: str = "rows") -> Column:
        """MIN over the ROWS frame — sparse-table range-minimum (doubling
        levels at power-of-two strides, two overlapping block gathers per
        row), O(n log w) with zero scatters; a sliding extremum has no
        prefix-difference form the way sums do."""
        return self._rolling_extremum(col_idx, preceding, following,
                                      "min", frame)

    @func_range("window_rolling_max")
    def rolling_max(self, col_idx: int, preceding: int,
                    following: int = 0, frame: str = "rows") -> Column:
        """MAX over the ROWS frame (see rolling_min for the design)."""
        return self._rolling_extremum(col_idx, preceding, following,
                                      "max", frame)

    def _rolling_extremum(self, col_idx: int, preceding: int,
                          following: int, op: str,
                          frame: str = "rows") -> Column:
        lo, hi = self._bounds(preceding, following, frame)
        c = self._sorted.column(col_idx)
        if c.dtype.is_string or c.dtype.is_decimal128:
            raise NotImplementedError(
                "rolling min/max needs fixed-width numeric columns")
        n = self._n
        valid = c.valid_mask()
        sentinel = self._sentinel(c.dtype.storage_dtype, op)
        vv = jnp.where(valid, c.data, jnp.asarray(sentinel, c.data.dtype))
        pick = jnp.minimum if op == "min" else jnp.maximum
        # levels[l][i] = extremum of vv[i : i + 2^l], enough levels to
        # cover the widest possible frame: the row budget for ROWS
        # frames, the whole table for RANGE frames (a value window may
        # span arbitrarily many rows)
        w = preceding + following + 1 if frame == "rows" else max(n, 1)
        nlev = max(1, min(w, max(n, 1)).bit_length())
        levels = [vv]
        for lev in range(nlev - 1):
            off = 1 << lev
            shifted = levels[-1][jnp.clip(self._idx + off, 0,
                                          max(n - 1, 0)).astype(jnp.int32)]
            levels.append(pick(levels[-1], shifted))
        stacked = jnp.stack(levels)  # (nlev, n)
        length = hi - lo + 1
        # k = floor(log2(length)) via static comparisons (exact, no fp)
        k = jnp.zeros((n,), dtype=jnp.int64)
        for lev in range(1, nlev):
            k = k + (length >= (1 << lev))
        span = jnp.left_shift(jnp.int64(1), k)
        # two overlapping 2^k blocks cover [lo, hi]; gather each level at
        # the block start, then select level k per row (take_along_axis
        # keeps indices within one axis — no nlev*n flat index to
        # overflow int32)
        idx32 = lambda i: jnp.clip(i, 0, max(n - 1, 0)).astype(jnp.int32)
        at_lo = stacked[:, idx32(lo)]
        at_hi = stacked[:, idx32(hi - span + 1)]
        k32 = k.astype(jnp.int32)[None, :]
        a = jnp.take_along_axis(at_lo, k32, axis=0)[0]
        b = jnp.take_along_axis(at_hi, k32, axis=0)[0]
        out = pick(a, b)
        wcnt = self._frame_valid_count(valid, lo, hi)
        return Column(c.dtype, self._unsort(out), self._unsort(wcnt > 0))

    @func_range("window_ntile")
    def ntile(self, buckets: int) -> Column:
        """NTILE(k): partition rows into k buckets whose sizes differ by
        at most one, larger buckets first (SQL/Spark semantics)."""
        if buckets <= 0:
            raise ValueError("ntile bucket count must be positive")
        size = self._p_end - self._p_start + 1
        pos = self._idx - self._p_start
        q = size // buckets
        r = size - q * buckets
        big = r * (q + 1)  # rows covered by the (q+1)-sized buckets
        in_big = pos < big
        # q == 0 only when size < buckets, where every row is its own
        # bucket and pos < big always holds — the q-branch is never taken
        tile = jnp.where(
            in_big,
            pos // jnp.maximum(q + 1, 1),
            r + (pos - big) // jnp.maximum(q, 1),
        )
        return self._int_col(tile + 1)

    @func_range("window_percent_rank")
    def percent_rank(self) -> Column:
        """PERCENT_RANK: (rank - 1) / (partition rows - 1); 0.0 for
        single-row partitions."""
        rank = self._first_peer() - self._p_start
        size = self._p_end - self._p_start + 1
        pr = rank.astype(jnp.float64) / jnp.maximum(
            size - 1, 1).astype(jnp.float64)
        return Column(DType(TypeId.FLOAT64), self._unsort(pr), None)

    @func_range("window_cume_dist")
    def cume_dist(self) -> Column:
        """CUME_DIST: rows up to and including the current row's peers,
        over the partition row count."""
        size = self._p_end - self._p_start + 1
        upto = self._peer_end - self._p_start + 1
        cd = upto.astype(jnp.float64) / size.astype(jnp.float64)
        return Column(DType(TypeId.FLOAT64), self._unsort(cd), None)

    def _gather_at(self, c: Column, pos: jnp.ndarray,
                   in_frame: jnp.ndarray) -> Column:
        """Gather column values at sorted positions ``pos``, null outside
        ``in_frame``, unsorted back to input row order."""
        if c.dtype.is_string:
            from spark_rapids_jni_tpu.ops import strings as s

            c = s.pad_strings(c)
        src = jnp.clip(pos, 0, max(self._n - 1, 0)).astype(jnp.int32)
        validity = c.valid_mask()[src] & in_frame
        chars = c.chars[src] if c.is_padded_string else None
        return Column(c.dtype, self._unsort(c.data[src]),
                      self._unsort(validity),
                      chars=None if chars is None else self._unsort(chars))

    @func_range("window_first_value")
    def first_value(self, col_idx: int) -> Column:
        """FIRST_VALUE under Spark's default frame (RANGE UNBOUNDED
        PRECEDING .. CURRENT ROW): the partition's first row."""
        c = self._sorted.column(col_idx)
        return self._gather_at(c, self._p_start,
                               jnp.ones((self._n,), jnp.bool_))

    @func_range("window_last_value")
    def last_value(self, col_idx: int) -> Column:
        """LAST_VALUE under Spark's default frame: the last row of the
        current row's peer group (RANGE frames include peers)."""
        c = self._sorted.column(col_idx)
        return self._gather_at(c, self._peer_end,
                               jnp.ones((self._n,), jnp.bool_))

    @func_range("window_nth_value")
    def nth_value(self, col_idx: int, k: int) -> Column:
        """NTH_VALUE(col, k), 1-based from the frame start; null when the
        default frame (partition start .. peer end) has fewer than k rows."""
        if k <= 0:
            raise ValueError("nth_value offset is 1-based and positive")
        c = self._sorted.column(col_idx)
        pos = self._p_start + (k - 1)
        return self._gather_at(c, pos, pos <= self._peer_end)

    @func_range("window_running_sum")
    def running_sum(self, col_idx: int) -> Column:
        """SUM over ROWS UNBOUNDED PRECEDING .. CURRENT ROW."""
        return self._running(col_idx, "sum")

    @func_range("window_running_min")
    def running_min(self, col_idx: int) -> Column:
        return self._running(col_idx, "min")

    @func_range("window_running_max")
    def running_max(self, col_idx: int) -> Column:
        return self._running(col_idx, "max")
