"""Window functions over sorted partitions — the cuDF rolling/window
surface Spark's window expressions lower to (vendored capability family,
SURVEY.md section 2.2).

TPU-first design: one sort by (partition keys, order keys), per-row
results computed with the groupby module's scatter-free segmented
machinery (log-depth segmented scans, cummax boundary tracking — no
segment_* scatters, which serialize on TPU), then one gather through the
sort's inverse permutation so every result column aligns with the INPUT
row order (Spark window semantics: results join back to their rows).

Null order keys sort by the sort module's null rules and otherwise
behave as values; null partition keys form their own partition (Spark).
"""

from __future__ import annotations

from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np

from spark_rapids_jni_tpu.columnar import Column, Table
from spark_rapids_jni_tpu.ops.groupby import (
    _rows_equal_prev,
    _segmented_extremum,
    _segmented_sum_scan,
)
from spark_rapids_jni_tpu.ops.sort import gather, sort_order
from spark_rapids_jni_tpu.types import DType, TypeId
from spark_rapids_jni_tpu.utils.tracing import func_range


class Window:
    """Shared precompute for one PARTITION BY / ORDER BY spec: the sort,
    its inverse, and the partition/peer boundary flags. Build once, call
    any number of window functions against it."""

    def __init__(
        self,
        table: Table,
        partition_by: Sequence[int],
        order_by: Sequence[int],
        ascending: Sequence[bool] | None = None,
        nulls_first: Sequence[bool] | None = None,
    ):
        self._table = table
        n = table.num_rows
        self._n = n
        keys = list(partition_by) + list(order_by)
        asc = ([True] * len(partition_by) + list(ascending)
               if ascending is not None else None)
        nf = ([True] * len(partition_by) + list(nulls_first)
              if nulls_first is not None else None)
        self._order = sort_order(table, keys, ascending=asc, nulls_first=nf)
        self._sorted = gather(table, self._order)
        # inverse permutation via argsort — a sort, never a scatter
        self._inv = jnp.argsort(self._order).astype(jnp.int32)
        # same_p[i]: sorted row i continues row i-1's partition;
        # same_peer[i]: ... AND has an equal order-key tuple (rank peers)
        self._same_p = _rows_equal_prev(self._sorted, list(
            range(len(partition_by))))
        self._same_peer = _rows_equal_prev(
            self._sorted, list(range(len(keys))))
        self._idx = jnp.arange(n, dtype=jnp.int64)
        # position of each sorted row's partition start (cummax of starts)
        self._p_start = jax.lax.associative_scan(
            jnp.maximum, jnp.where(~self._same_p, self._idx, -1))
        # ...and its partition end: the next start minus one (reverse
        # cummin of start positions, exclusive)
        start_pos = jnp.where(~self._same_p, self._idx, n)
        nxt = jnp.flip(jax.lax.associative_scan(
            jnp.minimum, jnp.flip(start_pos)))
        # nxt[i] = earliest start at or after i; shift to get "after i"
        nxt_after = jnp.concatenate(
            [nxt[1:], jnp.full((1,), n, dtype=nxt.dtype)]) if n else nxt
        self._p_end = nxt_after - 1

    def _unsort(self, sorted_vals: jnp.ndarray) -> jnp.ndarray:
        return sorted_vals[self._inv]

    def _int_col(self, sorted_vals: jnp.ndarray) -> Column:
        return Column(DType(TypeId.INT64),
                      self._unsort(sorted_vals.astype(jnp.int64)), None)

    @func_range("window_row_number")
    def row_number(self) -> Column:
        """1-based position within the partition (ROW_NUMBER)."""
        return self._int_col(self._idx - self._p_start + 1)

    @func_range("window_rank")
    def rank(self) -> Column:
        """RANK: 1 + rows strictly before the first peer (gaps on ties)."""
        first_peer = jax.lax.associative_scan(
            jnp.maximum, jnp.where(~self._same_peer, self._idx, -1))
        return self._int_col(first_peer - self._p_start + 1)

    @func_range("window_dense_rank")
    def dense_rank(self) -> Column:
        """DENSE_RANK: distinct order-key values seen so far (no gaps)."""
        new_val = (~self._same_peer).astype(jnp.int64)
        dr = _segmented_sum_scan(new_val[:, None], ~self._same_p)[:, 0]
        return self._int_col(dr)

    def _shifted(self, col_idx: int, k: int) -> Column:
        c = self._sorted.column(col_idx)
        if c.dtype.is_string:
            from spark_rapids_jni_tpu.ops import strings as s

            c = s.pad_strings(c)
        src = jnp.clip(self._idx - k, 0, max(self._n - 1, 0)).astype(
            jnp.int32)
        in_bounds = (self._idx - k >= 0) & (self._idx - k < self._n)
        # same partition iff the partition start did not change
        same_part = self._p_start[src] == self._p_start
        ok = in_bounds & same_part
        validity = c.valid_mask()[src] & ok
        chars = c.chars[src] if c.is_padded_string else None
        data = c.data[src]
        out = Column(c.dtype, self._unsort(data),
                     self._unsort(validity),
                     chars=None if chars is None else self._unsort(chars))
        return out

    @func_range("window_lag")
    def lag(self, col_idx: int, k: int = 1) -> Column:
        """Value k rows earlier in the partition, null past the edge."""
        if k < 0:
            raise ValueError("lag offset must be >= 0 (use lead)")
        return self._shifted(col_idx, k)

    @func_range("window_lead")
    def lead(self, col_idx: int, k: int = 1) -> Column:
        """Value k rows later in the partition, null past the edge."""
        if k < 0:
            raise ValueError("lead offset must be >= 0 (use lag)")
        return self._shifted(col_idx, -k)

    def _running(self, col_idx: int, op: str) -> Column:
        c = self._sorted.column(col_idx)
        if c.dtype.is_string or c.dtype.is_decimal128:
            raise NotImplementedError(
                f"running {op} needs fixed-width numeric columns"
            )
        valid = c.valid_mask()
        if op == "sum":
            from spark_rapids_jni_tpu.ops.groupby import _sum_dtype

            acc_dt = _sum_dtype(c.dtype)
            zero = jnp.zeros_like(c.data)
            vv = jnp.where(valid, c.data, zero)
            if acc_dt.storage_dtype.kind in ("i", "u"):
                vv = vv.astype(jnp.int64)
            else:
                vv = vv.astype(jnp.float64)
            run = _segmented_sum_scan(vv[:, None], ~self._same_p)[:, 0]
            # running count of valid values: all-null-so-far stays null
            cnt = _segmented_sum_scan(
                valid.astype(jnp.int64)[:, None], ~self._same_p)[:, 0]
            return Column(acc_dt,
                          self._unsort(run.astype(acc_dt.jnp_dtype)),
                          self._unsort(cnt > 0))
        np_dt = c.dtype.storage_dtype
        if np_dt.kind == "f":
            sentinel = jnp.inf if op == "min" else -jnp.inf
        else:
            info = np.iinfo(np_dt)
            sentinel = info.max if op == "min" else info.min
        vv = jnp.where(valid, c.data, jnp.asarray(sentinel, c.data.dtype))
        run = _segmented_extremum(vv, ~self._same_p, op)
        cnt = _segmented_sum_scan(
            valid.astype(jnp.int64)[:, None], ~self._same_p)[:, 0]
        return Column(c.dtype, self._unsort(run), self._unsort(cnt > 0))

    def _rolling_parts(self, col_idx: int, preceding: int, following: int):
        """Shared rolling-frame machinery: per-row frame sums and counts
        over ROWS BETWEEN preceding PRECEDING AND following FOLLOWING,
        clamped to the partition — prefix differences of the SEGMENTED
        running sum (resets each partition, so int lanes are exact and
        float error stays partition-local)."""
        if preceding < 0 or following < 0:
            raise ValueError("rolling bounds must be >= 0")
        c = self._sorted.column(col_idx)
        if c.dtype.is_string or c.dtype.is_decimal128:
            raise NotImplementedError(
                "rolling aggregates need fixed-width numeric columns")
        valid = c.valid_mask()
        vv = jnp.where(valid, c.data, jnp.zeros_like(c.data))
        if c.dtype.storage_dtype.kind in ("i", "u", "b"):
            vv = vv.astype(jnp.int64)
        else:
            vv = vv.astype(jnp.float64)
        n = self._n
        run = _segmented_sum_scan(vv[:, None], ~self._same_p)[:, 0]
        cnt = _segmented_sum_scan(
            valid.astype(jnp.int64)[:, None], ~self._same_p)[:, 0]
        lo = jnp.clip(self._idx - preceding, self._p_start, self._p_end)
        hi = jnp.clip(self._idx + following, self._p_start, self._p_end)
        safe = lambda a, i: a[jnp.clip(i, 0, max(n - 1, 0))]

        def frame(arr):
            upper = safe(arr, hi)
            base = jnp.where(lo > self._p_start, safe(arr, lo - 1), 0)
            return upper - base

        return c, frame(run), frame(cnt)

    @func_range("window_rolling_sum")
    def rolling_sum(self, col_idx: int, preceding: int,
                    following: int = 0) -> Column:
        """SUM over ROWS BETWEEN preceding PRECEDING AND following
        FOLLOWING (the cuDF rolling-window op). Exact for int/decimal
        lanes; float frames difference partition-local running sums
        (documented float-rounding posture)."""
        from spark_rapids_jni_tpu.ops.groupby import _sum_dtype

        c, wsum, wcnt = self._rolling_parts(col_idx, preceding, following)
        acc_dt = _sum_dtype(c.dtype)
        return Column(acc_dt,
                      self._unsort(wsum.astype(acc_dt.jnp_dtype)),
                      self._unsort(wcnt > 0))

    @func_range("window_rolling_count")
    def rolling_count(self, col_idx: int, preceding: int,
                      following: int = 0) -> Column:
        """COUNT of non-null values in the rolling frame."""
        _, _, wcnt = self._rolling_parts(col_idx, preceding, following)
        return Column(DType(TypeId.INT64), self._unsort(wcnt), None)

    @func_range("window_rolling_mean")
    def rolling_mean(self, col_idx: int, preceding: int,
                     following: int = 0) -> Column:
        """AVG over the rolling frame (FLOAT64, decimal-rescaled like the
        groupby mean contract)."""
        c, wsum, wcnt = self._rolling_parts(col_idx, preceding, following)
        denom = jnp.maximum(wcnt, 1).astype(jnp.float64)
        m = wsum.astype(jnp.float64) / denom
        if c.dtype.is_decimal:
            m = m * (10.0 ** c.dtype.scale)
        return Column(DType(TypeId.FLOAT64), self._unsort(m),
                      self._unsort(wcnt > 0))

    @func_range("window_running_sum")
    def running_sum(self, col_idx: int) -> Column:
        """SUM over ROWS UNBOUNDED PRECEDING .. CURRENT ROW."""
        return self._running(col_idx, "sum")

    @func_range("window_running_min")
    def running_min(self, col_idx: int) -> Column:
        return self._running(col_idx, "min")

    @func_range("window_running_max")
    def running_max(self, col_idx: int) -> Column:
        return self._running(col_idx, "max")
