"""Spark-compatible xxhash64 (north-star component: the reference family's
``xxhash64`` kernel backs Spark's HashPartitioning/Bloom filters;
BASELINE.json north_star lists it explicitly).

Implements XXH64's short-input paths — hashInt (4-byte) and hashLong
(8-byte) — exactly as Spark's ``XXH64`` utility applies them per column
value, chained across columns with the running hash as seed and nulls
skipped (Spark HashExpression semantics). Fully vectorized uint64
arithmetic: multiplies/rotates/xors are all implemented by the TPU x64
emulation pass (no bitcasts needed for integer inputs; floats go through
ops.bytecast encodings).

Spark value widening rules: bool/byte/short/int -> hashInt of the int32
value; long/timestamp/date64 -> hashLong; float -> hashInt of its IEEE
bits (-0.0 normalized to 0.0); double -> hashLong of its bits (-0.0
normalized); decimal32/64 -> hashLong of the unscaled value.
"""

from __future__ import annotations

from functools import partial
from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np

from spark_rapids_jni_tpu.columnar import Column, Table
from spark_rapids_jni_tpu.ops.bytecast import _f64_to_bits, _has_bitcast64
from spark_rapids_jni_tpu.types import TypeId
from spark_rapids_jni_tpu.utils.tracing import func_range

SPARK_DEFAULT_SEED = 42

_P1 = np.uint64(0x9E3779B185EBCA87)
_P2 = np.uint64(0xC2B2AE3D4F54DE4F)
_P3 = np.uint64(0x165667B19E3779F9)
_P4 = np.uint64(0x85EBCA77C2B2AE63)
_P5 = np.uint64(0x27D4EB2F165667C5)


def _rotl(x: jnp.ndarray, r: int) -> jnp.ndarray:
    return (x << np.uint64(r)) | (x >> np.uint64(64 - r))


def _avalanche(h: jnp.ndarray) -> jnp.ndarray:
    h = h ^ (h >> np.uint64(33))
    h = h * _P2
    h = h ^ (h >> np.uint64(29))
    h = h * _P3
    h = h ^ (h >> np.uint64(32))
    return h


def xxhash64_long(value: jnp.ndarray, seed: jnp.ndarray) -> jnp.ndarray:
    """XXH64 of one 8-byte little-endian value per row (Spark hashLong)."""
    value = value.astype(jnp.uint64)
    seed = seed.astype(jnp.uint64)
    h = seed + _P5 + np.uint64(8)
    k1 = _rotl(value * _P2, 31) * _P1
    h = h ^ k1
    h = _rotl(h, 27) * _P1 + _P4
    return _avalanche(h)


def xxhash64_int(value: jnp.ndarray, seed: jnp.ndarray) -> jnp.ndarray:
    """XXH64 of one 4-byte value per row (Spark hashInt)."""
    v = value.astype(jnp.uint32).astype(jnp.uint64)
    seed = seed.astype(jnp.uint64)
    h = seed + _P5 + np.uint64(4)
    h = h ^ (v * _P1)
    h = _rotl(h, 23) * _P2 + _P3
    return _avalanche(h)


def _column_hash(col: Column, seeds: jnp.ndarray) -> jnp.ndarray:
    """Hash one column's values with per-row seeds; null rows pass the seed
    through unchanged (Spark chaining semantics)."""
    tid = col.dtype.type_id
    v = col.data
    if tid == TypeId.STRING:
        from spark_rapids_jni_tpu.ops import strings as s

        # full variable-length XXH64 over the row's UTF-8 bytes — Spark's
        # hashUnsafeBytes / the reference family's string xxhash64 kernel.
        return s.hash_string_column(col, seeds)
    if tid in (TypeId.BOOL8, TypeId.INT8, TypeId.UINT8, TypeId.INT16,
               TypeId.UINT16, TypeId.INT32, TypeId.UINT32,
               TypeId.TIMESTAMP_DAYS, TypeId.DURATION_DAYS):
        # sign-extend to int32 like Spark's widening to int
        hashed = xxhash64_int(v.astype(jnp.int32), seeds)
    elif tid == TypeId.FLOAT32:
        norm = jnp.where(v == 0.0, jnp.float32(0.0), v)  # -0.0 -> 0.0
        bits = jax.lax.bitcast_convert_type(norm, jnp.uint32)
        hashed = xxhash64_int(bits, seeds)
    elif tid == TypeId.FLOAT64:
        norm = jnp.where(v == 0.0, jnp.float64(0.0), v)
        if _has_bitcast64():
            bits = jax.lax.bitcast_convert_type(norm, jnp.uint64)
        else:
            bits = _f64_to_bits(norm)
        hashed = xxhash64_long(bits, seeds)
    elif col.dtype.is_decimal128:
        # Spark hashes Decimal(precision > 18) as XXH64 over the MINIMAL
        # big-endian two's-complement byte array of the unscaled value
        # (java BigDecimal.unscaledValue().toByteArray()): build the
        # 16-byte big-endian image, strip redundant sign-filler bytes
        # (keeping one when the next byte's sign bit would flip the
        # value), left-align, and run the variable-length byte hash.
        from spark_rapids_jni_tpu.ops.strings import xxhash64_bytes

        lo = v[:, 0]
        hi = v[:, 1]
        shifts = jnp.arange(56, -1, -8, dtype=jnp.int64)
        be = jnp.concatenate(
            [((hi[:, None] >> shifts[None, :]) & 0xFF),
             ((lo[:, None] >> shifts[None, :]) & 0xFF)], axis=1
        ).astype(jnp.uint8)                         # (n, 16) big-endian
        filler = jnp.where(hi < 0, jnp.uint8(0xFF), jnp.uint8(0))
        is_filler = be == filler[:, None]
        # first non-filler byte index (16 when all filler: value 0 / -1)
        nf = jnp.argmin(is_filler.astype(jnp.int8), axis=1).astype(jnp.int32)
        all_filler = jnp.all(is_filler, axis=1)
        first = jnp.where(all_filler, 15, nf)
        # sign bit of the first kept byte must match the filler's, else
        # one filler byte stays (0x80 <-> sign flip)
        fb = jnp.take_along_axis(be, first[:, None], axis=1)[:, 0]
        sign_mismatch = (fb >= 0x80) != (hi < 0)
        start = jnp.where(all_filler, 15,
                          jnp.where(sign_mismatch, first - 1, first))
        lengths = (16 - start).astype(jnp.int32)
        src = jnp.clip(start[:, None] + jnp.arange(16, dtype=jnp.int32), 0, 15)
        shifted = jnp.take_along_axis(be, src, axis=1)
        hashed = xxhash64_bytes(shifted, lengths, seeds)
    else:
        hashed = xxhash64_long(v.astype(jnp.int64), seeds)
    if col.validity is None:
        return hashed
    return jnp.where(col.validity, hashed, seeds)


def _table_xxhash64_impl(row_args, aux, rvs, *, seed: int):
    ((table,),) = row_args
    n = table.num_rows
    h = jnp.full((n,), np.uint64(seed), dtype=jnp.uint64)
    for c in range(table.num_columns):
        h = _column_hash(table.column(c), h)
    return h.astype(jnp.int64)


@func_range("hash_table")
def table_xxhash64(
    table: Table,
    columns: Sequence[int] | None = None,
    seed: int = SPARK_DEFAULT_SEED,
) -> jnp.ndarray:
    """Row hash: per-column xxhash64 chained left-to-right with the running
    hash as seed (Spark HashExpression). Returns int64[n]. Spark-exact for
    every supported type, including DECIMAL128 (minimal two's-complement
    byte-array hash, the Decimal(precision > 18) rule)."""
    cols = tuple(range(table.num_columns) if columns is None else columns)
    # dispatch only the hashed columns: an unused Arrow-layout string
    # elsewhere in the table must not force the inline path (pad rows are
    # null -> they pass the seed through, and the tail is sliced off)
    sub = Table([table.column(c) for c in cols])
    from spark_rapids_jni_tpu.runtime import dispatch

    return dispatch.rowwise(
        "table_xxhash64", partial(_table_xxhash64_impl, seed=seed),
        (sub,), statics=(seed,))


def partition_hash(table: Table, columns: Sequence[int], num_partitions: int) -> jnp.ndarray:
    """Spark-style hash partitioning: pmod(hash, n). Returns int32[n].
    jnp's % follows Python semantics (result carries the divisor's sign),
    which IS pmod."""
    h = table_xxhash64(table, columns)
    return (h % jnp.int64(num_partitions)).astype(jnp.int32)


def probe_sorted_lo_hi(
    sorted_key: jnp.ndarray, probe_key: jnp.ndarray
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Kernel-tier seam for the hash-join/groupby probe loop: per probe
    key, the [lo, hi) match-run bounds in the sentinel-padded sorted
    build keys.

    Tier pick happens at TRACE time (the dispatch cache key carries the
    kernels digest, so join executables re-specialize when the tier
    flips). The Pallas twin (ops/pallas/hash_probe.py) streams the
    SMEM-resident build keys past each probe tile and is bit-identical
    to the searchsorted pair by construction; anything it cannot take —
    empty sides, > MAX_BUILD build rows, non-int32 keys — falls back to
    the XLA oracle below with the reason recorded, never silently.
    """
    from spark_rapids_jni_tpu.ops import pallas as pallas_tier

    op = "join.hash_probe"
    decision = pallas_tier.decide(op)
    if decision.use_pallas:
        from spark_rapids_jni_tpu.ops.pallas import hash_probe as hp

        if sorted_key.shape[0] == 0 or probe_key.shape[0] == 0:
            reason = "empty_input"
        else:
            reason = hp.unsupported_reason(
                sorted_key.shape[0], sorted_key.dtype)
        if reason is None:
            return hp.probe_lo_hi(
                sorted_key, probe_key, interpret=decision.interpret)
        pallas_tier.fall_back(op, reason)
    lo = jnp.searchsorted(sorted_key, probe_key, side="left")
    hi = jnp.searchsorted(sorted_key, probe_key, side="right")
    return lo, hi
