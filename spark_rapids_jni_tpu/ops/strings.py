"""String columns in the relational core — sort keys, equality, hashing,
gather, and the padded device layout.

The reference's relational substrate handles STRING keys everywhere (cuDF
sort/groupby/join capability surface, built by build-libcudf.xml:34-60).
cuDF's device layout is Arrow (offsets + chars); its kernels walk the ragged
buffers with per-thread char loops. That shape is hostile to the TPU: ragged
gathers serialize on the VPU and defeat XLA tiling.

TPU-first design — two layouts, one conversion boundary:

- **Arrow layout** (offsets int32[n+1], chars uint8[m]) at rest and in IO —
  what the Parquet/ORC readers produce and `collect` returns.
- **Padded layout** (lengths int32[n], bytes uint8[n, W]) on device for
  relational ops. W is a planner-chosen static width (max row length). Every
  string op becomes a dense, vectorized pass over the matrix: sort keys are
  big-endian packed uint32 words (memcmp order, length as tiebreak), row
  equality is one masked compare, and xxhash64 runs the *full* variable-length
  algorithm with masked lane updates — no per-row loops anywhere.

Conversions are single gathers (static shapes both ways; Arrow->padded pads,
padded->Arrow compacts into an n*W char buffer with the real total tracked by
offsets). Width is computed on host where data is host-visible, or passed
statically by the planner inside jit.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from spark_rapids_jni_tpu import telemetry
from spark_rapids_jni_tpu.columnar import Column
from spark_rapids_jni_tpu.types import DType, TypeId
from spark_rapids_jni_tpu.utils.tracing import func_range

STRING = DType(TypeId.STRING)


# ---------------------------------------------------------------------------
# Layout predicates / conversions
# ---------------------------------------------------------------------------

def is_padded(col: Column) -> bool:
    """True when a string column carries the padded (n, W) device layout."""
    return col.is_padded_string


def max_string_width(col: Column) -> int:
    """Host-side max row length (0 for an all-empty column). Only valid
    outside jit: forces a device->host read of the offsets."""
    if is_padded(col):
        return int(col.chars.shape[1])
    offsets = np.asarray(col.data)
    if offsets.shape[0] <= 1:
        return 0
    return int(np.max(offsets[1:] - offsets[:-1]))


def pad_strings(col: Column, width: int | None = None) -> Column:
    """Arrow -> padded layout. ``width`` must be >= every row length (rows
    longer than width would corrupt; callers use max_string_width or a
    planner bound). Cells past a row's length are zero."""
    if is_padded(col):
        return col
    if width is None:
        try:
            width = max_string_width(col)
        except jax.errors.TracerArrayConversionError:
            raise ValueError(
                "pad_strings inside jit needs an explicit static width — "
                "convert string columns to the padded layout (pad_strings) "
                "on host before entering jit, or pass width="
            ) from None
    width = max(int(width), 1)
    offsets = col.data
    chars = col.chars
    n = int(offsets.shape[0]) - 1
    if n == 0 or int(chars.shape[0]) == 0:
        return Column(
            STRING,
            jnp.zeros((n,), jnp.int32),
            col.validity,
            chars=jnp.zeros((n, width), jnp.uint8),
        )
    starts = offsets[:-1]
    lengths = (offsets[1:] - starts).astype(jnp.int32)
    idx = starts[:, None] + jnp.arange(width, dtype=jnp.int32)[None, :]
    present = jnp.arange(width, dtype=jnp.int32)[None, :] < lengths[:, None]
    cap = int(chars.shape[0]) - 1
    mat = jnp.where(present, chars[jnp.clip(idx, 0, cap)], jnp.uint8(0))
    return Column(STRING, lengths, col.validity, chars=mat)


def unpad_strings(col: Column) -> Column:
    """Padded -> Arrow layout. The chars buffer is allocated at the static
    bound n*W; offsets[-1] carries the true total (slack bytes at the end
    are dead, which the Arrow contract allows)."""
    if not is_padded(col):
        return col
    lengths = col.data
    mat = col.chars
    n, width = int(mat.shape[0]), int(mat.shape[1])
    if n == 0:
        return Column(
            STRING,
            jnp.zeros((1,), jnp.int32),
            col.validity,
            chars=jnp.zeros((0,), jnp.uint8),
        )
    offsets = jnp.concatenate(
        [jnp.zeros((1,), jnp.int32), jnp.cumsum(lengths).astype(jnp.int32)]
    )
    # Compact gather: output char position c belongs to the row r with
    # offsets[r] <= c < offsets[r+1]; its source byte is mat[r, c - offsets[r]].
    total_cap = max(n * width, 1)
    c = jnp.arange(total_cap, dtype=jnp.int32)
    row = jnp.searchsorted(offsets[1:], c, side="right").astype(jnp.int32)
    row = jnp.clip(row, 0, max(n - 1, 0))
    delta = c - offsets[row]
    inside = c < offsets[-1]
    flat = mat.reshape(-1)
    src = jnp.clip(row * width + delta, 0, max(n * width - 1, 0))
    chars = jnp.where(inside, flat[src], jnp.uint8(0))
    return Column(STRING, offsets, col.validity, chars=chars)


def pad_to_common_width(cols):
    """Pad several string columns to one shared (max) padded width —
    the normalization concatenate/coalesce need before mixing rows."""
    ps = [pad_strings(c) for c in cols]
    w = max(int(p.chars.shape[1]) for p in ps)
    return [
        p if int(p.chars.shape[1]) == w else Column(
            p.dtype, p.data, p.validity,
            chars=jnp.pad(p.chars, ((0, 0), (0, w - p.chars.shape[1]))))
        for p in ps
    ]


def gather_strings(col: Column, indices: jnp.ndarray) -> Column:
    """Row gather of a padded string column (padded layout makes this the
    same two-array gather as fixed-width columns)."""
    col = pad_strings(col)
    validity = None if col.validity is None else col.validity[indices]
    return Column(STRING, col.data[indices], validity, chars=col.chars[indices])


# ---------------------------------------------------------------------------
# Sort keys / equality
# ---------------------------------------------------------------------------

def packed_sort_keys(col: Column) -> list[jnp.ndarray]:
    """Order-preserving lexsort keys for a padded string column, minor to
    major: [length, word_k-1, ..., word_0]. Each word packs 4 bytes
    big-endian into uint32, so uint32 comparison == memcmp on those bytes;
    zero padding ties equal prefixes and the length key breaks them
    (shorter first) — exactly memcmp-then-length string order, correct for
    embedded NUL bytes too."""
    col = pad_strings(col)
    mat = col.chars
    lengths = col.data
    width = int(mat.shape[1])
    n_words = (width + 3) // 4
    pad_w = n_words * 4 - width
    if pad_w:
        mat = jnp.pad(mat, ((0, 0), (0, pad_w)))
    u = mat.astype(jnp.uint32).reshape(mat.shape[0], n_words, 4)
    words = (
        (u[:, :, 0] << 24) | (u[:, :, 1] << 16) | (u[:, :, 2] << 8) | u[:, :, 3]
    )
    keys = [words[:, i] for i in range(n_words - 1, -1, -1)]
    return [lengths.astype(jnp.uint32)] + keys


def strings_equal_prev(col: Column) -> jnp.ndarray:
    """bool[n-1]: row i+1's bytes equal row i's (groupby boundary test)."""
    col = pad_strings(col)
    mat, lengths = col.chars, col.data
    eq_len = lengths[1:] == lengths[:-1]
    eq_bytes = jnp.all(mat[1:] == mat[:-1], axis=1)
    return eq_len & eq_bytes


# ---------------------------------------------------------------------------
# Variable-length xxhash64 (Spark hashUnsafeBytes parity)
# ---------------------------------------------------------------------------

from spark_rapids_jni_tpu.ops.hash import (  # noqa: E402 — shared primitives
    _P1, _P2, _P3, _P4, _P5, _avalanche, _rotl,
)


def _le_words(mat: jnp.ndarray, n_lanes: int, lane_bytes: int) -> jnp.ndarray:
    """(n, n_lanes) little-endian words of ``lane_bytes`` each from the
    leading n_lanes*lane_bytes columns of the byte matrix."""
    u = mat[:, : n_lanes * lane_bytes].astype(jnp.uint64)
    u = u.reshape(mat.shape[0], n_lanes, lane_bytes)
    shifts = jnp.asarray(
        [np.uint64(8 * i) for i in range(lane_bytes)], dtype=jnp.uint64
    )
    return jnp.sum(u << shifts[None, None, :], axis=2, dtype=jnp.uint64)


@func_range("xxhash64_bytes")
def xxhash64_bytes(
    mat: jnp.ndarray, lengths: jnp.ndarray, seeds: jnp.ndarray
) -> jnp.ndarray:
    """Full XXH64 of each row's first ``lengths[i]`` bytes, vectorized over
    rows with per-row seeds — the exact algorithm Spark's hashUnsafeBytes /
    the reference family's string xxhash64 kernel computes, expressed as a
    static number of masked elementwise passes (width/8 lane updates), not
    per-row loops. Rows' bytes past their length MUST be zero-padded (they
    are masked out, but the packing helpers guarantee it anyway)."""
    width = int(mat.shape[1])
    lengths = lengths.astype(jnp.int64)
    seeds = seeds.astype(jnp.uint64)

    # Stripe phase: process 32-byte stripes for rows with length >= 32.
    n_stripes = width // 32
    n_rows_u64 = (width + 7) // 8
    padded_w = n_rows_u64 * 8
    if padded_w != width:
        mat8 = jnp.pad(mat, ((0, 0), (0, padded_w - width)))
    else:
        mat8 = mat
    lanes = _le_words(mat8, n_rows_u64, 8)  # (n, n_rows_u64) uint64 LE lanes

    full_stripes = jnp.where(lengths >= 32, lengths // 32, 0)
    v1 = seeds + _P1 + _P2
    v2 = seeds + _P2
    v3 = seeds
    v4 = seeds - _P1
    for s in range(n_stripes):
        active = s < full_stripes
        l0, l1 = lanes[:, 4 * s], lanes[:, 4 * s + 1]
        l2, l3 = lanes[:, 4 * s + 2], lanes[:, 4 * s + 3]
        v1 = jnp.where(active, _rotl(v1 + l0 * _P2, 31) * _P1, v1)
        v2 = jnp.where(active, _rotl(v2 + l1 * _P2, 31) * _P1, v2)
        v3 = jnp.where(active, _rotl(v3 + l2 * _P2, 31) * _P1, v3)
        v4 = jnp.where(active, _rotl(v4 + l3 * _P2, 31) * _P1, v4)
    h_long = (
        _rotl(v1, 1) + _rotl(v2, 7) + _rotl(v3, 12) + _rotl(v4, 18)
    )
    for v in (v1, v2, v3, v4):
        h_long = (h_long ^ (_rotl(v * _P2, 31) * _P1)) * _P1 + _P4
    h = jnp.where(lengths >= 32, h_long, seeds + _P5)
    h = h + lengths.astype(jnp.uint64)

    consumed = full_stripes * 32  # bytes already absorbed per row

    # 8-byte tail lanes: up to width//8 of them, masked per row.
    full_words = lengths // 8
    for w in range(n_rows_u64):
        active = (w >= consumed // 8) & (w < full_words)
        upd = (h ^ (_rotl(lanes[:, w] * _P2, 31) * _P1))
        upd = _rotl(upd, 27) * _P1 + _P4
        h = jnp.where(active, upd, h)

    # One optional 4-byte lane.
    word4 = _le_words(mat8, n_rows_u64 * 2, 4)  # (n, 2*n_rows_u64) uint32-in-u64
    pos4 = full_words * 2  # index of the 4-byte word at offset full_words*8
    has4 = (lengths % 8) >= 4
    lane4 = jnp.take_along_axis(
        word4, jnp.clip(pos4, 0, word4.shape[1] - 1)[:, None], axis=1
    )[:, 0]
    upd = (h ^ (lane4 * _P1))
    upd = _rotl(upd, 23) * _P2 + _P3
    h = jnp.where(has4, upd, h)

    # Up to 7 single-byte tail updates (3 if the 4-byte lane fired).
    tail_start = full_words * 8 + jnp.where(has4, 4, 0)
    n_tail_max = min(7, width) if width else 0
    matu = mat8.astype(jnp.uint64)
    for b in range(n_tail_max):
        pos = tail_start + b
        active = pos < lengths
        byte = jnp.take_along_axis(
            matu, jnp.clip(pos, 0, padded_w - 1).astype(jnp.int32)[:, None], axis=1
        )[:, 0]
        upd = _rotl(h ^ (byte * _P5), 11) * _P1
        h = jnp.where(active, upd, h)

    return _avalanche(h)


def hash_string_column(col: Column, seeds: jnp.ndarray) -> jnp.ndarray:
    """Chainable per-row hash of a string column: full XXH64 over each row's
    UTF-8 bytes with the running hash as seed; null rows pass the seed
    through (Spark HashExpression chaining semantics)."""
    col = pad_strings(col)
    hashed = xxhash64_bytes(col.chars, col.data, seeds)
    if col.validity is None:
        return hashed
    return jnp.where(col.validity, hashed, seeds)


# ---- search predicates (cuDF strings::contains/find, Spark LIKE) -----------


def _needle_windows(col: Column, needle: bytes) -> jnp.ndarray:
    """bool (n, W): position j starts a full match of ``needle`` (callers
    special-case empty needles; f >= 1 here)."""
    assert needle, "empty needles are the caller's fast path"
    p = pad_strings(col)
    mat, lengths = p.chars, p.data
    w = int(mat.shape[1])
    f = len(needle)
    if f > w:
        return jnp.zeros((p.size, w), jnp.bool_)
    jdx = jnp.arange(w, dtype=jnp.int32)
    win = jnp.ones((p.size, w), jnp.bool_)
    for off, byte in enumerate(needle):
        win = win & (jnp.roll(mat, -off, axis=1) == byte)
    return win & (jdx[None, :] + f <= lengths[:, None])


def _bool8_result(hit: jnp.ndarray, col: Column) -> Column:
    """BOOL8 predicate result; validity passes through untouched (None
    stays None — the no-null-mask fast path)."""
    return Column(DType(TypeId.BOOL8), hit.astype(jnp.uint8), col.validity)


@func_range("string_contains")
def contains(col: Column, needle: str) -> Column:
    """BOOL8: row contains ``needle`` (empty needle matches everything,
    Java String.contains). Null rows stay null."""
    nb = needle.encode("utf-8")
    if not nb:
        hit = jnp.ones((col.size,), jnp.bool_)
    else:
        hit = jnp.any(_needle_windows(col, nb), axis=1)
    return _bool8_result(hit, col)


@func_range("string_starts_with")
def starts_with(col: Column, prefix: str) -> Column:
    nb = prefix.encode("utf-8")
    if not nb:
        hit = jnp.ones((col.size,), jnp.bool_)
    else:
        hit = _needle_windows(col, nb)[:, 0]
    return _bool8_result(hit, col)


@func_range("string_ends_with")
def ends_with(col: Column, suffix: str) -> Column:
    nb = suffix.encode("utf-8")
    p = pad_strings(col)
    if not nb:
        hit = jnp.ones((col.size,), jnp.bool_)
    else:
        win = _needle_windows(p, nb)
        pos = jnp.clip(p.data - len(nb), 0, max(int(p.chars.shape[1]) - 1, 0))
        hit = jnp.take_along_axis(win, pos[:, None], axis=1)[:, 0]
        hit = hit & (p.data >= len(nb))
    return _bool8_result(hit, col)


@func_range("string_like")
def like(col: Column, pattern: str, escape: str = "\\") -> Column:
    """SQL LIKE: '%' any run, '_' any single CHARACTER, escape char
    literal-izes the next char. Compiled to a literal-segment plan
    evaluated with vectorized window matches + a per-gap reachability
    scan — no regex engine, no per-row host work.

    '_' advances one UTF-8 CHARACTER (Spark semantics) via character-
    boundary tracking; '%' and literals are byte-exact for any UTF-8
    data (a valid-UTF-8 literal cannot match at a continuation byte, so
    byte- and char-anchoring agree). On INVALID UTF-8, continuation
    bytes (0x80-0xBF) always extend the preceding character — e.g. a
    lone b"\\x80\\x80" row counts as one character — matching how a
    byte-oriented UTF-8 scanner segments garbage; behavior on such data
    is unspecified in Spark."""
    esc = escape.encode("utf-8")
    if len(esc) != 1:
        raise ValueError("LIKE escape must be one byte")
    # compile: list of (literal bytes, min_gap, floating) segments
    segs: list[bytes] = []
    gaps: list[tuple[int, bool]] = []  # (min single-char count, saw %)
    cur = bytearray()
    pend_gap = [0, False]
    i = 0
    pb = pattern.encode("utf-8")
    while i < len(pb):
        c = pb[i:i + 1]
        if c == esc:
            # Spark's checkLikePattern posture: the escape char must be
            # followed by %, _, or the escape char itself; a trailing
            # escape (or escaping an ordinary char) is an invalid pattern,
            # not a silent literal.
            nxt = pb[i + 1:i + 2]
            if not nxt or nxt not in (b"%", b"_", esc):
                raise ValueError(
                    f"invalid LIKE pattern {pattern!r}: the escape "
                    f"character must be followed by '%', '_', or the "
                    f"escape character itself"
                )
            cur += nxt
            i += 2
            continue
        if c in (b"%", b"_"):
            if cur:
                segs.append(bytes(cur))
                gaps.append(tuple(pend_gap))
                cur = bytearray()
                pend_gap = [0, False]
            if c == b"%":
                pend_gap[1] = True
            else:
                pend_gap[0] += 1
            i += 1
            continue
        cur += c
        i += 1
    segs.append(bytes(cur))
    gaps.append(tuple(pend_gap))
    tail_gap = (0, False)
    if not segs[-1] and len(segs) > 1:
        tail_gap = gaps.pop()
        segs.pop()

    p = pad_strings(col)
    n = p.size
    w = int(p.chars.shape[1])
    jdx = jnp.arange(w + 1, dtype=jnp.int32)
    # '_' advances one CHARACTER (Spark semantics): position j in [0, w]
    # is a character boundary iff j == 0 or the byte at j is not a UTF-8
    # continuation byte (0x80-0xBF); one-char advance moves each boundary
    # to the NEXT boundary via a prev-boundary gather. On pure-ASCII data
    # every position is a boundary and this degenerates to the one-byte
    # shift. '%' gaps stay byte-based: a valid-UTF-8 literal can never
    # match starting at a continuation byte (lead bytes are < 0x80 or
    # >= 0xC0), so byte-anchoring and char-anchoring agree.
    # boundary at position j <=> the byte AT j starts a character (j = 0
    # and j = w are always boundaries; chars past a row's length are
    # zero-padded, i.e. non-continuation, so the row end works out too)
    if any(g[0] for g in gaps) or tail_gap[0]:
        # only '_'-bearing patterns pay for the boundary machinery
        cont = (p.chars & 0xC0) == 0x80                  # (n, w)
        is_b = jnp.concatenate(
            [jnp.ones((n, 1), jnp.bool_), ~cont[:, 1:],
             jnp.ones((n, 1), jnp.bool_)], axis=1)       # (n, w+1)
        pos_if_b = jnp.where(is_b, jdx[None, :], -1)
        pb_incl = jax.lax.associative_scan(jnp.maximum, pos_if_b, axis=1)
        prev_b = jnp.concatenate(
            [jnp.full((n, 1), -1, jdx.dtype), pb_incl[:, :-1]], axis=1)

        def advance_chars(r, k):
            for _ in range(k):
                r = (is_b & (prev_b >= 0) & jnp.take_along_axis(
                    r, jnp.clip(prev_b, 0, w), axis=1))
            return r
    else:
        def advance_chars(r, k):  # pragma: no cover - zero-count gaps
            return r

    # reach[j] True: pattern consumed so far can end exactly at byte j
    reach = jnp.zeros((n, w + 1), jnp.bool_).at[:, 0].set(True)
    for seg, (mincnt, floating) in zip(segs, gaps):
        # gap: advance exactly mincnt chars (then any amount if floating)
        if mincnt:
            reach = advance_chars(reach, mincnt)
        reach = reach & (jdx[None, :] <= p.data[:, None])
        if floating:
            reach = jax.lax.associative_scan(jnp.logical_or, reach, axis=1)
        if seg:
            win = _needle_windows(p, seg)  # (n, w): match starting at j
            ok_start = jnp.concatenate(
                [win, jnp.zeros((n, 1), jnp.bool_)], axis=1)
            moved = jnp.roll(reach & ok_start, len(seg), axis=1)
            reach = moved & (jdx[None, :] >= len(seg))
    mincnt, floating = tail_gap
    if mincnt:
        reach = advance_chars(reach, mincnt)
    reach = reach & (jdx[None, :] <= p.data[:, None])
    if floating:
        hit = jnp.any(reach, axis=1)
    else:
        hit = jnp.take_along_axis(
            reach, jnp.clip(p.data, 0, w)[:, None], axis=1)[:, 0]
    return _bool8_result(hit, col)


# ---- transforms ------------------------------------------------------------


@func_range("substring")
def substring(col: Column, start: int, length: int | None = None) -> Column:
    """Byte-range substring (cuDF strings::slice_strings with fixed
    bounds): 0-based ``start``, optional ``length`` (None = to end).
    Negative ``start`` counts from the row end, Spark substr semantics.
    Byte-based: callers ensure boundaries are character-aligned for
    multi-byte UTF-8 (the cuDF kernel's posture)."""
    p = pad_strings(col)
    mat, lengths = p.chars, p.data
    w = int(mat.shape[1])
    if start < 0:
        # Spark substringSQL: the end is computed from the UNCLAMPED
        # position, so substr('abc', -5, 2) is '' (end = -2+2 = 0), not 'ab'
        raw = lengths + start
        begin = jnp.clip(raw, 0, lengths)
        if length is None:
            out_len = lengths - begin
        else:
            end = jnp.clip(raw + length, 0, lengths)
            out_len = jnp.maximum(end - begin, 0)
    else:
        begin = jnp.minimum(jnp.full_like(lengths, start), lengths)
        if length is None:
            out_len = lengths - begin
        else:
            out_len = jnp.clip(jnp.full_like(lengths, length), 0,
                               lengths - begin)
    src = begin[:, None] + jnp.arange(w, dtype=jnp.int32)[None, :]
    keep = jnp.arange(w, dtype=jnp.int32)[None, :] < out_len[:, None]
    out = jnp.where(keep, jnp.take_along_axis(
        mat, jnp.clip(src, 0, w - 1), axis=1), jnp.uint8(0))
    return Column(STRING, out_len.astype(jnp.int32), col.validity, chars=out)


def _host_case(col: Column, to_upper: bool) -> Column:
    """Full Unicode case mapping on host (Python's str.upper/lower applies
    the same Unicode full case mapping Java uses under Locale.ROOT, incl.
    one-to-many expansions like ß -> SS). The price is a device->host
    round trip — only taken when the column actually holds non-ASCII."""
    vals = col.to_pylist()
    out = [None if v is None else (v.upper() if to_upper else v.lower())
           for v in vals]
    return pad_strings(Column.from_pylist(out, STRING))


def _ascii_case(col: Column, to_upper: bool) -> Column:
    p = pad_strings(col)
    mat = p.chars
    if bool(jnp.any(mat >= 0x80)):
        # non-ASCII: the Unicode device engine (per-position classify +
        # case-LUT gather + in-place re-encode) handles every row whose
        # characters have 1:1 length-preserving mappings; only rows with
        # SPECIAL characters (ß→SS expansions, length-changing maps,
        # astral chars, invalid UTF-8) take the host engine
        from spark_rapids_jni_tpu.ops.unicode_case_device import (
            case_map_device,
        )

        out, row_special = case_map_device(mat, to_upper)
        spec_np = np.asarray(row_special)
        if col.validity is not None:
            # null rows' bytes are don't-care: never decode them
            spec_np = spec_np & np.asarray(col.validity)
        spec_idx = np.flatnonzero(spec_np)
        if spec_idx.size == 0:
            return Column(STRING, p.data, col.validity, chars=out)
        # per-row merge: only the SPECIAL rows (expansions, length-
        # changing maps, final sigma, invalid sequences) cross to the
        # host — the device mapping for every other row is kept
        lens_np = np.asarray(p.data)
        spec_rows = np.asarray(mat[jnp.asarray(spec_idx)])
        mapped_vals = []
        for row_i, i in enumerate(spec_idx):
            raw = spec_rows[row_i, : lens_np[i]].tobytes().decode()
            mapped_vals.append(raw.upper() if to_upper else raw.lower())
        mapped_bytes = [v.encode() for v in mapped_vals]
        w_out = max(int(mat.shape[1]),
                    max(len(b) for b in mapped_bytes))
        if w_out > mat.shape[1]:
            out = jnp.concatenate(
                [out, jnp.zeros((out.shape[0], w_out - mat.shape[1]),
                                jnp.uint8)], axis=1)
        host_mat = np.zeros((spec_idx.size, w_out), np.uint8)
        host_lens = np.zeros(spec_idx.size, np.int32)
        for row_i, b in enumerate(mapped_bytes):
            host_mat[row_i, : len(b)] = np.frombuffer(b, np.uint8)
            host_lens[row_i] = len(b)
        idx = jnp.asarray(spec_idx.astype(np.int32))
        out = out.at[idx].set(jnp.asarray(host_mat))
        lengths = p.data.at[idx].set(jnp.asarray(host_lens))
        return Column(STRING, lengths, col.validity, chars=out)
    if to_upper:
        out = jnp.where((mat >= ord("a")) & (mat <= ord("z")), mat - 32, mat)
    else:
        out = jnp.where((mat >= ord("A")) & (mat <= ord("Z")), mat + 32, mat)
    return Column(STRING, p.data, col.validity, chars=out)


@func_range("string_upper")
def upper(col: Column) -> Column:
    """Spark upper: ASCII and 1:1 length-preserving Unicode mappings ride
    the device path; rows with special characters fall back to the host
    Unicode engine."""
    return _ascii_case(col, True)


@func_range("string_lower")
def lower(col: Column) -> Column:
    """Spark lower: ASCII and 1:1 length-preserving Unicode mappings ride
    the device path; rows with special characters fall back to the host
    Unicode engine."""
    return _ascii_case(col, False)


# ---- regexp (host engine) --------------------------------------------------
#
# Spark's regexp functions compile java.util.regex patterns per-row on the
# GPU in cuDF; a device regex VM is out of scope here, so these run the
# HOST engine (Python `re`) — the documented two-engine posture
# (get_json_object precedent): correct results, device->host round trip.
# Java-compat measures: patterns compile with re.ASCII so \d/\w/\s/\b are
# the ASCII classes java.util.regex defaults to; possessive quantifiers
# (a*+) work natively on Python 3.11+; \p{...} classes are rejected by
# compile (fail loudly, never silently different).


def _java_replacement_to_python(rep: str, n_groups: int) -> str:
    """Java Matcher.appendReplacement syntax -> Python sub template.
    ``\\x`` in Java means LITERAL x (so ``\\n`` is the letter n, not a
    newline); ``$digits`` binds greedily to the longest prefix that is a
    valid group number <= ``n_groups`` (Java's rule — '$10' with two
    groups is group 1 then literal '0')."""
    out = []
    i = 0
    while i < len(rep):
        c = rep[i]
        if c == "\\":
            if i + 1 >= len(rep):
                raise ValueError(
                    "invalid regexp replacement: trailing backslash")
            nxt = rep[i + 1]
            out.append("\\\\" if nxt == "\\" else nxt)
            i += 2
            continue
        if c == "$":
            j = i + 1
            if j >= len(rep) or not rep[j].isdigit():
                raise ValueError(
                    f"invalid regexp replacement {rep!r}: '$' must be "
                    f"followed by a group number (escape literal '$' "
                    f"with a backslash)")
            # greedy: extend while the accumulated number stays a valid
            # group reference
            g = int(rep[j])
            j += 1
            while j < len(rep) and rep[j].isdigit()                     and g * 10 + int(rep[j]) <= n_groups:
                g = g * 10 + int(rep[j])
                j += 1
            if g > n_groups:
                raise ValueError(
                    f"invalid regexp replacement {rep!r}: group {g} "
                    f"exceeds the pattern's {n_groups} group(s)")
            out.append(f"\\g<{g}>")
            i = j
            continue
        out.append(c)  # backslashes were consumed by the branch above
        i += 1
    return "".join(out)


def _compile_java_regex(pattern: str):
    """Compile with re.ASCII so \\d/\\w/\\s/\\b mean what java.util.regex
    means by default ([0-9] etc.) — Python's Unicode-aware classes would
    silently match differently than Spark. Java-only character-class
    syntax Python would silently mis-parse (``[a-z&&[b]]`` intersection,
    nested classes) is rejected up front."""
    import re as _re

    # scan for class intersection / nesting inside [...] — Python re
    # compiles both without error but with different semantics
    depth = 0
    i = 0
    while i < len(pattern):
        c = pattern[i]
        if c == "\\":
            i += 2
            continue
        if c == "[":
            if depth > 0:
                raise ValueError(
                    f"unsupported java.util.regex syntax in {pattern!r}: "
                    f"nested character class (Python re would silently "
                    f"parse it differently)")
            depth = 1
        elif c == "]" and depth:
            depth = 0
        elif depth and pattern.startswith("&&", i):
            raise ValueError(
                f"unsupported java.util.regex syntax in {pattern!r}: "
                f"character-class intersection '&&' (Python re would "
                f"silently parse it differently)")
        i += 1
    return _re.compile(pattern, _re.ASCII)


def _host_regexp(col: Column, rx, fn):
    vals = col.to_pylist()
    return [None if v is None else fn(rx, v) for v in vals]


@func_range("regexp_contains", record=True)
def regexp_contains(col: Column, pattern: str) -> Column:
    """RLIKE / regexp-find (cuDF contains_re): True when the pattern
    matches anywhere in the string.

    Two engines (the get_json_object posture): patterns inside the
    DFA-compilable subset run ON DEVICE — a host-compiled byte DFA
    executed as one int32 gather per char column over the padded layout
    (``ops/regex_device.py``); everything else (backrefs, lookaround,
    class intersection, …) falls back to the host java.util.regex
    emulation. Rows with embedded NUL bytes would alias the device
    engine's end-of-row sentinel, so such columns are detected with one
    device reduction and routed to the host engine whole.

    Config ``regex.force_engine`` pins "device" (raises on unsupported
    patterns) or "host" for testing."""
    from spark_rapids_jni_tpu.types import BOOL8
    from spark_rapids_jni_tpu.utils.config import get_option

    validity = col.valid_mask() if col.validity is not None else None
    force = get_option("regex.force_engine")
    if force == "host":
        telemetry.record_fallback(
            "regexp_contains", "regex.force_engine=host pin", rows=col.size)
    else:
        from spark_rapids_jni_tpu.ops import regex_device as rd

        try:
            comp = rd.compile_pattern(pattern)
        except rd.RegexUnsupported as exc:
            if force == "device":
                raise
            telemetry.record_fallback(
                "regexp_contains", f"unsupported regex atom: {exc}",
                rows=col.size)
            comp = None
        if comp is not None:
            pc = pad_strings(col)
            # eligibility: zero count per row must equal the pad tail,
            # i.e. no NUL inside the content bytes
            w = pc.chars.shape[1]
            nzeros = jnp.sum((pc.chars == 0).astype(jnp.int32), axis=1)
            clean = bool(jnp.all(nzeros == (w - pc.data)))
            if clean:
                # the NUL check already synced lengths; reuse them to
                # skip run_dfa's defensive extra zero column when the
                # widest row leaves padding slack
                n_rows = pc.chars.shape[0]
                needs_pad = bool(
                    n_rows and int(jnp.max(pc.data)) >= w)
                flags = rd.run_dfa(
                    pc.chars, comp,
                    ensure_sentinel=needs_pad).astype(jnp.uint8)
                return Column(BOOL8, flags, validity)
            if force == "device":
                raise ValueError(
                    "regex.force_engine=device but the column has "
                    "embedded NUL bytes (sentinel alias)")
            telemetry.record_fallback(
                "regexp_contains",
                "embedded NUL bytes alias the 0x00 padding sentinel",
                rows=col.size)
    rx = _compile_java_regex(pattern)
    out = _host_regexp(col, rx, lambda r, v: r.search(v) is not None)
    flags = jnp.asarray([bool(v) for v in out], jnp.uint8)
    return Column(BOOL8, flags, validity)


def _device_capture_eligible(col: Column, pattern: str, op: str):
    """Shared extract/replace device-path gate: the pattern parses into
    the linear capture subset AND the column is all-ASCII with no
    embedded NULs (byte-level ``.``/negated classes equal char-level
    exactly on ASCII data; NULs alias the padding sentinel). Returns
    (compiled, padded_col) or (None, None) for host fallback; respects
    ``regex.force_engine`` like regexp_contains. Every (None, None)
    return records a telemetry fallback under ``op`` (the dispatcher
    the gate is deciding for)."""
    from spark_rapids_jni_tpu.utils.config import get_option

    force = get_option("regex.force_engine")
    if force == "host":
        telemetry.record_fallback(
            op, "regex.force_engine=host pin", rows=col.size)
        return None, None
    from spark_rapids_jni_tpu.ops import regex_capture_device as rc

    try:
        comp = rc.compile_linear(pattern)
    except rc.RegexUnsupported as exc:
        if force == "device":
            raise
        telemetry.record_fallback(
            op, f"unsupported linear-capture atom: {exc}", rows=col.size)
        return None, None
    pc = pad_strings(col)
    n, w = pc.chars.shape
    if n == 0:
        telemetry.record_fallback(
            op, "empty column: no rows to run on device", rows=0)
        return None, None
    nzeros = jnp.sum((pc.chars == 0).astype(jnp.int32), axis=1)
    clean = bool(jnp.all(nzeros == (w - pc.data))
                 & jnp.all(pc.chars < 0x80))
    if not clean:
        if force == "device":
            raise ValueError(
                "regex.force_engine=device but the column has embedded "
                "NULs or non-ASCII bytes (outside the capture engine's "
                "correctness scope)")
        telemetry.record_fallback(
            op,
            "embedded NULs or non-ASCII bytes (sentinel alias / outside "
            "the byte-level capture engine's correctness scope)",
            rows=col.size)
        return None, None
    # the boundary walk reads positions up to W inclusive: guarantee a
    # sentinel column (same rule as run_dfa's ensure_sentinel)
    if int(jnp.max(pc.data)) >= w:
        pc = Column(pc.dtype, pc.data, pc.validity, chars=jnp.concatenate(
            [pc.chars, jnp.zeros((n, 1), jnp.uint8)], axis=1))
    return comp, pc


@func_range("regexp_extract", record=True)
def regexp_extract(col: Column, pattern: str, group: int = 1) -> Column:
    """Spark regexp_extract: the group'th capture of the first match,
    '' when the pattern does not match (Spark returns empty string, not
    null).

    Two engines: LINEAR patterns (concatenated literals/classes with
    flat capture groups) over ASCII data run ON DEVICE via the
    reverse-feasibility capture engine (ops/regex_capture_device.py) —
    scatter-free, O(elements * n * W); everything else takes the host
    java.util.regex emulation."""
    rx = _compile_java_regex(pattern)
    if not 0 <= group <= rx.groups:
        # validate up front like regexp_replace — otherwise an invalid
        # index only crashes on rows that happen to match (Spark raises)
        raise ValueError(
            f"regexp_extract group {group} out of range: pattern has "
            f"{rx.groups} group(s)")
    comp, pc = _device_capture_eligible(col, pattern, "regexp_extract")
    if comp is not None:
        from spark_rapids_jni_tpu.ops import regex_capture_device as rc

        lengths, chars = rc.extract_device(pc.chars, comp, group,
                                           dispatch_key=pattern)
        return Column(STRING, lengths, pc.validity, chars=chars)

    def ext(r, v):
        m = r.search(v)
        if m is None:
            return ""
        g = m.group(group)
        return "" if g is None else g

    out = _host_regexp(col, rx, ext)
    return pad_strings(Column.from_pylist(out, STRING))


@func_range("regexp_replace", record=True)
def regexp_replace(col: Column, pattern: str, replacement: str) -> Column:
    """Spark regexp_replace: every match replaced; Java $N group refs
    (greedy multi-digit) and \\x literal escapes supported.

    Literal replacements of LINEAR patterns over ASCII data run ON
    DEVICE (bounded match rounds; rows with more matches than the
    round budget re-route the whole column to the host engine via the
    overflow flag — the narrowing_overflow posture). Group-ref
    replacements and non-linear patterns take the host engine."""
    rx = _compile_java_regex(pattern)
    rep = _java_replacement_to_python(replacement, rx.groups)
    literal_rep = "$" not in replacement and "\\" not in replacement
    if literal_rep:
        comp, pc = _device_capture_eligible(col, pattern, "regexp_replace")
        if comp is not None and all(
                el.lo == 0 for el in comp.pattern.elements):
            # a pattern that can match empty matches at EVERY position:
            # any row longer than the round budget is guaranteed to
            # overflow, so the device pass would be dead work
            telemetry.record_fallback(
                "regexp_replace",
                "empty-matching pattern: every position matches, device "
                "round budget would always overflow", rows=col.size)
            comp = None
        if comp is not None:
            from spark_rapids_jni_tpu.ops import regex_capture_device as rc

            out_len, out_chars, overflowed = rc.replace_device(
                pc.chars, pc.data, comp, replacement.encode(),
                dispatch_key=pattern)
            if not bool(overflowed):
                return Column(STRING, out_len, pc.validity,
                              chars=out_chars)
            # else: some row had more matches than the round budget —
            # fall through to the host engine for the whole column
            telemetry.record_fallback(
                "regexp_replace",
                "match-round budget overflow: a row exceeded the device "
                "replace rounds; rerouting whole column to host",
                rows=col.size)
    else:
        telemetry.record_fallback(
            "regexp_replace",
            "group-ref/escape replacement: device engine handles literal "
            "replacements only", rows=col.size)
    out = _host_regexp(col, rx, lambda r, v: r.sub(rep, v))
    return pad_strings(Column.from_pylist(out, STRING))
