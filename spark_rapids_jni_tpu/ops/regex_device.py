"""Device regex engine: host-compiled byte DFA, executed as one table
gather per character column.

cuDF ships a GPU regex engine (contains_re/matches_re are north-star ops
in the vendored capability surface, SURVEY.md section 2.2); this is its
TPU-first equivalent for the *containment* predicates (RLIKE /
regexp_contains). Design:

- the pattern compiles ON THE HOST to a DFA over the byte alphabet
  (Thompson NFA -> subset construction, state cap -> fallback);
- the device run is ``W`` steps of ``state = table[state, byte]`` over
  the padded (n, W) char matrix — a single int32 gather per column,
  fully vectorized across rows, zero scatters, O(n*W) like every other
  padded-string op in this package;
- matching is encoded in the LANGUAGE, not in control flow: the DFA
  recognizes ``.* P .*? SENTINEL any*`` (unanchored), where SENTINEL is
  the 0x00 padding byte that terminates every row, so ``$`` anchoring
  falls out naturally and the final state after all W steps is the
  verdict. Rows are guaranteed a sentinel by padding one extra zero
  column when the widest row fills the matrix.

UTF-8 is handled by desugaring at compile time: ``.`` and negated
classes expand to byte-level alternations (ASCII branch | 2/3/4-byte
lead+continuation branches), so multi-byte characters count as ONE
character — byte-DFA semantics match character semantics. Unanchored
search never starts inside a multi-byte character because no pattern
atom matches a lone continuation byte.

Supported syntax (the Spark/Java core): literals, ``.``, ``[...]``
classes with ranges/negation/escapes, ``\\d \\D \\w \\W \\s \\S``,
``* + ? {m} {m,} {m,n}``, ``|``, ``(...)``/``(?:...)``, ``^`` at the
pattern start, ``$`` at the end. Everything else (backrefs, lookaround,
inline flags, \\b, mid-pattern anchors) raises ``RegexUnsupported`` and
the dispatcher in ``ops.strings`` falls back to the host engine — the
same two-engine posture as get_json_object.
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from spark_rapids_jni_tpu.utils.tracing import func_range

# compile-time guards
MAX_DFA_STATES = 1024
MAX_EXPANSION = 256  # {m,n} repetition budget


class RegexUnsupported(ValueError):
    """Pattern uses syntax outside the device subset (host fallback)."""


_SENTINEL = 0  # the padded layout's zero byte, doubles as end-of-string

# deliberately contains 0x00: only ever used as `_ANY_BYTE - {_SENTINEL}`
# or on the post-sentinel state  # tpulint: disable=padding-byte-invariant
_ANY_BYTE = frozenset(range(256))
_ASCII_NO_NL = frozenset(range(1, 128)) - {0x0A}
_LEAD2 = frozenset(range(0xC2, 0xE0))
_LEAD3 = frozenset(range(0xE0, 0xF0))
_LEAD4 = frozenset(range(0xF0, 0xF5))
_CONT = frozenset(range(0x80, 0xC0))

_D = frozenset(range(0x30, 0x3A))
_W = (frozenset(range(0x30, 0x3A)) | frozenset(range(0x41, 0x5B))
      | frozenset(range(0x61, 0x7B)) | {0x5F})
_S = frozenset(b" \t\n\x0b\f\r")


# ---------------------------------------------------------------------------
# NFA (Thompson construction over byte classes)
# ---------------------------------------------------------------------------


class _Nfa:
    """States are ints; transitions either (byteset, target) consuming
    edges or epsilon edges."""

    def __init__(self):
        self.edges: list[list] = []      # state -> [(byteset|None, target)]

    def new_state(self) -> int:
        self.edges.append([])
        return len(self.edges) - 1

    def add(self, s: int, byteset, t: int) -> None:
        self.edges[s].append((byteset, t))


class _Frag(NamedTuple):
    start: int
    end: int  # single dangling accept per fragment (epsilon-joined)


def _char_frag(nfa: _Nfa, byteset: frozenset) -> _Frag:
    if _SENTINEL in byteset:
        # a pattern atom matching 0x00 would alias the end-of-row
        # sentinel and match across row boundaries
        raise RegexUnsupported("NUL byte in pattern")
    s, e = nfa.new_state(), nfa.new_state()
    nfa.add(s, byteset, e)
    return _Frag(s, e)


def _multibyte_char_frag(nfa: _Nfa) -> _Frag:
    """One full non-ASCII UTF-8 character (2-4 bytes)."""
    s, e = nfa.new_state(), nfa.new_state()
    # 2-byte
    m = nfa.new_state()
    nfa.add(s, _LEAD2, m)
    nfa.add(m, _CONT, e)
    # 3-byte
    m1, m2 = nfa.new_state(), nfa.new_state()
    nfa.add(s, _LEAD3, m1)
    nfa.add(m1, _CONT, m2)
    nfa.add(m2, _CONT, e)
    # 4-byte
    k1, k2, k3 = nfa.new_state(), nfa.new_state(), nfa.new_state()
    nfa.add(s, _LEAD4, k1)
    nfa.add(k1, _CONT, k2)
    nfa.add(k2, _CONT, k3)
    nfa.add(k3, _CONT, e)
    return _Frag(s, e)


def _any_char_frag(nfa: _Nfa) -> _Frag:
    """``.``: any character but newline (Java default)."""
    f = _multibyte_char_frag(nfa)
    nfa.add(f.start, _ASCII_NO_NL, f.end)
    return f


def _ascii_class_frag(nfa: _Nfa, byteset: frozenset,
                      negated: bool) -> _Frag:
    """A [...] class. Negated classes also match any multi-byte char
    (Java semantics: [^a] matches 'é')."""
    if not negated:
        return _char_frag(nfa, byteset)
    pos = frozenset(range(1, 128)) - byteset
    f = _multibyte_char_frag(nfa)
    if pos:
        nfa.add(f.start, pos, f.end)
    return f


# ---------------------------------------------------------------------------
# Parser (recursive descent over the supported subset)
# ---------------------------------------------------------------------------


class _Parser:
    def __init__(self, pattern: str, nfa: _Nfa):
        self.p = pattern
        self.i = 0
        self.nfa = nfa
        self.anchored_start = False
        self.anchored_end = False

    def peek(self):
        return self.p[self.i] if self.i < len(self.p) else None

    def take(self):
        c = self.peek()
        if c is None:
            raise RegexUnsupported("unexpected end of pattern")
        self.i += 1
        return c

    # -- grammar -----------------------------------------------------------
    def parse(self) -> _Frag:
        if self.peek() == "^":
            self.i += 1
            self.anchored_start = True
        frag = self.alt(top=True)
        if self.i < len(self.p):
            raise RegexUnsupported(
                f"unsupported syntax at offset {self.i}: {self.p[self.i:]!r}")
        return frag

    def alt(self, top: bool = False) -> _Frag:
        frags = [self.concat(top)]
        while self.peek() == "|":
            self.i += 1
            frags.append(self.concat(top))
        if top and len(frags) > 1 and (self.anchored_end
                                       or self.anchored_start):
            # `a|b$` / `^a|b` anchor only one branch in Java — the
            # global-anchor compile model can't express that; the host
            # engine handles them
            raise RegexUnsupported("anchor on one alternation branch")
        if len(frags) == 1:
            return frags[0]
        s, e = self.nfa.new_state(), self.nfa.new_state()
        for f in frags:
            self.nfa.add(s, None, f.start)
            self.nfa.add(f.end, None, e)
        return _Frag(s, e)

    def concat(self, top: bool = False) -> _Frag:
        frags: list[_Frag] = []
        while True:
            c = self.peek()
            if c is None or c in "|)":
                break
            if c == "$":
                # only valid as the very last pattern char (top level)
                if top and self.i == len(self.p) - 1:
                    self.i += 1
                    self.anchored_end = True
                    break
                raise RegexUnsupported("mid-pattern '$'")
            if c == "^":
                raise RegexUnsupported("mid-pattern '^'")
            frags.append(self.repeat())
        if not frags:
            s = self.nfa.new_state()
            return _Frag(s, s)
        for a, b in zip(frags, frags[1:]):
            self.nfa.add(a.end, None, b.start)
        return _Frag(frags[0].start, frags[-1].end)

    def repeat(self) -> _Frag:
        atom_start = self.i
        frag = self.atom()
        while True:
            c = self.peek()
            if c == "*":
                self.i += 1
                frag = self._star(frag)
            elif c == "+":
                self.i += 1
                # X+ = X X*  (rebuild X rather than aliasing the frag)
                again = self._reparse(atom_start)
                star = self._star(again)
                self.nfa.add(frag.end, None, star.start)
                frag = _Frag(frag.start, star.end)
            elif c == "?":
                self.i += 1
                self.nfa.add(frag.start, None, frag.end)
            elif c == "{":
                frag = self._bounded(frag, atom_start)
            else:
                return frag
            # reluctant/possessive quantifiers (X*?, X{2}?, X++) and
            # stacked repetitions (X{2}{3}, X**) — Java rejects most and
            # the naive re-application parse would change the language
            # for the rest (e.g. (X{2})? matches empty). Reject them all.
            if self.peek() in ("?", "+", "*", "{"):
                raise RegexUnsupported("stacked/reluctant quantifier")

    def _star(self, frag: _Frag) -> _Frag:
        s, e = self.nfa.new_state(), self.nfa.new_state()
        self.nfa.add(s, None, frag.start)
        self.nfa.add(s, None, e)
        self.nfa.add(frag.end, None, frag.start)
        self.nfa.add(frag.end, None, e)
        return _Frag(s, e)

    def _reparse(self, start: int) -> _Frag:
        """Re-run the parser over one atom's source to get a fresh copy
        (Thompson fragments are single-use)."""
        save = self.i
        self.i = start
        frag = self.atom()
        # the atom ends exactly where it ended the first time
        assert self.i <= save
        self.i = save
        return frag

    def _bounded(self, frag: _Frag, atom_start: int) -> _Frag:
        """{m} {m,} {m,n} by expansion (X{2,4} = XX X? X?)."""
        j = self.p.index("}", self.i) if "}" in self.p[self.i:] else -1
        if j < 0:
            raise RegexUnsupported("unterminated {")
        body = self.p[self.i + 1: j]
        self.i = j + 1
        # strict ASCII-digit grammar: {m} {m,} {m,n} and nothing else —
        # int()'s permissive parsing (whitespace, signs, fullwidth
        # digits, extra fields) would silently compile a language the
        # host engine treats as literal text
        import re as _re

        if not _re.fullmatch(r"[0-9]+(,[0-9]*)?", body):
            raise RegexUnsupported(f"bad repetition {{{body}}}")
        parts = body.split(",")
        lo = int(parts[0])
        hi = (lo if len(parts) == 1
              else (int(parts[1]) if parts[1] else None))
        if hi is not None and (hi < lo or lo < 0):
            raise RegexUnsupported(f"bad repetition {{{body}}}")
        if (hi or lo) > MAX_EXPANSION:
            raise RegexUnsupported("repetition too large for expansion")
        pieces: list[_Frag] = []
        for k in range(max(lo, 1) if lo else 0):
            pieces.append(self._reparse(atom_start) if (pieces or k)
                          else frag)
        if lo == 0 and hi is None:
            return self._star(frag)
        if hi is None:  # {m,}: last copy loops
            star = self._star(self._reparse(atom_start))
            pieces.append(star)
        else:
            for _ in range(hi - lo):
                opt = self._reparse(atom_start)
                self.nfa.add(opt.start, None, opt.end)  # optional
                pieces.append(opt)
            if lo == 0 and not pieces:
                s = self.nfa.new_state()
                return _Frag(s, s)
            if lo == 0:
                # all copies optional already
                pass
        for a, b in zip(pieces, pieces[1:]):
            self.nfa.add(a.end, None, b.start)
        return _Frag(pieces[0].start, pieces[-1].end)

    def atom(self) -> _Frag:
        c = self.take()
        if c == "(":
            if self.peek() == "?":
                self.i += 1
                nxt = self.take()
                if nxt != ":":
                    raise RegexUnsupported(f"(?{nxt} groups")
            frag = self.alt()
            if self.take() != ")":
                raise RegexUnsupported("unbalanced parenthesis")
            return frag
        if c == "[":
            return self._char_class()
        if c == ".":
            return _any_char_frag(self.nfa)
        if c == "\\":
            return self._escape()
        if c in "*+?{":
            raise RegexUnsupported(f"dangling quantifier {c!r}")
        b = c.encode()
        if len(b) == 1:
            return _char_frag(self.nfa, frozenset(b))
        # multi-byte literal: exact byte sequence
        frags = [_char_frag(self.nfa, frozenset([x])) for x in b]
        for a, bb in zip(frags, frags[1:]):
            self.nfa.add(a.end, None, bb.start)
        return _Frag(frags[0].start, frags[-1].end)

    def _escape(self) -> _Frag:
        c = self.take()
        if c in ("d", "w", "s"):
            return _char_frag(self.nfa, {"d": _D, "w": _W, "s": _S}[c])
        if c in ("D", "W", "S"):
            pos = {"D": _D, "W": _W, "S": _S}[c]
            return _ascii_class_frag(self.nfa, pos, negated=True)
        if c in "\\.[]()^$*+?{}|/":
            return _char_frag(self.nfa, frozenset(c.encode()))
        if c == "n":
            return _char_frag(self.nfa, frozenset(b"\n"))
        if c == "t":
            return _char_frag(self.nfa, frozenset(b"\t"))
        if c == "r":
            return _char_frag(self.nfa, frozenset(b"\r"))
        raise RegexUnsupported(f"escape \\{c}")

    def _class_escape(self) -> frozenset:
        c = self.take()
        if c == "d":
            return _D
        if c == "w":
            return _W
        if c == "s":
            return _S
        if c in "\\.[]()^$*+?{}|/-":
            return frozenset(c.encode())
        if c == "n":
            return frozenset(b"\n")
        if c == "t":
            return frozenset(b"\t")
        if c == "r":
            return frozenset(b"\r")
        raise RegexUnsupported(f"class escape \\{c}")

    def _char_class(self) -> _Frag:
        negated = False
        if self.peek() == "^":
            self.i += 1
            negated = True
        byteset: set = set()
        first = True
        while True:
            c = self.peek()
            if c is None:
                raise RegexUnsupported("unterminated character class")
            if c == "]" and not first:
                self.i += 1
                break
            first = False
            if c == "\\":
                self.i += 1
                byteset |= self._class_escape()
                continue
            if c == "[":
                # Java nested class — Python-style literal '[' would
                # silently change the language
                raise RegexUnsupported("nested character class")
            if c == "&" and self.i + 1 < len(self.p) \
                    and self.p[self.i + 1] == "&":
                raise RegexUnsupported("character class intersection")
            self.i += 1
            b = c.encode()
            if len(b) > 1:
                raise RegexUnsupported(
                    "non-ASCII character class member")
            lo = b[0]
            if self.peek() == "-" and self.i + 1 < len(self.p) \
                    and self.p[self.i + 1] != "]":
                self.i += 1
                hi_c = self.take()
                hb = hi_c.encode()
                if hb == b"\\" or len(hb) > 1:
                    raise RegexUnsupported("complex class range")
                if hb[0] < lo:
                    raise RegexUnsupported("inverted class range")
                byteset |= set(range(lo, hb[0] + 1))
            else:
                byteset.add(lo)
        return _ascii_class_frag(self.nfa, frozenset(byteset), negated)


# ---------------------------------------------------------------------------
# DFA (subset construction) + device table
# ---------------------------------------------------------------------------


class CompiledRegex(NamedTuple):
    table: np.ndarray    # int32[num_states * 256] flattened transitions
    accept: np.ndarray   # bool[num_states]
    num_states: int


def _closure(nfa: _Nfa, states: frozenset) -> frozenset:
    seen = set(states)
    stack = list(states)
    while stack:
        s = stack.pop()
        for byteset, t in nfa.edges[s]:
            if byteset is None and t not in seen:
                seen.add(t)
                stack.append(t)
    return frozenset(seen)


import functools


def compile_pattern(pattern: str) -> CompiledRegex:
    """Host compile: pattern -> byte DFA recognizing
    ``search(P) and end-of-row`` over zero-terminated padded rows.
    LRU-cached per pattern (immutable result) — repeated per-batch
    calls skip the subset construction. Cache hits/misses are recorded
    as telemetry compile_cache events (unsupported patterns raise out
    of the cache and always re-parse — accurately counted as misses)."""
    from spark_rapids_jni_tpu import telemetry

    if telemetry.enabled():
        before = _compile_pattern_cached.cache_info().hits
        out = _compile_pattern_cached(pattern)
        hit = _compile_pattern_cached.cache_info().hits > before
        telemetry.record_compile_cache("regex_dfa", hit=hit)
        return out
    return _compile_pattern_cached(pattern)


@functools.lru_cache(maxsize=256)
def _compile_pattern_cached(pattern: str) -> CompiledRegex:
    nfa = _Nfa()
    parser = _Parser(pattern, nfa)
    frag = parser.parse()

    start = nfa.new_state()
    if not parser.anchored_start:
        # unanchored search: any-byte self-loop before the pattern
        nfa.add(start, _ANY_BYTE - {_SENTINEL}, start)
    nfa.add(start, None, frag.start)

    # after the pattern body: consume the rest (unless $-anchored), then
    # the 0x00 sentinel, then anything (the remaining padding)
    tail = nfa.new_state()
    nfa.add(frag.end, None, tail)
    if not parser.anchored_end:
        nfa.add(tail, _ANY_BYTE - {_SENTINEL}, tail)
    final = nfa.new_state()
    nfa.add(tail, frozenset([_SENTINEL]), final)
    if parser.anchored_end:
        # Java/Python '$' also matches just before a single trailing
        # line terminator: allow one optional '\n' before the sentinel
        nl = nfa.new_state()
        nfa.add(tail, frozenset(b"\n"), nl)
        nfa.add(nl, frozenset([_SENTINEL]), final)
    nfa.add(final, _ANY_BYTE, final)

    # subset construction
    d0 = _closure(nfa, frozenset([start]))
    ids = {d0: 0}
    order = [d0]
    trans: list[np.ndarray] = []
    qi = 0
    while qi < len(order):
        cur = order[qi]
        qi += 1
        # bytes with no live NFA move go to the DEAD state (id assigned
        # after construction) — defaulting to 0 would silently restart
        # an anchored search
        row = np.full(256, -1, dtype=np.int32)
        # per-byte move: union of consuming edges
        move: dict[int, set] = {}
        for s in cur:
            for byteset, t in nfa.edges[s]:
                if byteset is None:
                    continue
                for b in byteset:
                    move.setdefault(b, set()).add(t)
        cache: dict[frozenset, int] = {}
        for b, tgts in move.items():
            key = frozenset(tgts)
            if key in cache:
                row[b] = cache[key]
                continue
            nxt = _closure(nfa, key)
            if nxt not in ids:
                if len(ids) >= MAX_DFA_STATES:
                    raise RegexUnsupported(
                        f"DFA exceeds {MAX_DFA_STATES} states")
                ids[nxt] = len(ids)
                order.append(nxt)
            row[b] = ids[nxt]
            cache[key] = ids[nxt]
        trans.append(row)
    dead = len(order)
    table = np.concatenate(trans).astype(np.int32)
    table[table < 0] = dead
    table = np.concatenate(
        [table, np.full(256, dead, dtype=np.int32)])
    # host-side DFA compile path, not device execution
    # tpulint: disable=no-host-transfer-in-device-path
    accept = np.array([final in st for st in order] + [False], dtype=bool)
    return CompiledRegex(table, accept, dead + 1)


# ---------------------------------------------------------------------------
# Device execution
# ---------------------------------------------------------------------------


def _run_dfa_impl(row_args, aux, rvs, *, ensure_sentinel: bool):
    ((chars,),) = row_args
    table, accept = aux
    n, w = chars.shape
    if ensure_sentinel:
        chars = jnp.concatenate(
            [chars, jnp.zeros((n, 1), jnp.uint8)], axis=1)

    def step(state, col):
        return table[state * 256 + col.astype(jnp.int32)], None

    init = jnp.zeros((n,), jnp.int32)
    final_state, _ = jax.lax.scan(step, init, chars.T)
    return accept[final_state]


@func_range("regex_device_match")
def run_dfa(chars: jnp.ndarray, compiled: CompiledRegex,
            ensure_sentinel: bool = True) -> jnp.ndarray:
    """bool[n]: DFA verdict per row of the padded (n, W) char matrix.
    One int32 gather per column via ``lax.scan`` (sequential in W,
    vectorized across rows — the LIKE engine's cost model).

    Every row must end in a 0x00 sentinel; callers that KNOW the widest
    row leaves padding slack (max length < W) pass
    ``ensure_sentinel=False`` to skip the defensive extra zero column
    (an O(n*W) copy otherwise).

    The DFA table/accept arrays are dispatch *aux* inputs — traced, not
    baked — so every pattern with the same state count and row width
    shares one bucketed executable (padded tail rows are all-zero and
    sliced off)."""
    from spark_rapids_jni_tpu.runtime import dispatch

    return dispatch.rowwise(
        "regex_run_dfa",
        partial(_run_dfa_impl, ensure_sentinel=ensure_sentinel),
        (chars,),
        (jnp.asarray(compiled.table), jnp.asarray(compiled.accept)),
        statics=(ensure_sentinel,))
