"""get_json_object over string columns — the Spark SQL JSONPath extractor
(north-star JNI kernel; BASELINE.json lists it explicitly).

The extraction engine is native C++ (src/native/src/get_json_object.cpp):
JSON navigation is a branchy byte-level state machine over variable-length
strings, which is host work in this design round — the column round-trips
host<->HBM around the call. Path grammar: ``$``, ``.field``, ``['field']``,
``[index]``; wildcards raise ValueError (Spark's analyzer behavior for
paths it cannot compile). String matches come back unquoted with escapes
decoded; object/array/number/bool matches come back as raw JSON text; JSON
null and missing paths are SQL NULL.
"""

from __future__ import annotations

import ctypes

import jax.numpy as jnp
import numpy as np

from spark_rapids_jni_tpu import types as t
from spark_rapids_jni_tpu.columnar import Column
from spark_rapids_jni_tpu.parquet.footer import NativeError
from spark_rapids_jni_tpu.runtime.native import load_native
from spark_rapids_jni_tpu.utils.tracing import func_range


@func_range("get_json_object")
def get_json_object(col: Column, path: str) -> Column:
    """Extract ``path`` from every JSON document in a STRING column."""
    if not col.dtype.is_string:
        raise TypeError("get_json_object requires a STRING column")
    lib = load_native()
    n = col.size
    offsets = np.ascontiguousarray(np.asarray(col.data), dtype=np.int32)
    chars = np.ascontiguousarray(np.asarray(col.chars), dtype=np.uint8)
    if chars.size == 0:
        chars = np.zeros(1, dtype=np.uint8)
    valid_in = None
    if col.validity is not None:
        valid_in = np.ascontiguousarray(
            np.asarray(col.validity), dtype=np.uint8
        )

    out_chars = ctypes.POINTER(ctypes.c_uint8)()
    out_len = ctypes.c_int64()
    out_offsets = np.empty(n + 1, dtype=np.int32)
    out_valid = np.empty(n, dtype=np.uint8)
    rc = lib.tpudf_get_json_object(
        chars.ctypes.data_as(ctypes.c_void_p),
        offsets.ctypes.data_as(ctypes.c_void_p),
        None if valid_in is None
        else valid_in.ctypes.data_as(ctypes.c_void_p),
        n,
        path.encode(),
        ctypes.byref(out_chars),
        ctypes.byref(out_len),
        out_offsets.ctypes.data_as(ctypes.c_void_p),
        out_valid.ctypes.data_as(ctypes.c_void_p),
    )
    if rc != 0:
        msg = lib.last_error()
        # PathError messages carry a fixed "JSONPath: " prefix (caller bug
        # -> ValueError); anything else is an engine failure.
        if msg.startswith("JSONPath:"):
            raise ValueError(msg)
        raise NativeError(msg)
    try:
        nbytes = out_len.value
        payload = np.ctypeslib.as_array(out_chars, shape=(max(nbytes, 1),))
        result_chars = np.array(payload[:nbytes], dtype=np.uint8, copy=True)
    finally:
        lib.tpudf_free_buffer(out_chars)
    return Column(
        t.STRING,
        jnp.asarray(out_offsets),
        jnp.asarray(out_valid.astype(bool)),
        chars=jnp.asarray(result_chars),
    )
