"""get_json_object over string columns — the Spark SQL JSONPath extractor
(north-star JNI kernel; BASELINE.json lists it explicitly).

Two engines behind one dispatcher:

* **Device fast path** (``ops/json_device.py``): fully vectorized XLA
  program over the padded (n, W) layout — structural classification via
  quote-parity/bracket-depth scans, span narrowing per path component. No
  host round trip. Taken when every row is escape-free and structurally
  sane (one scalar eligibility fetch decides).
* **Native host engine** (src/native/src/get_json_object.cpp): the branchy
  byte-level state machine, used for escaped/malformed inputs — the cases
  where a data-dependent parse genuinely beats a vectorized one.

Path grammar (both engines): ``$``, ``.field``, ``['field']``,
``[index]``; wildcards raise ValueError (Spark's analyzer behavior for
paths it cannot compile). String matches come back unquoted (escapes
decoded on the host path; the device path never sees escapes);
object/array/number/bool matches come back as raw JSON text; JSON null and
missing paths are SQL NULL.
"""

from __future__ import annotations

import ctypes

import jax.numpy as jnp
import numpy as np

from spark_rapids_jni_tpu import types as t
from spark_rapids_jni_tpu.columnar import Column
from spark_rapids_jni_tpu.parquet.footer import NativeError
from spark_rapids_jni_tpu.runtime.native import load_native
from spark_rapids_jni_tpu.utils.tracing import func_range


@func_range("get_json_object")
def get_json_object(col: Column, path: str) -> Column:
    """Extract ``path`` from every JSON document in a STRING column.
    Dispatches to the device engine when the column is eligible; the
    native host engine otherwise."""
    if not col.dtype.is_string:
        raise TypeError("get_json_object requires a STRING column")
    from spark_rapids_jni_tpu.ops import json_device as jd

    # one jitted device pass computes the extraction AND the eligibility
    # verdict from a shared structural classification; only the 1-byte
    # verdict crosses to the host
    result, eligible = jd.extract_with_eligibility(col, path)
    if bool(eligible):
        return result
    return get_json_object_host(col, path)


@func_range("get_json_object_host")
def get_json_object_host(col: Column, path: str) -> Column:
    """Native-engine path (host round trip) — escape decoding and full
    grammar validation live here."""
    if not col.dtype.is_string:
        raise TypeError("get_json_object requires a STRING column")
    if col.is_padded_string:
        from spark_rapids_jni_tpu.ops.strings import unpad_strings

        col = unpad_strings(col)
    lib = load_native()
    n = col.size
    offsets = np.ascontiguousarray(np.asarray(col.data), dtype=np.int32)
    chars = np.ascontiguousarray(np.asarray(col.chars), dtype=np.uint8)
    if chars.size == 0:
        chars = np.zeros(1, dtype=np.uint8)
    valid_in = None
    if col.validity is not None:
        valid_in = np.ascontiguousarray(
            np.asarray(col.validity), dtype=np.uint8
        )

    out_chars = ctypes.POINTER(ctypes.c_uint8)()
    out_len = ctypes.c_int64()
    out_offsets = np.empty(n + 1, dtype=np.int32)
    out_valid = np.empty(n, dtype=np.uint8)
    rc = lib.tpudf_get_json_object(
        chars.ctypes.data_as(ctypes.c_void_p),
        offsets.ctypes.data_as(ctypes.c_void_p),
        None if valid_in is None
        else valid_in.ctypes.data_as(ctypes.c_void_p),
        n,
        path.encode(),
        ctypes.byref(out_chars),
        ctypes.byref(out_len),
        out_offsets.ctypes.data_as(ctypes.c_void_p),
        out_valid.ctypes.data_as(ctypes.c_void_p),
    )
    if rc != 0:
        msg = lib.last_error()
        # PathError messages carry a fixed "JSONPath: " prefix (caller bug
        # -> ValueError); anything else is an engine failure.
        if msg.startswith("JSONPath:"):
            raise ValueError(msg)
        raise NativeError(msg)
    try:
        nbytes = out_len.value
        payload = np.ctypeslib.as_array(out_chars, shape=(max(nbytes, 1),))
        result_chars = np.array(payload[:nbytes], dtype=np.uint8, copy=True)
    finally:
        lib.tpudf_free_buffer(out_chars)
    return Column(
        t.STRING,
        jnp.asarray(out_offsets),
        jnp.asarray(out_valid.astype(bool)),
        chars=jnp.asarray(result_chars),
    )


@func_range("json_tuple")
def json_tuple(col: Column, *fields: str) -> list:
    """Spark ``json_tuple(json, f1, f2, ...)``: one STRING column per
    top-level field — each field runs the two-engine get_json_object
    dispatcher with the ``$.field`` path. Cost is one full pass per
    field (the k-field single-scan engine is future work — the
    dispatcher's per-column eligibility verdict is recomputed each
    time)."""
    if not fields:
        raise ValueError("json_tuple needs at least one field name")
    out = []
    for f in fields:
        if not f or any(ch in f for ch in ".[]'\"$*"):
            raise ValueError(
                f"json_tuple field {f!r} must be a plain top-level key "
                "(use get_json_object for nested paths)")
        out.append(get_json_object(col, f"$.{f}"))
    return out
