"""Multi-key table sort — the cuDF ``sort``/``order_by`` equivalent of the
vendored operator substrate (SURVEY.md section 2.2: libcudf sort is part of
the capability surface; exercised by TPC-H q1's final ORDER BY).

TPU-first design: no comparator kernels. Each key column is *encoded* into
an order-preserving unsigned integer word (floats via sign-magnitude flip,
signed ints via sign-bit flip, with a null indicator folded in), and the
whole thing is one ``jnp.lexsort`` — XLA's native multi-pass radix-friendly
sort — followed by a gather. Encoded keys also give Spark-compatible total
float order (NaN sorts greatest, -0.0 == 0.0 is NOT collapsed: -0.0 < 0.0
bitwise — documented deviation from Java's Double.compare only for -0.0).
"""

from __future__ import annotations

from functools import partial
from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np

from spark_rapids_jni_tpu.columnar import Column, Table
from spark_rapids_jni_tpu.types import DType
from spark_rapids_jni_tpu.utils.tracing import func_range


def _as_unsigned_key(col_data: jnp.ndarray, dtype: DType) -> jnp.ndarray:
    """Encode one column as an order-preserving uint key (uint32 or uint64)."""
    np_dt = dtype.storage_dtype
    if np_dt.kind == "u":
        return col_data
    if np_dt.kind == "i":
        bits = np_dt.itemsize * 8
        u = col_data.astype(jnp.dtype(f"uint{bits}"))
        return u ^ jnp.asarray(1 << (bits - 1), dtype=u.dtype)
    if np_dt == np.float32:
        u = jax.lax.bitcast_convert_type(col_data, jnp.uint32)
        sign = (u >> 31).astype(jnp.uint32)
        # negative: flip all bits; positive: flip sign bit
        enc = u ^ jnp.where(sign == 1, jnp.uint32(0xFFFFFFFF), jnp.uint32(0x80000000))
        # Canonicalize every NaN (either sign) above +inf: Spark treats NaN
        # as one greatest value; a negative NaN's payload would otherwise
        # sort smallest and split NaN groups in groupby.
        return jnp.where(jnp.isnan(col_data), jnp.uint32(0xFFFFFFFF), enc)
    # float64 never reaches here: _key_arrays routes it to the value-level
    # two-key encoding (no 64-bit bitcast on TPU).
    raise TypeError(f"unsupported sort key type {dtype}")


def _key_arrays(col: Column, ascending: bool, nulls_first: bool):
    """Return the lexsort key(s) for one column, minor-to-major order.

    Null rows' VALUE keys are forced to a constant: a null cell's stored
    bytes are unspecified (Column contract), and letting them order the
    null run would split the null group across clusters once later sort
    keys reset between them — adjacent-equality consumers (groupby,
    distinct, rank encoding) would then see several "null groups" where
    SQL semantics require one. With the constant, null rows tie on this
    column and order by the remaining keys, like any other equal run.
    """
    dtype = col.dtype
    valid = col.valid_mask()

    def null_const(keys):
        return [jnp.where(valid, k, jnp.zeros((), k.dtype)) for k in keys]

    if dtype.is_decimal128:
        # limb-pair compare: unsigned low limb minor, sign-flipped high limb
        # major — uint ordering on the pair == 128-bit integer ordering
        lo_u = col.data[:, 0].astype(jnp.uint64)
        hi_u = col.data[:, 1].astype(jnp.uint64) ^ jnp.uint64(1 << 63)
        value_keys = [lo_u, hi_u]
        if not ascending:
            value_keys = [~k for k in value_keys]
        null_key = jnp.where(valid, jnp.uint8(1), jnp.uint8(0))
        null_rank = null_key if nulls_first else jnp.uint8(1) - null_key
        return null_const(value_keys) + [null_rank]
    if dtype.is_string:
        from spark_rapids_jni_tpu.ops import strings as s

        value_keys = s.packed_sort_keys(col)
        if not ascending:
            value_keys = [~k for k in value_keys]
        null_key = jnp.where(valid, jnp.uint8(1), jnp.uint8(0))
        null_rank = null_key if nulls_first else jnp.uint8(1) - null_key
        return null_const(value_keys) + [null_rank]

    np_dt = dtype.storage_dtype
    n = col.size

    if np_dt == np.float64:
        # value-level key: works on all backends, Spark order for NaN
        v = col.data
        neg = jnp.where(jnp.isnan(v), jnp.inf, v)
        key = -neg if not ascending else neg
        # NaN: +inf surrogate already sorts greatest ascending; descending
        # -(+inf) = -inf sorts first, matching Spark's NaN-greatest order.
        nan_rank = jnp.isnan(v)
        value_keys = [key, (~nan_rank if not ascending else nan_rank)]
    else:
        u = _as_unsigned_key(col.data, dtype)
        if not ascending:
            u = ~u
        value_keys = [u]

    null_key = jnp.where(valid, jnp.uint8(1), jnp.uint8(0))
    if nulls_first:
        null_rank = null_key  # nulls (0) first
    else:
        null_rank = jnp.uint8(1) - null_key  # valids (0) first
    del n
    return null_const(value_keys) + [null_rank]  # null rank most significant


def _key_bits(arr: jnp.ndarray) -> int | None:
    """Bit width of a lexsort key array, or None if not a packable uint."""
    return {
        jnp.dtype(jnp.bool_): 1,
        jnp.dtype(jnp.uint8): 8,
        jnp.dtype(jnp.uint16): 16,
        jnp.dtype(jnp.uint32): 32,
    }.get(jnp.dtype(arr.dtype))


def _pack_lex_keys(lex_keys: list[jnp.ndarray]) -> list[jnp.ndarray]:
    """Fuse minor->major unsigned lex keys into as few words as possible.

    A variadic lexsort pays a multi-operand comparator per sort pass; when
    the combined key fits one machine word (the common relational case:
    a couple of flag/dictionary/date keys plus null ranks), packing them
    into a single uint32 collapses the whole thing to one single-key
    argsort, which XLA sorts substantially faster. 64-bit packs use a
    (hi, lo) uint32 pair rather than uint64 — int64 is emulated on the
    TPU VPU, and two 32-bit keys lexsort faster than one emulated 64-bit.
    """
    widths = [_key_bits(a) for a in lex_keys]
    if any(w is None for w in widths) or sum(widths) > 64:
        return lex_keys
    total = sum(widths)

    def fold(keys: list[jnp.ndarray]) -> jnp.ndarray:
        # keys are minor -> major: the LAST is the most significant field
        acc = None
        for a in reversed(keys):
            w = _key_bits(a)
            a32 = a.astype(jnp.uint32)
            acc = a32 if acc is None else (acc << w) | a32
        return acc

    if total <= 32:
        return [fold(lex_keys)]
    # split the minor->major run into a low word and a high word, each
    # <=32 bits; if the high run cannot fit its own word (e.g. a 32-bit
    # value key + null rank landing together), packing is not possible
    lo_run, bits = [], 0
    for i, a in enumerate(lex_keys):
        w = _key_bits(a)
        if bits + w > 32:
            if sum(widths[i:]) > 32:
                return lex_keys
            return [fold(lo_run), fold(lex_keys[i:])]
        lo_run.append(a)
        bits += w
    raise AssertionError("unreachable: total > 32 must split")


def _sort_order_impl(row_args, aux, rvs, *, keys, ascending, nulls_first):
    ((table, row_valid),) = row_args
    # phantom rows (padded tails, masked shuffle slots): rank them AFTER
    # every real row with one extra most-significant key; the sort is
    # stable, so the leading entries are exactly the real rows' stable
    # permutation — bit-identical to the unpadded sort after slicing.
    rv = row_valid
    if rv is None and rvs is not None:
        rv = rvs[0]
    lex_keys: list[jnp.ndarray] = []
    # jnp.lexsort treats the LAST key as primary; build minor -> major.
    for k, asc, nf in zip(reversed(list(keys)), reversed(list(ascending)),
                          reversed(list(nulls_first))):
        lex_keys.extend(_key_arrays(table.column(k), asc, nf))
    if rv is not None:
        lex_keys.append(jnp.where(rv, jnp.uint8(0), jnp.uint8(1)))
    lex_keys = _pack_lex_keys(lex_keys)
    if len(lex_keys) == 1:
        return jnp.argsort(lex_keys[0], stable=True).astype(jnp.int32)
    return jnp.lexsort(tuple(lex_keys)).astype(jnp.int32)


@func_range("sort_order")
def sort_order(
    table: Table,
    keys: Sequence[int],
    ascending: Sequence[bool] | None = None,
    nulls_first: Sequence[bool] | None = None,
    row_valid: jnp.ndarray | None = None,
) -> jnp.ndarray:
    """Stable sort permutation (int32) ordering rows by the key columns.
    Rows where ``row_valid`` is False sort after every real row (used by
    callers that carry phantom rows, e.g. bounded shuffles)."""
    if ascending is None:
        ascending = [True] * len(keys)
    if nulls_first is None:
        nulls_first = [True] * len(keys)
    from spark_rapids_jni_tpu.runtime import dispatch

    return dispatch.call(
        "sort_order",
        partial(_sort_order_impl, keys=tuple(keys),
                ascending=tuple(ascending), nulls_first=tuple(nulls_first)),
        ((table, row_valid),),
        statics=(tuple(keys), tuple(ascending), tuple(nulls_first)))


def gather(table: Table, indices: jnp.ndarray) -> Table:
    """Row gather — the cuDF gather primitive. Out-of-range indices are
    clamped by XLA (callers pass valid permutations)."""
    cols = []
    for c in table.columns:
        if c.dtype.is_string:
            from spark_rapids_jni_tpu.ops import strings as s

            cols.append(s.gather_strings(c, indices))
            continue
        validity = None if c.validity is None else c.validity[indices]
        cols.append(Column(c.dtype, c.data[indices], validity))
    return Table(cols)


@func_range("sort_table")
def sort_table(
    table: Table,
    keys: Sequence[int],
    ascending: Sequence[bool] | None = None,
    nulls_first: Sequence[bool] | None = None,
) -> Table:
    return gather(table, sort_order(table, keys, ascending, nulls_first))
