"""Multi-key table sort — the cuDF ``sort``/``order_by`` equivalent of the
vendored operator substrate (SURVEY.md section 2.2: libcudf sort is part of
the capability surface; exercised by TPC-H q1's final ORDER BY).

TPU-first design: no comparator kernels. Each key column is *encoded* into
an order-preserving unsigned integer word (floats via sign-magnitude flip,
signed ints via sign-bit flip, with a null indicator folded in), and the
whole thing is one ``jnp.lexsort`` — XLA's native multi-pass radix-friendly
sort — followed by a gather. Encoded keys also give Spark-compatible total
float order (NaN sorts greatest, -0.0 == 0.0 is NOT collapsed: -0.0 < 0.0
bitwise — documented deviation from Java's Double.compare only for -0.0).
"""

from __future__ import annotations

from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np

from spark_rapids_jni_tpu.columnar import Column, Table
from spark_rapids_jni_tpu.types import DType
from spark_rapids_jni_tpu.utils.tracing import func_range


def _as_unsigned_key(col_data: jnp.ndarray, dtype: DType) -> jnp.ndarray:
    """Encode one column as an order-preserving uint key (uint32 or uint64)."""
    np_dt = dtype.storage_dtype
    if np_dt.kind == "u":
        return col_data
    if np_dt.kind == "i":
        bits = np_dt.itemsize * 8
        u = col_data.astype(jnp.dtype(f"uint{bits}"))
        return u ^ jnp.asarray(1 << (bits - 1), dtype=u.dtype)
    if np_dt == np.float32:
        u = jax.lax.bitcast_convert_type(col_data, jnp.uint32)
        sign = (u >> 31).astype(jnp.uint32)
        # negative: flip all bits; positive: flip sign bit
        enc = u ^ jnp.where(sign == 1, jnp.uint32(0xFFFFFFFF), jnp.uint32(0x80000000))
        # Canonicalize every NaN (either sign) above +inf: Spark treats NaN
        # as one greatest value; a negative NaN's payload would otherwise
        # sort smallest and split NaN groups in groupby.
        return jnp.where(jnp.isnan(col_data), jnp.uint32(0xFFFFFFFF), enc)
    # float64 never reaches here: _key_arrays routes it to the value-level
    # two-key encoding (no 64-bit bitcast on TPU).
    raise TypeError(f"unsupported sort key type {dtype}")


def _key_arrays(col: Column, ascending: bool, nulls_first: bool):
    """Return the lexsort key(s) for one column, minor-to-major order."""
    dtype = col.dtype
    valid = col.valid_mask()

    if dtype.is_decimal128:
        raise NotImplementedError(
            "DECIMAL128 sort keys are not supported yet (limb-pair compare)"
        )
    if dtype.is_string:
        from spark_rapids_jni_tpu.ops import strings as s

        value_keys = s.packed_sort_keys(col)
        if not ascending:
            value_keys = [~k for k in value_keys]
        null_key = jnp.where(valid, jnp.uint8(1), jnp.uint8(0))
        null_rank = null_key if nulls_first else jnp.uint8(1) - null_key
        return value_keys + [null_rank]

    np_dt = dtype.storage_dtype
    n = col.size

    if np_dt == np.float64:
        # value-level key: works on all backends, Spark order for NaN
        v = col.data
        neg = jnp.where(jnp.isnan(v), jnp.inf, v)
        key = -neg if not ascending else neg
        # NaN: +inf surrogate already sorts greatest ascending; descending
        # -(+inf) = -inf sorts first, matching Spark's NaN-greatest order.
        nan_rank = jnp.isnan(v)
        value_keys = [key, (~nan_rank if not ascending else nan_rank)]
    else:
        u = _as_unsigned_key(col.data, dtype)
        if not ascending:
            u = ~u
        value_keys = [u]

    null_key = jnp.where(valid, jnp.uint8(1), jnp.uint8(0))
    if nulls_first:
        null_rank = null_key  # nulls (0) first
    else:
        null_rank = jnp.uint8(1) - null_key  # valids (0) first
    del n
    return value_keys + [null_rank]  # null rank is most significant


@func_range("sort_order")
def sort_order(
    table: Table,
    keys: Sequence[int],
    ascending: Sequence[bool] | None = None,
    nulls_first: Sequence[bool] | None = None,
) -> jnp.ndarray:
    """Stable sort permutation (int32) ordering rows by the key columns."""
    if ascending is None:
        ascending = [True] * len(keys)
    if nulls_first is None:
        nulls_first = [True] * len(keys)
    lex_keys: list[jnp.ndarray] = []
    # jnp.lexsort treats the LAST key as primary; build minor -> major.
    for k, asc, nf in zip(reversed(list(keys)), reversed(list(ascending)),
                          reversed(list(nulls_first))):
        lex_keys.extend(_key_arrays(table.column(k), asc, nf))
    return jnp.lexsort(tuple(lex_keys)).astype(jnp.int32)


def gather(table: Table, indices: jnp.ndarray) -> Table:
    """Row gather — the cuDF gather primitive. Out-of-range indices are
    clamped by XLA (callers pass valid permutations)."""
    cols = []
    for c in table.columns:
        if c.dtype.is_string:
            from spark_rapids_jni_tpu.ops import strings as s

            cols.append(s.gather_strings(c, indices))
            continue
        validity = None if c.validity is None else c.validity[indices]
        cols.append(Column(c.dtype, c.data[indices], validity))
    return Table(cols)


@func_range("sort_table")
def sort_table(
    table: Table,
    keys: Sequence[int],
    ascending: Sequence[bool] | None = None,
    nulls_first: Sequence[bool] | None = None,
) -> Table:
    return gather(table, sort_order(table, keys, ascending, nulls_first))
