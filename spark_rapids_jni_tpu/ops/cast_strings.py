"""String -> numeric casts (CastStrings component — BASELINE.json config #1
"CastStrings float/decimal parse microbench"; part of the reference
family's Spark-specific kernel set, north_star).

TPU-first design: no per-row character loops. The string column's ragged
(offsets, chars) buffers are gathered into a dense (n, max_len) character
matrix once, then every row parses in lockstep with vectorized digit
arithmetic — a fixed number of elementwise passes over the matrix
regardless of row count, which is exactly the shape the VPU wants. max_len
is a static bound (default 32: covers int64/decimal/float literals; longer
rows are invalid anyway for numeric casts except exotic floats, which
overflow to inf like Spark's Double.parseDouble on huge exponents).

Spark CAST semantics (non-ANSI): leading/trailing whitespace trimmed,
optional +/-, invalid input -> null, integer overflow -> null, decimal
rounds HALF_UP to the target scale and nulls on precision overflow.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from spark_rapids_jni_tpu import telemetry
from spark_rapids_jni_tpu import types as t
from spark_rapids_jni_tpu.columnar import Column
from spark_rapids_jni_tpu.ops._calendar import civil_from_days, days_from_civil
from spark_rapids_jni_tpu.types import DType, TypeId
from spark_rapids_jni_tpu.utils.tracing import func_range

DEFAULT_MAX_LEN = 32


def _char_matrix(col: Column, max_len: int):
    """Gather the ragged chars into (n, max_len) + per-cell presence mask.
    Cells beyond a row's length are 0x20 (space) so trim logic is uniform."""
    offsets = col.data
    chars = col.chars
    n = col.size
    starts = offsets[:-1]
    lengths = offsets[1:] - starts
    idx = starts[:, None] + jnp.arange(max_len, dtype=jnp.int32)[None, :]
    present = jnp.arange(max_len, dtype=jnp.int32)[None, :] < lengths[:, None]
    safe = jnp.clip(idx, 0, max(int(chars.shape[0]) - 1, 0))
    mat = jnp.where(present, chars[safe], jnp.uint8(0x20))
    too_long = lengths > max_len
    del n
    return mat, present, lengths, too_long


def _strip_and_sign(mat, present):
    """Identify the numeric payload: [start, end) after whitespace trim and
    optional sign. Returns (is_neg, payload_start, payload_end, had_sign)."""
    max_len = mat.shape[1]
    pos = jnp.arange(max_len, dtype=jnp.int32)[None, :]
    is_space = (mat == 0x20) | (mat == 0x09) | (mat == 0x0A) | (mat == 0x0D)
    nonspace = ~is_space & present
    big = jnp.int32(max_len)
    first = jnp.min(jnp.where(nonspace, pos, big), axis=1)
    last = jnp.max(jnp.where(nonspace, pos, -1), axis=1)
    end = last + 1
    first_c = jnp.take_along_axis(
        mat, jnp.clip(first, 0, max_len - 1)[:, None], axis=1
    )[:, 0]
    has_sign = (first_c == ord("-")) | (first_c == ord("+"))
    is_neg = first_c == ord("-")
    start = jnp.where(has_sign, first + 1, first)
    return is_neg, start, end, first


@func_range("cast_string_to_integer")
def string_to_integer(
    col: Column, dtype: DType, max_len: int = DEFAULT_MAX_LEN
) -> Column:
    """Parse to an integral column; invalid/overflow -> null."""
    if not col.dtype.is_string:
        raise TypeError("input must be a string column")
    mat, present, lengths, too_long = _char_matrix(col, max_len)
    is_neg, start, end, _ = _strip_and_sign(mat, present)
    pos = jnp.arange(max_len, dtype=jnp.int32)[None, :]
    in_payload = (pos >= start[:, None]) & (pos < end[:, None])
    digit = mat - jnp.uint8(ord("0"))
    is_digit = digit <= 9
    ok = jnp.all(is_digit | ~in_payload, axis=1)
    ok &= end > start  # at least one digit
    ok &= ~too_long

    # value = sum digit * 10^(distance from payload end), accumulated in
    # uint64 — exact for <= 19 digits (10^19 < 2^64), so overflow checks
    # are precise where float approximations are not (2^63 vs 2^63-1).
    weight_pos = end[:, None] - 1 - pos  # 0 for last digit
    d = jnp.where(in_payload, digit.astype(jnp.uint64), jnp.uint64(0))
    pow10 = jnp.where(
        (weight_pos >= 0) & (weight_pos < 19),
        jnp.power(
            jnp.uint64(10), jnp.clip(weight_pos, 0, 18).astype(jnp.uint64)
        ),
        jnp.uint64(0),
    )
    value_u = jnp.sum(d * pow10, axis=1)
    # Count significant digits (leading zeros don't count — "0...001" is a
    # perfectly good 1). 19 significant digits stay below 10^19 < 2^64:
    # exact; more would fall outside the pow10 window and silently wrap,
    # so reject.
    sig_start = jnp.min(
        jnp.where(in_payload & (digit != 0) & is_digit, pos, jnp.int32(max_len)),
        axis=1,
    )
    n_sig = jnp.maximum(end - sig_start, 0)
    ok &= n_sig <= 19
    np_dt = dtype.storage_dtype
    info = np.iinfo(np_dt if np_dt.kind in "iu" else np.int64)
    ok &= jnp.where(
        is_neg,
        value_u <= jnp.uint64(-int(info.min)),
        value_u <= jnp.uint64(info.max),
    )
    signed = jnp.where(is_neg, jnp.uint64(0) - value_u, value_u).astype(
        jnp.int64
    )
    return Column(dtype, signed.astype(dtype.jnp_dtype), ok)


@func_range("cast_string_to_decimal")
def string_to_decimal(
    col: Column, dtype: DType, max_len: int = DEFAULT_MAX_LEN
) -> Column:
    """Parse to decimal32/64 at the target scale, HALF_UP rounding;
    invalid/overflow -> null."""
    if not dtype.is_decimal:
        raise TypeError("target must be a decimal type")
    mat, present, lengths, too_long = _char_matrix(col, max_len)
    is_neg, start, end, _ = _strip_and_sign(mat, present)
    pos = jnp.arange(mat.shape[1], dtype=jnp.int32)[None, :]
    in_payload = (pos >= start[:, None]) & (pos < end[:, None])
    is_dot = mat == ord(".")
    digit = mat - jnp.uint8(ord("0"))
    is_digit = digit <= 9
    dot_count = jnp.sum(is_dot & in_payload, axis=1)
    ok = jnp.all(is_digit | is_dot | ~in_payload, axis=1)
    ok &= dot_count <= 1
    ok &= (end - start) > dot_count  # at least one digit
    ok &= ~too_long

    big = jnp.int32(mat.shape[1])
    dot_pos = jnp.min(jnp.where(is_dot & in_payload, pos, big), axis=1)
    dot_pos = jnp.where(dot_count == 0, end, dot_pos)
    # digit weight relative to the decimal point: 10^(int part distance)
    int_weight = dot_pos[:, None] - 1 - pos           # >=0 left of the dot
    frac_weight = pos - dot_pos[:, None]              # >=1 right of the dot
    # target scale: value_unscaled = round(value * 10^-scale), scale <= 0
    shift = -dtype.scale  # digits of fraction kept
    # unscaled integer = sum(int digits * 10^(int_weight + shift))
    #                  + sum(frac digits * 10^(shift - frac_weight)) [+ round]
    d64 = jnp.where(in_payload & is_digit, digit.astype(jnp.int64), 0)
    int_exp = int_weight + shift
    frac_exp = shift - frac_weight
    exp = jnp.where(pos < dot_pos[:, None], int_exp, frac_exp)
    contrib = jnp.where(
        (exp >= 0) & (exp < 19),
        d64 * jnp.power(jnp.int64(10), jnp.clip(exp, 0, 18).astype(jnp.int64)),
        0,
    )
    value = jnp.sum(contrib, axis=1)
    # HALF_UP: look at the first dropped fractional digit (exp == -1)
    round_digit = jnp.sum(jnp.where(exp == -1, d64, 0), axis=1)
    value = value + (round_digit >= 5).astype(jnp.int64)
    # Precision overflow, checked on the POST-rounding unscaled magnitude
    # (9999999.995 rounds up into a 10th digit). Leading zeros don't count:
    # guard the accumulator window with significant integer digits only.
    sig_start = jnp.min(
        jnp.where(in_payload & is_digit & (digit != 0), pos, big), axis=1
    )
    sig_int_digits = jnp.maximum(dot_pos - jnp.minimum(sig_start, dot_pos), 0)
    ok &= (sig_int_digits + shift) <= 18  # accumulator exactness bound
    max_digits = 18 if dtype.type_id == TypeId.DECIMAL64 else 9
    max_unscaled = jnp.int64(10 ** max_digits - 1)
    ok &= value <= max_unscaled
    signed = jnp.where(is_neg, -value, value)
    return Column(dtype, signed.astype(dtype.jnp_dtype), ok)


@func_range("cast_string_to_float")
def string_to_float(
    col: Column, dtype: DType, max_len: int = DEFAULT_MAX_LEN
) -> Column:
    """Parse to float32/64; accepts [+-]digits[.digits][eE[+-]digits],
    plus Infinity/NaN spellings (Spark-compatible); invalid -> null."""
    mat, present, lengths, too_long = _char_matrix(col, max_len)
    is_neg, start, end, _ = _strip_and_sign(mat, present)
    max_len_s = mat.shape[1]
    pos = jnp.arange(max_len_s, dtype=jnp.int32)[None, :]
    in_payload = (pos >= start[:, None]) & (pos < end[:, None])

    lower = jnp.where((mat >= ord("A")) & (mat <= ord("Z")), mat + 32, mat)

    def _matches(word: bytes):
        m = jnp.ones((mat.shape[0],), dtype=jnp.bool_)
        for i, ch in enumerate(word):
            at = jnp.clip(start + i, 0, max_len_s - 1)
            m &= jnp.take_along_axis(lower, at[:, None], axis=1)[:, 0] == ch
        m &= (end - start) == len(word)
        return m

    is_inf = _matches(b"infinity") | _matches(b"inf")
    is_nan = _matches(b"nan")

    is_e = (lower == ord("e")) & in_payload
    e_count = jnp.sum(is_e, axis=1)
    big = jnp.int32(max_len_s)
    e_pos = jnp.min(jnp.where(is_e, pos, big), axis=1)
    mant_end = jnp.minimum(e_pos, end)

    digit = mat - jnp.uint8(ord("0"))
    is_digit = digit <= 9
    is_dot = mat == ord(".")
    in_mant = (pos >= start[:, None]) & (pos < mant_end[:, None])
    dot_count = jnp.sum(is_dot & in_mant, axis=1)
    dot_pos = jnp.min(jnp.where(is_dot & in_mant, pos, big), axis=1)
    dot_pos = jnp.where(dot_count == 0, mant_end, dot_pos)

    ok = jnp.all(is_digit | is_dot | ~in_mant, axis=1)
    ok &= dot_count <= 1
    ok &= (mant_end - start) > dot_count

    # mantissa in f64 + decimal exponent of the last digit
    d = jnp.where(in_mant & is_digit, digit.astype(jnp.float64), 0.0)
    int_w = dot_pos[:, None] - 1 - pos
    frac_w = pos - dot_pos[:, None]
    expw = jnp.where(pos < dot_pos[:, None], int_w, -frac_w).astype(jnp.float64)
    mant = jnp.sum(
        d * jnp.power(10.0, jnp.where(in_mant & is_digit, expw, 0.0))
        * jnp.where(in_mant & is_digit, 1.0, 0.0),
        axis=1,
    )

    # exponent part
    exp_start = jnp.minimum(e_pos + 1, end)
    ec = jnp.take_along_axis(
        mat, jnp.clip(exp_start, 0, max_len_s - 1)[:, None], axis=1
    )[:, 0]
    e_sign = jnp.where(ec == ord("-"), -1, 1)
    e_digits_start = jnp.where(
        (ec == ord("-")) | (ec == ord("+")), exp_start + 1, exp_start
    )
    in_exp = (pos >= e_digits_start[:, None]) & (pos < end[:, None])
    has_e = e_count == 1
    ok &= jnp.where(
        has_e,
        jnp.all(is_digit | ~in_exp, axis=1) & (end > e_digits_start),
        e_count == 0,
    )
    e_weight = end[:, None] - 1 - pos
    # int64 accumulation: weights clip at 10^9 per digit, so any nonzero
    # digit at weight >= 10 still drives the sum past the +-400 saturation
    # point without int32 wraparound (a 12-digit exponent must saturate to
    # inf/zero, not wrap to a small finite exponent).
    e_val = jnp.sum(
        jnp.where(in_exp & is_digit, digit.astype(jnp.int64), 0)
        * jnp.power(10, jnp.clip(e_weight, 0, 9)).astype(jnp.int64)
        * (e_weight >= 0),
        axis=1,
    )
    e_val = jnp.clip(e_val * e_sign, -400, 400)
    scale10 = jnp.power(10.0, e_val.astype(jnp.float64))
    # 0e400: 0 * inf would be NaN; zero mantissa is zero at any exponent
    value = jnp.where(mant == 0.0, 0.0, mant * scale10)

    value = jnp.where(is_inf, jnp.inf, value)
    value = jnp.where(is_nan, jnp.nan, value)
    # too_long rejects unconditionally: a truncated payload that happens to
    # trim to an inf/nan spelling is still an overlong string, hence null.
    ok = (ok | is_inf | is_nan) & ~too_long
    signed = jnp.where(is_neg, -value, value)
    return Column(dtype, signed.astype(dtype.jnp_dtype), ok)


# ---- number -> string (the CastStrings reverse direction) ------------------

_MAX_I64_DIGITS = 20  # 19 digits + sign headroom


def _digit_matrix_u64_impl(row_args, aux, rvs) -> jnp.ndarray:
    ((mag,),) = row_args
    powers = jnp.asarray(
        [np.uint64(10) ** np.uint64(k) for k in range(_MAX_I64_DIGITS - 1, -1, -1)],
        dtype=jnp.uint64,
    )
    return ((mag[:, None] // powers[None, :]) % jnp.uint64(10)).astype(jnp.uint8)


def _digit_matrix_u64(mag: jnp.ndarray) -> jnp.ndarray:
    """uint64[n] -> uint8[n, 20] decimal digits, most significant first."""
    from spark_rapids_jni_tpu.runtime import dispatch

    return dispatch.rowwise("digit_matrix_u64", _digit_matrix_u64_impl,
                            (mag,))


def _signed_magnitude(v: jnp.ndarray) -> tuple[jnp.ndarray, jnp.ndarray]:
    v = v.astype(jnp.int64)
    neg = v < 0
    # INT64_MIN-safe negation: -(v+1) fits, then +1 in uint64
    mag = jnp.where(
        neg, (-(v + 1)).astype(jnp.uint64) + jnp.uint64(1), v.astype(jnp.uint64)
    )
    return neg, mag


@func_range("integer_to_string")
def integer_to_string(col: Column) -> Column:
    """Integral column -> STRING, matching Java's Long.toString (no leading
    zeros, '-' for negatives). Digit extraction runs on device; the
    variable-length Arrow assembly is host-side. Booleans go through
    boolean_to_string ('true'/'false', Spark semantics)."""
    kind = col.dtype.storage_dtype.kind
    if (
        kind not in ("i", "u")
        or col.dtype.is_decimal
        or col.dtype.type_id == TypeId.BOOL8
    ):
        raise TypeError(
            "integer_to_string requires an integral column (booleans cast "
            "via boolean_to_string)"
        )
    if kind == "u":
        # unsigned stays in uint64 end to end — casting through int64 would
        # wrap values >= 2^63 into negatives
        neg = jnp.zeros(col.data.shape, jnp.bool_)
        mag = col.data.astype(jnp.uint64)
    else:
        neg, mag = _signed_magnitude(col.data.astype(jnp.int64))
    digits = np.asarray(_digit_matrix_u64(mag))
    neg = np.asarray(neg)
    valid = np.asarray(col.valid_mask())
    return _assemble_decimal_strings(
        digits, neg, valid, scale=0, op="integer_to_string")


def _column_from_pieces(pieces: list, valid, op: str) -> Column:
    """Host-side Arrow assembly shared by every X->string cast. Each call
    is a device->host fallback by construction (variable-length string
    building has no device path yet) and is recorded as such."""
    telemetry.record_fallback(
        op, "host-side Arrow string assembly: variable-length X->string "
        "building has no device path", rows=len(pieces))
    offsets = np.zeros(len(pieces) + 1, dtype=np.int32)
    np.cumsum([len(p) for p in pieces], out=offsets[1:])
    chars = np.frombuffer(b"".join(pieces), dtype=np.uint8)
    return Column(
        t.STRING,
        jnp.asarray(offsets),
        None if valid.all() else jnp.asarray(valid),
        chars=jnp.asarray(chars.copy() if chars.size else np.zeros(0, np.uint8)),
    )


@func_range("boolean_to_string")
def boolean_to_string(col: Column) -> Column:
    """BOOL8 -> STRING: 'true'/'false' (Spark cast semantics)."""
    if col.dtype.type_id != TypeId.BOOL8:
        raise TypeError("boolean_to_string requires a BOOL8 column")
    vals = np.asarray(col.data) != 0
    valid = np.asarray(col.valid_mask())
    pieces = [
        (b"true" if v else b"false") if ok else b""
        for v, ok in zip(vals, valid)
    ]
    return _column_from_pieces(pieces, valid, "boolean_to_string")


@func_range("decimal_to_string")
def decimal_to_string(col: Column) -> Column:
    """Decimal column -> STRING with Spark's plain representation:
    scale -2, unscaled 5 -> "0.05"; scale 0 behaves like integers."""
    if not col.dtype.is_decimal:
        raise TypeError("decimal_to_string requires a decimal column")
    neg, mag = _signed_magnitude(col.data)
    digits = np.asarray(_digit_matrix_u64(mag))
    neg = np.asarray(neg)
    valid = np.asarray(col.valid_mask())
    if col.dtype.scale > 0:
        # value = unscaled * 10^scale: integral with trailing zeros
        # (Spark renders DECIMAL(p, negative-s) as a plain integer)
        return _assemble_decimal_strings(
            digits, neg, valid, scale=0, trailing_zeros=col.dtype.scale)
    return _assemble_decimal_strings(digits, neg, valid, scale=-col.dtype.scale)


def _assemble_decimal_strings(
    digits: np.ndarray, neg: np.ndarray, valid: np.ndarray, scale: int,
    trailing_zeros: int = 0, op: str = "decimal_to_string",
) -> Column:
    """Host assembly: digit rows -> Arrow string column. ``scale`` is the
    number of fractional digits (>= 0); ``trailing_zeros`` appends fixed
    zeros (positive decimal scales — integral values)."""
    n = digits.shape[0]
    pieces: list[bytes] = []
    for i in range(n):
        if not valid[i]:
            pieces.append(b"")
            continue
        ds = digits[i]
        s = bytes(ds + ord("0")).lstrip(b"0")
        if scale > 0:
            s = s.rjust(scale + 1, b"0")  # ensure a digit before the dot
            s = s[:-scale] + b"." + s[-scale:]
        elif not s:
            s = b"0"
        elif trailing_zeros:
            s = s + b"0" * trailing_zeros
        if neg[i]:
            s = b"-" + s
        pieces.append(s)
    return _column_from_pieces(pieces, valid, op)


# ---- date casts ------------------------------------------------------------


def _days_from_civil(y: jnp.ndarray, m: jnp.ndarray,
                     d: jnp.ndarray) -> jnp.ndarray:
    """(year, month, day) -> int32 days since 1970-01-01 (shared civil-
    calendar arithmetic, ops/_calendar.py)."""
    return days_from_civil(y, m, d).astype(jnp.int32)


def _civil_from_days(z: jnp.ndarray):
    """days since 1970-01-01 -> (year, month, day) (shared civil-calendar
    arithmetic, ops/_calendar.py)."""
    return civil_from_days(z)


_DAYS_IN_MONTH = jnp.asarray(
    [0, 31, 28, 31, 30, 31, 30, 31, 31, 30, 31, 30, 31], dtype=jnp.int32
)


def _trimmed_matrix(col: Column, max_len: int):
    """(mat, present, lengths, judgeable): the char matrix gathered from
    each row's first non-whitespace byte, lengths excluding trailing
    whitespace (Spark's UTF8String.trim: bytes <= 0x20). The trim runs on
    the flat chars buffer BEFORE the window gather (next/prev-non-ws via
    cumulative min/max scans), so arbitrarily long whitespace padding
    cannot push a short value out of the window; ``judgeable`` is False
    only when the TRIMMED content overruns ``max_len``."""
    offsets = col.data
    chars = col.chars
    n = col.size
    total = int(chars.shape[0])
    starts = offsets[:-1].astype(jnp.int32)
    ends = offsets[1:].astype(jnp.int32)
    if total == 0:
        lengths = jnp.zeros((n,), jnp.int32)
        mat = jnp.full((n, max_len), jnp.uint8(0x20))
        present = jnp.zeros((n, max_len), jnp.bool_)
        return mat, present, lengths, jnp.ones((n,), jnp.bool_)
    idxs = jnp.arange(total, dtype=jnp.int32)
    nonws = chars > 0x20
    # first non-ws index >= i / last non-ws index <= i, whole-buffer scans
    nxt = jax.lax.associative_scan(
        jnp.minimum, jnp.where(nonws, idxs, jnp.int32(total)),
        reverse=True)
    prv = jax.lax.associative_scan(
        jnp.maximum, jnp.where(nonws, idxs, jnp.int32(-1)))
    s_eff = jnp.minimum(nxt[jnp.clip(starts, 0, total - 1)], ends)
    e_eff = prv[jnp.clip(ends - 1, 0, total - 1)] + 1
    e_eff = jnp.where(ends > starts, jnp.minimum(e_eff, ends), starts)
    lengths = jnp.maximum(e_eff - s_eff, 0).astype(jnp.int32)
    jdx = jnp.arange(max_len, dtype=jnp.int32)
    idx = s_eff[:, None] + jdx[None, :]
    present = jdx[None, :] < lengths[:, None]
    mat = jnp.where(
        present, chars[jnp.clip(idx, 0, total - 1)], jnp.uint8(0x20)
    )
    return mat, present, lengths, lengths <= max_len


def _digit_field(mat: jnp.ndarray, present: jnp.ndarray):
    """Per-row digit classification + a [lo, hi)-window integer parser."""
    w = mat.shape[1]
    jdx = jnp.arange(w, dtype=jnp.int32)
    is_digit = present & (mat >= ord("0")) & (mat <= ord("9"))
    digit = jnp.where(is_digit, mat - ord("0"), 0).astype(jnp.int32)

    def field(lo, hi):  # digits in [lo, hi) -> int, plus all-digit flag
        sel = (jdx[None, :] >= lo[:, None]) & (jdx[None, :] < hi[:, None])
        ok = jnp.all(~sel | is_digit, axis=1)
        p = jnp.where(sel, hi[:, None] - 1 - jdx[None, :], 0)
        val = jnp.sum(
            jnp.where(sel,
                      digit.astype(jnp.int64) * (10 ** p.astype(jnp.int64)),
                      0),
            axis=1,
        )
        return val.astype(jnp.int32), ok & jnp.any(sel, axis=1)

    return is_digit, field


def _parse_civil_date(mat, present, date_len):
    """Parse 'yyyy-[M]M-[d]d' occupying [0, date_len) of each row ->
    (days, ok): 4-digit year, 1-2 digit month/day, calendar-validated."""
    w = mat.shape[1]
    jdx = jnp.arange(w, dtype=jnp.int32)
    in_date = present & (jdx[None, :] < date_len[:, None])
    is_digit, field = _digit_field(mat, in_date)
    is_dash = in_date & (mat == ord("-"))
    n_dash = jnp.sum(is_dash, axis=1)
    dash2 = jnp.argmax(is_dash & (jdx[None, :] > 4), axis=1).astype(jnp.int32)
    year, y_ok = field(jnp.zeros_like(date_len),
                       jnp.full_like(date_len, 4))
    month, m_ok = field(jnp.full_like(date_len, 5), dash2)
    day, d_ok = field(dash2 + 1, date_len)
    dash_ok = (
        (n_dash == 2)
        & is_dash[:, 4]
        & (dash2 > 5) & (dash2 <= 7)
        & (date_len - dash2 >= 2) & (date_len - dash2 <= 3)
        & (date_len >= 8) & (date_len <= 10)
    )
    month_ok = (month >= 1) & (month <= 12)
    leap = ((year % 4 == 0) & (year % 100 != 0)) | (year % 400 == 0)
    dim = _DAYS_IN_MONTH[jnp.clip(month, 0, 12)]
    dim = jnp.where((month == 2) & leap, 29, dim)
    day_ok = (day >= 1) & (day <= dim)
    ok = dash_ok & y_ok & m_ok & d_ok & month_ok & day_ok
    return _days_from_civil(year, month, day), ok


@func_range("string_to_date")
def string_to_date(col: Column) -> Column:
    """STRING 'yyyy-[M]M-[d]d' -> TIMESTAMP_DAYS (Spark date cast):
    leading/trailing whitespace trimmed (Spark's UTF8String.trim), then
    exactly a 4-digit year and 1-2 digit month/day with real calendar
    validation (month range, day-in-month, leap years). Anything else is
    NULL, the non-ANSI Spark cast posture. (Spark's shorter forms —
    'yyyy', 'yyyy-[M]M', trailing 'T...' — are not accepted yet.)"""
    if not col.dtype.is_string:
        raise TypeError("string_to_date requires a STRING column")
    mat, present, lengths, judgeable = _trimmed_matrix(col, max_len=16)
    days, ok = _parse_civil_date(mat, present, lengths)
    ok = ok & col.valid_mask() & judgeable & (lengths <= 10)
    return Column(
        t.TIMESTAMP_DAYS, jnp.where(ok, days, 0).astype(jnp.int32), ok
    )


@func_range("string_to_timestamp")
def string_to_timestamp(col: Column) -> Column:
    """STRING 'yyyy-[M]M-[d]d[ |T][H]H:[m]m:[s]s[.fraction]' ->
    TIMESTAMP_MICROSECONDS (UTC; Spark cast without zone suffixes). A bare
    date reads as midnight; fractions carry up to 6 digits (micros —
    longer fractions are NULL rather than silently truncated)."""
    if not col.dtype.is_string:
        raise TypeError("string_to_timestamp requires a STRING column")
    mat, present, lengths, judgeable = _trimmed_matrix(col, max_len=32)
    w = mat.shape[1]
    jdx = jnp.arange(w, dtype=jnp.int32)
    # the date/time separator: first ' ' or 'T' within the trimmed row
    sep_mask = present & ((mat == ord(" ")) | (mat == ord("T")))
    has_sep = jnp.any(sep_mask, axis=1)
    sep = jnp.where(
        has_sep, jnp.argmax(sep_mask, axis=1), lengths
    ).astype(jnp.int32)
    days, date_ok = _parse_civil_date(mat, present, sep)

    in_time = present & (jdx[None, :] > sep[:, None])
    _is_digit, field = _digit_field(mat, in_time)
    is_colon = in_time & (mat == ord(":"))
    n_colon = jnp.sum(is_colon, axis=1)
    c1 = jnp.where(jnp.any(is_colon, axis=1),
                   jnp.argmax(is_colon, axis=1), w).astype(jnp.int32)
    after_c1 = is_colon & (jdx[None, :] > c1[:, None])
    c2 = jnp.where(jnp.any(after_c1, axis=1),
                   jnp.argmax(after_c1, axis=1), w).astype(jnp.int32)
    dot_mask = in_time & (mat == ord("."))
    has_dot = jnp.any(dot_mask, axis=1)
    dot = jnp.where(has_dot, jnp.argmax(dot_mask, axis=1),
                    lengths).astype(jnp.int32)

    hh, h_ok = field(sep + 1, c1)
    mm, mi_ok = field(c1 + 1, c2)
    ss, s_ok = field(c2 + 1, jnp.minimum(dot, lengths))
    frac_digits = lengths - dot - 1
    fr, f_ok = field(dot + 1, lengths)
    # scale the fraction to microseconds by its digit count
    fscale = 10 ** jnp.clip(6 - frac_digits, 0, 6).astype(jnp.int64)
    micros_frac = jnp.where(has_dot, fr.astype(jnp.int64) * fscale, 0)
    f_ok = jnp.where(
        has_dot, f_ok & (frac_digits >= 1) & (frac_digits <= 6), True
    )

    def width_ok(lo, hi, wmin, wmax):
        width = hi - lo
        return (width >= wmin) & (width <= wmax)

    time_shape_ok = (
        (n_colon == 2)
        & width_ok(sep + 1, c1, 1, 2)
        & width_ok(c1 + 1, c2, 1, 2)
        & width_ok(c2 + 1, jnp.minimum(dot, lengths), 1, 2)
        & h_ok & mi_ok & s_ok & f_ok
        & (hh >= 0) & (hh <= 23) & (mm >= 0) & (mm <= 59)
        & (ss >= 0) & (ss <= 59)
    )
    time_micros = (
        (hh.astype(jnp.int64) * 3600 + mm.astype(jnp.int64) * 60
         + ss.astype(jnp.int64)) * 1_000_000 + micros_frac
    )
    time_value = jnp.where(has_sep, time_micros, 0)
    time_valid = jnp.where(has_sep, time_shape_ok, True)

    ok = col.valid_mask() & judgeable & date_ok & time_valid
    micros = days.astype(jnp.int64) * 86_400_000_000 + time_value
    return Column(
        t.TIMESTAMP_MICROSECONDS, jnp.where(ok, micros, 0), ok
    )


@func_range("date_to_string")
def date_to_string(col: Column) -> Column:
    """TIMESTAMP_DAYS -> STRING 'yyyy-MM-dd' (zero-padded). Years outside
    [0, 9999] render with a sign ('-0044-03-15', '+10000-01-01') rather
    than nulling a valid row — a non-null date always formats."""
    if col.dtype.type_id != TypeId.TIMESTAMP_DAYS:
        raise TypeError("date_to_string requires a TIMESTAMP_DAYS column")
    y, m, d = _civil_from_days(col.data)
    ok = np.asarray(col.valid_mask())
    y = np.asarray(y)
    m = np.asarray(m)
    d = np.asarray(d)

    def fmt(yy, mm, dd):
        if yy < 0:
            return ("-%04d-%02d-%02d" % (-yy, mm, dd)).encode()
        if yy > 9999:
            return ("+%d-%02d-%02d" % (yy, mm, dd)).encode()
        return ("%04d-%02d-%02d" % (yy, mm, dd)).encode()

    pieces = [
        fmt(yy, mm, dd) if v else b""
        for yy, mm, dd, v in zip(y, m, d, ok)
    ]
    return _column_from_pieces(pieces, ok, "date_to_string")


@func_range("string_to_boolean")
def string_to_boolean(col: Column) -> Column:
    """STRING -> BOOL8 (Spark cast): case-insensitive t/true/y/yes/1 and
    f/false/n/no/0, whitespace-trimmed; anything else is NULL."""
    if not col.dtype.is_string:
        raise TypeError("string_to_boolean requires a STRING column")
    mat, present, lengths, judgeable = _trimmed_matrix(col, max_len=8)
    lower = jnp.where(
        present & (mat >= ord("A")) & (mat <= ord("Z")), mat + 32, mat
    )

    def is_word(word: bytes) -> jnp.ndarray:
        ok = lengths == len(word)
        for i, b in enumerate(word):
            ok = ok & (lower[:, i] == b)
        return ok

    truthy = (is_word(b"t") | is_word(b"true") | is_word(b"y")
              | is_word(b"yes") | is_word(b"1"))
    falsy = (is_word(b"f") | is_word(b"false") | is_word(b"n")
             | is_word(b"no") | is_word(b"0"))
    ok = col.valid_mask() & judgeable & (truthy | falsy)
    return Column(t.BOOL8, truthy.astype(jnp.uint8), ok)


# ---- float -> string -------------------------------------------------------


def _java_float_repr(v, float32: bool) -> bytes:
    """One float as Java Double.toString/Float.toString renders it — the
    Spark ``cast(double as string)`` surface: shortest digits that
    round-trip at the column's width, plain decimal for 1e-3 <= |v| < 1e7
    (always one fractional digit), otherwise d.dddE[-]ee scientific."""
    if np.isnan(v):
        return b"NaN"
    if np.isinf(v):
        return b"Infinity" if v > 0 else b"-Infinity"
    # dtype-aware shortest digits: numpy's unique repr is computed at the
    # value's own width, so pin the declared width here rather than trust
    # the caller's scalar type (a bare Python float would silently format
    # at float64 width)
    v = np.float32(v) if float32 else np.float64(v)
    s = np.format_float_scientific(v, unique=True)
    sign = b""
    if s.startswith("-"):
        sign = b"-"
        s = s[1:]
    mant, exp = s.split("e")
    digits = mant.replace(".", "").rstrip("0")
    if not digits:  # +/- zero
        return sign + b"0.0"
    p = int(exp) + 1  # value = 0.<digits> * 10**p
    if -2 <= p <= 7:  # 1e-3 <= |v| < 1e7: plain decimal
        if p <= 0:
            out = "0." + "0" * (-p) + digits
        elif p >= len(digits):
            out = digits + "0" * (p - len(digits)) + ".0"
        else:
            out = digits[:p] + "." + digits[p:]
    else:
        frac = digits[1:] or "0"
        out = digits[0] + "." + frac + "E" + str(p - 1)
    return sign + out.encode()


@func_range("float_to_string")
def float_to_string(col: Column) -> Column:
    """FLOAT32/FLOAT64 -> STRING with Java Double.toString semantics (the
    Spark cast surface; closes the COVERAGE.md float->string gap). Host
    assembly like every X->string cast."""
    if col.dtype.storage_dtype.kind != "f":
        raise TypeError("float_to_string requires a float column")
    float32 = col.dtype.type_id == TypeId.FLOAT32
    vals = np.asarray(col.data)
    valid = np.asarray(col.valid_mask())
    pieces = [
        _java_float_repr(v, float32) if ok else b""
        for v, ok in zip(vals, valid)
    ]
    return _column_from_pieces(pieces, valid, "float_to_string")
