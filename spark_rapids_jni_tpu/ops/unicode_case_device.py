"""Device Unicode case mapping over the padded char matrix.

Closes the COVERAGE known-gap "non-ASCII falls back to the host Unicode
engine": the overwhelmingly common case — BMP characters whose case
mapping is 1:1 and UTF-8-length-preserving (all of Latin-1/Extended,
Greek, Cyrillic, full-width forms, ...) — now runs fully on device as
byte-parallel table lookups; only rows containing a SPECIAL character
(1:N expansions like ß→SS, length-changing mappings like ı→I,
supplementary-plane chars, or invalid UTF-8) take the host engine, and
that eligibility is itself decided by one device reduction.

Design (everything is a per-position classify + LUT gather + shifted
select over the (n, W) byte matrix — the LIKE/regex engine cost model,
zero scatters):

* positions classify by lead byte: ASCII, 2-byte lead (0xC2-0xDF),
  3-byte lead (0xE0-0xEF), continuation, 4-byte lead (always special —
  supplementary-plane case pairs exist, e.g. Deseret);
* codepoints decode AT LEAD POSITIONS from the lead and its shifted
  continuations; a 64Ki-entry mapping LUT (built once on host from
  Python's str.upper/str.lower — the same Unicode simple+full case
  tables Java uses under Locale.ROOT) yields the mapped codepoint, and
  a parallel SPECIAL LUT marks codepoints whose full mapping is not
  representable in place (multi-char, length-changing, or
  locale-sensitive); Unicode guarantees simple case mappings never
  cross UTF-8 length classes except the marked specials, and the
  SPECIAL table is derived mechanically so the guarantee is checked,
  not assumed;
* output bytes re-encode in place: each position selects its byte from
  its own mapping (ASCII/lead) or its lead's re-encoded continuation
  bytes (shift + gather) — same-length mapping means the row's layout
  is untouched.

Reference analogue: cuDF's device case kernels (vendored capability,
SURVEY.md §2.2); the unicode_to_lower host path of the footer engine
(reference NativeParquetJni.cpp:45-77) is the same table-driven idea
one level up.
"""

from __future__ import annotations

import functools

import jax.numpy as jnp
import numpy as np

from spark_rapids_jni_tpu.utils.tracing import func_range

_BMP = 0x10000


def _utf8_len(cp: int) -> int:
    if cp < 0x80:
        return 1
    if cp < 0x800:
        return 2
    if cp < _BMP:
        return 3
    return 4


@functools.lru_cache(maxsize=2)
def _tables(to_upper: bool):
    """(mapped int32[65536], special bool[65536]) — built once on host.

    ``special`` marks codepoints whose case mapping cannot be applied
    in place: multi-character results (ß→SS), results outside the BMP,
    or results whose UTF-8 length differs from the input's.
    """
    mapped = np.arange(_BMP, dtype=np.int32)
    special = np.zeros(_BMP, dtype=bool)
    for cp in range(_BMP):
        ch = chr(cp)
        out = ch.upper() if to_upper else ch.lower()
        if out == ch:
            continue
        if len(out) != 1:
            special[cp] = True
            continue
        ocp = ord(out)
        if ocp >= _BMP or _utf8_len(ocp) != _utf8_len(cp):
            special[cp] = True
            continue
        mapped[cp] = ocp
    # surrogates are invalid in UTF-8 — mark special so malformed input
    # routes host (which raises/handles per Python semantics)
    special[0xD800:0xE000] = True
    if not to_upper:
        # U+03A3 GREEK CAPITAL SIGMA: the one context-dependent default
        # mapping in Unicode SpecialCasing (word-final Σ -> ς, else σ).
        # A positionless LUT cannot apply it — route rows containing Σ
        # to the host engine, which does.
        special[0x03A3] = True
    return mapped, special


@func_range("unicode_case_device")
def case_map_device(chars: jnp.ndarray, to_upper: bool):
    """(out_chars uint8[n, W], row_special bool[n]) — mapped bytes and a
    per-row flag for rows the device path cannot map faithfully (the
    dispatcher routes those to the host engine)."""
    mapped_np, special_np = _tables(to_upper)
    mapped = jnp.asarray(mapped_np)
    special = jnp.asarray(special_np)
    n, w = chars.shape
    b = chars.astype(jnp.int32)
    zero = jnp.zeros((n, 1), jnp.int32)
    b1 = jnp.concatenate([b[:, 1:], zero], axis=1)   # byte at i+1
    b2 = jnp.concatenate([b[:, 2:], zero, zero], axis=1)

    ascii_ = b < 0x80
    cont = (b >= 0x80) & (b < 0xC0)
    lead2 = (b >= 0xC2) & (b < 0xE0)
    lead3 = (b >= 0xE0) & (b < 0xF0)
    bad_lead = ((b >= 0xC0) & (b < 0xC2)) | (b >= 0xF0)

    cont1_ok = (b1 >= 0x80) & (b1 < 0xC0)
    cont2_ok = (b2 >= 0x80) & (b2 < 0xC0)
    # structural validity: every lead has its continuations, every
    # continuation has a lead at the right offset
    prev_lead2 = jnp.concatenate([zero.astype(bool), lead2[:, :-1]], axis=1)
    prev_lead3 = jnp.concatenate([zero.astype(bool), lead3[:, :-1]], axis=1)
    prev2_lead3 = jnp.concatenate(
        [jnp.zeros((n, 2), bool), lead3[:, :-2]], axis=1)
    prev_cont = jnp.concatenate([zero.astype(bool), cont[:, :-1]], axis=1)
    cont_claimed = (prev_lead2 | prev_lead3
                    | (prev_cont & prev2_lead3))
    malformed = ((lead2 & ~cont1_ok)
                 | (lead3 & ~(cont1_ok & cont2_ok))
                 | (cont & ~cont_claimed)
                 | bad_lead)

    cp2 = ((b & 0x1F) << 6) | (b1 & 0x3F)
    cp3 = ((b & 0x0F) << 12) | ((b1 & 0x3F) << 6) | (b2 & 0x3F)
    # overlong encodings (cp3 < 0x800 in 3 bytes) are invalid
    overlong3 = lead3 & (cp3 < 0x800)
    cp = jnp.where(lead2, cp2, jnp.where(lead3, cp3, b))
    cp = jnp.clip(cp, 0, _BMP - 1)

    is_special = ((ascii_ | lead2 | lead3) & special[cp])
    row_special = jnp.any(
        is_special | malformed | overlong3, axis=1)

    m = mapped[cp]
    # re-encoded bytes at LEAD positions
    l2_b0 = 0xC0 | (m >> 6)
    l2_b1 = 0x80 | (m & 0x3F)
    l3_b0 = 0xE0 | (m >> 12)
    l3_b1 = 0x80 | ((m >> 6) & 0x3F)
    l3_b2 = 0x80 | (m & 0x3F)

    def shift1(x):
        return jnp.concatenate([zero, x[:, :-1]], axis=1)

    def shift2(x):
        return jnp.concatenate([jnp.zeros((n, 2), x.dtype), x[:, :-2]],
                               axis=1)

    out = jnp.where(ascii_, m, b)
    out = jnp.where(lead2, l2_b0, out)
    out = jnp.where(lead3, l3_b0, out)
    out = jnp.where(cont & prev_lead2, shift1(l2_b1), out)
    out = jnp.where(cont & prev_lead3, shift1(l3_b1), out)
    out = jnp.where(cont & prev_cont & prev2_lead3, shift2(l3_b2), out)
    return out.astype(jnp.uint8), row_special
