"""Bloom filter build/probe (north-star component: the reference family
ships xxhash64-based bloom filters for Spark runtime join pruning;
BASELINE.json north_star lists "xxhash64/bloom-filter").

TPU-first design: the filter lives on device as ONE BYTE PER BIT (uint8[m])
rather than a packed bitset. Packed words would force read-modify-write
bit twiddling through scatters; byte-per-bit makes build a single
``scatter-max`` (associative, deterministic, duplicate-safe — the role
CUDA's atomicOr plays in the reference family's kernels) and probe a pure
gather + AND-reduce. At Spark's default FPP the memory cost (8x) is a few
MB per filter — noise next to HBM capacity, and worth it for a one-scatter
build. ``to_packed``/``from_packed`` convert to the little-endian packed
form for interchange (e.g. with Spark's serialized BloomFilterImpl).

Bit placement replicates Spark's ``BloomFilterImpl.putLong`` exactly so
``to_packed``/``from_packed`` interchange with Spark-serialized filters:
h1 = Murmur3_x86_32.hashLong(item, 0), h2 = Murmur3_x86_32.hashLong(item, h1),
then for i in 1..k: combined = int32(h1 + i*h2), bitwise-NOT if negative,
bit = combined % m. Spark's SQL runtime-filter path (BloomFilterAggregate /
might_contain) additionally pre-hashes the column value with
xxhash64(seed=42) before putLong — ``spark_prehash`` / the ``*_spark``
wrappers provide that composition.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax.numpy as jnp
import numpy as np

from spark_rapids_jni_tpu.columnar.bitmask import pack_validity, unpack_validity
from spark_rapids_jni_tpu.ops.hash import xxhash64_long
from spark_rapids_jni_tpu.runtime.resilience import MalformedInputError
from spark_rapids_jni_tpu.telemetry.events import REGISTRY
from spark_rapids_jni_tpu.utils.tracing import func_range

_MM3_C1 = np.uint32(0xCC9E2D51)
_MM3_C2 = np.uint32(0x1B873593)


def _rotl32(x: jnp.ndarray, r: int) -> jnp.ndarray:
    return (x << np.uint32(r)) | (x >> np.uint32(32 - r))


def murmur3_hash_long(value: jnp.ndarray, seed) -> jnp.ndarray:
    """Vectorized Murmur3_x86_32.hashLong: two 4-byte little-endian blocks
    (low word then high word), finalized with length 8. Returns uint32[n]."""
    v = value.astype(jnp.uint64)
    low = (v & jnp.uint64(0xFFFFFFFF)).astype(jnp.uint32)
    high = (v >> jnp.uint64(32)).astype(jnp.uint32)
    h1 = jnp.broadcast_to(jnp.asarray(seed, jnp.uint32), low.shape)
    for word in (low, high):
        k1 = _rotl32(word * _MM3_C1, 15) * _MM3_C2
        h1 = _rotl32(h1 ^ k1, 13) * np.uint32(5) + np.uint32(0xE6546B64)
    h1 = h1 ^ np.uint32(8)  # fmix(h1, length=8)
    h1 = (h1 ^ (h1 >> np.uint32(16))) * np.uint32(0x85EBCA6B)
    h1 = (h1 ^ (h1 >> np.uint32(13))) * np.uint32(0xC2B2AE35)
    return h1 ^ (h1 >> np.uint32(16))


def spark_prehash(values: jnp.ndarray) -> jnp.ndarray:
    """BloomFilterAggregate's value hash: xxhash64(long value, seed=42)."""
    seeds = jnp.full(values.shape, np.uint64(42), dtype=jnp.uint64)
    return xxhash64_long(values.astype(jnp.int64), seeds).astype(jnp.int64)


@dataclass
class BloomFilter:
    bits: jnp.ndarray  # uint8[num_bits], one byte per bit (0/1)
    num_hashes: int

    @property
    def num_bits(self) -> int:
        return int(self.bits.shape[0])

    @classmethod
    def empty(cls, num_bits: int, num_hashes: int = 3) -> "BloomFilter":
        if num_bits <= 0:
            raise ValueError("num_bits must be positive")
        return cls(jnp.zeros((num_bits,), dtype=jnp.uint8), num_hashes)

    @classmethod
    def optimal(cls, expected_items: int, fpp: float = 0.03) -> "BloomFilter":
        """Size like Spark's BloomFilter.create: m = -n ln p / (ln 2)^2,
        k = max(1, round(m/n * ln 2))."""
        m, k = optimal_params(expected_items, fpp)
        return cls.empty(m, k)

    def to_packed(self) -> jnp.ndarray:
        """Little-endian packed uint8[ceil(m/8)] for interchange."""
        return pack_validity(self.bits.astype(jnp.bool_))

    @classmethod
    def from_packed(cls, packed: jnp.ndarray, num_bits: int,
                    num_hashes: int) -> "BloomFilter":
        return cls(
            unpack_validity(packed, num_bits).astype(jnp.uint8), num_hashes
        )


def optimal_params(expected_items: int, fpp: float = 0.03) -> tuple[int, int]:
    """(num_bits, num_hashes) for the Spark BloomFilter.create sizing —
    exposed separately so the runtime-filter planner can size a filter
    (and fold the size into fingerprints) without allocating bits."""
    n = max(int(expected_items), 1)
    m = max(int(-n * np.log(fpp) / (np.log(2) ** 2)), 64)
    k = max(1, int(round(m / n * np.log(2))))
    return m, k


def _bit_positions(values: jnp.ndarray, num_bits: int, num_hashes: int):
    """(n, k) bit indexes — BloomFilterImpl.putLong's double hashing."""
    h1 = murmur3_hash_long(values, np.uint32(0))
    h2 = murmur3_hash_long(values, h1)
    i = jnp.arange(1, num_hashes + 1, dtype=jnp.uint32)
    combined = (h1[:, None] + i[None, :] * h2[:, None]).astype(jnp.int32)
    combined = jnp.where(combined < 0, ~combined, combined)
    return combined % jnp.int32(num_bits)


def _put_bits(bits: jnp.ndarray, values: jnp.ndarray,
              valid: jnp.ndarray | None, num_bits: int,
              num_hashes: int) -> jnp.ndarray:
    pos = _bit_positions(values.astype(jnp.int64), num_bits, num_hashes)
    if valid is not None:
        # route invalid rows' updates out of range; scatter mode="drop"
        pos = jnp.where(valid[:, None], pos, num_bits)
    return bits.at[pos.reshape(-1)].max(jnp.uint8(1), mode="drop")


@func_range("bloom_filter_put")
def bloom_put(
    bf: BloomFilter,
    values: jnp.ndarray,
    valid: jnp.ndarray | None = None,
) -> BloomFilter:
    """Insert int64 values (null rows skipped). Functional update — under
    jit XLA donates/aliases the bitset buffer.

    Routed through the bucketed dispatch cache: the value column is the
    row group (padded rows masked out via ``row_valids``, exactly like
    null rows), the bitset rides as an aux arg, and (num_bits,
    num_hashes) are statics so differently-shaped filters never share an
    executable. Under tracers (e.g. inside a fused region) dispatch
    falls back to the inline trace — same bits either way."""
    from spark_rapids_jni_tpu.runtime import dispatch

    vld = valid if valid is not None \
        else jnp.ones(values.shape, dtype=jnp.bool_)
    num_bits, num_hashes = bf.num_bits, bf.num_hashes

    def _fn(row_args, aux_args, row_valids):
        (vals, v), = row_args
        (bits,) = aux_args
        rv = row_valids[0] if row_valids is not None else None
        keep = v if rv is None else (v & rv)
        return _put_bits(bits, vals, keep, num_bits, num_hashes)

    bits = dispatch.call(
        "bloom.put", _fn, ((values, vld),), (bf.bits,),
        statics=(num_bits, num_hashes), slice_rows=False)
    return BloomFilter(bits, num_hashes)


@func_range("bloom_filter_might_contain")
def bloom_might_contain(bf: BloomFilter, values: jnp.ndarray) -> jnp.ndarray:
    """bool[n]: definitely-absent rows are False.

    Dispatch-routed like :func:`bloom_put`; the bucket-padded tail rows
    gather in-range garbage that ``slice_rows`` trims away."""
    from spark_rapids_jni_tpu.runtime import dispatch

    num_bits, num_hashes = bf.num_bits, bf.num_hashes

    def _fn(row_args, aux_args, row_valids):
        (vals,), = row_args
        (bits,) = aux_args
        pos = _bit_positions(vals.astype(jnp.int64), num_bits, num_hashes)
        return jnp.all(bits[pos] == 1, axis=1)

    return dispatch.call(
        "bloom.might_contain", _fn, ((values,),), (bf.bits,),
        statics=(num_bits, num_hashes))


def bloom_merge(a: BloomFilter, b: BloomFilter) -> BloomFilter:
    """Union — how Spark combines per-task filters.

    Two filters only OR meaningfully when they agree on BOTH geometry
    parameters: same num_bits AND same num_hashes (equal bit counts with
    different hash counts place bits incompatibly, and a silent OR would
    yield a filter that drops rows its inputs would keep). Disagreement
    is classified :class:`MalformedInputError` — the filters are wrong,
    not the engine — and counted under ``rtfilter.merge_mismatch``."""
    if a.num_bits != b.num_bits or a.num_hashes != b.num_hashes:
        REGISTRY.counter("rtfilter.merge_mismatch").inc()
        raise MalformedInputError(
            f"bloom merge geometry mismatch: "
            f"(num_bits={a.num_bits}, num_hashes={a.num_hashes}) vs "
            f"(num_bits={b.num_bits}, num_hashes={b.num_hashes})")
    return BloomFilter(jnp.maximum(a.bits, b.bits), a.num_hashes)


def bloom_put_spark(
    bf: BloomFilter,
    values: jnp.ndarray,
    valid: jnp.ndarray | None = None,
) -> BloomFilter:
    """BloomFilterAggregate semantics: xxhash64(value, 42) then putLong."""
    return bloom_put(bf, spark_prehash(values), valid)


def bloom_might_contain_spark(bf: BloomFilter, values: jnp.ndarray) -> jnp.ndarray:
    """Spark SQL might_contain: pre-hash then mightContainLong."""
    return bloom_might_contain(bf, spark_prehash(values))
