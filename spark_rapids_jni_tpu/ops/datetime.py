"""Datetime extraction and arithmetic over DATE/TIMESTAMP columns — the
cuDF datetime op family (vendored capability surface, SURVEY.md section
2.2) Spark lowers year()/month()/dayofmonth()/date_add()/datediff()/
last_day()/trunc() and friends to.

TPU-first design: the civil-calendar conversion (days since epoch ->
year/month/day) is pure branch-free integer arithmetic on the era/
day-of-era decomposition — elementwise VPU code with no lookup tables,
no data-dependent control flow, fully fusable by XLA. Timestamps reduce
to days + intra-day remainder with floor-division semantics correct for
negative (pre-1970) values.

Null semantics: every function is null-in -> null-out per row (Spark).
"""

from __future__ import annotations

import jax.numpy as jnp

from spark_rapids_jni_tpu.columnar import Column
from spark_rapids_jni_tpu.ops._calendar import civil_from_days, days_from_civil
from spark_rapids_jni_tpu.types import DType, TypeId
from spark_rapids_jni_tpu.utils.tracing import func_range

_DAY_US = 86_400_000_000

_TS_TO_DAY_DIV = {
    TypeId.TIMESTAMP_DAYS: 1,
    TypeId.TIMESTAMP_SECONDS: 86_400,
    TypeId.TIMESTAMP_MILLISECONDS: 86_400_000,
    TypeId.TIMESTAMP_MICROSECONDS: _DAY_US,
    TypeId.TIMESTAMP_NANOSECONDS: 86_400_000_000_000,
}


def _days_since_epoch(col: Column) -> jnp.ndarray:
    """int64 civil days since 1970-01-01, floor division (pre-epoch
    instants land on the correct earlier day)."""
    div = _TS_TO_DAY_DIV.get(col.dtype.type_id)
    if div is None:
        raise NotImplementedError(
            f"datetime op needs a DATE/TIMESTAMP column, got {col.dtype}")
    d = col.data.astype(jnp.int64)
    return d if div == 1 else jnp.floor_divide(d, div)


def _int_out(col: Column, vals: jnp.ndarray, dtype=None) -> Column:
    dt = dtype or DType(TypeId.INT32)
    return Column(dt, vals.astype(dt.jnp_dtype), col.valid_mask())


@func_range("dt_year")
def year(col: Column) -> Column:
    """Civil year (Spark year())."""
    y, _, _ = civil_from_days(_days_since_epoch(col))
    return _int_out(col, y)


@func_range("dt_month")
def month(col: Column) -> Column:
    """Civil month 1-12 (Spark month())."""
    _, m, _ = civil_from_days(_days_since_epoch(col))
    return _int_out(col, m)


@func_range("dt_day")
def day(col: Column) -> Column:
    """Day of month 1-31 (Spark dayofmonth())."""
    _, _, d = civil_from_days(_days_since_epoch(col))
    return _int_out(col, d)


@func_range("dt_day_of_week")
def day_of_week(col: Column) -> Column:
    """ISO day of week, Monday=1..Sunday=7 (1970-01-01 was a Thursday)."""
    z = _days_since_epoch(col)
    return _int_out(col, jnp.mod(z + 3, 7) + 1)


@func_range("dt_day_of_week_spark")
def day_of_week_spark(col: Column) -> Column:
    """Spark dayofweek(): Sunday=1..Saturday=7."""
    z = _days_since_epoch(col)
    return _int_out(col, jnp.mod(z + 4, 7) + 1)


@func_range("dt_day_of_year")
def day_of_year(col: Column) -> Column:
    """1-based ordinal day within the year (Spark dayofyear())."""
    z = _days_since_epoch(col)
    y, _, _ = civil_from_days(z)
    jan1 = days_from_civil(y, jnp.ones_like(y), jnp.ones_like(y))
    return _int_out(col, z - jan1 + 1)


@func_range("dt_quarter")
def quarter(col: Column) -> Column:
    _, m, _ = civil_from_days(_days_since_epoch(col))
    return _int_out(col, jnp.floor_divide(m - 1, 3) + 1)


@func_range("dt_last_day")
def last_day(col: Column) -> Column:
    """Last day of the instant's month, as TIMESTAMP_DAYS (Spark
    last_day())."""
    y, m, _ = civil_from_days(_days_since_epoch(col))
    ny = y + (m == 12)
    nm = jnp.where(m == 12, 1, m + 1)
    first_next = days_from_civil(ny, nm, jnp.ones_like(nm))
    return _int_out(col, first_next - 1, DType(TypeId.TIMESTAMP_DAYS))


@func_range("dt_date_add")
def date_add(col: Column, days: int | jnp.ndarray) -> Column:
    """DATE +/- integer days (Spark date_add / date_sub via negative)."""
    if col.dtype.type_id != TypeId.TIMESTAMP_DAYS:
        raise NotImplementedError("date_add needs a TIMESTAMP_DAYS column")
    return _int_out(col, col.data.astype(jnp.int64) + days,
                    DType(TypeId.TIMESTAMP_DAYS))


@func_range("dt_datediff")
def datediff(end: Column, start: Column) -> Column:
    """end - start in whole civil days (Spark datediff)."""
    d = _days_since_epoch(end) - _days_since_epoch(start)
    return Column(DType(TypeId.INT32), d.astype(jnp.int32),
                  end.valid_mask() & start.valid_mask())


@func_range("dt_add_months")
def add_months(col: Column, n: int) -> Column:
    """Calendar-aware month shift: day-of-month clamps to the target
    month's length (Spark add_months: Jan 31 + 1 month = Feb 28/29)."""
    if col.dtype.type_id != TypeId.TIMESTAMP_DAYS:
        raise NotImplementedError(
            "add_months needs a TIMESTAMP_DAYS column")
    y, m, d = civil_from_days(_days_since_epoch(col))
    tot = y * 12 + (m - 1) + n
    ny = jnp.floor_divide(tot, 12)
    nm = tot - ny * 12 + 1
    # clamp to the target month's last day
    ny2 = ny + (nm == 12)
    nm2 = jnp.where(nm == 12, 1, nm + 1)
    month_len = (days_from_civil(ny2, nm2, jnp.ones_like(nm))
                 - days_from_civil(ny, nm, jnp.ones_like(nm)))
    out = days_from_civil(ny, nm, jnp.minimum(d, month_len))
    return _int_out(col, out, DType(TypeId.TIMESTAMP_DAYS))


_TRUNC_UNITS = ("year", "quarter", "month", "week")


@func_range("dt_trunc")
def trunc(col: Column, unit: str) -> Column:
    """Truncate to the start of year/quarter/month/ISO week (Spark
    trunc())."""
    unit = unit.lower()
    if unit not in _TRUNC_UNITS:
        raise ValueError(f"trunc unit must be one of {_TRUNC_UNITS}")
    z = _days_since_epoch(col)
    if unit == "week":  # back to Monday
        out = z - jnp.mod(z + 3, 7)
    else:
        y, m, _ = civil_from_days(z)
        if unit == "year":
            m = jnp.ones_like(m)
        elif unit == "quarter":
            m = (jnp.floor_divide(m - 1, 3) * 3) + 1
        out = days_from_civil(y, m, jnp.ones_like(m))
    return _int_out(col, out, DType(TypeId.TIMESTAMP_DAYS))


def _intraday(col: Column, unit_per_day: int) -> jnp.ndarray:
    """Units into the civil day, floor semantics (pre-epoch instants get
    the positive intra-day remainder)."""
    div = _TS_TO_DAY_DIV.get(col.dtype.type_id)
    if div is None or div == 1:
        raise NotImplementedError(
            f"time-of-day op needs a sub-day TIMESTAMP column, got "
            f"{col.dtype}")
    d = col.data.astype(jnp.int64)
    rem = d - jnp.floor_divide(d, div) * div     # [0, div)
    return jnp.floor_divide(rem * unit_per_day, div)


@func_range("dt_hour")
def hour(col: Column) -> Column:
    """Spark hour(): 0-23 within the instant's civil day."""
    return _int_out(col, _intraday(col, 24))


@func_range("dt_minute")
def minute(col: Column) -> Column:
    return _int_out(col, jnp.mod(_intraday(col, 24 * 60), 60))


@func_range("dt_second")
def second(col: Column) -> Column:
    return _int_out(col, jnp.mod(_intraday(col, 86_400), 60))


@func_range("dt_weekofyear")
def weekofyear(col: Column) -> Column:
    """Spark weekofyear(): ISO-8601 week number (1-53), branch-free.

    w = (doy - isodow + 10) / 7; w == 0 rolls into the previous year's
    last week, w == 53 rolls into week 1 when the year doesn't have 53
    ISO weeks (i.e. Jan 1 is not Thu and it's not a leap year starting
    Wed)."""
    z = _days_since_epoch(col)
    y, m, d = civil_from_days(z)
    jan1 = days_from_civil(y, jnp.ones_like(m), jnp.ones_like(d))
    doy = (z - jan1 + 1).astype(jnp.int64)       # 1-based
    isodow = jnp.mod(z + 3, 7) + 1               # 1=Mon..7=Sun
    w = jnp.floor_divide(doy - isodow + 10, 7)
    # w == 0: belongs to the previous ISO year's last week
    prev_jan1 = days_from_civil(y - 1, jnp.ones_like(m), jnp.ones_like(d))
    prev_len = jan1 - prev_jan1
    prev_doy = doy + prev_len
    w_prev = jnp.floor_divide(prev_doy - isodow + 10, 7)
    # w == 53: valid only when Dec 28 of y is still in week 53 (ISO long
    # year); otherwise it's week 1 of y+1
    dec28 = days_from_civil(y, jnp.full_like(m, 12), jnp.full_like(d, 28))
    dec28_dow = jnp.mod(dec28 + 3, 7) + 1
    dec28_doy = (dec28 - jan1 + 1).astype(jnp.int64)
    w_dec28 = jnp.floor_divide(dec28_doy - dec28_dow + 10, 7)
    out = jnp.where(w < 1, w_prev, jnp.where(w > w_dec28, 1, w))
    return _int_out(col, out)


@func_range("dt_months_between")
def months_between(end: Column, start: Column,
                   round_off: bool = True) -> Column:
    """Spark months_between(date1, date2): whole months plus a 31-day
    fractional remainder; exact integer when the days-of-month match or
    both are month-ends; rounded to 8 digits when ``round_off``.
    FLOAT64 output. Sub-day TIMESTAMP operands follow Spark's exact
    formula: the day-of-month comparison uses the civil DATE, and the
    fraction is (domDiff*86400 + secs1 - secs2) / (31*86400) with
    seconds TRUNCATED from the sub-second precision (Spark's
    MICROSECONDS.toSeconds). Mixed precisions are fine — both operands
    reduce to (civil day, intraday seconds)."""
    def _day_secs(c: Column):
        z = _days_since_epoch(c)
        if c.dtype.type_id == TypeId.TIMESTAMP_DAYS:
            return z, jnp.zeros_like(z)
        return z, _intraday(c, 86_400)

    z1, s1 = _day_secs(end)
    z2, s2 = _day_secs(start)
    y1, m1, d1 = civil_from_days(z1)
    y2, m2, d2 = civil_from_days(z2)
    months = ((y1 - y2) * 12 + (m1 - m2)).astype(jnp.float64)

    def _is_month_end(y, m, d, z):
        nxt = days_from_civil(
            y + jnp.floor_divide(m, 12),
            jnp.mod(m, 12) + 1, jnp.ones_like(d))
        return z == nxt - 1

    both_end = _is_month_end(y1, m1, d1, z1) & _is_month_end(y2, m2, d2, z2)
    same_dom = d1 == d2
    secs_diff = ((d1 - d2) * 86_400 + s1 - s2).astype(jnp.float64)
    frac = secs_diff / (31.0 * 86_400.0)
    out = jnp.where(same_dom | both_end, months, months + frac)
    if round_off:
        out = jnp.round(out * 1e8) / 1e8
    validity = end.valid_mask() & start.valid_mask()
    return Column(DType(TypeId.FLOAT64), out, validity)


_NEXT_DAY_NAMES = {
    # Spark's DateTimeUtils.getDayOfWeekFromString accepts 2-letter,
    # 3-letter, and full names
    "mo": 1, "mon": 1, "monday": 1, "tu": 2, "tue": 2, "tuesday": 2,
    "we": 3, "wed": 3, "wednesday": 3, "th": 4, "thu": 4, "thursday": 4,
    "fr": 5, "fri": 5, "friday": 5, "sa": 6, "sat": 6, "saturday": 6,
    "su": 7, "sun": 7, "sunday": 7,
}


@func_range("dt_next_day")
def next_day(col: Column, day_name: str) -> Column:
    """Spark next_day(date, dayOfWeek): the first date LATER than the
    input that falls on the given weekday."""
    key = day_name.strip().lower()
    if key not in _NEXT_DAY_NAMES:
        raise ValueError(f"unknown day-of-week name {day_name!r}")
    target = _NEXT_DAY_NAMES[key]                # 1=Mon..7=Sun
    z = _days_since_epoch(col)
    isodow = jnp.mod(z + 3, 7) + 1
    ahead = jnp.mod(target - isodow + 6, 7) + 1  # 1..7 strictly ahead
    return _int_out(col, z + ahead, DType(TypeId.TIMESTAMP_DAYS))
