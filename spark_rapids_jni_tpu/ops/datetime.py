"""Datetime extraction and arithmetic over DATE/TIMESTAMP columns — the
cuDF datetime op family (vendored capability surface, SURVEY.md section
2.2) Spark lowers year()/month()/dayofmonth()/date_add()/datediff()/
last_day()/trunc() and friends to.

TPU-first design: the civil-calendar conversion (days since epoch ->
year/month/day) is pure branch-free integer arithmetic on the era/
day-of-era decomposition — elementwise VPU code with no lookup tables,
no data-dependent control flow, fully fusable by XLA. Timestamps reduce
to days + intra-day remainder with floor-division semantics correct for
negative (pre-1970) values.

Null semantics: every function is null-in -> null-out per row (Spark).
"""

from __future__ import annotations

import jax.numpy as jnp

from spark_rapids_jni_tpu.columnar import Column
from spark_rapids_jni_tpu.ops._calendar import civil_from_days, days_from_civil
from spark_rapids_jni_tpu.types import DType, TypeId
from spark_rapids_jni_tpu.utils.tracing import func_range

_DAY_US = 86_400_000_000

_TS_TO_DAY_DIV = {
    TypeId.TIMESTAMP_DAYS: 1,
    TypeId.TIMESTAMP_SECONDS: 86_400,
    TypeId.TIMESTAMP_MILLISECONDS: 86_400_000,
    TypeId.TIMESTAMP_MICROSECONDS: _DAY_US,
    TypeId.TIMESTAMP_NANOSECONDS: 86_400_000_000_000,
}


def _days_since_epoch(col: Column) -> jnp.ndarray:
    """int64 civil days since 1970-01-01, floor division (pre-epoch
    instants land on the correct earlier day)."""
    div = _TS_TO_DAY_DIV.get(col.dtype.type_id)
    if div is None:
        raise NotImplementedError(
            f"datetime op needs a DATE/TIMESTAMP column, got {col.dtype}")
    d = col.data.astype(jnp.int64)
    return d if div == 1 else jnp.floor_divide(d, div)


def _int_out(col: Column, vals: jnp.ndarray, dtype=None) -> Column:
    dt = dtype or DType(TypeId.INT32)
    return Column(dt, vals.astype(dt.jnp_dtype), col.valid_mask())


@func_range("dt_year")
def year(col: Column) -> Column:
    """Civil year (Spark year())."""
    y, _, _ = civil_from_days(_days_since_epoch(col))
    return _int_out(col, y)


@func_range("dt_month")
def month(col: Column) -> Column:
    """Civil month 1-12 (Spark month())."""
    _, m, _ = civil_from_days(_days_since_epoch(col))
    return _int_out(col, m)


@func_range("dt_day")
def day(col: Column) -> Column:
    """Day of month 1-31 (Spark dayofmonth())."""
    _, _, d = civil_from_days(_days_since_epoch(col))
    return _int_out(col, d)


@func_range("dt_day_of_week")
def day_of_week(col: Column) -> Column:
    """ISO day of week, Monday=1..Sunday=7 (1970-01-01 was a Thursday)."""
    z = _days_since_epoch(col)
    return _int_out(col, jnp.mod(z + 3, 7) + 1)


@func_range("dt_day_of_week_spark")
def day_of_week_spark(col: Column) -> Column:
    """Spark dayofweek(): Sunday=1..Saturday=7."""
    z = _days_since_epoch(col)
    return _int_out(col, jnp.mod(z + 4, 7) + 1)


@func_range("dt_day_of_year")
def day_of_year(col: Column) -> Column:
    """1-based ordinal day within the year (Spark dayofyear())."""
    z = _days_since_epoch(col)
    y, _, _ = civil_from_days(z)
    jan1 = days_from_civil(y, jnp.ones_like(y), jnp.ones_like(y))
    return _int_out(col, z - jan1 + 1)


@func_range("dt_quarter")
def quarter(col: Column) -> Column:
    _, m, _ = civil_from_days(_days_since_epoch(col))
    return _int_out(col, jnp.floor_divide(m - 1, 3) + 1)


@func_range("dt_last_day")
def last_day(col: Column) -> Column:
    """Last day of the instant's month, as TIMESTAMP_DAYS (Spark
    last_day())."""
    y, m, _ = civil_from_days(_days_since_epoch(col))
    ny = y + (m == 12)
    nm = jnp.where(m == 12, 1, m + 1)
    first_next = days_from_civil(ny, nm, jnp.ones_like(nm))
    return _int_out(col, first_next - 1, DType(TypeId.TIMESTAMP_DAYS))


@func_range("dt_date_add")
def date_add(col: Column, days: int | jnp.ndarray) -> Column:
    """DATE +/- integer days (Spark date_add / date_sub via negative)."""
    if col.dtype.type_id != TypeId.TIMESTAMP_DAYS:
        raise NotImplementedError("date_add needs a TIMESTAMP_DAYS column")
    return _int_out(col, col.data.astype(jnp.int64) + days,
                    DType(TypeId.TIMESTAMP_DAYS))


@func_range("dt_datediff")
def datediff(end: Column, start: Column) -> Column:
    """end - start in whole civil days (Spark datediff)."""
    d = _days_since_epoch(end) - _days_since_epoch(start)
    return Column(DType(TypeId.INT32), d.astype(jnp.int32),
                  end.valid_mask() & start.valid_mask())


@func_range("dt_add_months")
def add_months(col: Column, n: int) -> Column:
    """Calendar-aware month shift: day-of-month clamps to the target
    month's length (Spark add_months: Jan 31 + 1 month = Feb 28/29)."""
    if col.dtype.type_id != TypeId.TIMESTAMP_DAYS:
        raise NotImplementedError(
            "add_months needs a TIMESTAMP_DAYS column")
    y, m, d = civil_from_days(_days_since_epoch(col))
    tot = y * 12 + (m - 1) + n
    ny = jnp.floor_divide(tot, 12)
    nm = tot - ny * 12 + 1
    # clamp to the target month's last day
    ny2 = ny + (nm == 12)
    nm2 = jnp.where(nm == 12, 1, nm + 1)
    month_len = (days_from_civil(ny2, nm2, jnp.ones_like(nm))
                 - days_from_civil(ny, nm, jnp.ones_like(nm)))
    out = days_from_civil(ny, nm, jnp.minimum(d, month_len))
    return _int_out(col, out, DType(TypeId.TIMESTAMP_DAYS))


_TRUNC_UNITS = ("year", "quarter", "month", "week")


@func_range("dt_trunc")
def trunc(col: Column, unit: str) -> Column:
    """Truncate to the start of year/quarter/month/ISO week (Spark
    trunc())."""
    unit = unit.lower()
    if unit not in _TRUNC_UNITS:
        raise ValueError(f"trunc unit must be one of {_TRUNC_UNITS}")
    z = _days_since_epoch(col)
    if unit == "week":  # back to Monday
        out = z - jnp.mod(z + 3, 7)
    else:
        y, m, _ = civil_from_days(z)
        if unit == "year":
            m = jnp.ones_like(m)
        elif unit == "quarter":
            m = (jnp.floor_divide(m - 1, 3) * 3) + 1
        out = days_from_civil(y, m, jnp.ones_like(m))
    return _int_out(col, out, DType(TypeId.TIMESTAMP_DAYS))
