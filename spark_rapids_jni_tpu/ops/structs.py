"""STRUCT-column utilities: build, unpack (Spark ``col.*``), and field
access.

The Parquet reader assembles STRUCT columns (Dremel nested assembly);
this module makes them usable in the relational core the way Spark
does — by star-expansion: ``unpack_struct`` replaces a STRUCT column
with its fields (struct-level nulls ANDed into every field, the
three-valued reading of ``null_struct.field``), after which the
existing sort/groupby/join machinery applies directly. A null struct
therefore sorts/groups exactly like a row whose every field is null —
Spark's observable ordering for struct keys with null structs.
"""

from __future__ import annotations

from typing import Sequence

import jax.numpy as jnp

from spark_rapids_jni_tpu.columnar import Column, Table
from spark_rapids_jni_tpu.types import DType, TypeId
from spark_rapids_jni_tpu.utils.tracing import func_range


def make_struct_column(fields: Sequence[Column],
                       validity=None) -> Column:
    """Host-side STRUCT builder over equal-length field columns."""
    if not fields:
        raise ValueError("STRUCT needs at least one field")
    n = fields[0].size
    for f in fields:
        if f.size != n:
            raise ValueError("STRUCT fields must have equal row counts")
    return Column(DType(TypeId.STRUCT),
                  jnp.zeros((n,), jnp.uint8), validity,
                  children=list(fields))


def struct_field(col: Column, idx: int) -> Column:
    """``struct.field`` access: the field column with the struct's nulls
    propagated (Spark: null_struct.field IS NULL)."""
    if col.dtype.type_id != TypeId.STRUCT:
        raise TypeError(f"struct_field needs a STRUCT column, got "
                        f"{col.dtype}")
    f = col.children[idx]
    if col.validity is None:
        return f
    sv = col.valid_mask()
    return Column(f.dtype, f.data, f.valid_mask() & sv,
                  chars=f.chars, children=f.children)


@func_range("unpack_struct")
def unpack_struct(table: Table, col_idx: int) -> Table:
    """Spark ``col.*`` star-expansion: replace the STRUCT column with
    its fields in place (struct nulls ANDed into each field). Nested
    structs expand one level; call again for deeper levels."""
    c = table.column(col_idx)
    if c.dtype.type_id != TypeId.STRUCT:
        raise TypeError(f"unpack_struct needs a STRUCT column, got "
                        f"{c.dtype}")
    fields = [struct_field(c, i) for i in range(len(c.children))]
    cols = (list(table.columns[:col_idx]) + fields
            + list(table.columns[col_idx + 1:]))
    return Table(cols)
