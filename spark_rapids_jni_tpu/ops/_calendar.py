"""Shared proleptic-Gregorian civil-calendar arithmetic (days since
1970-01-01 <-> year/month/day) — used by the string date casts and the
datetime op family, so the two can never disagree on a date.

The era decomposition: shift to 0000-03-01 so leap days land at the end
of each 400-year cycle, split into eras / years-of-era with the leap
corrections as integer divisions, and read month/day off the 5-month
cycle polynomial (153m+2)/5. Everything is int64 elementwise
``floor_divide`` — jnp's ``//`` is already floor division, so no
truncation compensation is needed (or wanted: compensating on top of
floor division would shift exact negative multiples by one era).
"""

from __future__ import annotations

import jax.numpy as jnp


def civil_from_days(z: jnp.ndarray):
    """days since 1970-01-01 -> (year, month, day), int64 each."""
    z = z.astype(jnp.int64) + 719_468  # days since 0000-03-01
    era = jnp.floor_divide(z, 146_097)
    doe = z - era * 146_097  # [0, 146096]
    yoe = jnp.floor_divide(
        doe - jnp.floor_divide(doe, 1460) + jnp.floor_divide(doe, 36_524)
        - jnp.floor_divide(doe, 146_096),
        365,
    )  # [0, 399]
    y = yoe + era * 400
    doy = doe - (365 * yoe + jnp.floor_divide(yoe, 4)
                 - jnp.floor_divide(yoe, 100))  # [0, 365]
    mp = jnp.floor_divide(5 * doy + 2, 153)  # March-based month [0, 11]
    d = doy - jnp.floor_divide(153 * mp + 2, 5) + 1  # [1, 31]
    m = mp + jnp.where(mp < 10, 3, -9)  # civil month [1, 12]
    return y + (mp >= 10), m, d


def days_from_civil(y: jnp.ndarray, m: jnp.ndarray,
                    d: jnp.ndarray) -> jnp.ndarray:
    """(year, month, day) -> int64 days since 1970-01-01; inverse of
    civil_from_days."""
    y = y.astype(jnp.int64)
    m = m.astype(jnp.int64)
    d = d.astype(jnp.int64)
    y = y - (m <= 2)
    era = jnp.floor_divide(y, 400)
    yoe = y - era * 400
    mp = m + jnp.where(m > 2, -3, 9)
    doy = jnp.floor_divide(153 * mp + 2, 5) + d - 1
    doe = 365 * yoe + jnp.floor_divide(yoe, 4) - jnp.floor_divide(
        yoe, 100) + doy
    return era * 146_097 + doe - 719_468
