"""Byte views of fixed-width device arrays, portable across backends.

The packed-row format (ops.row_conversion) and hashing (ops.hash) need the
little-endian byte image of every fixed-width type. On CPU/GPU that is one
``lax.bitcast_convert_type``. XLA:TPU's x64-rewriting pass (which emulates
64-bit types: s64/u64 as u32 pairs, f64 as an f32 pair) does NOT implement
bitcast-convert for 64-bit element types, so here:

  * <= 4-byte types: direct bitcast (supported everywhere);
  * 64-bit integers: arithmetic decomposition into (lo, hi) uint32 words —
    shift/mask/convert are all implemented by the emulation pass;
  * float64: exact bitcast where supported; elsewhere an arithmetic
    IEEE-754 encode/decode built on log2/floor/exact-power-of-two scaling
    (frexp/ldexp/signbit all lower to bitcasts and are unavailable there).
    TPU's f64 emulation carries ~49 mantissa bits (f32-pair) so the low
    bits of the emitted mantissa are zero there, and subnormals flush to
    signed zero — documented deviations; the byte layout is identical.

Byte order is little-endian in all cases (verified: u32 0x01020304 bitcasts
to [4,3,2,1]), matching the reference row format, which inherits x86/GPU
native order (reference row_conversion.cu:86-105 reinterprets row bytes as
int64 words directly).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from spark_rapids_jni_tpu.types import DType

# Backends whose XLA pipeline implements 64-bit bitcast-convert.
_BITCAST64_BACKENDS = ("cpu", "cuda", "gpu", "rocm")


def _has_bitcast64() -> bool:
    return jax.default_backend() in _BITCAST64_BACKENDS


def _u32_words_to_bytes(words: jnp.ndarray) -> jnp.ndarray:
    """(n, k) uint32 -> (n, 4k) uint8, little-endian."""
    n, k = words.shape
    return jax.lax.bitcast_convert_type(words, jnp.uint8).reshape(n, 4 * k)


def _bytes_to_u32_words(b: jnp.ndarray) -> jnp.ndarray:
    """(n, 4k) uint8 -> (n, k) uint32, little-endian."""
    n, nbytes = b.shape
    return jax.lax.bitcast_convert_type(
        b.reshape(n, nbytes // 4, 4), jnp.uint32
    )


def _exact_exp2(e: jnp.ndarray) -> jnp.ndarray:
    """Exactly 2.0**e for integer-valued float e in [-1074, 1023].

    ``jnp.exp2`` is an approximation (off by ulps for large |e|), which is
    not good enough for mantissa extraction. Binary exponentiation over the
    exact constants 2**(2**b) / 2**-(2**b) uses only exact multiplies:
    ascending-order partial products never leave the representable range
    when the final value is a normal number.
    """
    neg = e < 0
    mag = jnp.abs(e)
    out = jnp.ones_like(e)
    for b in range(11):  # 2**11 > 1074
        if b == 10:
            # 2**1024 overflows f64; bit 10 only occurs for negative e
            # (denormal decode, e = -1074), where 2**-1024 is representable.
            factor = jnp.where(neg, 2.0**-1024, 1.0)
        else:
            step = float(2 ** (2**b))
            factor = jnp.where(neg, 1.0 / step, step)
        out = out * jnp.where((mag.astype(jnp.int64) >> b) & 1 == 1, factor, 1.0)
    return out


def _f64_to_bits(x: jnp.ndarray) -> jnp.ndarray:
    """Arithmetic IEEE-754 binary64 encode: f64[n] -> u64[n] bit pattern.

    Uses only primitives the TPU x64-emulation pass implements: abs, log2,
    floor, exp2, division, comparisons (signbit/frexp bitcast internally and
    are unavailable there). Exponent from floor(log2) is verified and
    corrected by one step, so boundary values are safe even though log2 is
    approximate under the f32-pair emulation.
    """
    negative = jnp.where(x != 0.0, x < 0.0, 1.0 / x < 0.0)  # catches -0.0
    sign = negative.astype(jnp.uint64) << 63
    ax = jnp.abs(x)
    safe = jnp.where((ax == 0.0) | ~jnp.isfinite(ax), 1.0, ax)
    e = jnp.floor(jnp.log2(safe))
    m = safe / _exact_exp2(e)
    # one correction step against log2 rounding at power-of-two boundaries
    e = jnp.where(m >= 2.0, e + 1.0, jnp.where(m < 1.0, e - 1.0, e))
    m = safe / _exact_exp2(e)
    frac = jnp.round((m - 1.0) * (2.0**52))
    # mantissa rounding may carry into the exponent
    carry = frac >= 2.0**52
    e = jnp.where(carry, e + 1.0, e)
    frac = jnp.where(carry, 0.0, frac)
    biased = jnp.clip(e.astype(jnp.int64) + 1023, 0, 2046).astype(jnp.uint64)
    bits = sign | (biased << 52) | frac.astype(jnp.uint64)
    # Subnormals encode as signed zero, by contract: every backend that
    # needs this path flushes subnormal operands in arithmetic (XLA:CPU is
    # DAZ; TPU's f32-pair emulation cannot even represent them), so their
    # significand is unobservable here. The bitcast path is bit-exact.
    bits = jnp.where(ax < 2.0**-1022, sign, bits)
    bits = jnp.where(jnp.isinf(ax), sign | (jnp.uint64(2047) << 52), bits)
    bits = jnp.where(jnp.isnan(x), jnp.uint64(0x7FF8000000000000), bits)
    return bits


def _bits_to_f64(bits: jnp.ndarray) -> jnp.ndarray:
    """Arithmetic IEEE-754 binary64 decode: u64[n] -> f64[n].

    Exponents outside the emulated range under/overflow to 0/inf on TPU —
    consistent with that backend's own f64 value range.
    """
    sign = jnp.where((bits >> 63) != 0, -1.0, 1.0)
    biased = ((bits >> 52) & jnp.uint64(2047)).astype(jnp.int64)
    frac = (bits & jnp.uint64((1 << 52) - 1)).astype(jnp.float64)
    mant = 1.0 + frac * (2.0**-52)
    val = sign * mant * _exact_exp2((biased - 1023).astype(jnp.float64))
    # denormals: value = frac * 2**-1074 (0 on TPU's f32 exponent range)
    val = jnp.where(
        biased == 0, sign * frac * _exact_exp2(jnp.float64(-1074)), val
    )
    val = jnp.where((biased == 2047) & (frac == 0), sign * jnp.inf, val)
    val = jnp.where((biased == 2047) & (frac != 0), jnp.nan, val)
    return val


def to_bytes(data: jnp.ndarray, dtype: DType) -> jnp.ndarray:
    """(n,) fixed-width array -> (n, size) little-endian uint8 bytes.

    DECIMAL128 input is the int64[n, 2] limb pair (lo, hi little-endian);
    its byte image is the 16-byte little-endian two's-complement integer —
    lo limb bytes then hi limb bytes, exactly the __int128_t layout the
    reference's generic row path stores (row_conversion.cu:462-468)."""
    if dtype.is_decimal128:
        return jnp.concatenate(
            [_i64_to_bytes(data[:, 0]), _i64_to_bytes(data[:, 1])], axis=1)
    size = dtype.size_bytes
    if size == 1:
        return jax.lax.bitcast_convert_type(data, jnp.uint8).reshape(-1, 1)
    if size <= 4 or _has_bitcast64():
        return jax.lax.bitcast_convert_type(data, jnp.uint8)
    # 64-bit on a backend without 64-bit bitcast: go through u32 words.
    if dtype.storage_dtype == np.dtype(np.float64):
        u = _f64_to_bits(data)
    else:
        u = data.astype(jnp.uint64)
    return _i64_to_bytes(u)


def _i64_to_bytes(v: jnp.ndarray) -> jnp.ndarray:
    """(n,) 64-bit integer -> (n, 8) little-endian bytes, portable to
    backends without 64-bit bitcast-convert (the u32-word decomposition)."""
    if _has_bitcast64():
        return jax.lax.bitcast_convert_type(v, jnp.uint8).reshape(-1, 8)
    u = v.astype(jnp.uint64)
    lo = (u & jnp.uint64(0xFFFFFFFF)).astype(jnp.uint32)
    hi = (u >> 32).astype(jnp.uint32)
    return _u32_words_to_bytes(jnp.stack([lo, hi], axis=-1))


def _bytes_to_i64(b: jnp.ndarray) -> jnp.ndarray:
    """(n, 8) little-endian bytes -> (n,) int64, portable (u32 words)."""
    if _has_bitcast64():
        return jax.lax.bitcast_convert_type(b, jnp.int64)
    words = _bytes_to_u32_words(b)
    u = words[:, 0].astype(jnp.uint64) | (
        words[:, 1].astype(jnp.uint64) << 32
    )
    return u.astype(jnp.int64)


def from_bytes(b: jnp.ndarray, dtype: DType) -> jnp.ndarray:
    """(n, size) little-endian uint8 bytes -> (n,) of the storage dtype
    (int64[n, 2] limb pairs for DECIMAL128)."""
    if dtype.is_decimal128:
        return jnp.stack(
            [_bytes_to_i64(b[:, :8]), _bytes_to_i64(b[:, 8:])], axis=1)
    target = dtype.jnp_dtype
    size = dtype.size_bytes
    if size == 1:
        return jax.lax.bitcast_convert_type(b.reshape(-1), target)
    if size <= 4 or _has_bitcast64():
        return jax.lax.bitcast_convert_type(b, target)
    words = _bytes_to_u32_words(b)
    u = words[:, 0].astype(jnp.uint64) | (
        words[:, 1].astype(jnp.uint64) << 32
    )
    if dtype.storage_dtype == np.dtype(np.float64):
        return _bits_to_f64(u)
    return u.astype(target)
