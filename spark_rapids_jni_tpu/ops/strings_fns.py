"""String transform/function breadth over the padded device layout.

The cuDF strings API (vendored capability surface, SURVEY.md section 2.2)
carries the full Spark string-function family; this module adds the
widely-used transforms missing from ``ops.strings``: length, trim
variants, pad variants, concat/concat_ws, instr, repeat, reverse,
translate, and split (producing LIST<STRING> for the split+explode
pattern).

Design: everything is index arithmetic + ``take_along_axis`` gathers
over the (n, W) padded char matrix — no scatters, no per-row host work.
Char-level semantics (Spark counts CHARACTERS) are handled either
exactly on device (length, reverse, instr — continuation-byte masks) or
by an ASCII-device/host-Unicode split (lpad/rpad/initcap — the
upper/lower posture).

Null semantics are Spark's: unary transforms propagate nulls; concat is
null-if-any-null; concat_ws SKIPS nulls; split of null is null.
"""

from __future__ import annotations

from typing import NamedTuple, Sequence

import jax.numpy as jnp
import numpy as np

from spark_rapids_jni_tpu import types as t
from spark_rapids_jni_tpu.columnar import Column
from spark_rapids_jni_tpu.ops.strings import (
    is_padded,
    pad_strings,
)
from spark_rapids_jni_tpu.types import DType, TypeId
from spark_rapids_jni_tpu.utils.tracing import func_range

_CONT_MASK = jnp.uint8(0xC0)
_CONT_BITS = jnp.uint8(0x80)


def _padded(col: Column) -> Column:
    if not col.dtype.is_string:
        raise TypeError(f"string op needs a STRING column, got {col.dtype}")
    return col if is_padded(col) else pad_strings(col)


def _in_row(lens: jnp.ndarray, w: int) -> jnp.ndarray:
    return jnp.arange(w, dtype=jnp.int32)[None, :] < lens[:, None]


def _is_cont(chars: jnp.ndarray) -> jnp.ndarray:
    return (chars & _CONT_MASK) == _CONT_BITS


def _validity(col: Column):
    return col.valid_mask() if col.validity is not None else None


def _string_col(lens: jnp.ndarray, chars: jnp.ndarray, validity):
    return Column(DType(TypeId.STRING), lens.astype(jnp.int32), validity,
                  chars=chars)


@func_range("string_length")
def length(col: Column) -> Column:
    """Spark ``length``: CHARACTER count (UTF-8 aware)."""
    p = _padded(col)
    w = p.chars.shape[1]
    nch = jnp.sum(
        (_in_row(p.data, w) & ~_is_cont(p.chars)).astype(jnp.int32), axis=1)
    return Column(DType(TypeId.INT32), nch.astype(jnp.int32),
                  _validity(col))


def _trim_bounds(p: Column, charset: bytes, left: bool, right: bool):
    w = p.chars.shape[1]
    member = jnp.zeros_like(p.chars, dtype=jnp.bool_)
    for b in charset:
        member = member | (p.chars == jnp.uint8(b))
    in_row = _in_row(p.data, w)
    keep = ~member & in_row
    any_keep = jnp.any(keep, axis=1)
    if left:
        first = jnp.argmax(keep, axis=1).astype(jnp.int32)
        start = jnp.where(any_keep, first, p.data)
    else:
        start = jnp.zeros_like(p.data)
    if right:
        last = (w - 1 - jnp.argmax(keep[:, ::-1], axis=1)).astype(jnp.int32)
        end = jnp.where(any_keep, last + 1, start)
    else:
        end = p.data
    return start, jnp.maximum(end, start)


def _shift_rows(chars: jnp.ndarray, start: jnp.ndarray,
                new_len: jnp.ndarray) -> jnp.ndarray:
    w = chars.shape[1]
    idx = jnp.arange(w, dtype=jnp.int32)[None, :] + start[:, None]
    out = jnp.take_along_axis(chars, jnp.clip(idx, 0, w - 1), axis=1)
    return jnp.where(_in_row(new_len, w), out, jnp.uint8(0))


def _trim_impl(col: Column, charset: str, left: bool,
               right: bool) -> Column:
    cs = charset.encode()
    if any(b >= 0x80 for b in cs):
        raise NotImplementedError(
            "trim charset must be ASCII (multi-byte trim chars need the "
            "host path)")
    p = _padded(col)
    start, end = _trim_bounds(p, cs, left, right)
    new_len = end - start
    return _string_col(new_len, _shift_rows(p.chars, start, new_len),
                       _validity(col))


@func_range("string_trim")
def trim(col: Column, charset: str = " ") -> Column:
    """Spark ``trim``/``btrim``: strip leading+trailing charset chars."""
    return _trim_impl(col, charset, True, True)


@func_range("string_ltrim")
def ltrim(col: Column, charset: str = " ") -> Column:
    return _trim_impl(col, charset, True, False)


@func_range("string_rtrim")
def rtrim(col: Column, charset: str = " ") -> Column:
    return _trim_impl(col, charset, False, True)


def _ascii_only(p: Column) -> bool:
    """Host-synced check: every content byte < 0x80."""
    w = p.chars.shape[1]
    return bool(jnp.all(~_in_row(p.data, w) | (p.chars < 0x80)))


def _pad_impl(col: Column, width: int, pad: str, left: bool) -> Column:
    """lpad/rpad, CHARACTER-counted. ASCII data + ASCII pad rides the
    device path; anything else falls back to the host (the upper/lower
    posture)."""
    pb = pad.encode()
    p = _padded(col)
    if width <= 0:
        # Spark UTF8String.lpad/rpad with len <= 0 is always ''
        n = p.chars.shape[0]
        return _string_col(jnp.zeros((n,), jnp.int32),
                           jnp.zeros((n, 1), jnp.uint8), _validity(col))
    if not pb:
        # Spark with an empty pad string truncates but never extends
        pb = b"\x00"  # placeholder, never used when npad clamps to 0
        can_pad = False
    else:
        can_pad = True
    if any(b >= 0x80 for b in pb) or not _ascii_only(p):
        vals = col.to_pylist()  # handles both string layouts directly
        out = []
        for v in vals:
            if v is None:
                out.append(None)
            elif len(v) >= width:
                out.append(v[:width])
            elif not pad:
                out.append(v)
            else:
                need = width - len(v)
                fill = (pad * (need // len(pad) + 1))[:need]
                out.append(fill + v if left else v + fill)
        return pad_strings(Column.from_pylist(out, t.STRING))
    # ASCII device path: chars == bytes
    w = p.chars.shape[1]
    out_w = max(width, 1)
    lens = p.data
    trunc = jnp.minimum(lens, width)
    if can_pad:
        npad = jnp.maximum(width - lens, 0)
    else:
        npad = jnp.zeros_like(lens)
    out_len = jnp.where(lens >= width, trunc, trunc + npad)
    j = jnp.arange(out_w, dtype=jnp.int32)[None, :]
    pad_arr = jnp.asarray(np.frombuffer(pb, dtype=np.uint8))
    plen = len(pb)
    if left:
        in_pad = j < npad[:, None]
        src = jnp.clip(j - npad[:, None], 0, w - 1)
    else:
        in_pad = (j >= trunc[:, None]) & (j < out_len[:, None])
        src = jnp.clip(j, 0, w - 1)
    data = jnp.take_along_axis(
        p.chars[:, :w], src, axis=1) if w else jnp.zeros(
        (p.chars.shape[0], out_w), jnp.uint8)
    padj = (j % plen) if left else ((j - trunc[:, None]) % plen)
    pad_bytes = pad_arr[padj.astype(jnp.int32).reshape(-1)].reshape(
        padj.shape) if plen > 1 else jnp.broadcast_to(
        pad_arr[0], padj.shape)
    out = jnp.where(in_pad, pad_bytes, data)
    out = jnp.where(_in_row(out_len, out_w), out, jnp.uint8(0))
    return _string_col(out_len, out, _validity(col))


@func_range("string_lpad")
def lpad(col: Column, width: int, pad: str = " ") -> Column:
    return _pad_impl(col, width, pad, left=True)


@func_range("string_rpad")
def rpad(col: Column, width: int, pad: str = " ") -> Column:
    return _pad_impl(col, width, pad, left=False)


@func_range("string_concat")
def concat(a: Column, b: Column) -> Column:
    """Spark ``concat(a, b)``: null if EITHER side is null."""
    pa, pb = _padded(a), _padded(b)
    wa, wb = pa.chars.shape[1], pb.chars.shape[1]
    out_w = wa + wb
    la, lb = pa.data, pb.data
    out_len = la + lb
    j = jnp.arange(out_w, dtype=jnp.int32)[None, :]
    from_a = j < la[:, None]
    a_src = jnp.clip(j, 0, wa - 1)
    b_src = jnp.clip(j - la[:, None], 0, wb - 1)
    av = jnp.take_along_axis(pa.chars, a_src, axis=1)
    bv = jnp.take_along_axis(pb.chars, b_src, axis=1)
    out = jnp.where(from_a, av, bv)
    out = jnp.where(_in_row(out_len, out_w), out, jnp.uint8(0))
    validity = pa.valid_mask() & pb.valid_mask()
    if a.validity is None and b.validity is None:
        validity = None
    return _string_col(out_len, out, validity)


@func_range("string_concat_ws")
def concat_ws(sep: str, cols: Sequence[Column]) -> Column:
    """Spark ``concat_ws``: join NON-NULL operands with ``sep`` (null
    operands are skipped; the result is null only when... never — Spark
    returns '' when all operands are null)."""
    sb = sep.encode()
    slen = len(sb)
    if not cols:
        raise ValueError(
            "concat_ws needs at least one column (a zero-operand "
            "concat_ws is a planner constant, not a columnar kernel)")
    ps = [_padded(c) for c in cols]
    n = ps[0].chars.shape[0]
    out_w = sum(p.chars.shape[1] for p in ps) + slen * max(len(ps) - 1, 0)
    sep_arr = jnp.asarray(np.frombuffer(sb, dtype=np.uint8)) if slen \
        else None
    out = jnp.zeros((n, max(out_w, 1)), jnp.uint8)
    cur_len = jnp.zeros((n,), jnp.int32)
    j = jnp.arange(max(out_w, 1), dtype=jnp.int32)[None, :]
    started = jnp.zeros((n,), jnp.bool_)
    for p in ps:
        ok = p.valid_mask()
        piece_len = jnp.where(ok, p.data, 0)
        sep_here = jnp.where(started & ok, slen, 0).astype(jnp.int32)
        # separator bytes
        if slen:
            rel = j - cur_len[:, None]
            in_sep = (rel >= 0) & (rel < sep_here[:, None])
            sep_b = sep_arr[jnp.clip(rel, 0, slen - 1).reshape(-1)].reshape(
                rel.shape)
            out = jnp.where(in_sep, sep_b, out)
            cur_len = cur_len + sep_here
        rel = j - cur_len[:, None]
        wp = p.chars.shape[1]
        in_piece = (rel >= 0) & (rel < piece_len[:, None])
        src = jnp.clip(rel, 0, max(wp - 1, 0))
        pv = jnp.take_along_axis(p.chars, src, axis=1)
        out = jnp.where(in_piece, pv, out)
        cur_len = cur_len + piece_len
        started = started | ok
    out = jnp.where(_in_row(cur_len, max(out_w, 1)), out, jnp.uint8(0))
    return _string_col(cur_len, out, None)


@func_range("string_instr")
def instr(col: Column, sub: str) -> Column:
    """Spark ``instr``: 1-based CHARACTER position of the first
    occurrence, 0 when absent, null for null input. Empty needle -> 1
    (Java indexOf convention)."""
    from spark_rapids_jni_tpu.ops.strings import _needle_windows

    p = _padded(col)
    w = p.chars.shape[1]
    nb = sub.encode()
    if not nb:
        one = jnp.ones((p.chars.shape[0],), jnp.int32)
        return Column(DType(TypeId.INT32), one, _validity(col))
    # _needle_windows already masks hits to needle-fits-in-row
    hit = _needle_windows(p, nb)   # (n, w) byte-position hits
    any_hit = jnp.any(hit, axis=1)
    first_byte = jnp.argmax(hit, axis=1).astype(jnp.int32)
    # char index of that byte = count of non-continuation bytes before it
    notcont = (~_is_cont(p.chars)).astype(jnp.int32)
    pre = jnp.cumsum(notcont, axis=1)
    idx = jnp.take_along_axis(
        pre, jnp.clip(first_byte - 1, 0, w - 1)[:, None], axis=1)[:, 0]
    charpos = jnp.where(first_byte > 0, idx, 0) + 1
    return Column(DType(TypeId.INT32),
                  jnp.where(any_hit, charpos, 0).astype(jnp.int32),
                  _validity(col))


@func_range("string_repeat")
def repeat(col: Column, k: int) -> Column:
    """Spark ``repeat(str, k)``; k <= 0 gives ''."""
    p = _padded(col)
    w = p.chars.shape[1]
    if k <= 0:
        n = p.chars.shape[0]
        return _string_col(jnp.zeros((n,), jnp.int32),
                           jnp.zeros((n, 1), jnp.uint8), _validity(col))
    out_w = w * k
    lens = p.data
    out_len = lens * k
    j = jnp.arange(out_w, dtype=jnp.int32)[None, :]
    safe = jnp.maximum(lens, 1)[:, None]
    src = jnp.clip(j % safe, 0, w - 1)
    out = jnp.take_along_axis(p.chars, src, axis=1)
    out = jnp.where(_in_row(out_len, out_w), out, jnp.uint8(0))
    return _string_col(out_len, out, _validity(col))


@func_range("string_reverse")
def reverse(col: Column) -> Column:
    """Spark ``reverse``: CHARACTER-level reversal (multi-byte UTF-8
    sequences keep their byte order). For output byte j, mirror to
    e = len-1-j, find e's character [start s, final f], and read byte
    s + (f - e) — two masked scans, one gather, no host work."""
    p = _padded(col)
    n, w = p.chars.shape
    lens = p.data
    idx = jnp.arange(w, dtype=jnp.int32)[None, :]
    starts = ~_is_cont(p.chars)  # zero padding is a start too
    # char start position per byte: running max of start indices
    import jax

    s_per = jax.lax.cummax(jnp.where(starts, idx, -1), axis=1)
    # char final position per byte: a byte is final iff the NEXT byte
    # starts a char (the zero pad after the last byte is a start)
    nxt = jnp.concatenate(
        [starts[:, 1:], jnp.ones((n, 1), jnp.bool_)], axis=1)
    f_per = jax.lax.cummin(jnp.where(nxt, idx, w), axis=1, reverse=True)
    e = jnp.clip(lens[:, None] - 1 - idx, 0, w - 1)
    s_e = jnp.take_along_axis(s_per, e, axis=1)
    f_e = jnp.take_along_axis(f_per, e, axis=1)
    src = jnp.clip(s_e + (f_e - e), 0, w - 1)
    out = jnp.take_along_axis(p.chars, src, axis=1)
    out = jnp.where(_in_row(lens, w), out, jnp.uint8(0))
    return _string_col(lens, out, _validity(col))


@func_range("string_translate")
def translate(col: Column, from_str: str, to_str: str) -> Column:
    """Spark ``translate``: per-character substitution; chars in
    ``from_str`` beyond ``to_str``'s length are DELETED. Single-byte
    (ASCII) mappings ride the device 256-entry table; any multi-byte
    character in the mapping or the data falls back to the host."""
    fb, tb = from_str.encode(), to_str.encode()
    p = _padded(col)
    if (any(b >= 0x80 for b in fb) or any(b >= 0x80 for b in tb)
            or not _ascii_only(p)):
        table = {}
        for i, ch in enumerate(from_str):
            if ch not in table:
                table[ch] = to_str[i] if i < len(to_str) else None
        vals = col.to_pylist()  # handles both string layouts directly
        out = [None if v is None else
               "".join((table[ch] if table[ch] is not None else "")
                       if ch in table else ch for ch in v) for v in vals]
        return pad_strings(Column.from_pylist(out, t.STRING))
    # device path: map[256] with a delete marker, then compact kept bytes
    m = np.arange(256, dtype=np.int16)
    seen = set()
    for i, b in enumerate(fb):
        if b in seen:
            continue
        seen.add(b)
        m[b] = tb[i] if i < len(tb) else -1
    tbl = jnp.asarray(m)
    w = p.chars.shape[1]
    mapped = tbl[p.chars.astype(jnp.int32)]
    keep = (mapped >= 0) & _in_row(p.data, w)
    new_len = jnp.sum(keep.astype(jnp.int32), axis=1)
    # compact kept bytes to the front: position among kept = exclusive
    # prefix; dense gather via argsort of ~keep (stable)
    order = jnp.argsort(~keep, axis=1, stable=True)
    gathered = jnp.take_along_axis(
        jnp.where(keep, mapped, 0).astype(jnp.uint8), order, axis=1)
    out = jnp.where(_in_row(new_len, w), gathered, jnp.uint8(0))
    return _string_col(new_len, out, _validity(col))


class SplitResult(NamedTuple):
    column: Column            # LIST<STRING>, one list per input row
    overflowed: jnp.ndarray   # True when a row had more pieces than cap


@func_range("string_split")
def split(col: Column, sep: str, limit: int = -1,
          max_pieces: int | None = None) -> SplitResult:
    """Spark ``split(str, sep[, limit])`` for LITERAL separators (regex
    separators go through the host engine upstream): LIST<STRING> with
    the split+explode contract.

    ``limit > 0``: at most ``limit`` pieces, the last keeps the rest
    (Java semantics) — the static piece budget is ``limit``.
    ``limit <= 0``: unbounded; the caller must pass ``max_pieces`` as
    the static budget, and rows exceeding it set ``overflowed`` (the
    shuffle-capacity posture) with their excess pieces dropped.
    """
    import jax

    sb = sep.encode()
    if not sb:
        raise ValueError("split separator must be non-empty")
    cap = limit if limit > 0 else max_pieces
    if cap is None:
        raise ValueError(
            "split with limit <= 0 needs max_pieces (static piece budget)")
    if cap < 1:
        raise ValueError("split piece budget must be >= 1")
    from spark_rapids_jni_tpu.ops.strings import _needle_windows

    p = _padded(col)
    n, w = p.chars.shape
    lens = p.data
    raw = _needle_windows(p, sb)   # already masked to fits-in-row
    if len(sb) > 1:
        # leftmost non-overlapping matches: a scan over byte columns
        # kills hits that start inside an earlier match
        def step(allowed, col_hits):
            jcol, hits = col_hits
            ok = hits & (jcol >= allowed)
            allowed = jnp.where(ok, jcol + len(sb), allowed)
            return allowed, ok

        cols_idx = jnp.arange(w, dtype=jnp.int32)
        _, kept = jax.lax.scan(
            step, jnp.zeros((n,), jnp.int32),
            (cols_idx, raw.T))
        hits = kept.T
    else:
        hits = raw
    ndelim = jnp.sum(hits.astype(jnp.int32), axis=1)
    use_delim = jnp.minimum(ndelim, cap - 1)
    # null input rows contribute no pieces at all — the dense child and
    # the offsets must agree row-for-row
    npieces = jnp.where(p.valid_mask(), use_delim + 1, 0)
    overflowed = jnp.any((ndelim > cap - 1) if limit <= 0
                         else jnp.zeros((n,), jnp.bool_))
    # k-th delimiter byte position per row via searchsorted over the
    # inclusive hit prefix (the _group_starts idiom)
    incl = jnp.cumsum(hits.astype(jnp.int32), axis=1)
    ks = jnp.arange(1, cap + 1, dtype=jnp.int32)  # delim ranks 1..cap
    dpos = jax.vmap(
        lambda pr: jnp.searchsorted(pr, ks, side="left"))(incl)
    dpos = dpos.astype(jnp.int32)              # (n, cap); absent rank -> w
    # piece p: [start_p, end_p) where end_p is delim rank p+1 (the
    # natural/extended last piece is overridden below)
    zero = jnp.zeros((n, 1), jnp.int32)
    starts = jnp.concatenate(
        [zero, dpos[:, :cap - 1] + len(sb)], axis=1)   # (n, cap)
    ends = dpos
    pidx = jnp.arange(cap, dtype=jnp.int32)[None, :]
    live = pidx < npieces[:, None]
    if limit > 0:
        # Java limit semantics: the last kept piece keeps the REST
        # (separators included)
        extend = pidx == (npieces - 1)[:, None]
    else:
        # cap mode: only a row's NATURAL last piece runs to end-of-row;
        # overflowing rows get their excess pieces dropped cleanly
        extend = pidx == ndelim[:, None]
    p_start = jnp.where(live, starts, 0)
    p_end = jnp.where(extend, lens[:, None], jnp.where(live, ends, 0))
    p_len = jnp.maximum(p_end - p_start, 0)
    # child: (n*cap, w) padded strings, row-major (row, piece)
    flat_start = p_start.reshape(-1)
    flat_len = p_len.reshape(-1)
    src_rows = jnp.repeat(jnp.arange(n, dtype=jnp.int32), cap)
    j = jnp.arange(w, dtype=jnp.int32)[None, :]
    src = jnp.clip(flat_start[:, None] + j, 0, w - 1)
    child_chars = jnp.take_along_axis(p.chars[src_rows], src, axis=1)
    child_chars = jnp.where(_in_row(flat_len, w), child_chars,
                            jnp.uint8(0))
    # compact live pieces to the front of the child (argsort idiom) so
    # offsets index a dense child
    live_flat = live.reshape(-1)
    order = jnp.argsort(~live_flat, stable=True).astype(jnp.int32)
    child = Column(
        DType(TypeId.STRING),
        flat_len[order].astype(jnp.int32),
        None,
        chars=child_chars[order],
    )
    offsets = jnp.concatenate(
        [jnp.zeros((1,), jnp.int64),
         jnp.cumsum(npieces.astype(jnp.int64))]).astype(jnp.int32)
    lc = Column(DType(TypeId.LIST), offsets, _validity(col),
                children=[child])
    return SplitResult(lc, overflowed)


@func_range("string_initcap")
def initcap(col: Column) -> Column:
    """Spark ``initcap``: first letter of each SPACE-delimited word
    uppercased, every other letter lowercased — Spark's
    UTF8String.toTitleCase treats only ' ' (0x20) as a delimiter, so
    tabs/newlines do NOT start words. ASCII rides the device path;
    non-ASCII data falls back to the host (the upper/lower posture)."""
    p = _padded(col)
    if not _ascii_only(p):
        vals = col.to_pylist()
        out = []
        for v in vals:
            if v is None:
                out.append(None)
                continue
            chars = []
            prev_sp = True
            for ch in v:
                if ch == " ":
                    chars.append(ch)
                    prev_sp = True
                else:
                    chars.append(ch.upper() if prev_sp else ch.lower())
                    prev_sp = False
            out.append("".join(chars))
        return pad_strings(Column.from_pylist(out, t.STRING))
    n, w = p.chars.shape
    ws = p.chars == jnp.uint8(0x20)
    prev_ws = jnp.concatenate(
        [jnp.ones((n, 1), jnp.bool_), ws[:, :-1]], axis=1)
    is_lower = (p.chars >= 0x61) & (p.chars <= 0x7A)
    is_upper = (p.chars >= 0x41) & (p.chars <= 0x5A)
    up = jnp.where(is_lower, p.chars - 0x20, p.chars)
    low = jnp.where(is_upper, p.chars + 0x20, p.chars)
    out = jnp.where(prev_ws, up, low)
    out = jnp.where(_in_row(p.data, w), out, jnp.uint8(0))
    return _string_col(p.data, out, _validity(col))
