"""Device-side JSONPath extraction — the TPU-vectorized fast path of
``get_json_object`` (role of the reference's JSON kernel family; the host
engine in src/native/src/get_json_object.cpp is the semantic oracle and the
fallback).

Design (simdjson's structural-index idea, re-expressed for the VPU): the
column lives in the padded device layout (n, W) uint8. All parsing state is
computed as (n, W) masks with per-row scans along the W axis only —
quote-parity classifies string interiors, a cumsum over bracket characters
outside strings yields nesting depth, and "first index >= j with property P"
queries are a reverse cumulative minimum. Each JSONPath component then
narrows a per-row (start, end) span: field steps match the literal
``"name"`` window at the component's static depth inside the span and hop
to the value after the colon; index steps count depth-level commas. No
scatters, no data-dependent control flow, no host round trip.

Supported grammar (same as the native engine, minus wildcards): ``$``,
``.field``, ``['field']``, ``[index]``. Output matches the host engine:
string values unquoted, object/array/number/bool raw text, JSON null and
missing paths -> SQL NULL.

Eligibility (checked on device, one scalar fetch): no backslash anywhere
(escape decoding is host work) and structural sanity per row (balanced
quotes, balanced brackets, depth never negative). Ineligible columns fall
back to the native engine. On structurally balanced but grammatically
invalid JSON (e.g. a missing colon) the fast path may differ from the host
engine — full grammar validation is exactly the branchy byte machine this
path exists to avoid; the dispatcher's sanity checks bound that divergence
to malformed documents.
"""

from __future__ import annotations

import re
from typing import NamedTuple

import jax
import jax.numpy as jnp

from spark_rapids_jni_tpu.columnar import Column
from spark_rapids_jni_tpu.ops.strings import STRING, pad_strings
from spark_rapids_jni_tpu.utils.tracing import func_range


class PathStep(NamedTuple):
    field: str | None   # object member name, or None for an array index
    index: int | None


# dotted names run to the next '.' or '[' (the native engine's rule —
# ']' and '*' are legal name bytes; only the exact name "*" is a wildcard)
_FIELD_RE = re.compile(r"\.([^.\[]+)|\['([^']*)'\]|\[(\d+)\]")


def parse_json_path(path: str) -> list[PathStep]:
    """``$``-rooted JSONPath -> steps. ValueError on wildcards/garbage (the
    host engine's PathError contract: Spark fails paths it cannot compile)."""
    if not path.startswith("$"):
        raise ValueError(f"JSONPath: must start with '$': {path!r}")
    rest = path[1:]
    steps: list[PathStep] = []
    pos = 0
    while pos < len(rest):
        m = _FIELD_RE.match(rest, pos)
        if m is None:
            raise ValueError(f"JSONPath: cannot compile {path!r} at {pos+1}")
        if m.group(3) is not None:
            steps.append(PathStep(None, int(m.group(3))))
        else:
            name = m.group(1) if m.group(1) is not None else m.group(2)
            if name == "*":
                raise ValueError(f"JSONPath: wildcards unsupported: {path!r}")
            steps.append(PathStep(name, None))
        pos = m.end()
    return steps


def _next_index(mask: jnp.ndarray) -> jnp.ndarray:
    """(n, W) bool -> (n, W) int32: smallest j' >= j with mask[j'] (W if
    none) — a reverse cumulative minimum over candidate indices."""
    w = mask.shape[1]
    j = jnp.arange(w, dtype=jnp.int32)
    cand = jnp.where(mask, j[None, :], jnp.int32(w))
    return jax.lax.associative_scan(jnp.minimum, cand, reverse=True, axis=1)


def _at(arr2d: jnp.ndarray, pos: jnp.ndarray, fill):
    """arr2d[i, pos[i]] with pos == W treated as out-of-doc -> fill."""
    w = arr2d.shape[1]
    safe = jnp.clip(pos, 0, w - 1)
    got = jnp.take_along_axis(arr2d, safe[:, None].astype(jnp.int32),
                              axis=1)[:, 0]
    return jnp.where(pos < w, got, jnp.asarray(fill, dtype=arr2d.dtype))


class _Doc(NamedTuple):
    ch: jnp.ndarray          # (n, W) uint8, zeroed past row length
    in_content: jnp.ndarray  # char is string interior or closing quote
    depth: jnp.ndarray       # nesting depth AFTER processing char j
    nonws: jnp.ndarray       # non-whitespace, in-row
    quote: jnp.ndarray       # '"' chars
    row_len: jnp.ndarray     # (n,) int32
    sane: jnp.ndarray        # (n,) structural sanity
    has_escape: jnp.ndarray  # (n,)


def _classify(mat: jnp.ndarray, lengths: jnp.ndarray) -> _Doc:
    w = mat.shape[1]
    j = jnp.arange(w, dtype=jnp.int32)
    inrow = j[None, :] < lengths[:, None]
    ch = jnp.where(inrow, mat, jnp.uint8(0))
    quote = ch == 34  # "
    qcum = jnp.cumsum(quote, axis=1)
    # a char is string interior (or the closing quote) iff an odd number of
    # quotes strictly precede it; the opening quote itself is structural
    in_content = ((qcum - quote) % 2) == 1
    openb = ~in_content & ((ch == 123) | (ch == 91))    # { [
    closeb = ~in_content & ((ch == 125) | (ch == 93))   # } ]
    delta = openb.astype(jnp.int32) - closeb.astype(jnp.int32)
    depth = jnp.cumsum(delta, axis=1)
    ws = (ch == 32) | (ch == 9) | (ch == 10) | (ch == 13)
    nonws = inrow & ~ws & (ch != 0)
    sane = (
        (qcum[:, -1] % 2 == 0)
        & (depth[:, -1] == 0)
        & (jnp.min(depth, axis=1) >= 0)
    )
    has_escape = jnp.any(ch == 92, axis=1)
    return _Doc(ch, in_content, depth, nonws, quote,
                lengths.astype(jnp.int32), sane, has_escape)


def _value_span(doc: _Doc, vstart: jnp.ndarray, level: int, ok: jnp.ndarray):
    """Given per-row value-start positions at container depth ``level``,
    return (start, end_exclusive, is_string, ok). Strings keep their
    quotes here; the extraction step strips them."""
    w = doc.ch.shape[1]
    j = jnp.arange(w, dtype=jnp.int32)[None, :]
    first = _at(doc.ch, vstart, 0)
    after_v = j > vstart[:, None]

    # string value: closing quote is the next quote after the opener
    str_end = _next_index(doc.quote & after_v)
    e_string = _at(str_end, vstart, w - 1)  # position of closing quote

    # nested value: matching close returns depth to `level`
    close_at_level = (~doc.in_content & ((doc.ch == 125) | (doc.ch == 93))
                      & (doc.depth == level))
    nest_end = _next_index(close_at_level & after_v)
    e_nested = _at(nest_end, vstart, w - 1)

    # scalar: terminated by a level-comma, the container's own close, or
    # the end of the document
    term = (~doc.in_content
            & (((doc.ch == 44) & (doc.depth == level))
               | (((doc.ch == 125) | (doc.ch == 93))
                  & (doc.depth == level - 1))))
    scal_end = _next_index(term & after_v)
    e_scalar = jnp.minimum(_at(scal_end, vstart, w), doc.row_len)

    is_string = first == 34
    is_nested = (first == 123) | (first == 91)
    end = jnp.where(is_string, e_string + 1,
                    jnp.where(is_nested, e_nested + 1, e_scalar))
    # trim trailing whitespace off scalar spans
    last_tok = jnp.max(
        jnp.where(doc.nonws & (j >= vstart[:, None]) & (j < end[:, None]),
                  j, -1), axis=1)
    end = jnp.where(is_string | is_nested, end, last_tok + 1)
    ok = ok & (vstart < w) & (end > vstart)
    return vstart, end, is_string, ok


def _eligibility(doc: _Doc, valid: jnp.ndarray, s0: jnp.ndarray,
                 root_s: jnp.ndarray, root_e: jnp.ndarray) -> jnp.ndarray:
    """Scalar: every row escape-free, structurally sane, no content past
    the root value, and bare scalars one contiguous token — computed from
    an already-classified document (shared with extraction)."""
    w = doc.ch.shape[1]
    j = jnp.arange(w, dtype=jnp.int32)[None, :]
    last_nonws = jnp.max(jnp.where(doc.nonws, j, -1), axis=1)
    no_trailing = last_nonws < root_e
    first = _at(doc.ch, s0, 0)
    is_nested = (first == 123) | (first == 91)
    in_span = (j >= root_s[:, None]) & (j < root_e[:, None])
    contiguous = jnp.all(~in_span | doc.nonws, axis=1)
    scalar_ok = (first == 34) | is_nested | contiguous
    empty = s0 == w
    row_ok = (
        (~doc.has_escape & doc.sane & no_trailing & scalar_ok)
        | ~valid | empty
    )
    return jnp.all(row_ok)


def _device_extract(mat: jnp.ndarray, lengths: jnp.ndarray,
                    valid: jnp.ndarray, steps: tuple[PathStep, ...]):
    """Core (jittable): (n, W) padded docs ->
    (lengths, validity, out_mat, eligible) — eligibility rides the same
    structural classification, so the dispatcher pays one device pass."""
    doc = _classify(mat, lengths)
    w = mat.shape[1]
    j = jnp.arange(w, dtype=jnp.int32)[None, :]
    nxt_nonws = _next_index(doc.nonws)

    n = mat.shape[0]
    s0 = _at(nxt_nonws, jnp.zeros((n,), jnp.int32), w)  # first token
    ok = valid & doc.sane & (s0 < w)
    # the root document is itself a value span at container depth 0 — this
    # (not "last non-ws") bounds the result, so a root object followed by
    # trailing bytes ends at its matching close, like the host engine
    s, e, is_string, ok = _value_span(doc, s0, 0, ok)
    eligible = _eligibility(doc, valid, s0, s, e)

    level = 0
    for step in steps:
        level += 1
        in_span = (j > s[:, None]) & (j < e[:, None])
        if step.field is not None:
            ok = ok & (_at(doc.ch, s, 0) == 123)  # must be an object
            pat = step.field.encode("utf-8")
            f = len(pat)
            # literal window '"field"' at this level, structurally a key
            win = (doc.ch == 34) & ~doc.in_content & (doc.depth == level)
            for off, byte in enumerate(pat):
                shifted = jnp.roll(doc.ch, -(off + 1), axis=1)
                win = win & (shifted == byte)
            closing = jnp.roll(doc.ch, -(f + 1), axis=1)
            win = win & (closing == 34) & (j + f + 1 < doc.row_len[:, None])
            # the next non-ws char after the closing quote must be a colon —
            # part of the window itself, so a VALUE string that happens to
            # equal '"field"' cannot shadow a later real key
            cpos_all = jnp.roll(nxt_nonws, -(f + 2), axis=1)
            ch_at_cpos = jnp.take_along_axis(
                doc.ch, jnp.clip(cpos_all, 0, w - 1), axis=1)
            win = win & (ch_at_cpos == 58) & (cpos_all < w) & in_span
            kq = _next_index(win)
            kpos = _at(kq, s + 1, w)                  # first real key match
            ok = ok & (kpos < w)
            cpos = _at(cpos_all, kpos, w)
            vstart = _at(nxt_nonws, cpos + 1, w)
        else:
            ok = ok & (_at(doc.ch, s, 0) == 91)  # must be an array
            k = step.index
            if k == 0:
                vstart = _at(nxt_nonws, s + 1, w)
                # empty array: first token would be the closing bracket
                ok = ok & (_at(doc.ch, vstart, 0) != 93)
            else:
                commas = (~doc.in_content & (doc.ch == 44)
                          & (doc.depth == level) & in_span)
                ccum = jnp.cumsum(commas, axis=1)
                kth = _next_index(commas & (ccum == k))
                cpos = _at(kth, s + 1, w)
                ok = ok & (cpos < w)
                vstart = _at(nxt_nonws, cpos + 1, w)
        s, e, is_string, ok = _value_span(doc, vstart, level, ok)

    # assemble result strings: strip quotes for strings; 'null' -> SQL NULL
    out_s = jnp.where(is_string, s + 1, s)
    out_e = jnp.where(is_string, e - 1, e)
    out_len = jnp.maximum(out_e - out_s, 0)
    is_null_lit = (
        ~is_string & (out_len == 4)
        & (_at(doc.ch, out_s, 0) == 110) & (_at(doc.ch, out_s + 1, 0) == 117)
        & (_at(doc.ch, out_s + 2, 0) == 108) & (_at(doc.ch, out_s + 3, 0) == 108)
    )
    ok = ok & ~is_null_lit
    out_len = jnp.where(ok, out_len, 0)
    src = out_s[:, None] + jnp.arange(w, dtype=jnp.int32)[None, :]
    out_mat = jnp.take_along_axis(
        jnp.where(ok[:, None], doc.ch, jnp.uint8(0)),
        jnp.clip(src, 0, w - 1), axis=1)
    out_mat = jnp.where(jnp.arange(w)[None, :] < out_len[:, None],
                        out_mat, jnp.uint8(0))
    return out_len.astype(jnp.int32), ok, out_mat, eligible


def device_eligible(col: Column) -> jnp.ndarray:
    """Scalar bool (device): every row is escape-free, structurally sane,
    and free of content past the root value (trailing-garbage documents are
    grammar errors only the host state machine adjudicates). The dispatcher
    fetches this one byte to pick the engine."""
    p = pad_strings(col)
    doc = _classify(p.chars, p.data)
    w = doc.ch.shape[1]
    j = jnp.arange(w, dtype=jnp.int32)[None, :]
    n = doc.ch.shape[0]
    nxt_nonws = _next_index(doc.nonws)
    s0 = _at(nxt_nonws, jnp.zeros((n,), jnp.int32), w)
    ones = jnp.ones((n,), jnp.bool_)
    s, e, is_string, span_ok = _value_span(doc, s0, 0, ones)
    last_nonws = jnp.max(jnp.where(doc.nonws, j, -1), axis=1)
    no_trailing = last_nonws < e
    first = _at(doc.ch, s0, 0)
    is_nested = (first == 123) | (first == 91)
    # bare scalars must be one contiguous token ('17 garbage' is not)
    in_span = (j >= s[:, None]) & (j < e[:, None])
    contiguous = jnp.all(~in_span | doc.nonws, axis=1)
    scalar_ok = is_string | is_nested | contiguous
    empty = s0 == w
    row_ok = (
        (~doc.has_escape & doc.sane & no_trailing & scalar_ok)
        | ~p.valid_mask() | empty
    )
    return jnp.all(row_ok)


@func_range("get_json_object_device")
def get_json_object_device(col: Column, path: str) -> Column:
    """Fully on-device JSONPath extraction over a padded STRING column.
    Jittable; caller is responsible for eligibility (``device_eligible``) —
    the public ``get_json_object`` dispatcher does both."""
    steps = tuple(parse_json_path(path))
    p = pad_strings(col)
    out_len, ok, out_mat, _elig = _device_extract(
        p.chars, p.data, p.valid_mask(), steps)
    return Column(STRING, out_len, ok, chars=out_mat)


@func_range("extract_with_eligibility")
def extract_with_eligibility(col: Column, path: str):
    """One device pass for the dispatcher: (result Column, eligible scalar).
    The result is only meaningful when ``eligible`` is True."""
    steps = tuple(parse_json_path(path))
    p = pad_strings(col)
    out_len, ok, out_mat, elig = _device_extract(
        p.chars, p.data, p.valid_mask(), steps)
    return Column(STRING, out_len, ok, chars=out_mat), elig
