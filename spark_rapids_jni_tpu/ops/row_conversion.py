"""Row-major <-> column-major table conversion (component C1' — the TPU
equivalent of reference src/main/cpp/src/row_conversion.cu).

The byte-level row format is contract-identical to the reference
(RowConversion.java:40-99):

  * columns packed in schema order, each aligned to its own element size
    (row_conversion.cu:432-446);
  * one validity byte per 8 columns appended directly after the last column,
    byte-aligned, bit ``col % 8`` of byte ``col // 8`` set <=> valid
    (row_conversion.cu:158-165,255-272);
  * each row zero-padded to a 64-bit boundary (row_conversion.cu:454-455);
  * output split into batches of < 2**31 bytes, batch row counts a multiple
    of 32 (row_conversion.cu:476-511);
  * fixed-width types only (row_conversion.cu:515,573);
  * rows larger than ~1.5KB rejected — the reference's shared-memory limit
    (row_conversion.cu:334-347; documented as "1KB" in
    RowConversion.java:98-99). TPU has no such hardware limit; the check
    keeps API-contract parity and can be lifted via ``enforce_row_limit``.

The *implementation* is nothing like the CUDA kernel. The reference stages
row images through 48KB of shared memory with a 2-D thread grid and warp
ballots. On TPU the whole conversion is expressed as a static byte-layout
transform — per-column ``bitcast_convert_type`` to bytes, zero-pad columns,
validity packed via an (n,8)x(8,) weighted sum, and a single concatenate —
which XLA fuses into one HBM-bandwidth-bound copy. No scalar loops, no
dynamic shapes, so it tiles cleanly onto the VPU.

One deliberate difference: padding bytes are 0 (the reference leaves
whatever was in shared memory — i.e. garbage — in pad slots). Deterministic
output makes rows byte-comparable, which Spark range-partition sort needs.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial
from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np

from spark_rapids_jni_tpu.columnar import Column, Table
from spark_rapids_jni_tpu.columnar.bitmask import pack_bits_last_axis
from spark_rapids_jni_tpu.ops.bytecast import from_bytes, to_bytes
from spark_rapids_jni_tpu.types import DType
from spark_rapids_jni_tpu.utils.config import get_option
from spark_rapids_jni_tpu.utils.tracing import func_range

INT32_MAX = 2**31 - 1
# (48KB shared mem / 32-thread minimum block) in the reference sets the max
# row size; we enforce the same documented contract.
MAX_ROW_SIZE = 1536


def _align(offset: int, alignment: int) -> int:
    return (offset + alignment - 1) & ~(alignment - 1)


def compute_fixed_width_layout(
    schema: Sequence[DType],
) -> tuple[list[int], list[int], int]:
    """Return (column_start, column_size, size_per_row) for the packed row.

    Contract-identical to reference row_conversion.cu:432-456: each column is
    aligned to its own size, validity bytes ((ncols+7)//8) follow the last
    column unaligned, and the row is padded to 8 bytes.
    """
    column_start: list[int] = []
    column_size: list[int] = []
    at_offset = 0
    for dt in schema:
        if not (dt.is_fixed_width or dt.is_decimal128):
            raise TypeError("Only fixed width types are currently supported")
        # DECIMAL128 rows: 16-byte element, 16-byte alignment — the
        # reference's generic rule (alignment == element size,
        # row_conversion.cu:439-443) applied to __int128_t
        s = dt.size_bytes
        at_offset = _align(at_offset, s)
        column_start.append(at_offset)
        column_size.append(s)
        at_offset += s
    validity_bytes = (len(schema) + 7) // 8
    at_offset += validity_bytes
    return column_start, column_size, _align(at_offset, 8)


@dataclass
class RowsColumn:
    """One output batch: the LIST<INT8> column of the reference
    (row_conversion.cu:405-406) — ``data`` is the flat byte child, offsets
    are the implicit arithmetic sequence ``i * row_size``."""

    num_rows: int
    row_size: int
    data: jnp.ndarray  # uint8[num_rows * row_size]

    @property
    def offsets(self) -> jnp.ndarray:
        return jnp.arange(self.num_rows + 1, dtype=jnp.int32) * self.row_size

    @property
    def size_bytes(self) -> int:
        return self.num_rows * self.row_size


def _pack_validity_bytes(valids: jnp.ndarray) -> jnp.ndarray:
    """(n, ncols) bool -> (n, (ncols+7)//8) uint8, bit col%8 of byte col//8."""
    return pack_bits_last_axis(valids)


def _to_rows_impl(
    datas: list[jnp.ndarray],
    valids: list[jnp.ndarray],
    schema: tuple[DType, ...],
) -> jnp.ndarray:
    """Jittable core: full-table row image as uint8[n, size_per_row]."""
    column_start, column_size, size_per_row = compute_fixed_width_layout(schema)
    n = datas[0].shape[0]
    pieces: list[jnp.ndarray] = []
    starts: list[int] = []  # byte offset of each piece in the row image
    cursor = 0
    for i, dt in enumerate(schema):
        start, size = column_start[i], column_size[i]
        starts.append(start)
        pieces.append(to_bytes(datas[i], dt))
        cursor = start + size
    starts.append(cursor)
    pieces.append(_pack_validity_bytes(jnp.stack(valids, axis=1)))

    # kernel-tier seam: the XLA oracle interleaves by lane concatenation
    # (alignment gaps / trailing row pad as explicit zero pieces); the
    # Pallas twin assembles the same bytes by where-selects with gaps
    # falling out of its zero-initialized tile. Tier pick is trace-time,
    # keyed into the dispatch cache via the kernels digest.
    from spark_rapids_jni_tpu.ops import pallas as pallas_tier

    decision = pallas_tier.decide("row_conversion.to_rows")
    if decision.use_pallas:
        from spark_rapids_jni_tpu.ops.pallas import row_transpose as prt

        reason = prt.unsupported_reason(n, size_per_row)
        if reason is None:
            return prt.assemble_rows(
                pieces, starts, size_per_row,
                interpret=decision.interpret)
        pallas_tier.fall_back("row_conversion.to_rows", reason)

    padded: list[jnp.ndarray] = []
    cursor = 0
    for start, piece in zip(starts, pieces):
        if start > cursor:  # alignment padding before this piece
            padded.append(jnp.zeros((n, start - cursor), dtype=jnp.uint8))
        padded.append(piece)
        cursor = start + piece.shape[1]
    if size_per_row > cursor:  # trailing pad to the 64-bit row boundary
        padded.append(jnp.zeros((n, size_per_row - cursor), dtype=jnp.uint8))
    return jnp.concatenate(padded, axis=1)


def _to_rows_dispatch(row_args, aux, rvs, *, schema):
    ((datas, valids),) = row_args
    return _to_rows_impl(datas, valids, schema)


@func_range("convert_to_rows")
def convert_to_rows(
    table: Table, *, enforce_row_limit: bool | None = None
) -> list[RowsColumn]:
    """Columnar -> packed rows. Returns one or more RowsColumn batches, each
    under 2**31 bytes with a 32-row-multiple row count (except the last),
    matching reference row_conversion.cu:458-517.

    ``enforce_row_limit`` defaults to the ``row_conversion.enforce_row_limit``
    config option (env SPARK_RAPIDS_TPU_ROW_CONVERSION_ENFORCE_ROW_LIMIT).
    """
    if enforce_row_limit is None:
        enforce_row_limit = get_option("row_conversion.enforce_row_limit")
    if table.num_columns == 0:
        raise ValueError("table must have at least one column")
    schema = tuple(table.schema())
    _, _, size_per_row = compute_fixed_width_layout(schema)
    if enforce_row_limit and size_per_row > MAX_ROW_SIZE:
        raise ValueError("Row size is too large to fit in shared memory")

    datas = [c.data for c in table.columns]
    valids = [c.valid_mask() for c in table.columns]
    from spark_rapids_jni_tpu.runtime import dispatch

    # padded tail rows pack to all-zero row images and are sliced off
    rows = dispatch.rowwise(
        "convert_to_rows", partial(_to_rows_dispatch, schema=schema),
        (datas, valids), statics=(schema,))  # (n, size_per_row)

    num_rows = table.num_rows
    max_rows_per_batch = (INT32_MAX // size_per_row) // 32 * 32
    out: list[RowsColumn] = []
    for row_start in range(0, max(num_rows, 1), max_rows_per_batch):
        count = min(num_rows - row_start, max_rows_per_batch)
        batch = rows[row_start : row_start + count].reshape(-1)
        out.append(RowsColumn(count, size_per_row, batch))
    return out


def _from_rows_impl(
    rows: jnp.ndarray, schema: tuple[DType, ...]
) -> tuple[list[jnp.ndarray], list[jnp.ndarray]]:
    """Jittable core over the 2-D row image uint8[n, size_per_row]."""
    column_start, column_size, size_per_row = compute_fixed_width_layout(schema)
    rows = rows.reshape(-1, size_per_row)
    datas, valids = [], []
    vld_base = column_start[-1] + column_size[-1] if schema else 0
    for i, dt in enumerate(schema):
        start, size = column_start[i], column_size[i]
        datas.append(from_bytes(rows[:, start : start + size], dt))
        vbyte = rows[:, vld_base + i // 8]
        valids.append(((vbyte >> (i % 8)) & 1).astype(jnp.bool_))
    return datas, valids


def _from_rows_dispatch(row_args, aux, rvs, *, schema):
    ((rows,),) = row_args
    return _from_rows_impl(rows, schema)


@func_range("convert_from_rows")
def convert_from_rows(rows: RowsColumn, schema: Sequence[DType]) -> Table:
    """Packed rows -> columnar. Validates the byte length against the layout
    like reference row_conversion.cu:536-542, and returns columns that always
    carry a validity mask (the reference allocates masks unconditionally,
    row_conversion.cu:551-555)."""
    schema_t = tuple(schema)
    for dt in schema_t:
        if not (dt.is_fixed_width or dt.is_decimal128):
            raise TypeError("Only fixed width types are currently supported")
    _, _, size_per_row = compute_fixed_width_layout(schema_t)
    if size_per_row != rows.row_size or rows.data.shape[0] != rows.num_rows * size_per_row:
        raise ValueError("The layout of the data appears to be off")
    from spark_rapids_jni_tpu.runtime import dispatch

    rows2d = rows.data.reshape(rows.num_rows, size_per_row)
    datas, valids = dispatch.rowwise(
        "convert_from_rows", partial(_from_rows_dispatch, schema=schema_t),
        (rows2d,), statics=(schema_t,))
    return Table(
        [Column(dt, d, v) for dt, d, v in zip(schema_t, datas, valids)]
    )
