"""Hash-groupby-aggregate equivalent (cuDF groupby is part of the vendored
capability surface, SURVEY.md section 2.2; TPC-H q1 is the canonical
workload, BASELINE.json config #3).

TPU-first design: no device hash table (no CUDA-style concurrent hash map
idiom on the VPU — SURVEY.md section 7 "hard parts" calls this out). Instead
sort-based grouping: stable-sort rows by the encoded keys, mark segment
boundaries, turn them into dense group ids with a cumulative sum, and run
null-aware ``jax.ops.segment_*`` reductions — all static-shape, all fused by
XLA. Output is padded to the input row count with ``num_groups`` reported
alongside (static shapes are the price of jit; callers slice on host).

Null semantics are Spark's: null keys form their own group; aggregates skip
null values; COUNT counts non-null; an all-null group's SUM/MIN/MAX/MEAN is
null.
"""

from __future__ import annotations

import numbers
from functools import partial
from typing import Any, NamedTuple, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from spark_rapids_jni_tpu.columnar import Column, Table
from spark_rapids_jni_tpu.ops.sort import gather, sort_order
from spark_rapids_jni_tpu.types import DType, TypeId, decimal128
from spark_rapids_jni_tpu.utils.tracing import func_range

SUPPORTED_AGGS = ("sum", "count", "min", "max", "mean", "var", "std",
                  "var_pop", "std_pop", "nunique", "first", "last",
                  "first_include_nulls", "last_include_nulls")
# two-column aggregates: the agg spec is (col_x, (op, col_y))
SUPPORTED_BINARY_AGGS = ("covar_samp", "covar_pop", "corr")


class GroupByResult(NamedTuple):
    table: Table          # keys then aggregates, padded to max_groups rows
    num_groups: jnp.ndarray  # scalar int32
    # True when num_groups exceeded the caller's max_groups bound: groups
    # past the bound were dropped; the caller re-plans with a larger bound
    # (grow-and-retry lives in the host wrapper, not here).
    overflowed: jnp.ndarray | bool = False
    # True when a DECIMAL128 SUM exceeded 128 bits in some group: the
    # affected group's sum is null, never a silently wrapped value (the
    # Spark ANSI overflow posture, surfaced like the shuffle codec's
    # narrowing_overflow rather than corrupting data).
    sum_overflow: jnp.ndarray | bool = False

    def compact(self) -> Table:
        """Host-side trim to the real group count."""
        if bool(self.overflowed):
            raise ValueError(
                "groupby output overflowed max_groups (groups were dropped); "
                "grow and retry (groupby_aggregate_auto) before compacting"
            )
        from spark_rapids_jni_tpu.ops.table_ops import trim_table

        return trim_table(self.table, int(self.num_groups))


def _col_values_equal_prev(c: Column) -> jnp.ndarray:
    """bool[n-1]: row i+1's VALUE equals row i's (validity ignored here;
    NaNs compare equal — the grouping convention)."""
    if c.dtype.is_string:
        from spark_rapids_jni_tpu.ops import strings as s

        return s.strings_equal_prev(c)
    if c.dtype.is_decimal128:
        return jnp.all(c.data[1:] == c.data[:-1], axis=-1)
    eq_val = c.data[1:] == c.data[:-1]
    if c.dtype.storage_dtype.kind == "f":
        eq_val = eq_val | (jnp.isnan(c.data[1:]) & jnp.isnan(c.data[:-1]))
    return eq_val


def _rows_equal_prev(table: Table, keys: Sequence[int]) -> jnp.ndarray:
    """bool[n]: row i has the same key tuple (incl. null-ness) as row i-1."""
    n = table.num_rows
    same = jnp.ones((n,), dtype=jnp.bool_)
    if n == 0:
        return same
    for k in keys:
        c = table.column(k)
        valid = c.valid_mask()
        eq_val = _col_values_equal_prev(c)
        eq_valid = valid[1:] == valid[:-1]
        both_null = ~valid[1:] & ~valid[:-1]
        eq = (eq_val & valid[1:] & eq_valid) | both_null
        same = same.at[1:].set(same[1:] & eq)
    return same.at[0].set(n == 0)


# Below this group-count bound (and when the boundary work is actually
# smaller than the scan it replaces — see the gate in groupby_aggregate)
# the boundary machinery switches from full-length scans to block-level
# reductions (see _group_starts / _boundary_prefix): a cumsum over n rows
# is latency-bound on the TPU (measured 68ms for 4M int64 lanes, ~0.9 GB/s
# effective — BASELINE.md), while a block-sum pass is bandwidth-bound and
# the per-boundary partials are O(m * block).
_SMALL_M = 1024
_MIN_BLOCK = 32
_MAX_BLOCK = 512


def _pick_block(n: int, m: int) -> int:
    """Block size balancing the two costs of the boundary path: the block-sum
    pass reads n rows; the per-boundary partials read ~2*m*block rows. Cap
    block so boundary work stays under the streaming pass."""
    b = _MIN_BLOCK
    while b < _MAX_BLOCK and 2 * m * (b * 2) <= n:
        b *= 2
    return b


def _group_starts(same: jnp.ndarray, q: int,
                  block: int) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Positions of the first ``q`` group starts over sorted keys, plus the
    exact total group count — without materializing per-row group ids.

    ``same[i]`` is True when sorted row i has the same key as row i-1, so
    group starts are the set bits of ``~same``. The g-th start is located
    with per-block popcounts: a tiny cumsum over block counts finds the
    block containing it, then a (q, BLOCK) within-block scan finds the bit.
    Absent groups (g >= total) report position n.
    """
    n = same.shape[0]
    flags = (~same).astype(jnp.int32)
    nb = -(-n // block)
    pad = nb * block - n
    fb = jnp.pad(flags, (0, pad)).reshape(nb, block)
    bpre = jnp.cumsum(fb.sum(axis=1))            # (nb,) inclusive
    total = bpre[-1].astype(jnp.int32)
    g = jnp.arange(q, dtype=jnp.int32)
    ib = jnp.clip(jnp.searchsorted(bpre, g, side="right"), 0, nb - 1)
    prev = jnp.where(ib > 0, bpre[jnp.maximum(ib - 1, 0)], 0)
    rank = g - prev                              # g-th start's rank in block
    rows = fb[ib]                                # (q, BLOCK) gather
    within = jnp.cumsum(rows, axis=1)
    hit = (within == (rank + 1)[:, None]) & (rows > 0)
    idx_in = jnp.argmax(hit, axis=1).astype(jnp.int32)
    starts = ib.astype(jnp.int32) * block + idx_in
    return jnp.where(g < total, starts, n).astype(jnp.int32), total


def _boundary_prefix(stack: jnp.ndarray, idx: jnp.ndarray,
                     block: int) -> jnp.ndarray:
    """Prefix sums of ``stack`` (n, k) evaluated only at the ``idx`` (q,)
    boundaries: per-block sums (one bandwidth pass) + a tiny block-level
    cumsum + a (q, BLOCK, k) masked partial for each boundary's own block.
    Replaces the full-length (n, k) cumsum when boundaries are few.
    int64-only: tree reductions of int64 are exact, so this matches the
    scan path bit-for-bit (float lanes take _segmented_sum_scan instead —
    prefix differencing would cancel catastrophically across groups)."""
    n, k = stack.shape
    nb = -(-n // block)
    pad = nb * block - n
    sp = jnp.pad(stack, ((0, pad), (0, 0))).reshape(nb, block, k)
    bpre = jnp.cumsum(sp.sum(axis=1), axis=0)    # (nb, k) inclusive
    ib = jnp.clip(idx // block, 0, nb - 1)
    r = idx - ib * block                         # may equal block at idx == n
    base = jnp.where((ib > 0)[:, None], bpre[jnp.maximum(ib - 1, 0)], 0)
    rows = sp[ib]                                # (q, block, k)
    mask = jnp.arange(block, dtype=jnp.int32)[None, :, None] < r[:, None, None]
    return base + jnp.sum(jnp.where(mask, rows, 0), axis=1)


def _range_sums_from_cumsum(cs: jnp.ndarray, lo: jnp.ndarray,
                            hi: jnp.ndarray) -> jnp.ndarray:
    """Per-range sums over rows [lo, hi) from an inclusive cumsum ``cs``
    of shape (n,) or (n, k); empty ranges (hi <= lo) give 0. The shared
    boundary-difference idiom of the int lane path and nunique."""
    n = cs.shape[0]
    upper = cs[jnp.clip(hi - 1, 0, n - 1)]
    lower_raw = cs[jnp.clip(lo - 1, 0, n - 1)]
    if cs.ndim == 2:
        lower = jnp.where((lo > 0)[:, None], lower_raw, 0)
        return jnp.where((hi > lo)[:, None], upper - lower, 0)
    lower = jnp.where(lo > 0, lower_raw, 0)
    return jnp.where(hi > lo, upper - lower, 0)


def _segmented_sum_scan(stack: jnp.ndarray,
                        seg_start: jnp.ndarray) -> jnp.ndarray:
    """Inclusive segmented running sum along sorted rows: the accumulator
    resets wherever ``seg_start`` is True, so each group's sum only ever
    adds that group's own values — the error of a group's float sum scales
    with the group's magnitude, like ``segment_sum``, NOT with the global
    prefix (prefix differencing cancels the running total and loses small
    groups that follow large ones entirely). The (sum, flag) combine is
    the segmented-sum monoid (associative) -> log-depth scan, no scatter.
    ``stack`` is (n, k); read per-group results at each group's last row."""

    def combine(a, b):
        av, af = a
        bv, bf = b
        return jnp.where(bf, bv, av + bv), af | bf

    v, _ = jax.lax.associative_scan(
        combine, (stack, seg_start[:, None] | jnp.zeros(
            stack.shape, jnp.bool_)))
    return v


def _segmented_extremum(vv: jnp.ndarray, seg_start: jnp.ndarray,
                        op: str) -> jnp.ndarray:
    """Inclusive segmented running min/max along sorted rows: the value
    resets wherever ``seg_start`` is True. The (value, start-flag) combine
    is the segmented-reduce monoid (associative), so
    ``lax.associative_scan`` compiles it to a log-depth scan — replacing
    ``jax.ops.segment_min/max``, whose scatter formulation serializes on
    the TPU (BASELINE.md measured 1.6-4x against scan forms). Read the
    per-group result at each group's last row."""
    pick = jnp.minimum if op == "min" else jnp.maximum

    def combine(a, b):
        av, af = a
        bv, bf = b
        return jnp.where(bf, bv, pick(av, bv)), af | bf

    v, _ = jax.lax.associative_scan(combine, (vv, seg_start))
    return v


_U32 = jnp.uint64(0xFFFFFFFF)


def _mean128_exact(lo: jnp.ndarray, hi: jnp.ndarray,
                   count: jnp.ndarray):
    """Exact DECIMAL128 mean: (S * 10^4) / count with HALF_UP rounding,
    computed entirely in integer limb arithmetic (TPU f64 is f32-pair
    emulated, so a float mean would silently lose precision — this path
    never touches floats). ``lo``/``hi`` are the exact 128-bit group sums
    (two's complement int64 pair), ``count`` the per-group non-null
    counts. Works because counts fit 32 bits: limb-wise long division
    with 32-bit limbs keeps every intermediate inside uint64.

    Returns (limbs (m, 2) int64, overflow bool[m]) — overflow when the
    widened value exceeds signed 128 bits (Spark ANSI: null + flag)."""
    ulo = lo.astype(jnp.uint64)
    uhi = hi.astype(jnp.uint64)
    neg = hi < 0
    # |S|: two's-complement negate the 128-bit pair where negative
    nlo = (~ulo) + jnp.uint64(1)
    nhi = (~uhi) + jnp.where(ulo == 0, jnp.uint64(1), jnp.uint64(0))
    mlo = jnp.where(neg, nlo, ulo)
    mhi = jnp.where(neg, nhi, uhi)
    m = [mlo & _U32, mlo >> 32, mhi & _U32, mhi >> 32]

    # |S| * 10^4 with carry propagation (limb * 1e4 < 2^46, in-range)
    ten4 = jnp.uint64(10_000)
    t, carry = [], jnp.zeros_like(mlo)
    for limb in m:
        cur = limb * ten4 + carry
        t.append(cur & _U32)
        carry = cur >> 32
    t.append(carry)  # 5th limb

    c = count.astype(jnp.uint64)
    count_too_big = c > _U32
    c_safe = jnp.maximum(jnp.where(count_too_big, jnp.uint64(1), c),
                         jnp.uint64(1))
    # + c//2: HALF_UP (away from zero on the magnitude)
    add = c_safe >> 1
    for i in range(5):
        cur = t[i] + add
        t[i] = cur & _U32
        add = cur >> 32

    # long division top -> bottom; r < c <= 2^32 keeps cur inside uint64
    q = [None] * 5
    r = jnp.zeros_like(mlo)
    for i in range(4, -1, -1):
        cur = (r << 32) | t[i]
        q[i] = cur // c_safe
        r = cur - q[i] * c_safe
    overflow = (q[4] != 0) | (q[3] >> 31 != 0) | count_too_big

    qlo = q[0] | (q[1] << 32)
    qhi = q[2] | (q[3] << 32)
    # negate back where the sum was negative
    rlo = jnp.where(neg, (~qlo) + jnp.uint64(1), qlo)
    rhi = jnp.where(
        neg, (~qhi) + jnp.where(qlo == 0, jnp.uint64(1), jnp.uint64(0)),
        qhi)
    limbs = jnp.stack(
        [rlo.astype(jnp.int64), rhi.astype(jnp.int64)], axis=-1)
    return limbs, overflow


# ---------------------------------------------------------------------------
# Exact DECIMAL128 variance: base-2^16 limb arithmetic.
#
# var_samp over unscaled 128-bit integers U is
#     (n * ΣU² − (ΣU)²) / (n(n−1)) * 10^(2·scale) (scale here follows the columnar convention value = unscaled·10^scale)
# The numerator is computed EXACTLY in 16-bit limbs (up to 2^316 — both
# terms are ≤ n²·2^254) and rounded to float64 once at the end, so the
# result carries none of the cancellation the two-pass float form suffers
# under TPU's f32-pair float64 (~49-bit mantissa, documented posture).
# 16-bit limbs keep every intermediate inside int64: per-row squared limbs
# are < 2^16, so per-group lane sums are < 2^16·n ≤ 2^47; convolution
# partial sums are < 24·2^32 < 2^37; limb×count products are < 2^47.
# ---------------------------------------------------------------------------

_M16 = jnp.int64(0xFFFF)


def _i128_mag_limbs16(lo: jnp.ndarray, hi: jnp.ndarray):
    """(8 magnitude limbs base 2^16, int64 each in [0, 2^16)) plus the
    negative mask of a two's-complement (lo, hi) int64 pair."""
    ulo = lo.astype(jnp.uint64)
    uhi = hi.astype(jnp.uint64)
    neg = hi < 0
    nlo = (~ulo) + jnp.uint64(1)
    nhi = (~uhi) + jnp.where(ulo == 0, jnp.uint64(1), jnp.uint64(0))
    mlo = jnp.where(neg, nlo, ulo)
    mhi = jnp.where(neg, nhi, uhi)
    u16 = jnp.uint64(0xFFFF)
    limbs = [((mlo >> (16 * k)) & u16).astype(jnp.int64) for k in range(4)]
    limbs += [((mhi >> (16 * k)) & u16).astype(jnp.int64) for k in range(4)]
    return limbs, neg


def _carry_norm16(vals: list, width: int):
    """Carry-normalize base-2^16 limbs (possibly signed / un-normalized
    int64) into ``width`` limbs in [0, 2^16) + the final arithmetic carry
    (0 when the value is non-negative and fits; -1 when negative)."""
    carry = jnp.int64(0)
    out = []
    for k in range(width):
        v = (vals[k] + carry) if k < len(vals) else (
            carry if k else jnp.int64(0))
        out.append(v & _M16)
        carry = v >> 16  # arithmetic shift == floor division: signed-safe
    return out, carry


def _negate_limbs16_if(limbs: list, neg: jnp.ndarray) -> list:
    """Two's-complement negate a normalized limb vector where ``neg``."""
    out = []
    carry = jnp.int64(1)
    for l in limbs:
        v = (_M16 - l) + carry
        out.append(jnp.where(neg, v & _M16, l))
        carry = v >> 16
    return out


def _conv_limbs16(a: list, b: list) -> list:
    """Un-normalized convolution c_p = Σ_{i+j=p} a_i·b_j (schoolbook
    multiply of two normalized limb vectors)."""
    c = [None] * (len(a) + len(b) - 1)
    for i, ai in enumerate(a):
        for j, bj in enumerate(b):
            t = ai * bj
            c[i + j] = t if c[i + j] is None else c[i + j] + t
    return c


def _sub_limbs16(a: list, b: list) -> list:
    """Exact a − b over normalized limb vectors, a ≥ b elementwise-wide."""
    out = []
    borrow = jnp.int64(0)
    for x, y in zip(a, b):
        v = x - y - borrow
        out.append(v & _M16)
        borrow = jnp.where(v < 0, jnp.int64(1), jnp.int64(0))
    return out


def _limbs16_to_f64(limbs: list) -> jnp.ndarray:
    """Round a normalized limb vector to float64 (top-down fold: one
    rounding per limb, ~len ulps total — vastly tighter than squaring in
    floats)."""
    acc = jnp.zeros_like(limbs[-1], dtype=jnp.float64)
    for l in reversed(limbs):
        acc = acc * 65536.0 + l.astype(jnp.float64)
    return acc


def _sq_limbs16_rows(lo: jnp.ndarray, hi: jnp.ndarray) -> list:
    """Per-row U² as 16 normalized base-2^16 limbs (U² < 2^254 always
    fits). These become int64 lanes for the streaming group-sum pass."""
    mag, _ = _i128_mag_limbs16(lo, hi)  # sign squares away
    sq, _carry = _carry_norm16(_conv_limbs16(mag, mag), 16)
    return sq


def _cmp_limbs16(a: list, b: list) -> jnp.ndarray:
    """int32 sign of (a - b) over equal-length normalized limb vectors:
    lexicographic from the top limb, vectorized."""
    cmp = jnp.zeros_like(a[0], dtype=jnp.int32)
    for x, y in zip(reversed(a), reversed(b)):
        here = jnp.sign(x - y).astype(jnp.int32)
        cmp = jnp.where(cmp != 0, cmp, here)
    return cmp


def _add_limbs16(a: list, b: list) -> list:
    """Exact a + b over normalized limb vectors (same length; the caller
    sizes the vectors so the sum cannot carry out of the top limb)."""
    out = []
    carry = jnp.int64(0)
    for x, y in zip(a, b):
        v = x + y + carry
        out.append(v & _M16)
        carry = v >> 16
    return out


def _signed_sub_limbs16(a_mag: list, a_neg: jnp.ndarray,
                        b_mag: list, b_neg: jnp.ndarray):
    """Sign-magnitude a − b over normalized limb vectors: returns
    (magnitude limbs, negative mask). Same-sign operands subtract the
    smaller magnitude from the larger; opposite signs add magnitudes."""
    same_sign = a_neg == b_neg
    cmp = _cmp_limbs16(a_mag, b_mag)      # sign of |a| - |b|
    a_ge = cmp >= 0
    hi_ = [jnp.where(a_ge, x, y) for x, y in zip(a_mag, b_mag)]
    lo_ = [jnp.where(a_ge, y, x) for x, y in zip(a_mag, b_mag)]
    diff = _sub_limbs16(hi_, lo_)
    added = _add_limbs16(a_mag, b_mag)
    mag = [jnp.where(same_sign, d, s) for d, s in zip(diff, added)]
    # same sign: result sign follows the dominant operand (a if |a|>=|b|
    # else flipped); opposite signs: a - (-|b|-ish) keeps a's sign when
    # a is the positive one... spelled out: a + (-b) where b_neg
    # flipped — the sum's sign is a's sign (magnitudes add).
    neg_same = jnp.where(a_ge, a_neg, ~b_neg)
    neg = jnp.where(same_sign, neg_same, a_neg)
    # canonical zero: non-negative
    is_zero = jnp.ones_like(a_neg)
    for l in mag:
        is_zero = is_zero & (l == 0)
    return mag, neg & ~is_zero


def split_sum128_lanes(lo: jnp.ndarray, hi: jnp.ndarray) -> list:
    """Four 32-bit limb lanes of a masked (lo, hi) int64 pair — int64
    lane sums over up to 2^31 rows cannot overflow. Shared by the
    groupby, reduction, and window exact-SUM paths."""
    m32 = jnp.int64(0xFFFFFFFF)
    return [lo & m32, (lo >> 32) & m32, hi & m32, hi >> 32]


def recombine_sum128(s0, s1, s2, s3):
    """(lo, hi, overflow) from four limb-lane sums: carry recombination
    with the signed-128-bit overflow check (`top` must be the sign
    extension of its own low 32 bits). The ONE implementation all three
    exact-sum paths share — a carry-math fix lands everywhere."""
    m32 = jnp.int64(0xFFFFFFFF)
    c0 = s0 & m32
    t = s1 + (s0 >> 32)
    lo = c0 | ((t & m32) << 32)
    u = s2 + (t >> 32)
    top = s3 + (u >> 32)
    hi = (u & m32) + (top << 32)
    ovf = top != ((top << 32) >> 32)
    return lo, hi, ovf


def minmax_sentinel(dt: DType, op: str):
    """The null-neutral fill for a min/max reduction over ``dt``: the
    dtype's +inf/max for ``min``, -inf/min for ``max``. One definition
    shared by the local bounded/general paths and the distributed merge
    (a dtype rule fixed in one place must apply to all three)."""
    np_dt = dt.storage_dtype
    if np_dt.kind == "f":
        lo, hi = -jnp.inf, jnp.inf
    else:
        info = np.iinfo(np_dt)
        lo, hi = info.min, info.max
    return hi if op == "min" else lo


def _sum_dtype(dt: DType) -> DType:
    """Spark widens SUM: integral -> INT64, decimal keeps scale (wider
    precision), floats stay floating."""
    if dt.is_decimal128:
        raise NotImplementedError(
            "DECIMAL128 aggregation is not supported yet (limb-pair "
            "arithmetic); cast to DECIMAL64 first if the values fit"
        )
    kind = dt.storage_dtype.kind
    if dt.is_decimal:
        return DType(TypeId.DECIMAL64, dt.scale)
    if kind in ("i", "u", "b"):
        return DType(TypeId.INT64)
    return dt


def _dense_group_bounds(group_id: jnp.ndarray | None, n: int,
                        m: int) -> tuple:
    """(num_groups, g_lo, g_hi) from sorted dense group ids: every
    per-group boundary is a binary search, not a scatter — scatters
    serialize on the TPU (measured 4x slower than the scan/searchsorted
    formulation at 4M rows on v5e; BASELINE.md). ``group_id`` is None
    only when n == 0."""
    garange = jnp.arange(m, dtype=jnp.int32)
    if group_id is None or n == 0:
        return (jnp.int32(0), jnp.zeros((m,), jnp.int32),
                jnp.zeros((m,), jnp.int32))
    num_groups = (group_id[-1] + 1).astype(jnp.int32)
    g_lo = jnp.searchsorted(group_id, garange, side="left").astype(jnp.int32)
    g_hi = jnp.searchsorted(group_id, garange, side="right").astype(jnp.int32)
    return num_groups, g_lo, g_hi


def _gather_group_keys(sorted_tbl: Table, keys: Sequence[int],
                       first_idx: jnp.ndarray, m: int,
                       n: int) -> list[Column]:
    """One output row per group: each key column gathered at its group's
    first sorted row (absent groups carry first_idx == n -> null)."""
    out_cols: list[Column] = []
    for k in keys:
        c = sorted_tbl.column(k)
        valid = jnp.zeros((m,), jnp.bool_)
        if n == 0:
            # nothing to gather from — emit all-null keys (num_groups = 0)
            if c.dtype.is_string:
                out_cols.append(Column(
                    c.dtype, jnp.zeros((m,), jnp.int32), valid,
                    chars=jnp.zeros((m, 1), jnp.uint8),
                ))
            elif c.dtype.is_decimal128:
                out_cols.append(
                    Column(c.dtype, jnp.zeros((m, 2), jnp.int64), valid)
                )
            else:
                out_cols.append(
                    Column(c.dtype, jnp.zeros((m,), c.dtype.jnp_dtype), valid)
                )
            continue
        safe_first = jnp.clip(first_idx, 0, n - 1)
        valid = c.valid_mask()[safe_first] & (first_idx < n)
        if c.dtype.is_string:
            from spark_rapids_jni_tpu.ops import strings as s

            g = s.gather_strings(c, safe_first)
            out_cols.append(Column(c.dtype, g.data, valid, chars=g.chars))
        else:
            out_cols.append(Column(c.dtype, c.data[safe_first], valid))
    return out_cols


def _groupby_aggregate_impl(row_args, aux, rvs, *, keys, aggs,
                            max_groups) -> GroupByResult:
    ((table, row_valid),) = row_args
    rv = row_valid
    if rv is None and rvs is not None:
        rv = rvs[0]
    n = table.num_rows
    m = n if max_groups is None else int(max_groups)
    order = sort_order(table, keys, row_valid=rv)
    sorted_tbl = gather(table, order)

    same = _rows_equal_prev(sorted_tbl, keys)
    if rv is not None:
        # phantom rows (bucketed padding tails / masked shuffle slots)
        # sort LAST and never start a group: they merge into the final
        # real group, where their all-null cells are neutral for every
        # aggregate (sums add 0, counts skip, min/max see sentinels,
        # first/last skip-null scans pass over them). The one positional
        # exception, last_include_nulls, is kept off the bucketed path by
        # the public wrapper (bucket_rows=False).
        same = same | ~rv[order]
    # small-m boundary path: locate group starts with block popcounts and
    # defer (often skip entirely) the full-length group-id scan. Gated on
    # the boundary work (2*m*block rows) actually undercutting the scan.
    small = n > 0 and m <= _SMALL_M and 2 * m * _MIN_BLOCK <= n
    block = _pick_block(n, m) if small else 0
    _gid_cache: list = []

    def _gid() -> jnp.ndarray:
        """Per-row dense group id — materialized only for aggregates with
        no boundary-difference form (float sums, min/max, string ranks)."""
        if not _gid_cache:
            _gid_cache.append((jnp.cumsum(~same) - 1).astype(jnp.int32))
        return _gid_cache[0]

    garange = jnp.arange(m, dtype=jnp.int32)
    if small:
        starts, num_groups = _group_starts(same, m + 1, block)
        g_lo, g_hi = starts[:m], starts[1:]
    else:
        num_groups, g_lo, g_hi = _dense_group_bounds(
            _gid() if n else None, n, m)
    overflowed = num_groups > m
    # first row of each group (n = absent, matching the old scatter-min)
    first_idx = jnp.where(g_hi > g_lo, g_lo, n)
    out_cols = _gather_group_keys(sorted_tbl, keys, first_idx, m, n)

    # Sum-form reductions (sums of ints/decimals/floats, all counts) batch
    # into ONE (n, k) prefix pass per accumulator dtype + per-group
    # boundary differences: one streaming pass, zero scatters. int64 lanes
    # are exact; float lanes carry parallel-reduction rounding (summation
    # order is unspecified, like any parallel float sum — Spark makes the
    # same non-guarantee). Min/max ride a segmented log-depth scan
    # (_segmented_extremum) instead of segment_* scatters.
    int_lanes: list[jnp.ndarray] = []    # (n,) int64 each
    float_lanes: list[jnp.ndarray] = []  # (n,) float64 each
    # sibling aggs on one column (sum+mean+var, every agg's count) must
    # share lanes, not stack identical copies into the streaming pass
    _lane_memo: dict = {}

    def lane(arr: jnp.ndarray, memo_key=None) -> tuple[str, int]:
        if memo_key is not None and memo_key in _lane_memo:
            return _lane_memo[memo_key]
        int_lanes.append(arr.astype(jnp.int64))
        spec = ("i", len(int_lanes) - 1)
        if memo_key is not None:
            _lane_memo[memo_key] = spec
        return spec

    def flane(arr: jnp.ndarray, memo_key=None) -> tuple[str, int]:
        if memo_key is not None and memo_key in _lane_memo:
            return _lane_memo[memo_key]
        float_lanes.append(arr.astype(jnp.float64))
        spec = ("f", len(float_lanes) - 1)
        if memo_key is not None:
            _lane_memo[memo_key] = spec
        return spec

    def _seg_sums(stack: jnp.ndarray) -> jnp.ndarray:
        """(n, k) lane stack -> (m, k) per-group sums. int64 lanes ride
        prefix differencing (exact, so cancellation is a non-issue): block
        prefixes when small, full cumsum + searchsorted differences
        otherwise. Float lanes instead ride a segmented scan that resets
        at group boundaries — prefix differencing would cancel the global
        running total and absorb small groups that follow large ones
        (catastrophic cancellation, worse under TPU's f32-pair f64)."""
        if n == 0:
            return jnp.zeros((m, stack.shape[1]), stack.dtype)
        if stack.dtype.kind == "f":
            run = _segmented_sum_scan(stack, ~same)
            out = run[jnp.clip(g_hi - 1, 0, n - 1)]
            return jnp.where((g_hi > g_lo)[:, None], out, 0)
        if small:
            # empty groups have g_lo == g_hi == n so their difference is 0
            pref = _boundary_prefix(
                stack, jnp.concatenate([g_hi, g_lo]), block)
            return pref[:m] - pref[m:]
        return _range_sums_from_cumsum(
            jnp.cumsum(stack, axis=0), g_lo, g_hi)

    _M32 = jnp.int64(0xFFFFFFFF)

    plan = []  # (op, column, acc_dt / other column, lane ids / None)
    for col_idx, op in aggs:
        c = sorted_tbl.column(col_idx)
        valid = c.valid_mask()
        if isinstance(op, tuple):
            # binary aggregates (covar_samp/covar_pop/corr): Spark counts
            # only rows where BOTH operands are non-null, so these ride
            # dedicated pairwise-masked sum + count lanes (memoized per
            # column pair — corr shares them with sibling covar aggs).
            kind, oidx = op
            cy = sorted_tbl.column(oidx)
            for cc in (c, cy):
                if cc.dtype.is_string or (
                        not cc.dtype.is_decimal128
                        and cc.dtype.storage_dtype.kind not in
                        ("i", "u", "f")):
                    raise TypeError(
                        f"{kind} needs numeric columns, got {cc.dtype}")
            both = valid & cy.valid_mask()
            pair = (id(c), id(cy))
            both_lane = lane(both, memo_key=(pair, "count2"))
            if c.dtype.is_decimal128 or cy.dtype.is_decimal128:
                # exact wide path: both operands must have integral
                # storage (a float partner has no exact form — cast it
                # to a decimal first)
                for cc in (c, cy):
                    if (not cc.dtype.is_decimal128
                            and cc.dtype.storage_dtype.kind not in
                            ("i", "u")):
                        raise TypeError(
                            f"{kind} with a DECIMAL128 operand needs an "
                            f"integral-storage partner, got {cc.dtype}")

                def _as_i128(cc):
                    if cc.dtype.is_decimal128:
                        lo_ = jnp.where(both, cc.data[:, 0], jnp.int64(0))
                        hi_ = jnp.where(both, cc.data[:, 1], jnp.int64(0))
                    else:
                        v = jnp.where(
                            both, cc.data.astype(jnp.int64), jnp.int64(0))
                        if cc.dtype.storage_dtype.kind == "u":
                            # unsigned: the int64 cast keeps the BITS;
                            # zero-extend (v >> 63 would sign-wrap
                            # values >= 2^63)
                            hi_ = jnp.zeros_like(v)
                        else:
                            hi_ = v >> 63       # sign extension
                        lo_ = v
                    return lo_, hi_

                lox, hix = _as_i128(c)
                loy, hiy = _as_i128(cy)
                magx, negx = _i128_mag_limbs16(lox, hix)
                magy, negy = _i128_mag_limbs16(loy, hiy)
                sx_specs = tuple(
                    lane(jnp.where(negx, -magx[k], magx[k]),
                         memo_key=(pair, "cx128", k)) for k in range(8))
                sy_specs = tuple(
                    lane(jnp.where(negy, -magy[k], magy[k]),
                         memo_key=(pair, "cy128", k)) for k in range(8))
                xy, _ = _carry_norm16(_conv_limbs16(magx, magy), 16)
                neg_xy = negx != negy
                sxy_specs = tuple(
                    lane(jnp.where(neg_xy, -xy[k], xy[k]),
                         memo_key=(pair, "cxy128", k)) for k in range(16))
                if kind == "corr":
                    sqx = _sq_limbs16_rows(lox, hix)
                    sqy = _sq_limbs16_rows(loy, hiy)
                    sq_specs = (
                        tuple(lane(sqx[k], memo_key=(pair, "cqx128", k))
                              for k in range(16)),
                        tuple(lane(sqy[k], memo_key=(pair, "cqy128", k))
                              for k in range(16)),
                    )
                else:
                    sq_specs = None
                plan.append((kind + "128pair", c, cy,
                             (sx_specs, sy_specs, sxy_specs, sq_specs),
                             both_lane))
                continue
            specs = []
            for cc, tag in ((c, "sx"), (cy, "sy")):
                vv = jnp.where(both, cc.data, jnp.zeros_like(cc.data))
                mk = (pair, tag)
                specs.append(
                    lane(vv, memo_key=mk)
                    if cc.dtype.storage_dtype.kind in ("i", "u")
                    else flane(vv, memo_key=mk))
            plan.append((kind, c, cy, tuple(specs), both_lane))
            continue
        count_lane = lane(valid, memo_key=(id(c), "count"))
        if op in ("sum", "mean") and c.dtype.is_decimal128:
            # exact 128-bit sum: split (lo, hi) into four 32-bit limb
            # lanes so no int64 lane can overflow (sums bounded by
            # 2^32 * n), recombined with carry propagation below; totals
            # beyond 128 bits null the group and set sum_overflow.
            # mean128 divides the exact sum by the count with limb-wise
            # long division (exact, no f64) — see the consume branch.
            lo = jnp.where(valid, c.data[:, 0], jnp.int64(0))
            hi = jnp.where(valid, c.data[:, 1], jnp.int64(0))
            lanes128 = tuple(
                lane(l, memo_key=(id(c), "s128", k))
                for k, l in enumerate(split_sum128_lanes(lo, hi)))
            if op == "mean":
                # Spark avg(decimal) carries 4 extra fractional digits
                plan.append(("mean128", c, decimal128(c.dtype.scale - 4),
                             lanes128, count_lane))
            else:
                plan.append(("sum128", c, c.dtype, lanes128, count_lane))
            continue
        if op in ("var", "std", "var_pop", "std_pop"):
            if c.dtype.is_decimal128:
                # exact wide second moments: 8 signed ±|U| limb lanes for
                # ΣU plus 16 per-row U² limb lanes for ΣU² — every lane
                # sum is exact int64; the variance numerator is combined
                # in wide limb arithmetic in the consume loop and rounded
                # to float64 once.
                lo = jnp.where(valid, c.data[:, 0], jnp.int64(0))
                hi = jnp.where(valid, c.data[:, 1], jnp.int64(0))
                mag, negr = _i128_mag_limbs16(lo, hi)
                key128 = id(c)
                sum_specs = tuple(
                    lane(jnp.where(negr, -mag[k], mag[k]),
                         memo_key=(key128, "v128s", k))
                    for k in range(8))
                sq = _sq_limbs16_rows(lo, hi)
                sq_specs = tuple(
                    lane(sq[k], memo_key=(key128, "v128q", k))
                    for k in range(16))
                plan.append((op + "128", c, None, (sum_specs, sq_specs),
                             count_lane))
                continue
            if c.dtype.is_string or \
                    c.dtype.storage_dtype.kind not in ("i", "u", "f"):
                raise TypeError(
                    f"var/std need a numeric column, got {c.dtype}"
                )
            # first pass (the per-group sum for the mean) rides the lane
            # machinery: exact int64 for integral/decimal storage, a float
            # lane otherwise; the centered second pass is a _seg_sums call
            # in the consume loop (no scatter either way)
            vv = jnp.where(valid, c.data, jnp.zeros_like(c.data))
            if c.dtype.storage_dtype.kind in ("i", "u"):
                sum_spec = lane(vv, memo_key=(id(c), "sum_i"))
            else:
                sum_spec = flane(vv, memo_key=(id(c), "sum_f"))
            plan.append((op, c, None, sum_spec, count_lane))
            continue
        if op == "nunique":
            plan.append((op, c, DType(TypeId.INT64), col_idx, count_lane))
            continue
        if op in ("sum", "mean"):
            acc_dt = _sum_dtype(c.dtype)
            vv = jnp.where(valid, c.data, jnp.zeros_like(c.data))
            if acc_dt.storage_dtype.kind in ("i", "u"):
                plan.append((op, c, acc_dt,
                             lane(vv, memo_key=(id(c), "sum_i")), count_lane))
            else:  # float accumulation rides a float lane — no scatter
                plan.append((op, c, acc_dt,
                             flane(vv, memo_key=(id(c), "sum_f")), count_lane))
        else:
            plan.append((op, c, None, None, count_lane))

    _rank_order_cache: dict = {}  # value-sort order per column, shared
                                  # between a column's min and max aggs
    _var_cache: dict = {}         # per-column variance, shared var<->std
    _covar_cache: dict = {}       # per-pair centered moments, shared
                                  # between covar_samp/covar_pop/corr

    def _rank_minmax(c: Column, op: str, vcount: jnp.ndarray) -> Column:
        """MIN/MAX of a column with no elementwise-reducible storage
        (strings, DECIMAL128 limb pairs): rank rows by value order (one
        sort of the value column), segment-reduce the int ranks, gather
        the winning row — order statistics via ranks instead of per-group
        comparator loops."""
        if n == 0:
            if c.dtype.is_string:
                return Column(c.dtype, jnp.zeros((m,), jnp.int32),
                              jnp.zeros((m,), jnp.bool_),
                              chars=jnp.zeros((m, 1), jnp.uint8))
            return Column(c.dtype, jnp.zeros((m, 2), jnp.int64),
                          jnp.zeros((m,), jnp.bool_))
        cache_key = id(c)
        if cache_key not in _rank_order_cache:
            order_c = sort_order(
                Table([c]), [0], nulls_first=[False]  # nulls last
            )
            # inverse permutation via argsort (a sort, not a scatter —
            # scatters serialize on TPU); cached so a column's min and max
            # share both sorts
            _rank_order_cache[cache_key] = (
                order_c, jnp.argsort(order_c).astype(jnp.int32))
        order_v, rank = _rank_order_cache[cache_key]
        # null values never win: give them the worst rank for the op
        sentinel = jnp.int32(n if op == "min" else -1)
        rank = jnp.where(c.valid_mask(), rank, sentinel)
        # segmented log-depth scan over the key-sorted rows, read at each
        # group's last row — replaces the .at[gid].min/max scatter
        run = _segmented_extremum(rank, ~same, op)
        best = run[jnp.clip(g_hi - 1, 0, n - 1)]
        has_any = vcount > 0
        winner_row = order_v[jnp.clip(best, 0, max(n - 1, 0))]
        if c.dtype.is_string:
            from spark_rapids_jni_tpu.ops import strings as s

            g = s.gather_strings(c, winner_row)
            return Column(c.dtype, g.data, has_any, chars=g.chars)
        return Column(c.dtype, c.data[winner_row], has_any)

    seg_i = (_seg_sums(jnp.stack(int_lanes, axis=1)) if int_lanes
             else jnp.zeros((m, 1), jnp.int64))
    seg_f = (_seg_sums(jnp.stack(float_lanes, axis=1)) if float_lanes
             else jnp.zeros((m, 1), jnp.float64))

    def seg_col(spec: tuple[str, int]) -> jnp.ndarray:
        kind, idx = spec
        return seg_i[:, idx] if kind == "i" else seg_f[:, idx]

    def _row_gid() -> jnp.ndarray:
        """Per-row dense group id for the centered variance pass. In the
        small-m path group starts are already known, so a searchsorted
        replaces the full-length cumsum scan."""
        if small:
            return (jnp.searchsorted(
                g_lo, jnp.arange(n, dtype=jnp.int32), side="right"
            ) - 1).astype(jnp.int32)
        return _gid()

    sum128_overflow = jnp.bool_(False)
    for op, c, acc_dt, val_lane, count_lane in plan:
        valid = c.valid_mask()
        vcount = seg_col(count_lane)
        if op in ("sum128", "mean128"):
            s0, s1, s2, s3 = (seg_col(i) for i in val_lane)
            # shared carry recombination + Spark-ANSI overflow check
            lo, hi, ovf = recombine_sum128(s0, s1, s2, s3)
            ovf_g = ovf & (vcount > 0)
            if op == "mean128":
                limbs, div_ovf = _mean128_exact(lo, hi, vcount)
                ovf_g = ovf_g | (div_ovf & (vcount > 0))
                out = limbs
            else:
                out = jnp.stack([lo, hi], axis=-1)
            sum128_overflow = sum128_overflow | jnp.any(
                ovf_g & (garange < num_groups))
            out_cols.append(Column(
                acc_dt, out, (vcount > 0) & ~ovf_g
            ))
            continue
        if op == "count":
            out_cols.append(
                Column(DType(TypeId.INT64), vcount,
                       jnp.arange(m) < num_groups)
            )
            continue
        if op in ("sum", "mean"):
            has_any = vcount > 0
            total = seg_col(val_lane).astype(acc_dt.jnp_dtype)
            if op == "sum":
                out_cols.append(Column(acc_dt, total, has_any))
            else:
                denom = jnp.maximum(vcount, 1).astype(jnp.float64)
                mean = total.astype(jnp.float64) / denom
                if c.dtype.is_decimal:
                    # Rescale so the FLOAT64 result carries the true value:
                    # the unscaled-integer mean alone is off by 10^-scale
                    # and the float dtype has no scale field to recover it.
                    mean = mean * (10.0 ** c.dtype.scale)
                out_cols.append(Column(DType(TypeId.FLOAT64), mean, has_any))
            continue
        if op in ("var128", "std128", "var_pop128", "std_pop128"):
            # exact DECIMAL128 variance: combine the 8+16 exact lane sums
            # into n·ΣU² − (ΣU)² with base-2^16 limb arithmetic (≤ 2^316,
            # every intermediate in int64), round to float64 once, then
            # divide by n(n−1) (sample) or n² (population) and apply
            # 10^(2·scale). The exact numerator is cached per column and
            # shared by all four variants.
            cache_key = id(c)
            if cache_key not in _var_cache:
                sum_specs, sq_specs = val_lane
                s_lanes = [seg_col(i) for i in sum_specs]
                q_lanes = [seg_col(i) for i in sq_specs]
                # exact ΣU: signed lane sums → 12 normalized limbs + sign
                # (|ΣU| < 2^16·2^31·2^112 = 2^159 < 2^192); the final
                # carry is the sign (-1 ⟺ negative)
                sl, s_carry = _carry_norm16(s_lanes, 12)
                sl = _negate_limbs16_if(sl, s_carry < 0)
                # (ΣU)²: 12×12 convolution → 24 normalized limbs
                bsq, _ = _carry_norm16(_conv_limbs16(sl, sl), 24)
                # n·ΣU²: lane sums (< 2^47) → 20 limbs, × count (< 2^31
                # keeps limb·n < 2^47), renormalized to 24
                ql, _ = _carry_norm16(q_lanes, 20)
                nq, _ = _carry_norm16([q * vcount for q in ql], 24)
                # numerator is ≥ 0 by Cauchy–Schwarz — exact subtraction
                num = _limbs16_to_f64(_sub_limbs16(nq, bsq))
                _var_cache[cache_key] = num * (10.0 ** (2 * c.dtype.scale))
            pop = "pop" in op
            denom = (vcount * vcount if pop
                     else vcount * (vcount - 1))
            var = _var_cache[cache_key] / jnp.maximum(
                denom, 1).astype(jnp.float64)
            out_val = jnp.sqrt(var) if op.startswith("std") else var
            out_cols.append(Column(
                DType(TypeId.FLOAT64), out_val,
                vcount > (0 if pop else 1)
            ))
            continue
        if op in ("var", "std", "var_pop", "std_pop"):
            # variance (Spark var_samp/stddev_samp/var_pop/stddev_pop):
            # two-pass centered form in float64 for numerical robustness;
            # the centered second moment M2 is computed once per column
            # and shared by all four variants (the _rank_order_cache
            # pattern). The group sum came from the lane machinery (exact
            # int64 for integral/decimal storage); the centered second
            # pass is one more _seg_sums lane — zero scatters end to end.
            # NB: TPU f64 is f32-pair emulated (~49-bit mantissa) —
            # documented precision posture, matching the mean contract.
            cache_key = id(c)
            if cache_key not in _var_cache:
                scale_f = (10.0 ** c.dtype.scale) if c.dtype.is_decimal \
                    else 1.0
                denom = jnp.maximum(vcount, 1).astype(jnp.float64)
                mean_g = seg_col(val_lane).astype(jnp.float64) * scale_f \
                    / denom
                if n:
                    x = c.data.astype(jnp.float64) * scale_f
                    centered = jnp.where(valid, x - mean_g[_row_gid()], 0.0)
                    m2 = _seg_sums((centered * centered)[:, None])[:, 0]
                else:
                    m2 = jnp.zeros((m,), jnp.float64)
                _var_cache[cache_key] = m2
            pop = op.endswith("_pop")
            var = _var_cache[cache_key] / jnp.maximum(
                vcount - (0 if pop else 1), 1).astype(jnp.float64)
            out_val = jnp.sqrt(var) if op.startswith("std") else var
            out_cols.append(Column(
                DType(TypeId.FLOAT64), out_val,
                vcount > (0 if pop else 1)
            ))
            continue
        if op in ("covar_samp128pair", "covar_pop128pair",
                  "corr128pair"):
            # exact DECIMAL128(-compatible) covariance/correlation: the
            # numerator n·ΣXY − ΣX·ΣY is assembled in sign-magnitude
            # base-2^16 limb arithmetic (|terms| ≤ n²·2^254 < 2^317,
            # 25-limb vectors) and rounded to float64 once. corr divides
            # by the exact variance numerators, so the decimal scales
            # cancel identically.
            cy = acc_dt
            sx_specs, sy_specs, sxy_specs, sq_specs = val_lane
            WIDTH = 25
            pair_key = (id(c), id(cy), "128pair")

            def _norm_sums():
                # normalized sign-magnitude ΣX / ΣY (shared by the
                # numerator and corr's variance terms)
                if (pair_key, "sums") not in _covar_cache:
                    sxl, cxc = _carry_norm16(
                        [seg_col(i) for i in sx_specs], 12)
                    sx_neg = cxc < 0
                    sxl = _negate_limbs16_if(sxl, sx_neg)
                    syl, cyc = _carry_norm16(
                        [seg_col(i) for i in sy_specs], 12)
                    sy_neg = cyc < 0
                    syl = _negate_limbs16_if(syl, sy_neg)
                    _covar_cache[(pair_key, "sums")] = (
                        sxl, sx_neg, syl, sy_neg)
                return _covar_cache[(pair_key, "sums")]

            if (pair_key, "num") not in _covar_cache:
                sxl, sx_neg, syl, sy_neg = _norm_sums()
                sxyl, cxyc = _carry_norm16(
                    [seg_col(i) for i in sxy_specs], 20)
                sxy_neg = cxyc < 0
                sxyl = _negate_limbs16_if(sxyl, sxy_neg)
                # A = n·|ΣXY| (sign sxy_neg), B = |ΣX|·|ΣY| (sign xor)
                a_mag, _ = _carry_norm16(
                    [l * vcount for l in sxyl], WIDTH)
                b_mag, _ = _carry_norm16(_conv_limbs16(sxl, syl), WIDTH)
                n_mag, n_neg = _signed_sub_limbs16(
                    a_mag, sxy_neg, b_mag, sx_neg != sy_neg)
                _covar_cache[(pair_key, "num")] = (
                    jnp.where(n_neg, -1.0, 1.0) * _limbs16_to_f64(n_mag))
            num = _covar_cache[(pair_key, "num")]
            var_nums = None
            if sq_specs is not None:
                if (pair_key, "varnums") not in _covar_cache:
                    sxl, _sxn, syl, _syn = _norm_sums()
                    vn = []
                    for sq, sl in ((sq_specs[0], sxl),
                                   (sq_specs[1], syl)):
                        ql, _ = _carry_norm16(
                            [seg_col(i) for i in sq], 20)
                        nq, _ = _carry_norm16(
                            [q * vcount for q in ql], WIDTH)
                        bsq, _ = _carry_norm16(
                            _conv_limbs16(sl, sl), WIDTH)
                        vn.append(
                            _limbs16_to_f64(_sub_limbs16(nq, bsq)))
                    _covar_cache[(pair_key, "varnums")] = vn
                var_nums = _covar_cache[(pair_key, "varnums")]
            scale = sum((cc.dtype.scale if cc.dtype.is_decimal else 0)
                        for cc in (c, cy))
            if op == "corr128pair":
                # scales cancel between numerator and the sqrt of the
                # variance-numerator product
                out_val = num / jnp.sqrt(var_nums[0] * var_nums[1])
                validity = vcount > 0
            elif op == "covar_pop128pair":
                out_val = num / jnp.maximum(
                    vcount * vcount, 1).astype(jnp.float64) \
                    * (10.0 ** scale)
                validity = vcount > 0
            else:
                out_val = num / jnp.maximum(
                    vcount * (vcount - 1), 1).astype(jnp.float64) \
                    * (10.0 ** scale)
                validity = vcount > 1
            out_cols.append(
                Column(DType(TypeId.FLOAT64), out_val, validity))
            continue
        if op in ("covar_samp", "covar_pop", "corr"):
            # pairwise centered moments Σcx·cy, Σcx², Σcy² in one
            # _seg_sums pass (float64 two-pass form, the var posture),
            # cached per column pair so corr + sibling covar aggs share
            # it. vcount here is the BOTH-non-null count (Spark's
            # Covariance/Corr row semantics).
            cy = acc_dt
            spec_x, spec_y = val_lane
            cache_key = (id(c), id(cy))
            if cache_key not in _covar_cache:
                sfx = (10.0 ** c.dtype.scale) if c.dtype.is_decimal else 1.0
                sfy = (10.0 ** cy.dtype.scale) if cy.dtype.is_decimal \
                    else 1.0
                denom = jnp.maximum(vcount, 1).astype(jnp.float64)
                mean_x = seg_col(spec_x).astype(jnp.float64) * sfx / denom
                mean_y = seg_col(spec_y).astype(jnp.float64) * sfy / denom
                if n:
                    both = valid & cy.valid_mask()
                    gid = _row_gid()
                    cxv = jnp.where(
                        both,
                        c.data.astype(jnp.float64) * sfx - mean_x[gid], 0.0)
                    cyv = jnp.where(
                        both,
                        cy.data.astype(jnp.float64) * sfy - mean_y[gid],
                        0.0)
                    moments = _seg_sums(jnp.stack(
                        [cxv * cyv, cxv * cxv, cyv * cyv], axis=1))
                else:
                    moments = jnp.zeros((m, 3), jnp.float64)
                _covar_cache[cache_key] = moments
            sxy, sxx, syy = (
                _covar_cache[cache_key][:, i] for i in range(3))
            if op == "corr":
                # constant series / singleton groups give 0/0 → NaN, the
                # Spark Corr value posture; only empty groups are null
                out_val = sxy / jnp.sqrt(sxx * syy)
                validity = vcount > 0
            elif op == "covar_pop":
                out_val = sxy / jnp.maximum(vcount, 1).astype(jnp.float64)
                validity = vcount > 0
            else:  # covar_samp: n ≤ 1 is null (the var_samp posture)
                out_val = sxy / jnp.maximum(
                    vcount - 1, 1).astype(jnp.float64)
                validity = vcount > 1
            out_cols.append(
                Column(DType(TypeId.FLOAT64), out_val, validity))
            continue
        if op == "nunique":
            # distinct non-null values per group: secondary sort by
            # (keys, value) with value nulls last; count positions that
            # start a new valid value run within their group
            col_idx2 = val_lane  # original column index stashed in plan
            nf = [True] * len(keys) + [False]
            order2 = sort_order(table, list(keys) + [col_idx2],
                                nulls_first=nf, row_valid=rv)
            sub = gather(
                Table([table.column(k) for k in keys]
                      + [table.column(col_idx2)]), order2)
            kix = list(range(len(keys)))
            same_k = _rows_equal_prev(sub, kix)
            if rv is not None:
                # phantom rows merge into the last group here too, so
                # gid2's group numbering stays aligned with gid's
                same_k = same_k | ~rv[order2]
            vcol = sub.column(len(keys))
            vvalid2 = vcol.valid_mask()
            eqv = _col_values_equal_prev(vcol)
            prev_same_valid = jnp.concatenate(
                [jnp.zeros((1,), jnp.bool_), eqv & vvalid2[:-1]])
            flag = vvalid2 & (~same_k | ~prev_same_valid)
            # gid2 is monotone over its own sort, so per-group flag counts
            # are cumsum boundary differences — same idiom as the lanes,
            # no scatter
            if n:
                gid2 = (jnp.cumsum(~same_k) - 1).astype(jnp.int32)
                lo2 = jnp.searchsorted(gid2, garange, side="left")
                hi2 = jnp.searchsorted(gid2, garange, side="right")
                cnt = _range_sums_from_cumsum(
                    jnp.cumsum(flag.astype(jnp.int64)), lo2, hi2)
            else:
                cnt = jnp.zeros((m,), jnp.int64)
            out_cols.append(
                Column(acc_dt, cnt, garange < num_groups)
            )
            continue
        if op in ("first", "last", "first_include_nulls",
                  "last_include_nulls"):
            # "first"/"last" skip nulls (Spark First/Last with
            # ignoreNulls=true): a segmented first-valid scan over row
            # indices finds the winning row — one mechanism for every
            # dtype, gathered afterwards. The *_include_nulls variants
            # (Spark's DEFAULT ignoreNulls=false) are simply the group's
            # first/last ROW: g_lo / g_hi - 1, no scan at all. Rows are
            # key-sorted STABLY, so order within a group is input order.
            if op.endswith("_include_nulls"):
                if op.startswith("first"):
                    win = jnp.where(g_hi > g_lo, g_lo.astype(jnp.int64),
                                    jnp.int64(-1))
                else:
                    win = jnp.where(g_hi > g_lo,
                                    (g_hi - 1).astype(jnp.int64),
                                    jnp.int64(-1))
                has = (win >= 0)
                if n:
                    row = jnp.clip(win, 0, n - 1).astype(jnp.int32)
                    has = has & valid[row]
                else:
                    row = jnp.zeros((m,), jnp.int32)
                    has = jnp.zeros((m,), jnp.bool_)
            elif n:
                row_idx = jnp.arange(n, dtype=jnp.int64)
                cand = jnp.where(valid, row_idx, jnp.int64(-1))

                if op == "first":
                    def combine(a, b):
                        av, af = a
                        bv, bf = b
                        return jnp.where(
                            bf, bv, jnp.where(av >= 0, av, bv)), af | bf
                else:
                    def combine(a, b):
                        av, af = a
                        bv, bf = b
                        return jnp.where(
                            bf, bv, jnp.where(bv >= 0, bv, av)), af | bf

                run, _ = jax.lax.associative_scan(combine, (cand, ~same))
                win = run[jnp.clip(g_hi - 1, 0, n - 1)]
                has = (win >= 0) & (g_hi > g_lo)
                row = jnp.clip(win, 0, n - 1).astype(jnp.int32)
            else:
                has = jnp.zeros((m,), jnp.bool_)
                row = jnp.zeros((m,), jnp.int32)
            if c.dtype.is_string:
                from spark_rapids_jni_tpu.ops import strings as s

                if n:
                    g = s.gather_strings(c, row)
                    out_cols.append(Column(c.dtype, g.data, has,
                                           chars=g.chars))
                else:
                    out_cols.append(Column(
                        c.dtype, jnp.zeros((m,), jnp.int32), has,
                        chars=jnp.zeros((m, 1), jnp.uint8)))
            elif n:
                out_cols.append(Column(c.dtype, c.data[row], has))
            else:
                shape = (m, 2) if c.dtype.is_decimal128 else (m,)
                out_cols.append(Column(
                    c.dtype, jnp.zeros(shape, c.data.dtype), has))
            continue
        # min / max with null-neutral sentinels
        if c.dtype.is_string or c.dtype.is_decimal128:
            out_cols.append(_rank_minmax(c, op, vcount))
            continue
        sentinel = minmax_sentinel(c.dtype, op)
        vv = jnp.where(valid, c.data, jnp.asarray(sentinel, c.data.dtype))
        if n:
            run = _segmented_extremum(vv, ~same, op)
            red = run[jnp.clip(g_hi - 1, 0, n - 1)]
        else:
            red = jnp.zeros((m,), c.data.dtype)
        out_cols.append(Column(c.dtype, red, vcount > 0))

    return GroupByResult(Table(out_cols), num_groups, overflowed,
                         sum128_overflow)


@func_range("groupby_aggregate")
def groupby_aggregate(
    table: Table,
    keys: Sequence[int],
    aggs: Sequence[tuple[int, str]],
    max_groups: int | None = None,
    row_valid: jnp.ndarray | None = None,
) -> GroupByResult:
    """Group by `keys`; compute [(value_col, op)] aggregates.

    Returns keys + one column per agg, in order, padded to ``max_groups``
    rows (default: n, which can never overflow). A smaller ``max_groups``
    bounds output memory for high-cardinality aggregation; if the true
    group count exceeds it, rows of the excess groups are dropped and
    ``overflowed`` is set so the host can grow and retry
    (``groupby_aggregate_auto``).

    Rows where ``row_valid`` is False are phantom rows (masked shuffle
    slots): they contribute to no group and no aggregate.
    """
    for _, op in aggs:
        if isinstance(op, tuple):
            if (len(op) != 2 or op[0] not in SUPPORTED_BINARY_AGGS
                    or not isinstance(op[1], numbers.Integral)
                    or not 0 <= op[1] < table.num_columns):
                raise ValueError(
                    f"unsupported binary aggregation {op!r}; expected "
                    f"(op, col_y) with op in {SUPPORTED_BINARY_AGGS} and "
                    f"col_y a column index of the input table")
        elif op not in SUPPORTED_AGGS:
            raise ValueError(f"unsupported aggregation {op!r}")
    keys_t = tuple(int(k) for k in keys)
    aggs_t = tuple(
        (int(c), (tuple(op) if isinstance(op, tuple) else op))
        for c, op in aggs)
    # last_include_nulls is POSITIONAL (the group's literal last row):
    # a padded tail row would be that last row, so such plans run at
    # exact shape (memoized, just not bucketed)
    bucket = not any(op == "last_include_nulls" for _, op in aggs_t)
    from spark_rapids_jni_tpu.runtime import dispatch

    return dispatch.call(
        "groupby_aggregate",
        partial(_groupby_aggregate_impl, keys=keys_t, aggs=aggs_t,
                max_groups=max_groups),
        ((table, row_valid),),
        statics=(keys_t, aggs_t, max_groups),
        slice_rows=(max_groups is None),
        bucket_rows=bucket)


def groupby_aggregate_auto(
    table: Table,
    keys: Sequence[int],
    aggs: Sequence[tuple[int, str]],
    initial_max_groups: int,
    growth: int = 4,
) -> GroupByResult:
    """Host-level grow-and-retry around the cardinality bound: start at
    ``initial_max_groups`` and multiply by ``growth`` until the result fits
    (capped at n, which always fits). Each retry recompiles for the new
    static bound — the bucketed-padding discipline, applied to output
    cardinality. Growth runs through the shared resilience ladder
    (``runtime/resilience.escalate``, rung ``grow_capacity``) with the
    capacity schedule — min(initial·growth^k, n) — preserved exactly; with
    ``resilience.enabled=false`` the pre-resilience loop runs verbatim."""
    from spark_rapids_jni_tpu.runtime import resilience

    n = table.num_rows
    m = max(1, int(initial_max_groups))
    if not resilience.enabled() or n < 1:
        while True:
            res = groupby_aggregate(table, keys, aggs, max_groups=min(m, n))
            if m >= n or not bool(res.overflowed):
                return res
            m *= growth

    def _attempt(cap):
        res = groupby_aggregate(table, keys, aggs, max_groups=cap)
        # cap == n always fits (distinct groups <= rows): never grow past it
        return res, cap < n and bool(res.overflowed), None

    return resilience.escalate(
        "groupby_aggregate_auto", _attempt, seam="dispatch.execute",
        initial=m, growth=growth, max_capacity=n, rows=n)


@func_range("groupby_percentile")
def groupby_percentile(
    table: Table,
    keys: Sequence[int],
    value_col: int,
    qs: Sequence[float],
    max_groups: int | None = None,
) -> GroupByResult:
    """Exact per-group percentiles (Spark `percentile` semantics: linear
    interpolation between closest ranks over non-null values; median is
    qs=[0.5]). Output: keys + one FLOAT64 column per q.

    Sort-based order statistics: ONE sort by (keys..., value) with value
    nulls last, so each group's valid values occupy a contiguous run
    [g_lo, g_lo + cnt); every percentile is then two gathers at computed
    offsets — no scatters, no per-group loops. Exact, unlike HLL-style
    sketches; the reference's capability family is cuDF's
    quantile/median groupby (vendored surface, SURVEY.md section 2.2).
    """
    qs = [float(q) for q in qs]
    if not qs or any(q < 0.0 or q > 1.0 for q in qs):
        raise ValueError("percentile fractions must be in [0, 1]")
    c_in = table.column(value_col)
    if c_in.dtype.is_string or c_in.dtype.is_decimal128:
        raise NotImplementedError(
            "groupby_percentile needs fixed-width numeric values")
    n = table.num_rows
    m = n if max_groups is None else int(max_groups)
    sort_keys = list(keys) + [value_col]
    order = sort_order(
        table, sort_keys,
        nulls_first=[True] * len(keys) + [False])
    sorted_tbl = gather(table, order)
    same = _rows_equal_prev(sorted_tbl, keys)
    group_id = (jnp.cumsum(~same) - 1).astype(jnp.int32) if n else None
    num_groups, g_lo, g_hi = _dense_group_bounds(group_id, n, m)
    overflowed = num_groups > m
    first_idx = jnp.where(g_hi > g_lo, g_lo, n)
    out_cols = _gather_group_keys(sorted_tbl, keys, first_idx, m, n)

    c = sorted_tbl.column(value_col)
    if n:
        vcum = jnp.cumsum(c.valid_mask().astype(jnp.int64))
        upper = vcum[jnp.clip(g_hi - 1, 0, n - 1)]
        base = jnp.where(g_lo > 0, vcum[jnp.clip(g_lo - 1, 0, n - 1)], 0)
        cnt = jnp.where(g_hi > g_lo, upper - base, 0)
    else:
        cnt = jnp.zeros((m,), jnp.int64)
    vals = c.data.astype(jnp.float64)
    if c.dtype.is_decimal:
        vals = vals * (10.0 ** c.dtype.scale)
    group_ok = cnt > 0
    for q in qs:
        p = q * (cnt - 1).astype(jnp.float64)
        lo_off = jnp.floor(p).astype(jnp.int64)
        frac = p - lo_off.astype(jnp.float64)
        i0 = g_lo.astype(jnp.int64) + lo_off
        i1 = g_lo.astype(jnp.int64) + jnp.minimum(lo_off + 1, cnt - 1)
        safe = lambda i: jnp.clip(i, 0, max(n - 1, 0)).astype(jnp.int32)
        if n:
            v0 = vals[safe(i0)]
            v1 = vals[safe(i1)]
            out = v0 * (1.0 - frac) + v1 * frac
        else:
            out = jnp.zeros((m,), jnp.float64)
        out_cols.append(Column(DType(TypeId.FLOAT64), out, group_ok))
    return GroupByResult(Table(out_cols), num_groups, overflowed)


def bounded_group_layout(domain_lens: Sequence[int]):
    """Static (trace-time) layout of the bounded-groupby output.

    One slot per combination of (domain value | null) per key:
    ``m = prod(len+1)``. Returns ``(sizes, m, codes, order)`` where
    ``codes[g, pos]`` is key ``pos``'s domain index for group ``g``
    (``== domain_lens[pos]`` means the null slot) and ``order`` is the
    output permutation — real-key groups first in lexicographic key
    order, null-key groups after (the ORDER BY ... NULLS LAST every
    consumer wants, at zero device cost). Shared by
    ``groupby_aggregate_bounded`` and the planner's string-key decoding
    (ops/planner.py) so the two can never disagree about slot layout.
    """
    sizes = [int(l) + 1 for l in domain_lens]
    m = int(np.prod(sizes)) if sizes else 1
    codes = np.zeros((m, len(sizes)), dtype=np.int64)
    for pos, size in enumerate(sizes):
        stride = int(np.prod(sizes[pos + 1:])) or 1
        codes[:, pos] = (np.arange(m) // stride) % size
    has_null = (codes == (np.asarray(sizes) - 1)).any(axis=1) \
        if sizes else np.zeros((m,), bool)
    order = np.asarray(
        sorted(range(m), key=lambda g: (bool(has_null[g]), g)),
        dtype=np.int64)
    return sizes, m, codes, order


class _XlaBoundedAccumulator:
    """The bit-identity ORACLE accumulate for bounded groupby: one
    masked whole-column reduction per (group, lane) — byte-for-byte the
    pre-kernel-tier path (XLA fuses the m masked reductions into a
    single pass over the rows). The Pallas twin
    (``groupby.bounded_accumulate``, ops/pallas/groupby_accumulate.py)
    must reproduce every method bit-for-bit; tpulint rule 19 keeps this
    path reachable via ``kernels.tier=xla``."""

    def __init__(self, table: Table, gid: jnp.ndarray, n: int, m: int):
        self._table = table
        self._n = n
        self._m = m
        # one (n,) bool per group, built once and shared by all
        # aggregates
        self._masks = [gid == g for g in range(m)] if n else None

    def _per_group(self, vals: jnp.ndarray, reduce_fn, neutral):
        if self._n == 0:
            return jnp.full((self._m,), neutral, vals.dtype)
        return jnp.stack([
            reduce_fn(jnp.where(self._masks[g], vals, neutral))
            for g in range(self._m)
        ])

    def rows_per_group(self) -> jnp.ndarray:
        return self._per_group(
            jnp.ones((self._n,), jnp.int64), jnp.sum, jnp.int64(0))

    def vcount(self, col_idx: int) -> jnp.ndarray:
        valid = self._table.column(col_idx).valid_mask()
        return self._per_group(
            valid.astype(jnp.int64), jnp.sum, jnp.int64(0))

    def sum_int(self, col_idx: int) -> jnp.ndarray:
        c = self._table.column(col_idx)
        vv_zero = jnp.where(c.valid_mask(), c.data, jnp.zeros_like(c.data))
        return self._per_group(
            vv_zero.astype(jnp.int64), jnp.sum, jnp.int64(0))

    def sum_float(self, col_idx: int) -> jnp.ndarray:
        c = self._table.column(col_idx)
        vv_zero = jnp.where(c.valid_mask(), c.data, jnp.zeros_like(c.data))
        return self._per_group(
            vv_zero.astype(jnp.float64), jnp.sum, jnp.float64(0))

    def minmax(self, col_idx: int, op: str) -> jnp.ndarray:
        c = self._table.column(col_idx)
        sentinel = minmax_sentinel(c.dtype, op)
        vv = jnp.where(
            c.valid_mask(), c.data, jnp.asarray(sentinel, c.data.dtype))
        return self._per_group(vv, jnp.min if op == "min" else jnp.max,
                               jnp.asarray(sentinel, c.data.dtype))


class _PallasBoundedAccumulator:
    """Pallas-tier accumulate: every (group, lane) partial from ONE
    streaming kernel launch (ops/pallas/groupby_accumulate.py). Integer
    sums ride 16-bit limb lanes recombined in wrapping int64 — exact
    mod 2^64, which is bit-identical to the oracle's int64 sums; min/max
    lanes carry the oracle's own sentinel so empty groups match too.
    Built only after :func:`_pallas_bounded_plan` proved every aggregate
    eligible."""

    def __init__(self, table: Table, aggs, gid: jnp.ndarray, n: int,
                 m: int, *, interpret: bool):
        from spark_rapids_jni_tpu.ops.pallas import groupby_accumulate as pga

        lanes: list[jnp.ndarray] = []
        meta: list[tuple[str, int]] = []

        def add(arr, op, neutral):
            lanes.append(arr)
            meta.append((op, int(neutral)))
            return len(lanes) - 1

        self._rows_lane = add(jnp.ones((n,), jnp.int32), "sum", 0)
        self._vcount_lane: dict[int, int] = {}
        self._sum_lanes: dict[int, list[int]] = {}
        self._minmax_lane: dict[tuple[int, str], int] = {}
        self._storage: dict[int, Any] = {}
        for col_idx, op in aggs:
            c = table.column(col_idx)
            valid = c.valid_mask()
            self._storage[col_idx] = c.data.dtype
            if col_idx not in self._vcount_lane:
                self._vcount_lane[col_idx] = add(
                    valid.astype(jnp.int32), "sum", 0)
            if op in ("sum", "mean") and col_idx not in self._sum_lanes:
                vv_zero = jnp.where(valid, c.data, jnp.zeros_like(c.data))
                limbs = pga.split_limbs(
                    vv_zero, np.dtype(c.data.dtype).itemsize)
                self._sum_lanes[col_idx] = [
                    add(limb, "sum", 0) for limb in limbs]
            if op in ("min", "max") and (col_idx, op) not in self._minmax_lane:
                sentinel = int(minmax_sentinel(c.dtype, op))
                vv = jnp.where(
                    valid, c.data, jnp.asarray(sentinel, c.data.dtype))
                self._minmax_lane[(col_idx, op)] = add(
                    vv.astype(jnp.int32), op, sentinel)
        self._sums, self._mins, self._maxs = pga.accumulate(
            gid, lanes, tuple(meta), m, interpret=interpret)
        self._combine = pga.combine_limbs

    def rows_per_group(self) -> jnp.ndarray:
        return self._sums[:, self._rows_lane]

    def vcount(self, col_idx: int) -> jnp.ndarray:
        return self._sums[:, self._vcount_lane[col_idx]]

    def sum_int(self, col_idx: int) -> jnp.ndarray:
        return self._combine(
            [self._sums[:, li] for li in self._sum_lanes[col_idx]])

    def sum_float(self, col_idx: int) -> jnp.ndarray:
        raise AssertionError(
            "float aggregates never kernelize (summation order would "
            "break bit-identity) — _pallas_bounded_plan must have "
            "routed this op to the oracle")

    def minmax(self, col_idx: int, op: str) -> jnp.ndarray:
        source = self._mins if op == "min" else self._maxs
        red = source[:, self._minmax_lane[(col_idx, op)]]
        return red.astype(self._storage[col_idx])


def _pallas_bounded_plan(table: Table, aggs, n: int, m: int):
    """Trace-time eligibility of one bounded groupby for the Pallas
    accumulate tier. Returns a fallback reason (recorded by the caller)
    or None when every aggregate kernelizes bit-identically."""
    from spark_rapids_jni_tpu.ops.pallas import groupby_accumulate as pga

    lane_count = 1  # the row-count lane
    seen_vcount: set[int] = set()
    seen_sum: set[int] = set()
    for col_idx, op in aggs:
        c = table.column(col_idx)
        st = np.dtype(c.data.dtype)
        if col_idx not in seen_vcount:
            seen_vcount.add(col_idx)
            lane_count += 1
        if op in ("sum", "mean"):
            acc_dt = _sum_dtype(c.dtype)
            if acc_dt.storage_dtype.kind not in ("i", "u"):
                # float sums are order-sensitive: kernelizing them would
                # trade bit-identity for speed — never silently
                return "float_agg"
            if st.kind not in ("i", "u", "b"):
                return "float_agg"
            if col_idx not in seen_sum:
                seen_sum.add(col_idx)
                lane_count += pga.limb_count(st.itemsize)
        elif op in ("min", "max"):
            # the in-kernel lanes are int32: the cast must preserve
            # order and value
            if not (st.kind == "i" and st.itemsize <= 4
                    or st.kind == "u" and st.itemsize <= 2):
                return "minmax_width"
            lane_count += 1
    return pga.unsupported_reason(n, m, lane_count)


class BoundedGroupByResult(NamedTuple):
    """Output of groupby_aggregate_bounded: one row per domain combination
    (null slots included), in a STATIC order — real-key groups first in
    lexicographic key order, null-key groups after (the q1 ORDER BY comes
    free). Empty combinations carry validity False everywhere."""

    table: Table
    # bool[m]: at least one input row landed in this group
    present: jnp.ndarray
    # scalar bool: some row's key value was outside its declared domain
    # (and not null) — that row is in NO group; the caller must re-plan
    # with the general groupby (the narrowing_overflow posture)
    domain_miss: jnp.ndarray


@func_range("groupby_aggregate_bounded")
def groupby_aggregate_bounded(
    table: Table,
    keys: Sequence[int],
    aggs: Sequence[tuple[int, str]],
    key_domains: Sequence[Sequence[int]],
    row_valid: Optional[jnp.ndarray] = None,
) -> BoundedGroupByResult:
    """Groupby with PLANNER-DECLARED key domains: zero sort, zero gather,
    zero scan, zero scatter — one streaming pass.

    The general groupby's cost on TPU is the key sort + row gather +
    boundary machinery (BASELINE.md: sort 55 ms + gather 32 ms of the
    ~280 ms q1 iteration at 4M rows). When the planner knows each key
    column's candidate values (dictionary stats; CHAR(1) flag domains in
    TPC-H q1), dense group ids come from a searchsorted against the tiny
    sorted domain and every aggregate is a masked whole-column reduction
    per group — XLA fuses the per-group masked sums into one multi-output
    reduction pass over the lanes.

    ``key_domains``: one sorted sequence of candidate raw values per key
    column. Each key also gets an implicit NULL slot (Spark: null keys
    form their own group), so m = prod(len(d)+1). Supported aggs: sum,
    count, mean, min, max (the associative single-pass set). Rows whose
    key value is outside its domain land in no group and raise
    ``domain_miss``.

    ``row_valid``: bool[n] marking rows that EXIST — False rows (e.g.
    shard_table padding) join NO group, not even the null slot, and
    never raise ``domain_miss`` (a padding row is not a null-key row —
    the shard_table return_row_valid contract).
    """
    for _, op in aggs:
        if op not in ("sum", "count", "mean", "min", "max"):
            raise ValueError(
                f"groupby_aggregate_bounded supports sum/count/mean/min/"
                f"max, not {op!r} (use groupby_aggregate)"
            )
    if len(key_domains) != len(keys):
        raise ValueError("one domain per key column required")
    n = table.num_rows
    sizes, m, slot_codes, order = bounded_group_layout(
        [len(d) for d in key_domains])

    # dense gid over the domain cross product; miss detection per key
    gid = jnp.zeros((n,), jnp.int32)
    domain_miss = jnp.bool_(False)
    for k, dom in zip(keys, key_domains):
        c = table.column(k)
        if c.dtype.is_string or c.dtype.is_decimal128:
            raise NotImplementedError(
                "bounded-domain keys are fixed-width scalars (pack string "
                "dictionary codes first)"
            )
        dom_arr = jnp.asarray(sorted(dom), c.data.dtype)
        valid = c.valid_mask()
        code = jnp.searchsorted(dom_arr, c.data).astype(jnp.int32)
        hit = (dom_arr[jnp.clip(code, 0, len(dom) - 1)] == c.data)
        miss_rows = valid & ~hit
        if row_valid is not None:
            miss_rows = miss_rows & row_valid
        domain_miss = domain_miss | jnp.any(miss_rows)
        # null slot = len(dom); missed rows park there too but are
        # excluded from every group by the miss flag contract
        code = jnp.where(valid & hit, jnp.clip(code, 0, len(dom) - 1),
                         len(dom))
        gid = gid * (len(dom) + 1) + code
    if row_valid is not None:
        # non-rows (shard padding) match NO group mask, not even null
        gid = jnp.where(row_valid, gid, jnp.int32(m))

    out_cols: list[Column] = []

    # kernel tier pick happens at TRACE time: the dispatch cache key
    # carries the kernels digest, so a tier flip never reuses a stale
    # executable and fused plans inherit the same decision
    from spark_rapids_jni_tpu.ops import pallas as pallas_tier

    decision = pallas_tier.decide("groupby.bounded_accumulate")
    acc = None
    if decision.use_pallas:
        reason = _pallas_bounded_plan(table, aggs, n, m)
        if reason is None:
            acc = _PallasBoundedAccumulator(
                table, aggs, gid, n, m, interpret=decision.interpret)
        else:
            pallas_tier.fall_back("groupby.bounded_accumulate", reason)
    if acc is None:
        acc = _XlaBoundedAccumulator(table, gid, n, m)

    rows_per_group = acc.rows_per_group()
    present = rows_per_group > 0

    # static key materialization: group g's key tuple is known at trace
    # time; null slot -> validity False
    for pos, (k, dom) in enumerate(zip(keys, key_domains)):
        c = table.column(k)
        vals = np.zeros((m,), dtype=np.dtype(c.dtype.storage_dtype))
        kvalid = np.zeros((m,), dtype=bool)
        dom_sorted = sorted(dom)
        for g in range(m):
            code = slot_codes[g, pos]
            if code < len(dom_sorted):
                vals[g] = dom_sorted[code]
                kvalid[g] = True
        out_cols.append(Column(
            c.dtype, jnp.asarray(vals), jnp.asarray(kvalid) & present))

    for col_idx, op in aggs:
        c = table.column(col_idx)
        vcount = acc.vcount(col_idx)
        if op == "count":
            out_cols.append(Column(DType(TypeId.INT64), vcount, present))
            continue
        if op in ("sum", "mean"):
            acc_dt = _sum_dtype(c.dtype)
            if acc_dt.storage_dtype.kind in ("i", "u"):
                total = acc.sum_int(col_idx).astype(acc_dt.jnp_dtype)
            else:
                total = acc.sum_float(col_idx)
            if op == "sum":
                out_cols.append(Column(
                    acc_dt, total.astype(acc_dt.jnp_dtype), vcount > 0))
            else:
                denom = jnp.maximum(vcount, 1).astype(jnp.float64)
                mean = total.astype(jnp.float64) / denom
                if c.dtype.is_decimal:
                    mean = mean * (10.0 ** c.dtype.scale)
                out_cols.append(
                    Column(DType(TypeId.FLOAT64), mean, vcount > 0))
            continue
        # min / max
        red = acc.minmax(col_idx, op)
        out_cols.append(Column(c.dtype, red, vcount > 0))

    # static reorder from the shared layout: real-key groups first
    # (lexicographic), null-key groups after — zero device sort (the
    # permutation is a trace-time constant)
    perm = jnp.asarray(order, jnp.int32)
    out_cols = [
        Column(c.dtype, c.data[perm],
               None if c.validity is None else c.validity[perm])
        for c in out_cols
    ]
    return BoundedGroupByResult(
        Table(out_cols), present[perm], domain_miss)
