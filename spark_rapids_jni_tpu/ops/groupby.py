"""Hash-groupby-aggregate equivalent (cuDF groupby is part of the vendored
capability surface, SURVEY.md section 2.2; TPC-H q1 is the canonical
workload, BASELINE.json config #3).

TPU-first design: no device hash table (no CUDA-style concurrent hash map
idiom on the VPU — SURVEY.md section 7 "hard parts" calls this out). Instead
sort-based grouping: stable-sort rows by the encoded keys, mark segment
boundaries, turn them into dense group ids with a cumulative sum, and run
null-aware ``jax.ops.segment_*`` reductions — all static-shape, all fused by
XLA. Output is padded to the input row count with ``num_groups`` reported
alongside (static shapes are the price of jit; callers slice on host).

Null semantics are Spark's: null keys form their own group; aggregates skip
null values; COUNT counts non-null; an all-null group's SUM/MIN/MAX/MEAN is
null.
"""

from __future__ import annotations

from typing import NamedTuple, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from spark_rapids_jni_tpu.columnar import Column, Table
from spark_rapids_jni_tpu.ops.sort import gather, sort_order
from spark_rapids_jni_tpu.types import DType, TypeId
from spark_rapids_jni_tpu.utils.tracing import func_range

SUPPORTED_AGGS = ("sum", "count", "min", "max", "mean")


class GroupByResult(NamedTuple):
    table: Table          # keys then aggregates, padded to n rows
    num_groups: jnp.ndarray  # scalar int32

    def compact(self) -> Table:
        """Host-side trim to the real group count."""
        k = int(self.num_groups)
        cols = []
        for c in self.table.columns:
            validity = None if c.validity is None else c.validity[:k]
            cols.append(Column(c.dtype, c.data[:k], validity))
        return Table(cols)


def _rows_equal_prev(table: Table, keys: Sequence[int]) -> jnp.ndarray:
    """bool[n]: row i has the same key tuple (incl. null-ness) as row i-1."""
    n = table.num_rows
    same = jnp.ones((n,), dtype=jnp.bool_)
    for k in keys:
        c = table.column(k)
        v = c.data
        valid = c.valid_mask()
        eq_val = v[1:] == v[:-1]
        if c.dtype.storage_dtype.kind == "f":
            eq_val = eq_val | (jnp.isnan(v[1:]) & jnp.isnan(v[:-1]))
        eq_valid = valid[1:] == valid[:-1]
        both_null = ~valid[1:] & ~valid[:-1]
        eq = (eq_val & valid[1:] & eq_valid) | both_null
        same = same.at[1:].set(same[1:] & eq)
    return same.at[0].set(n == 0)


def _sum_dtype(dt: DType) -> DType:
    """Spark widens SUM: integral -> INT64, decimal keeps scale (wider
    precision), floats stay floating."""
    kind = dt.storage_dtype.kind
    if dt.is_decimal:
        return DType(TypeId.DECIMAL64, dt.scale)
    if kind in ("i", "u", "b"):
        return DType(TypeId.INT64)
    return dt


@func_range("groupby_aggregate")
def groupby_aggregate(
    table: Table,
    keys: Sequence[int],
    aggs: Sequence[tuple[int, str]],
) -> GroupByResult:
    """Group by `keys`; compute [(value_col, op)] aggregates.

    Returns keys + one column per agg, in order, padded to n rows.
    """
    for _, op in aggs:
        if op not in SUPPORTED_AGGS:
            raise ValueError(f"unsupported aggregation {op!r}")
    n = table.num_rows
    order = sort_order(table, keys)
    sorted_tbl = gather(table, order)

    same = _rows_equal_prev(sorted_tbl, keys)
    group_id = jnp.cumsum(~same) - 1  # dense ids, 0-based, sorted order
    num_groups = (group_id[-1] + 1).astype(jnp.int32) if n else jnp.int32(0)

    # Key output columns: first row of each group (scatter-min of row index;
    # rows are sorted so the first is the group representative).
    first_idx = jnp.full((n,), n, dtype=jnp.int32).at[group_id].min(
        jnp.arange(n, dtype=jnp.int32)
    )
    out_cols: list[Column] = []
    for k in keys:
        c = sorted_tbl.column(k)
        safe_first = jnp.clip(first_idx, 0, max(n - 1, 0))
        data = c.data[safe_first]
        valid = c.valid_mask()[safe_first] & (first_idx < n)
        out_cols.append(Column(c.dtype, data, valid))

    for col_idx, op in aggs:
        c = sorted_tbl.column(col_idx)
        v = c.data
        valid = c.valid_mask()
        vcount = jax.ops.segment_sum(
            valid.astype(jnp.int64), group_id, num_segments=n
        )
        if op == "count":
            out_cols.append(
                Column(DType(TypeId.INT64), vcount,
                       jnp.arange(n) < num_groups)
            )
            continue
        if op in ("sum", "mean"):
            acc_dt = _sum_dtype(c.dtype)
            vv = jnp.where(valid, v, jnp.zeros_like(v)).astype(acc_dt.jnp_dtype)
            total = jax.ops.segment_sum(vv, group_id, num_segments=n)
            has_any = vcount > 0
            if op == "sum":
                out_cols.append(Column(acc_dt, total, has_any))
            else:
                denom = jnp.maximum(vcount, 1).astype(jnp.float64)
                mean = total.astype(jnp.float64) / denom
                if c.dtype.is_decimal:
                    # Rescale so the FLOAT64 result carries the true value:
                    # the unscaled-integer mean alone is off by 10^-scale
                    # and the float dtype has no scale field to recover it.
                    mean = mean * (10.0 ** c.dtype.scale)
                out_cols.append(Column(DType(TypeId.FLOAT64), mean, has_any))
            continue
        # min / max with null-neutral sentinels
        np_dt = c.dtype.storage_dtype
        if np_dt.kind == "f":
            lo, hi = -jnp.inf, jnp.inf
        else:
            info = np.iinfo(np_dt)
            lo, hi = info.min, info.max
        if op == "min":
            vv = jnp.where(valid, v, jnp.asarray(hi, dtype=v.dtype))
            red = jax.ops.segment_min(vv, group_id, num_segments=n)
        else:
            vv = jnp.where(valid, v, jnp.asarray(lo, dtype=v.dtype))
            red = jax.ops.segment_max(vv, group_id, num_segments=n)
        out_cols.append(Column(c.dtype, red, vcount > 0))

    return GroupByResult(Table(out_cols), num_groups)
