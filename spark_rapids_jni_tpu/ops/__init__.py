from spark_rapids_jni_tpu.ops.row_conversion import (
    RowsColumn,
    compute_fixed_width_layout,
    convert_from_rows,
    convert_to_rows,
)

__all__ = [
    "RowsColumn",
    "compute_fixed_width_layout",
    "convert_from_rows",
    "convert_to_rows",
]
