"""Bounded-domain groupby planning — the facility behind the 125x q1 win.

Round-4 hardware measurements (BASELINE.md) showed planner-declared key
domains beat the general sort-based groupby by 125x at 16M rows: when every
key column's candidate values are known at plan time, grouping lowers to
``groupby_aggregate_bounded`` — zero sort, zero gather, zero scan, zero
scatter; one streaming masked-reduction pass the TPU backend fuses. That
win was hand-wired into q1 (``_Q1_RF_DOMAIN``); this module makes it a
planner facility any query can use (VERDICT r4 item 3).

Domain sources mirror what a production Spark planner sees:

* ``scalar_domain`` / ``string_domain`` — DDL facts (CHAR(1) check
  constraints, enum-like dictionaries: TPC-H fixes l_returnflag to A/N/R,
  l_shipmode to 7 values, o_orderpriority to 5).
* ``observed_domain`` — planning-time column statistics (host-side
  distinct scan; the role the Parquet dictionary page / ORC column
  statistics play in production — the readers under
  ``spark_rapids_jni_tpu/parquet`` decode those pages).
* ``month_domain`` + ``month_bucket`` — date columns bucketed by calendar
  month: the bucket cardinality is tiny even when the date cardinality is
  not, so date-bucketed rollups ride the sort-free path.

``plan_groupby`` lowers to the bounded plan when every key carries a
domain and the slot count fits the budget, else falls back to the general
``groupby_aggregate`` — with ``domain_miss`` as the runtime escape hatch
(out-of-domain data re-plans, it never silently drops; the
``narrowing_overflow`` posture).

Reference analogue: cuDF's groupby dispatches hash vs. sort strategies on
key properties (vendored capability, /root/reference/build-libcudf.xml:
34-60); this is the TPU-shaped version of that dispatch, with the planner
supplying the cardinality facts Spark's optimizer carries.
"""

from __future__ import annotations

from typing import NamedTuple, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from spark_rapids_jni_tpu import types as t
from spark_rapids_jni_tpu.columnar import Column, Table
from spark_rapids_jni_tpu.ops.groupby import (
    bounded_group_layout,
    groupby_aggregate,
    groupby_aggregate_bounded,
)
from spark_rapids_jni_tpu.ops.sort import sort_table
from spark_rapids_jni_tpu.runtime.resilience import FatalExecutionError
from spark_rapids_jni_tpu.utils.tracing import func_range


class Domain(NamedTuple):
    """Planner-declared candidate values for one groupby key column.

    ``values`` are raw storage scalars for fixed-width keys, or ``str``
    for string keys; always kept sorted so group output order is the
    deterministic ORDER BY ... NULLS LAST. ``source`` is provenance
    ("ddl", "dictionary", "observed", "derived") — recorded for plan
    explainability, never branched on.
    """

    values: tuple
    kind: str  # "scalar" | "string"
    source: str


def scalar_domain(values: Sequence, source: str = "ddl") -> Domain:
    vals = tuple(sorted(set(int(v) for v in values)))
    if not vals:
        raise ValueError("empty domain")
    return Domain(vals, "scalar", source)


def string_domain(values: Sequence[str], source: str = "ddl") -> Domain:
    # byte-wise sort: the same collation packed_sort_keys uses, so the
    # bounded output order matches what sort_table would have produced
    vals = tuple(sorted(set(values), key=lambda s: s.encode()))
    if not vals:
        raise ValueError("empty domain")
    return Domain(vals, "string", source)


_OBSERVED_DEFAULT_CAP = 1024


def observed_domain(col: Column, max_size: int = _OBSERVED_DEFAULT_CAP,
                    source: str = "observed") -> Domain | None:
    """Planning-time statistics: the column's distinct values, gathered
    host-side (this runs at PLAN time over a sample/stats source, not in
    the jitted query — production gets the same facts from Parquet
    dictionary pages or ORC statistics without touching row data).
    Returns None when cardinality exceeds ``max_size`` — the key is not
    boundable and the caller stays on the general plan."""
    if col.dtype.is_string:
        vals = sorted({v for v in col.to_pylist() if v is not None},
                      key=lambda s: s.encode())
        if len(vals) > max_size:
            return None
        return Domain(tuple(vals), "string", source) if vals else None
    if col.dtype.is_decimal128 or col.children is not None:
        return None
    data = np.asarray(col.data)
    if col.validity is not None:
        data = data[np.asarray(col.validity)]
    vals = np.unique(data)
    if vals.size > max_size or vals.size == 0:
        return None
    return Domain(tuple(int(v) for v in vals), "scalar", source)


def domain_from_parquet(path, column: int,
                        max_size: int = _OBSERVED_DEFAULT_CAP,
                        sample_row_groups: int = 1) -> Domain | None:
    """Planner-time domain derivation from a Parquet file: decode the
    first ``sample_row_groups`` row groups of one column through the
    native reader and take the observed distinct values.

    This is the practical stand-in for reading the dictionary PAGE
    directly (the native reader decodes dictionary pages internally but
    does not yet expose their value arrays through the C ABI): a
    planning-time sample, so the derived domain is declared with
    ``source="observed"`` and the runtime ``domain_miss`` check remains
    the correctness backstop — exactly the posture that makes an
    inaccurate sample a re-plan, never a wrong answer.
    """
    from spark_rapids_jni_tpu.parquet.reader import (
        read_table,
        row_group_info,
    )

    n_groups = len(row_group_info(path))
    groups = list(range(min(sample_row_groups, n_groups)))
    tbl = read_table(path, columns=[column], row_groups=groups)
    return observed_domain(tbl.column(0), max_size=max_size)


def month_code(year: int, month: int) -> int:
    """Static month-bucket code: year*12 + (month-1)."""
    return year * 12 + (month - 1)


def month_bucket(col: Column) -> Column:
    """Derived key column: the calendar-month bucket of a date column
    (int32 ``year*12 + month-1``), jit-traceable. Date cardinality is
    unbounded; month-bucket cardinality over any query's date range is
    tiny, which is what puts date-bucketed rollups on the sort-free
    plan."""
    from spark_rapids_jni_tpu.ops import datetime as dt

    y = dt.year(col)
    mth = dt.month(col)
    code = y.data.astype(jnp.int32) * 12 + (mth.data.astype(jnp.int32) - 1)
    return Column(t.INT32, code, col.validity)


def month_domain(year_lo: int, month_lo: int, year_hi: int, month_hi: int,
                 source: str = "ddl") -> Domain:
    """All month-bucket codes in [year_lo-month_lo, year_hi-month_hi]
    inclusive — the domain a planner derives from a date-range predicate
    or min/max column statistics."""
    lo = month_code(year_lo, month_lo)
    hi = month_code(year_hi, month_hi)
    if hi < lo:
        raise ValueError("month range is empty")
    return Domain(tuple(range(lo, hi + 1)), "scalar", source)


def encode_string_key(col: Column, domain: Domain) -> Column:
    """Dictionary-encode a string key against its declared domain, fully
    on device: one padded-bytes equality compare per domain value (XLA
    fuses the d compares into a single pass over the char matrix — no
    sort, no hash table). Code = index in the sorted domain; rows whose
    value is outside the domain get code ``len(domain)`` which
    ``groupby_aggregate_bounded`` flags as ``domain_miss``; null rows
    stay null (the null slot)."""
    from spark_rapids_jni_tpu.ops.strings import pad_strings

    if domain.kind != "string":
        raise ValueError("encode_string_key needs a string domain")
    col = pad_strings(col)
    w = col.chars.shape[1] if col.chars is not None else 0
    n = col.chars.shape[0]
    k = len(domain.values)
    code = jnp.full((n,), k, jnp.int32)
    for idx, v in enumerate(domain.values):
        b = v.encode()
        if len(b) > w:
            continue  # longer than every row: cannot match
        target = np.zeros((w,), np.uint8)
        target[: len(b)] = np.frombuffer(b, np.uint8)
        hit = jnp.all(col.chars == jnp.asarray(target)[None, :], axis=1) \
            if w else jnp.full((n,), len(b) == 0)
        code = jnp.where(hit, jnp.int32(idx), code)
    return Column(t.INT32, code, col.validity)


class DensePkJoinResult(NamedTuple):
    """LEFT PK-join result: one output row per probe row (PK fanout is
    exactly <= 1, so there is no join-maps machinery, no capacity
    estimate, no overflow). Probe columns first, then build columns
    (the apply_join_maps convention); unmatched probe rows carry null
    build columns."""

    table: Table
    matched: jnp.ndarray       # bool[n] probe rows with a build match
    total: jnp.ndarray         # scalar match count
    # True when the declared layout lied: a clustered slot held a
    # DIFFERENT valid key (clustered mode), or the build side held
    # duplicate keys (sorted mode). The caller re-plans on the general
    # join — the domain_miss posture, never a silent wrong answer.
    pk_violation: jnp.ndarray


@func_range("dense_pk_join")
def dense_pk_join(
    probe: Table,
    build: Table,
    probe_key: int,
    build_key: int,
    key_lo: int,
    key_hi: int,
    clustered: bool = False,
) -> DensePkJoinResult:
    """LEFT join against a DECLARED dense primary-key build side.

    The planner fact: ``build``'s key column holds unique keys from the
    contiguous range [key_lo, key_hi] (a TPC-H DDL fact — orderkey /
    custkey / partkey are dense 1..N — and what a real planner reads
    from PK constraints + min/max statistics).

    * ``clustered=True``: build row i holds key ``key_lo + i`` (the
      layout of a loaded dimension or generated key column). The join
      is then pure arithmetic + one row gather — ZERO sorts anywhere,
      and the general join's build-side lexsort + probe searchsorted
      (the dominant terms of the 230 ns/row unbounded pipeline,
      BASELINE.md) vanish. The declaration is VERIFIED, not trusted:
      each gathered build key is compared to the probe key, and a slot
      holding a different valid key raises ``pk_violation``.
    * ``clustered=False``: one lexsort of the (small) build side; the
      probe side is searchsorted + gather. Duplicate build keys raise
      ``pk_violation`` (PK uniqueness is part of the declaration).

    Build rows with NULL keys are filtered rows (the _null_where WHERE
    idiom): probes pointing at them are unmatched, not violations.
    """
    from spark_rapids_jni_tpu.ops.sort import gather

    n = probe.num_rows
    nb = build.num_rows
    pk = probe.column(probe_key)
    bk = build.column(build_key)
    if pk.dtype.is_string or bk.dtype.is_string:
        raise NotImplementedError(
            "dense PK keys are integers (dictionary-encode first)")
    in_range = (pk.valid_mask()
                & (pk.data >= pk.data.dtype.type(key_lo))
                & (pk.data <= pk.data.dtype.type(key_hi)))
    if clustered:
        if key_hi - key_lo + 1 != nb:
            raise ValueError(
                f"clustered dense PK needs build rows == key range "
                f"({nb} != {key_hi - key_lo + 1})")
        pos = jnp.clip(pk.data - key_lo, 0, nb - 1).astype(jnp.int32)
        bkey_at = bk.data[pos]
        bvalid_at = bk.valid_mask()[pos]
        matched = in_range & bvalid_at & (bkey_at == pk.data)
        # a slot holding a DIFFERENT valid key means the layout is not
        # clustered after all
        pk_violation = jnp.any(in_range & bvalid_at
                               & (bkey_at != pk.data))
    else:
        # null keys (filtered rows) overwritten with the dtype max so
        # the sorted array is GLOBALLY monotone — sorting raw data with
        # a null rank leaves the tail unsorted and breaks the binary
        # search for large valid keys (silently dropped matches)
        bvalid = bk.valid_mask()
        dt_max = np.iinfo(np.dtype(bk.data.dtype)).max
        if key_hi >= dt_max:
            # the declared key range touches the null sentinel: a
            # legitimate key equal to dtype max would be overwritten
            # into the null slot and silently drop its matches
            raise ValueError(
                f"dense PK range [{key_lo}, {key_hi}] reaches "
                f"iinfo({np.dtype(bk.data.dtype).name}).max, the null "
                f"sentinel; widen the key dtype or shrink the range")
        key_clean = jnp.where(bvalid, bk.data,
                              jnp.asarray(dt_max, bk.data.dtype))
        perm = jnp.argsort(key_clean).astype(jnp.int32)
        skey = key_clean[perm]
        n_valid = jnp.sum(bvalid.astype(jnp.int32))
        pos0 = jnp.searchsorted(skey, pk.data).astype(jnp.int32)
        within = pos0 < n_valid
        hit = within & (skey[jnp.clip(pos0, 0, nb - 1)] == pk.data)
        pos = perm[jnp.clip(pos0, 0, nb - 1)]
        matched = in_range & hit
        dup = jnp.any((skey[1:] == skey[:-1])
                      & (jnp.arange(1, nb) < n_valid)) if nb > 1 \
            else jnp.bool_(False)
        # the declaration also claims build keys live in [lo, hi]: an
        # out-of-range valid build key is a lie, not an unmatched row
        oor = jnp.any(bvalid & ((bk.data < bk.data.dtype.type(key_lo))
                                | (bk.data > bk.data.dtype.type(key_hi))))
        pk_violation = dup | oor

    out_cols = list(probe.columns)
    gathered = gather(build, pos)
    for c in gathered.columns:
        out_cols.append(Column(
            c.dtype, c.data, c.valid_mask() & matched, chars=c.chars))
    return DensePkJoinResult(
        Table(out_cols), matched,
        jnp.sum(matched.astype(jnp.int64)), pk_violation)


def _dense_prologue(gid: jnp.ndarray, m: int, block: int,
                    values: jnp.ndarray | None):
    """Shared scaffolding of the dense-id reductions: range-check in
    the INPUT dtype before narrowing (an int64 gid beyond 2^31 must not
    wrap into [0, m)), clamp the block, pad to a block multiple with
    the discard sentinel m, and reshape for the scan. Returns
    (gid_blocks int32[(nb, block)], value_blocks int64 | None)."""
    n = gid.shape[0]
    block = min(block, n)
    pad = (-n) % block
    safe = jnp.where((gid >= 0) & (gid < m), gid,
                     jnp.asarray(m, gid.dtype)).astype(jnp.int32)
    if pad:
        safe = jnp.concatenate([safe, jnp.full((pad,), jnp.int32(m))])
    vb = None
    if values is not None:
        v64 = values.astype(jnp.int64)
        if pad:
            v64 = jnp.concatenate([v64, jnp.zeros((pad,), jnp.int64)])
        vb = v64.reshape(-1, block)
    return safe.reshape(-1, block), vb


class PlanBudgetExceeded(FatalExecutionError, ValueError):
    """A groupby's distinct-group count exceeded ``max_budget``.

    Classified fatal in the resilience taxonomy (the budget is a caller
    contract, not a transient condition) while remaining the ValueError
    this API historically raised, so existing ``except ValueError`` /
    message-matching callers are unaffected."""


def plan_groupby_auto(
    table: Table,
    keys: Sequence[int],
    aggs: Sequence[tuple[int, str]],
    domains: Sequence["Domain | None"],
    budget: int = 4096,
    max_budget: int | None = None,
    row_valid: jnp.ndarray | None = None,
) -> "PlannedGroupBy":
    """Host wrapper completing the overflow posture: when the general
    fallback drops groups (``overflowed``), double the budget and
    retry until the result is complete (the groupby_aggregate_auto
    pattern). The bounded plan never overflows (slot count checked at
    plan time), so retries only occur on the general path. Growth runs
    through the shared resilience ladder — budget schedule min(b·2^k,
    cap) preserved exactly — and exhaustion raises
    :class:`PlanBudgetExceeded` (a ``FatalExecutionError`` that is still
    the ValueError callers match on)."""
    from spark_rapids_jni_tpu.runtime import resilience

    cap = max_budget if max_budget is not None else max(table.num_rows, 1)
    # clamp both ways: a sub-positive budget would loop forever (0*2 == 0)
    # and a starting budget above the cap would silently ignore it
    b = min(max(budget, 1), cap)
    if not resilience.enabled():
        while True:
            res = plan_groupby(table, keys, aggs, domains, budget=b,
                               row_valid=row_valid)
            if not bool(res.overflowed) or b >= cap:
                if bool(res.overflowed):
                    raise PlanBudgetExceeded(
                        f"groupby exceeded max_budget={cap} distinct groups")
                return res
            b = min(b * 2, cap)

    def _attempt(budget_):
        res = plan_groupby(table, keys, aggs, domains, budget=budget_,
                           row_valid=row_valid)
        return res, bool(res.overflowed), None

    return resilience.escalate(
        "plan_groupby_auto", _attempt, seam="dispatch.execute",
        initial=b, growth=2, max_capacity=cap,
        exhaust=lambda c, steps: PlanBudgetExceeded(
            f"groupby exceeded max_budget={cap} distinct groups"))


@func_range("dense_id_counts")
def dense_id_counts(gid: jnp.ndarray, m: int,
                    block: int = 8192) -> jnp.ndarray:
    """COUNT(*) per dense group id WITHOUT sort or scatter: a
    ``lax.scan`` over row blocks, each step materializing one
    (block, m) one-hot compare and reducing it — total traffic n*m
    bools, streamed block-by-block so VMEM holds one tile at a time.

    This is the groupby for mid-cardinality DENSE keys (m in the
    hundreds-to-thousands): too many groups for the bounded
    masked-reduction unroll (m Python-level mask terms), no sort needed
    because the key IS the group id. ``gid`` entries outside [0, m)
    (invalid/filtered/padding rows) count nowhere. Exact: int32
    accumulation, counts <= n < 2^31."""
    n = gid.shape[0]
    if n == 0:
        return jnp.zeros((m,), jnp.int64)
    gb, _ = _dense_prologue(gid, m, block, None)
    slots = jnp.arange(m, dtype=jnp.int32)[None, :]

    def step(acc, blk):
        oh = blk[:, None] == slots
        return acc + jnp.sum(oh, axis=0, dtype=jnp.int32), None

    # init derives from the input so its varying-manner annotation
    # matches the carry under shard_map (a plain zeros constant is
    # 'replicated' and the scan rejects the carry type mismatch)
    init = jnp.zeros((m,), jnp.int32) + gb[0, 0] * 0
    acc, _ = jax.lax.scan(step, init, gb)
    return acc.astype(jnp.int64)


@func_range("dense_id_sums")
def dense_id_sums(gid: jnp.ndarray, values: jnp.ndarray, m: int,
                  block: int = 1024) -> jnp.ndarray:
    """SUM(values) per dense group id, exact int64, without sort or
    scatter — the ``dense_id_counts`` scheme with a masked value
    broadcast per block: each scan step materializes one
    (block, m) int64 select and column-reduces it. ``gid`` entries
    outside [0, m) contribute nowhere; ``values`` rows whose slot they
    feed must already be zeroed for SQL null semantics (callers mask
    with validity before the call)."""
    n = gid.shape[0]
    if n == 0:
        return jnp.zeros((m,), jnp.int64)
    gb, vb = _dense_prologue(gid, m, block, values)
    slots = jnp.arange(m, dtype=jnp.int32)[None, :]

    def step(acc, xs):
        blk_gid, blk_val = xs
        sel = jnp.where(blk_gid[:, None] == slots,
                        blk_val[:, None], jnp.int64(0))
        return acc + jnp.sum(sel, axis=0), None

    init = jnp.zeros((m,), jnp.int64) + vb[0, 0] * 0  # vma-matching init
    acc, _ = jax.lax.scan(step, init, (gb, vb))
    return acc


class PlannedGroupBy(NamedTuple):
    """Uniform result of ``plan_groupby`` over both lowerings.

    ``table`` rows are in key order with null-key groups last. On the
    bounded plan the shape is the static slot count m and ``present``
    marks live groups; on the general plan the shape is the padded
    ``max_groups`` budget and ``present`` marks the first
    ``num_groups`` rows. ``domain_miss`` is False on the general plan
    (nothing to miss). ``overflowed`` is the general plan's escape
    hatch: True when the data held more groups than the budget (the
    excess was dropped — grow the budget and retry, the
    groupby_aggregate_auto posture); always False on the bounded plan,
    whose slot count is checked at plan time."""

    table: Table
    present: jnp.ndarray
    domain_miss: jnp.ndarray
    lowered: str  # "bounded" | "general" — static plan fact
    # bool or jnp scalar; a plain False default keeps module import free
    # of backend initialization (import-hygiene contract)
    overflowed: object = False


@func_range("plan_groupby")
def plan_groupby(
    table: Table,
    keys: Sequence[int],
    aggs: Sequence[tuple[int, str]],
    domains: Sequence[Domain | None],
    budget: int = 4096,
    row_valid: jnp.ndarray | None = None,
) -> PlannedGroupBy:
    """Lower a groupby to the sort-free bounded plan when the planner can
    bound every key, else to the general sort-based plan.

    Bounded eligibility: every key has a declared ``Domain``, the slot
    count ``prod(len(d)+1)`` fits ``budget``, and every agg is in the
    associative single-pass set (sum/count/mean/min/max). String keys are
    dictionary-encoded on device (``encode_string_key``) and decoded back
    to static string columns at the output — the decode costs nothing at
    runtime (trace-time constants from ``bounded_group_layout``).

    ``row_valid``: bool[n] marking rows that EXIST (shard_table padding
    contract). On the bounded plan non-rows join no slot; on the
    general fallback their keys and values are nulled, so they fold
    into the null-key pseudo-group every consumer already discards.
    """
    if len(domains) != len(keys):
        raise ValueError("one Domain (or None) per key required")
    # NOTE: no row-count condition — lowering is a static plan fact
    # (empty tables take the bounded plan too; groupby_aggregate_bounded
    # handles n == 0 with its static slot table)
    bounded_ok = (
        all(d is not None for d in domains)
        and all(op in ("sum", "count", "mean", "min", "max")
                for _, op in aggs)
        and int(np.prod([len(d.values) + 1 for d in domains])) <= budget
    )
    if not bounded_ok:
        if row_valid is not None:
            table = Table([
                Column(c.dtype, c.data, c.valid_mask() & row_valid,
                       chars=c.chars, children=c.children)
                for c in table.columns
            ])
        g = groupby_aggregate(table, keys=list(keys), aggs=list(aggs),
                              max_groups=min(budget, table.num_rows) or 1)
        srt = sort_table(g.table, list(range(len(keys))),
                         nulls_first=[False] * len(keys))
        present = (jnp.arange(srt.num_rows, dtype=jnp.int32)
                   < g.num_groups)
        # overflowed surfaces budget-dropped groups — the caller's signal
        # to grow and retry; never silently swallowed
        return PlannedGroupBy(srt, present, jnp.bool_(False), "general",
                              g.overflowed)

    # bounded plan: encode string keys to dense codes, run the static
    # masked-reduction groupby, decode codes back to strings
    work_cols = list(table.columns)
    key_domains: list[Sequence[int]] = []
    string_positions: dict[int, Domain] = {}
    for pos, (k, dom) in enumerate(zip(keys, domains)):
        if dom.kind == "string":
            work_cols[k] = encode_string_key(table.column(k), dom)
            key_domains.append(tuple(range(len(dom.values))))
            string_positions[pos] = dom
        else:
            key_domains.append(dom.values)
    res = groupby_aggregate_bounded(
        Table(work_cols), keys=list(keys), aggs=list(aggs),
        key_domains=key_domains, row_valid=row_valid)

    if string_positions:
        _, m, slot_codes, order = bounded_group_layout(
            [len(d) for d in key_domains])
        out_cols = list(res.table.columns)
        for pos, dom in string_positions.items():
            # static decode, built in numpy (trace-time constants): group
            # slot i's string is fully determined by the layout
            w = max((len(v.encode()) for v in dom.values), default=1) or 1
            mat = np.zeros((m, w), np.uint8)
            lens = np.zeros((m,), np.int32)
            valid_np = np.zeros((m,), bool)
            for i in range(m):
                code = slot_codes[order[i], pos]
                if code < len(dom.values):
                    b = dom.values[code].encode()
                    mat[i, : len(b)] = np.frombuffer(b, np.uint8)
                    lens[i] = len(b)
                    valid_np[i] = True
            out_cols[pos] = Column(
                t.STRING, jnp.asarray(lens),
                jnp.asarray(valid_np) & res.present,
                chars=jnp.asarray(mat))
        return PlannedGroupBy(Table(out_cols), res.present,
                              res.domain_miss, "bounded")
    return PlannedGroupBy(res.table, res.present, res.domain_miss,
                          "bounded")
