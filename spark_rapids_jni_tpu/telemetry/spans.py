"""Hierarchical query spans: one causal tree per served query.

The reference answers "why was this query slow" with NVTX ranges
(``CUDF_FUNC_RANGE()``) that nest into a causal timeline in Nsight; our
flat counters and unordered JSONL events cannot. A span is a named,
timestamped (``time.monotonic``) node with an id, a parent id and a
status (``ok`` / ``degraded`` / ``cancelled`` / ``failed``); the serving
path opens one root per query and every instrumented seam underneath
(admission wait, degrade rung, fused region, out-of-core chunk stage,
spill/unspill) attaches a child, so a single tree records
``query -> admission.wait -> rung.* -> region.* / pipeline.chunk ->
pipeline.{decode,staging,transfer,compute,merge} -> spill/unspill``.

Contracts:
- **Zero overhead when disabled.** Every factory checks
  ``telemetry.enabled`` once and hands back a shared no-op span; nothing
  allocates, nothing locks, nothing emits.
- **Never on the device path.** Spans only read the host clock and
  append to host-side structures; opening or closing one never forces a
  device sync or transfer.
- **Emission through the one JSONL sink.** Closing a span emits a
  ``kind="span"`` record via events._emit — same ring buffer, same
  file, same never-raise posture as every other telemetry record.
- **Scope discipline.** A span must be used as a context manager (tpulint
  rule span-must-scope): ``with spans.span(...) as sp:`` — a raise then
  still closes it, marking status from the exception class
  (QueryCancelled -> ``cancelled``, anything else -> ``failed``).

The **flight recorder** keeps a bounded ring of recent span trees
(``telemetry.flight_recorder_depth``); ``dump_flight_record`` snapshots
the current tree plus caller-supplied limiter/queue state into one
structured artifact, written to ``telemetry.flight_recorder_path`` when
set and referenced from the server's rejection/failure records.

Chrome-trace export (``chrome_trace`` / ``python -m
spark_rapids_jni_tpu.telemetry trace``) lays the same records out as
``chrome://tracing`` / Perfetto complete events, one display track per
(query, OS thread) pair so overlapping decode-pool chunks render side
by side while each track stays properly nested.
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from typing import Any, Iterable, Optional

import importlib

from spark_rapids_jni_tpu.telemetry.registry import REGISTRY
from spark_rapids_jni_tpu.utils.config import get_option

# The package __init__ re-exports the events() *function*, which shadows the
# submodule attribute — resolve the module itself, unambiguously.
_events = importlib.import_module("spark_rapids_jni_tpu.telemetry.events")

__all__ = [
    "Span",
    "NULL_SPAN",
    "span",
    "child",
    "current_span",
    "current_root",
    "validate",
    "chrome_trace",
    "write_chrome_trace",
    "phase_breakdown",
    "flight_records",
    "dump_flight_record",
    "reset",
]

STATUSES = ("ok", "degraded", "cancelled", "failed")

# Walking __mro__ by class NAME keeps this module import-free of the
# runtime layer (resilience imports telemetry; the reverse would cycle).
_CANCEL_EXC_NAME = "QueryCancelled"

_ctx = threading.local()  # .stack: list[Span] — this thread's open spans

_id_lock = threading.Lock()
_next_id = 0


def _new_id() -> int:
    global _next_id
    with _id_lock:
        _next_id += 1
        return _next_id


def _stack() -> list:
    stack = getattr(_ctx, "stack", None)
    if stack is None:
        stack = []
        _ctx.stack = stack
    return stack


class _NullSpan:
    """Shared no-op span: what the factories return when telemetry is
    disabled (or ``child`` finds no open parent). Supports the full Span
    surface so call sites never branch on enablement."""

    __slots__ = ()
    id = None
    parent_id = None
    root = None
    name = ""
    status = "ok"

    def __enter__(self) -> "_NullSpan":
        return self

    def __exit__(self, *exc: object) -> bool:
        return False

    def set_status(self, status: str) -> None:
        pass

    def annotate(self, **attrs: Any) -> None:
        pass

    def __bool__(self) -> bool:
        return False


NULL_SPAN = _NullSpan()


class Span:
    """One live node of a query's causal tree.

    Created via the ``span``/``child`` factories, entered immediately
    (``with``), closed by ``__exit__`` — which stamps the end timestamp,
    derives status from any in-flight exception, emits the JSONL record
    and, for a root, hands the completed tree to the flight recorder.
    Children normally attach to the thread-local current span; crossing
    a thread boundary (decode pool) passes ``parent=`` explicitly and
    the child still pushes onto *its* thread's stack so deeper spans
    nest correctly.
    """

    __slots__ = ("id", "parent", "root", "name", "status", "start", "end",
                 "attrs", "children", "tid", "_entered",
                 "_tree_lock", "_nodes", "_dropped", "_max_nodes")

    def __init__(self, name: str, parent: Optional["Span"],
                 attrs: dict) -> None:
        self.id = _new_id()
        self.name = str(name)
        self.parent = parent
        self.status = "ok"
        self.attrs = dict(attrs)
        self.children: list = []
        self.start: float = 0.0
        self.end: Optional[float] = None
        self.tid = threading.get_ident()
        self._entered = False
        if parent is None:
            self.root = self
            # the in-memory tree backs the flight recorder and inspect();
            # the JSONL sink stays unbounded — past the cap, records still
            # emit but the tree stops growing.
            self._tree_lock = threading.Lock()
            self._nodes = 1
            self._dropped = 0
            self._max_nodes = int(get_option("telemetry.max_spans_per_tree"))
        else:
            self.root = parent.root
            self._tree_lock = None
            self._nodes = 0
            self._dropped = 0
            self._max_nodes = 0

    # -- context management --------------------------------------------------

    def __enter__(self) -> "Span":
        if self._entered:
            raise RuntimeError(f"span {self.name!r} entered twice")
        self._entered = True
        self.tid = threading.get_ident()
        if self.parent is not None:
            root = self.root
            with root._tree_lock:
                if root._nodes < root._max_nodes:
                    root._nodes += 1
                    self.parent.children.append(self)
                else:
                    root._dropped += 1
        _stack().append(self)
        self.start = time.monotonic()
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        self.end = time.monotonic()
        if exc_type is not None and self.status == "ok":
            names = {c.__name__ for c in getattr(exc_type, "__mro__", ())}
            self.status = ("cancelled" if _CANCEL_EXC_NAME in names
                           else "failed")
            if self.status == "failed":
                self.attrs.setdefault("error", exc_type.__name__)
        stack = _stack()
        if stack and stack[-1] is self:
            stack.pop()
        if _events.enabled():
            rec = dict(self.attrs)
            rec.update({
                "kind": "span",
                "op": self.name,
                "span": self.id,
                "parent": self.parent.id if self.parent is not None else None,
                "root": self.root.id,
                "t0": self.start,
                "t1": self.end,
                "dur_ms": round((self.end - self.start) * 1e3, 6),
                "status": self.status,
                "tid": self.tid,
            })
            _events._emit(rec)
            REGISTRY.counter("spans_total").inc()
            if self.parent is None:
                _RECORDER.note({
                    "trigger": "completed",
                    "root": self.id,
                    "tree": self.tree(),
                })
        return False

    # -- mutation ------------------------------------------------------------

    def set_status(self, status: str) -> None:
        if status not in STATUSES:
            raise ValueError(
                f"span status {status!r} not in {STATUSES}")
        self.status = status

    def annotate(self, **attrs: Any) -> None:
        self.attrs.update(attrs)

    # -- tree inspection -----------------------------------------------------

    def _node(self) -> dict:
        return {
            "span": self.id,
            "name": self.name,
            "status": self.status if self.end is not None else "open",
            "t0": self.start,
            "t1": self.end,
            "attrs": dict(self.attrs),
            "children": [c._node() for c in self.children],
        }

    def tree(self) -> dict:
        """Serialize the whole tree this span roots (or belongs to).
        Open spans appear with ``t1: null`` / status ``open``."""
        root = self.root
        with root._tree_lock:
            out = root._node()
        if root._dropped:
            out["dropped_spans"] = root._dropped
        return out

    def deepest_open(self) -> Optional["Span"]:
        """The deepest not-yet-closed span in this tree — 'where is this
        query right now' for live introspection."""
        root = self.root
        with root._tree_lock:
            node = root if root.end is None else None
            cur = root
            while True:
                nxt = None
                for c in reversed(cur.children):
                    if c.end is None:
                        nxt = c
                        break
                if nxt is None:
                    return node
                node = nxt
                cur = nxt


def span(name: str, *, parent: Optional[Span] = None, **attrs: Any):
    """Open a span. With no ``parent`` and no thread-local current span
    this starts a new root (a new query tree) — seams that must never
    create trees of their own use :func:`child` instead."""
    if not _events.enabled():
        return NULL_SPAN
    if parent is None:
        parent = current_span()
    if isinstance(parent, _NullSpan):
        parent = None
    return Span(name, parent, attrs)


def child(name: str, *, parent: Optional[Span] = None, **attrs: Any):
    """Open a child span only when there is a tree to attach to: returns
    the no-op span when telemetry is disabled or no parent exists. The
    instrumentation seams (trace_range, pipeline stages, dispatch,
    spill) all use this, so standalone calls outside a served query
    never fabricate orphan roots."""
    if not _events.enabled():
        return NULL_SPAN
    p = parent if parent is not None else current_span()
    if p is None or isinstance(p, _NullSpan):
        return NULL_SPAN
    return Span(name, p, attrs)


def current_span() -> Optional[Span]:
    stack = getattr(_ctx, "stack", None)
    return stack[-1] if stack else None


def current_root() -> Optional[Span]:
    cur = current_span()
    return cur.root if cur is not None else None


# ---------------------------------------------------------------------------
# flight recorder
# ---------------------------------------------------------------------------


class _FlightRecorder:
    """Bounded ring of recent span trees (completed roots and explicit
    dumps). Depth re-reads ``telemetry.flight_recorder_depth`` on every
    note so tests/operators can resize without rebuilding the ring."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._ring: deque = deque()
        self._seq = 0

    def note(self, entry: dict) -> None:
        depth = max(1, int(get_option("telemetry.flight_recorder_depth")))
        with self._lock:
            self._seq += 1
            entry = dict(entry)
            entry["seq"] = self._seq
            self._ring.append(entry)
            while len(self._ring) > depth:
                self._ring.popleft()

    def records(self) -> list:
        with self._lock:
            return list(self._ring)

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()


_RECORDER = _FlightRecorder()


def flight_records() -> list:
    """The in-memory flight-recorder ring, oldest first."""
    return _RECORDER.records()


def _safe_name(text: str) -> str:
    return "".join(c if c.isalnum() or c in "-_" else "_" for c in str(text))


def dump_flight_record(trigger: str, *, root: Optional[Span] = None,
                       state: Optional[dict] = None) -> Optional[str]:
    """Snapshot one query's span tree plus the caller-supplied runtime
    state (limiter watermarks, queue depths) into a single structured
    artifact: appended to the in-memory ring always, written as JSON
    under ``telemetry.flight_recorder_path`` when that is set. Returns
    the artifact path (referenced from QueryRejected / failure records)
    or None. Never raises — a failed write only bumps the
    ``dropped_writes`` counter, matching the JSONL sink's posture."""
    if not _events.enabled():
        return None
    if root is None:
        root = current_root()
    tree = root.tree() if isinstance(root, Span) else None
    artifact = {
        "kind": "flight_record",
        "trigger": str(trigger),
        "ts": time.time(),
        "session": _events.current_session(),
        "root": root.id if isinstance(root, Span) else None,
        "tree": tree,
        "state": dict(state) if state else {},
    }
    _RECORDER.note(artifact)
    out_dir = str(get_option("telemetry.flight_recorder_path") or "")
    if not out_dir:
        return None
    with _RECORDER._lock:
        seq = _RECORDER._seq
    fname = os.path.join(
        out_dir,
        f"flight-{seq:06d}-{_safe_name(trigger)}-"
        f"{artifact['root'] or 'noroot'}.json")
    try:
        os.makedirs(out_dir, exist_ok=True)
        with open(fname, "w", encoding="utf-8") as fh:
            json.dump(artifact, fh, sort_keys=True, default=str)
    except OSError:
        REGISTRY.counter("dropped_writes").inc()
        return None
    REGISTRY.counter("flight_records").inc()
    return fname


def reset() -> None:
    """Clear the flight-recorder ring (tests)."""
    _RECORDER.clear()


# ---------------------------------------------------------------------------
# record-stream analysis: wellformedness, Chrome trace, phase attribution
# ---------------------------------------------------------------------------


def _span_records(records: Iterable[dict]) -> list:
    return [r for r in records
            if isinstance(r, dict) and r.get("kind") == "span"
            and "t0" in r and "t1" in r]


def validate(records: Iterable[dict]) -> list:
    """Wellformedness of the span records in a telemetry stream: every
    tree has exactly one root, every parent id resolves inside the same
    tree, and end >= start. Returns a list of problem strings (empty =
    well-formed) — used by tests and the CI trace smoke."""
    recs = _span_records(records)
    problems = []
    by_id = {}
    for r in recs:
        sid = r.get("span")
        if sid in by_id:
            problems.append(f"duplicate span id {sid}")
        by_id[sid] = r
    roots_of: dict = {}
    for r in recs:
        roots_of.setdefault(r.get("root"), []).append(r)
    for root_id, members in sorted(roots_of.items(), key=lambda kv: str(kv[0])):
        roots = [r for r in members if r.get("parent") is None]
        if len(roots) != 1:
            problems.append(
                f"tree {root_id}: {len(roots)} parentless spans (want 1)")
        elif roots[0].get("span") != root_id:
            problems.append(
                f"tree {root_id}: root record has span id "
                f"{roots[0].get('span')}")
        for r in members:
            pid = r.get("parent")
            if pid is not None:
                parent = by_id.get(pid)
                if parent is None:
                    problems.append(
                        f"span {r.get('span')} ({r.get('op')}): orphan "
                        f"parent id {pid}")
                elif parent.get("root") != r.get("root"):
                    problems.append(
                        f"span {r.get('span')}: parent {pid} belongs to "
                        f"tree {parent.get('root')}, not {r.get('root')}")
            if float(r.get("t1", 0.0)) < float(r.get("t0", 0.0)):
                problems.append(
                    f"span {r.get('span')} ({r.get('op')}): end < start")
            if r.get("status") not in STATUSES:
                problems.append(
                    f"span {r.get('span')} ({r.get('op')}): bad status "
                    f"{r.get('status')!r}")
    return problems


_ARG_KEYS = ("span", "parent", "root", "status", "session")


def chrome_trace(records: Iterable[dict]) -> dict:
    """Lay the span records out as Chrome-trace / Perfetto 'complete'
    (ph: X) events. Chrome nests events per (pid, tid) by time
    containment, and our stack discipline only holds per OS thread
    within one query, so each (query root, OS thread) pair gets its own
    display track — overlapping decode-pool chunks land side by side
    instead of corrupting one track's nesting."""
    recs = sorted(_span_records(records),
                  key=lambda r: float(r.get("t0", 0.0)))
    lanes: dict = {}
    root_labels: dict = {}
    events = []
    for r in recs:
        root = r.get("root", r.get("span"))
        key = (root, r.get("tid", 0))
        tid = lanes.setdefault(key, len(lanes) + 1)
        if r.get("parent") is None:
            sess = r.get("session", "")
            root_labels[root] = (f"{r.get('op', '?')}"
                                 + (f" [{sess}]" if sess else ""))
        args = {k: r[k] for k in _ARG_KEYS if k in r}
        for k, v in r.items():
            if k not in args and k not in ("kind", "op", "t0", "t1",
                                           "dur_ms", "tid", "ts",
                                           "platform"):
                args[k] = v
        events.append({
            "name": r.get("op", "?"),
            "cat": "span",
            "ph": "X",
            "ts": round(float(r["t0"]) * 1e6, 3),
            "dur": max(round((float(r["t1"]) - float(r["t0"])) * 1e6, 3),
                       0.001),
            "pid": 1,
            "tid": tid,
            "args": args,
        })
    meta = [{"name": "process_name", "ph": "M", "pid": 1,
             "args": {"name": "spark_rapids_jni_tpu"}}]
    for (root, os_tid), tid in sorted(lanes.items(),
                                      key=lambda kv: kv[1]):
        label = root_labels.get(root, f"tree {root}")
        meta.append({"name": "thread_name", "ph": "M", "pid": 1,
                     "tid": tid, "args": {"name": label}})
        meta.append({"name": "thread_sort_index", "ph": "M", "pid": 1,
                     "tid": tid, "args": {"sort_index": tid}})
    return {"traceEvents": meta + events, "displayTimeUnit": "ms"}


def write_chrome_trace(records: Iterable[dict], out_path: str) -> int:
    """Export ``records`` as Chrome-trace JSON; returns the number of
    span events written."""
    doc = chrome_trace(records)
    with open(out_path, "w", encoding="utf-8") as fh:
        json.dump(doc, fh, sort_keys=True)
    return sum(1 for e in doc["traceEvents"] if e.get("ph") == "X")


# span name -> bench phase. region.* spans also count as compute, and a
# span nested under an already-attributed ancestor contributes nothing
# (e.g. the merge region inside outofcore.merge, or per-chunk regions
# inside pipeline.compute) — each wall-clock second lands in ONE phase.
_PHASE_OF = {
    "admission.wait": "admission",
    "pipeline.decode": "decode",
    "pipeline.staging": "staging",
    "pipeline.transfer": "transfer",
    "pipeline.compute": "compute",
    "pipeline.merge": "merge",
    "outofcore.merge": "merge",
}

PHASES = ("admission", "queue", "decode", "staging", "transfer",
          "compute", "merge")


def phase_breakdown(records: Iterable[dict]) -> dict:
    """Span-derived per-phase wall attribution for the bench blocks:
    seconds (and fractions of total root-span wall) spent in admission
    wait, pre-admission queueing, decode/staging/transfer, compute and
    merge. Queue time comes from the server's ``admitted`` events
    (submit-to-grant wait) minus the admission-wait spans nested in it."""
    records = list(records)
    recs = _span_records(records)
    by_id = {r.get("span"): r for r in recs}

    def _phase_of(rec: dict) -> Optional[str]:
        op = str(rec.get("op", ""))
        phase = _PHASE_OF.get(op)
        if phase is None and op.startswith("region."):
            phase = "compute"
        return phase

    def _ancestor_attributed(rec: dict) -> bool:
        hops = 0
        cur = rec
        while hops < 64:
            pid = cur.get("parent")
            if pid is None:
                return False
            cur = by_id.get(pid)
            if cur is None:
                return False
            if _phase_of(cur) is not None:
                return True
            hops += 1
        return False

    roots = [r for r in recs if r.get("parent") is None]
    total = sum(max(0.0, float(r["t1"]) - float(r["t0"])) for r in roots)
    phases = {p: 0.0 for p in PHASES}
    for r in recs:
        dur = max(0.0, float(r["t1"]) - float(r["t0"]))
        phase = _phase_of(r)
        if phase is not None and not _ancestor_attributed(r):
            phases[phase] += dur
    queue_s = 0.0
    for r in records:
        if (isinstance(r, dict) and r.get("kind") == "server"
                and r.get("event") == "admitted"):
            queue_s += float(r.get("wait_ms", 0.0)) / 1e3
    phases["queue"] = max(0.0, queue_s - phases["admission"])
    return {
        "queries": len(roots),
        "total_s": round(total, 6),
        "phases_s": {k: round(v, 6) for k, v in phases.items()},
        "fractions": ({k: (round(v / total, 4) if total else 0.0)
                       for k, v in phases.items()} if roots else {}),
    }
