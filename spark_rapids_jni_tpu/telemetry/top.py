"""Live serving introspection: the ``top`` view over in-flight queries.

``python -m spark_rapids_jni_tpu.telemetry top`` renders, per in-flight
query: session, plan, ticket status, current degrade tier/rung, held
reservation bytes, deadline remaining and the deepest currently-open
span — plus the limiter watermark state and per-session queue depths
that explain WHY a query is parked.

Two sources feed the same renderer:

- **live**: :func:`collect` finds every open ``QueryServer`` in THIS
  process through ``runtime.server.live_servers()`` and snapshots each
  via ``inspect()``. The lookup goes through ``sys.modules`` — telemetry
  never imports the runtime (which would pull in jax), the same
  zero-dependency posture as the rest of the package. No server module
  loaded means no servers: ``collect`` returns ``[]``.
- **file**: a JSON snapshot previously captured from ``inspect()``
  (e.g. shipped from another process), passed as the CLI's optional
  path argument.

Pure stdlib; rendering never raises on missing keys so snapshots from
older writers stay readable.
"""

from __future__ import annotations

import sys
from typing import Any, Dict, List, Optional

__all__ = ["collect", "collect_fleet", "collect_cluster", "render_top",
           "render_fleet", "render_cluster"]


def collect() -> List[Dict[str, Any]]:
    """Snapshot every open QueryServer in this process (may be [])."""
    # sys.modules lookup, NOT an import: if the serving runtime was never
    # loaded there is nothing to inspect, and importing it from here
    # would drag jax into the telemetry package
    mod = sys.modules.get("spark_rapids_jni_tpu.runtime.server")
    if mod is None:
        return []
    return [srv.inspect() for srv in mod.live_servers()]


def collect_fleet() -> List[Dict[str, Any]]:
    """Snapshot every open QueryFleet supervisor in this process (may be
    []). Same ``sys.modules`` posture as :func:`collect` — no fleet
    module loaded means no fleets. Mesh clusters subclass the fleet and
    register in the same live set; they render through the cluster view
    (:func:`collect_cluster`) instead, so they are skipped here."""
    mod = sys.modules.get("spark_rapids_jni_tpu.runtime.fleet")
    if mod is None:
        return []
    return [f.inspect() for f in mod.live_fleets()
            if not getattr(f, "is_cluster", False)]


def collect_cluster() -> List[Dict[str, Any]]:
    """Snapshot every open QueryCluster mesh supervisor in this process
    (may be []). Same ``sys.modules`` posture — no cluster module loaded
    means no clusters."""
    mod = sys.modules.get("spark_rapids_jni_tpu.runtime.cluster")
    if mod is None:
        return []
    return [c.inspect() for c in mod.live_clusters()]


def _fmt_bytes(n: Optional[int]) -> str:
    if n is None:
        return "-"
    n = int(n)
    if n >= 1 << 30:
        return f"{n / (1 << 30):.2f}GiB"
    if n >= 1 << 20:
        return f"{n / (1 << 20):.2f}MiB"
    if n >= 1 << 10:
        return f"{n / (1 << 10):.1f}KiB"
    return str(n)


def _render_one(snap: Dict[str, Any]) -> List[str]:
    lines: List[str] = []
    lim = snap.get("limiter") or {}
    used = lim.get("used", 0)
    budget = lim.get("budget", 0)
    pct = (100.0 * used / budget) if budget else 0.0
    pressure = "PRESSURE" if lim.get("pressure") else "ok"
    lines.append(
        f"limiter: {_fmt_bytes(used)} / {_fmt_bytes(budget)} "
        f"({pct:.0f}%)  peak={_fmt_bytes(lim.get('peak'))}  "
        f"state={pressure}  waiters={lim.get('waiters', 0)} "
        f"(admission={lim.get('admission_waiters', 0)})")
    queues = snap.get("queues") or {}
    if queues:
        depth = "  ".join(f"{sid}={n}" for sid, n in sorted(queues.items()))
        lines.append(f"queued: {snap.get('queued', 0)}  [{depth}]")
    else:
        lines.append(f"queued: {snap.get('queued', 0)}")
    inflight = snap.get("inflight") or []
    headers = ("session", "plan", "status", "tier", "rung", "held",
               "age_s", "deadline_s", "span")
    rows = []
    for q in inflight:
        deadline = q.get("deadline_remaining_s")
        rows.append((
            str(q.get("session", "?")),
            str(q.get("plan", "?")),
            str(q.get("status", "?")),
            str(q.get("tier", "-")),
            str(q.get("rung", "-")),
            _fmt_bytes(q.get("held_bytes")),
            f"{q.get('age_s', 0.0):.3f}",
            "-" if deadline is None else f"{deadline:.3f}",
            str(q.get("current_span") or "-"),
        ))
    if not rows:
        lines.append("(no queries in flight)")
        return lines
    widths = [max(len(headers[i]), max(len(r[i]) for r in rows))
              for i in range(len(headers))]
    lines.append("  ".join(h.ljust(widths[i])
                           for i, h in enumerate(headers)).rstrip())
    lines.append("  ".join("-" * w for w in widths))
    for r in rows:
        lines.append("  ".join(c.ljust(widths[i])
                               for i, c in enumerate(r)).rstrip())
    return lines


def _render_fleet_one(snap: Dict[str, Any]) -> List[str]:
    lines: List[str] = []
    counters = snap.get("counters") or {}
    lines.append(
        f"pending={snap.get('pending_queries', 0)}  "
        f"memo={snap.get('memo_entries', 0)}  "
        f"learned={snap.get('learned_signatures', 0)}  "
        f"served={counters.get('fleet.served', 0)}  "
        f"failovers={counters.get('fleet.failovers', 0)}  "
        f"deaths={counters.get('fleet.replica_deaths', 0)}  "
        f"quarantines={counters.get('fleet.quarantines', 0)}")
    headers = ("replica", "state", "pid", "gen", "inflight", "served",
               "crashes", "pong_age_s", "restart_in_s", "queued", "leaked")
    rows = []
    for r in snap.get("replicas") or []:
        pong = r.get("last_pong_age_s")
        restart = r.get("restart_in_s")
        load = r.get("load") or {}
        rows.append((
            str(r.get("replica", "?")),
            str(r.get("state", "?")),
            str(r.get("pid") or "-"),
            str(r.get("generation", "-")),
            str(r.get("inflight", 0)),
            str(r.get("served", 0)),
            str(r.get("crashes", 0)),
            "-" if pong is None else f"{pong:.2f}",
            "-" if restart is None else f"{restart:.2f}",
            str(load.get("queued", "-")),
            _fmt_bytes(load.get("leaked")) if "leaked" in load else "-",
        ))
    if not rows:
        lines.append("(no replicas)")
        return lines
    widths = [max(len(headers[i]), max(len(r[i]) for r in rows))
              for i in range(len(headers))]
    lines.append("  ".join(h.ljust(widths[i])
                           for i, h in enumerate(headers)).rstrip())
    lines.append("  ".join("-" * w for w in widths))
    for r in rows:
        lines.append("  ".join(c.ljust(widths[i])
                               for i, c in enumerate(r)).rstrip())
    return lines


def render_fleet(snapshots: Any) -> str:
    """Text view of one :meth:`QueryFleet.inspect` snapshot or a list."""
    if isinstance(snapshots, dict):
        snapshots = [snapshots]
    if not snapshots:
        return "no live query fleets in this process"
    blocks = []
    for i, snap in enumerate(snapshots):
        lines = _render_fleet_one(snap)
        if len(snapshots) > 1:
            lines.insert(0, f"fleet {i}:")
        blocks.append("\n".join(lines))
    return "\n\n".join(blocks)


def _render_cluster_one(snap: Dict[str, Any]) -> List[str]:
    # the host table IS the replica table (one worker per simulated
    # host), rendered by the shared fleet renderer; the cluster adds its
    # partition map and routing counters on top
    lines = _render_fleet_one(snap)
    counters = snap.get("counters") or {}
    lines.insert(1, (
        f"routing: local={counters.get('cluster.route_local', 0)}  "
        f"rehomed={counters.get('cluster.route_rehomed', 0)}  "
        f"fanouts={counters.get('cluster.fanouts', 0)}  "
        f"merges={counters.get('cluster.merges', 0)}  "
        f"host_deaths={counters.get('cluster.host_deaths', 0)}"))
    tables = snap.get("tables") or {}
    for name in sorted(tables):
        t = tables[name]
        owners = t.get("owners") or []
        parts = "  ".join(f"p{i}->{o or '?'}" for i, o in enumerate(owners))
        lines.append(
            f"table {name}: parts={t.get('parts', len(owners))} "
            f"keys={t.get('keys')} rows={t.get('rows', '-')}  [{parts}]")
    return lines


def render_cluster(snapshots: Any) -> str:
    """Text view of one :meth:`QueryCluster.inspect` snapshot or a
    list: per-host worker table + partition map + routing counters."""
    if isinstance(snapshots, dict):
        snapshots = [snapshots]
    if not snapshots:
        return "no live query clusters in this process"
    blocks = []
    for i, snap in enumerate(snapshots):
        lines = _render_cluster_one(snap)
        if len(snapshots) > 1:
            lines.insert(0, f"cluster {i}:")
        blocks.append("\n".join(lines))
    return "\n\n".join(blocks)


def render_top(snapshots: Any) -> str:
    """Text view of one ``inspect()`` snapshot or a list of them."""
    if isinstance(snapshots, dict):
        snapshots = [snapshots]
    if not snapshots:
        return "no live query servers in this process"
    blocks = []
    for i, snap in enumerate(snapshots):
        lines = _render_one(snap)
        if len(snapshots) > 1:
            lines.insert(0, f"server {i}:")
        blocks.append("\n".join(lines))
    return "\n\n".join(blocks)
