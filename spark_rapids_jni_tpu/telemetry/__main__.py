"""CLI for telemetry runs: ``report``, ``trace`` and ``top``.

- ``report [--session <id>] [--kind <k>] <run.jsonl>`` — per-op table
  plus event summaries, optionally narrowed to one session or one event
  kind (dispatch | fallback | spill | server | degrade).
- ``trace [<run.jsonl>] <out.json>`` — export the run's span records as
  Chrome-trace / Perfetto JSON (load in ``chrome://tracing`` or
  https://ui.perfetto.dev). With one argument the input defaults to the
  configured ``telemetry.path``.
- ``top [<snapshot.json>]`` — render in-flight queries: from a saved
  ``QueryServer.inspect()`` snapshot, or live from this process. Saved
  or live ``QueryFleet.inspect()`` snapshots (self-identified by
  ``"fleet": true``) render as the per-replica fleet table.
"""

from __future__ import annotations

import json
import sys

from spark_rapids_jni_tpu.telemetry import spans, top
from spark_rapids_jni_tpu.telemetry.report import (
    KINDS, load_jsonl, report)
from spark_rapids_jni_tpu.utils.config import get_option

_USAGE = """\
usage: python -m spark_rapids_jni_tpu.telemetry <command> ...

commands:
  report [--session <id>] [--kind <k>] <run.jsonl>
  trace  [<run.jsonl>] <out.json>
  top    [<snapshot.json>]
"""


def _usage() -> int:
    print(_USAGE, file=sys.stderr)
    return 2


def _report(argv: list[str]) -> int:
    session = kind = None
    paths: list[str] = []
    i = 0
    while i < len(argv):
        arg = argv[i]
        if arg == "--session":
            if i + 1 >= len(argv):
                return _usage()
            session = argv[i + 1]
            i += 2
        elif arg == "--kind":
            if i + 1 >= len(argv):
                return _usage()
            kind = argv[i + 1]
            if kind not in KINDS:
                print(f"error: unknown kind {kind!r} "
                      f"(expected one of {', '.join(KINDS)})",
                      file=sys.stderr)
                return 2
            i += 2
        elif arg.startswith("-"):
            return _usage()
        else:
            paths.append(arg)
            i += 1
    if len(paths) != 1:
        return _usage()
    try:
        text = report(paths[0], session=session, kind=kind)
    except OSError as exc:
        print(f"error: {exc}", file=sys.stderr)
        return 1
    print(text)
    return 0


def _trace(argv: list[str]) -> int:
    if len(argv) == 1:
        src, out = str(get_option("telemetry.path")), argv[0]
        if not src:
            print("error: no input given and telemetry.path is unset",
                  file=sys.stderr)
            return 2
    elif len(argv) == 2:
        src, out = argv
    else:
        return _usage()
    try:
        n = spans.write_chrome_trace(load_jsonl(src), out)
    except OSError as exc:
        print(f"error: {exc}", file=sys.stderr)
        return 1
    print(f"wrote {n} span events to {out}")
    return 0


def _top(argv: list[str]) -> int:
    if len(argv) > 1:
        return _usage()
    if argv:
        try:
            with open(argv[0], "r", encoding="utf-8") as fh:
                snapshots = json.load(fh)
        except (OSError, json.JSONDecodeError) as exc:
            print(f"error: {exc}", file=sys.stderr)
            return 1
        # a QueryFleet.inspect() snapshot self-identifies ("fleet": True),
        # a QueryCluster one additionally carries "cluster": True, so
        # saved state renders through the matching view
        items = snapshots if isinstance(snapshots, list) else [snapshots]
        clusters = [s for s in items
                    if isinstance(s, dict) and s.get("cluster")]
        fleets = [s for s in items if isinstance(s, dict) and s.get("fleet")
                  and s not in clusters]
        servers = [s for s in items if s not in fleets and s not in clusters]
        out = []
        if servers or not (fleets or clusters):
            out.append(top.render_top(servers))
        if fleets:
            out.append("fleet:\n" + top.render_fleet(fleets))
        if clusters:
            out.append("cluster:\n" + top.render_cluster(clusters))
        print("\n\n".join(out))
        return 0
    print(top.render_top(top.collect()))
    fleets = top.collect_fleet()
    if fleets:
        print("\nfleet:\n" + top.render_fleet(fleets))
    clusters = top.collect_cluster()
    if clusters:
        print("\ncluster:\n" + top.render_cluster(clusters))
    return 0


def main(argv: list[str]) -> int:
    if not argv:
        return _usage()
    cmd, rest = argv[0], argv[1:]
    if cmd == "report":
        return _report(rest)
    if cmd == "trace":
        return _trace(rest)
    if cmd == "top":
        return _top(rest)
    return _usage()


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
