"""CLI: ``python -m spark_rapids_jni_tpu.telemetry report <run.jsonl>``."""

from __future__ import annotations

import sys

from spark_rapids_jni_tpu.telemetry.report import report

_USAGE = "usage: python -m spark_rapids_jni_tpu.telemetry report <run.jsonl>"


def main(argv: list[str]) -> int:
    if len(argv) != 2 or argv[0] != "report":
        print(_USAGE, file=sys.stderr)
        return 2
    try:
        text = report(argv[1])
    except OSError as exc:
        print(f"error: {exc}", file=sys.stderr)
        return 1
    print(text)
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
