"""Process-local metrics registry: counters, gauges, bounded histograms.

The reference exposes per-operator NVTX ranges plus RMM/cuDF counters that
operators scrape to see where GPU time goes; this is the TPU-side analogue,
deliberately dependency-free (no prometheus_client, no jax import) so it can
be pulled in from any layer — including ``bench.py``'s no-jax parent process —
without cost. All state is process-local and guarded by a single lock;
instruments are created on first use and live for the life of the process.

Cost model: when telemetry is disabled the record_* helpers in ``events.py``
return before touching the registry, so the only steady-state overhead is one
config lookup per instrumented call. The registry itself is always usable
(tests exercise it directly without flipping any option).
"""

from __future__ import annotations

import math
import threading
from typing import Dict, List, Sequence, Tuple

__all__ = [
    "Counter",
    "Gauge",
    "Histogram",
    "Registry",
    "REGISTRY",
    "DEFAULT_BOUNDS",
]

def _exposition_name(name: str) -> str:
    """Map an instrument name onto the Prometheus metric charset."""
    out = "".join(c if c.isalnum() or c in "_:" else "_" for c in name)
    if out and out[0].isdigit():
        out = "_" + out
    return out


# Default histogram bounds: geometric ms-scale ladder wide enough for both
# sub-ms device dispatches and multi-second out-of-core runs.
DEFAULT_BOUNDS: Tuple[float, ...] = (
    0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 25.0, 50.0,
    100.0, 250.0, 500.0, 1000.0, 2500.0, 5000.0, 10000.0,
)


class Counter:
    """Monotonic counter. ``inc`` with a negative amount is a bug."""

    __slots__ = ("name", "_value", "_lock")

    def __init__(self, name: str) -> None:
        self.name = name
        self._value = 0
        self._lock = threading.Lock()

    def inc(self, amount: int = 1) -> None:
        if amount < 0:
            raise ValueError(f"counter {self.name}: negative inc {amount}")
        with self._lock:
            self._value += amount

    @property
    def value(self) -> int:
        return self._value


class Gauge:
    """Last-write-wins scalar (e.g. current host-staged bytes)."""

    __slots__ = ("name", "_value", "_lock")

    def __init__(self, name: str) -> None:
        self.name = name
        self._value = 0.0
        self._lock = threading.Lock()

    def set(self, value: float) -> None:
        with self._lock:
            self._value = float(value)

    def add(self, delta: float) -> None:
        with self._lock:
            self._value += float(delta)

    @property
    def value(self) -> float:
        return self._value


class Histogram:
    """Bounded histogram: fixed bucket bounds, O(len(bounds)) memory.

    Observations land in the first bucket whose upper bound is >= the value;
    values above the last bound land in the overflow bucket. Percentiles are
    estimated by linear interpolation inside the winning bucket — good enough
    for p50/p95 reporting, and bounded regardless of observation count.
    """

    __slots__ = ("name", "bounds", "_counts", "_sum", "_count", "_max", "_lock")

    def __init__(self, name: str, bounds: Sequence[float] = DEFAULT_BOUNDS) -> None:
        if not bounds or list(bounds) != sorted(bounds):
            raise ValueError(f"histogram {name}: bounds must be sorted, non-empty")
        self.name = name
        self.bounds = tuple(float(b) for b in bounds)
        self._counts = [0] * (len(self.bounds) + 1)  # +1 overflow bucket
        self._sum = 0.0
        self._count = 0
        self._max = 0.0
        self._lock = threading.Lock()

    def observe(self, value: float) -> None:
        v = float(value)
        idx = len(self.bounds)
        for i, b in enumerate(self.bounds):
            if v <= b:
                idx = i
                break
        with self._lock:
            self._counts[idx] += 1
            self._sum += v
            self._count += 1
            if v > self._max:
                self._max = v

    @property
    def count(self) -> int:
        return self._count

    @property
    def sum(self) -> float:
        return self._sum

    def percentile(self, q: float) -> float:
        """Estimated q-th percentile (q in [0, 100])."""
        if not 0.0 <= q <= 100.0:
            raise ValueError(f"percentile {q} out of [0, 100]")
        with self._lock:
            total = self._count
            if total == 0:
                return 0.0
            rank = math.ceil(q / 100.0 * total) or 1
            seen = 0
            for i, c in enumerate(self._counts):
                if c == 0:
                    continue
                lo = 0.0 if i == 0 else self.bounds[i - 1]
                hi = self.bounds[i] if i < len(self.bounds) else self._max
                if seen + c >= rank:
                    frac = (rank - seen) / c
                    return lo + (hi - lo) * frac
                seen += c
            return self._max

    def snapshot(self) -> Dict[str, object]:
        with self._lock:
            return {
                "count": self._count,
                "sum": self._sum,
                "max": self._max,
                "bounds": list(self.bounds),
                "counts": list(self._counts),
            }


class Registry:
    """Named instrument store; create-on-first-use, thread-safe."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._counters: Dict[str, Counter] = {}
        self._gauges: Dict[str, Gauge] = {}
        self._histograms: Dict[str, Histogram] = {}

    def counter(self, name: str) -> Counter:
        with self._lock:
            c = self._counters.get(name)
            if c is None:
                c = self._counters[name] = Counter(name)
            return c

    def gauge(self, name: str) -> Gauge:
        with self._lock:
            g = self._gauges.get(name)
            if g is None:
                g = self._gauges[name] = Gauge(name)
            return g

    def histogram(self, name: str, bounds: Sequence[float] = DEFAULT_BOUNDS) -> Histogram:
        with self._lock:
            h = self._histograms.get(name)
            if h is None:
                h = self._histograms[name] = Histogram(name, bounds)
            return h

    def counters(self, prefix: str = "") -> Dict[str, int]:
        with self._lock:
            return {
                n: c.value for n, c in sorted(self._counters.items())
                if n.startswith(prefix)
            }

    def snapshot(self) -> Dict[str, object]:
        with self._lock:
            return {
                "counters": {n: c.value for n, c in sorted(self._counters.items())},
                "gauges": {n: g.value for n, g in sorted(self._gauges.items())},
                "histograms": {
                    n: h.snapshot() for n, h in sorted(self._histograms.items())
                },
            }

    def exposition(self) -> str:
        """Prometheus-style text exposition of every instrument, for a
        scrape endpoint or ``curl``-style operator inspection. Names are
        sanitized to the Prometheus charset ([a-zA-Z0-9_:]); histograms
        render cumulative ``_bucket{le=...}`` series plus ``_sum`` /
        ``_count``, matching the native histogram text format."""
        with self._lock:
            counters = sorted(self._counters.items())
            gauges = sorted(self._gauges.items())
            histograms = sorted(self._histograms.items())
        lines: List[str] = []
        for name, c in counters:
            metric = _exposition_name(name)
            lines.append(f"# TYPE {metric} counter")
            lines.append(f"{metric} {c.value}")
        for name, g in gauges:
            metric = _exposition_name(name)
            lines.append(f"# TYPE {metric} gauge")
            lines.append(f"{metric} {g.value}")
        for name, h in histograms:
            metric = _exposition_name(name)
            snap = h.snapshot()
            lines.append(f"# TYPE {metric} histogram")
            cumulative = 0
            counts = snap["counts"]
            for bound, count in zip(snap["bounds"], counts):
                cumulative += count
                lines.append(f'{metric}_bucket{{le="{bound}"}} {cumulative}')
            cumulative += counts[-1]  # overflow bucket
            lines.append(f'{metric}_bucket{{le="+Inf"}} {cumulative}')
            lines.append(f"{metric}_sum {snap['sum']}")
            lines.append(f"{metric}_count {snap['count']}")
        return "\n".join(lines) + "\n"

    def reset(self) -> None:
        """Drop all instruments (test isolation; not for production paths)."""
        with self._lock:
            self._counters.clear()
            self._gauges.clear()
            self._histograms.clear()


#: The process-local registry every instrumented seam records into.
REGISTRY = Registry()
