"""Aggregate a telemetry JSONL run into a per-op table.

``python -m spark_rapids_jni_tpu.telemetry report <run.jsonl>`` renders, per
op: how many executions landed on device vs. host (the fallback split the
round-5 bench couldn't see), p50/p95 wall time of the timed dispatches, and
bytes moved by spills. Pure stdlib; torn/garbage lines are skipped, matching
the bench ledger's crash-tolerant read posture.
"""

from __future__ import annotations

import json
from typing import Any, Dict, Iterable, List, Optional, Tuple

from spark_rapids_jni_tpu.telemetry.events import summary

__all__ = ["load_jsonl", "filter_records", "aggregate", "render_table",
           "report"]

# --kind values the CLI accepts ("span" records are the trace
# substrate, not an event category: export those with ``trace``)
KINDS = ("dispatch", "fallback", "spill", "server", "degrade", "integrity")


def filter_records(
    records: Iterable[Dict[str, Any]],
    *,
    session: Optional[str] = None,
    kind: Optional[str] = None,
) -> List[Dict[str, Any]]:
    """Narrow a record stream to one session and/or one event kind.

    ``session`` matches the ambient session id every emitter stamps;
    records with no session (emitted outside ``session_scope``) only
    survive when no session filter is given. ``kind`` must be one of
    :data:`KINDS` (ValueError otherwise).
    """
    if kind is not None and kind not in KINDS:
        raise ValueError(
            f"unknown kind {kind!r}: expected one of {', '.join(KINDS)}")
    out: List[Dict[str, Any]] = []
    for rec in records:
        if session is not None and rec.get("session") != session:
            continue
        if kind is not None and rec.get("kind") != kind:
            continue
        out.append(rec)
    return out


def load_jsonl(path: str) -> List[Dict[str, Any]]:
    """Parse a JSONL event file, skipping torn or non-JSON lines."""
    out: List[Dict[str, Any]] = []
    with open(path, "r", encoding="utf-8") as fh:
        for line in fh:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                continue
            if isinstance(rec, dict):
                out.append(rec)
    return out


def _percentile(sorted_vals: List[float], q: float) -> float:
    # nearest-rank on the exact sample (file-based: we have every observation)
    if not sorted_vals:
        return 0.0
    rank = max(1, int(round(q / 100.0 * len(sorted_vals) + 0.5)))
    return sorted_vals[min(rank, len(sorted_vals)) - 1]


def aggregate(records: Iterable[Dict[str, Any]]) -> Dict[str, Dict[str, Any]]:
    """Per-op stats: device/host split, p50/p95 wall ms, bytes moved.

    An op instrumented through ``trace_range(record=True)`` records one
    ``dispatch`` per *call* regardless of where it landed; the fallback
    event is what marks a call as host-run. So: host = fallback count,
    device = calls - host (fallback-only seams have calls=0, device=0).
    """
    per_op: Dict[str, Dict[str, Any]] = {}

    def row(op: str) -> Dict[str, Any]:
        r = per_op.get(op)
        if r is None:
            r = per_op[op] = {
                "calls": 0, "host": 0, "spills": 0,
                "bytes_moved": 0, "wall_ms": [], "reasons": {},
            }
        return r

    for rec in records:
        kind = rec.get("kind")
        op = str(rec.get("op", "?"))
        if kind == "dispatch":
            r = row(op)
            r["calls"] += 1
            if "wall_ms" in rec:
                r["wall_ms"].append(float(rec["wall_ms"]))
        elif kind == "fallback":
            r = row(op)
            r["host"] += 1
            reason = str(rec.get("reason", ""))
            if reason:
                r["reasons"][reason] = r["reasons"].get(reason, 0) + 1
        elif kind == "spill":
            r = row(op)
            r["spills"] += 1
            r["bytes_moved"] += int(rec.get("bytes_moved", 0))

    for r in per_op.values():
        walls = sorted(r.pop("wall_ms"))
        r["p50_ms"] = _percentile(walls, 50.0)
        r["p95_ms"] = _percentile(walls, 95.0)
        r["timed"] = len(walls)
        r["device"] = max(r["calls"] - r["host"], 0)
    return per_op


def _fmt_bytes(n: int) -> str:
    if n >= 1 << 30:
        return f"{n / (1 << 30):.2f}GiB"
    if n >= 1 << 20:
        return f"{n / (1 << 20):.2f}MiB"
    if n >= 1 << 10:
        return f"{n / (1 << 10):.1f}KiB"
    return str(n)


def render_table(per_op: Dict[str, Dict[str, Any]]) -> str:
    """Fixed-width text table, one row per op plus a TOTAL row."""
    headers = ("op", "device", "host", "p50_ms", "p95_ms", "bytes_moved")
    rows: List[Tuple[str, ...]] = []
    tot_dev = tot_host = tot_bytes = 0
    for op in sorted(per_op):
        r = per_op[op]
        tot_dev += r["device"]
        tot_host += r["host"]
        tot_bytes += r["bytes_moved"]
        rows.append((
            op,
            str(r["device"]),
            str(r["host"]),
            f"{r['p50_ms']:.2f}" if r["timed"] else "-",
            f"{r['p95_ms']:.2f}" if r["timed"] else "-",
            _fmt_bytes(r["bytes_moved"]) if r["bytes_moved"] else "-",
        ))
    rows.append(("TOTAL", str(tot_dev), str(tot_host), "", "", _fmt_bytes(tot_bytes)))
    widths = [
        max(len(headers[i]), max((len(row[i]) for row in rows), default=0))
        for i in range(len(headers))
    ]

    def line(cells: Tuple[str, ...]) -> str:
        # op column left-aligned, numerics right-aligned
        parts = [cells[0].ljust(widths[0])]
        parts += [cells[i].rjust(widths[i]) for i in range(1, len(headers))]
        return "  ".join(parts).rstrip()

    sep = "  ".join("-" * w for w in widths)
    return "\n".join([line(headers), sep] + [line(r) for r in rows])


def report(path: str, *, session: Optional[str] = None,
           kind: Optional[str] = None) -> str:
    """Full report text for a JSONL run: per-op table + summary counts.

    ``session``/``kind`` narrow the input through
    :func:`filter_records` before aggregation (the CLI's ``--session``
    and ``--kind`` flags), so every table and count below reflects the
    filtered view.
    """
    records = load_jsonl(path)
    if session is not None or kind is not None:
        records = filter_records(records, session=session, kind=kind)
    per_op = aggregate(records)
    s = summary(records)
    lines = [render_table(per_op), ""]
    lines.append(
        "events={events}  fallbacks={fallbacks_total}  "
        "spill_bytes={sb}  cache_hit/miss={h}/{m}  stale_reads={stale}".format(
            events=s["events"], fallbacks_total=s["fallbacks_total"],
            sb=_fmt_bytes(s["spill_bytes_total"]),
            h=s["compile_cache"]["hit"], m=s["compile_cache"]["miss"],
            stale=s["stale_reads"],
        )
    )
    # serving-runtime sections render only when such events exist, so
    # dispatch-only runs keep their historical output byte-for-byte
    if s["server"]:
        lines.append("server events:")
        for ev, n in sorted(s["server"].items()):
            lines.append(f"  {n:4d}x  {ev}")
    if s["degrade"]:
        lines.append("degrade events:")
        for ev, n in sorted(s["degrade"].items()):
            lines.append(f"  {n:4d}x  {ev}")
        if s["degrade_tiers"]:
            tiers = "  ".join(
                f"{t}={n}" for t, n in sorted(s["degrade_tiers"].items()))
            lines.append(f"  step tiers: {tiers}")
    if s["integrity"]:
        lines.append("integrity events:")
        for ev, n in sorted(s["integrity"].items()):
            lines.append(f"  {n:4d}x  {ev}")
        if s["integrity_seams"]:
            seams = "  ".join(
                f"{sm}={n}" for sm, n in sorted(s["integrity_seams"].items()))
            lines.append(f"  mismatch seams: {seams}")
    if s.get("compress"):
        c = s["compress"]
        lines.append(
            "compress: in={bi}  out={bo}  ratio={r}  schemes={sch}".format(
                bi=_fmt_bytes(c["bytes_in"]), bo=_fmt_bytes(c["bytes_out"]),
                r=c["ratio"] if c["ratio"] is not None else "n/a",
                sch=" ".join(f"{k}={n}"
                             for k, n in sorted(c["schemes"].items()))
                or "none",
            )
        )
    if s.get("cluster"):
        lines.append("cluster events:")
        for ev, n in sorted(s["cluster"].items()):
            lines.append(f"  {n:4d}x  {ev}")
    if s.get("hosts"):
        # per-host aggregation of the host= stamp (cluster workers set
        # telemetry.host; the supervisor stamps its own cluster.* events)
        per_host = s.get("per_host") or {}
        counts = "  ".join(f"{h}={per_host.get(h, 0)}" for h in s["hosts"])
        lines.append(f"hosts: {counts}")
    if s.get("spans"):
        status = "  ".join(
            f"{st}={n}" for st, n in sorted(s["span_status"].items()))
        lines.append(f"spans: {s['spans']}  ({status})")
    reasons: Dict[str, int] = {}
    for rec in records:
        if rec.get("kind") in ("fallback", "spill"):
            key = f"{rec.get('op', '?')}: {rec.get('reason', '')}"
            reasons[key] = reasons.get(key, 0) + 1
    if reasons:
        lines.append("fallback/spill reasons:")
        for key, n in sorted(reasons.items(), key=lambda kv: (-kv[1], kv[0])):
            lines.append(f"  {n:4d}x  {key}")
    return "\n".join(lines)
