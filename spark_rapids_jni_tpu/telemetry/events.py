"""Structured JSONL event log: dispatches, fallbacks, cache hits, staleness.

Every record answers the question the round-5 bench could not: *where did
this op actually run, and why?* Four event kinds:

- ``dispatch``      — an op ran on its intended engine (``engine`` says which);
                      carries ``wall_ms`` when timed via ``trace_range(record=)``.
- ``fallback``      — a device path handed the row set to the host engine.
                      ``reason`` is mandatory and must be non-empty: a fallback
                      without a reason is unaccountable and raises ValueError
                      at the call site (enforced even when telemetry is off, so
                      the bug surfaces in tests, not production).
- ``compile_cache`` — hit/miss on a pattern-compile cache (regex DFA / linear).
- ``spill``         — device→host spill under memory pressure; carries
                      ``bytes_moved``.
- ``bench_stale``   — bench served a last-known-good ledger value instead of a
                      fresh measurement.
- ``span``          — one closed node of a query's causal span tree
                      (telemetry/spans.py): id/parent/root, monotonic t0/t1,
                      status (ok/degraded/cancelled/failed).

Each record is stamped with ``ts`` (epoch seconds), ``platform`` (jax backend
if jax is already imported — telemetry itself never imports jax, keeping the
zero-dep/no-backend-init contract of tests/test_import_hygiene.py), and the
caller-supplied ``op`` / ``rows`` / ``dtype_widths``.

Sink: when ``telemetry.path`` is set, records append to that JSONL file (one
json object per line, crash-tolerant — a torn final line is skipped by the
reader). Always, the last 4096 records are kept in an in-process ring for the
bench summary and tests. Emission never raises on I/O failure; dropped writes
are counted in ``telemetry.dropped_writes``.
"""

from __future__ import annotations

import collections
import json
import os
import sys
import threading
import time
from typing import Any, Deque, Dict, Iterable, List, Optional, Sequence

from spark_rapids_jni_tpu.telemetry.registry import REGISTRY
from spark_rapids_jni_tpu.utils.config import get_option

__all__ = [
    "enabled",
    "record_dispatch",
    "record_fallback",
    "record_compile_cache",
    "record_spill",
    "record_resilience",
    "record_bench_stale",
    "record_server",
    "record_degrade",
    "record_integrity",
    "record_cache",
    "record_fleet",
    "record_kernel_tier",
    "session_scope",
    "current_session",
    "events",
    "drain",
    "summary",
]

_RING_MAX = 4096
_ring: Deque[Dict[str, Any]] = collections.deque(maxlen=_RING_MAX)
_ring_lock = threading.Lock()

# Ambient session attribution (runtime/server.py): while a served query
# executes inside session_scope(sid), every record emitted on that thread —
# including fallbacks/spills/resilience events from layers that know nothing
# about sessions — is stamped with ``session``.
_session_ctx = threading.local()


class session_scope:
    """Attribute every telemetry record emitted on this thread to a session.

    Re-entrant in the shadowing sense: nesting restores the outer session
    on exit. Explicit ``session=`` kwargs on record_* calls win over the
    ambient scope (``_emit`` uses ``setdefault``).
    """

    def __init__(self, session_id: str):
        if not session_id or not str(session_id).strip():
            raise ValueError("session_scope: session_id must be non-empty")
        self._sid = str(session_id)
        self._outer: Optional[str] = None

    def __enter__(self) -> "session_scope":
        self._outer = getattr(_session_ctx, "sid", None)
        _session_ctx.sid = self._sid
        return self

    def __exit__(self, *exc) -> bool:
        _session_ctx.sid = self._outer
        return False


def current_session() -> Optional[str]:
    """The session id attributed to this thread, or None outside a scope."""
    return getattr(_session_ctx, "sid", None)


def enabled() -> bool:
    """True when the ``telemetry.enabled`` option is on."""
    return bool(get_option("telemetry.enabled"))


def _platform() -> str:
    # Never import jax from here: telemetry is zero-dep and must not trigger
    # backend init (test_import_hygiene.py). If the workload already imported
    # jax, report its backend; otherwise "none".
    jax = sys.modules.get("jax")
    if jax is None:
        return "none"
    try:
        return str(jax.default_backend())
    except Exception:
        return "unknown"


def _replica() -> str:
    """The replica identity this process stamps onto every record/span
    (fleet workers get it via SPARK_RAPIDS_TPU_TELEMETRY_REPLICA in
    their environment); "" = unstamped single-process operation."""
    return str(get_option("telemetry.replica") or "")


def _host() -> str:
    """The mesh host identity stamped next to the replica stamp
    (cluster workers get it via SPARK_RAPIDS_TPU_TELEMETRY_HOST in
    their environment); "" = unstamped single-host operation."""
    return str(get_option("telemetry.host") or "")


def _emit(rec: Dict[str, Any]) -> Dict[str, Any]:
    rec.setdefault("ts", time.time())
    rec.setdefault("platform", _platform())
    sid = current_session()
    if sid is not None:
        rec.setdefault("session", sid)
    rid = _replica()
    if rid:
        rec.setdefault("replica", rid)
    hid = _host()
    if hid:
        rec.setdefault("host", hid)
    with _ring_lock:
        _ring.append(rec)
    REGISTRY.counter("events_total").inc()
    path = get_option("telemetry.path")
    if path:
        # N fleet replicas share one JSONL path: each record must land as
        # ONE O_APPEND os.write so a reader (report/trace) can never see
        # two processes' lines torn into each other. Buffered file-object
        # writes flush in arbitrary chunks; a single write(2) of a line
        # that fits a pipe/page is atomic on POSIX.
        line = (json.dumps(rec, sort_keys=True) + "\n").encode("utf-8")
        try:
            fd = os.open(path, os.O_WRONLY | os.O_APPEND | os.O_CREAT, 0o644)
            try:
                os.write(fd, line)
            finally:
                os.close(fd)
        except OSError:
            # telemetry must never take the workload down with it
            REGISTRY.counter("dropped_writes").inc()
    return rec


def _base(
    kind: str,
    op: str,
    rows: Optional[int],
    dtype_widths: Optional[Sequence[int]],
    extra: Dict[str, Any],
) -> Dict[str, Any]:
    rec: Dict[str, Any] = {"kind": kind, "op": op}
    if rows is not None:
        rec["rows"] = int(rows)
    if dtype_widths is not None:
        rec["dtype_widths"] = [int(w) for w in dtype_widths]
    rec.update(extra)
    return rec


def record_dispatch(
    op: str,
    *,
    engine: str = "device",
    rows: Optional[int] = None,
    dtype_widths: Optional[Sequence[int]] = None,
    wall_ms: Optional[float] = None,
    **extra: Any,
) -> bool:
    """An op executed on ``engine``; optionally timed. Returns True if recorded."""
    if not enabled():
        return False
    rec = _base("dispatch", op, rows, dtype_widths, extra)
    rec["engine"] = engine
    if wall_ms is not None:
        rec["wall_ms"] = float(wall_ms)
        REGISTRY.histogram(f"wall_ms.{op}").observe(float(wall_ms))
    REGISTRY.counter(f"dispatch.{op}").inc()
    _emit(rec)
    return True


def record_fallback(
    op: str,
    reason: str,
    *,
    rows: Optional[int] = None,
    dtype_widths: Optional[Sequence[int]] = None,
    **extra: Any,
) -> bool:
    """A device path handed execution to the host engine, because ``reason``."""
    if not reason or not str(reason).strip():
        # validated even when disabled: an unaccountable fallback is a bug
        raise ValueError(f"record_fallback({op!r}): reason must be non-empty")
    if not enabled():
        return False
    rec = _base("fallback", op, rows, dtype_widths, extra)
    rec["reason"] = str(reason)
    rec["engine"] = "host"
    REGISTRY.counter(f"fallback.{op}").inc()
    REGISTRY.counter("fallbacks_total").inc()
    _emit(rec)
    return True


def record_kernel_tier(
    op: str,
    *,
    tier: str,
    mode: str,
    reason: str,
    **extra: Any,
) -> bool:
    """The Pallas kernel tier (ops/pallas/) decided how op ``op`` traces:
    ``tier`` ("pallas" | "xla") via ``mode`` ("native" | "interpret" |
    "oracle"), because ``reason``. Every decision — including the xla
    default and every fallback — is recorded, so a tier flip can never be
    a silent behavior change. Decisions happen at trace time: a cached
    executable replays its recorded decision without re-deciding."""
    if not reason or not str(reason).strip():
        # validated even when disabled: an unaccountable tier pick is a bug
        raise ValueError(f"record_kernel_tier({op!r}): reason must be non-empty")
    # Counters bump unconditionally (like dispatch.compile): the tier ledger
    # must exist even when event emission is off, or a fallback is silent.
    REGISTRY.counter(f"kernels.{op}.{tier}").inc()
    REGISTRY.counter(f"kernels.tier.{tier}").inc()
    if mode == "interpret":
        REGISTRY.counter("kernels.interpret").inc()
    if tier == "xla" and reason != "config":
        # a non-config xla decision is a fallback: count it by reason
        REGISTRY.counter(f"kernels.fallback.{reason}").inc()
    if not enabled():
        return False
    rec = _base("kernel_tier", op, None, None, extra)
    rec["tier"] = str(tier)
    rec["mode"] = str(mode)
    rec["reason"] = str(reason)
    _emit(rec)
    return True


def record_compile_cache(op: str, *, hit: bool, **extra: Any) -> bool:
    """A pattern-compile cache was consulted (regex DFA / linear-capture)."""
    if not enabled():
        return False
    rec = _base("compile_cache", op, None, None, extra)
    rec["hit"] = bool(hit)
    REGISTRY.counter("compile_cache.hit" if hit else "compile_cache.miss").inc()
    _emit(rec)
    return True


def record_spill(
    op: str,
    reason: str,
    *,
    bytes_moved: int = 0,
    rows: Optional[int] = None,
    **extra: Any,
) -> bool:
    """Device→host spill under memory pressure; ``reason`` mandatory."""
    if not reason or not str(reason).strip():
        raise ValueError(f"record_spill({op!r}): reason must be non-empty")
    if not enabled():
        return False
    rec = _base("spill", op, rows, None, extra)
    rec["reason"] = str(reason)
    rec["bytes_moved"] = int(bytes_moved)
    REGISTRY.counter(f"spill.{op}").inc()
    REGISTRY.counter("spill_bytes_total").inc(max(0, int(bytes_moved)))
    _emit(rec)
    return True


def record_resilience(
    op: str,
    event: str,
    *,
    seam: str,
    attempt: int,
    rung: str,
    rows: Optional[int] = None,
    **extra: Any,
) -> bool:
    """A resilience-policy decision: retry, recovery, escalation, or fatal.

    ``event`` is one of ``retry`` / ``recovered`` / ``escalate`` / ``fatal``;
    ``seam`` names the instrumented boundary (runtime/faults.py registry);
    ``rung`` is the degradation-ladder rung taken (``same_capacity``,
    ``grow_capacity``, ``replay_chunk``, ``staged_fallback``, ...). Like
    fallback reasons, seam and rung are mandatory even when telemetry is off —
    an unaccountable recovery is a bug.
    """
    if not seam or not str(seam).strip():
        raise ValueError(f"record_resilience({op!r}): seam must be non-empty")
    if not rung or not str(rung).strip():
        raise ValueError(f"record_resilience({op!r}): rung must be non-empty")
    if "kind" in extra or "op" in extra:
        raise ValueError(
            f"record_resilience({op!r}): 'kind'/'op' are reserved record "
            "fields; pass the classified error as error_kind")
    if not enabled():
        return False
    rec = _base("resilience", op, rows, None, extra)
    rec["event"] = str(event)
    rec["seam"] = str(seam)
    rec["attempt"] = int(attempt)
    rec["rung"] = str(rung)
    REGISTRY.counter(f"resilience.{event}").inc()
    REGISTRY.counter(f"resilience.rung.{rung}").inc()
    _emit(rec)
    return True


def record_server(
    op: str,
    event: str,
    *,
    session: str,
    rows: Optional[int] = None,
    **extra: Any,
) -> bool:
    """A serving-runtime decision for one query of one session.

    ``event`` is one of ``submitted`` / ``queued`` / ``rejected`` /
    ``admitted`` / ``served`` / ``failed``; ``session`` is mandatory and
    must be non-empty even when telemetry is off — an unattributable
    serving event is a bug (tpulint rule 12 enforces the static half of
    this contract on the server path).
    """
    if not session or not str(session).strip():
        raise ValueError(f"record_server({op!r}): session must be non-empty")
    if not enabled():
        return False
    rec = _base("server", op, rows, None, extra)
    rec["event"] = str(event)
    rec["session"] = str(session)
    # no counter side effects here: the serving runtime owns the
    # ``server.*`` counters and counts unconditionally (admission
    # accounting must hold even with telemetry off, like the limiter's)
    _emit(rec)
    return True


def record_degrade(
    op: str,
    event: str,
    *,
    tier: str,
    trigger: str,
    rung: int,
    rows: Optional[int] = None,
    **extra: Any,
) -> bool:
    """A graceful-degradation decision for one query (runtime/degrade.py).

    ``event`` is one of ``step`` / ``completed`` / ``parked`` / ``resumed``
    / ``exhausted`` / ``pressure`` / ``cancelled`` / ``state_discarded``;
    ``tier`` names the execution tier the ladder is moving to (``fused``,
    ``staged``, ``outofcore``, ``parked``); ``trigger`` is what forced the
    move (the classified error kind, ``deadline``, ``watermark``); ``rung``
    is the 0-based ladder position. Tier and trigger are mandatory even when
    telemetry is off — an unaccountable degradation is a bug (same contract
    as fallback reasons).
    """
    if not tier or not str(tier).strip():
        raise ValueError(f"record_degrade({op!r}): tier must be non-empty")
    if not trigger or not str(trigger).strip():
        raise ValueError(f"record_degrade({op!r}): trigger must be non-empty")
    if not enabled():
        return False
    rec = _base("degrade", op, rows, None, extra)
    rec["event"] = str(event)
    rec["tier"] = str(tier)
    rec["trigger"] = str(trigger)
    rec["rung"] = int(rung)
    REGISTRY.counter(f"degrade.{event}").inc()
    REGISTRY.counter(f"degrade.tier.{tier}").inc()
    _emit(rec)
    return True


def record_integrity(
    op: str,
    event: str,
    *,
    seam: str,
    nbytes: Optional[int] = None,
    **extra: Any,
) -> bool:
    """An integrity-layer event (runtime/integrity.py and its call sites).

    ``event`` is one of ``mismatch`` (a checksum trailer failed
    verification) / ``refetch`` (a corrupt wire frame was NAK'd for
    resend) / ``recovered`` (a refetch or checkpoint replay produced good
    bytes) / ``replay`` (a corrupt checkpoint partial was discarded and
    its chunk recomputed) / ``malformed`` (untrusted input rejected at
    ingestion). ``seam`` names the verification boundary
    (``integrity.spill`` / ``integrity.wire`` / ``integrity.checkpoint``
    / ``integrity.ingest``) and is mandatory even when telemetry is off —
    an unattributable corruption event is a bug, same contract as
    fallback reasons and resilience seams.
    """
    if not seam or not str(seam).strip():
        raise ValueError(f"record_integrity({op!r}): seam must be non-empty")
    if "kind" in extra or "op" in extra:
        raise ValueError(
            f"record_integrity({op!r}): 'kind'/'op' are reserved record "
            "fields; pass caller context under other names")
    if not enabled():
        return False
    rec = _base("integrity", op, None, None, extra)
    rec["event"] = str(event)
    rec["seam"] = str(seam)
    if nbytes is not None:
        rec["nbytes"] = int(nbytes)
    # no counter side effects here: integrity.verify owns the
    # ``integrity.*`` counters and counts unconditionally (verification
    # accounting must hold even with telemetry off, like the limiter's)
    _emit(rec)
    return True


def record_rtfilter(
    op: str,
    event: str,
    *,
    reason: str,
    **extra: Any,
) -> bool:
    """A runtime-filter planner decision or observation
    (runtime/rtfilter.py).

    ``event`` is one of ``apply`` / ``skip`` / ``observed`` /
    ``state_discarded`` / ``prune``; ``reason`` says WHY (``selective``,
    ``no_history_optimistic``, ``learned_nonselective``,
    ``build_too_large``, ``disabled``, ``corrupt``, ...) and is
    mandatory even when telemetry is off — an unexplained filter
    decision is a bug (tpulint rule 24 enforces the static half of this
    contract on the rtfilter path)."""
    if not reason or not str(reason).strip():
        raise ValueError(f"record_rtfilter({op!r}): reason must be non-empty")
    if not enabled():
        return False
    rec = _base("rtfilter", op, None, None, extra)
    rec["event"] = str(event)
    rec["reason"] = str(reason)
    # no counter side effects: rtfilter owns its ``rtfilter.*`` counters
    # and counts unconditionally (decision accounting must hold whether
    # or not anyone is watching, like the server's admission counters)
    _emit(rec)
    return True


def record_cache(
    op: str,
    event: str,
    *,
    key: str,
    nbytes: Optional[int] = None,
    **extra: Any,
) -> bool:
    """A result/subplan-cache decision (runtime/resultcache.py).

    ``event`` is one of ``hit`` / ``miss`` / ``put`` / ``evict`` /
    ``shed`` / ``corrupt_discard`` / ``subplan_hit`` /
    ``subplan_materialize``. ``key`` is the entry's short composite key
    (``<signature12>@<fingerprint12>``) and is mandatory even when
    telemetry is off — a cache record without the fingerprinted key is
    unattributable to an entry, the same contract tpulint rule 16
    enforces statically on cache call sites.
    """
    if not key or not str(key).strip():
        raise ValueError(f"record_cache({op!r}): key must be non-empty")
    if not enabled():
        return False
    rec = _base("cache", op, None, None, extra)
    rec["event"] = str(event)
    rec["key"] = str(key)
    if nbytes is not None:
        rec["nbytes"] = int(nbytes)
    # no counter side effects here: the result cache owns the ``cache.*``
    # counters and counts unconditionally (hit/miss accounting must hold
    # even with telemetry off, like the server's admission counters)
    _emit(rec)
    return True


def record_fleet(
    op: str,
    event: str,
    *,
    replica: str,
    **extra: Any,
) -> bool:
    """A serving-fleet supervision event (runtime/fleet.py).

    ``event`` is one of ``boot`` / ``live`` / ``dispatch`` / ``served`` /
    ``replica_death`` / ``failover`` / ``duplicate_drop`` / ``memo_hit``
    / ``restart`` / ``quarantine`` / ``drain`` / ``identity_mismatch``.
    ``replica`` names the replica the event is about and is mandatory
    even when telemetry is off — an unattributable fleet event is a bug,
    the same contract record_server enforces for sessions (tpulint rule
    18 enforces the classification half on worker-exit reaping sites).
    """
    if not replica or not str(replica).strip():
        raise ValueError(f"record_fleet({op!r}): replica must be non-empty")
    if "kind" in extra or "op" in extra:
        raise ValueError(
            f"record_fleet({op!r}): 'kind'/'op' are reserved record "
            "fields; pass caller context under other names")
    if not enabled():
        return False
    rec = _base("fleet", op, None, None, extra)
    rec["event"] = str(event)
    rec["replica"] = str(replica)
    # no counter side effects here: the fleet supervisor owns the
    # ``fleet.*`` counters and counts unconditionally (supervision
    # accounting must hold even with telemetry off, like admission's)
    _emit(rec)
    return True


def record_exchange(
    op: str,
    event: str,
    *,
    rows: Optional[int] = None,
    **extra: Any,
) -> bool:
    """A distributed-exchange lifecycle event (runtime/exchange.py).

    ``event`` is one of ``pack`` / ``flight`` / ``overflow_escalate`` /
    ``chunked_flights`` / ``spill_demote`` / ``merge`` / ``recovered``.
    ``rows`` is the row count the event is about (routed rows for
    ``pack``, flight rows for ``flight``, ...). Byte/flight context
    rides in ``extra`` (``wire_bytes`` / ``raw_bytes`` / ``flights`` /
    ``capacity`` / ``partition``). Like record_fleet, no counter side
    effects: runtime/exchange.py owns the ``exchange.*`` counters and
    counts unconditionally (transport accounting must hold even with
    telemetry off).
    """
    if not event or not str(event).strip():
        raise ValueError(f"record_exchange({op!r}): event must be non-empty")
    if "kind" in extra or "op" in extra:
        raise ValueError(
            f"record_exchange({op!r}): 'kind'/'op' are reserved record "
            "fields; pass caller context under other names")
    if not enabled():
        return False
    rec = _base("exchange", op, rows, None, extra)
    rec["event"] = str(event)
    _emit(rec)
    return True


def record_bench_stale(
    metric: str,
    *,
    stale_s: float,
    reason: str,
    **extra: Any,
) -> bool:
    """Bench served a last-known-good ledger value instead of measuring."""
    if not reason or not str(reason).strip():
        raise ValueError(f"record_bench_stale({metric!r}): reason must be non-empty")
    if not enabled():
        return False
    rec = _base("bench_stale", metric, None, None, extra)
    rec["reason"] = str(reason)
    rec["stale_s"] = float(stale_s)
    REGISTRY.counter("bench_stale_total").inc()
    _emit(rec)
    return True


def events(n: Optional[int] = None) -> List[Dict[str, Any]]:
    """The last ``n`` (default: all buffered) records, oldest first."""
    with _ring_lock:
        buf = list(_ring)
    return buf if n is None else buf[-n:]


def drain() -> List[Dict[str, Any]]:
    """Return and clear the in-process ring (test isolation)."""
    with _ring_lock:
        buf = list(_ring)
        _ring.clear()
    return buf


def summary(records: Optional[Iterable[Dict[str, Any]]] = None) -> Dict[str, Any]:
    """Aggregate counts for the bench telemetry block.

    With no argument, summarizes the in-process ring; pass parsed JSONL
    records to summarize a file written by another process (bench children).
    """
    recs = list(records) if records is not None else events()
    # the columnar codec (runtime/compress.py) is counter-based — its
    # hot path never emits per-array event records — so its section is
    # derived from the in-process REGISTRY and only meaningful for the
    # no-argument (same-process) view; summarizing another process's
    # JSONL keeps the key with an empty dict
    compress: Dict[str, Any] = {}
    if records is None:
        comp = REGISTRY.counters("compress.")
        if comp:
            bytes_in = comp.get("compress.bytes_in", 0)
            bytes_out = comp.get("compress.bytes_out", 0)
            compress = {
                "bytes_in": bytes_in,
                "bytes_out": bytes_out,
                "ratio": round(bytes_in / bytes_out, 3)
                if bytes_out else None,
                "encode_us": comp.get("compress.encode_us", 0),
                "decode_us": comp.get("compress.decode_us", 0),
                "bytes_decoded": comp.get("compress.bytes_decoded", 0),
                "mismatches": comp.get("compress.mismatch", 0),
                "schemes": {
                    k.split(".", 2)[2]: v for k, v in sorted(comp.items())
                    if k.startswith("compress.scheme.")
                },
                "seams": {
                    seam: {
                        "bytes_in": comp.get(f"compress.{seam}.bytes_in", 0),
                        "bytes_out": comp.get(f"compress.{seam}.bytes_out", 0),
                    }
                    for seam in ("spill", "wire", "checkpoint", "cache")
                    if f"compress.{seam}.bytes_in" in comp
                },
            }
    fallbacks: Dict[str, int] = {}
    spills: Dict[str, int] = {}
    cache = {"hit": 0, "miss": 0}
    resilience: Dict[str, int] = {}
    server: Dict[str, int] = {}
    degrade: Dict[str, int] = {}
    degrade_tiers: Dict[str, int] = {}
    integrity: Dict[str, int] = {}
    integrity_seams: Dict[str, int] = {}
    result_cache: Dict[str, int] = {}
    fleet: Dict[str, int] = {}
    replicas: set = set()
    cluster: Dict[str, int] = {}
    hosts: set = set()
    per_host: Dict[str, int] = {}
    stale_reads = 0
    dispatches = 0
    spill_bytes = 0
    spans = 0
    span_status: Dict[str, int] = {}
    for r in recs:
        kind = r.get("kind")
        if r.get("replica"):
            replicas.add(str(r["replica"]))
        if r.get("host"):
            h = str(r["host"])
            hosts.add(h)
            per_host[h] = per_host.get(h, 0) + 1
        if kind == "span":
            spans += 1
            st = str(r.get("status", "?"))
            span_status[st] = span_status.get(st, 0) + 1
            continue
        if kind == "resilience":
            ev = str(r.get("event", "?"))
            resilience[ev] = resilience.get(ev, 0) + 1
        elif kind == "server":
            ev = str(r.get("event", "?"))
            server[ev] = server.get(ev, 0) + 1
        elif kind == "degrade":
            ev = str(r.get("event", "?"))
            degrade[ev] = degrade.get(ev, 0) + 1
            if ev == "step":
                tier = str(r.get("tier", "?"))
                degrade_tiers[tier] = degrade_tiers.get(tier, 0) + 1
        elif kind == "integrity":
            ev = str(r.get("event", "?"))
            integrity[ev] = integrity.get(ev, 0) + 1
            if ev == "mismatch":
                seam = str(r.get("seam", "?"))
                integrity_seams[seam] = integrity_seams.get(seam, 0) + 1
        elif kind == "cache":
            ev = str(r.get("event", "?"))
            result_cache[ev] = result_cache.get(ev, 0) + 1
        elif kind == "fleet":
            ev = str(r.get("event", "?"))
            fleet[ev] = fleet.get(ev, 0) + 1
            # the mesh supervisor emits its cross-host events through
            # record_fleet under cluster.* ops: aggregate them as their
            # own section so the cluster view needs no second pass
            if str(r.get("op", "")).startswith("cluster."):
                cluster[ev] = cluster.get(ev, 0) + 1
        elif kind == "fallback":
            op = str(r.get("op", "?"))
            fallbacks[op] = fallbacks.get(op, 0) + 1
        elif kind == "spill":
            op = str(r.get("op", "?"))
            spills[op] = spills.get(op, 0) + 1
            spill_bytes += int(r.get("bytes_moved", 0))
        elif kind == "compile_cache":
            cache["hit" if r.get("hit") else "miss"] += 1
        elif kind == "bench_stale":
            stale_reads += 1
        elif kind == "dispatch":
            dispatches += 1
    return {
        "events": len(recs),
        "dispatches": dispatches,
        "fallbacks": dict(sorted(fallbacks.items())),
        "fallbacks_total": sum(fallbacks.values()),
        "spills": dict(sorted(spills.items())),
        "spill_bytes_total": spill_bytes,
        "compile_cache": cache,
        "resilience": dict(sorted(resilience.items())),
        "server": dict(sorted(server.items())),
        "degrade": dict(sorted(degrade.items())),
        "degrade_tiers": dict(sorted(degrade_tiers.items())),
        "integrity": dict(sorted(integrity.items())),
        "integrity_seams": dict(sorted(integrity_seams.items())),
        "result_cache": dict(sorted(result_cache.items())),
        "fleet": dict(sorted(fleet.items())),
        "replicas": sorted(replicas),
        "cluster": dict(sorted(cluster.items())),
        "hosts": sorted(hosts),
        "per_host": dict(sorted(per_host.items())),
        "compress": compress,
        "spans": spans,
        "span_status": dict(sorted(span_status.items())),
        "stale_reads": stale_reads,
    }
