"""Execution telemetry & fallback accounting.

The reference answers "where did GPU time go" with NVTX ranges
(``ai.rapids.cudf.nvtx.enabled``) plus RMM counters; this package is the TPU
port's equivalent *and* closes the gap NVTX never covered: counting where
execution actually landed. Every device→host fallback (regex NUL byteset,
unsupported regex atom, cast-strings host assembly, out-of-core spill,
shuffle overflow reroute) records an event with a mandatory ``reason``; the
bench stamps a telemetry summary into every BENCH_*.json; and
``python -m spark_rapids_jni_tpu.telemetry report run.jsonl`` renders the
per-op device/host split with p50/p95 wall times and bytes moved.

On top of the flat stream sit hierarchical per-query span trees
(``spans`` — one causal tree per served query), a bounded flight
recorder with structured dump artifacts, Chrome-trace/Perfetto export
(``python -m spark_rapids_jni_tpu.telemetry trace``), live serving
introspection (``QueryServer.inspect()`` rendered by ``... telemetry
top``) and Prometheus-style text exposition (``REGISTRY.exposition()``).

Toggles (utils/config.py): ``telemetry.enabled``
(``SPARK_RAPIDS_TPU_TELEMETRY_ENABLED=1``) turns recording on;
``telemetry.path`` (``SPARK_RAPIDS_TPU_TELEMETRY_PATH=run.jsonl``) adds a
JSONL file sink on top of the in-process ring. Zero third-party deps, no jax
import, near-zero cost when disabled (one config lookup per instrumented
call).
"""

from spark_rapids_jni_tpu.telemetry.events import (
    current_session,
    drain,
    enabled,
    events,
    record_bench_stale,
    record_compile_cache,
    record_degrade,
    record_dispatch,
    record_exchange,
    record_fallback,
    record_fleet,
    record_integrity,
    record_kernel_tier,
    record_resilience,
    record_rtfilter,
    record_server,
    record_spill,
    session_scope,
    summary,
)
from spark_rapids_jni_tpu.telemetry.registry import REGISTRY, Registry
from spark_rapids_jni_tpu.telemetry import spans
from spark_rapids_jni_tpu.telemetry.spans import (
    chrome_trace,
    current_span,
    dump_flight_record,
    flight_records,
    span,
)

__all__ = [
    "REGISTRY",
    "Registry",
    "chrome_trace",
    "current_session",
    "current_span",
    "drain",
    "dump_flight_record",
    "enabled",
    "events",
    "flight_records",
    "record_bench_stale",
    "record_compile_cache",
    "record_degrade",
    "record_dispatch",
    "record_exchange",
    "record_fallback",
    "record_fleet",
    "record_integrity",
    "record_kernel_tier",
    "record_resilience",
    "record_rtfilter",
    "record_server",
    "record_spill",
    "session_scope",
    "span",
    "spans",
    "summary",
]
