from spark_rapids_jni_tpu.orc.reader import (
    OrcChunkedReader,
    read_table,
    stripe_info,
)

__all__ = ["OrcChunkedReader", "read_table", "stripe_info"]
