"""ORC reader — native stripe decode staged into device tables.

The ORC half of the vendored "Parquet/ORC readers incl. chunked reads"
capability (SURVEY.md section 2.2; the reference links cuDF's ORC reader
into libcudf, build-libcudf.xml:34-60). Decode is C++
(src/native/src/orc_reader.cpp); chunked reads iterate stripes under a
byte budget — the stripe is ORC's row-group analogue.

Type mapping (ORC kind -> DType):
  BOOLEAN -> BOOL8        BYTE -> INT8       SHORT -> INT16
  INT -> INT32            LONG -> INT64      FLOAT/DOUBLE -> FLOAT32/64
  STRING/VARCHAR/CHAR/BINARY -> STRING       DATE -> TIMESTAMP_DAYS
  TIMESTAMP -> TIMESTAMP_MICROS (unix epoch; ORC 2015-epoch + nano
  trailing-zero encoding decoded natively; non-UTC writer timezones
  converted wall-clock -> UTC here via the tz database — pyarrow's
  assume_timezone, ambiguous/nonexistent local times resolve to the
  EARLIEST candidate, a documented choice where implementations differ)
  DECIMAL(p<=18, s) -> decimal64(-s)         DECIMAL(p>18, s) ->
  decimal128(-s) (int64 limb pairs)
"""

from __future__ import annotations

import ctypes
import os
from typing import Iterator, Optional, Sequence

import numpy as np

from spark_rapids_jni_tpu import types as t
from spark_rapids_jni_tpu.columnar import Table
from spark_rapids_jni_tpu.parquet.footer import MalformedFileError, NativeError
from spark_rapids_jni_tpu.runtime import faults, integrity
from spark_rapids_jni_tpu.runtime.native import load_native
from spark_rapids_jni_tpu.utils.fspath import as_fs_path
from spark_rapids_jni_tpu.utils.tracing import func_range

_K_BOOLEAN, _K_BYTE, _K_SHORT, _K_INT, _K_LONG = 0, 1, 2, 3, 4
_K_FLOAT, _K_DOUBLE, _K_STRING, _K_BINARY, _K_TIMESTAMP = 5, 6, 7, 8, 9
_K_DECIMAL, _K_DATE, _K_VARCHAR, _K_CHAR = 14, 15, 16, 17

_STRING_KINDS = (_K_STRING, _K_VARCHAR, _K_CHAR, _K_BINARY)


def _map_dtype(kind: int, scale: int, precision: int = 0):
    if kind == _K_DECIMAL and precision > 18:
        return t.decimal128(-scale)
    return {
        _K_BOOLEAN: t.BOOL8,
        _K_BYTE: t.INT8,
        _K_SHORT: t.INT16,
        _K_INT: t.INT32,
        _K_LONG: t.INT64,
        _K_FLOAT: t.FLOAT32,
        _K_DOUBLE: t.FLOAT64,
        _K_STRING: t.STRING,
        _K_BINARY: t.STRING,   # raw bytes ride the string layout
        _K_VARCHAR: t.STRING,
        _K_CHAR: t.STRING,
        _K_TIMESTAMP: t.TIMESTAMP_MICROSECONDS,
        _K_DATE: t.TIMESTAMP_DAYS,
        _K_DECIMAL: t.decimal64(-scale),
    }[kind]


def _check(lib, ok: bool, what: str) -> None:
    # decode failures on untrusted bytes classify as malformed input
    # (MalformedFileError is-a NativeError, so legacy catches still work)
    if not ok:
        raise integrity.reject_malformed(
            f"orc.{what}", f"{what}: {lib.last_error()}",
            exc_type=MalformedFileError)


_ORC_MAGIC = b"ORC"


def _validate_orc_envelope(data: "bytes | str | os.PathLike") -> None:
    """Untrusted-input preflight: check the ORC file envelope — leading
    magic, trailing postscript magic, and the one-byte postscript length
    against the file size — BEFORE any decoder touches the bytes. Pure
    Python (no native lib needed), so a truncated or clobbered file is
    rejected classified even where the native engine is absent. Deep
    structural checks (protobuf footer, stripe bounds) run inside the
    hardened native parse behind the same classification."""
    if not integrity.enabled():
        return
    path = as_fs_path(data)
    if path is None:
        n = len(data)
        head, tail = bytes(data[:3]), bytes(data[-4:])
    else:
        try:
            n = os.path.getsize(path)
            with open(path, "rb") as fh:
                head = fh.read(3)
                fh.seek(max(0, n - 4))
                tail = fh.read(4)
        except OSError:
            return  # unreadable path: let the native open report it
    if n < 8:
        raise integrity.reject_malformed(
            "orc.envelope", "file too short to be ORC",
            exc_type=MalformedFileError, size=n)
    if head != _ORC_MAGIC:
        raise integrity.reject_malformed(
            "orc.envelope", "bad leading magic (not an ORC file)",
            exc_type=MalformedFileError, size=n)
    if tail[:3] != _ORC_MAGIC:
        raise integrity.reject_malformed(
            "orc.envelope",
            "bad trailing postscript magic (truncated or clobbered file)",
            exc_type=MalformedFileError, size=n)
    ps_len = tail[3]
    # the postscript (+ its length byte) must fit between head magic and EOF
    if ps_len == 0 or ps_len + 1 > n - len(_ORC_MAGIC):
        raise integrity.reject_malformed(
            "orc.envelope", "postscript length field points outside the file",
            exc_type=MalformedFileError, ps_len=ps_len, size=n)


def _check_orc_rows(prev: "int | None", rows: int, col: int) -> None:
    """Every column of one read must agree on the row count — a file
    whose columns disagree would otherwise build a ragged Table that
    downstream kernels silently broadcast or truncate."""
    if not integrity.enabled():
        return
    if rows < 0:
        raise integrity.reject_malformed(
            "orc.column", "negative row count from decoder",
            exc_type=MalformedFileError, column=col, rows=rows)
    if prev is not None and rows != prev:
        raise integrity.reject_malformed(
            "orc.table", "columns disagree on row count",
            exc_type=MalformedFileError, column=col,
            rows=rows, expected=prev)


def _check_orc_string(offsets: np.ndarray, num_rows: int,
                      chars_bytes: int, col: int) -> None:
    """Post-decode bounds check on one string column: offsets monotone,
    zero-based, and ending exactly at the character payload size —
    caught here, before a clobbered offset indexes out of bounds inside
    a device gather where there is no fault to catch."""
    if not integrity.enabled():
        return
    if chars_bytes < 0 or int(offsets[0]) != 0 \
            or int(offsets[-1]) != chars_bytes \
            or (num_rows > 0 and bool(np.any(np.diff(offsets) < 0))):
        raise integrity.reject_malformed(
            "orc.column",
            "string offsets inconsistent with character payload",
            exc_type=MalformedFileError, column=col,
            rows=num_rows, chars_bytes=chars_bytes)


_UTC_NAMES = ("", "UTC", "GMT", "Etc/UTC", "Etc/GMT")


def _wall_to_utc_micros(raw: np.ndarray, valid, tz: str) -> np.ndarray:
    """Wall-clock micros in the writer's zone -> unix-epoch UTC micros,
    via the tz database (the dependency the native layer deliberately
    does not own). Ambiguous/nonexistent wall times (DST transitions)
    resolve to the earliest valid instant."""
    import pyarrow as pa
    import pyarrow.compute as pc

    mask = None if valid is None else ~np.asarray(valid, dtype=bool)
    arr = pa.array(raw.view("datetime64[us]"), mask=mask)
    out = pc.assume_timezone(
        arr, tz, ambiguous="earliest", nonexistent="earliest")
    return np.asarray(out.cast(pa.int64()).fill_null(0))


def _i32_array(vals: Optional[Sequence[int]]):
    if vals is None:
        return None, 0
    arr = (ctypes.c_int32 * len(vals))(*vals)
    return arr, len(vals)


def stripe_info(data) -> list[tuple[int, int]]:
    """[(num_rows, data_bytes)] per stripe — the chunk-planning probe.
    ``data`` may be bytes or a filesystem path (mmap; only tail pages
    fault in)."""
    _validate_orc_envelope(data)
    lib = load_native()
    cap = 4096
    while True:
        nr = (ctypes.c_int64 * cap)()
        bs = (ctypes.c_int64 * cap)()
        path = as_fs_path(data)
        if path is not None:
            n = lib.tpudf_orc_stripes_path(path, nr, bs, cap)
        else:
            n = lib.tpudf_orc_stripes(data, len(data), nr, bs, cap)
        _check(lib, n >= 0, "stripe_info")
        if n <= cap:
            return [(nr[i], bs[i]) for i in range(n)]
        cap = n


@func_range("orc_read_table")
def read_table(
    data,
    columns: Optional[Sequence[int]] = None,
    stripes: Optional[Sequence[int]] = None,
    stage: str = "device",
) -> Table:
    """Decode an ORC file into a device Table. ``data`` may be in-memory
    bytes OR a filesystem path: paths decode through a native mmap (the
    cuFile/GDS-role storage path, like the Parquet reader) — stripe-
    selective reads fault in only the selected byte ranges. None selects
    all columns/stripes; an empty list selects none.

    ``stage="host"`` stops at the host boundary and returns a
    ``HostTableChunk`` (numpy snapshots + exact device bytes): the
    pipelined executor decodes there so the device-budget reservation
    precedes the host->device copy; ``stage()``-ing yields a Table
    bit-identical to the default path."""
    from spark_rapids_jni_tpu.runtime.memory import (
        _col_from_host,
        host_table_chunk,
    )

    if stage not in ("device", "host"):
        raise ValueError(f"unknown stage {stage!r}")

    if as_fs_path(data) is None:
        # fault-injection window: integrity.ingest corruptions land on
        # the untrusted bytes before any validation sees them
        data = faults.fire_corrupt("integrity.ingest", 0, data)
    _validate_orc_envelope(data)
    lib = load_native()
    cols, n_cols = _i32_array(columns)
    sts, n_sts = _i32_array(stripes)
    path = as_fs_path(data)
    if path is not None:
        handle = lib.tpudf_orc_read_path(path, cols, n_cols, sts, n_sts)
    else:
        handle = lib.tpudf_orc_read(
            data, len(data), cols, n_cols, sts, n_sts)
    _check(lib, handle != 0, "orc read")
    try:
        tz_raw = lib.tpudf_orc_writer_timezone(handle)
        _check(lib, tz_raw is not None, "writer_timezone")
        writer_tz = tz_raw.decode("utf-8")
        n_columns = lib.tpudf_orc_num_columns(handle)
        _check(lib, n_columns >= 0, "num_columns")
        # decode every column to a HOST snapshot first (the
        # memory._col_to_host tuple format); device staging happens at
        # the end — or not at all for stage="host", where the pipelined
        # executor reserves budget before staging
        snaps = []
        table_rows = 0
        for i in range(n_columns):
            meta = (ctypes.c_int32 * 4)()
            sizes = (ctypes.c_int64 * 2)()
            _check(lib, lib.tpudf_orc_col_meta(handle, i, meta, sizes) == 0,
                   "col_meta")
            kind, prec, scale, has_valid = list(meta)
            num_rows, chars_bytes = list(sizes)
            _check_orc_rows(table_rows if i else None, num_rows, i)
            table_rows = num_rows
            dtype = _map_dtype(kind, scale, prec)

            vbuf = np.empty(num_rows, dtype=np.uint8) if has_valid else None
            if kind in _STRING_KINDS:
                offsets = np.empty(num_rows + 1, dtype=np.int32)
                chars = np.empty(max(chars_bytes, 1), dtype=np.uint8)
                _check(
                    lib,
                    lib.tpudf_orc_col_copy(
                        handle, i, None,
                        offsets.ctypes.data_as(ctypes.c_void_p),
                        chars.ctypes.data_as(ctypes.c_void_p),
                        None if vbuf is None
                        else vbuf.ctypes.data_as(ctypes.c_void_p),
                    ) == 0,
                    "col_copy",
                )
                _check_orc_string(offsets, num_rows, chars_bytes, i)
                validity = None if vbuf is None else vbuf.astype(bool)
                snaps.append(
                    (dtype, offsets, validity, chars[:chars_bytes], None))
                continue

            n_vals = 2 * num_rows if dtype.is_decimal128 else num_rows
            raw = np.empty(max(n_vals, 1), dtype=np.int64)
            _check(
                lib,
                lib.tpudf_orc_col_copy(
                    handle, i, raw.ctypes.data_as(ctypes.c_void_p), None,
                    None,
                    None if vbuf is None
                    else vbuf.ctypes.data_as(ctypes.c_void_p),
                ) == 0,
                "col_copy",
            )
            validity = None if vbuf is None else vbuf.astype(bool)
            if dtype.is_decimal128:
                limbs = raw[: 2 * num_rows].reshape(num_rows, 2)
                snaps.append((dtype, limbs, validity, None, None))
                continue
            raw = raw[:num_rows]
            if kind == _K_FLOAT:
                values = raw.astype(np.uint32).view(np.float32)
            elif kind == _K_DOUBLE:
                values = raw.view(np.uint64).view(np.float64)
            elif kind == _K_TIMESTAMP and writer_tz not in _UTC_NAMES:
                values = _wall_to_utc_micros(raw, vbuf, writer_tz)
            else:
                values = raw.astype(dtype.storage_dtype, copy=False)
            snaps.append((dtype, values, validity, None, None))
        if stage == "host":
            return host_table_chunk(snaps, table_rows)
        return Table([_col_from_host(s) for s in snaps])
    finally:
        lib.tpudf_orc_close(handle)


class OrcChunkedReader:
    """Iterate an ORC file as Tables bounded by a byte budget — chunk
    boundaries at stripe granularity, always at least one stripe.
    ``data`` may be bytes or a filesystem path (mmap route: each chunk
    faults in only its stripes' byte ranges)."""

    def __init__(
        self,
        data,
        chunk_read_limit: int,
        columns: Optional[Sequence[int]] = None,
    ):
        self._data = data
        self._columns = list(columns) if columns is not None else None
        self._limit = max(int(chunk_read_limit), 1)
        self._infos = stripe_info(data)
        self._next = 0
        # cross-stripe invariants (e.g. agreeing writerTimezone) are
        # checked per read_file call, so per-chunk reads would silently
        # miss a conflict between stripes of DIFFERENT chunks — walk all
        # stripe footers once up front (no column decode: columns=[])
        read_table(data, columns=[])

    def has_next(self) -> bool:
        return self._next < len(self._infos)

    def _chunk_end(self, start: int) -> int:
        total = 0
        end = start
        while end < len(self._infos):
            total += self._infos[end][1]
            if end > start and total > self._limit:
                break
            end += 1
        return end

    def read_chunk(self) -> Table:
        if not self.has_next():
            raise StopIteration
        start = self._next
        end = self._chunk_end(start)
        self._next = end
        return read_table(self._data, self._columns, list(range(start, end)))

    def chunk_plan(self) -> list[list[int]]:
        """Stripe index runs, one per REMAINING chunk. Pure planning:
        does not decode or advance the iteration cursor."""
        plans = []
        start = self._next
        while start < len(self._infos):
            end = self._chunk_end(start)
            plans.append(list(range(start, end)))
            start = end
        return plans

    def chunk_sources(self, stage: str = "host") -> list:
        """Zero-arg decode thunks, one per remaining chunk — the
        pipeline's read/decode-stage contract (see
        ``ParquetChunkedReader.chunk_sources``). ``stage="host"``
        decodes to ``HostTableChunk`` so the device copy can wait for
        its MemoryLimiter reservation."""
        data, columns = self._data, self._columns
        return [
            (lambda sts=sts: read_table(data, columns, sts, stage=stage))
            for sts in self.chunk_plan()
        ]

    def __iter__(self) -> Iterator[Table]:
        while self.has_next():
            yield self.read_chunk()
