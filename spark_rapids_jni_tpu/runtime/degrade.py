"""Graceful degradation under memory pressure: the execution-tier ladder.

The serving runtime (runtime/server.py) admits queries on an HBM estimate;
a mis-estimated query used to either be rejected while HBM sat idle or die
mid-flight once the retry/escalate budget (runtime/resilience.py) topped
out. On a big shared-memory machine the scheduler must bend queries, not
break them: the runtime already has three *bit-identical* execution tiers —
the fused whole-stage path, the staged op-by-op oracle, and out-of-core
chunked execution with chunk-level checkpoint/resume — and this module adds
the controller that steps a live query down them when a classified
``ResourceExhausted`` / ``CapacityOverflow`` escapes the retry budget:

    rung 0  fused       one executable per region (the fast path)
    rung 1  staged      op-by-op oracle — smaller peak (no whole-region
                        intermediates resident at once), same bytes out
    rung 2  outofcore   row-chunked partial->merge under the limiter, the
                        chunk size HALVING on each further pressure failure
                        (completed partials checkpoint in the SpillStore,
                        so replay resumes — it never recomputes)
    rung 3  parked      wait for the limiter to drain below its low
                        watermark, then retry the most degraded tier

Every step emits a ``degrade.step`` telemetry event (tier, trigger, rung)
and fires the ``degrade.step`` fault seam, so chaos suites can script
mid-degrade failures deterministically. Results are bit-identical at every
tier — the ladder trades latency for survival, never correctness. A query
that exhausts the ladder re-raises its ORIGINAL classified failure: no
unclassified error ever leaves the controller. With ``donate_inputs=True``
the controller also verifies the bound buffers are still live before each
step — a genuine pressure failure that lands AFTER XLA consumed the
donated inputs dies classified instead of replaying a lower tier against
dead buffers.

Deliberate stops are not failures: :class:`~.resilience.QueryCancelled`
(deadline expiry or explicit cancel) passes straight through — a cancelled
query must release and die, not climb down the ladder.

``degrade.enabled=false`` restores the exact pre-degradation behavior:
:meth:`DegradationController.execute` is then a plain ``fusion.execute``
call and the first classified failure propagates verbatim.
"""

from __future__ import annotations

from typing import Callable, NamedTuple, Optional

from spark_rapids_jni_tpu import telemetry
from spark_rapids_jni_tpu.runtime import faults, fusion, resilience
from spark_rapids_jni_tpu.runtime.memory import MemoryLimiter, SpillStore
from spark_rapids_jni_tpu.telemetry import spans
from spark_rapids_jni_tpu.utils.config import get_option
from spark_rapids_jni_tpu.utils.log import get_logger

_log = get_logger(__name__)

__all__ = [
    "DegradableQuery",
    "DegradationController",
    "row_chunked_tier",
]


class DegradableQuery(NamedTuple):
    """One query plus everything the ladder needs to re-execute it.

    ``plan`` / ``bindings`` / ``donate_inputs`` are exactly what
    ``fusion.execute`` takes (rungs 0 and 1 reuse them verbatim).
    ``outofcore`` is the optional rung-2 runner — a callable
    ``(chunk_rows, cancel_token) -> Table`` returning the bit-identical
    final table computed chunk-wise under the budget; build one with
    :func:`row_chunked_tier` for queries with a partial->merge
    decomposition. Queries without it skip rung 2 (fused -> staged ->
    parked)."""

    plan: object
    bindings: dict
    donate_inputs: bool = False
    outofcore: Optional[Callable[[int, object], object]] = None


def _row_sliceable(table) -> bool:
    """Can ``_row_slice`` chunk this table? Nested (children) columns and
    string payloads without a per-row leading dimension cannot be sliced
    by row range. :func:`row_chunked_tier` probes this EAGERLY when the
    runner is built, so an unsliceable scan means "no rung-2 tier" at
    ladder-construction time — never a lazy unclassified ValueError in
    the middle of a degrade step."""
    n = table.num_rows
    for c in table.columns:
        if c.children:
            return False
        chars = c.chars
        if chars is not None and not (
                getattr(chars, "ndim", 0) >= 1 and chars.shape[0] == n):
            return False
    return True


def _row_slice(table, start: int, stop: int):
    """A row-range slice of a flat device table (the chunk source for the
    out-of-core rung). Nested (children) columns and non-row-major string
    payloads are not sliceable this way and raise — ``row_chunked_tier``
    screens them out up front with :func:`_row_sliceable`, so this raise
    is a belt-and-suspenders guard, not a reachable path."""
    from spark_rapids_jni_tpu.columnar import Column, Table

    n = table.num_rows
    cols = []
    for c in table.columns:
        if c.children:
            raise ValueError(
                "row_chunked_tier: nested (LIST/STRUCT) columns are not "
                "row-sliceable")
        data = c.data
        if getattr(data, "ndim", 0) >= 1 and data.shape[0] == n:
            data = data[start:stop]
        validity = c.validity
        if validity is not None:
            validity = validity[start:stop]
        chars = c.chars
        if chars is not None:
            if getattr(chars, "ndim", 0) >= 1 and chars.shape[0] == n:
                chars = chars[start:stop]
            else:
                raise ValueError(
                    "row_chunked_tier: string payload without a per-row "
                    "leading dimension is not row-sliceable")
        cols.append(Column(c.dtype, data, validity, chars=chars))
    return Table(cols)


def row_chunked_tier(
    bindings: dict,
    chunk_scan: str,
    partial_fn: Callable,
    merge_fn: Callable,
    *,
    limiter: MemoryLimiter,
    spill_budget_bytes: Optional[int] = None,
    spill_store: Optional[SpillStore] = None,
) -> Optional[Callable[[int, object], object]]:
    """Build a rung-2 out-of-core runner from a partial->merge algebra.

    ``bindings[chunk_scan]`` is the big table to stream in row chunks;
    ``partial_fn(chunk_table) -> partial_table`` and
    ``merge_fn(stacked_partials) -> final_table`` are the same shapes
    ``run_chunked_aggregate`` takes (models/tpch.py q1's partial/merge
    plans are the canonical pair). The returned callable runs the query
    at a given ``chunk_rows`` under ``limiter`` with partials
    checkpointed through a :class:`SpillStore` — chunk-level
    checkpoint/resume (and the halving ladder above it) comes for free
    from ``run_chunked_aggregate``.

    Returns ``None`` when the scan table is not row-sliceable (nested
    LIST/STRUCT columns, string payloads without a per-row leading
    dimension): the caller then has no rung-2 tier (fused -> staged ->
    parked) — decided here, eagerly, so the ladder never discovers it as
    an unclassified error mid-degrade.
    """
    from spark_rapids_jni_tpu.runtime.outofcore import run_chunked_aggregate

    table = bindings[chunk_scan]
    if not _row_sliceable(table):
        telemetry.record_degrade(
            f"degrade.{chunk_scan}", "tier_unavailable", tier="outofcore",
            trigger="not_row_sliceable", rung=2)
        _log.info("row_chunked_tier: %r is not row-sliceable (nested or "
                  "non-row-major string columns) — no rung-2 tier",
                  chunk_scan)
        return None

    def run(chunk_rows: int, cancel_token=None):
        n = int(table.num_rows)
        rows = max(1, min(int(chunk_rows), n))
        chunks = (_row_slice(table, s, min(s + rows, n))
                  for s in range(0, n, rows))
        # a caller-owned store (e.g. the serving runtime's, attached to
        # the limiter for proactive pressure spills) is reused so the
        # watermark reaction can see this query's checkpointed partials
        spill = spill_store if spill_store is not None else SpillStore(
            spill_budget_bytes if spill_budget_bytes is not None
            else limiter.budget)
        res = run_chunked_aggregate(
            chunks, partial_fn, merge_fn, limiter=limiter, spill=spill,
            cancel_token=cancel_token)
        return res.table

    return run


def _bindings_live(bindings: dict) -> bool:
    """Are every bound table's device buffers still alive? With
    ``donate_inputs=True`` the fused executable donates input buffers to
    XLA; the ``fusion.region`` seam fires before dispatch, so INJECTED
    faults always leave the bindings intact — but a genuine failure
    raised mid-execution can land after donation consumed them. Replaying
    a lower tier against deleted arrays would compute garbage (or crash
    unclassified), so the ladder checks liveness before every step and
    dies with the original classified failure when donation already
    happened. Arrays without ``is_deleted`` (numpy hosts) are live by
    definition."""
    def _col_live(c) -> bool:
        for arr in (c.data, c.validity, c.chars):
            deleted = getattr(arr, "is_deleted", None)
            if deleted is not None and deleted():
                return False
        return all(_col_live(ch) for ch in (c.children or ()))

    for v in bindings.values():
        cols = getattr(v, "columns", None)
        if cols is not None and not all(_col_live(c) for c in cols):
            return False
    return True


def _pressure_kind(exc: BaseException) -> Optional[str]:
    """The pressure-classified taxonomy name that makes ``exc`` a ladder
    trigger, or None. Walks the ``__cause__`` chain so a
    ``FatalExecutionError`` raised by an exhausted retry budget over a
    ``CapacityOverflow`` still reads as pressure — the ladder is exactly
    the "beyond the retry/escalate budget" recovery."""
    seen: set = set()
    e: Optional[BaseException] = exc
    while e is not None and id(e) not in seen:
        seen.add(id(e))
        kind = resilience.classify(e)
        if kind is resilience.ResourceExhausted or issubclass(
                kind, resilience.CapacityOverflow):
            return kind.__name__
        e = e.__cause__
    return None


class DegradationController:
    """Steps a live query down the bit-identical tier ladder on pressure.

    One controller per :class:`MemoryLimiter` (the serving runtime holds
    one); :meth:`execute` runs a :class:`DegradableQuery` at the fused
    tier and reacts to classified pressure failures by stepping down —
    never sideways into global state: the staged rung forces the oracle
    path per-call (``fusion.execute(force_staged=True)``), so concurrent
    sessions at different rungs never perturb each other.
    """

    def __init__(self, limiter: MemoryLimiter, *, session: str = "") -> None:
        self.limiter = limiter
        self.session = str(session)

    def execute(self, query: DegradableQuery, *, cancel_token=None,
                label: Optional[str] = None, held_bytes: int = 0,
                observer: Optional[Callable[[str, int, int,
                                             Optional[int]], None]] = None):
        """Run ``query``; returns a ``fusion.FusedResult``.

        With ``degrade.enabled=false`` this is exactly
        ``fusion.execute(plan, bindings, donate_inputs=...)`` — the
        verbatim pre-degradation path. Otherwise classified
        ``ResourceExhausted`` / ``CapacityOverflow`` failures step the
        ladder (bounded by ``degrade.max_steps``); anything else — and
        ``QueryCancelled`` always — re-raises immediately. Ladder
        exhaustion re-raises the ORIGINAL classified failure.

        ``held_bytes`` is the caller's own outstanding limiter
        reservation for this query (the serving runtime passes its
        admission estimate): the parked rung subtracts it from the drain
        threshold, so a query big enough to exceed the low watermark on
        its own can still observe everyone else draining.

        ``observer`` (optional) is called as ``observer(tier, rung,
        steps, chunk_rows)`` at the start of every tier attempt —
        including ``parked`` — independent of telemetry enablement; the
        serving runtime uses it to keep :meth:`QueryServer.inspect`
        current without the controller knowing about servers.
        """
        op = label or f"degrade.{getattr(query.plan, 'name', 'query')}"
        # session attribution rides as an extra field only when known —
        # a None value would mask the ambient session_scope stamp
        attrs = {"session": self.session} if self.session else {}

        if not get_option("degrade.enabled"):
            # the verbatim pre-degradation path, implicit staged
            # fallback (runtime/fusion.py) included
            return fusion.execute(
                query.plan, query.bindings,
                donate_inputs=query.donate_inputs,
                cancel_token=cancel_token)

        tiers = ["fused", "staged"]
        if query.outofcore is not None:
            tiers.append("outofcore")
        tiers.append("parked")
        max_steps = max(1, int(get_option("degrade.max_steps")))
        park_timeout = float(get_option("degrade.park_timeout_s"))
        chunk_rows = max(1, int(get_option("degrade.chunk_rows")))
        rung = 0        # position in ``tiers``
        steps = 0       # total downward steps taken (the telemetry ordinal)
        original: Optional[BaseException] = None
        trigger = "initial"

        while True:
            tier = tiers[min(rung, len(tiers) - 1)]
            if observer is not None:
                observer(tier, rung, steps,
                         chunk_rows if tier == "outofcore" else None)
            try:
                with spans.child(f"rung.{tier}", tier=tier, rung=rung,
                                 step=steps) as rspan:
                    try:
                        if tier == "fused":
                            # the controller owns the fused->staged
                            # transition under pressure: surface those
                            # failures so the step is visible
                            # (degrade.step) rather than silent;
                            # non-pressure faults keep the PR-6 staged
                            # fallback
                            result = fusion.execute(
                                query.plan, query.bindings,
                                donate_inputs=query.donate_inputs,
                                surface_pressure=True,
                                cancel_token=cancel_token)
                        elif tier == "staged":
                            result = fusion.execute(
                                query.plan, query.bindings,
                                donate_inputs=query.donate_inputs,
                                force_staged=True,
                                cancel_token=cancel_token)
                        elif tier == "outofcore":
                            table = query.outofcore(
                                chunk_rows, cancel_token)
                            result = fusion.FusedResult(
                                table, {"degrade.chunk_rows": chunk_rows})
                        else:  # parked
                            telemetry.record_degrade(
                                op, "parked", tier="parked",
                                trigger=trigger, rung=steps, **attrs)
                            drained = self.limiter.wait_below_low(
                                timeout=park_timeout,
                                cancel=None if cancel_token is None
                                else cancel_token.event,
                                own_held=held_bytes)
                            if cancel_token is not None:
                                cancel_token.check("degrade.park")
                            if not drained:
                                telemetry.record_degrade(
                                    op, "exhausted", tier="parked",
                                    trigger=trigger, rung=steps, **attrs)
                                raise original  # noqa: TRY301 — the classified cause
                            telemetry.record_degrade(
                                op, "resumed", tier="parked",
                                trigger=trigger, rung=steps, **attrs)
                            # the drain threshold discounts EVICTABLE
                            # result-cache bytes (memory.py); make that
                            # promise real before retrying, so the
                            # resumed attempt's reservations land on
                            # freed budget instead of re-tripping
                            # pressure against cold cached results
                            self.limiter.reclaim_cache()
                            # retry the most degraded EXECUTABLE tier
                            # after drain
                            rung = len(tiers) - 2
                            continue
                    except resilience.QueryCancelled:
                        raise
                    except BaseException as exc:
                        # a pressure-classified failure is the ladder
                        # working as designed, not this rung dying —
                        # record it as "degraded" in the tree
                        if (exc is not original
                                and _pressure_kind(exc) is not None):
                            rspan.set_status("degraded")
                        raise
            except resilience.QueryCancelled:
                raise
            except BaseException as exc:
                if exc is original:
                    # the parked rung re-raising ladder exhaustion
                    raise
                kind = _pressure_kind(exc)
                if kind is None:
                    raise
                original = original or exc
                if query.donate_inputs and not _bindings_live(
                        query.bindings):
                    # the failed attempt already donated the inputs to
                    # XLA: every lower tier would replay against dead
                    # buffers — die with the classified failure instead
                    telemetry.record_degrade(
                        op, "exhausted", tier=tier, trigger=kind,
                        rung=steps, donated=True, **attrs)
                    if exc is original:
                        raise
                    raise original from exc
                steps += 1
                if steps > max_steps:
                    telemetry.record_degrade(
                        op, "exhausted", tier=tier, trigger=kind,
                        rung=steps, **attrs)
                    raise original from exc
                if tier == "outofcore" and chunk_rows > 1:
                    # same rung, half the chunk — completed partials are
                    # already checkpointed in the SpillStore, only the
                    # remainder re-executes
                    chunk_rows = max(chunk_rows // 2, 1)
                else:
                    rung += 1
                next_tier = tiers[min(rung, len(tiers) - 1)]
                trigger = kind
                extra = dict(attrs)
                if next_tier == "outofcore":
                    extra["chunk_rows"] = chunk_rows
                # seam BEFORE the step commits: chaos scripts inject
                # mid-degrade faults here; an injected raise propagates
                # (it is not itself degraded — one recovery at a time)
                faults.fire("degrade.step", steps, tier=next_tier,
                            trigger=kind, chunk_rows=chunk_rows)
                # flight-record the tree as it stood when the rung
                # stepped: the open root (if the serving runtime holds
                # one on this thread) plus the limiter's watermark state
                flight = spans.dump_flight_record(
                    "degrade_step", state={
                        "limiter": self.limiter.watermarks(),
                        "op": op, "tier": next_tier, "trigger": kind,
                        "steps": steps, "chunk_rows": chunk_rows,
                    })
                if flight:
                    extra["flight_record"] = flight
                telemetry.record_degrade(
                    op, "step", tier=next_tier, trigger=kind, rung=steps,
                    **extra)
                _log.info("%s: %s -> %s after %s (step %d)", op, tier,
                          next_tier, kind, steps)
                continue
            if steps > 0:
                telemetry.record_degrade(
                    op, "completed", tier=tier, trigger=trigger, rung=steps,
                    **attrs)
            return result
