"""Resilient execution: one error taxonomy, one retry/degradation policy.

Before this layer, fault handling was per-call-site improvisation:
``groupby_aggregate_auto`` and ``join_auto`` each hand-rolled grow-and-retry,
the distributed shuffle had a one-shot doubled-capacity retry that gave up on
the second overflow, and everything else let raw exceptions fly. This module
centralizes all of it:

- **Taxonomy** — every runtime seam classifies failure into
  :class:`TransientDeviceError` / :class:`CapacityOverflow` /
  :class:`ResourceExhausted` / :class:`TransportError` /
  :class:`FatalExecutionError`. Transient kinds are retried; the rest
  propagate immediately. Foreign exceptions are *classified for labeling*
  (:func:`classify`) but never blindly retried: an unknown ``RuntimeError``
  from deep inside XLA re-raises unchanged, so enabling resilience does not
  change any legacy propagation behavior.
- **Retry policy** (:func:`retrying`) — bounded attempts
  (``resilience.max_attempts``) with optional geometric backoff
  (``resilience.backoff_ms`` × ``resilience.backoff_multiplier``).
  Exhaustion raises a classified :class:`FatalExecutionError` chaining the
  final cause — never a hang, never a silent wrong result.
- **Degradation ladder** (:func:`escalate`) — grow static capacity
  geometrically (``resilience.growth``), quantized through the dispatch
  bucket schedule where the caller asks; downstream rungs (shrink bucket /
  split chunk, spill via SpillStore, host fallback with mandatory telemetry
  reason) live at the seams that own those mechanisms
  (dispatch ``_inline``, out-of-core chunk replay, fusion staged fallback)
  and report through the same ``resilience.*`` telemetry events.

Every retry/escalation/recovery emits :func:`telemetry.record_resilience`
with the attempt count and ladder rung. ``resilience.enabled=false`` makes
:func:`retrying` a plain call and every rewired call site take its verbatim
pre-resilience code path.

No jax import (import-hygiene contract): usable from telemetry-adjacent and
host-only code.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Optional, Tuple, TypeVar

from spark_rapids_jni_tpu import telemetry
from spark_rapids_jni_tpu.runtime import faults
from spark_rapids_jni_tpu.utils.config import get_option

__all__ = [
    "ResilienceError",
    "TransientDeviceError",
    "CapacityOverflow",
    "ResourceExhausted",
    "TransportError",
    "CorruptDataError",
    "MalformedInputError",
    "FatalExecutionError",
    "QueryCancelled",
    "ReplicaDeadError",
    "CancelToken",
    "Policy",
    "policy",
    "enabled",
    "classify",
    "classify_worker_exit",
    "is_transient",
    "retrying",
    "retry_or_none",
    "escalate",
]

T = TypeVar("T")


# --------------------------------------------------------------------------
# taxonomy
# --------------------------------------------------------------------------


class ResilienceError(RuntimeError):
    """Base of the structured error taxonomy.

    ``context`` carries seam-local diagnostics (rows, capacity, seam, attempt)
    into the message and up to the caller; ``transient`` is the class-level
    retry eligibility the policy consults.
    """

    transient = False

    def __init__(self, message: str, **context: Any) -> None:
        if context:
            detail = ", ".join(f"{k}={v}" for k, v in sorted(context.items()))
            message = f"{message} [{detail}]"
        super().__init__(message)
        self.context = context


class TransientDeviceError(ResilienceError):
    """Device-local failure expected to clear on replay (flaky compile/run)."""

    transient = True


class CapacityOverflow(TransientDeviceError):
    """A static capacity (groups / join slots / shuffle slots) was too small.

    Transient in the ladder sense: recoverable by growing the capacity, not
    by blind replay — :func:`escalate` is the recovery, :func:`retrying`
    alone would loop at the same capacity.
    """


class ResourceExhausted(ResilienceError):
    """A memory budget was genuinely exceeded (MemoryLimiter, HBM).

    Not blind-retried: a deterministic budget violation replays identically.
    Recovery is structural — spill, shrink the chunk, or admit less work —
    and belongs to the seam that owns the budget.
    """

    transient = False


class TransportError(ResilienceError):
    """Shuffle / DCN transport loss (connection reset, timeout, short read)."""

    transient = True


class CorruptDataError(ResilienceError):
    """A checksummed payload (spill entry, DCN wire frame, out-of-core
    checkpoint) failed integrity verification.

    Not transient in the blind-replay sense — re-reading the same bytes
    reproduces the same mismatch deterministically. The recovery is
    structural and seam-specific: at transport seams a fresh copy exists
    on the peer, so :func:`is_transient` special-cases those to drive a
    refetch; at rest the bytes are gone — the owning seam discards the
    payload and replays from source (out-of-core checkpoints) or dies
    classified with a flight record (spill entries with no source).
    """

    transient = False


class MalformedInputError(ResilienceError):
    """Untrusted input (a customer Parquet/ORC file) failed structural
    validation — bad magic, an offset or size pointing outside the file,
    declared counts disagreeing with actual payload. Never retried and
    never degraded: the file is wrong, not the engine — the server
    rejects that one query cleanly and other sessions proceed."""

    transient = False


class FatalExecutionError(ResilienceError):
    """Classified dead end: retries exhausted or failure is unrecoverable."""

    transient = False


class QueryCancelled(ResilienceError):
    """The query was cancelled cooperatively — deadline expiry or an
    explicit caller cancel. Deliberate, so never retried, never degraded:
    the recovery is releasing everything the query held (reservations,
    queue slots) in the same ``finally`` that would have released them on
    success."""

    transient = False


class ReplicaDeadError(ResilienceError):
    """A serving-fleet replica subprocess died — missed its liveness
    deadline, exited nonzero, was killed by a signal, or dropped its
    control socket mid-frame.

    Not transient in the blind-replay sense: the process is gone and
    pinging it again reproduces the silence deterministically. The
    recovery is structural and lives at exactly one seam —
    ``fleet.dispatch`` — where the supervisor re-dispatches the dead
    replica's in-flight queries to a healthy replica under the bounded
    failover budget (:func:`is_transient` special-cases that seam the
    same way transport seams drive a corrupt-frame refetch). Everywhere
    else (heartbeat loop, exit reaping) it propagates classified so the
    caller restarts or quarantines instead of retrying into a corpse."""

    transient = False


class CancelToken:
    """Cooperative cancellation + wall-clock deadline for one query.

    Checked — never preempted — at the boundaries where a query can stop
    cleanly: fused-region dispatch, out-of-core chunk/merge boundaries, and
    inside the pipeline decode pool. ``check(where)`` raises
    :class:`QueryCancelled` once the token is cancelled or its deadline has
    passed; the raise unwinds through the same ``finally`` blocks that
    release reservations and queue slots on success, so cancellation can
    never leak budget.

    ``event`` is a plain ``threading.Event`` set on cancellation, shaped to
    slot directly into ``MemoryLimiter.reserve_blocking(cancel=...)`` and
    the pipeline's cancel plumbing so a *blocked* reservation wakes within
    its poll interval instead of waiting out the budget.

    Every ``check`` fires the ``server.cancel`` fault seam (seq = check
    ordinal), so a FaultScript can inject failures at exact cancellation
    checkpoints deterministically.
    """

    def __init__(self, deadline_ms: int = 0, *, label: str = "query") -> None:
        self.label = str(label)
        self.event = threading.Event()
        self.reason: Optional[str] = None
        self._deadline = (
            None if not deadline_ms
            else time.monotonic() + float(deadline_ms) / 1000.0)
        self._deadline_ms = int(deadline_ms or 0)
        self._checks = 0

    def cancel(self, reason: str = "cancelled") -> None:
        """Request cancellation; idempotent (the first reason wins)."""
        if not self.event.is_set():
            self.reason = str(reason)
            self.event.set()

    def expired(self) -> bool:
        return self._deadline is not None and time.monotonic() >= self._deadline

    def remaining_s(self) -> Optional[float]:
        """Seconds until the deadline (floored at 0), or None when no
        deadline is armed — live introspection (QueryServer.inspect())."""
        if self._deadline is None:
            return None
        return max(0.0, self._deadline - time.monotonic())

    def cancelled(self) -> bool:
        """True once cancelled or past deadline (latches deadline expiry)."""
        if self.event.is_set():
            return True
        if self.expired():
            self.cancel(f"deadline of {self._deadline_ms}ms expired")
            return True
        return False

    def check(self, where: str = "") -> None:
        """Raise :class:`QueryCancelled` if cancellation was requested."""
        self._checks += 1
        faults.fire("server.cancel", self._checks, where=where,
                    label=self.label)
        if self.cancelled():
            raise QueryCancelled(
                f"{self.label}: cancelled at {where or 'checkpoint'}",
                reason=self.reason or "cancelled", where=where or "checkpoint")


# Message markers XLA/jaxlib use for genuinely transient device conditions.
_TRANSIENT_MARKERS = ("RESOURCE_EXHAUSTED", "UNAVAILABLE", "DEADLINE_EXCEEDED")
_TRANSPORT_SEAMS = ("shuffle.transport", "dcn.transport")
# Fleet control-plane seams: socket-layer failures here mean the *replica*
# is gone (the socketpair peer is a child process, not a network), so they
# classify as ReplicaDeadError rather than TransportError.
_FLEET_SEAMS = ("fleet.dispatch", "fleet.heartbeat", "fleet.worker_exit")


def classify(exc: BaseException, *, seam: str = "") -> type:
    """Map an exception to its taxonomy class (for labeling and policy).

    Taxonomy exceptions classify as themselves. Foreign exceptions get a
    best-effort label: MemoryLimiter overruns -> :class:`ResourceExhausted`;
    socket-layer errors at transport seams -> :class:`TransportError`;
    XLA transient status markers -> :class:`TransientDeviceError`; everything
    else -> :class:`FatalExecutionError`. Classification never converts the
    exception object — callers that give up re-raise the *original*.
    """
    if isinstance(exc, ResilienceError):
        return type(exc)
    if isinstance(exc, MemoryError):
        # includes runtime.memory.MemoryLimitExceeded without importing it
        # (avoids a memory<->resilience import cycle)
        return ResourceExhausted
    if seam in _TRANSPORT_SEAMS and isinstance(exc, (ConnectionError, TimeoutError, OSError)):
        return TransportError
    if seam in _FLEET_SEAMS and isinstance(exc, (EOFError, ConnectionError, TimeoutError, OSError)):
        # Broken pipe / EOF / timeout on a replica's control socketpair:
        # the peer is a supervised child process, so socket death *is*
        # replica death — not a retriable transport blip.
        return ReplicaDeadError
    msg = str(exc)
    if any(marker in msg for marker in _TRANSIENT_MARKERS):
        return TransientDeviceError
    return FatalExecutionError


def is_transient(exc: BaseException, *, seam: str = "") -> bool:
    """Retry eligibility under the shared policy.

    Only taxonomy exceptions — and foreign socket errors at transport seams,
    where retry is a protocol concern — are eligible. A foreign exception
    that merely *looks* transient is not retried: resilience must not change
    legacy propagation of errors it does not own.
    """
    if isinstance(exc, CorruptDataError):
        # At a transport seam the peer still holds a pristine copy, so a
        # corrupt frame is refetchable; at rest the bytes are simply gone
        # and re-reading them reproduces the mismatch deterministically.
        return seam in _TRANSPORT_SEAMS
    if isinstance(exc, ReplicaDeadError):
        # Only the dispatch seam can recover from a dead replica — by
        # re-placing the query on a *different* replica under the bounded
        # failover budget. Heartbeat and reap paths must not retry into
        # the corpse.
        return seam == "fleet.dispatch"
    if isinstance(exc, ResilienceError):
        return exc.transient
    if seam in _TRANSPORT_SEAMS and isinstance(exc, (ConnectionError, TimeoutError)):
        return True
    return False


def classify_worker_exit(
    returncode: Optional[int],
    *,
    replica: str = "",
    seam: str = "fleet.worker_exit",
    **context: Any,
) -> ReplicaDeadError:
    """Turn a reaped worker exit status into a classified taxonomy error.

    Maps the three subprocess death shapes into :class:`ReplicaDeadError`
    with cause context instead of letting a raw exit code (or a raw
    ``OSError``/``EOFError`` from the control socket) escape unlabeled:

    - negative ``returncode`` — killed by a signal (``-9`` -> ``SIGKILL``);
    - positive ``returncode`` — exited nonzero;
    - ``None`` — still officially running yet unresponsive (missed its
      liveness deadline or dropped the control socket mid-frame).

    Returns the exception (callers raise it, record it, or attach it to an
    in-flight query's failover) — construction never raises.
    """
    if returncode is None:
        cause = "unresponsive"
    elif returncode < 0:
        try:
            import signal as _signal

            cause = f"signal:{_signal.Signals(-int(returncode)).name}"
        except (ValueError, ImportError):
            cause = f"signal:{-int(returncode)}"
    elif returncode != 0:
        cause = f"exit:{int(returncode)}"
    else:
        cause = "exit:0"
    ctx = dict(context)
    if replica:
        ctx["replica"] = replica
    return ReplicaDeadError(
        f"replica worker died ({cause})",
        cause=cause, seam=seam,
        returncode=-1 if returncode is None else int(returncode), **ctx)


# --------------------------------------------------------------------------
# policy
# --------------------------------------------------------------------------


class Policy:
    """A snapshot of the ``resilience.*`` options (one config read per run)."""

    __slots__ = ("enabled", "max_attempts", "growth", "backoff_ms", "backoff_multiplier")

    def __init__(self) -> None:
        self.enabled = bool(get_option("resilience.enabled"))
        self.max_attempts = max(1, int(get_option("resilience.max_attempts")))
        self.growth = max(2, int(get_option("resilience.growth")))
        self.backoff_ms = max(0, int(get_option("resilience.backoff_ms")))
        self.backoff_multiplier = max(1.0, float(get_option("resilience.backoff_multiplier")))


def policy() -> Policy:
    return Policy()


def enabled() -> bool:
    return bool(get_option("resilience.enabled"))


def _backoff(pol: Policy, attempt: int) -> None:
    if pol.backoff_ms <= 0:
        return
    time.sleep(pol.backoff_ms * (pol.backoff_multiplier ** (attempt - 1)) / 1000.0)


# --------------------------------------------------------------------------
# retry
# --------------------------------------------------------------------------


def retrying(
    op: str,
    fn: Callable[[], T],
    *,
    seam: str,
    rung: str = "same_capacity",
    pol: Optional[Policy] = None,
    **context: Any,
) -> T:
    """Run ``fn`` under the shared bounded-retry policy.

    Transient failures (per :func:`is_transient`) are retried up to
    ``resilience.max_attempts`` total attempts with the configured backoff;
    each retry and the eventual recovery emit ``resilience.*`` telemetry with
    the attempt count and ladder ``rung``. Non-transient failures re-raise
    the original immediately. Exhaustion raises
    :class:`FatalExecutionError` chaining the final transient cause, with the
    cause's message embedded so existing match-on-message tests survive.

    With ``resilience.enabled=false`` this is exactly ``fn()``.
    """
    pol = pol or policy()
    if not pol.enabled:
        return fn()
    attempt = 1
    while True:
        try:
            result = fn()
        except BaseException as exc:
            if not is_transient(exc, seam=seam):
                raise
            # "kind" is the record's reserved discriminator — the
            # classified taxonomy name travels as error_kind
            error_kind = classify(exc, seam=seam).__name__
            if attempt >= pol.max_attempts:
                telemetry.record_resilience(
                    op, "fatal", seam=seam, attempt=attempt, rung=rung,
                    error_kind=error_kind, **context,
                )
                raise FatalExecutionError(
                    f"{op}: retries exhausted after {attempt} attempts at seam "
                    f"{seam}: {exc}",
                    seam=seam, attempts=attempt, **context,
                ) from exc
            telemetry.record_resilience(
                op, "retry", seam=seam, attempt=attempt, rung=rung,
                error_kind=error_kind, **context,
            )
            _backoff(pol, attempt)
            attempt += 1
            continue
        if attempt > 1:
            telemetry.record_resilience(
                op, "recovered", seam=seam, attempt=attempt, rung=rung, **context,
            )
        return result


def retry_or_none(
    op: str,
    fn: Callable[[], T],
    *,
    seam: str,
    rung: str = "same_capacity",
    pol: Optional[Policy] = None,
    **context: Any,
) -> Tuple[Optional[T], Optional[BaseException]]:
    """Like :func:`retrying` but never raises: ``(result, None)`` on success,
    ``(None, final_exc)`` on give-up.

    For seams with their own downstream ladder rung (dispatch falls back to
    the host inline path, fusion falls back to the staged evaluator): the
    caller inspects the exception, takes its rung, and records why.
    """
    try:
        return retrying(op, fn, seam=seam, rung=rung, pol=pol, **context), None
    except BaseException as exc:  # tpulint: disable=error-must-classify — give-up is returned for the caller's ladder rung
        return None, exc


# --------------------------------------------------------------------------
# capacity escalation (the grow-static-capacity ladder rung)
# --------------------------------------------------------------------------


def escalate(
    op: str,
    attempt_fn: Callable[[int], Tuple[T, bool, Optional[int]]],
    *,
    seam: str,
    initial: int,
    growth: Optional[int] = None,
    max_capacity: Optional[int] = None,
    quantize: Optional[Callable[[int], int]] = None,
    pol: Optional[Policy] = None,
    exhaust: Optional[Callable[[int, int], BaseException]] = None,
    **context: Any,
) -> T:
    """Bounded geometric capacity escalation — the shared grow-and-retry.

    ``attempt_fn(capacity)`` returns ``(result, needs_more, required)``:
    ``needs_more`` says the capacity overflowed; ``required``, when the
    attempt can name the exact need (join's total-matches count), jumps the
    schedule there directly. Growth is geometric (``growth`` or the policy
    default), optionally quantized (dispatch bucket schedule), clamped to
    ``max_capacity``. Growing to a cap is intrinsically bounded, so the
    attempt bound applies to *transient* failures at one capacity (delegated
    to :func:`retrying`), not to growth steps.

    Still-overflowing at ``max_capacity`` raises ``exhaust(capacity, steps)``
    when given (site-specific exception contracts, e.g. the planner's
    ValueError) or a classified :class:`FatalExecutionError`. Each growth
    step emits an ``escalate`` event with rung ``grow_capacity``.
    """
    pol = pol or policy()
    grow = int(growth) if growth is not None else pol.growth
    cap = max(1, int(initial))
    if max_capacity is not None:
        cap = min(cap, max(1, int(max_capacity)))
    step = 0
    while True:
        result, needs_more, required = retrying(
            op, lambda: attempt_fn(cap), seam=seam, pol=pol,
            capacity=cap, **context,
        )
        if not needs_more:
            if step > 0:
                telemetry.record_resilience(
                    op, "recovered", seam=seam, attempt=step + 1,
                    rung="grow_capacity", capacity=cap, **context,
                )
            return result
        at_max = max_capacity is not None and cap >= int(max_capacity)
        if at_max:
            telemetry.record_resilience(
                op, "fatal", seam=seam, attempt=step + 1, rung="grow_capacity",
                capacity=cap, **context,
            )
            if exhaust is not None:
                raise exhaust(cap, step + 1)
            raise FatalExecutionError(
                f"{op}: capacity escalation exhausted at {cap}",
                seam=seam, capacity=cap, steps=step + 1, **context,
            )
        new_cap = cap * grow
        if required is not None:
            new_cap = max(int(required), new_cap)
        if quantize is not None:
            new_cap = int(quantize(new_cap))
        if max_capacity is not None:
            new_cap = min(new_cap, max(1, int(max_capacity)))
        new_cap = max(new_cap, cap + 1)
        step += 1
        telemetry.record_resilience(
            op, "escalate", seam=seam, attempt=step, rung="grow_capacity",
            capacity=new_cap, previous_capacity=cap, **context,
        )
        cap = new_cap
