"""Whole-stage fusion: compile query plans into single donated executables.

The reference ships ONE fat native library so a Spark stage runs as few
device launches as possible; Flare (PAPERS.md) shows whole-stage native
compilation is the dominant win for Spark-shaped plans. Our models were
still executing op-by-op: every filter/project/groupby/join/sort went
through ``dispatch.call`` as its OWN executable, materializing each
intermediate Table in HBM and paying per-op dispatch overhead. This module
closes that gap: a small logical-plan IR (scan / filter / project /
groupby / join / sort / limit nodes over ``Table``) plus a fuser that
composes a fusible region's per-op device functions into ONE traced
callable and dispatches it once through ``dispatch.call`` — so a fused
region inherits shape bucketing and the executable cache, and a whole
query compiles to one executable per bucket instead of one per op per
bucket.

Region discipline
-----------------
``execute`` runs ONE fusible region. Genuine host boundaries — out-of-core
partial compaction (``trim_table`` between chunk and merge), the shuffle
collective between distributed partial and merge, the planner
``domain_miss`` / ``pk_violation`` re-plan check — stay in the model's
host wrapper, which composes one plan per region (see
``models/tpch.tpch_q1_outofcore`` for the two-region shape). Inside a
region every op is inlined into the single trace: the per-op
``dispatch.call`` sites detect the tracer inputs and take their inline
path, so the op implementations themselves are byte-for-byte the staged
ones.

Bit-identity
------------
The region's inputs are bucket-padded ONCE at the region boundary; the
per-group ``row_valid`` masks thread through the same user-level
``row_valid`` parameters the staged ops already expose (``join``'s
``left_row_valid``, ``groupby_aggregate``'s and ``plan_groupby``'s
``row_valid``, ``sort_order``'s phantom-last ranking), so a fused region
computes exactly what the staged path computes at the same bucket — every
fused query is bit-identical to its op-by-op reference at any row count
(tests/test_fusion.py pins this at 1, 2^k-1, 2^k, 2^k+1 rows with null
tails).

Kernel tier
-----------
The Pallas kernel tier (ops/pallas/, ``kernels.tier``) composes with
fusion for free: tier selection happens at TRACE time inside each per-op
implementation (``groupby_aggregate_bounded``, ``probe_sorted_lo_hi``,
``_to_rows_impl``), so when a fused region inlines those ops the chosen
kernels are baked into the single fused executable — Pallas kernels
inherit the region's shape bucketing, executable cache, and donation
exactly like their XLA twins. Every ``dispatch.call`` key (fused or
staged) carries the kernels digest, so flipping ``kernels.tier`` or a
per-op override re-specializes fused executables instead of reusing a
stale tier's cache entry.

Donation
--------
``execute(..., donate_inputs=True)`` is the caller's declaration that the
bound input tables are DEAD after the call (an intermediate table the plan
runner owns, an out-of-core chunk nothing else reads): the fused
executable then compiles with ``donate_argnums`` on its row param so XLA
reuses those buffers for outputs instead of double-buffering HBM
(``fusion.donate`` config gates this; bytes are accounted under
``dispatch.donated_bytes``).

Telemetry: ``fusion.regions`` / ``fusion.nodes_fused`` /
``fusion.staged_regions`` counters; executables per query are the
``dispatch.compile.fusion.<plan>`` counters (one region op name per
plan); ``fusion.stats()`` aggregates all of it for the bench block.

Config knobs (utils/config.py): ``fusion.enabled`` (off = the same plan
runs op-by-op, the staged reference path), ``fusion.donate``.
"""

from __future__ import annotations

from typing import Any, Callable, NamedTuple, Optional

import jax.numpy as jnp
import numpy as np

from spark_rapids_jni_tpu.columnar import Column, Table
from spark_rapids_jni_tpu.runtime import faults, resilience
from spark_rapids_jni_tpu.telemetry import spans
from spark_rapids_jni_tpu.telemetry.events import record_fallback
from spark_rapids_jni_tpu.telemetry.registry import REGISTRY
from spark_rapids_jni_tpu.utils.config import get_option

__all__ = [
    "Scan",
    "Filter",
    "Project",
    "GroupBy",
    "Join",
    "DensePkJoin",
    "BloomBuild",
    "BloomProbe",
    "Sort",
    "Limit",
    "Exchange",
    "Plan",
    "FusedResult",
    "rows_of",
    "min_rows_of",
    "execute",
    "inject_runtime_filters",
    "estimate_hbm_bytes",
    "plan_fingerprint",
    "scan_prefix_chains",
    "replace_node",
    "stats",
]


# ---------------------------------------------------------------------------
# resolvable row specs — statics that depend on TRUE input row counts
# ---------------------------------------------------------------------------
#
# Capacities like a join's out_size or a partial groupby's budget are
# STATIC plan parameters derived from the true (pre-padding) row count of
# an input — never from the bucket, or the fused output shape would drift
# from the staged reference. They resolve at execute() time and ride the
# dispatch key, exactly like the statics the staged op calls carry.


def rows_of(name: str, factor: int = 1):
    """out_rows spec: ``factor *`` the bound table's true row count."""
    return ("rows_of", name, int(factor))


def min_rows_of(name: str, cap: int):
    """max_groups spec: ``min(cap, true row count)`` — the out-of-core
    partial's ``min(_Q1_GROUP_BUDGET, work.num_rows)`` shape."""
    return ("min_rows_of", name, int(cap))


def _resolve(spec, true_rows: dict) -> Optional[int]:
    if spec is None or isinstance(spec, int):
        return spec
    if isinstance(spec, tuple) and len(spec) == 3:
        kind, name, arg = spec
        if kind == "rows_of":
            return int(true_rows[name]) * arg
        if kind == "min_rows_of":
            return min(arg, int(true_rows[name]))
    raise ValueError(f"unresolvable row spec {spec!r}")


# ---------------------------------------------------------------------------
# logical-plan IR
# ---------------------------------------------------------------------------
#
# Nodes are plain NamedTuples forming a DAG (shared subplans are shared by
# object identity). Node callables (Filter predicates, Project fns) must
# be module-level functions — they are fingerprinted by qualified name for
# the executable-cache key, with all per-query variation carried in the
# ``params`` tuple (the same discipline dispatch ``statics`` impose).


class Scan(NamedTuple):
    """A named input table. ``bucket=False`` keeps the table at its exact
    shape (an aux arg — broadcast build sides whose row count is a planner
    fact, e.g. a clustered dense-PK build whose rows MUST equal the
    declared key range)."""

    name: str
    bucket: bool = True


class Filter(NamedTuple):
    """WHERE via the masking idiom: ``pred(table, *params) -> bool[n]``;
    rows where the predicate is False get their validity nulled in every
    column (never compacted — static shapes)."""

    child: Any
    pred: Callable
    params: tuple = ()


class Project(NamedTuple):
    """``fn(table, *params) -> Table``. ``rowwise=True`` (the default)
    promises the output rows align 1:1 with the input rows (derived
    columns, key masking). ``rowwise=False`` marks a shape-changing
    compute (a full-table reduction like q6's multiply-accumulate); the fn
    then receives the region row_valid as ``fn(table, row_valid, *params)``
    and its output is its own row space."""

    child: Any
    fn: Callable
    params: tuple = ()
    rowwise: bool = True


class GroupBy(NamedTuple):
    """``groupby_aggregate`` (or ``plan_groupby`` when ``domains`` is
    given). ``max_groups`` may be an int, None, or a ``min_rows_of`` spec.
    Side outputs land in the result meta under ``<label>.*``
    (num_groups/overflowed/sum_overflow, or present/domain_miss/lowered
    on the planned lowering)."""

    child: Any
    keys: tuple
    aggs: tuple
    max_groups: Any = None
    domains: Any = None
    budget: int = 4096
    label: str = "groupby"


class Join(NamedTuple):
    """General equi-join + ``apply_join_maps`` materialization: left
    columns then right columns, ``out_rows`` output rows (an int or a
    ``rows_of`` spec — resolved from TRUE row counts, never buckets).
    Meta: ``<label>.total``."""

    left: Any
    right: Any
    left_on: tuple
    right_on: tuple
    out_rows: Any
    how: str = "inner"
    label: str = "join"


class DensePkJoin(NamedTuple):
    """Planner-declared dense-PK lookup join (``ops/planner.dense_pk_join``):
    probe-aligned output, no capacity estimate. ``key_hi`` may be a
    ``rows_of`` spec. The build child should hang off an unbucketed Scan
    when ``clustered=True`` (build rows must equal the declared range).
    Meta: ``<label>.total`` / ``<label>.pk_violation``."""

    probe: Any
    build: Any
    probe_key: int
    build_key: int
    key_lo: int
    key_hi: Any
    clustered: bool = False
    label: str = "pk_join"


class BloomBuild(NamedTuple):
    """Runtime-filter build side: materialize the child's key column into
    a Spark-compatible bloom filter (``bloom_put_spark`` — null keys and
    phantom rows skipped), emitted as a one-column uint8 bits table.
    Inserted by :func:`inject_runtime_filters`, never written by hand;
    geometry (num_bits, num_hashes) is a static chosen by the gate and
    fingerprinted, so on/off — and differently-sized — plans never alias
    an executable."""

    child: Any
    key: int
    num_bits: int
    num_hashes: int
    label: str = "rtf"


class BloomProbe(NamedTuple):
    """Runtime-filter probe side: rows whose key is definitely absent
    from the ``build`` filter get that KEY's validity nulled — exactly
    the WHERE-before-join masking idiom, so the join downstream treats
    them as the non-matches they are provably about to be. No row is
    compacted and no data byte changes: results are bit-identical with
    the probe present or absent, for probe-aligned and compacting joins
    alike (a bloom filter has no false negatives). ``build`` is either a
    :class:`BloomBuild` or an unbucketed Scan bound to a bits table
    (``packed=True`` when those bits are the ``to_packed`` wire form a
    cluster shard received). Meta: ``<label>.rows_in`` /
    ``<label>.rows_pass`` — the observed selectivity the learned gate
    feeds on."""

    child: Any
    build: Any
    key: int
    num_bits: int
    num_hashes: int
    packed: bool = False
    label: str = "rtf"


class Sort(NamedTuple):
    """``sort_table``; when the input still carries a region row_valid the
    phantom rows rank strictly last (``sort_order``'s row_valid contract),
    so the real prefix is exactly the staged sort."""

    child: Any
    keys: tuple
    ascending: Any = None
    nulls_first: Any = None


class Limit(NamedTuple):
    """Positional head: first ``min(count, true rows)`` rows."""

    child: Any
    count: int


class Exchange(NamedTuple):
    """General-cardinality hash repartition of the child's output — the
    distributed-exchange boundary (runtime/exchange.py). A shuffle is a
    genuine host boundary, so an Exchange never evaluates INSIDE a
    fused/staged region; the planner instead breaks the plan at it. As
    a plan ROOT, the child region fuses and executes normally and the
    exchange pack runs as its own dispatch op on the result (the wire
    form the cluster ships). Placed MID-PLAN, ``execute`` splits the
    DAG at the (deepest-first) interior Exchange into region ->
    exchange -> region: the pack half runs as an Exchange root, the
    remainder re-runs per destination with the Exchange swapped for a
    Scan bound to that destination's regrouped rows, and the
    part-ordered concatenation is the plan's result — bit-identical to
    the hand-split (pack plan, merge plan) pair it replaces.

    ``keys`` are column indices hashed with the Spark-compatible
    ``partition_hash``; ``parts`` is the destination count (cluster
    hosts), or 0 for "auto" — resolved at execute time from the
    learned-selectivity store (``exchange.choose_parts``; fingerprints
    only ever see the resolved count). ``capacity`` is the
    per-destination slot count (an int, a ``rows_of`` spec, or None for
    the escalation ladder's derived start). ``valid_meta`` optionally
    names a child meta key holding the TRUE row count of the child's
    padded output (e.g. a partial groupby's ``partial.num_groups``) so
    budget-padding phantom rows never ride the wire. Meta:
    ``<label>.parts`` / ``<label>.capacity`` / ``<label>.flights`` /
    ``<label>.row_counts`` / ``<label>.rows`` (plain Python — they
    survive the fleet's result frames)."""

    child: Any
    keys: tuple
    parts: int
    capacity: Any = None
    valid_meta: Optional[str] = None
    label: str = "exchange"


class Plan(NamedTuple):
    """A named fusible region: one root node, one fused executable. The
    name becomes the dispatch op (``fusion.<name>``), so executables per
    query are countable (``dispatch.compile.fusion.<name>``)."""

    name: str
    root: Any


_NODE_TYPES = (Scan, Filter, Project, GroupBy, Join, DensePkJoin,
               BloomBuild, BloomProbe, Sort, Limit, Exchange)


class FusedResult(NamedTuple):
    table: Table
    # side outputs of labeled nodes: "<label>.<field>" -> scalar/array
    # (plus static plan facts like "<label>.lowered")
    meta: dict


# ---------------------------------------------------------------------------
# static plan analysis
# ---------------------------------------------------------------------------


def _children(node) -> tuple:
    if isinstance(node, Scan):
        return ()
    if isinstance(node, (Filter, Project, GroupBy, Sort, Limit, BloomBuild,
                         Exchange)):
        return (node.child,)
    if isinstance(node, Join):
        return (node.left, node.right)
    if isinstance(node, DensePkJoin):
        return (node.probe, node.build)
    if isinstance(node, BloomProbe):
        return (node.child, node.build)
    raise TypeError(f"not a plan node: {type(node).__name__}")


def _topo(root) -> list:
    """Children-first topological order over the node DAG."""
    order: list = []
    seen: set = set()

    def visit(node):
        if id(node) in seen:
            return
        seen.add(id(node))
        for c in _children(node):
            visit(c)
        order.append(node)

    visit(root)
    return order


def _scan_names(nodes) -> tuple[list, list]:
    """(bucketed, exact) scan names in first-appearance order. A name
    must be scanned consistently (one bucket flag per table)."""
    bucketed: list = []
    exact: list = []
    flags: dict = {}
    for node in nodes:
        if not isinstance(node, Scan):
            continue
        if node.name in flags:
            if flags[node.name] != node.bucket:
                raise ValueError(
                    f"scan {node.name!r} used both bucketed and exact")
            continue
        flags[node.name] = node.bucket
        (bucketed if node.bucket else exact).append(node.name)
    return bucketed, exact


def _fn_key(fn) -> tuple:
    mod = getattr(fn, "__module__", None)
    qual = getattr(fn, "__qualname__", None)
    if mod is None or qual is None or "<locals>" in (qual or ""):
        raise ValueError(
            "plan callables must be module-level functions (their "
            "qualified name keys the executable cache); got "
            f"{fn!r} — carry per-query variation in params instead")
    return (mod, qual)


def _fingerprint(nodes, resolved: dict) -> tuple:
    """Structural digest of the plan DAG: node kinds, static params,
    resolved row specs, and child indices — the fused region's dispatch
    ``statics``. Two plans collide only if they trace identically."""
    index = {id(n): i for i, n in enumerate(nodes)}
    out = []
    for node in nodes:
        kids = tuple(index[id(c)] for c in _children(node))
        if isinstance(node, Scan):
            entry = ("scan", node.name, node.bucket)
        elif isinstance(node, Filter):
            entry = ("filter", _fn_key(node.pred), node.params)
        elif isinstance(node, Project):
            entry = ("project", _fn_key(node.fn), node.params, node.rowwise)
        elif isinstance(node, GroupBy):
            doms = None
            if node.domains is not None:
                doms = tuple(
                    (None if d is None else (tuple(d.values), d.kind))
                    for d in node.domains)
            entry = ("groupby", node.keys, node.aggs,
                     resolved[id(node)], doms, node.budget)
        elif isinstance(node, Join):
            entry = ("join", node.left_on, node.right_on,
                     resolved[id(node)], node.how)
        elif isinstance(node, DensePkJoin):
            entry = ("pk_join", node.probe_key, node.build_key, node.key_lo,
                     resolved[id(node)], node.clustered)
        elif isinstance(node, BloomBuild):
            entry = ("bloom_build", node.key, node.num_bits, node.num_hashes)
        elif isinstance(node, BloomProbe):
            entry = ("bloom_probe", node.key, node.num_bits,
                     node.num_hashes, node.packed)
        elif isinstance(node, Sort):
            entry = ("sort", node.keys,
                     None if node.ascending is None else tuple(node.ascending),
                     None if node.nulls_first is None
                     else tuple(node.nulls_first))
        elif isinstance(node, Limit):
            entry = ("limit", resolved[id(node)])
        elif isinstance(node, Exchange):
            entry = ("exchange", node.keys, node.parts,
                     resolved[id(node)], node.valid_meta)
        else:  # pragma: no cover - _children already rejects
            raise TypeError(type(node).__name__)
        out.append(entry + (kids,))
    return tuple(out)


def _resolve_statics(nodes, true_rows: dict) -> dict:
    """Evaluate every row-count-derived static against TRUE row counts."""
    resolved: dict = {}
    for node in nodes:
        if isinstance(node, GroupBy):
            resolved[id(node)] = _resolve(node.max_groups, true_rows)
        elif isinstance(node, Join):
            resolved[id(node)] = _resolve(node.out_rows, true_rows)
        elif isinstance(node, DensePkJoin):
            resolved[id(node)] = _resolve(node.key_hi, true_rows)
        elif isinstance(node, Limit):
            resolved[id(node)] = int(node.count)
        elif isinstance(node, Exchange):
            resolved[id(node)] = _resolve(node.capacity, true_rows)
    return resolved


def _spaces(nodes) -> dict:
    """Static row-space analysis: node id -> scan name whose POSITIONAL
    row space the node's output lives in (sliceable back to the true row
    count after a padded fused run), or None for fixed/derived shapes
    (groupby budgets, join out_size, bounded-plan slot tables)."""
    spaces: dict = {}
    for node in nodes:
        if isinstance(node, Scan):
            spaces[id(node)] = node.name if node.bucket else None
        elif isinstance(node, Filter):
            spaces[id(node)] = spaces[id(node.child)]
        elif isinstance(node, Project):
            spaces[id(node)] = (
                spaces[id(node.child)] if node.rowwise else None)
        elif isinstance(node, GroupBy):
            # max_groups=None pads the output to the input row count, so
            # it stays positionally sliceable; an explicit budget (or the
            # bounded plan's slot count) is its own fixed shape
            if node.max_groups is None and node.domains is None:
                spaces[id(node)] = spaces[id(node.child)]
            else:
                spaces[id(node)] = None
        elif isinstance(node, DensePkJoin):
            spaces[id(node)] = spaces[id(node.probe)]  # probe-aligned
        elif isinstance(node, BloomBuild):
            spaces[id(node)] = None  # fixed shape: num_bits bytes
        elif isinstance(node, BloomProbe):
            # only a key's validity changes — strictly row-preserving
            spaces[id(node)] = spaces[id(node.child)]
        elif isinstance(node, Sort):
            spaces[id(node)] = spaces[id(node.child)]
        elif isinstance(node, (Join, Limit, Exchange)):
            spaces[id(node)] = None
    return spaces


def _side_keys(nodes) -> list:
    """Deterministic (label, field) order of traced side outputs."""
    keys: list = []
    for node in nodes:
        if isinstance(node, GroupBy):
            if node.domains is not None:
                keys += [f"{node.label}.present",
                         f"{node.label}.domain_miss",
                         f"{node.label}.overflowed"]
            else:
                keys += [f"{node.label}.num_groups",
                         f"{node.label}.overflowed",
                         f"{node.label}.sum_overflow"]
        elif isinstance(node, Join):
            keys.append(f"{node.label}.total")
        elif isinstance(node, DensePkJoin):
            keys += [f"{node.label}.total", f"{node.label}.pk_violation"]
        elif isinstance(node, BloomProbe):
            keys += [f"{node.label}.rows_in", f"{node.label}.rows_pass"]
    return keys


# ---------------------------------------------------------------------------
# evaluation — one shared walker for the fused trace AND the staged path
# ---------------------------------------------------------------------------


def _null_all(table: Table, keep: jnp.ndarray) -> Table:
    return Table([
        Column(c.dtype, c.data, c.valid_mask() & keep,
               chars=c.chars, children=c.children)
        for c in table.columns
    ])


def _head(table: Table, k: int) -> Table:
    return Table([
        Column(c.dtype, c.data[:k],
               None if c.validity is None else c.validity[:k],
               chars=None if c.chars is None else c.chars[:k])
        for c in table.columns
    ])


def _eval_plan(root, tables: dict, rvs: dict, resolved: dict,
               true_rows: dict):
    """Evaluate the DAG. ``tables``/``rvs`` hold the (possibly padded)
    input tables and their region row_valid masks. Returns
    (root table, [(side key, traced value), ...]). Called with tracer
    tables inside the fused region fn and with concrete tables on the
    staged path — the SAME per-op calls either way."""
    from spark_rapids_jni_tpu import types as _t
    from spark_rapids_jni_tpu.ops import bloom_filter as _bloom
    from spark_rapids_jni_tpu.ops.groupby import groupby_aggregate
    from spark_rapids_jni_tpu.ops.join import apply_join_maps, join
    from spark_rapids_jni_tpu.ops.planner import dense_pk_join, plan_groupby
    from spark_rapids_jni_tpu.ops.sort import gather, sort_order

    env: dict = {}
    side: list = []

    def ev(node):
        if id(node) in env:
            return env[id(node)]
        if isinstance(node, Scan):
            out = (tables[node.name], rvs.get(node.name))
        elif isinstance(node, Filter):
            tbl, rv = ev(node.child)
            out = (_null_all(tbl, node.pred(tbl, *node.params)), rv)
        elif isinstance(node, Project):
            tbl, rv = ev(node.child)
            if node.rowwise:
                out = (node.fn(tbl, *node.params), rv)
            else:
                out = (node.fn(tbl, rv, *node.params), None)
        elif isinstance(node, GroupBy):
            tbl, rv = ev(node.child)
            if node.domains is not None:
                res = plan_groupby(
                    tbl, list(node.keys), list(node.aggs),
                    list(node.domains), budget=node.budget, row_valid=rv)
                side.extend([
                    (f"{node.label}.present", res.present),
                    (f"{node.label}.domain_miss", res.domain_miss),
                    (f"{node.label}.overflowed",
                     jnp.asarray(res.overflowed)),
                ])
                out = (res.table, None)
            else:
                g = groupby_aggregate(
                    tbl, list(node.keys), list(node.aggs),
                    max_groups=resolved[id(node)], row_valid=rv)
                side.extend([
                    (f"{node.label}.num_groups", g.num_groups),
                    (f"{node.label}.overflowed", jnp.asarray(g.overflowed)),
                    (f"{node.label}.sum_overflow",
                     jnp.asarray(g.sum_overflow)),
                ])
                # a None budget pads to the input rows: still positional
                rv_out = rv if resolved[id(node)] is None else None
                out = (g.table, rv_out)
        elif isinstance(node, Join):
            ltbl, lrv = ev(node.left)
            rtbl, rrv = ev(node.right)
            maps = join(ltbl, rtbl, list(node.left_on), list(node.right_on),
                        out_size=resolved[id(node)], how=node.how,
                        left_row_valid=lrv, right_row_valid=rrv)
            side.append((f"{node.label}.total", maps.total))
            out = (apply_join_maps(ltbl, rtbl, maps), None)
        elif isinstance(node, DensePkJoin):
            ptbl, prv = ev(node.probe)
            btbl, brv = ev(node.build)
            if brv is not None:
                # a padded build side would break the declared layout;
                # phantom build rows are nulled out of the lookup instead
                btbl = _null_all(btbl, brv)
            r = dense_pk_join(ptbl, btbl, node.probe_key, node.build_key,
                              node.key_lo, resolved[id(node)],
                              clustered=node.clustered)
            side.extend([
                (f"{node.label}.total", r.total),
                (f"{node.label}.pk_violation", r.pk_violation),
            ])
            out = (r.table, prv)
        elif isinstance(node, BloomBuild):
            tbl, rv = ev(node.child)
            col = tbl.columns[node.key]
            kv = col.valid_mask()
            if rv is not None:
                kv = kv & rv
            bf = _bloom.BloomFilter(
                jnp.zeros((node.num_bits,), dtype=jnp.uint8),
                node.num_hashes)
            bf = _bloom.bloom_put_spark(bf, col.data, kv)
            out = (Table([Column(_t.UINT8, bf.bits)]), None)
        elif isinstance(node, BloomProbe):
            tbl, rv = ev(node.child)
            btbl, _ = ev(node.build)
            bits = btbl.columns[0].data
            if node.packed:
                bf = _bloom.BloomFilter.from_packed(
                    bits, node.num_bits, node.num_hashes)
            else:
                bf = _bloom.BloomFilter(bits, node.num_hashes)
            col = tbl.columns[node.key]
            kv = col.valid_mask()
            if rv is not None:
                kv = kv & rv
            hit = _bloom.bloom_might_contain_spark(bf, col.data)
            side.extend([
                (f"{node.label}.rows_in",
                 jnp.sum(kv.astype(jnp.int32))),
                (f"{node.label}.rows_pass",
                 jnp.sum((kv & hit).astype(jnp.int32))),
            ])
            # null ONLY the key's validity where the filter proves the
            # key absent from the build — data bytes and every other
            # column untouched, so this is indistinguishable from the
            # key having been nulled by a WHERE upstream
            cols = list(tbl.columns)
            cols[node.key] = Column(
                col.dtype, col.data, col.valid_mask() & (hit | ~kv),
                chars=col.chars, children=col.children)
            out = (Table(cols), rv)
        elif isinstance(node, Sort):
            tbl, rv = ev(node.child)
            asc = None if node.ascending is None else list(node.ascending)
            nf = None if node.nulls_first is None else list(node.nulls_first)
            order = sort_order(tbl, list(node.keys), asc, nf, row_valid=rv)
            srt = gather(tbl, order)
            if rv is None:
                out = (srt, None)
            else:
                # phantoms ranked strictly last: the real prefix is the
                # staged sort, and the mask becomes positional again
                n = jnp.sum(rv.astype(jnp.int32))
                out = (srt,
                       jnp.arange(tbl.num_rows, dtype=jnp.int32) < n)
        elif isinstance(node, Limit):
            tbl, rv = ev(node.child)
            out = (_head(tbl, resolved[id(node)]), None)
        elif isinstance(node, Exchange):
            raise TypeError(
                "Exchange is a host boundary: it is only valid as a plan "
                "root (execute() routes it to runtime.exchange), never "
                "inside a fused/staged region")
        else:
            raise TypeError(f"not a plan node: {type(node).__name__}")
        env[id(node)] = out
        return out

    value, _ = ev(root)
    return value, side


def _limit_bound(nodes, resolved: dict, spaces: dict,
                 true_rows: dict) -> None:
    """Clamp Limit counts to the true row count of their space so the
    fused (padded) head matches the staged (exact) head shape."""
    for node in nodes:
        if isinstance(node, Limit):
            space = spaces[id(node.child)]
            if space is not None:
                resolved[id(node)] = min(resolved[id(node)],
                                         int(true_rows[space]))


def _slice_to(out, n: int):
    """Trim a padded leading dimension back to the true row count."""
    from spark_rapids_jni_tpu.runtime.dispatch import _slice_tree

    if isinstance(out, Table):
        rows = out.num_rows
    elif isinstance(out, Column):
        rows = out.size
    else:
        return out
    if rows == n:
        return out
    return _slice_tree(out, n, rows)


# ---------------------------------------------------------------------------
# runtime-filter planner pass
# ---------------------------------------------------------------------------


def _subtree_rows_estimate(node, bindings: dict) -> int:
    """Static upper-ish bound on the distinct keys a subtree can feed a
    bloom build: bound scan rows summed, and any interior join's resolved
    out_rows taken as a floor (a join can expand past its scans). Used
    only for gating and bits sizing — an overestimate just buys a larger,
    lower-FPP filter, never a wrong result."""
    rows = 0
    for n in _topo(node):
        if isinstance(n, Scan) and n.name in bindings:
            rows += int(bindings[n.name].num_rows)
    for n in _topo(node):
        if isinstance(n, Join):
            spec = n.out_rows
            if isinstance(spec, int):
                rows = max(rows, spec)
            elif (isinstance(spec, tuple) and len(spec) == 3
                    and spec[0] == "rows_of" and spec[1] in bindings):
                rows = max(rows,
                           int(bindings[spec[1]].num_rows) * int(spec[2]))
    return rows


def inject_runtime_filters(plan: Plan, bindings: dict) -> Plan:
    """The RuntimeFilter planner pass: for each single-key inner Join
    (either direction — the smaller side builds) and each DensePkJoin
    (build side fixed by the layout), ask the learned gate
    (``runtime/rtfilter.decide`` — every decision recorded with a
    reason) whether a bloom filter pays, and when it does, insert a
    :class:`BloomBuild` over the build child and a :class:`BloomProbe`
    over the probe child. The probe sits INSIDE the region — below the
    fusion boundary — so the pruned scan fuses with everything above it;
    chunked/out-of-core paths prune per chunk on the host side instead
    (``rtfilter.prune_chunk``), where compaction is free. Results are
    bit-identical with the pass on or off (see :class:`BloomProbe`);
    what changes is the dispatch fingerprint, so filtered and unfiltered
    plans never alias an executable."""
    from spark_rapids_jni_tpu.runtime import rtfilter

    root = plan.root
    done: set = set()
    while True:
        target = None
        for node in _topo(root):
            if isinstance(node, Join):
                if (node.how != "inner" or len(node.left_on) != 1
                        or len(node.right_on) != 1):
                    continue
                if node.label in done:
                    continue
                if isinstance(node.left, BloomProbe) \
                        or isinstance(node.right, BloomProbe):
                    done.add(node.label)
                    continue
                left_rows = _subtree_rows_estimate(node.left, bindings)
                right_rows = _subtree_rows_estimate(node.right, bindings)
                if right_rows <= left_rows:
                    sides = ("left", node.left, node.left_on[0],
                             node.right, node.right_on[0], right_rows)
                else:
                    sides = ("right", node.right, node.right_on[0],
                             node.left, node.left_on[0], left_rows)
                target = (node,) + sides
                break
            if isinstance(node, DensePkJoin):
                if node.label in done:
                    continue
                if isinstance(node.probe, BloomProbe):
                    done.add(node.label)
                    continue
                build_rows = _subtree_rows_estimate(node.build, bindings)
                target = (node, "probe", node.probe, node.probe_key,
                          node.build, node.build_key, build_rows)
                break
        if target is None:
            break
        node, side, probe_child, probe_key, build_child, build_key, \
            build_rows = target
        done.add(node.label)
        decision = rtfilter.decide(plan.name, node.label, build_rows)
        if not decision.apply:
            continue
        rtf_label = f"rtf_{node.label}"
        bb = BloomBuild(build_child, build_key, decision.num_bits,
                        decision.num_hashes, label=rtf_label)
        bp = BloomProbe(probe_child, bb, probe_key, decision.num_bits,
                        decision.num_hashes, label=rtf_label)
        if isinstance(node, DensePkJoin):
            new_node = node._replace(probe=bp)
        elif side == "left":
            new_node = node._replace(left=bp)
        else:
            new_node = node._replace(right=bp)
        root = replace_node(root, node, new_node)
    if root is plan.root:
        return plan
    return plan._replace(root=root)


def _harvest_rtfilter(plan: Plan, nodes, meta: dict) -> None:
    """Feed each probe's observed pass fraction back to the learned
    gate (no-op when the region produced tracers)."""
    probes = [n for n in nodes if isinstance(n, BloomProbe)]
    if not probes:
        return
    from spark_rapids_jni_tpu.runtime import rtfilter

    for n in probes:
        rtfilter.observe(plan.name, n.label,
                         meta.get(f"{n.label}.rows_in"),
                         meta.get(f"{n.label}.rows_pass"))


# ---------------------------------------------------------------------------
# the fuser
# ---------------------------------------------------------------------------


def split_at_exchange(plan: Plan):
    """Break a plan at its deepest INTERIOR ``Exchange`` node — the
    planner-placed exchange: regions already break at genuine host
    boundaries, and a mid-plan shuffle is one. Returns ``None`` when the
    plan has no interior Exchange (a root Exchange is the classic pack
    plan, handled by ``execute`` directly); otherwise
    ``(pack_plan, merge_plan, binding, exchange_node)`` where the pack
    plan roots the Exchange subtree and the merge plan is the remainder
    with the Exchange swapped for a ``Scan(binding)`` — exactly the
    hand-split plan pair shape ``QueryCluster.submit_exchange`` has
    always driven, derived instead of hand-written. Multi-exchange
    plans split one boundary at a time (deepest first); the remainder's
    own interior exchanges split recursively at execute time."""
    nodes = _topo(plan.root)
    xs = [n for n in nodes
          if isinstance(n, Exchange) and n is not plan.root]
    if not xs:
        return None
    x = xs[0]  # _topo is children-first: the deepest boundary splits first
    binding = f"__exchange__{x.label}"
    pack = Plan(f"{plan.name}.pack_{x.label}", x)
    merge = Plan(f"{plan.name}.merge_{x.label}",
                 replace_node(plan.root, x, Scan(binding)))
    return pack, merge, binding, x


def _trim_region_result(res: FusedResult, root) -> Table:
    """True-row slice of one per-destination merge-region result: an
    unbounded groupby root pads to its input row count, and only its
    ``<label>.num_groups`` rows are real."""
    from spark_rapids_jni_tpu.ops.table_ops import _slice_rows

    if isinstance(root, GroupBy) and root.max_groups is None:
        # region boundary: ``res`` is an already-executed region's
        # output, so reading its meta here cannot split a trace
        n = int(np.asarray(  # tpulint: disable=fusion-region-host-sync
            res.meta[f"{root.label}.num_groups"]))
        return _slice_rows(res.table, 0, n)
    return res.table


def _execute_midplan_exchange(plan: Plan, bindings: dict, *,
                              donate_inputs: bool,
                              force_staged: bool,
                              surface_pressure: bool,
                              cancel_token) -> FusedResult:
    """Execute a plan with an interior Exchange as region -> exchange ->
    region: run the pack half (an Exchange-rooted plan — the overflow
    ladder, valid_meta trim and wire form all apply unchanged), regroup
    the wire table per destination, run the remainder once per non-empty
    destination with the exchange output bound as its scan, and
    concatenate part-ordered. Destination key spaces are disjoint by
    construction, so the concatenation IS the plan's result —
    bit-identical to the hand-split (pack, merge) plan pair and to the
    ``exchange_local`` oracle over the same child output."""
    from spark_rapids_jni_tpu.ops.table_ops import _slice_rows, concatenate
    from spark_rapids_jni_tpu.runtime import exchange as _exchange

    pack_plan, merge_plan, binding, x = split_at_exchange(plan)
    pb, pe = _scan_names(_topo(x))
    pack_bindings = {n: bindings[n] for n in pb + pe if n in bindings}
    x = _exchange.resolve_auto_parts(pack_plan.name, x, pack_bindings)
    pack_plan = Plan(pack_plan.name, x)
    mb, me = _scan_names(_topo(merge_plan.root))
    merge_scans = (set(mb) | set(me)) - {binding}
    # the pack may only donate bindings the remainder never rereads
    donate_pack = (bool(donate_inputs)
                   and not (merge_scans & set(pack_bindings)))
    REGISTRY.counter("fusion.midplan_exchanges").inc()
    label, parts = x.label, int(x.parts)
    with spans.child(f"midplan.{plan.name}", label=label, parts=parts):
        fused = execute(pack_plan, pack_bindings,
                        donate_inputs=donate_pack,
                        force_staged=force_staged,
                        surface_pressure=surface_pressure,
                        cancel_token=cancel_token)
        rc = fused.meta[f"{label}.row_counts"]
        per_dest = _exchange.split_wire(fused.table, rc, parts)
        empty = _slice_rows(fused.table, 0, 0)
        merge_base = {n: bindings[n] for n in merge_scans
                      if n in bindings}
        outs: list = []
        for flights in per_dest:
            if not flights:
                continue
            dest_in = (flights[0] if len(flights) == 1
                       else concatenate(flights))
            res = execute(merge_plan, {**merge_base, binding: dest_in},
                          force_staged=force_staged,
                          surface_pressure=surface_pressure,
                          cancel_token=cancel_token)
            outs.append(_trim_region_result(res, merge_plan.root))
        if outs:
            tbl = outs[0] if len(outs) == 1 else concatenate(outs)
        else:
            res = execute(merge_plan, {**merge_base, binding: empty},
                          force_staged=force_staged,
                          surface_pressure=surface_pressure,
                          cancel_token=cancel_token)
            tbl = _slice_rows(res.table, 0, 0)
    meta = {
        f"{label}.parts": parts,
        f"{label}.rows": int(fused.meta[f"{label}.rows"]),
        f"{label}.dests": len(outs),
    }
    root = merge_plan.root
    if isinstance(root, GroupBy) and root.max_groups is None:
        # the concatenation is already trimmed: every row is real
        meta[f"{root.label}.num_groups"] = int(tbl.num_rows)
    return FusedResult(tbl, meta)


def execute(plan: Plan, bindings: dict, *,
            donate_inputs: bool = False,
            force_staged: bool = False,
            surface_pressure: bool = False,
            cancel_token=None) -> FusedResult:
    """Run one fusible region.

    ``bindings`` maps every Scan name to a Table. With ``fusion.enabled``
    the whole region dispatches as ONE callable through ``dispatch.call``
    (op name ``fusion.<plan.name>``): bucketed scans are the row groups,
    exact scans ride as aux args, and each per-op implementation inlines
    into the single trace. With fusion disabled — or when the bindings are
    tracers, dispatch is disabled, or compilation fails — the exact same
    node walk runs op-by-op (each op dispatching itself), which IS the
    staged reference path; results are bit-identical either way.

    ``donate_inputs=True`` declares every bound table dead after the call
    (intermediates the caller owns — never user-visible inputs); see the
    module docstring.

    ``force_staged=True`` takes the staged reference path for THIS call
    regardless of the global ``fusion.enabled`` option — the per-query
    knob the degradation ladder (runtime/degrade.py) steps a live query
    down on without flipping global state under concurrent sessions.
    ``surface_pressure=True`` lets PRESSURE-classified failures
    (``ResourceExhausted`` / ``CapacityOverflow``) that exhaust the retry
    budget propagate instead of silently taking the implicit staged
    fallback, so the degradation controller can take — and account for —
    the fused->staged step itself. Non-pressure failures keep the
    fallback either way.

    ``cancel_token`` (a ``resilience.CancelToken``) is checked at the
    region boundary before any compute or donation happens; cancellation
    raises ``QueryCancelled`` with the bound inputs untouched.
    """
    if cancel_token is not None:
        cancel_token.check(f"fusion.{plan.name}")
    if isinstance(plan.root, Exchange):
        # host boundary: partition-hash pack + wire framing happen outside
        # any fused region — runtime.exchange runs the child plan, then
        # packs per-destination flights on the host side of the seam
        from spark_rapids_jni_tpu.runtime import exchange as _exchange
        return _exchange.execute_exchange_root(
            plan, bindings,
            donate_inputs=donate_inputs,
            force_staged=force_staged,
            surface_pressure=surface_pressure,
            cancel_token=cancel_token)
    if split_at_exchange(plan) is not None:
        # planner-placed mid-plan exchange: break the region at the
        # interior Exchange and run region -> exchange -> region
        return _execute_midplan_exchange(
            plan, bindings,
            donate_inputs=donate_inputs,
            force_staged=force_staged,
            surface_pressure=surface_pressure,
            cancel_token=cancel_token)
    if get_option("rtfilter.enabled"):
        plan = inject_runtime_filters(plan, bindings)
    nodes = _topo(plan.root)
    bucketed, exact = _scan_names(nodes)
    for name in bucketed + exact:
        if name not in bindings:
            raise KeyError(f"plan {plan.name!r} scans unbound table "
                           f"{name!r}")
    true_rows = {name: bindings[name].num_rows for name in bucketed + exact}
    resolved = _resolve_statics(nodes, true_rows)
    spaces = _spaces(nodes)
    _limit_bound(nodes, resolved, spaces, true_rows)
    static_meta = {
        f"{n.label}.lowered": _planned_lowering(n)
        for n in nodes
        if isinstance(n, GroupBy) and n.domains is not None
    }
    side_keys = _side_keys(nodes)

    def _staged_eval() -> FusedResult:
        # the staged reference path (the bit-identity oracle): the same
        # node walk op-by-op, each op dispatching itself. The region seam
        # fires here too (seq=1; the fused attempt is seq=0) so chaos
        # scripts can kill each tier independently — per-op dispatch
        # failures below never propagate (dispatch falls back to the
        # host inline path), so this is the staged tier's one seam
        faults.fire("fusion.region", 1, plan=plan.name, staged=True)
        REGISTRY.counter("fusion.staged_regions").inc()
        with spans.child(f"region.{plan.name}", mode="staged"):
            tables = {name: bindings[name] for name in bucketed + exact}
            rvs = {name: None for name in tables}
            value, side = _eval_plan(plan.root, tables, rvs, resolved,
                                     true_rows)
        meta = dict(side)
        meta.update(static_meta)
        res = FusedResult(value, meta)
        _harvest_rtfilter(plan, nodes, res.meta)
        return res

    if force_staged or not get_option("fusion.enabled"):
        return _staged_eval()

    from spark_rapids_jni_tpu.runtime import dispatch

    REGISTRY.counter("fusion.regions").inc()
    REGISTRY.counter("fusion.nodes_fused").inc(len(nodes))

    row_args = tuple(bindings[name] for name in bucketed)
    aux_args = tuple(bindings[name] for name in exact)
    fingerprint = _fingerprint(nodes, resolved)

    def _region(row_args_, aux_args_, row_valids):
        rvs_ = row_valids if row_valids is not None \
            else (None,) * len(bucketed)
        tables = dict(zip(bucketed, row_args_))
        tables.update(zip(exact, aux_args_))
        rvmap = dict(zip(bucketed, rvs_))
        value, side = _eval_plan(plan.root, tables, rvmap, resolved,
                                 true_rows)
        return value, tuple(v for _, v in side)

    donate = (bool(donate_inputs) and bool(get_option("fusion.donate"))
              and bool(bucketed))

    def _dispatch_region():
        # the seam fires BEFORE dispatch.call touches (and possibly
        # donates) the bound buffers, so both the retry and the staged
        # fallback below replay against intact inputs
        faults.fire("fusion.region", 0, plan=plan.name)
        with spans.child(f"region.{plan.name}", mode="fused"):
            return dispatch.call(
                f"fusion.{plan.name}", _region, row_args, aux_args,
                statics=("fusion", fingerprint), slice_rows=False,
                donate_rows=donate)

    if resilience.enabled():
        out, exc = resilience.retry_or_none(
            f"fusion.{plan.name}", _dispatch_region,
            seam="fusion.region", rung="staged_fallback")
        if exc is not None:
            if not isinstance(exc, Exception):
                raise exc
            if surface_pressure:
                # the degradation controller owns tier transitions under
                # memory pressure: let the classified failure surface so
                # the step is taken — and accounted — at the ladder, not
                # silently here; anything else still falls back below
                kind = resilience.classify(exc)
                if kind is resilience.ResourceExhausted or issubclass(
                        kind, resilience.CapacityOverflow):
                    raise exc
            # final ladder rung: run the region through the staged
            # evaluator (bit-identical) and account for it
            record_fallback(
                f"fusion.{plan.name}",
                f"fused region dispatch failed "
                f"({type(exc).__name__}): staged evaluator fallback")
            return _staged_eval()
        value, side_vals = out
    else:
        value, side_vals = _dispatch_region()

    root_space = spaces[id(plan.root)]
    if root_space is not None:
        value = _slice_to(value, int(true_rows[root_space]))
    meta = dict(zip(side_keys, side_vals))
    meta.update(static_meta)
    _harvest_rtfilter(plan, nodes, meta)
    return FusedResult(value, meta)


def plan_fingerprint(plan: Plan, bindings: dict) -> tuple:
    """Canonical structural digest of a whole plan against its bound row
    counts — the plan-signature half of the result-cache key
    (runtime/resultcache.py). Deliberately excludes ``plan.name``: two
    plans that trace identically produce identical results, whatever they
    are called. Row-count-derived statics resolve (and Limit counts clamp)
    exactly as :func:`execute` resolves them, so a cached entry can never
    be replayed against a binding set the executable would have shaped
    differently — everything else row-dependent is covered by the input
    fingerprint half of the key."""
    nodes = _topo(plan.root)
    bucketed, exact = _scan_names(nodes)
    for name in bucketed + exact:
        if name not in bindings:
            raise KeyError(f"plan {plan.name!r} scans unbound table "
                           f"{name!r}")
    true_rows = {name: bindings[name].num_rows for name in bucketed + exact}
    resolved = _resolve_statics(nodes, true_rows)
    _limit_bound(nodes, resolved, _spaces(nodes), true_rows)
    return _fingerprint(nodes, resolved)


def scan_prefix_chains(root) -> list:
    """Maximal single-consumer chains of Filter / rowwise-Project nodes
    sitting directly on a bucketed Scan — the shareable scan+filter+project
    prefixes subplan caching keys on. Returns ``(scan, top, length)``
    tuples where ``top`` is the highest chain node and ``length`` counts
    the non-Scan nodes in it; ``top`` is never ``root`` itself (a whole-
    plan prefix is the final-result cache's job). Only mask-preserving
    nodes qualify: Filter nulls validity in place and a rowwise Project
    stays in the scan's row space, so the materialized chain output is a
    drop-in replacement table for any consumer."""
    nodes = _topo(root)
    consumers: dict = {}
    for node in nodes:
        for c in _children(node):
            consumers.setdefault(id(c), []).append(node)
    chains = []
    for node in nodes:
        if not (isinstance(node, Scan) and node.bucket):
            continue
        top, length = node, 0
        while True:
            nexts = consumers.get(id(top), [])
            if len(nexts) != 1 or nexts[0] is root:
                break
            nxt = nexts[0]
            if isinstance(nxt, Filter):
                pass
            elif isinstance(nxt, Project) and nxt.rowwise:
                pass
            else:
                break
            top, length = nxt, length + 1
        if length > 0:
            chains.append((node, top, length))
    return chains


def replace_node(root, target, replacement):
    """Rebuild the plan DAG with ``target`` (matched by object identity)
    swapped for ``replacement`` — the subplan-cache rewrite: a cached
    prefix's subtree becomes a Scan bound to the materialized
    intermediate. Shared nodes stay shared; untouched subtrees are reused
    as-is."""
    memo: dict = {id(target): replacement}

    def rebuild(node):
        if id(node) in memo:
            return memo[id(node)]
        kids = _children(node)
        new_kids = tuple(rebuild(c) for c in kids)
        if all(nk is k for nk, k in zip(new_kids, kids)):
            out = node
        elif isinstance(node, (Filter, Project, GroupBy, Sort, Limit,
                               BloomBuild, Exchange)):
            out = node._replace(child=new_kids[0])
        elif isinstance(node, Join):
            out = node._replace(left=new_kids[0], right=new_kids[1])
        elif isinstance(node, DensePkJoin):
            out = node._replace(probe=new_kids[0], build=new_kids[1])
        elif isinstance(node, BloomProbe):
            out = node._replace(child=new_kids[0], build=new_kids[1])
        else:  # pragma: no cover - Scan has no children to rebuild
            out = node
        memo[id(node)] = out
        return out

    return rebuild(root)


def estimate_hbm_bytes(plan: Plan, bindings: dict) -> int:
    """Plan-aware HBM footprint estimate for serving admission control.

    The inputs' exact device bytes plus the materialized output of every
    capacity-bearing node — joins at their resolved ``out_rows``, groupbys
    at their group budget — each costed at the inputs' mean row width. An
    estimate the admission gate reserves through the ``MemoryLimiter``,
    not a hard bound: ``runtime/server.py`` applies the configured
    ``server.estimate_headroom`` multiplier on top for intermediates this
    static walk cannot see.
    """
    from spark_rapids_jni_tpu.runtime.memory import _table_nbytes

    nodes = _topo(plan.root)
    bucketed, exact = _scan_names(nodes)
    for name in bucketed + exact:
        if name not in bindings:
            raise KeyError(f"plan {plan.name!r} scans unbound table "
                           f"{name!r}")
    true_rows = {name: bindings[name].num_rows for name in bucketed + exact}
    resolved = _resolve_statics(nodes, true_rows)
    input_bytes = sum(
        _table_nbytes(bindings[name]) for name in bucketed + exact)
    total_rows = max(1, sum(true_rows.values()))
    row_width = max(1, input_bytes // total_rows)
    out_rows = 0
    extra_bytes = 0
    for node in nodes:
        if isinstance(node, (Join, DensePkJoin)):
            out_rows += int(resolved[id(node)] or 0)
        elif isinstance(node, GroupBy):
            cap = resolved.get(id(node))
            out_rows += int(cap if cap is not None else node.budget)
        elif isinstance(node, BloomBuild):
            # byte-per-bit filter plus the (n, k) position scratch
            extra_bytes += int(node.num_bits)
        elif isinstance(node, Exchange):
            # destination-sorted pack materializes parts * capacity rows
            cap = resolved.get(id(node))
            if cap is not None:
                out_rows += int(node.parts) * int(cap)
    return int(input_bytes + out_rows * row_width + extra_bytes)


def _planned_lowering(node: GroupBy) -> str:
    """The static ``lowered`` plan fact, mirroring ``plan_groupby``'s
    eligibility check (it never depends on data)."""
    bounded_ok = (
        all(d is not None for d in node.domains)
        and all(op in ("sum", "count", "mean", "min", "max")
                for _, op in node.aggs)
        and int(np.prod([len(d.values) + 1 for d in node.domains]))
        <= node.budget
    )
    return "bounded" if bounded_ok else "general"


# ---------------------------------------------------------------------------
# introspection
# ---------------------------------------------------------------------------


def stats() -> dict:
    """Aggregate fusion counters for the bench ``fusion`` block:
    regions/nodes fused, executables per query (the
    ``dispatch.compile.fusion.<plan>`` counters), and donated bytes."""
    c = REGISTRY.counters("fusion.")
    d = REGISTRY.counters("dispatch.compile.fusion.")
    per_query = {
        name[len("dispatch.compile.fusion."):]: count
        for name, count in sorted(d.items())
    }
    return {
        "regions": c.get("fusion.regions", 0),
        "staged_regions": c.get("fusion.staged_regions", 0),
        "nodes_fused": c.get("fusion.nodes_fused", 0),
        "executables": sum(per_query.values()),
        "executables_per_query": per_query,
        "donated_bytes": REGISTRY.counters("dispatch.").get(
            "dispatch.donated_bytes", 0),
    }
