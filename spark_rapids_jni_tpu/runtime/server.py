"""Multi-query serving runtime: admission, fair scheduling, session budgets.

Every layer below this one executes exactly one query at a time; the
ROADMAP north star is heavy concurrent traffic on one shared device.
Sparkle (PAPERS.md) shows Spark-shaped work on a single shared machine is
won or lost at the admission/queueing layer; Flare shows that once kernels
are fused the marginal cost of a query is dominated by plan reuse — which
is exactly what the bucketed executable cache already gives concurrent
queries at ragged row counts. This module is the layer that cashes that
in: N sessions submit fusion plans (``runtime/fusion.py`` IR) and share
the dispatch executable cache, one ``MemoryLimiter``, and the pipeline's
shared decode pool.

Contracts, in order of importance:

* **No overcommit** — every query's HBM estimate is reserved through the
  shared ``MemoryLimiter`` BEFORE execution starts. A query whose
  estimate exceeds the whole budget, or whose session queue is full, is
  rejected at submit; one that merely does not fit *right now* waits its
  turn (the limiter's FIFO blocking reserve), bounded by
  ``server.admission_timeout_s``.
* **Fairness** — queued work is drained round-robin across sessions with
  at most ``server.max_inflight`` queries executing concurrently, so one
  heavy session cannot starve the rest: each scheduling turn takes the
  next session's oldest query, not the globally oldest.
* **Attribution** — end-to-end latency and queue wait land in per-session
  histograms (``server.latency_ms.<sid>`` / ``server.queue_wait_ms.<sid>``),
  admitted/queued/rejected/served/failed counters count per session and
  globally, and the whole execution runs inside
  ``telemetry.session_scope(sid)`` so fallback/spill/resilience events
  emitted by ANY inner layer carry ``session`` attribution.
* **No leaks** — a query that dies, however it dies, releases its
  reservation and its in-flight slot; the failure is classified through
  ``resilience.classify`` and recorded before the ticket resolves.

Config knobs (utils/config.py, env ``SPARK_RAPIDS_TPU_SERVER_*``):
``server.max_inflight``, ``server.hbm_budget_bytes``,
``server.admission_timeout_s``, ``server.queue_depth``,
``server.estimate_headroom``.
"""

from __future__ import annotations

import collections
import threading
import time
from typing import Any, Optional

from spark_rapids_jni_tpu.runtime import faults, fusion, pipeline, resilience
from spark_rapids_jni_tpu.runtime.memory import (
    HostTableChunk,
    MemoryLimiter,
    _table_nbytes,
)
from spark_rapids_jni_tpu.telemetry.events import (
    events as _ring_events,
    record_server,
    session_scope,
)
from spark_rapids_jni_tpu.telemetry.registry import REGISTRY
from spark_rapids_jni_tpu.utils.config import get_option
from spark_rapids_jni_tpu.utils.log import get_logger

__all__ = ["QueryRejected", "QueryTicket", "Session", "QueryServer"]

_log = get_logger("spark_rapids_jni_tpu.server")


class QueryRejected(RuntimeError):
    """Admission control refused the query: estimate over the whole
    budget, session queue full, admission timeout, or server shutdown."""


class QueryTicket:
    """One submitted query's future. Resolves to the plan's
    ``FusedResult`` (``result()``), a raised ``QueryRejected``, or the
    classified execution error. ``status`` walks
    queued -> admitted -> served | rejected | failed."""

    def __init__(self, session_id: str, plan: fusion.Plan, bindings: dict,
                 estimate: int, donate_inputs: bool):
        self.session = session_id
        self.plan = plan
        self.bindings = bindings
        self.estimate = int(estimate)
        self.donate_inputs = bool(donate_inputs)
        self.status = "queued"
        self.queue_wait_s: Optional[float] = None
        self.latency_s: Optional[float] = None
        self._submitted_at = time.monotonic()
        self._value: Any = None
        self._exc: Optional[BaseException] = None
        self._done = threading.Event()

    def done(self) -> bool:
        return self._done.is_set()

    def result(self, timeout: Optional[float] = None):
        if not self._done.wait(timeout):
            raise TimeoutError(
                f"query {self.plan.name!r} (session {self.session}) not "
                f"done within {timeout}s")
        if self._exc is not None:
            raise self._exc
        return self._value

    def _resolve(self, status: str, value: Any = None,
                 exc: Optional[BaseException] = None) -> None:
        self.status = status
        self._value = value
        self._exc = exc
        self._done.set()


class Session:
    """A client handle: submits against one session id on the server."""

    def __init__(self, server: "QueryServer", session_id: str):
        self._server = server
        self.session_id = session_id

    def submit(self, plan: fusion.Plan, bindings: dict, *,
               estimate_bytes: Optional[int] = None,
               donate_inputs: bool = False) -> QueryTicket:
        return self._server.submit(
            self.session_id, plan, bindings,
            estimate_bytes=estimate_bytes, donate_inputs=donate_inputs)

    def stats(self) -> dict:
        return self._server.session_stats(self.session_id)


class QueryServer:
    """The serving runtime. Construct, ``session(sid).submit(...)``,
    ``ticket.result()``; ``close()`` (or the context manager) drains the
    workers and rejects whatever is still queued."""

    def __init__(self, *,
                 limiter: Optional[MemoryLimiter] = None,
                 budget_bytes: Optional[int] = None,
                 max_inflight: Optional[int] = None,
                 admission_timeout_s: Optional[float] = None,
                 queue_depth: Optional[int] = None,
                 estimate_headroom: Optional[float] = None):
        if limiter is not None and budget_bytes is not None:
            raise ValueError("pass limiter OR budget_bytes, not both")
        self.limiter = limiter if limiter is not None else MemoryLimiter(
            int(budget_bytes if budget_bytes is not None
                else get_option("server.hbm_budget_bytes")))
        self.max_inflight = max(1, int(
            max_inflight if max_inflight is not None
            else get_option("server.max_inflight")))
        self.admission_timeout_s = float(
            admission_timeout_s if admission_timeout_s is not None
            else get_option("server.admission_timeout_s"))
        self.queue_depth = max(1, int(
            queue_depth if queue_depth is not None
            else get_option("server.queue_depth")))
        self.estimate_headroom = float(
            estimate_headroom if estimate_headroom is not None
            else get_option("server.estimate_headroom"))
        # every concurrent query shares ONE host decode/staging pool
        # (runtime/pipeline.py) instead of spinning a private executor
        self.decode_pool = pipeline.shared_decode_pool()
        self._cond = threading.Condition()
        self._queues: dict[str, collections.deque] = {}
        # round-robin ring over session ids, registration order
        self._ring: collections.deque = collections.deque()
        self._stop = threading.Event()
        self._closed = False
        self._workers = [
            threading.Thread(target=self._worker, daemon=True,
                             name=f"tpu-server-worker-{i}")
            for i in range(self.max_inflight)
        ]
        for w in self._workers:
            w.start()

    # -- client surface ------------------------------------------------------

    def session(self, session_id: str) -> Session:
        if not session_id or not str(session_id).strip():
            raise ValueError("session_id must be non-empty")
        sid = str(session_id)
        with self._cond:
            if sid not in self._queues:
                self._queues[sid] = collections.deque()
                self._ring.append(sid)
        return Session(self, sid)

    def submit(self, session_id: str, plan: fusion.Plan, bindings: dict, *,
               estimate_bytes: Optional[int] = None,
               donate_inputs: bool = False) -> QueryTicket:
        """Queue one query. Never blocks: over-the-whole-budget estimates
        and full session queues come back as immediately-rejected tickets
        (backpressure belongs to the client, not to unbounded memory)."""
        sid = str(session_id)
        self.session(sid)  # idempotent registration
        estimate = int(estimate_bytes) if estimate_bytes is not None \
            else self._default_estimate(plan, bindings)
        ticket = QueryTicket(sid, plan, bindings, estimate, donate_inputs)
        self._count("submitted", sid)
        record_server(plan.name, "submitted", session=sid,
                      estimate_bytes=estimate)
        if estimate > self.limiter.budget:
            self._reject(ticket,
                         f"estimate {estimate} exceeds the whole HBM "
                         f"budget ({self.limiter.budget}): can never fit")
            return ticket
        with self._cond:
            if self._closed:
                reject_why = "server closed"
            elif len(self._queues[sid]) >= self.queue_depth:
                reject_why = (f"session queue full "
                              f"({self.queue_depth} deep)")
            else:
                reject_why = None
                self._queues[sid].append(ticket)
                self._cond.notify()
        if reject_why is not None:
            self._reject(ticket, reject_why)
            return ticket
        self._count("queued", sid)
        record_server(plan.name, "queued", session=sid,
                      estimate_bytes=estimate)
        return ticket

    def close(self, timeout: Optional[float] = 30.0) -> None:
        """Stop accepting work, drain the workers, reject the backlog."""
        with self._cond:
            if self._closed:
                return
            self._closed = True
            self._stop.set()
            self._cond.notify_all()
        for w in self._workers:
            w.join(timeout)
        # whatever the workers never picked up resolves as rejected
        with self._cond:
            backlog = [t for q in self._queues.values() for t in q]
            for q in self._queues.values():
                q.clear()
        for t in backlog:
            self._reject(t, "server shutdown")

    def __enter__(self) -> "QueryServer":
        return self

    def __exit__(self, *exc) -> bool:
        self.close()
        return False

    # -- introspection -------------------------------------------------------

    def stats(self) -> dict:
        c = REGISTRY.counters("server.")
        lat = REGISTRY.histogram("server.latency_ms")
        wait = REGISTRY.histogram("server.queue_wait_ms")
        return {
            "submitted": c.get("server.submitted", 0),
            "queued": c.get("server.queued", 0),
            "admitted": c.get("server.admitted", 0),
            "served": c.get("server.served", 0),
            "rejected": c.get("server.rejected", 0),
            "failed": c.get("server.failed", 0),
            "latency_ms_p50": lat.percentile(50),
            "latency_ms_p95": lat.percentile(95),
            "queue_wait_ms_p50": wait.percentile(50),
            "queue_wait_ms_p95": wait.percentile(95),
            "reserved_bytes": self.limiter.used,
            "budget_bytes": self.limiter.budget,
            "sessions": sorted(self._queues),
        }

    def session_stats(self, session_id: str) -> dict:
        """Per-session attribution: counters, latency/queue-wait
        percentiles, and fallback/spill accounting from the telemetry
        ring (events stamped by ``session_scope`` during execution)."""
        sid = str(session_id)
        c = REGISTRY.counters("server.")
        lat = REGISTRY.histogram(f"server.latency_ms.{sid}")
        wait = REGISTRY.histogram(f"server.queue_wait_ms.{sid}")
        fallbacks = 0
        spills = 0
        resilience_events = 0
        for rec in _ring_events():
            if rec.get("session") != sid:
                continue
            kind = rec.get("kind")
            if kind == "fallback":
                fallbacks += 1
            elif kind == "spill":
                spills += 1
            elif kind == "resilience":
                resilience_events += 1
        return {
            "session": sid,
            "submitted": c.get(f"server.submitted.{sid}", 0),
            "queued": c.get(f"server.queued.{sid}", 0),
            "admitted": c.get(f"server.admitted.{sid}", 0),
            "served": c.get(f"server.served.{sid}", 0),
            "rejected": c.get(f"server.rejected.{sid}", 0),
            "failed": c.get(f"server.failed.{sid}", 0),
            "latency_ms_p50": lat.percentile(50),
            "latency_ms_p95": lat.percentile(95),
            "queue_wait_ms_p50": wait.percentile(50),
            "queue_wait_ms_p95": wait.percentile(95),
            "fallbacks": fallbacks,
            "spills": spills,
            "resilience_events": resilience_events,
        }

    # -- internals -----------------------------------------------------------

    def _count(self, event: str, sid: str) -> None:
        # unconditional (not gated on telemetry.enabled): admission
        # accounting must hold whether or not anyone is watching
        REGISTRY.counter(f"server.{event}").inc()
        REGISTRY.counter(f"server.{event}.{sid}").inc()

    def _default_estimate(self, plan: fusion.Plan, bindings: dict) -> int:
        """Headroom x the plan-aware input+output estimate; host-staged
        chunk bindings are costed at their exact device footprint."""
        if any(isinstance(v, HostTableChunk) for v in bindings.values()):
            base = sum(
                v.nbytes if isinstance(v, HostTableChunk)
                else _table_nbytes(v)
                for v in bindings.values())
        else:
            base = fusion.estimate_hbm_bytes(plan, bindings)
        return int(self.estimate_headroom * base)

    def _reject(self, ticket: QueryTicket, reason: str) -> None:
        self._count("rejected", ticket.session)
        record_server(ticket.plan.name, "rejected", session=ticket.session,
                      reason=reason, estimate_bytes=ticket.estimate)
        _log.warning("rejected %s (session %s): %s",
                     ticket.plan.name, ticket.session, reason)
        ticket._resolve("rejected", exc=QueryRejected(
            f"{ticket.plan.name} (session {ticket.session}): {reason}"))

    def _next_ticket(self) -> Optional[QueryTicket]:
        """Round-robin pop: the next session (in ring order after the
        previously scheduled one) that has queued work gives up its
        OLDEST query. Blocks until work arrives or the server stops."""
        with self._cond:
            while True:
                for _ in range(len(self._ring)):
                    sid = self._ring[0]
                    self._ring.rotate(-1)
                    q = self._queues.get(sid)
                    if q:
                        return q.popleft()
                if self._stop.is_set():
                    return None
                self._cond.wait(0.1)

    def _worker(self) -> None:
        while True:
            ticket = self._next_ticket()
            if ticket is None:
                return
            self._serve(ticket)

    def _stage_bindings(self, bindings: dict) -> dict:
        """Stage host-decoded chunk bindings to device tables on the
        SHARED decode pool, concurrently across tables. Runs after
        admission: the reservation already covers these bytes."""
        futs = {
            name: self.decode_pool.submit(val.stage)
            for name, val in bindings.items()
            if isinstance(val, HostTableChunk)
        }
        if not futs:
            return bindings
        staged = dict(bindings)
        for name, fut in futs.items():
            staged[name] = fut.result()
        return staged

    def _serve(self, ticket: QueryTicket) -> None:
        sid = ticket.session
        held = 0
        try:
            faults.fire("server.admit", 0, session=sid,
                        plan=ticket.plan.name)
            ok = self.limiter.reserve_blocking(
                ticket.estimate, cancel=self._stop,
                timeout=self.admission_timeout_s)
            if not ok:
                self._reject(
                    ticket,
                    "server shutdown" if self._stop.is_set()
                    else f"admission timeout "
                         f"({self.admission_timeout_s}s) waiting for "
                         f"{ticket.estimate} bytes")
                return
            held = ticket.estimate
            ticket.status = "admitted"
            ticket.queue_wait_s = time.monotonic() - ticket._submitted_at
            wait_ms = ticket.queue_wait_s * 1e3
            REGISTRY.histogram("server.queue_wait_ms").observe(wait_ms)
            REGISTRY.histogram(
                f"server.queue_wait_ms.{sid}").observe(wait_ms)
            self._count("admitted", sid)
            record_server(ticket.plan.name, "admitted", session=sid,
                          wait_ms=wait_ms, reserved_bytes=held)
            with session_scope(sid):
                faults.fire("server.execute", 0, session=sid,
                            plan=ticket.plan.name)
                bindings = self._stage_bindings(ticket.bindings)
                result = fusion.execute(
                    ticket.plan, bindings,
                    donate_inputs=ticket.donate_inputs)
            ticket.latency_s = time.monotonic() - ticket._submitted_at
            lat_ms = ticket.latency_s * 1e3
            REGISTRY.histogram("server.latency_ms").observe(lat_ms)
            REGISTRY.histogram(f"server.latency_ms.{sid}").observe(lat_ms)
            self._count("served", sid)
            record_server(ticket.plan.name, "served", session=sid,
                          wall_ms=lat_ms, wait_ms=ticket.queue_wait_s * 1e3)
            ticket._resolve("served", value=result)
        except BaseException as exc:
            # a dying query releases everything it holds (the finally
            # below) and resolves CLASSIFIED — never a silent wedge
            kind = resilience.classify(exc, seam="server.execute").__name__
            ticket.latency_s = time.monotonic() - ticket._submitted_at
            self._count("failed", sid)
            record_server(ticket.plan.name, "failed", session=sid,
                          error_kind=kind,
                          reason=str(exc) or type(exc).__name__)
            _log.warning("query %s (session %s) failed classified as %s",
                         ticket.plan.name, sid, kind)
            ticket._resolve("failed", exc=exc)
            if not isinstance(exc, Exception):
                raise  # KeyboardInterrupt etc: not the server's to absorb
        finally:
            if held:
                self.limiter.release(held)
