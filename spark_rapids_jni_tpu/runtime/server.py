"""Multi-query serving runtime: admission, fair scheduling, session budgets.

Every layer below this one executes exactly one query at a time; the
ROADMAP north star is heavy concurrent traffic on one shared device.
Sparkle (PAPERS.md) shows Spark-shaped work on a single shared machine is
won or lost at the admission/queueing layer; Flare shows that once kernels
are fused the marginal cost of a query is dominated by plan reuse — which
is exactly what the bucketed executable cache already gives concurrent
queries at ragged row counts. This module is the layer that cashes that
in: N sessions submit fusion plans (``runtime/fusion.py`` IR) and share
the dispatch executable cache, one ``MemoryLimiter``, and the pipeline's
shared decode pool.

Contracts, in order of importance:

* **No overcommit** — every query's HBM estimate is reserved through the
  shared ``MemoryLimiter`` BEFORE execution starts. A query whose
  estimate exceeds the whole budget, or whose session queue is full, is
  rejected at submit; one that merely does not fit *right now* waits its
  turn (the limiter's FIFO blocking reserve), bounded by
  ``server.admission_timeout_s``.
* **Fairness** — queued work is drained round-robin across sessions with
  at most ``server.max_inflight`` queries executing concurrently, so one
  heavy session cannot starve the rest: each scheduling turn takes the
  next session's oldest query, not the globally oldest.
* **Attribution** — end-to-end latency and queue wait land in per-session
  histograms (``server.latency_ms.<sid>`` / ``server.queue_wait_ms.<sid>``),
  admitted/queued/rejected/served/failed counters count per session and
  globally, and the whole execution runs inside
  ``telemetry.session_scope(sid)`` so fallback/spill/resilience events
  emitted by ANY inner layer carry ``session`` attribution.
* **No leaks** — a query that dies, however it dies, releases its
  reservation and its in-flight slot; the failure is classified through
  ``resilience.classify`` and recorded before the ticket resolves.
* **Bend, don't break** — classified pressure failures
  (``ResourceExhausted`` / ``CapacityOverflow`` beyond the retry budget)
  step the query down the bit-identical execution-tier ladder
  (``runtime/degrade.py``: fused -> staged -> out-of-core -> park) instead
  of killing it; the limiter's high watermark proactively spills the
  server's coldest SpillStore entries and pauses NEW admissions (in-flight
  queries keep draining) until usage falls below the low watermark.
* **Deadlines are cooperative** — ``server.deadline_ms`` (or a per-submit
  ``deadline_ms``) arms a ``CancelToken`` checked at region/chunk
  boundaries and inside the pipeline decode pool; expiry (or an explicit
  ``ticket.cancel()``) resolves the ticket ``cancelled`` with the
  classified ``QueryCancelled``, releasing reservation and queue slot in
  the same ``finally`` as every other exit.
* **Admission learns** — after each served query the measured working set
  (input + result device bytes) is blended (EMA, ``server.estimate_alpha``)
  into a per-plan-signature estimate that replaces the static
  ``fusion.estimate_hbm_bytes`` base for future submits, persisted
  crash-safely beside the dispatch persistent cache
  (``server.estimate_path``), so a fresh process admits from measured
  truth. Persistence is debounced off the hot path (at most one write
  per ``server.estimate_save_interval_s``; ``close()`` flushes).

Config knobs (utils/config.py, env ``SPARK_RAPIDS_TPU_SERVER_*``):
``server.max_inflight``, ``server.hbm_budget_bytes``,
``server.admission_timeout_s``, ``server.queue_depth``,
``server.estimate_headroom``, ``server.deadline_ms``,
``server.estimate_alpha``, ``server.estimate_path``,
``server.estimate_save_interval_s``; the ladder's own knobs are
``degrade.*`` (utils/config.py).
"""

from __future__ import annotations

import collections
import os
import threading

try:  # POSIX advisory locks for the shared learned-estimate file
    import fcntl
except ImportError:  # non-POSIX: merge-on-load still runs, unlocked
    fcntl = None  # type: ignore[assignment]
import time
import weakref
from typing import Any, Callable, Optional

from spark_rapids_jni_tpu.runtime import (
    degrade,
    faults,
    fusion,
    pipeline,
    resilience,
    resultcache,
)
from spark_rapids_jni_tpu.runtime.memory import (
    HostTableChunk,
    MemoryLimiter,
    SpillStore,
    _table_nbytes,
)
from spark_rapids_jni_tpu.telemetry.events import (
    events as _ring_events,
    record_degrade,
    record_integrity,
    record_server,
    session_scope,
)
from spark_rapids_jni_tpu.utils.atomic_io import atomic_write_json, load_json
from spark_rapids_jni_tpu.telemetry import spans
from spark_rapids_jni_tpu.telemetry.registry import REGISTRY
from spark_rapids_jni_tpu.utils.config import get_option
from spark_rapids_jni_tpu.utils.log import get_logger

__all__ = ["QueryRejected", "QueryTicket", "Session", "QueryServer",
           "live_servers", "register_warmup_builder", "warmup_builders"]

_log = get_logger("spark_rapids_jni_tpu.server")

# Open servers in this process, for live introspection: ``python -m
# spark_rapids_jni_tpu.telemetry top`` renders inspect() of each. Weak so
# the registry never keeps a dropped server (and its limiter) alive.
_LIVE_SERVERS: "weakref.WeakSet[QueryServer]" = weakref.WeakSet()


def live_servers() -> list:
    """The not-yet-closed QueryServers of this process."""
    return [s for s in list(_LIVE_SERVERS) if not s._closed]


# ---------------------------------------------------------------------------
# AOT warmup builders
# ---------------------------------------------------------------------------
#
# The learned-estimate file keys plans by SIGNATURE (``<plan>@<bucket>``)
# — exactly the granularity at which dispatch memoizes executables — so a
# fresh replica already knows which executables its predecessors spent
# the most HBM on. ``QueryServer.warmup`` replays the top-N signatures
# against synthetic inputs at the signature's bucket BEFORE the replica
# advertises boot_ok, converting first-query compile stalls into boot
# work. A builder takes the bucket row count and runs its plan end to end
# (filling the dispatch/fusion executable caches); models register
# builders for the plans they own (models/tpch.py).

_WARMUP_BUILDERS: dict = {}


def register_warmup_builder(plan_name: str, builder: Callable[[int], Any],
                            ) -> None:
    """Register the warmup entrypoint for one plan name. ``builder(rows)``
    must build synthetic bindings at ``rows`` input rows and execute the
    plan through its normal path; its return value is discarded."""
    if not plan_name or not str(plan_name).strip():
        raise ValueError("register_warmup_builder: plan_name is required")
    if not callable(builder):
        raise TypeError(f"warmup builder for {plan_name!r} is not callable")
    _WARMUP_BUILDERS[str(plan_name)] = builder


def warmup_builders() -> dict:
    """Snapshot of the registered warmup builders (name -> callable)."""
    return dict(_WARMUP_BUILDERS)


class QueryRejected(RuntimeError):
    """Admission control refused the query: estimate over the whole
    budget, session queue full, admission timeout, or server shutdown.

    Structured context rides on the exception so clients can react
    programmatically instead of parsing the message: ``session``,
    ``reason``, ``queue_depth`` (entries waiting in the session's queue
    at rejection), ``bytes_requested`` vs ``bytes_available`` (the
    limiter's free bytes at rejection), and ``retry_after_s`` — the
    server's backoff suggestion (``None`` means retrying can never
    succeed, e.g. an estimate larger than the whole budget).
    ``flight_record`` is the path of the flight-recorder artifact dumped
    at rejection (None when the recorder is disabled or the rejection
    happened before a span tree existed)."""

    def __init__(self, message: str, *,
                 session: str = "",
                 reason: str = "",
                 queue_depth: int = 0,
                 bytes_requested: int = 0,
                 bytes_available: int = 0,
                 retry_after_s: Optional[float] = None,
                 flight_record: Optional[str] = None):
        super().__init__(message)
        self.session = session
        self.reason = reason
        self.queue_depth = int(queue_depth)
        self.bytes_requested = int(bytes_requested)
        self.bytes_available = int(bytes_available)
        self.retry_after_s = retry_after_s
        self.flight_record = flight_record


class QueryTicket:
    """One submitted query's future. Resolves to the plan's
    ``FusedResult`` (``result()``), a raised ``QueryRejected``, the
    classified ``QueryCancelled`` (deadline expiry or ``cancel()``), or
    the classified execution error. ``status`` walks
    queued -> admitted -> served | rejected | cancelled | failed."""

    def __init__(self, session_id: str, plan: fusion.Plan, bindings: dict,
                 estimate: int, donate_inputs: bool,
                 deadline_ms: int = 0,
                 outofcore: Optional[Callable] = None):
        self.session = session_id
        self.plan = plan
        self.bindings = bindings
        self.estimate = int(estimate)
        self.donate_inputs = bool(donate_inputs)
        self.outofcore = outofcore
        # (signature, input fingerprint) — set by submit when the result
        # cache is on; the serve path populates the cache under it
        self.cache_key = None
        # the deadline clock starts at SUBMIT: queue wait counts against
        # it, so a query stuck behind a backlog cancels instead of running
        # pointlessly after its client gave up
        self.deadline_ms = int(deadline_ms)
        self.cancel_token = resilience.CancelToken(
            self.deadline_ms, label=f"{plan.name}/{session_id}")
        self.status = "queued"
        self.queue_wait_s: Optional[float] = None
        self.latency_s: Optional[float] = None
        self._submitted_at = time.monotonic()
        self._value: Any = None
        self._exc: Optional[BaseException] = None
        self._done = threading.Event()

    def cancel(self, reason: str = "client cancel") -> None:
        """Cooperatively cancel: the query stops at its next region/chunk
        boundary (or decode-pool checkpoint), releases everything it
        holds, and the ticket resolves ``cancelled``."""
        self.cancel_token.cancel(reason)

    def done(self) -> bool:
        return self._done.is_set()

    def result(self, timeout: Optional[float] = None):
        if not self._done.wait(timeout):
            raise TimeoutError(
                f"query {self.plan.name!r} (session {self.session}) not "
                f"done within {timeout}s")
        if self._exc is not None:
            raise self._exc
        return self._value

    def _resolve(self, status: str, value: Any = None,
                 exc: Optional[BaseException] = None) -> None:
        self.status = status
        self._value = value
        self._exc = exc
        self._done.set()


class Session:
    """A client handle: submits against one session id on the server."""

    def __init__(self, server: "QueryServer", session_id: str):
        self._server = server
        self.session_id = session_id

    def submit(self, plan: fusion.Plan, bindings: dict, *,
               estimate_bytes: Optional[int] = None,
               donate_inputs: bool = False,
               deadline_ms: Optional[int] = None,
               outofcore: Optional[Callable] = None,
               cache_fingerprint: Optional[str] = None) -> QueryTicket:
        return self._server.submit(
            self.session_id, plan, bindings,
            estimate_bytes=estimate_bytes, donate_inputs=donate_inputs,
            deadline_ms=deadline_ms, outofcore=outofcore,
            cache_fingerprint=cache_fingerprint)

    def stats(self) -> dict:
        return self._server.session_stats(self.session_id)


class QueryServer:
    """The serving runtime. Construct, ``session(sid).submit(...)``,
    ``ticket.result()``; ``close()`` (or the context manager) drains the
    workers and rejects whatever is still queued."""

    def __init__(self, *,
                 limiter: Optional[MemoryLimiter] = None,
                 budget_bytes: Optional[int] = None,
                 max_inflight: Optional[int] = None,
                 admission_timeout_s: Optional[float] = None,
                 queue_depth: Optional[int] = None,
                 estimate_headroom: Optional[float] = None):
        if limiter is not None and budget_bytes is not None:
            raise ValueError("pass limiter OR budget_bytes, not both")
        self.limiter = limiter if limiter is not None else MemoryLimiter(
            int(budget_bytes if budget_bytes is not None
                else get_option("server.hbm_budget_bytes")))
        self.max_inflight = max(1, int(
            max_inflight if max_inflight is not None
            else get_option("server.max_inflight")))
        self.admission_timeout_s = float(
            admission_timeout_s if admission_timeout_s is not None
            else get_option("server.admission_timeout_s"))
        self.queue_depth = max(1, int(
            queue_depth if queue_depth is not None
            else get_option("server.queue_depth")))
        self.estimate_headroom = float(
            estimate_headroom if estimate_headroom is not None
            else get_option("server.estimate_headroom"))
        # every concurrent query shares ONE host decode/staging pool
        # (runtime/pipeline.py) instead of spinning a private executor
        self.decode_pool = pipeline.shared_decode_pool()
        # the server-owned spill store backs degraded queries' partials
        # AND is the limiter's proactive-spill target when the high
        # watermark trips (memory.py)
        self.spill_store = SpillStore(self.limiter.budget)
        self.limiter.attach_spill_store(self.spill_store)
        self.degrader = degrade.DegradationController(self.limiter)
        # plan-signature result & subplan cache (runtime/resultcache.py):
        # entries ride the server's spill store under the integrity.cache
        # seam and are byte-charged against the shared limiter; attaching
        # makes them the FIRST thing high-watermark pressure evicts (and
        # discounts them from parked queries' drain thresholds). All hot-
        # path probes gate on ``cache.enabled`` — off is byte-for-byte
        # today's serving path
        self.result_cache = resultcache.ResultCache(
            self.spill_store, self.limiter)
        self.limiter.attach_result_cache(self.result_cache)
        # learned admission: plan signature -> EMA of measured working-set
        # bytes, loaded from (and written through to) the crash-safe state
        # file beside the dispatch persistent cache
        self._learned_lock = threading.Lock()
        self._learned: dict[str, float] = {}
        self._learned_dirty = False
        self._last_save: Optional[float] = None  # None = never saved
        self._estimate_path = self._resolve_estimate_path()
        self._load_learned()
        self._cond = threading.Condition()
        self._queues: dict[str, collections.deque] = {}
        # round-robin ring over session ids, registration order
        self._ring: collections.deque = collections.deque()
        # live introspection: ticket id -> {ticket, span, tier, rung, ...}
        # maintained by _serve (register/deregister in its try/finally)
        # and updated by the degrade observer; inspect() snapshots it
        self._inflight: dict[int, dict] = {}
        self._inflight_lock = threading.Lock()
        # resident registered tables (the mesh's shard store): name ->
        # (table, fingerprint). Shard-step submits bind these by name so
        # the query ships to the data, not the data to the query.
        self._registered: dict[str, tuple] = {}
        self._registered_lock = threading.Lock()
        self._stop = threading.Event()
        self._closed = False
        self._draining = False
        _LIVE_SERVERS.add(self)
        self._workers = [
            threading.Thread(target=self._worker, daemon=True,
                             name=f"tpu-server-worker-{i}")
            for i in range(self.max_inflight)
        ]
        for w in self._workers:
            w.start()

    # -- client surface ------------------------------------------------------

    def session(self, session_id: str) -> Session:
        if not session_id or not str(session_id).strip():
            raise ValueError("session_id must be non-empty")
        sid = str(session_id)
        with self._cond:
            if sid not in self._queues:
                self._queues[sid] = collections.deque()
                self._ring.append(sid)
        return Session(self, sid)

    def register_table(self, name: str, table) -> str:
        """Install a resident table for shard-step submits (the mesh's
        "ship the query to the shard" surface): subsequent queries bind
        it by name via :meth:`registered_table` so only the plan — not
        the shard's bytes — rides each submit. Returns the table's
        content fingerprint, the input half of the idempotency pair a
        supervisor verifies across hosts and failovers. Re-registering
        a name replaces it (re-homed shards after a host death)."""
        if not name or not str(name).strip():
            raise ValueError("registered table name must be non-empty")
        fp = resultcache.table_fingerprint(table)
        with self._registered_lock:
            self._registered[str(name)] = (table, fp)
        record_server("server", "registered", session="_cluster",
                      table=str(name), rows=int(table.num_rows),
                      fingerprint=fp)
        return fp

    def registered_table(self, name: str):
        """The resident table registered under ``name`` (KeyError when
        absent — the caller classifies)."""
        with self._registered_lock:
            return self._registered[str(name)][0]

    def registered_fingerprint(self, name: str) -> str:
        with self._registered_lock:
            return self._registered[str(name)][1]

    def submit(self, session_id: str, plan: fusion.Plan, bindings: dict, *,
               estimate_bytes: Optional[int] = None,
               donate_inputs: bool = False,
               deadline_ms: Optional[int] = None,
               outofcore: Optional[Callable] = None,
               cache_fingerprint: Optional[str] = None) -> QueryTicket:
        """Queue one query. Never blocks: over-the-whole-budget estimates
        and full session queues come back as immediately-rejected tickets
        (backpressure belongs to the client, not to unbounded memory).

        ``deadline_ms`` (default ``server.deadline_ms``; 0 = none) arms the
        ticket's :class:`~.resilience.CancelToken` from SUBMIT time.
        ``outofcore`` optionally supplies the degradation ladder's rung-2
        runner factory, ``(bindings, limiter) -> (chunk_rows, token) ->
        Table`` (see ``degrade.row_chunked_tier``); without it the ladder
        for this query is fused -> staged -> parked.

        With ``cache.enabled``, a submission whose ``(plan signature,
        input fingerprint)`` matches a cached result resolves served
        IMMEDIATELY — no admission, no compile, no execution; the hit is
        visible as a ``cache.hit`` span under the query's root span.
        ``cache_fingerprint`` overrides the content digest of the
        bindings (e.g. a :func:`resultcache.source_fingerprint` the
        client maintains for file-backed scans) — changing it is the
        invalidation handle."""
        sid = str(session_id)
        self.session(sid)  # idempotent registration
        estimate = int(estimate_bytes) if estimate_bytes is not None \
            else self._default_estimate(plan, bindings)
        ddl = int(deadline_ms if deadline_ms is not None
                  else get_option("server.deadline_ms"))
        ticket = QueryTicket(sid, plan, bindings, estimate, donate_inputs,
                             deadline_ms=ddl, outofcore=outofcore)
        self._count("submitted", sid)
        record_server(plan.name, "submitted", session=sid,
                      estimate_bytes=estimate)
        if resultcache.enabled():
            try:
                ticket.cache_key = resultcache.cache_key(
                    plan, bindings, fingerprint=cache_fingerprint)
            except (ValueError, KeyError, TypeError):
                # unfingerprintable plan/bindings (local callables,
                # non-table bindings): serve normally, never cache
                ticket.cache_key = None
            if ticket.cache_key is not None:
                hit = self.result_cache.get(ticket.cache_key)
                if hit is not None:
                    self._serve_hit(ticket, hit)
                    return ticket
        if estimate > self.limiter.budget:
            self._reject(ticket,
                         f"estimate {estimate} exceeds the whole HBM "
                         f"budget ({self.limiter.budget}): can never fit",
                         retry_after_s=None)
            return ticket
        with self._cond:
            if self._closed:
                reject_why = "server closed"
                retry_after: Optional[float] = None
            elif self._draining:
                reject_why = "server draining"
                retry_after = None
            elif len(self._queues[sid]) >= self.queue_depth:
                reject_why = (f"session queue full "
                              f"({self.queue_depth} deep)")
                # the queue drains roughly one p50 latency per entry; a
                # zero histogram (cold server) suggests a short poll
                p50 = REGISTRY.histogram("server.latency_ms").percentile(50)
                retry_after = max(0.05, float(p50 or 0.0) / 1e3)
            else:
                reject_why = None
                retry_after = None
                self._queues[sid].append(ticket)
                self._cond.notify()
        if reject_why is not None:
            self._reject(ticket, reject_why, retry_after_s=retry_after)
            return ticket
        self._count("queued", sid)
        record_server(plan.name, "queued", session=sid,
                      estimate_bytes=estimate)
        return ticket

    def close(self, timeout: Optional[float] = 30.0) -> None:
        """Stop accepting work, drain the workers, reject the backlog."""
        with self._cond:
            if self._closed:
                return
            self._closed = True
            self._stop.set()
            self._cond.notify_all()
        for w in self._workers:
            w.join(timeout)
        # whatever the workers never picked up resolves as rejected
        with self._cond:
            backlog = [t for q in self._queues.values() for t in q]
            for q in self._queues.values():
                q.clear()
        for t in backlog:
            self._reject(t, "server shutdown")
        # drop cached entries and release their limiter charges before
        # anyone inspects the limiter for leaks
        self.result_cache.close()
        self._save_learned()

    def drain(self, timeout: Optional[float] = 30.0) -> dict:
        """Graceful drain: stop admitting (new submits reject with
        "server draining"), let every queued and in-flight query finish,
        then flush learned estimates to the shared state file. The
        server object stays alive — the fleet supervisor drains a
        replica before recycling it so a warm restart (shared JAX
        persistent compile cache + merged learned estimates) loses no
        state. Returns ``{"drained": bool, "inflight": n, "queued": n}``
        — ``drained=False`` means the timeout expired with work still
        running (the caller decides whether to wait more or kill)."""
        with self._cond:
            self._draining = True
        deadline = (None if timeout is None
                    else time.monotonic() + float(timeout))
        while True:
            with self._cond:
                queued = sum(len(q) for q in self._queues.values())
            with self._inflight_lock:
                inflight = len(self._inflight)
            if queued == 0 and inflight == 0:
                break
            if deadline is not None and time.monotonic() >= deadline:
                break
            time.sleep(0.01)
        self.flush_learned()
        record_server("server", "drained", session="_fleet",
                      inflight=inflight, queued=queued)
        return {"drained": queued == 0 and inflight == 0,
                "inflight": inflight, "queued": queued}

    def flush_learned(self) -> None:
        """Force-persist learned estimates now, ignoring the debounce
        interval (drain/recycle hook: the successor replica warm-starts
        off this file)."""
        self._save_learned()

    def warmup(self, top_n: Optional[int] = None) -> dict:
        """AOT-precompile the ``top_n`` costliest learned plan signatures
        (by estimated working set, descending) before serving traffic.

        Each signature ``<plan>@<bucket>`` replays through its registered
        warmup builder (:func:`register_warmup_builder`) at exactly the
        signature's bucket rows, so the executables a first query would
        stall compiling are already in the dispatch cache — the fleet
        replica boot hook (runtime/fleet.py) runs this before ``boot_ok``
        when ``server.warmup_top_n`` > 0. Warmup NEVER fails boot: a
        signature with no registered builder is skipped (counted under
        ``server.warmup_skipped``), a builder that raises is counted
        under ``server.warmup_failed`` and logged, and the summary dict
        reports attempted/compiled/skipped/failed either way."""
        if top_n is None:
            top_n = int(get_option("server.warmup_top_n"))
        summary = {"attempted": 0, "compiled": 0, "skipped": 0, "failed": 0}
        if top_n <= 0:
            return summary
        with self._learned_lock:
            ranked = sorted(self._learned.items(), key=lambda kv: -kv[1])
        for sig, _est in ranked[:int(top_n)]:
            name, _, bucket = sig.rpartition("@")
            builder = _WARMUP_BUILDERS.get(name)
            if builder is None or not bucket.isdigit() or int(bucket) <= 0:
                summary["skipped"] += 1
                REGISTRY.counter("server.warmup_skipped").inc()
                continue
            summary["attempted"] += 1
            try:
                with spans.span(f"warmup.{name}", rows=int(bucket)):
                    builder(int(bucket))
            except Exception as exc:
                # a warmup miss costs the first real query a compile,
                # never the boot — same posture as learned-state I/O
                summary["failed"] += 1
                REGISTRY.counter("server.warmup_failed").inc()
                _log.warning("warmup of %s failed: %s", sig, exc)
            else:
                summary["compiled"] += 1
                REGISTRY.counter("server.warmup_compiled").inc()
        return summary

    def __enter__(self) -> "QueryServer":
        return self

    def __exit__(self, *exc) -> bool:
        self.close()
        return False

    # -- introspection -------------------------------------------------------

    def stats(self) -> dict:
        c = REGISTRY.counters("server.")
        lat = REGISTRY.histogram("server.latency_ms")
        wait = REGISTRY.histogram("server.queue_wait_ms")
        return {
            "submitted": c.get("server.submitted", 0),
            "queued": c.get("server.queued", 0),
            "admitted": c.get("server.admitted", 0),
            "served": c.get("server.served", 0),
            "rejected": c.get("server.rejected", 0),
            "cancelled": c.get("server.cancelled", 0),
            "failed": c.get("server.failed", 0),
            "latency_ms_p50": lat.percentile(50),
            "latency_ms_p95": lat.percentile(95),
            "queue_wait_ms_p50": wait.percentile(50),
            "queue_wait_ms_p95": wait.percentile(95),
            "reserved_bytes": self.limiter.used,
            "budget_bytes": self.limiter.budget,
            "pressure_crossings": self.limiter.pressure_crossings,
            "degrade_steps": REGISTRY.counters("degrade.").get(
                "degrade.step", 0),
            "learned_signatures": len(self._learned),
            "sessions": sorted(self._queues),
            "cache": self.result_cache.stats(),
        }

    def inspect(self) -> dict:
        """Live serving introspection: every in-flight query with its
        current span (the deepest open node of its tree), degradation
        tier/rung, held bytes, deadline remaining and age, plus queue
        depths and the limiter's watermark state. Pure host-side reads —
        safe to call from any thread at any time; rendered by
        ``python -m spark_rapids_jni_tpu.telemetry top``."""
        with self._cond:
            queues = {sid: len(q) for sid, q in self._queues.items()}
        with self._inflight_lock:
            infos = [dict(i) for i in self._inflight.values()]
        now = time.monotonic()
        inflight = []
        for info in infos:
            ticket = info["ticket"]
            sp = info.get("span")
            current = None
            if isinstance(sp, spans.Span):
                deepest = sp.deepest_open()
                current = deepest.name if deepest is not None else None
            inflight.append({
                "session": info["session"],
                "plan": info["plan"],
                "status": ticket.status,
                "tier": info["tier"],
                "rung": info["rung"],
                "steps": info["steps"],
                "chunk_rows": info["chunk_rows"],
                "held_bytes": info["held_bytes"],
                "age_s": round(now - ticket._submitted_at, 3),
                "deadline_remaining_s": ticket.cancel_token.remaining_s(),
                "current_span": current,
            })
        return {
            "inflight": sorted(inflight,
                               key=lambda q: (q["session"], -q["age_s"])),
            "queues": dict(sorted(queues.items())),
            "queued": sum(queues.values()),
            "max_inflight": self.max_inflight,
            "limiter": self.limiter.watermarks(),
            "spill": self.spill_store.stats(),
            "cache": self.result_cache.stats(),
            "closed": self._closed,
        }

    def session_stats(self, session_id: str) -> dict:
        """Per-session attribution: counters, latency/queue-wait
        percentiles, and fallback/spill accounting from the telemetry
        ring (events stamped by ``session_scope`` during execution)."""
        sid = str(session_id)
        c = REGISTRY.counters("server.")
        lat = REGISTRY.histogram(f"server.latency_ms.{sid}")
        wait = REGISTRY.histogram(f"server.queue_wait_ms.{sid}")
        fallbacks = 0
        spills = 0
        resilience_events = 0
        degrades = 0
        for rec in _ring_events():
            if rec.get("session") != sid:
                continue
            kind = rec.get("kind")
            if kind == "fallback":
                fallbacks += 1
            elif kind == "spill":
                spills += 1
            elif kind == "resilience":
                resilience_events += 1
            elif kind == "degrade" and rec.get("event") == "step":
                degrades += 1
        return {
            "session": sid,
            "submitted": c.get(f"server.submitted.{sid}", 0),
            "queued": c.get(f"server.queued.{sid}", 0),
            "admitted": c.get(f"server.admitted.{sid}", 0),
            "served": c.get(f"server.served.{sid}", 0),
            "rejected": c.get(f"server.rejected.{sid}", 0),
            "cancelled": c.get(f"server.cancelled.{sid}", 0),
            "failed": c.get(f"server.failed.{sid}", 0),
            "latency_ms_p50": lat.percentile(50),
            "latency_ms_p95": lat.percentile(95),
            "queue_wait_ms_p50": wait.percentile(50),
            "queue_wait_ms_p95": wait.percentile(95),
            "fallbacks": fallbacks,
            "spills": spills,
            "resilience_events": resilience_events,
            "degrade_steps": degrades,
        }

    # -- internals -----------------------------------------------------------

    def _count(self, event: str, sid: str) -> None:
        # unconditional (not gated on telemetry.enabled): admission
        # accounting must hold whether or not anyone is watching
        REGISTRY.counter(f"server.{event}").inc()
        REGISTRY.counter(f"server.{event}.{sid}").inc()

    # -- adaptive admission --------------------------------------------------

    @staticmethod
    def _resolve_estimate_path() -> str:
        """Where learned estimates persist: ``server.estimate_path`` if
        set, else ``learned_estimates.json`` beside the dispatch
        persistent cache; empty (in-memory only) when neither exists."""
        explicit = str(get_option("server.estimate_path") or "")
        if explicit:
            return explicit
        cache_dir = os.environ.get("SPARK_RAPIDS_TPU_DISPATCH_CACHE") or str(
            get_option("dispatch.persistent_cache_dir") or "")
        if cache_dir:
            return os.path.join(cache_dir, "learned_estimates.json")
        return ""

    def _read_learned_file(self) -> Optional[dict]:
        """Read + sanitize the shared estimate file. ``None`` = nothing
        usable (absent, or corrupt — counted and discarded)."""
        state, corrupt = load_json(self._estimate_path)
        if corrupt is not None:
            # a crash mid-write can't produce this (atomic replace), but
            # disk rot / manual edits can: discard, count, keep serving
            REGISTRY.counter("server.estimate_state_discarded").inc()
            record_degrade("server.learned_estimates", "state_discarded",
                           tier="persistent", trigger="corrupt", rung=0,
                           path=self._estimate_path, reason=corrupt)
            return None
        if not isinstance(state, dict):
            return None
        return {
            str(k): float(v) for k, v in state.items()
            if isinstance(v, (int, float)) and float(v) > 0
        }

    @staticmethod
    def _merge_learned(mine: dict, disk: dict) -> dict:
        """Per-signature EMA-combine of two estimate maps: a signature
        known to only one side transfers verbatim; one known to both
        blends 50/50 (each side's value is already an EMA of its own
        measurements, so the blend is a fair co-estimate, and repeated
        merge cycles converge instead of oscillating)."""
        merged = dict(disk)
        for sig, mine_v in mine.items():
            disk_v = merged.get(sig)
            merged[sig] = float(mine_v) if disk_v is None \
                else 0.5 * float(mine_v) + 0.5 * float(disk_v)
        return merged

    def _load_learned(self) -> None:
        if not self._estimate_path:
            return
        disk = self._read_learned_file()
        if disk is None:
            return
        with self._learned_lock:
            # merge, don't replace: N replicas share one state file, and
            # a reload must never discard what this process has measured
            self._learned = self._merge_learned(self._learned, disk)

    def _save_learned(self) -> None:
        if not self._estimate_path:
            return
        with self._learned_lock:
            if not self._learned_dirty:
                return
            snapshot = dict(self._learned)
            self._learned_dirty = False
        self._last_save = time.monotonic()
        # N replica processes debounce-write this file concurrently; a
        # bare tmp+replace is last-writer-wins and clobbers every other
        # replica's learning. Serialize writers with an fcntl lock on a
        # sidecar (the data file itself is replaced, so locking it would
        # lock a dead inode) and merge-on-load inside the critical
        # section: read what the last writer left, EMA-combine per
        # signature, then atomically replace.
        lock_fh = None
        try:
            if fcntl is not None:
                lock_fh = open(self._estimate_path + ".lock", "a")
                fcntl.flock(lock_fh.fileno(), fcntl.LOCK_EX)
            disk = self._read_learned_file()
            merged = self._merge_learned(snapshot, disk or {})
            atomic_write_json(self._estimate_path, merged)
        except OSError as exc:
            # warm-start state is an optimization; losing a write only
            # costs the next process a cold estimate, never a query —
            # but stay dirty so close() (or the next interval) retries
            with self._learned_lock:
                self._learned_dirty = True
            REGISTRY.counter("server.estimate_state_write_error").inc()
            _log.warning("could not persist learned estimates to %s: %s",
                         self._estimate_path, exc)
        else:
            with self._learned_lock:
                # adopt signatures sibling replicas learned (disk-only
                # keys) so this replica's admission warms too; our own
                # EMAs keep their in-memory values
                for sig, v in merged.items():
                    self._learned.setdefault(sig, float(v))
        finally:
            if lock_fh is not None:
                try:
                    fcntl.flock(lock_fh.fileno(), fcntl.LOCK_UN)
                finally:
                    lock_fh.close()

    @staticmethod
    def _plan_signature(plan: fusion.Plan, bindings: dict) -> str:
        """Plan name + pow2 bucket of total input rows: the granularity at
        which measured working sets transfer between queries (matches the
        dispatch bucketing, so same-signature queries share executables
        AND footprints)."""
        rows = 0
        for v in bindings.values():
            rows += int(getattr(v, "num_rows", 0) or 0)
        bucket = 1 << max(rows - 1, 0).bit_length() if rows else 0
        return f"{plan.name}@{bucket}"

    def _record_actual(self, ticket: QueryTicket, bindings: dict,
                       result) -> None:
        """Blend this query's measured working set (input + result device
        bytes — the floor on its true peak; headroom covers
        intermediates) into the signature's EMA. Persistence is
        debounced: at most one fsynced write per
        ``server.estimate_save_interval_s`` on the serving path (the
        first learn saves immediately; ``close()`` flushes the rest) —
        two synchronous fsyncs per served query is tail latency the hot
        path does not owe a warm-start optimization."""
        try:
            actual = _table_nbytes(result.table)
            for v in bindings.values():
                actual += v.nbytes if isinstance(v, HostTableChunk) \
                    else _table_nbytes(v)
        except (TypeError, AttributeError):
            return  # non-table result (nothing measurable to learn from)
        sig = self._plan_signature(ticket.plan, ticket.bindings)
        alpha = min(max(float(get_option("server.estimate_alpha")), 0.0), 1.0)
        with self._learned_lock:
            prev = self._learned.get(sig)
            self._learned[sig] = float(actual) if prev is None \
                else (1.0 - alpha) * prev + alpha * float(actual)
            self._learned_dirty = True
        interval = float(get_option("server.estimate_save_interval_s"))
        if (interval <= 0 or self._last_save is None
                or time.monotonic() - self._last_save >= interval):
            self._save_learned()

    def _default_estimate(self, plan: fusion.Plan, bindings: dict) -> int:
        """Headroom x the measured-truth EMA for this plan signature when
        one exists, else headroom x the static plan-aware input+output
        estimate; host-staged chunk bindings are costed at their exact
        device footprint."""
        with self._learned_lock:
            learned = self._learned.get(self._plan_signature(plan, bindings))
        if learned is not None:
            return int(self.estimate_headroom * learned)
        if any(isinstance(v, HostTableChunk) for v in bindings.values()):
            base = sum(
                v.nbytes if isinstance(v, HostTableChunk)
                else _table_nbytes(v)
                for v in bindings.values())
        else:
            base = fusion.estimate_hbm_bytes(plan, bindings)
        return int(self.estimate_headroom * base)

    def _serve_hit(self, ticket: QueryTicket, result) -> None:
        """Resolve a submit-time cache hit: the cached result is returned
        bit-identically with zero admission wait, zero compiles and zero
        execution spans — one root span carrying a single ``cache.hit``
        child is the query's whole trace."""
        sid = ticket.session
        with spans.span(f"query.{ticket.plan.name}", session=sid,
                        plan=ticket.plan.name,
                        estimate_bytes=ticket.estimate) as qspan:
            qspan.annotate(cache_hit=True)
            with spans.child("cache.hit", session=sid,
                             key=ticket.cache_key.short):
                pass
        ticket.queue_wait_s = 0.0
        ticket.latency_s = time.monotonic() - ticket._submitted_at
        lat_ms = ticket.latency_s * 1e3
        REGISTRY.histogram("server.latency_ms").observe(lat_ms)
        REGISTRY.histogram(f"server.latency_ms.{sid}").observe(lat_ms)
        REGISTRY.histogram("server.queue_wait_ms").observe(0.0)
        REGISTRY.histogram(f"server.queue_wait_ms.{sid}").observe(0.0)
        self._count("served", sid)
        record_server(ticket.plan.name, "served", session=sid,
                      wall_ms=lat_ms, wait_ms=0.0, cache_hit=True)
        ticket._resolve("served", value=result)

    def _reject(self, ticket: QueryTicket, reason: str,
                retry_after_s: Optional[float] = None,
                flight_record: Optional[str] = None) -> None:
        sid = ticket.session
        with self._cond:
            depth = len(self._queues.get(sid, ()))
        available = max(self.limiter.budget - self.limiter.used, 0)
        self._count("rejected", sid)
        extra = {"flight_record": flight_record} if flight_record else {}
        record_server(ticket.plan.name, "rejected", session=sid,
                      reason=reason, estimate_bytes=ticket.estimate,
                      queue_depth=depth, bytes_available=available,
                      **extra)
        _log.warning("rejected %s (session %s): %s",
                     ticket.plan.name, sid, reason)
        ticket._resolve("rejected", exc=QueryRejected(
            f"{ticket.plan.name} (session {sid}): {reason}",
            session=sid, reason=reason, queue_depth=depth,
            bytes_requested=ticket.estimate, bytes_available=available,
            retry_after_s=retry_after_s, flight_record=flight_record))

    def _next_ticket(self) -> Optional[QueryTicket]:
        """Round-robin pop: the next session (in ring order after the
        previously scheduled one) that has queued work gives up its
        OLDEST query. Blocks until work arrives or the server stops."""
        with self._cond:
            while True:
                for _ in range(len(self._ring)):
                    sid = self._ring[0]
                    self._ring.rotate(-1)
                    q = self._queues.get(sid)
                    if q:
                        return q.popleft()
                if self._stop.is_set():
                    return None
                self._cond.wait(0.1)

    def _worker(self) -> None:
        while True:
            ticket = self._next_ticket()
            if ticket is None:
                return
            self._serve(ticket)

    def _stage_bindings(self, bindings: dict) -> dict:
        """Stage host-decoded chunk bindings to device tables on the
        SHARED decode pool, concurrently across tables. Runs after
        admission: the reservation already covers these bytes."""
        futs = {
            name: self.decode_pool.submit(val.stage)
            for name, val in bindings.items()
            if isinstance(val, HostTableChunk)
        }
        if not futs:
            return bindings
        staged = dict(bindings)
        for name, fut in futs.items():
            staged[name] = fut.result()
        return staged

    def _cancelled(self, ticket: QueryTicket,
                   exc: resilience.QueryCancelled,
                   flight_record: Optional[str] = None) -> None:
        sid = ticket.session
        reason = str(exc.context.get("reason") or "cancelled")
        where = str(exc.context.get("where") or "checkpoint")
        ticket.latency_s = time.monotonic() - ticket._submitted_at
        self._count("cancelled", sid)
        extra = {"flight_record": flight_record} if flight_record else {}
        record_server(ticket.plan.name, "cancelled", session=sid,
                      reason=reason, where=where,
                      wall_ms=ticket.latency_s * 1e3, **extra)
        record_degrade(f"degrade.{ticket.plan.name}", "cancelled",
                       tier="cancelled", trigger=reason, rung=0,
                       session=sid)
        _log.info("query %s (session %s) cancelled: %s",
                  ticket.plan.name, sid, reason)
        ticket._resolve("cancelled", exc=exc)

    def _state_snapshot(self) -> dict:
        """Runtime state stamped into flight-recorder dumps: limiter
        watermarks, queue depths, in-flight count, spill-store totals."""
        with self._cond:
            queues = {sid: len(q) for sid, q in self._queues.items()}
        with self._inflight_lock:
            inflight = len(self._inflight)
        return {
            "limiter": self.limiter.watermarks(),
            "queues": queues,
            "inflight": inflight,
            "spill": self.spill_store.stats(),
        }

    def _serve(self, ticket: QueryTicket) -> None:
        sid = ticket.session
        token = ticket.cancel_token
        stop = self._stop

        class _admission_cancel:
            # wake a BLOCKED admission on shutdown OR query cancellation
            # (the limiter polls this inside reserve_blocking)
            @staticmethod
            def is_set() -> bool:
                return stop.is_set() or token.cancelled()

        held = 0
        info = {
            "ticket": ticket, "session": sid, "plan": ticket.plan.name,
            "tier": "fused", "rung": 0, "steps": 0, "chunk_rows": None,
            "held_bytes": 0, "span": None,
        }
        with self._inflight_lock:
            self._inflight[id(ticket)] = info
        try:
            # ONE root span per query: every instrumented seam below
            # (admission, degrade rungs, regions, pipeline chunks,
            # spills) attaches to this tree via the thread-local stack
            with spans.span(f"query.{ticket.plan.name}", session=sid,
                            plan=ticket.plan.name,
                            estimate_bytes=ticket.estimate) as qspan:
                info["span"] = qspan
                try:
                    faults.fire("server.admit", 0, session=sid,
                                plan=ticket.plan.name)
                    if token.cancelled():
                        # expired (or explicitly cancelled) while queued:
                        # resolve without ever reserving — the budget
                        # goes to live queries
                        token.check("server.admit")
                    # cached results must never make a live query wait:
                    # if this admission does not currently fit, shed
                    # resident cache entries FIRST so the reserve below
                    # parks only for bytes live queries actually hold
                    if resultcache.enabled():
                        self.result_cache.make_room(ticket.estimate)
                    # admission=True: NEW work parks while the limiter is
                    # above its high watermark; in-flight queries keep
                    # draining
                    # admission runs BEFORE the execution session_scope, so
                    # the session stamp must be explicit here
                    with spans.child("admission.wait", session=sid,
                                     estimate_bytes=ticket.estimate) as asp:
                        ok = self.limiter.reserve_blocking(
                            ticket.estimate, cancel=_admission_cancel,
                            timeout=self.admission_timeout_s,
                            admission=True)
                        if not ok:
                            asp.set_status("failed")
                    if not ok:
                        if token.cancelled():
                            token.check("server.admit")
                        qspan.set_status("failed")
                        why = ("server shutdown" if self._stop.is_set()
                               else f"admission timeout "
                                    f"({self.admission_timeout_s}s) "
                                    f"waiting for {ticket.estimate} bytes")
                        qspan.annotate(reason=why)
                        self._reject(
                            ticket, why,
                            retry_after_s=None if self._stop.is_set()
                            else self.admission_timeout_s,
                            flight_record=spans.dump_flight_record(
                                "rejected", root=qspan,
                                state=self._state_snapshot()))
                        return
                    held = ticket.estimate
                    info["held_bytes"] = held
                    ticket.status = "admitted"
                    ticket.queue_wait_s = (
                        time.monotonic() - ticket._submitted_at)
                    wait_ms = ticket.queue_wait_s * 1e3
                    REGISTRY.histogram(
                        "server.queue_wait_ms").observe(wait_ms)
                    REGISTRY.histogram(
                        f"server.queue_wait_ms.{sid}").observe(wait_ms)
                    self._count("admitted", sid)
                    record_server(ticket.plan.name, "admitted", session=sid,
                                  wait_ms=wait_ms, reserved_bytes=held)

                    def _observe(tier: str, rung: int, steps: int,
                                 chunk_rows: Optional[int]) -> None:
                        # degrade-ladder progress -> inspect(); runs with
                        # telemetry on OR off (it carries no records)
                        info["tier"] = tier
                        info["rung"] = rung
                        info["steps"] = steps
                        info["chunk_rows"] = chunk_rows
                        if steps and qspan.status == "ok":
                            qspan.set_status("degraded")

                    with session_scope(sid):
                        faults.fire("server.execute", 0, session=sid,
                                    plan=ticket.plan.name)
                        token.check("server.execute")
                        bindings = self._stage_bindings(ticket.bindings)
                        runner = None if ticket.outofcore is None \
                            else ticket.outofcore(bindings, self.limiter)
                        # subplan-prefix reuse: shared scan+filter+project
                        # prefixes collapse to cached intermediates — or
                        # materialize them once for the next plan that
                        # shares them. A rewritten plan must not donate:
                        # the injected binding is cache-owned
                        run_plan, run_bindings, rewrote = \
                            resultcache.apply_subplans(
                                self.result_cache, ticket.plan, bindings,
                                cancel_token=token)
                        # held_bytes: the parked rung must discount this
                        # query's own admission reservation from the
                        # drain threshold, or a query bigger than the low
                        # watermark parks forever
                        result = self.degrader.execute(
                            degrade.DegradableQuery(
                                run_plan, run_bindings,
                                donate_inputs=(ticket.donate_inputs
                                               and not rewrote),
                                outofcore=runner),
                            cancel_token=token, held_bytes=held,
                            observer=_observe)
                    ticket.latency_s = (
                        time.monotonic() - ticket._submitted_at)
                    lat_ms = ticket.latency_s * 1e3
                    REGISTRY.histogram("server.latency_ms").observe(lat_ms)
                    REGISTRY.histogram(
                        f"server.latency_ms.{sid}").observe(lat_ms)
                    self._count("served", sid)
                    record_server(ticket.plan.name, "served", session=sid,
                                  wall_ms=lat_ms,
                                  wait_ms=ticket.queue_wait_s * 1e3)
                    self._record_actual(ticket, bindings, result)
                    if ticket.cache_key is not None:
                        try:
                            self.result_cache.put(ticket.cache_key, result)
                        except Exception as exc:
                            # a cache-population failure must never fail
                            # a query that already served
                            REGISTRY.counter("cache.put_error").inc()
                            _log.warning(
                                "result-cache put failed for %s: %s",
                                ticket.plan.name, exc)
                    ticket._resolve("served", value=result)
                except resilience.QueryCancelled as exc:
                    # a deliberate stop, not a failure: the reservation
                    # and the in-flight slot release in the SAME finally
                    # as every exit
                    qspan.set_status("cancelled")
                    self._cancelled(
                        ticket, exc,
                        flight_record=spans.dump_flight_record(
                            "cancelled", root=qspan,
                            state=self._state_snapshot()))
                except BaseException as exc:
                    # a dying query releases everything it holds (the
                    # finally below) and resolves CLASSIFIED — never a
                    # silent wedge
                    kind = resilience.classify(
                        exc, seam="server.execute").__name__
                    if isinstance(exc, resilience.MalformedInputError):
                        # untrusted-input rejection: this one query dies
                        # clean (no retry, no degradation); count it so
                        # operators can tell hostile inputs from bugs
                        REGISTRY.counter("integrity.malformed_rejects").inc()
                        record_integrity(
                            ticket.plan.name, "malformed",
                            seam="integrity.ingest", session=sid)
                    qspan.set_status("failed")
                    qspan.annotate(error_kind=kind)
                    flight = spans.dump_flight_record(
                        "failed", root=qspan, state=self._state_snapshot())
                    ticket.latency_s = (
                        time.monotonic() - ticket._submitted_at)
                    self._count("failed", sid)
                    extra = {"flight_record": flight} if flight else {}
                    record_server(ticket.plan.name, "failed", session=sid,
                                  error_kind=kind,
                                  reason=str(exc) or type(exc).__name__,
                                  **extra)
                    _log.warning(
                        "query %s (session %s) failed classified as %s",
                        ticket.plan.name, sid, kind)
                    ticket._resolve("failed", exc=exc)
                    if not isinstance(exc, Exception):
                        # KeyboardInterrupt etc: not the server's to absorb
                        raise
        finally:
            with self._inflight_lock:
                self._inflight.pop(id(ticket), None)
            if held:
                self.limiter.release(held)
