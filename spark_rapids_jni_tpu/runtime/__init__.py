from spark_rapids_jni_tpu.runtime.native import NativeLib, load_native

__all__ = ["NativeLib", "load_native"]
