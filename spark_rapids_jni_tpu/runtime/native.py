"""Native library loader — the NativeDepsLoader equivalent.

The reference extracts per-platform .so resources from the jar and
System.load()s them on first API touch (reference RowConversion.java:23-25,
packaging scheme pom.xml:385-421). Here the equivalent search order is:

  1. ``SPARK_RAPIDS_TPU_NATIVE_LIB`` env var (explicit path);
  2. a packaged ``_lib/libtpudf.so`` next to this module;
  3. ``build/native/libtpudf.so`` under the repo root;
  4. if a toolchain is available, configure+build it with cmake/ninja into
     ``build/native`` (the dev-workflow path; the reference drives the same
     step from Maven at the validate phase, pom.xml:306-333).

Loading is lazy and memoized; errors carry the full search trail.
"""

from __future__ import annotations

import ctypes
import os
import pathlib
import subprocess
import threading
from typing import Optional

_REPO_ROOT = pathlib.Path(__file__).resolve().parents[2]
_LIB_NAME = "libtpudf.so"

_lock = threading.Lock()
_loaded: Optional["NativeLib"] = None


class NativeLib:
    """ctypes surface of libtpudf with argtypes pinned."""

    def __init__(self, cdll: ctypes.CDLL, path: pathlib.Path):
        self.path = path
        self._c = cdll
        c = cdll
        c.tpudf_last_error.restype = ctypes.c_char_p
        c.tpudf_footer_read_and_filter.restype = ctypes.c_int64
        c.tpudf_footer_read_and_filter.argtypes = [
            ctypes.c_char_p,
            ctypes.c_uint64,
            ctypes.c_int64,
            ctypes.c_int64,
            ctypes.POINTER(ctypes.c_char_p),
            ctypes.POINTER(ctypes.c_int32),
            ctypes.c_int32,
            ctypes.c_int32,
            ctypes.c_int32,
        ]
        c.tpudf_footer_num_rows.restype = ctypes.c_int64
        c.tpudf_footer_num_rows.argtypes = [ctypes.c_int64]
        c.tpudf_footer_num_columns.restype = ctypes.c_int32
        c.tpudf_footer_num_columns.argtypes = [ctypes.c_int64]
        c.tpudf_footer_serialize.restype = ctypes.c_int32
        c.tpudf_footer_serialize.argtypes = [
            ctypes.c_int64,
            ctypes.POINTER(ctypes.POINTER(ctypes.c_uint8)),
            ctypes.POINTER(ctypes.c_uint64),
        ]
        c.tpudf_free_buffer.argtypes = [ctypes.POINTER(ctypes.c_uint8)]
        c.tpudf_footer_close.restype = ctypes.c_int32
        c.tpudf_footer_close.argtypes = [ctypes.c_int64]
        c.tpudf_open_handles.restype = ctypes.c_int64
        # Parquet data reader
        c.tpudf_parquet_read.restype = ctypes.c_int64
        c.tpudf_parquet_read.argtypes = [
            ctypes.c_char_p,
            ctypes.c_uint64,
            ctypes.POINTER(ctypes.c_int32),
            ctypes.c_int32,
            ctypes.POINTER(ctypes.c_int32),
            ctypes.c_int32,
        ]
        c.tpudf_parquet_row_groups.restype = ctypes.c_int32
        c.tpudf_parquet_row_groups.argtypes = [
            ctypes.c_char_p,
            ctypes.c_uint64,
            ctypes.POINTER(ctypes.c_int64),
            ctypes.POINTER(ctypes.c_int64),
            ctypes.c_int32,
        ]
        c.tpudf_read_num_rows.restype = ctypes.c_int64
        c.tpudf_read_num_rows.argtypes = [ctypes.c_int64]
        c.tpudf_read_num_columns.restype = ctypes.c_int32
        c.tpudf_read_num_columns.argtypes = [ctypes.c_int64]
        c.tpudf_read_col_meta.restype = ctypes.c_int32
        c.tpudf_read_col_meta.argtypes = [
            ctypes.c_int64,
            ctypes.c_int32,
            ctypes.POINTER(ctypes.c_int32),
            ctypes.POINTER(ctypes.c_int64),
        ]
        c.tpudf_parquet_read_path.restype = ctypes.c_int64
        c.tpudf_parquet_read_path.argtypes = [
            ctypes.c_char_p,
            ctypes.POINTER(ctypes.c_int32),
            ctypes.c_int32,
            ctypes.POINTER(ctypes.c_int32),
            ctypes.c_int32,
        ]
        c.tpudf_parquet_row_groups_path.restype = ctypes.c_int32
        c.tpudf_parquet_row_groups_path.argtypes = [
            ctypes.c_char_p,
            ctypes.POINTER(ctypes.c_int64),
            ctypes.POINTER(ctypes.c_int64),
            ctypes.c_int32,
        ]
        c.tpudf_read_col_meta2.restype = ctypes.c_int32
        c.tpudf_read_col_meta2.argtypes = [
            ctypes.c_int64,
            ctypes.c_int32,
            ctypes.POINTER(ctypes.c_int32),
            ctypes.POINTER(ctypes.c_int64),
        ]
        c.tpudf_read_col_levels.restype = ctypes.c_int32
        c.tpudf_read_col_levels.argtypes = [
            ctypes.c_int64,
            ctypes.c_int32,
            ctypes.c_void_p,
            ctypes.c_void_p,
        ]
        c.tpudf_read_schema_desc.restype = ctypes.c_char_p
        c.tpudf_read_schema_desc.argtypes = [ctypes.c_int64]
        c.tpudf_read_col_name.restype = ctypes.c_char_p
        c.tpudf_read_col_name.argtypes = [ctypes.c_int64, ctypes.c_int32]
        c.tpudf_read_col_copy.restype = ctypes.c_int32
        c.tpudf_read_col_copy.argtypes = [
            ctypes.c_int64,
            ctypes.c_int32,
            ctypes.c_void_p,
            ctypes.c_void_p,
            ctypes.c_void_p,
            ctypes.c_void_p,
        ]
        c.tpudf_read_close.restype = ctypes.c_int32
        c.tpudf_read_close.argtypes = [ctypes.c_int64]
        # ORC reader
        c.tpudf_orc_read.restype = ctypes.c_int64
        c.tpudf_orc_read.argtypes = [
            ctypes.c_char_p,
            ctypes.c_uint64,
            ctypes.POINTER(ctypes.c_int32),
            ctypes.c_int32,
            ctypes.POINTER(ctypes.c_int32),
            ctypes.c_int32,
        ]
        c.tpudf_orc_stripes.restype = ctypes.c_int32
        c.tpudf_orc_stripes.argtypes = [
            ctypes.c_char_p,
            ctypes.c_uint64,
            ctypes.POINTER(ctypes.c_int64),
            ctypes.POINTER(ctypes.c_int64),
            ctypes.c_int32,
        ]
        c.tpudf_orc_num_columns.restype = ctypes.c_int32
        c.tpudf_orc_num_columns.argtypes = [ctypes.c_int64]
        c.tpudf_orc_num_rows.restype = ctypes.c_int64
        c.tpudf_orc_num_rows.argtypes = [ctypes.c_int64]
        c.tpudf_orc_col_meta.restype = ctypes.c_int32
        c.tpudf_orc_col_meta.argtypes = [
            ctypes.c_int64,
            ctypes.c_int32,
            ctypes.POINTER(ctypes.c_int32),
            ctypes.POINTER(ctypes.c_int64),
        ]
        c.tpudf_orc_col_name.restype = ctypes.c_char_p
        c.tpudf_orc_col_name.argtypes = [ctypes.c_int64, ctypes.c_int32]
        c.tpudf_orc_writer_timezone.restype = ctypes.c_char_p
        c.tpudf_orc_writer_timezone.argtypes = [ctypes.c_int64]
        c.tpudf_orc_read_path.restype = ctypes.c_int64
        c.tpudf_orc_read_path.argtypes = [
            ctypes.c_char_p,
            ctypes.POINTER(ctypes.c_int32), ctypes.c_int32,
            ctypes.POINTER(ctypes.c_int32), ctypes.c_int32,
        ]
        c.tpudf_orc_stripes_path.restype = ctypes.c_int32
        c.tpudf_orc_stripes_path.argtypes = [
            ctypes.c_char_p,
            ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_int64),
            ctypes.c_int32,
        ]
        c.tpudf_orc_col_copy.restype = ctypes.c_int32
        c.tpudf_orc_col_copy.argtypes = [
            ctypes.c_int64,
            ctypes.c_int32,
            ctypes.c_void_p,
            ctypes.c_void_p,
            ctypes.c_void_p,
            ctypes.c_void_p,
        ]
        c.tpudf_orc_close.restype = ctypes.c_int32
        c.tpudf_orc_close.argtypes = [ctypes.c_int64]
        c.tpudf_orc_decode_rle2.restype = ctypes.c_int32
        c.tpudf_orc_decode_rle2.argtypes = [
            ctypes.c_char_p,
            ctypes.c_uint64,
            ctypes.c_int64,
            ctypes.c_int32,
            ctypes.c_void_p,
        ]
        # host packed-row codec
        c.tpudf_rows_layout.restype = ctypes.c_int32
        c.tpudf_rows_layout.argtypes = [
            ctypes.POINTER(ctypes.c_int32),
            ctypes.c_int32,
            ctypes.POINTER(ctypes.c_int32),
        ]
        c.tpudf_to_rows.restype = ctypes.c_int32
        c.tpudf_to_rows.argtypes = [
            ctypes.POINTER(ctypes.c_void_p),
            ctypes.POINTER(ctypes.c_void_p),
            ctypes.POINTER(ctypes.c_int32),
            ctypes.c_int32,
            ctypes.c_int64,
            ctypes.c_void_p,
        ]
        c.tpudf_from_rows.restype = ctypes.c_int32
        c.tpudf_from_rows.argtypes = [
            ctypes.c_void_p,
            ctypes.c_int64,
            ctypes.POINTER(ctypes.c_int32),
            ctypes.c_int32,
            ctypes.POINTER(ctypes.c_void_p),
            ctypes.POINTER(ctypes.c_void_p),
        ]
        # get_json_object
        c.tpudf_get_json_object.restype = ctypes.c_int32
        c.tpudf_get_json_object.argtypes = [
            ctypes.c_void_p,                          # chars
            ctypes.c_void_p,                          # offsets
            ctypes.c_void_p,                          # valid (nullable)
            ctypes.c_int64,                           # n_rows
            ctypes.c_char_p,                          # path
            ctypes.POINTER(ctypes.POINTER(ctypes.c_uint8)),
            ctypes.POINTER(ctypes.c_int64),
            ctypes.c_void_p,                          # out offsets
            ctypes.c_void_p,                          # out valid
        ]

    def __getattr__(self, name):
        return getattr(self._c, name)

    def last_error(self) -> str:
        return self._c.tpudf_last_error().decode(errors="replace")


def _candidate_paths() -> list[pathlib.Path]:
    out = []
    env = os.environ.get("SPARK_RAPIDS_TPU_NATIVE_LIB")
    if env:
        out.append(pathlib.Path(env))
    out.append(pathlib.Path(__file__).parent / "_lib" / _LIB_NAME)
    out.append(_REPO_ROOT / "build" / "native" / _LIB_NAME)
    return out


def _build_native() -> Optional[pathlib.Path]:
    src = _REPO_ROOT / "src" / "native"
    build = _REPO_ROOT / "build" / "native"
    if not src.exists():
        return None
    try:
        subprocess.run(
            ["cmake", "-S", str(src), "-B", str(build), "-G", "Ninja"],
            check=True,
            capture_output=True,
        )
        subprocess.run(
            ["ninja", "-C", str(build)], check=True, capture_output=True
        )
    except (OSError, subprocess.CalledProcessError):
        return None
    lib = build / _LIB_NAME
    return lib if lib.exists() else None


def load_native() -> NativeLib:
    global _loaded
    with _lock:
        if _loaded is not None:
            return _loaded
        tried = []
        for path in _candidate_paths():
            if path.exists():
                _loaded = NativeLib(ctypes.CDLL(str(path)), path)
                return _loaded
            tried.append(str(path))
        built = _build_native()
        if built is not None:
            _loaded = NativeLib(ctypes.CDLL(str(built)), built)
            return _loaded
        raise OSError(
            f"could not locate or build {_LIB_NAME}; searched: {tried} "
            "and cmake build of src/native failed"
        )
