"""Device/host memory management — the RMM-equivalent layer.

The reference leans on RMM for device memory pools, per-thread streams and
an ``RMM_LOGGING_LEVEL`` knob (reference pom.xml:82, CMakeLists.txt:56-63;
rmm::device_uvector use throughout row_conversion.cu). On TPU the HBM
allocator itself belongs to XLA — JAX arrays live in XLA's BFC arena, and
re-implementing that would fight the runtime. What this layer provides is
the part of RMM's surface a Spark executor actually interacts with:

  * ``device_memory_stats()`` — live/peak/limit HBM numbers per device
    (RMM's ``mr.get_info`` role) for spill decisions and telemetry;
  * ``MemoryLimiter`` — a soft budget gate: reserve/release accounting
    with the same fail-fast contract as a capped RMM pool, used by the
    chunked reader to size batches;
  * ``HostStagingPool`` — recycled pinned-style host buffers for the
    parquet/IO staging path (the role of RMM's pinned-host pool), a size-
    class freelist so repeated chunked reads stop hammering the allocator;
  * allocation logging behind ``memory.log_level``
    (env SPARK_RAPIDS_TPU_MEMORY_LOG_LEVEL) — RMM_LOGGING_LEVEL parity.
"""

from __future__ import annotations

import collections
import os
import pickle
import threading
import time
from dataclasses import dataclass
from typing import NamedTuple, Optional

import numpy as np

from spark_rapids_jni_tpu import telemetry
from spark_rapids_jni_tpu.runtime import compress, faults, integrity
from spark_rapids_jni_tpu.telemetry import spans
from spark_rapids_jni_tpu.utils.config import get_option
from spark_rapids_jni_tpu.utils.log import get_logger

_log = get_logger("spark_rapids_jni_tpu.memory")


@dataclass(frozen=True)
class DeviceMemoryStats:
    bytes_in_use: int
    peak_bytes_in_use: int
    bytes_limit: int

    @property
    def bytes_free(self) -> int:
        return max(self.bytes_limit - self.bytes_in_use, 0)


def device_memory_stats(device=None) -> DeviceMemoryStats:
    """Live HBM stats from the XLA allocator (zeros when the backend does
    not report — e.g. some CPU builds)."""
    import jax

    if device is None:
        device = jax.devices()[0]
    stats = {}
    try:
        stats = device.memory_stats() or {}
    except (RuntimeError, AttributeError):
        pass
    return DeviceMemoryStats(
        bytes_in_use=int(stats.get("bytes_in_use", 0)),
        peak_bytes_in_use=int(stats.get("peak_bytes_in_use", 0)),
        bytes_limit=int(stats.get("bytes_limit", 0)),
    )


class MemoryLimitExceeded(MemoryError):
    pass


class _Waiter:
    """One blocked ``reserve_blocking`` ticket. The ``admission`` flag is
    what lets the head-of-line check distinguish a pressure-parked
    admission (which must NOT hold the FIFO line — the in-flight work
    behind it is what drains the pressure) from an ordinarily blocked
    reservation (which must)."""

    __slots__ = ("admission",)

    def __init__(self, admission: bool):
        self.admission = bool(admission)


class MemoryLimiter:
    """Soft budget gate with capped-pool semantics: ``reserve`` beyond the
    budget raises (fail-fast, like a capped RMM pool) instead of letting a
    giant batch OOM the device mid-kernel.

    Pressure watermarks (``memory.high_watermark`` / ``memory.low_watermark``
    fractions of the budget, overridable per instance): a grant that lifts
    usage across the high watermark enters the *pressure* state — the
    ``memory.pressure`` fault seam fires, a ``degrade.pressure`` telemetry
    event is emitted, the coldest entries of an attached :class:`SpillStore`
    are proactively spilled, and ``reserve_blocking(..., admission=True)``
    callers (the serving runtime's admission gate) park until usage drains
    back below the low watermark. Non-admission reservations (pipeline
    chunks of already-running queries) are never paused — a pressure-parked
    admission ticket does not even hold the FIFO line against them — so
    in-flight work keeps draining toward the low watermark instead of
    deadlocking behind the very admission that is waiting for it.
    """

    def __init__(self, budget_bytes: int, *,
                 high_watermark: "float | None" = None,
                 low_watermark: "float | None" = None):
        if budget_bytes <= 0:
            raise ValueError("budget must be positive")
        self.budget = int(budget_bytes)
        self._used = 0
        self._peak = 0
        self._high_frac = None if high_watermark is None else float(high_watermark)
        self._low_frac = None if low_watermark is None else float(low_watermark)
        self._pressure = False
        self._pressure_crossings = 0
        self._spill_store: "SpillStore | None" = None
        self._result_cache = None
        # a Condition so reserve_blocking can sleep until release() frees
        # budget; plain reserve/release take the same underlying lock
        self._lock = threading.Condition()
        # FIFO queue of blocked reserve_blocking tickets: budget freed by a
        # release is offered to the longest-waiting reserver first, so a
        # small late request cannot barge past a large early one forever
        self._waiters: "collections.deque[_Waiter]" = collections.deque()

    @property
    def used(self) -> int:
        return self._used

    @property
    def peak(self) -> int:
        return self._peak

    @property
    def pressure(self) -> bool:
        """True between a high-watermark crossing and the drain below low."""
        return self._pressure

    @property
    def pressure_crossings(self) -> int:
        """How many times usage has crossed the high watermark (the seq the
        ``memory.pressure`` fault seam fires with — lets a FaultScript
        target the Nth crossing deterministically)."""
        return self._pressure_crossings

    def attach_spill_store(self, store: "SpillStore | None") -> None:
        """Register the SpillStore whose coldest entries a high-watermark
        crossing proactively spills (None detaches)."""
        self._spill_store = store

    def attach_result_cache(self, cache) -> None:
        """Register a ResultCache (runtime/resultcache.py) whose entries a
        high-watermark crossing sheds BEFORE any live query's working set
        is spilled, and whose evictable resident bytes do not count as
        "held" for drain waits (None detaches). The limiter only ever
        reads the cache's lock-free ``evictable_bytes`` int under its own
        lock and calls ``shed()`` outside it — the cache takes its own
        lock then the limiter's (release), never the reverse, so the two
        locks cannot deadlock."""
        self._result_cache = cache

    def _evictable_cache_bytes(self) -> int:
        """Resident limiter-charged cache bytes a pressure event could
        reclaim. Lock-free read of a plain int attribute — safe under the
        limiter lock (see attach_result_cache)."""
        cache = self._result_cache
        if cache is None:
            return 0
        return max(int(cache.evictable_bytes), 0)

    def watermarks(self) -> dict:
        """One consistent snapshot of the limiter's watermark state —
        live introspection (QueryServer.inspect(), flight-recorder
        dumps). Read under the lock so used/waiters/pressure cohere."""
        with self._lock:
            return {
                "used": self._used,
                "budget": self.budget,
                "peak": self._peak,
                "pressure": self._pressure,
                "pressure_crossings": self._pressure_crossings,
                "high_bytes": self._high_bytes(),
                "low_bytes": self._low_bytes(),
                "waiters": len(self._waiters),
                "admission_waiters": sum(
                    1 for w in self._waiters if w.admission),
            }

    def _high_bytes(self) -> int:
        frac = self._high_frac
        if frac is None:
            frac = float(get_option("memory.high_watermark"))
        return int(self.budget * frac)

    def _low_bytes(self) -> int:
        frac = self._low_frac
        if frac is None:
            frac = float(get_option("memory.low_watermark"))
        # a misconfigured low > high would make pressure un-clearable the
        # moment it is entered; clamp instead of wedging admission
        return min(int(self.budget * frac), self._high_bytes())

    def _held_back_locked(self, ticket: "_Waiter") -> bool:
        """Under the lock: is an EARLIER waiter legitimately holding the
        FIFO line against ``ticket``? Pressure-parked admission tickets
        (admission waiters while the limiter is in the pressure state) do
        not hold the line — the non-admission reservations behind them
        belong to in-flight queries whose releases are the only thing that
        can drain the pressure, so blocking them would wedge the limiter
        until the admission timeout. Parked admissions keep their queue
        position: the moment pressure clears they are the head again and
        ordinary no-barge FIFO resumes."""
        for w in self._waiters:
            if w is ticket:
                return False
            if not (w.admission and self._pressure):
                return True
        return False

    def _note_grant_locked(self) -> bool:
        """Called under the lock after ``_used`` grew; returns True exactly
        when this grant crossed the high watermark (caller reacts outside
        the lock — the pressure reaction spills and fires fault seams)."""
        # doubly gated: on degrade.enabled (with degradation off the
        # limiter is byte-for-byte the pre-watermark accounting — the PR-7
        # parity contract) AND on an attached spill store — watermarks are
        # a managed-limiter feature (the serving runtime attaches its
        # store); a bare limiter shared with external holders would
        # otherwise park admission on pressure nothing can ever drain
        if (not self._pressure and self._spill_store is not None
                and self._used >= self._high_bytes()
                and get_option("degrade.enabled")):
            self._pressure = True
            self._pressure_crossings += 1
            return True
        return False

    def _enter_pressure(self) -> None:
        """React to a high-watermark crossing: fault seam, telemetry,
        proactive spill of the attached store's coldest entries. Runs
        OUTSIDE the lock; an injected ``memory.pressure`` fault propagates
        to the reserving caller (which rolls back its grant)."""
        faults.fire("memory.pressure", self._pressure_crossings,
                    used=self._used, budget=self.budget,
                    watermark=self._high_bytes())
        freed = 0
        shed = 0
        target = max(self._used - self._low_bytes(), 1)
        # eviction ordering: cached results are the FIRST thing to go —
        # shedding a cache entry demotes it to the host/disk tier and
        # releases its limiter charge, so live queries' working sets are
        # only spilled for whatever pressure the cache could not absorb
        cache = self._result_cache
        if cache is not None:
            shed = cache.shed(target)
        store = self._spill_store
        if store is not None and shed < target:
            # ambition: drain resident spill-store bytes by as much as the
            # limiter is above its low watermark, coldest entries first
            freed = store.spill_coldest(target - shed)
        telemetry.record_degrade(
            "memory_limiter", "pressure", tier="high", trigger="watermark",
            rung=0, used=self._used, budget=self.budget,
            proactive_spill_bytes=freed, cache_shed_bytes=shed)
        if get_option("memory.log_level") >= 1:
            _log.info("memory pressure: %d/%d in use (high watermark %d), "
                      "proactively spilled %d bytes", self._used, self.budget,
                      self._high_bytes(), freed)

    def reserve(self, nbytes: int) -> None:
        # fault seam BEFORE the lock: an injected reservation failure must
        # leave the accounting untouched, like a real allocator rejection
        faults.fire("memory.reserve", nbytes, blocking=False)
        with self._lock:
            if self._used + nbytes > self.budget:
                raise MemoryLimitExceeded(
                    f"reservation of {nbytes} bytes exceeds budget "
                    f"({self._used}/{self.budget} in use)"
                )
            self._used += nbytes
            self._peak = max(self._peak, self._used)
            crossed = self._note_grant_locked()
            if get_option("memory.log_level") >= 2:
                _log.info("reserve %d bytes (%d in use)", nbytes, self._used)
        if crossed:
            try:
                self._enter_pressure()
            except BaseException:
                # an injected pressure fault must not leak the grant it
                # was reacting to
                self.release(nbytes)
                raise

    def reserve_blocking(self, nbytes: int, cancel=None,
                         timeout: "float | None" = None,
                         admission: bool = False) -> bool:
        """Wait until ``nbytes`` fits inside the budget, then reserve it.

        The pipeline's backpressure primitive: where ``reserve`` fails
        loud, this form parks the producer until a consumer ``release``
        frees room, so a tight budget degrades throughput toward serial
        instead of raising mid-run. A request larger than the WHOLE
        budget can never fit and raises ``MemoryLimitExceeded``
        immediately (same contract as ``reserve``). Returns True on
        success, False if ``cancel`` (a threading.Event) was set or
        ``timeout`` seconds elapsed first — cancellation is polled, so
        a cancelled producer wakes within ~50ms.

        Ordering contract: concurrent blocked reservers are served FIFO —
        freed budget goes to the longest-waiting request first, and a
        later (even smaller) request never barges past an earlier blocked
        one. A plain ``reserve`` keeps its fail-fast semantics and does
        not queue.

        ``admission=True`` marks this reservation as a NEW unit of work
        (the serving runtime's admission gate): while the limiter is in
        the pressure state, admission reservations park until usage
        drains below the low watermark even if the bytes would fit.
        Plain reservations (chunks of already-admitted queries) ignore
        pressure AND flow past pressure-parked admission tickets in the
        queue — in-flight work keeps draining; the parked admission keeps
        its FIFO position for when pressure clears.
        """
        faults.fire("memory.reserve", nbytes, blocking=True)
        if nbytes > self.budget:
            raise MemoryLimitExceeded(
                f"reservation of {nbytes} bytes exceeds the whole budget "
                f"({self.budget}): can never fit"
            )
        deadline = None if timeout is None else time.monotonic() + timeout
        ticket = _Waiter(admission)
        with self._lock:
            self._waiters.append(ticket)
            try:
                # grant only when no earlier ticket holds the line AND the
                # bytes fit: a blocked earlier ticket holds back every
                # later one (the no-barge property) — except a pressure-
                # parked admission, which in-flight reservations bypass
                while (self._held_back_locked(ticket)
                       or self._used + nbytes > self.budget
                       or (admission and self._pressure)):
                    if cancel is not None and cancel.is_set():
                        return False
                    wait = 0.05
                    if deadline is not None:
                        remaining = deadline - time.monotonic()
                        if remaining <= 0:
                            return False
                        wait = min(wait, remaining)
                    self._lock.wait(wait)
                self._used += nbytes
                self._peak = max(self._peak, self._used)
                crossed = self._note_grant_locked()
                if get_option("memory.log_level") >= 2:
                    _log.info(
                        "reserve %d bytes (%d in use)", nbytes, self._used)
            finally:
                # leaving for ANY reason (granted, cancelled, timed out)
                # unblocks the next ticket in line
                self._waiters.remove(ticket)
                self._lock.notify_all()
        if crossed:
            try:
                self._enter_pressure()
            except BaseException:
                self.release(nbytes)
                raise
        return True

    def wait_below_low(self, timeout: "float | None" = None,
                       cancel=None, own_held: int = 0) -> bool:
        """Park until usage drains below the low watermark — the
        park-and-retry ladder rung's drain wait (runtime/degrade.py).
        ``own_held`` is the caller's OWN outstanding reservation (the
        serving runtime's admission estimate): it is subtracted from the
        drain threshold, because a query whose own hold exceeds the low
        watermark could otherwise never observe the drain it is waiting
        for. Evictable result-cache bytes (attach_result_cache) are also
        subtracted: they are reclaimable on demand, so a parked query must
        not wait out a drain the next pressure event would provide for
        free. Returns True once drained, False if ``cancel`` (anything
        with ``is_set()``) fired or ``timeout`` seconds elapsed first;
        cancellation is polled (~50ms), same as ``reserve_blocking``."""
        deadline = None if timeout is None else time.monotonic() + timeout
        own = max(int(own_held), 0)
        with self._lock:
            while (self._used - own - self._evictable_cache_bytes()
                   > self._low_bytes()):
                if cancel is not None and cancel.is_set():
                    return False
                wait = 0.05
                if deadline is not None:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        return False
                    wait = min(wait, remaining)
                self._lock.wait(wait)
        return True

    def reclaim_cache(self, nbytes: "int | None" = None) -> int:
        """Turn the drain ``wait_below_low`` promised into real free
        bytes: shed evictable result-cache entries (demote + release
        charge) for up to ``nbytes`` (default: whatever stands between
        current usage and the low watermark). Called OUTSIDE the limiter
        lock — the parked rung (runtime/degrade.py) invokes it after a
        drain wait returns, so a resumed query's retry reserve finds the
        budget the evictable discount counted on."""
        cache = self._result_cache
        if cache is None:
            return 0
        target = (max(self._used - self._low_bytes(), 0)
                  if nbytes is None else max(int(nbytes), 0))
        if target <= 0:
            return 0
        return cache.shed(target)

    def release(self, nbytes: int) -> None:
        with self._lock:
            self._used = max(self._used - nbytes, 0)
            cleared = self._pressure and self._used <= self._low_bytes()
            if cleared:
                self._pressure = False
            self._lock.notify_all()
            if get_option("memory.log_level") >= 2:
                _log.info("release %d bytes (%d in use)", nbytes, self._used)
        if cleared:
            telemetry.record_degrade(
                "memory_limiter", "pressure", tier="low", trigger="watermark",
                rung=0, used=self._used, budget=self.budget)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        with self._lock:
            self._used = 0
            self._pressure = False
            self._lock.notify_all()
        return False


class HostStagingPool:
    """Freelist of host staging buffers, bucketed by power-of-two size.

    ``take(nbytes)`` returns a uint8 array of at least nbytes (callers
    slice); ``give(buf)`` recycles it. Thread-safe; bounded per bucket so a
    burst cannot pin unbounded host memory."""

    def __init__(self, max_buffers_per_class: int = 8):
        self._free: dict[int, list[np.ndarray]] = {}
        self._max = max_buffers_per_class
        self._lock = threading.Lock()
        self.hits = 0
        self.misses = 0

    @staticmethod
    def _size_class(nbytes: int) -> int:
        return 1 << max(int(nbytes - 1).bit_length(), 6)  # min 64B

    def take(self, nbytes: int) -> np.ndarray:
        cls = self._size_class(max(nbytes, 1))
        with self._lock:
            bucket = self._free.get(cls)
            if bucket:
                self.hits += 1
                return bucket.pop()
            self.misses += 1
        if get_option("memory.log_level") >= 1:
            _log.info("staging alloc %d bytes (class %d)", nbytes, cls)
        return np.empty(cls, dtype=np.uint8)

    def give(self, buf: np.ndarray) -> None:
        cls = int(buf.nbytes)
        # only recycle buffers this pool could have produced: uint8,
        # power-of-two size, at least the minimum size class
        if buf.dtype != np.uint8 or cls < 64 or cls & (cls - 1):
            return
        with self._lock:
            bucket = self._free.setdefault(cls, [])
            if len(bucket) < self._max:
                bucket.append(buf)

    def clear(self) -> None:
        with self._lock:
            self._free.clear()


_default_pool: Optional[HostStagingPool] = None
_default_pool_lock = threading.Lock()


def default_staging_pool() -> HostStagingPool:
    global _default_pool
    with _default_pool_lock:
        if _default_pool is None:
            _default_pool = HostStagingPool()
        return _default_pool


# ---- spill store (the RMM arena's overflow valve) --------------------------


def _col_nbytes(c) -> int:
    total = int(np.prod(c.data.shape)) * c.data.dtype.itemsize
    if c.validity is not None:
        total += int(c.validity.shape[0])
    if c.chars is not None:
        total += int(np.prod(c.chars.shape))
    for child in (c.children or ()):
        total += _col_nbytes(child)
    return total


def _table_nbytes(table) -> int:
    return sum(_col_nbytes(c) for c in table.columns)


def _pack_array(arr, cctx, codec_seam=None):
    """Re-encode one host buffer for the spilled tiers (the nvcomp role
    for the HOST path). ``codec_seam`` routes it through the columnar
    codec (runtime/compress.py) as a self-describing ``("tpcc", ...)``
    pack; otherwise ``cctx`` keeps the legacy whole-buffer zstd pack, and
    with both off the plain array passes through — byte-for-byte the
    pre-codec snapshot."""
    if arr is None:
        return None
    if codec_seam is not None:
        return compress.pack_array(arr, codec_seam)
    a = np.ascontiguousarray(arr)
    if cctx is None:
        return a
    # compress() takes buffer-protocol objects — no tobytes() copy
    return ("zstd", a.dtype.str, a.shape, cctx.compress(a))


def _unpack_array(obj, dctx, seam="integrity.spill"):
    if obj is None or not isinstance(obj, tuple):
        return obj
    if compress.is_codec_pack(obj):
        # runs after the seam's trailer/crc verified; the codec re-checks
        # the frame itself so a corrupt-after-decompress header is still
        # a classified CorruptDataError, never garbage staged to HBM
        return compress.unpack_array(obj, seam=seam, op="spill_store.unpack")
    _, dtype_str, shape, blob = obj
    return np.frombuffer(
        dctx.decompress(blob), dtype=np.dtype(dtype_str)).reshape(shape)


def _packed_nbytes(obj) -> int:
    if obj is None:
        return 0
    if isinstance(obj, tuple):
        return len(obj[3])
    return obj.nbytes


def _col_to_host(c, cctx=None, codec_seam=None) -> tuple:
    """Recursive host snapshot of a column (incl. LIST/STRUCT children)."""
    return (
        c.dtype,
        _pack_array(np.asarray(c.data), cctx, codec_seam),
        None if c.validity is None
        else _pack_array(np.asarray(c.validity), cctx, codec_seam),
        None if c.chars is None
        else _pack_array(np.asarray(c.chars), cctx, codec_seam),
        None if not c.children
        else [_col_to_host(ch, cctx, codec_seam) for ch in c.children],
    )


def _col_from_host(snap, dctx=None, seam="integrity.spill"):
    import jax.numpy as jnp

    from spark_rapids_jni_tpu.columnar import Column

    dtype, data, validity, chars, children = snap
    return Column(
        dtype, jnp.asarray(_unpack_array(data, dctx, seam)),
        None if validity is None
        else jnp.asarray(_unpack_array(validity, dctx, seam)),
        chars=None if chars is None
        else jnp.asarray(_unpack_array(chars, dctx, seam)),
        children=None if children is None
        else [_col_from_host(ch, dctx, seam) for ch in children],
    )


class HostTableChunk(NamedTuple):
    """A host-decoded table chunk awaiting device staging.

    ``cols`` holds column snapshots in the ``_col_to_host`` format
    (dtype, data, validity, chars, children — all numpy); ``nbytes`` is
    the exact device footprint ``stage()`` will allocate. The pipelined
    executor decodes chunks to this form in its read/decode stage so the
    MemoryLimiter reservation can be taken on exact bytes BEFORE the
    host->device copy — backpressure that cannot over-commit the budget
    on a size guess."""

    cols: tuple
    nbytes: int
    num_rows: int

    def stage(self):
        """Host->device copy. Callers reserve ``nbytes`` first."""
        from spark_rapids_jni_tpu.columnar import Table

        return Table([_col_from_host(snap) for snap in self.cols])


def host_table_chunk(snaps, num_rows: int) -> HostTableChunk:
    snaps = tuple(snaps)
    return HostTableChunk(
        snaps, sum(_host_snap_nbytes(s) for s in snaps), int(num_rows))


def _host_snap_nbytes(snap) -> int:
    _, data, validity, chars, children = snap
    n = (_packed_nbytes(data) + _packed_nbytes(validity)
         + _packed_nbytes(chars))
    for ch in (children or []):
        n += _host_snap_nbytes(ch)
    return n


def _unlink_quiet(path: "str | None") -> None:
    if not path:
        return
    try:
        os.unlink(path)
    except OSError:
        pass


def _inject_snap_corruption(snaps: list, seam: str, eid: int) -> None:
    """Fault-script corruption window for IN-MEMORY spill snapshots:
    route the first packed host buffer through :func:`faults.fire_corrupt`
    so the chaos suite can plant latent corruption that unspill must
    detect. Live numpy arrays cannot shrink, so only length-preserving
    mutations land on raw buffers; zstd packs accept any mutation. One
    ``is None`` check when no injector is installed."""
    if faults.active_injector() is None:
        return
    for si, snap in enumerate(snaps):
        dtype, data, validity, chars, children = snap
        for bi, buf in enumerate((data, validity, chars)):
            if buf is None:
                continue
            if isinstance(buf, tuple):  # ("zstd", dtype_str, shape, blob)
                blob = buf[3]
                mutated = faults.fire_corrupt(seam, eid, blob)
                if mutated is blob:
                    continue
                new_buf = (buf[0], buf[1], buf[2], mutated)
            else:
                raw = buf.tobytes()
                mutated = faults.fire_corrupt(seam, eid, raw)
                if mutated is raw or len(mutated) != len(raw):
                    continue
                new_buf = np.frombuffer(
                    bytearray(mutated), dtype=buf.dtype).reshape(buf.shape)
            bufs = [data, validity, chars]
            bufs[bi] = new_buf
            snaps[si] = (dtype, bufs[0], bufs[1], bufs[2], children)
            return


class SpillStore:
    """HBM pressure valve — the role RMM's spillable pool plays for the
    Spark plugin: registered tables count against a device budget; when a
    new registration would exceed it, least-recently-used tables SPILL to
    host numpy copies (freeing their HBM the moment the JAX arrays drop),
    and touching a spilled table stages it back, spilling others if needed.

    Deliberate scope: inter-OPERATOR working sets (shuffle partitions,
    chunked-read batches, cached build sides) — not intra-kernel memory,
    which belongs to XLA's own arena. Thread-safe; spill/unspill events log
    under ``memory.log_level`` >= 1.
    """

    def __init__(self, budget_bytes: int, compress_spill: bool = False,
                 compress_level: int = 3,
                 spill_dir: "str | None" = None):
        """``compress_spill`` zstd-compresses spilled host buffers (the
        nvcomp general-codec role on the host path): logical HBM bytes
        stay the accounting unit; ``stats()['host_stored_bytes']``
        reports the actual compressed footprint.

        ``spill_dir`` (default: the ``memory.spill_dir`` option; "" =
        off) moves spilled payloads from host memory to files in that
        directory. Files are written crash-safe — tmp + ``os.replace``
        + fsync + read-back verify — and carry the integrity trailer
        when ``integrity.enabled``, so a torn write or bitrot on the
        spill device is a classified ``CorruptDataError`` at unspill,
        never silently wrong bytes staged back to HBM."""
        if budget_bytes <= 0:
            raise ValueError("budget must be positive")
        self.budget = int(budget_bytes)
        if spill_dir is None:
            spill_dir = str(get_option("memory.spill_dir")) or None
        self._spill_dir = spill_dir
        if self._spill_dir:
            os.makedirs(self._spill_dir, exist_ok=True)
            # stores may share a directory: namespace this store's files
            self._spill_prefix = f"spill-{os.getpid()}-{id(self):x}"
        else:
            self._spill_dir = None
            self._spill_prefix = ""
        self._lock = threading.Lock()
        self._next_id = 1
        # id -> dict(state="device"|"host", table|host_cols, nbytes, tick)
        self._entries: dict[int, dict] = {}
        self._tick = 0
        self.spill_count = 0
        self.unspill_count = 0
        # cumulative bytes moved across the PCIe-equivalent boundary
        self.spilled_bytes = 0
        self.unspilled_bytes = 0
        self._cctx = None
        self._dctx = None
        if compress_spill:
            # the shared availability guard (runtime/compress.py) — wire
            # and spill can never disagree on whether zstandard exists
            self._cctx, self._dctx = compress.zstd_codec(compress_level)

    def _device_bytes_locked(self) -> int:
        return sum(e["nbytes"] for e in self._entries.values()
                   if e["state"] == "device")

    @property
    def device_bytes(self) -> int:
        with self._lock:
            return self._device_bytes_locked()

    def _coldest_device_locked(self) -> "int | None":
        """Handle of the least-recently-used resident entry, or None."""
        candidates = [
            (e["tick"], eid) for eid, e in self._entries.items()
            if e["state"] == "device"
        ]
        if not candidates:
            return None
        _, eid = min(candidates)
        return eid

    def _spill_entry_locked(self, eid: int, reason: str) -> int:
        """Spill one resident entry to host; returns its device bytes."""
        e = self._entries[eid]
        # fire before mutating the entry: an injected spill-IO failure
        # must leave the victim resident and the store consistent
        faults.fire("spill.spill", eid, nbytes=e["nbytes"])
        seam = e.get("iseam", "integrity.spill")
        # compress -> seal ordering: the codec re-encode happens INSIDE
        # the snapshot (per buffer), before the crc / trailer is taken
        # over it, so verification always covers the compressed bytes
        codec_seam = seam if compress.seam_enabled(seam) else None
        with spans.child("spill", handle=eid, nbytes=e["nbytes"]):
            e["host_cols"] = [
                _col_to_host(c, self._cctx, codec_seam)
                for c in e["table"].columns]
            if self._spill_dir is not None:
                # disk tier: pickle the snapshot, seal it, write it
                # crash-safe (tmp + os.replace + read-back verify)
                payload = pickle.dumps(
                    e["host_cols"], protocol=pickle.HIGHEST_PROTOCOL)
                sealed = integrity.enabled()
                blob = integrity.seal(payload) if sealed else payload
                blob = faults.fire_corrupt(seam, eid, blob, nbytes=e["nbytes"])
                path = os.path.join(
                    self._spill_dir, f"{self._spill_prefix}-{eid}.bin")
                integrity.write_payload_file(path, blob)
                e["host_cols"] = None
                e["path"] = path
                e["sealed"] = sealed
                e["stored_bytes"] = len(blob)
            elif integrity.enabled():
                # in-memory tier: checksum the packed snapshot now so
                # unspill can prove the host copy never drifted
                e["crc"] = integrity.snaps_checksum(e["host_cols"])
                _inject_snap_corruption(e["host_cols"], seam, eid)
        e["table"] = None  # drop the device arrays -> XLA frees HBM
        e["state"] = "disk" if self._spill_dir is not None else "host"
        self.spill_count += 1
        self.spilled_bytes += e["nbytes"]
        telemetry.record_spill(
            "spill_store", reason,
            bytes_moved=e["nbytes"], direction="device_to_host")
        if get_option("memory.log_level") >= 1:
            _log.info("spill table %d (%d bytes) to host", eid,
                      e["nbytes"])
        return e["nbytes"]

    def _spill_lru_locked(self, need: int) -> None:
        """Spill least-recently-used device entries until ``need`` fits."""
        while self._device_bytes_locked() + need > self.budget:
            eid = self._coldest_device_locked()
            if eid is None:
                raise MemoryLimitExceeded(
                    f"table of {need} bytes exceeds the spill budget "
                    f"({self.budget}) even with everything spilled"
                )
            self._spill_entry_locked(
                eid, "device spill budget exceeded: LRU eviction to host")

    def spill_coldest(self, nbytes: int) -> int:
        """Proactively spill coldest-first resident entries until at least
        ``nbytes`` device bytes are freed (or nothing is left resident).

        The memory-pressure valve: a :class:`MemoryLimiter` crossing its
        high watermark calls this on its attached store so HBM held by
        idle inter-operator working sets drains before new admissions
        resume. Returns the bytes actually freed."""
        freed = 0
        with self._lock:
            while freed < nbytes:
                eid = self._coldest_device_locked()
                if eid is None:
                    break
                freed += self._spill_entry_locked(
                    eid, "memory pressure: proactive spill of coldest entry")
        return freed

    def spill(self, handle: int) -> int:
        """Demote ONE entry to the host/disk tier (no-op if already
        spilled). The result cache's shed path: evicting a cached result
        from HBM must keep the integrity-sealed host copy so a later hit
        can stage it back verified. Returns the device bytes freed."""
        with self._lock:
            e = self._entries.get(handle)
            if e is None:
                raise KeyError(f"unknown spill-store handle {handle}")
            if e["state"] != "device":
                return 0
            return self._spill_entry_locked(
                handle, "result cache shed: demote cached entry to host")

    def state(self, handle: int) -> str:
        """Residency tier of an entry ("device" | "host" | "disk") without
        touching its LRU tick — lets the result cache reconcile limiter
        charges after this store's own LRU spilled a cache entry."""
        with self._lock:
            e = self._entries.get(handle)
            if e is None:
                raise KeyError(f"unknown spill-store handle {handle}")
            return e["state"]

    def put(self, table, *, integrity_seam: str = "integrity.spill") -> int:
        """Register a device table; returns its handle. May spill others.

        ``integrity_seam`` tags which verification boundary this entry's
        payload belongs to (``integrity.spill`` for plain working sets,
        ``integrity.checkpoint`` for out-of-core partials) — it routes
        both the corruption-injection window and the mismatch
        classification, so a corrupt checkpoint is distinguishable from
        a corrupt spill in telemetry and recovery."""
        nbytes = _table_nbytes(table)
        with self._lock:
            self._spill_lru_locked(nbytes)
            self._tick += 1
            eid = self._next_id
            self._next_id += 1
            self._entries[eid] = {
                "state": "device", "table": table, "host_cols": None,
                "nbytes": nbytes, "tick": self._tick,
                "iseam": str(integrity_seam),
            }
            return eid

    def get(self, handle: int):
        """Fetch a table, staging it back to device if it was spilled."""
        from spark_rapids_jni_tpu.columnar import Table

        with self._lock:
            e = self._entries.get(handle)
            if e is None:
                raise KeyError(f"unknown spill-store handle {handle}")
            self._tick += 1
            e["tick"] = self._tick
            if e["state"] == "device":
                return e["table"]
            # fire before any staging: an injected unspill failure must
            # leave the entry spilled (host copy intact, retryable)
            faults.fire("spill.unspill", handle, nbytes=e["nbytes"])
            seam = e.get("iseam", "integrity.spill")
            with spans.child("unspill", handle=handle, nbytes=e["nbytes"]):
                # verify BEFORE any byte is decoded or staged: a corrupt
                # payload raises classified CorruptDataError with the
                # entry still spilled (file/host copy untouched, so the
                # owning seam can replay from source or die with a
                # flight record — never stage garbage to HBM)
                if e["state"] == "disk":
                    blob = integrity.read_payload_file(
                        e["path"], seam=seam, sealed=e["sealed"],
                        op="spill_store.get", handle=handle)
                    snaps = pickle.loads(blob)
                elif e.get("crc") is not None:
                    snaps = e["host_cols"]
                    integrity.verify_snaps(
                        snaps, e["crc"], seam=seam,
                        op="spill_store.get", handle=handle)
                else:
                    snaps = e["host_cols"]
                self._spill_lru_locked(e["nbytes"])
                cols = [
                    _col_from_host(snap, self._dctx, seam)
                    for snap in snaps]
            e["table"] = Table(cols)
            e["host_cols"] = None
            e["crc"] = None
            if e["state"] == "disk":
                _unlink_quiet(e.pop("path"))
                e.pop("stored_bytes", None)
            e["state"] = "device"
            self.unspill_count += 1
            self.unspilled_bytes += e["nbytes"]
            telemetry.record_spill(
                "spill_store",
                "spilled table touched: staging back to device",
                bytes_moved=e["nbytes"], direction="host_to_device")
            if get_option("memory.log_level") >= 1:
                _log.info("unspill table %d (%d bytes)", handle, e["nbytes"])
            return e["table"]

    def get_reserved(self, handle: int, limiter: MemoryLimiter):
        """Fetch a table with its device bytes reserved against
        ``limiter`` BEFORE the host->device copy runs.

        Ordering contract: a spilled entry that would not fit the budget
        must raise ``MemoryLimitExceeded`` before ANY device staging
        happens — reserving after ``get`` would let the unspill allocate
        first and account later, exactly the over-commit window the
        limiter exists to close (and the window a prefetching pipeline
        widens, since unspills race concurrent chunk admissions there).
        Returns ``(table, nbytes)``; on success the CALLER owns the
        reservation. On any failure — including the reserve itself —
        no reservation is left behind.
        """
        nb = self.nbytes(handle)
        limiter.reserve(nb)
        try:
            return self.get(handle), nb
        except BaseException:
            limiter.release(nb)
            raise

    def nbytes(self, handle: int) -> int:
        """Logical (device) size of a stored table WITHOUT staging it —
        lets callers reserve budget before a ``get`` faults bytes in."""
        with self._lock:
            if handle not in self._entries:
                raise KeyError(f"unknown spill handle {handle}")
            return self._entries[handle]["nbytes"]

    def stored_nbytes(self, handle: int) -> int:
        """RESIDENT footprint of one entry in its current tier: logical
        HBM bytes while device-resident, the (possibly codec-compressed)
        packed snapshot bytes on the host tier, the sealed file size on
        the disk tier. The result cache's LRU charges this — compressed
        entries make the same ``cache.max_bytes`` hold more results."""
        with self._lock:
            e = self._entries.get(handle)
            if e is None:
                raise KeyError(f"unknown spill handle {handle}")
            if e["state"] == "device":
                return e["nbytes"]
            if e["state"] == "disk":
                return int(e.get("stored_bytes", 0))
            return sum(_host_snap_nbytes(s) for s in e["host_cols"])

    def drop(self, handle: int) -> None:
        with self._lock:
            e = self._entries.pop(handle, None)
            if e is not None and e["state"] == "disk":
                _unlink_quiet(e.get("path"))

    def close(self) -> None:
        """Release every entry and unlink this store's spill files."""
        with self._lock:
            for e in self._entries.values():
                if e["state"] == "disk":
                    _unlink_quiet(e.get("path"))
            self._entries.clear()

    def stats(self) -> dict:
        with self._lock:
            device = self._device_bytes_locked()
            host = sum(e["nbytes"] for e in self._entries.values()
                       if e["state"] == "host")
            stored = sum(
                sum(_host_snap_nbytes(s) for s in e["host_cols"])
                for e in self._entries.values() if e["state"] == "host"
            )
            disk = sum(e["nbytes"] for e in self._entries.values()
                       if e["state"] == "disk")
            disk_stored = sum(
                e.get("stored_bytes", 0)
                for e in self._entries.values() if e["state"] == "disk")
            return {
                "device_bytes": device, "host_bytes": host,
                "host_stored_bytes": stored,  # compressed footprint
                "disk_bytes": disk,  # logical HBM bytes parked on disk
                "disk_stored_bytes": disk_stored,  # file footprint
                "spill_dir": self._spill_dir or "",
                "budget_bytes": self.budget,
                "spills": self.spill_count, "unspills": self.unspill_count,
                "spilled_bytes": self.spilled_bytes,
                "unspilled_bytes": self.unspilled_bytes,
                "tables": len(self._entries),
            }
