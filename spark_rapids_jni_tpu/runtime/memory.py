"""Device/host memory management — the RMM-equivalent layer.

The reference leans on RMM for device memory pools, per-thread streams and
an ``RMM_LOGGING_LEVEL`` knob (reference pom.xml:82, CMakeLists.txt:56-63;
rmm::device_uvector use throughout row_conversion.cu). On TPU the HBM
allocator itself belongs to XLA — JAX arrays live in XLA's BFC arena, and
re-implementing that would fight the runtime. What this layer provides is
the part of RMM's surface a Spark executor actually interacts with:

  * ``device_memory_stats()`` — live/peak/limit HBM numbers per device
    (RMM's ``mr.get_info`` role) for spill decisions and telemetry;
  * ``MemoryLimiter`` — a soft budget gate: reserve/release accounting
    with the same fail-fast contract as a capped RMM pool, used by the
    chunked reader to size batches;
  * ``HostStagingPool`` — recycled pinned-style host buffers for the
    parquet/IO staging path (the role of RMM's pinned-host pool), a size-
    class freelist so repeated chunked reads stop hammering the allocator;
  * allocation logging behind ``memory.log_level``
    (env SPARK_RAPIDS_TPU_MEMORY_LOG_LEVEL) — RMM_LOGGING_LEVEL parity.
"""

from __future__ import annotations

import collections
import threading
import time
from dataclasses import dataclass
from typing import NamedTuple, Optional

import numpy as np

from spark_rapids_jni_tpu import telemetry
from spark_rapids_jni_tpu.runtime import faults
from spark_rapids_jni_tpu.utils.config import get_option
from spark_rapids_jni_tpu.utils.log import get_logger

_log = get_logger("spark_rapids_jni_tpu.memory")


@dataclass(frozen=True)
class DeviceMemoryStats:
    bytes_in_use: int
    peak_bytes_in_use: int
    bytes_limit: int

    @property
    def bytes_free(self) -> int:
        return max(self.bytes_limit - self.bytes_in_use, 0)


def device_memory_stats(device=None) -> DeviceMemoryStats:
    """Live HBM stats from the XLA allocator (zeros when the backend does
    not report — e.g. some CPU builds)."""
    import jax

    if device is None:
        device = jax.devices()[0]
    stats = {}
    try:
        stats = device.memory_stats() or {}
    except (RuntimeError, AttributeError):
        pass
    return DeviceMemoryStats(
        bytes_in_use=int(stats.get("bytes_in_use", 0)),
        peak_bytes_in_use=int(stats.get("peak_bytes_in_use", 0)),
        bytes_limit=int(stats.get("bytes_limit", 0)),
    )


class MemoryLimitExceeded(MemoryError):
    pass


class MemoryLimiter:
    """Soft budget gate with capped-pool semantics: ``reserve`` beyond the
    budget raises (fail-fast, like a capped RMM pool) instead of letting a
    giant batch OOM the device mid-kernel."""

    def __init__(self, budget_bytes: int):
        if budget_bytes <= 0:
            raise ValueError("budget must be positive")
        self.budget = int(budget_bytes)
        self._used = 0
        self._peak = 0
        # a Condition so reserve_blocking can sleep until release() frees
        # budget; plain reserve/release take the same underlying lock
        self._lock = threading.Condition()
        # FIFO queue of blocked reserve_blocking tickets: budget freed by a
        # release is offered to the longest-waiting reserver first, so a
        # small late request cannot barge past a large early one forever
        self._waiters: "collections.deque[object]" = collections.deque()

    @property
    def used(self) -> int:
        return self._used

    @property
    def peak(self) -> int:
        return self._peak

    def reserve(self, nbytes: int) -> None:
        # fault seam BEFORE the lock: an injected reservation failure must
        # leave the accounting untouched, like a real allocator rejection
        faults.fire("memory.reserve", nbytes, blocking=False)
        with self._lock:
            if self._used + nbytes > self.budget:
                raise MemoryLimitExceeded(
                    f"reservation of {nbytes} bytes exceeds budget "
                    f"({self._used}/{self.budget} in use)"
                )
            self._used += nbytes
            self._peak = max(self._peak, self._used)
            if get_option("memory.log_level") >= 2:
                _log.info("reserve %d bytes (%d in use)", nbytes, self._used)

    def reserve_blocking(self, nbytes: int, cancel=None,
                         timeout: "float | None" = None) -> bool:
        """Wait until ``nbytes`` fits inside the budget, then reserve it.

        The pipeline's backpressure primitive: where ``reserve`` fails
        loud, this form parks the producer until a consumer ``release``
        frees room, so a tight budget degrades throughput toward serial
        instead of raising mid-run. A request larger than the WHOLE
        budget can never fit and raises ``MemoryLimitExceeded``
        immediately (same contract as ``reserve``). Returns True on
        success, False if ``cancel`` (a threading.Event) was set or
        ``timeout`` seconds elapsed first — cancellation is polled, so
        a cancelled producer wakes within ~50ms.

        Ordering contract: concurrent blocked reservers are served FIFO —
        freed budget goes to the longest-waiting request first, and a
        later (even smaller) request never barges past an earlier blocked
        one. A plain ``reserve`` keeps its fail-fast semantics and does
        not queue.
        """
        faults.fire("memory.reserve", nbytes, blocking=True)
        if nbytes > self.budget:
            raise MemoryLimitExceeded(
                f"reservation of {nbytes} bytes exceeds the whole budget "
                f"({self.budget}): can never fit"
            )
        deadline = None if timeout is None else time.monotonic() + timeout
        ticket = object()
        with self._lock:
            self._waiters.append(ticket)
            try:
                # grant only at head-of-line AND when the bytes fit: a
                # blocked earlier ticket holds back every later one, which
                # is exactly the no-barge property
                while (self._waiters[0] is not ticket
                       or self._used + nbytes > self.budget):
                    if cancel is not None and cancel.is_set():
                        return False
                    wait = 0.05
                    if deadline is not None:
                        remaining = deadline - time.monotonic()
                        if remaining <= 0:
                            return False
                        wait = min(wait, remaining)
                    self._lock.wait(wait)
                self._used += nbytes
                self._peak = max(self._peak, self._used)
                if get_option("memory.log_level") >= 2:
                    _log.info(
                        "reserve %d bytes (%d in use)", nbytes, self._used)
            finally:
                # leaving for ANY reason (granted, cancelled, timed out)
                # unblocks the next ticket in line
                self._waiters.remove(ticket)
                self._lock.notify_all()
        return True

    def release(self, nbytes: int) -> None:
        with self._lock:
            self._used = max(self._used - nbytes, 0)
            self._lock.notify_all()
            if get_option("memory.log_level") >= 2:
                _log.info("release %d bytes (%d in use)", nbytes, self._used)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        with self._lock:
            self._used = 0
            self._lock.notify_all()
        return False


class HostStagingPool:
    """Freelist of host staging buffers, bucketed by power-of-two size.

    ``take(nbytes)`` returns a uint8 array of at least nbytes (callers
    slice); ``give(buf)`` recycles it. Thread-safe; bounded per bucket so a
    burst cannot pin unbounded host memory."""

    def __init__(self, max_buffers_per_class: int = 8):
        self._free: dict[int, list[np.ndarray]] = {}
        self._max = max_buffers_per_class
        self._lock = threading.Lock()
        self.hits = 0
        self.misses = 0

    @staticmethod
    def _size_class(nbytes: int) -> int:
        return 1 << max(int(nbytes - 1).bit_length(), 6)  # min 64B

    def take(self, nbytes: int) -> np.ndarray:
        cls = self._size_class(max(nbytes, 1))
        with self._lock:
            bucket = self._free.get(cls)
            if bucket:
                self.hits += 1
                return bucket.pop()
            self.misses += 1
        if get_option("memory.log_level") >= 1:
            _log.info("staging alloc %d bytes (class %d)", nbytes, cls)
        return np.empty(cls, dtype=np.uint8)

    def give(self, buf: np.ndarray) -> None:
        cls = int(buf.nbytes)
        # only recycle buffers this pool could have produced: uint8,
        # power-of-two size, at least the minimum size class
        if buf.dtype != np.uint8 or cls < 64 or cls & (cls - 1):
            return
        with self._lock:
            bucket = self._free.setdefault(cls, [])
            if len(bucket) < self._max:
                bucket.append(buf)

    def clear(self) -> None:
        with self._lock:
            self._free.clear()


_default_pool: Optional[HostStagingPool] = None
_default_pool_lock = threading.Lock()


def default_staging_pool() -> HostStagingPool:
    global _default_pool
    with _default_pool_lock:
        if _default_pool is None:
            _default_pool = HostStagingPool()
        return _default_pool


# ---- spill store (the RMM arena's overflow valve) --------------------------


def _col_nbytes(c) -> int:
    total = int(np.prod(c.data.shape)) * c.data.dtype.itemsize
    if c.validity is not None:
        total += int(c.validity.shape[0])
    if c.chars is not None:
        total += int(np.prod(c.chars.shape))
    for child in (c.children or ()):
        total += _col_nbytes(child)
    return total


def _table_nbytes(table) -> int:
    return sum(_col_nbytes(c) for c in table.columns)


def _pack_array(arr, cctx):
    """Optionally zstd-compress one host buffer (the nvcomp role for the
    HOST path: spilled working sets, future DCN exchange). Returns the
    plain array when compression is off."""
    if arr is None:
        return None
    a = np.ascontiguousarray(arr)
    if cctx is None:
        return a
    # compress() takes buffer-protocol objects — no tobytes() copy
    return ("zstd", a.dtype.str, a.shape, cctx.compress(a))


def _unpack_array(obj, dctx):
    if obj is None or not isinstance(obj, tuple):
        return obj
    _, dtype_str, shape, blob = obj
    return np.frombuffer(
        dctx.decompress(blob), dtype=np.dtype(dtype_str)).reshape(shape)


def _packed_nbytes(obj) -> int:
    if obj is None:
        return 0
    if isinstance(obj, tuple):
        return len(obj[3])
    return obj.nbytes


def _col_to_host(c, cctx=None) -> tuple:
    """Recursive host snapshot of a column (incl. LIST/STRUCT children)."""
    return (
        c.dtype,
        _pack_array(np.asarray(c.data), cctx),
        None if c.validity is None
        else _pack_array(np.asarray(c.validity), cctx),
        None if c.chars is None else _pack_array(np.asarray(c.chars), cctx),
        None if not c.children
        else [_col_to_host(ch, cctx) for ch in c.children],
    )


def _col_from_host(snap, dctx=None):
    import jax.numpy as jnp

    from spark_rapids_jni_tpu.columnar import Column

    dtype, data, validity, chars, children = snap
    return Column(
        dtype, jnp.asarray(_unpack_array(data, dctx)),
        None if validity is None
        else jnp.asarray(_unpack_array(validity, dctx)),
        chars=None if chars is None
        else jnp.asarray(_unpack_array(chars, dctx)),
        children=None if children is None
        else [_col_from_host(ch, dctx) for ch in children],
    )


class HostTableChunk(NamedTuple):
    """A host-decoded table chunk awaiting device staging.

    ``cols`` holds column snapshots in the ``_col_to_host`` format
    (dtype, data, validity, chars, children — all numpy); ``nbytes`` is
    the exact device footprint ``stage()`` will allocate. The pipelined
    executor decodes chunks to this form in its read/decode stage so the
    MemoryLimiter reservation can be taken on exact bytes BEFORE the
    host->device copy — backpressure that cannot over-commit the budget
    on a size guess."""

    cols: tuple
    nbytes: int
    num_rows: int

    def stage(self):
        """Host->device copy. Callers reserve ``nbytes`` first."""
        from spark_rapids_jni_tpu.columnar import Table

        return Table([_col_from_host(snap) for snap in self.cols])


def host_table_chunk(snaps, num_rows: int) -> HostTableChunk:
    snaps = tuple(snaps)
    return HostTableChunk(
        snaps, sum(_host_snap_nbytes(s) for s in snaps), int(num_rows))


def _host_snap_nbytes(snap) -> int:
    _, data, validity, chars, children = snap
    n = (_packed_nbytes(data) + _packed_nbytes(validity)
         + _packed_nbytes(chars))
    for ch in (children or []):
        n += _host_snap_nbytes(ch)
    return n


class SpillStore:
    """HBM pressure valve — the role RMM's spillable pool plays for the
    Spark plugin: registered tables count against a device budget; when a
    new registration would exceed it, least-recently-used tables SPILL to
    host numpy copies (freeing their HBM the moment the JAX arrays drop),
    and touching a spilled table stages it back, spilling others if needed.

    Deliberate scope: inter-OPERATOR working sets (shuffle partitions,
    chunked-read batches, cached build sides) — not intra-kernel memory,
    which belongs to XLA's own arena. Thread-safe; spill/unspill events log
    under ``memory.log_level`` >= 1.
    """

    def __init__(self, budget_bytes: int, compress_spill: bool = False,
                 compress_level: int = 3):
        """``compress_spill`` zstd-compresses spilled host buffers (the
        nvcomp general-codec role on the host path): logical HBM bytes
        stay the accounting unit; ``stats()['host_stored_bytes']``
        reports the actual compressed footprint."""
        if budget_bytes <= 0:
            raise ValueError("budget must be positive")
        self.budget = int(budget_bytes)
        self._lock = threading.Lock()
        self._next_id = 1
        # id -> dict(state="device"|"host", table|host_cols, nbytes, tick)
        self._entries: dict[int, dict] = {}
        self._tick = 0
        self.spill_count = 0
        self.unspill_count = 0
        # cumulative bytes moved across the PCIe-equivalent boundary
        self.spilled_bytes = 0
        self.unspilled_bytes = 0
        self._cctx = None
        self._dctx = None
        if compress_spill:
            import zstandard as zstd

            self._cctx = zstd.ZstdCompressor(level=compress_level)
            self._dctx = zstd.ZstdDecompressor()

    def _device_bytes_locked(self) -> int:
        return sum(e["nbytes"] for e in self._entries.values()
                   if e["state"] == "device")

    @property
    def device_bytes(self) -> int:
        with self._lock:
            return self._device_bytes_locked()

    def _spill_lru_locked(self, need: int) -> None:
        """Spill least-recently-used device entries until ``need`` fits."""
        while self._device_bytes_locked() + need > self.budget:
            candidates = [
                (e["tick"], eid) for eid, e in self._entries.items()
                if e["state"] == "device"
            ]
            if not candidates:
                raise MemoryLimitExceeded(
                    f"table of {need} bytes exceeds the spill budget "
                    f"({self.budget}) even with everything spilled"
                )
            _, eid = min(candidates)
            e = self._entries[eid]
            # fire before mutating the entry: an injected spill-IO failure
            # must leave the victim resident and the store consistent
            faults.fire("spill.spill", eid, nbytes=e["nbytes"])
            e["host_cols"] = [
                _col_to_host(c, self._cctx) for c in e["table"].columns]
            e["table"] = None  # drop the device arrays -> XLA frees HBM
            e["state"] = "host"
            self.spill_count += 1
            self.spilled_bytes += e["nbytes"]
            telemetry.record_spill(
                "spill_store",
                "device spill budget exceeded: LRU eviction to host",
                bytes_moved=e["nbytes"], direction="device_to_host")
            if get_option("memory.log_level") >= 1:
                _log.info("spill table %d (%d bytes) to host", eid,
                          e["nbytes"])

    def put(self, table) -> int:
        """Register a device table; returns its handle. May spill others."""
        nbytes = _table_nbytes(table)
        with self._lock:
            self._spill_lru_locked(nbytes)
            self._tick += 1
            eid = self._next_id
            self._next_id += 1
            self._entries[eid] = {
                "state": "device", "table": table, "host_cols": None,
                "nbytes": nbytes, "tick": self._tick,
            }
            return eid

    def get(self, handle: int):
        """Fetch a table, staging it back to device if it was spilled."""
        from spark_rapids_jni_tpu.columnar import Table

        with self._lock:
            e = self._entries.get(handle)
            if e is None:
                raise KeyError(f"unknown spill-store handle {handle}")
            self._tick += 1
            e["tick"] = self._tick
            if e["state"] == "device":
                return e["table"]
            # fire before any staging: an injected unspill failure must
            # leave the entry spilled (host copy intact, retryable)
            faults.fire("spill.unspill", handle, nbytes=e["nbytes"])
            self._spill_lru_locked(e["nbytes"])
            cols = [
                _col_from_host(snap, self._dctx) for snap in e["host_cols"]]
            e["table"] = Table(cols)
            e["host_cols"] = None
            e["state"] = "device"
            self.unspill_count += 1
            self.unspilled_bytes += e["nbytes"]
            telemetry.record_spill(
                "spill_store",
                "spilled table touched: staging back to device",
                bytes_moved=e["nbytes"], direction="host_to_device")
            if get_option("memory.log_level") >= 1:
                _log.info("unspill table %d (%d bytes)", handle, e["nbytes"])
            return e["table"]

    def get_reserved(self, handle: int, limiter: MemoryLimiter):
        """Fetch a table with its device bytes reserved against
        ``limiter`` BEFORE the host->device copy runs.

        Ordering contract: a spilled entry that would not fit the budget
        must raise ``MemoryLimitExceeded`` before ANY device staging
        happens — reserving after ``get`` would let the unspill allocate
        first and account later, exactly the over-commit window the
        limiter exists to close (and the window a prefetching pipeline
        widens, since unspills race concurrent chunk admissions there).
        Returns ``(table, nbytes)``; on success the CALLER owns the
        reservation. On any failure — including the reserve itself —
        no reservation is left behind.
        """
        nb = self.nbytes(handle)
        limiter.reserve(nb)
        try:
            return self.get(handle), nb
        except BaseException:
            limiter.release(nb)
            raise

    def nbytes(self, handle: int) -> int:
        """Logical (device) size of a stored table WITHOUT staging it —
        lets callers reserve budget before a ``get`` faults bytes in."""
        with self._lock:
            if handle not in self._entries:
                raise KeyError(f"unknown spill handle {handle}")
            return self._entries[handle]["nbytes"]

    def drop(self, handle: int) -> None:
        with self._lock:
            self._entries.pop(handle, None)

    def stats(self) -> dict:
        with self._lock:
            device = self._device_bytes_locked()
            host = sum(e["nbytes"] for e in self._entries.values()
                       if e["state"] == "host")
            stored = sum(
                sum(_host_snap_nbytes(s) for s in e["host_cols"])
                for e in self._entries.values() if e["state"] == "host"
            )
            return {
                "device_bytes": device, "host_bytes": host,
                "host_stored_bytes": stored,  # compressed footprint
                "budget_bytes": self.budget,
                "spills": self.spill_count, "unspills": self.unspill_count,
                "spilled_bytes": self.spilled_bytes,
                "unspilled_bytes": self.unspilled_bytes,
                "tables": len(self._entries),
            }
