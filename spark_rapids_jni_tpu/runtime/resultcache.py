"""Plan-signature result & subplan cache.

Production query traffic is wildly repetitive — dashboards re-issue the
same plans against slowly-changing data — yet every admitted query pays
admission, compile, and full execution even when an identical plan ran
seconds ago. Sparkle (PAPERS.md) makes the case that sharing materialized
intermediates across queries dominates once kernels are fast; Flare shows
plan-level specialization only pays when repeated plans amortize it. This
module cashes both in for the serving runtime (``runtime/server.py``):

* **Final results** — a :class:`ResultCache` memoizes whole-query
  ``FusedResult``s keyed by :class:`CacheKey` ``(plan signature, input
  fingerprint)``. A hit in ``QueryServer.submit`` short-circuits
  admission, compile, and execution, returning the cached table
  bit-identically under a ``cache.hit`` span.
* **Subplan intermediates** — :func:`apply_subplans` hashes canonicalized
  scan+filter+project prefixes (``fusion.scan_prefix_chains``), so two
  distinct plans sharing a prefix execute the shared region exactly once
  and the second reuses the materialized intermediate.

Keying. The signature half is a sha256 over the fusion IR's structural
fingerprint (node kinds, qualified callable names, static params, resolved
row specs — ``fusion.plan_fingerprint``); the fingerprint half digests the
bound input CONTENT (every column buffer, dtype and shape, memoized per
Table object), so slowly-changing data invalidates exactly when it
changes. ``source_fingerprint`` offers the cheap path+size+mtime digest
for file-backed scans. Both halves are mandatory: a ``get``/``put`` whose
key lacks the input fingerprint raises (tpulint rule 16
``cache-key-must-fingerprint`` enforces the static half at call sites).

Storage. Entries live in the server's shared :class:`SpillStore` under
the ``integrity.cache`` seam: a fresh entry shares the just-computed
result's device buffers (zero copy) and rides the store's integrity-sealed
host/disk tiers under pressure, verifying at read — a corrupt cached
payload is a classified discard-and-recompute, never wrong bytes served.

Accounting. Resident entries are charged against the shared
``MemoryLimiter`` so cached results can never starve live queries, and
they are the FIRST thing pressure evicts: the limiter's high-watermark
reaction sheds cache entries (demote to host tier + release charge)
before any live query's working set spills, and a parked query's drain
threshold discounts evictable cache bytes (``memory.py``). Capacity is an
LRU in RESIDENT (stored) bytes (``cache.max_bytes``): entries demoted to
the host/disk tier count at their codec-compressed footprint
(``compress.py``), so the same budget holds more results; ``stats()``
reports both ``bytes`` (logical) and ``stored_bytes`` (resident).

Config: ``cache.enabled`` / ``cache.max_bytes`` / ``cache.subplan_enabled``
(env ``SPARK_RAPIDS_TPU_CACHE_*``). Off restores today's serving path
byte-for-byte.
"""

from __future__ import annotations

import collections
import hashlib
import os
import threading
from typing import NamedTuple, Optional

import numpy as np

from spark_rapids_jni_tpu.runtime import fusion, resilience
from spark_rapids_jni_tpu.runtime.memory import (
    HostTableChunk,
    MemoryLimitExceeded,
    MemoryLimiter,
    SpillStore,
    _table_nbytes,
)
from spark_rapids_jni_tpu.telemetry.events import (
    record_cache,
    record_integrity,
)
from spark_rapids_jni_tpu.telemetry import spans
from spark_rapids_jni_tpu.telemetry.registry import REGISTRY
from spark_rapids_jni_tpu.utils.config import get_option
from spark_rapids_jni_tpu.utils.log import get_logger

__all__ = [
    "CacheKey",
    "ResultCache",
    "enabled",
    "subplan_enabled",
    "cache_key",
    "plan_signature",
    "input_fingerprint",
    "table_fingerprint",
    "source_fingerprint",
    "apply_subplans",
]

_log = get_logger("spark_rapids_jni_tpu.resultcache")


def enabled() -> bool:
    """True when the ``cache.enabled`` option is on."""
    return bool(get_option("cache.enabled"))


def subplan_enabled() -> bool:
    return enabled() and bool(get_option("cache.subplan_enabled"))


class CacheKey(NamedTuple):
    """The two-part cache key. BOTH halves are mandatory: ``signature``
    identifies the computation (structural plan digest), ``fingerprint``
    identifies the input content — a key missing either would serve a
    stale result the moment the data (or the plan) changed."""

    signature: str
    fingerprint: str

    @property
    def short(self) -> str:
        return f"{self.signature[:12]}@{self.fingerprint[:12]}"


# ---------------------------------------------------------------------------
# key derivation
# ---------------------------------------------------------------------------


def plan_signature(plan: fusion.Plan, bindings: dict) -> str:
    """sha256 over the fusion IR's canonical structural fingerprint
    (``fusion.plan_fingerprint``): node kinds, qualified callable names,
    static params, resolved row-count statics. Excludes the plan's
    display name — identically-traced plans share results. Raises
    ``ValueError`` for plans whose callables are not module-level (they
    cannot be canonically named) and ``KeyError`` for unbound scans."""
    fp = fusion.plan_fingerprint(plan, bindings)
    return hashlib.sha256(repr(fp).encode()).hexdigest()


def _hash_buffer(h, buf) -> None:
    if buf is None:
        h.update(b"\xff")
        return
    if isinstance(buf, tuple):  # packed ("zstd", dtype_str, shape, blob)
        h.update(buf[1].encode())
        h.update(repr(buf[2]).encode())
        h.update(buf[3])
        return
    arr = np.ascontiguousarray(np.asarray(buf))
    h.update(str(arr.dtype).encode())
    h.update(repr(arr.shape).encode())
    h.update(arr.tobytes())


def _hash_column(h, col) -> None:
    h.update(repr(col.dtype).encode())
    _hash_buffer(h, col.data)
    _hash_buffer(h, col.validity)
    _hash_buffer(h, col.chars)
    for child in (col.children or ()):
        _hash_column(h, child)


def _hash_snap(h, snap) -> None:
    dtype, data, validity, chars, children = snap
    h.update(repr(dtype).encode())
    _hash_buffer(h, data)
    _hash_buffer(h, validity)
    _hash_buffer(h, chars)
    for ch in (children or ()):
        _hash_snap(h, ch)


def table_fingerprint(table) -> str:
    """Content digest of a device Table: every column's data/validity/
    chars buffers plus dtype and shape, recursively. Memoized on the
    Table object (JAX arrays are immutable, so a table's content never
    drifts under its fingerprint) — repeat submissions of the same bound
    table hash once."""
    cached = getattr(table, "_resultcache_fp", None)
    if cached is not None:
        return cached
    h = hashlib.sha256()
    for col in table.columns:
        _hash_column(h, col)
    fp = h.hexdigest()
    try:
        table._resultcache_fp = fp
    except (AttributeError, TypeError):
        pass  # slotted/frozen table: recompute next time
    return fp


def _chunk_fingerprint(chunk: HostTableChunk) -> str:
    h = hashlib.sha256()
    for snap in chunk.cols:
        _hash_snap(h, snap)
    return h.hexdigest()


def source_fingerprint(path: str) -> str:
    """Cheap file-backed-scan fingerprint: path + size + mtime digest —
    the invalidation handle for bindings too large to content-hash on
    every submit (pass it as ``submit(..., cache_fingerprint=...)``).
    Any rewrite of the source file changes it."""
    st = os.stat(path)
    token = f"{os.path.abspath(path)}\0{st.st_size}\0{st.st_mtime_ns}"
    return hashlib.sha256(token.encode()).hexdigest()


def input_fingerprint(bindings: dict) -> str:
    """Content digest over every bound input, name-keyed and
    order-independent. Device tables hash their buffers (memoized);
    host-decoded chunks hash their snapshots. Raises ``TypeError`` for
    bindings that are neither."""
    h = hashlib.sha256()
    for name in sorted(bindings):
        value = bindings[name]
        h.update(str(name).encode())
        h.update(b"\0")
        if isinstance(value, HostTableChunk):
            h.update(_chunk_fingerprint(value).encode())
        elif hasattr(value, "columns"):
            h.update(table_fingerprint(value).encode())
        else:
            raise TypeError(
                f"binding {name!r} is not fingerprintable: "
                f"{type(value).__name__}")
    return h.hexdigest()


def cache_key(plan: fusion.Plan, bindings: dict,
              fingerprint: Optional[str] = None) -> CacheKey:
    """Derive the full two-part key for one submission. ``fingerprint``
    overrides the content digest (e.g. a ``source_fingerprint`` the
    caller maintains for file-backed scans)."""
    fp = str(fingerprint) if fingerprint else input_fingerprint(bindings)
    if not fp:
        raise ValueError("cache key requires a non-empty input fingerprint")
    return CacheKey(plan_signature(plan, bindings), fp)


# ---------------------------------------------------------------------------
# meta snapshots — FusedResult.meta holds jax scalars; cached copies must
# not pin device buffers beyond the table the SpillStore manages
# ---------------------------------------------------------------------------


def _snap_meta(meta: dict) -> dict:
    out = {}
    for k, v in (meta or {}).items():
        if hasattr(v, "dtype") and hasattr(v, "shape"):
            out[k] = np.asarray(v)
        else:
            out[k] = v
    return out


def _rehydrate_meta(meta: dict) -> dict:
    import jax.numpy as jnp

    out = {}
    for k, v in (meta or {}).items():
        if isinstance(v, np.ndarray):
            out[k] = jnp.asarray(v)
        else:
            out[k] = v
    return out


# ---------------------------------------------------------------------------
# the cache
# ---------------------------------------------------------------------------


class ResultCache:
    """LRU of ``FusedResult``s stored through an integrity-sealed
    :class:`SpillStore`, byte-charged against a shared
    :class:`MemoryLimiter`.

    Locking: the cache's own RLock is taken FIRST, then (inside put/get/
    shed) the store's and limiter's locks — the limiter never takes the
    cache lock (it reads the lock-free ``evictable_bytes`` int and calls
    ``shed()`` outside its own lock; see
    ``MemoryLimiter.attach_result_cache``), so the ordering is acyclic.
    Reentrancy matters: a ``limiter.reserve`` inside ``put`` can cross the
    high watermark and call straight back into ``shed`` on this thread.
    """

    def __init__(self, store: SpillStore, limiter: MemoryLimiter,
                 max_bytes: Optional[int] = None):
        self._store = store
        self._limiter = limiter
        self._max_bytes_override = max_bytes
        self._lock = threading.RLock()
        # key -> {handle, nbytes, stored, meta, charged}; insertion order
        # IS the LRU order (move_to_end on touch)
        self._entries: "collections.OrderedDict[CacheKey, dict]" = (
            collections.OrderedDict())
        # two byte sums: _bytes is LOGICAL (uncompressed HBM-equivalent)
        # payload across all tiers; _stored_bytes is the RESIDENT
        # footprint (codec-compressed once an entry leaves the device
        # tier) and is what the LRU capacity bound charges — compressed
        # entries make the same cache.max_bytes hold more results
        self._bytes = 0
        self._stored_bytes = 0
        # resident limiter-charged bytes a pressure event could reclaim;
        # a PLAIN int read lock-free by the limiter (under ITS lock), so
        # it must always be updated in the same critical section as the
        # charge it mirrors
        self.evictable_bytes = 0

    def _max_bytes(self) -> int:
        if self._max_bytes_override is not None:
            return int(self._max_bytes_override)
        return int(get_option("cache.max_bytes"))

    @staticmethod
    def _validate_key(key) -> CacheKey:
        # the runtime half of tpulint rule 16: a signature-only key would
        # serve stale results across data changes — reject it loudly
        if not isinstance(key, CacheKey):
            raise ValueError(
                f"result-cache keys must be CacheKey instances, got "
                f"{type(key).__name__}")
        if not key.fingerprint or not str(key.fingerprint).strip():
            raise ValueError(
                "result-cache key is missing its input fingerprint "
                "(signature-only keying serves stale results)")
        if not key.signature or not str(key.signature).strip():
            raise ValueError("result-cache key is missing its plan signature")
        return key

    def _count(self, event: str) -> None:
        # unconditional, like the server's admission counters: hit/miss
        # accounting must hold whether or not telemetry is watching
        REGISTRY.counter(f"cache.{event}").inc()

    def _refresh_stored_locked(self, entry: dict) -> None:
        """Re-read one entry's resident footprint from the store (it
        shrinks to the codec-compressed size when the entry is demoted
        off the device tier, and grows back to logical on re-stage) and
        fold the delta into the LRU accounting."""
        try:
            stored = self._store.stored_nbytes(entry["handle"])
        except KeyError:
            return  # store closed / entry dropped under us: keep last
        self._stored_bytes += stored - entry["stored"]
        entry["stored"] = stored

    def _reconcile_locked(self, entry: dict) -> None:
        """The SpillStore's OWN LRU may have demoted a charged entry
        while making room for live working sets; fold that into the
        charge so the limiter never counts bytes HBM no longer holds."""
        self._refresh_stored_locked(entry)
        if not entry["charged"]:
            return
        try:
            state = self._store.state(entry["handle"])
        except KeyError:
            state = "host"  # store closed under us: treat as not resident
        if state != "device":
            entry["charged"] = False
            self.evictable_bytes -= entry["nbytes"]
            self._limiter.release(entry["nbytes"])

    def _uncharge_locked(self, entry: dict) -> None:
        if entry["charged"]:
            entry["charged"] = False
            self.evictable_bytes -= entry["nbytes"]
            self._limiter.release(entry["nbytes"])

    def _discard_locked(self, key: CacheKey, entry: dict,
                        event: str) -> None:
        self._uncharge_locked(entry)
        self._entries.pop(key, None)
        self._bytes -= entry["nbytes"]
        self._stored_bytes -= entry["stored"]
        try:
            self._store.drop(entry["handle"])
        except KeyError:
            pass
        self._count(event)

    def _shed_locked(self, nbytes: int) -> int:
        """Demote resident charged entries (coldest first) to the store's
        host/disk tier, releasing their limiter charges. Entries SURVIVE
        a shed — a later hit stages them back verified."""
        freed = 0
        for key, entry in list(self._entries.items()):
            if freed >= nbytes:
                break
            self._reconcile_locked(entry)
            if not entry["charged"]:
                continue
            try:
                self._store.spill(entry["handle"])
            except KeyError:
                self._discard_locked(key, entry, "eviction")
                continue
            self._uncharge_locked(entry)
            self._refresh_stored_locked(entry)
            freed += entry["nbytes"]
            record_cache("result_cache", "shed", key=key.short,
                         nbytes=entry["nbytes"])
        if freed:
            REGISTRY.counter("cache.shed_bytes").inc(freed)
        return freed

    def shed(self, nbytes: int) -> int:
        """The limiter's pressure hook: free up to ``nbytes`` of resident
        cache HBM before any live query's working set is spilled."""
        with self._lock:
            return self._shed_locked(max(int(nbytes), 0))

    def make_room(self, nbytes: int) -> int:
        """Displacement before an admission reserve: if ``nbytes`` does
        not currently fit the limiter's budget, shed enough resident
        cache bytes that it could — cached results never make a live
        query wait."""
        need = int(nbytes) - (self._limiter.budget - self._limiter.used)
        if need <= 0:
            return 0
        with self._lock:
            return self._shed_locked(need)

    def _charge_locked(self, nbytes: int) -> bool:
        """Reserve ``nbytes`` for a resident entry, shedding own colder
        entries to make room; False when the budget genuinely cannot
        take it (the entry then lives uncharged in the spilled tier)."""
        try:
            self._limiter.reserve(nbytes)
            return True
        except MemoryLimitExceeded:
            pass
        need = nbytes - (self._limiter.budget - self._limiter.used)
        if need > 0:
            self._shed_locked(need)
        try:
            self._limiter.reserve(nbytes)
            return True
        except MemoryLimitExceeded:
            return False

    def put(self, key: CacheKey, result: fusion.FusedResult) -> bool:
        """Memoize one result. The entry shares the result's device
        buffers (zero copy) and is charged against the limiter while
        resident; when the charge cannot fit it is demoted to the
        integrity-sealed host tier immediately instead of starving live
        queries. Returns True when the entry was stored."""
        if not enabled():
            return False
        self._validate_key(key)
        table = result.table
        nbytes = _table_nbytes(table)
        with self._lock:
            existing = self._entries.get(key)
            if existing is not None:
                self._entries.move_to_end(key)
                return True
            if nbytes > self._max_bytes():
                self._count("too_big")
                return False
            # LRU capacity bound charges RESIDENT (stored) bytes: demoted
            # entries count at their codec-compressed footprint, so the
            # same cache.max_bytes holds more results once the compress
            # seam shrinks the spilled tier. The incoming entry starts
            # device-resident, i.e. at its full logical size.
            while (self._stored_bytes + nbytes > self._max_bytes()
                   and self._entries):
                old_key, old = next(iter(self._entries.items()))
                self._discard_locked(old_key, old, "eviction")
                record_cache("result_cache", "evict", key=old_key.short,
                             nbytes=old["nbytes"])
            charged = self._charge_locked(nbytes)
            handle = self._store.put(table, integrity_seam="integrity.cache")
            if not charged:
                # no budget for residency: keep only the sealed host copy
                self._store.spill(handle)
            entry = {
                "handle": handle, "nbytes": nbytes, "stored": nbytes,
                "meta": _snap_meta(result.meta), "charged": charged,
            }
            self._entries[key] = entry
            self._bytes += nbytes
            self._stored_bytes += nbytes
            if charged:
                self.evictable_bytes += nbytes
            else:
                # already demoted: account the compressed footprint now
                self._refresh_stored_locked(entry)
        self._count("put")
        record_cache("result_cache", "put", key=key.short, nbytes=nbytes)
        return True

    def get(self, key: CacheKey) -> Optional[fusion.FusedResult]:
        """Probe for a bit-identical memoized result. A spilled entry is
        re-charged and staged back through the store's verify-before-
        decode read; a corrupt payload (classified ``CorruptDataError``)
        discards the entry and returns a miss — the caller recomputes,
        with zero reservation left behind."""
        if not enabled():
            return None
        self._validate_key(key)
        with self._lock:
            entry = self._entries.get(key)
            if entry is None:
                self._count("miss")
                record_cache("result_cache", "miss", key=key.short)
                return None
            nbytes = entry["nbytes"]
            self._reconcile_locked(entry)
            reserved = False
            if not entry["charged"]:
                # staging back needs HBM: charge (shedding colder entries
                # if needed) BEFORE the host->device copy, the same
                # reserve-first contract as SpillStore.get_reserved
                if not self._charge_locked(nbytes):
                    self._count("bypass")
                    record_cache("result_cache", "miss", key=key.short,
                                 reason="no budget to stage")
                    return None
                reserved = True
            try:
                table = self._store.get(entry["handle"])
            except resilience.CorruptDataError as exc:
                # verified-at-read caught a corrupt cached payload:
                # classified discard, then the caller recomputes from
                # source — never serve wrong bytes, never leak the charge
                if reserved:
                    self._limiter.release(nbytes)
                    entry["charged"] = False
                else:
                    self._uncharge_locked(entry)
                entry["charged"] = False
                self._discard_locked(key, entry, "corrupt_discard")
                record_integrity(
                    "result_cache", "mismatch", seam="integrity.cache",
                    nbytes=nbytes, reason=str(exc))
                record_cache("result_cache", "corrupt_discard",
                             key=key.short, nbytes=nbytes)
                _log.warning("corrupt cached entry %s discarded: %s",
                             key.short, exc)
                return None
            except KeyError:
                if reserved:
                    self._limiter.release(nbytes)
                self._entries.pop(key, None)
                self._bytes -= nbytes
                self._stored_bytes -= entry["stored"]
                self._count("miss")
                return None
            if reserved:
                entry["charged"] = True
                self.evictable_bytes += nbytes
            # staged back to the device tier: resident footprint is the
            # full logical size again
            self._refresh_stored_locked(entry)
            self._entries.move_to_end(key)
            meta = _rehydrate_meta(entry["meta"])
        self._count("hit")
        record_cache("result_cache", "hit", key=key.short, nbytes=nbytes)
        return fusion.FusedResult(table, meta)

    def invalidate(self, key: CacheKey) -> bool:
        """Drop one entry (e.g. the caller knows its source changed)."""
        with self._lock:
            entry = self._entries.get(key)
            if entry is None:
                return False
            self._discard_locked(key, entry, "invalidated")
        return True

    def clear(self) -> None:
        with self._lock:
            for key, entry in list(self._entries.items()):
                self._discard_locked(key, entry, "cleared")

    def close(self) -> None:
        self.clear()

    def stats(self) -> dict:
        c = REGISTRY.counters("cache.")
        with self._lock:
            entries = len(self._entries)
            total = self._bytes
            stored = self._stored_bytes
            resident = self.evictable_bytes
        hits = c.get("cache.hit", 0)
        misses = c.get("cache.miss", 0)
        return {
            "entries": entries,
            "bytes": total,
            "stored_bytes": stored,
            "resident_bytes": resident,
            "max_bytes": self._max_bytes(),
            "hits": hits,
            "misses": misses,
            "hit_rate": round(hits / (hits + misses), 4)
            if hits + misses else None,
            "puts": c.get("cache.put", 0),
            "evictions": c.get("cache.eviction", 0),
            "shed_bytes": c.get("cache.shed_bytes", 0),
            "corrupt_discards": c.get("cache.corrupt_discard", 0),
            "subplan_hits": c.get("cache.subplan_hit", 0),
            "subplan_materializations": c.get(
                "cache.subplan_materialize", 0),
        }


# ---------------------------------------------------------------------------
# subplan-prefix reuse
# ---------------------------------------------------------------------------

# a prefix must carry at least this many non-Scan nodes to be worth a
# separate region dispatch + materialization (a lone Project re-executes
# faster than it round-trips the cache)
_MIN_PREFIX_NODES = 2


def apply_subplans(cache: Optional[ResultCache], plan: fusion.Plan,
                   bindings: dict, *, cancel_token=None):
    """Rewrite ``plan`` so every cacheable scan+filter+project prefix is
    served from (or materialized into) ``cache``.

    For each maximal Filter/rowwise-Project chain over a bucketed Scan
    (``fusion.scan_prefix_chains``, at least ``_MIN_PREFIX_NODES`` deep),
    the chain's canonical digest + its scan binding's content fingerprint
    key a cached intermediate: on a hit the subtree collapses to a Scan
    bound to the cached table; on a miss the prefix executes ONCE as its
    own fused region, is cached, and then collapses the same way — so two
    plans sharing the prefix execute it exactly once between them.

    Bit-identity holds because Filter masks validity in place and a
    rowwise Project stays in the scan's row space: the materialized
    intermediate is, content-for-content, exactly what the consumer node
    would have seen mid-region, and fused==staged per region is already
    the repo's core contract.

    Returns ``(plan, bindings, rewritten)``; when ``rewritten`` the
    caller MUST NOT donate inputs (the injected binding is cache-owned).
    A pressure/compile failure while materializing a prefix leaves that
    chain unrewritten — the degradation ladder handles the full plan.
    """
    if cache is None or not subplan_enabled():
        return plan, bindings, False
    chains = fusion.scan_prefix_chains(plan.root)
    root = plan.root
    out_bindings = dict(bindings)
    rewritten = False
    for scan, top, length in chains:
        if length < _MIN_PREFIX_NODES or scan.name not in out_bindings:
            continue
        binding = out_bindings[scan.name]
        sub_plan = fusion.Plan(f"{plan.name}.prefix.{scan.name}", top)
        try:
            key = cache_key(sub_plan, {scan.name: binding})
        except (ValueError, KeyError, TypeError):
            continue  # unfingerprintable prefix (e.g. local callables)
        hit = cache.get(key)
        if hit is not None:
            REGISTRY.counter("cache.subplan_hit").inc()
            record_cache(sub_plan.name, "subplan_hit", key=key.short)
            table = hit.table
        else:
            try:
                with spans.child(f"cache.subplan.{scan.name}",
                                 mode="materialize"):
                    res = fusion.execute(
                        sub_plan, {scan.name: binding},
                        donate_inputs=False, cancel_token=cancel_token)
            except resilience.QueryCancelled:
                raise
            except Exception:
                REGISTRY.counter("cache.subplan_abort").inc()
                continue
            REGISTRY.counter("cache.subplan_materialize").inc()
            record_cache(sub_plan.name, "subplan_materialize",
                         key=key.short)
            cache.put(key, res)
            table = res.table
        alias = f"__subplan_{key.signature[:12]}"
        root = fusion.replace_node(root, top, fusion.Scan(alias, True))
        out_bindings[alias] = table
        rewritten = True
    if not rewritten:
        return plan, bindings, False
    return fusion.Plan(plan.name, root), out_bindings, True
