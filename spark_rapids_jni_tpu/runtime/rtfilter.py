"""Runtime bloom-join filters: learned-selectivity gating and state.

The reference family ships xxhash64 + Spark-compatible bloom filters
precisely so selective joins can prune probe-side scans before they
stage ("Accelerating Presto with GPUs", PAPERS.md, shows multi-join
analytics queries go scan-bound without this). This module is the
DECISION half of that subsystem: the planner pass itself lives in
``runtime/fusion.inject_runtime_filters`` (it owns the plan IR), and
calls back here for every on/off/sizing choice.

Contract: every decision is recorded with a mandatory reason
(``record_rtfilter`` + the ``rtfilter.decision.*`` counters — tpulint
rule 24 ``rtfilter-decision-must-record`` enforces the static half), and
results are bit-identical whatever this module decides: a bloom filter
only drops rows the join was about to drop, so the gate trades probe
overhead against pruning payoff, never correctness.

Learned gating: each ``(plan, join label)`` signature keeps an EMA of
its observed pass fraction (``rows_pass / rows_in`` harvested from the
``BloomProbe`` side outputs after every region). A signature whose EMA
rises above ``rtfilter.gate_pass_frac`` is judged non-selective and the
filter switches off for it; signatures with no history run
optimistically. The EMAs persist in ``learned_selectivity.json`` beside
the learned admission estimates with the SAME crash-safe discipline
(``runtime/server.py``): sidecar ``fcntl`` lock, read-merge-replace via
``atomic_write_json``, corrupt files discarded and counted — N replica
processes share one state file without clobbering each other.

Chunked/out-of-core paths can't prune inside a region (static shapes —
masking never drops a row); they prune on the HOST side instead, where
chunk boundaries make dynamic shapes free: ``prune_chunk`` compacts a
decoded chunk down to its possibly-matching rows before the per-chunk
region stages it, which is where the rows-scanned (and bytes reserved /
spilled) reduction actually lands. ``packed_table`` wraps a filter's
``to_packed`` wire form as a one-column table so a cluster fan-out ships
it inline over the sealed DCN transport and every shard prunes locally.

Config (utils/config.py): ``rtfilter.enabled`` / ``max_build_rows`` /
``fpp`` / ``gate_pass_frac`` / ``alpha`` / ``path`` /
``save_interval_s`` (env ``SPARK_RAPIDS_TPU_RTFILTER_*``).
"""

from __future__ import annotations

import os
import threading
import time
from typing import NamedTuple, Optional

import jax.numpy as jnp
import numpy as np

from spark_rapids_jni_tpu import types as t
from spark_rapids_jni_tpu.columnar import Column, Table
from spark_rapids_jni_tpu.ops.bloom_filter import (
    BloomFilter,
    bloom_might_contain_spark,
    bloom_put_spark,
    optimal_params,
)
from spark_rapids_jni_tpu.telemetry import spans
from spark_rapids_jni_tpu.telemetry.events import record_rtfilter
from spark_rapids_jni_tpu.telemetry.registry import REGISTRY
from spark_rapids_jni_tpu.utils.atomic_io import atomic_write_json, load_json
from spark_rapids_jni_tpu.utils.config import get_option

try:
    import fcntl
except ImportError:  # pragma: no cover - non-POSIX
    fcntl = None  # type: ignore[assignment]

__all__ = [
    "Decision",
    "decide",
    "observe",
    "build_filter",
    "prune_chunk",
    "pruned_chunks",
    "packed_table",
    "learned_pass_frac",
    "flush",
    "reset",
    "stats",
]


class Decision(NamedTuple):
    """One recorded planner choice for one join of one plan."""

    apply: bool
    reason: str
    num_bits: int
    num_hashes: int


# ---------------------------------------------------------------------------
# learned selectivity state (the admission-estimate persistence twin)
# ---------------------------------------------------------------------------


class _SelectivityStore:
    """Per-signature pass-fraction EMAs with the flock-merge write
    discipline of ``QueryServer._save_learned`` (one file, N writers)."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._ema: dict[str, float] = {}
        self._dirty = False
        self._last_save: Optional[float] = None
        self._loaded_from = ""

    # -- path / file ----------------------------------------------------

    @staticmethod
    def _resolve_path() -> str:
        explicit = str(get_option("rtfilter.path") or "")
        if explicit:
            return explicit
        cache_dir = os.environ.get("SPARK_RAPIDS_TPU_DISPATCH_CACHE") or str(
            get_option("dispatch.persistent_cache_dir") or "")
        if cache_dir:
            return os.path.join(cache_dir, "learned_selectivity.json")
        return ""

    def _read_file(self, path: str) -> Optional[dict]:
        state, corrupt = load_json(path)
        if corrupt is not None:
            # atomic replace means a crash can't produce this; disk rot
            # or a manual edit can — discard, count, keep deciding
            REGISTRY.counter("rtfilter.state_discarded").inc()
            record_rtfilter("rtfilter.state", "state_discarded",
                            reason="corrupt", path=path, detail=corrupt)
            return None
        if not isinstance(state, dict):
            return None
        return {
            str(k): float(v) for k, v in state.items()
            if isinstance(v, (int, float)) and 0.0 <= float(v) <= 1.0
        }

    @staticmethod
    def _merge(mine: dict, disk: dict) -> dict:
        # 50/50 blend of two EMAs is a fair co-estimate and converges
        # under repeated merge cycles (same rationale as the admission
        # estimates' _merge_learned)
        merged = dict(disk)
        for sig, v in mine.items():
            dv = merged.get(sig)
            merged[sig] = float(v) if dv is None \
                else 0.5 * float(v) + 0.5 * float(dv)
        return merged

    def _maybe_load(self) -> None:
        path = self._resolve_path()
        with self._lock:
            if path == self._loaded_from:
                return
            self._loaded_from = path
        if not path:
            return
        disk = self._read_file(path)
        if disk is None:
            return
        with self._lock:
            self._ema = self._merge(self._ema, disk)

    # -- reads ----------------------------------------------------------

    def get(self, sig: str) -> Optional[float]:
        self._maybe_load()
        with self._lock:
            return self._ema.get(sig)

    def snapshot(self) -> dict:
        with self._lock:
            return dict(self._ema)

    # -- writes ---------------------------------------------------------

    def update(self, sig: str, pass_frac: float) -> float:
        self._maybe_load()
        alpha = float(get_option("rtfilter.alpha"))
        with self._lock:
            old = self._ema.get(sig)
            new = float(pass_frac) if old is None \
                else (1.0 - alpha) * old + alpha * float(pass_frac)
            self._ema[sig] = new
            self._dirty = True
            last = self._last_save
        interval = float(get_option("rtfilter.save_interval_s"))
        if last is None or time.monotonic() - last >= interval:
            self.save()
        return new

    def save(self) -> None:
        path = self._resolve_path()
        if not path:
            return
        with self._lock:
            if not self._dirty:
                return
            snapshot = dict(self._ema)
            self._dirty = False
            self._last_save = time.monotonic()
        lock_fh = None
        try:
            if fcntl is not None:
                lock_fh = open(path + ".lock", "a")
                fcntl.flock(lock_fh.fileno(), fcntl.LOCK_EX)
            disk = self._read_file(path)
            atomic_write_json(path, self._merge(snapshot, disk or {}))
        except OSError:
            # selectivity history is an optimization: losing a write
            # costs the next process one optimistic run, never a result
            with self._lock:
                self._dirty = True
            REGISTRY.counter("rtfilter.state_write_error").inc()
        finally:
            if lock_fh is not None:
                try:
                    fcntl.flock(lock_fh.fileno(), fcntl.LOCK_UN)
                finally:
                    lock_fh.close()

    def reset(self) -> None:
        with self._lock:
            self._ema = {}
            self._dirty = False
            self._last_save = None
            self._loaded_from = ""


_STORE = _SelectivityStore()


def _signature(plan_name: str, label: str) -> str:
    return f"{plan_name}/{label}"


def learned_pass_frac(plan_name: str, label: str) -> Optional[float]:
    """The signature's current EMA (None = no history)."""
    return _STORE.get(_signature(plan_name, label))


def flush() -> None:
    """Force-persist dirty selectivity state now (close/atexit twin)."""
    _STORE.save()


def reset() -> None:
    """Drop in-memory selectivity state (tests; disk is untouched)."""
    _STORE.reset()


# ---------------------------------------------------------------------------
# decisions
# ---------------------------------------------------------------------------


def decide(plan_name: str, label: str, build_rows: int) -> Decision:
    """Gate one join: filter on/off plus bits sizing. EVERY path records
    its reason (counter + ``record_rtfilter``) — an unexplained decision
    is a bug (tpulint rule 24)."""
    sig = _signature(plan_name, label)

    def _skip(reason: str) -> Decision:
        REGISTRY.counter("rtfilter.decision.skip").inc()
        record_rtfilter(sig, "skip", reason=reason, build_rows=build_rows)
        return Decision(False, reason, 0, 0)

    if not get_option("rtfilter.enabled"):
        return _skip("disabled")
    if build_rows > int(get_option("rtfilter.max_build_rows")):
        return _skip("build_too_large")
    ema = _STORE.get(sig)
    gate = float(get_option("rtfilter.gate_pass_frac"))
    if ema is not None and ema > gate:
        return _skip("learned_nonselective")
    reason = "no_history_optimistic" if ema is None else "selective"
    num_bits, num_hashes = optimal_params(
        build_rows, float(get_option("rtfilter.fpp")))
    REGISTRY.counter("rtfilter.decision.apply").inc()
    record_rtfilter(sig, "apply", reason=reason, build_rows=build_rows,
                    num_bits=num_bits, num_hashes=num_hashes,
                    pass_frac_ema=ema)
    return Decision(True, reason, num_bits, num_hashes)


def observe(plan_name: str, probe_label: str, rows_in, rows_pass) -> None:
    """Harvest one probe's measured pass fraction into the learned EMA
    (and the ``rtfilter.rows_pruned`` ledger). Accepts the raw
    ``<label>.rows_in`` / ``<label>.rows_pass`` side outputs; silently a
    no-op under tracers (a fused region evaluated inside another trace
    has nothing concrete to learn from yet)."""
    if rows_in is None or rows_pass is None:
        return
    try:
        n_in, n_pass = int(rows_in), int(rows_pass)
    except TypeError:  # tracer values: nothing concrete to learn from
        return
    if n_in <= 0:
        # an empty probe side carries no selectivity information
        return
    label = probe_label[4:] if probe_label.startswith("rtf_") \
        else probe_label
    sig = _signature(plan_name, label)
    pass_frac = n_pass / n_in
    REGISTRY.counter("rtfilter.rows_in").inc(n_in)
    REGISTRY.counter("rtfilter.rows_pruned").inc(n_in - n_pass)
    REGISTRY.counter("rtfilter.observations").inc()
    ema = _STORE.update(sig, pass_frac)
    record_rtfilter(sig, "observed", reason="measured", rows_in=n_in,
                    rows_pass=n_pass, pass_frac=pass_frac,
                    pass_frac_ema=ema)


# ---------------------------------------------------------------------------
# host-side helpers (chunked and cluster paths)
# ---------------------------------------------------------------------------


def build_filter(values: jnp.ndarray, valid=None, *,
                 expected_items: int,
                 fpp: Optional[float] = None) -> BloomFilter:
    """Materialize build keys into a filter (dispatch-routed
    ``bloom_put_spark``), timing the build into
    ``rtfilter.build_us``."""
    num_bits, num_hashes = optimal_params(
        expected_items,
        float(get_option("rtfilter.fpp")) if fpp is None else float(fpp))
    start = time.monotonic()
    with spans.child("rtfilter.build", num_bits=num_bits,
                     num_hashes=num_hashes):
        bf = bloom_put_spark(BloomFilter.empty(num_bits, num_hashes),
                             values, valid)
        jnp.asarray(bf.bits).block_until_ready()
    build_us = (time.monotonic() - start) * 1e6
    REGISTRY.counter("rtfilter.builds").inc()
    REGISTRY.histogram("rtfilter.build_us").observe(build_us)
    return bf


def prune_chunk(chunk: Table, bf: BloomFilter, key: int, *,
                plan_name: str = "", label: str = "",
                min_rows: int = 1) -> Table:
    """Compact a decoded chunk down to its possibly-matching rows before
    the per-chunk region stages it — the HOST half of the pushdown,
    where chunk boundaries make dynamic shapes free. Null-keyed rows are
    KEPT (their fate belongs to the plan's own masking, not to us); at
    least ``min_rows`` rows survive so the downstream plan never sees an
    empty table. Bit-identity: every dropped row is provably unmatched
    (no false negatives) and the survivors keep their relative order.
    With ``plan_name``/``label`` the measured pass fraction also feeds
    the learned gate via :func:`observe`."""
    from spark_rapids_jni_tpu.ops.sort import gather

    col = chunk.columns[key]
    kv = np.asarray(col.valid_mask())
    hit = np.asarray(bloom_might_contain_spark(bf, col.data))
    keep = hit | ~kv
    n_pass = int(keep.sum())
    if plan_name and label:
        observe(plan_name, label, int(chunk.num_rows), n_pass)
    else:
        REGISTRY.counter("rtfilter.rows_in").inc(int(chunk.num_rows))
        REGISTRY.counter("rtfilter.rows_pruned").inc(
            int(chunk.num_rows) - n_pass)
    idx = np.flatnonzero(keep)
    if idx.size < min_rows:
        idx = np.arange(min(min_rows, chunk.num_rows))
    record_rtfilter("rtfilter.chunk", "prune", reason="measured",
                    rows_in=int(chunk.num_rows), rows_out=int(idx.size))
    if idx.size == chunk.num_rows:
        return chunk
    with spans.child("rtfilter.prune", rows_in=int(chunk.num_rows),
                     rows_out=int(idx.size)):
        return gather(chunk, jnp.asarray(idx, dtype=jnp.int32))


class _PrunedReader:
    """Chunked-reader wrapper that ALSO forwards ``chunk_sources()`` so
    the pipelined out-of-core executor keeps its decode-thunk overlap:
    each thunk decodes, then prunes, still on the host side of the
    staging boundary."""

    def __init__(self, inner, prune) -> None:
        self._inner = inner
        self._prune = prune

    def __iter__(self):
        return (self._prune(c) for c in self._inner)

    def chunk_sources(self):
        return [
            (lambda s=s: self._prune(s()))
            for s in self._inner.chunk_sources()
        ]


def pruned_chunks(chunks, bf: BloomFilter, key: int, *,
                  plan_name: str = "", label: str = ""):
    """Wrap a chunk iterable (or a ``chunk_sources()`` reader) so every
    chunk is bloom-pruned BEFORE the out-of-core runner reserves or
    stages it — fewer bytes reserved, spilled, and shipped, same
    bytes out."""
    def _prune(chunk: Table) -> Table:
        return prune_chunk(chunk, bf, key, plan_name=plan_name,
                           label=label)

    if hasattr(chunks, "chunk_sources"):
        return _PrunedReader(chunks, _prune)
    return (_prune(c) for c in chunks)


def packed_table(bf: BloomFilter) -> Table:
    """The filter's ``to_packed`` wire form as a one-column uint8 table —
    what a cluster fan-out ships inline (sealed DCN transport) so each
    shard probes locally via ``BloomProbe(packed=True)`` over an
    unbucketed Scan bound to this table."""
    return Table([Column(t.UINT8, bf.to_packed())])


def stats() -> dict:
    """Aggregate runtime-filter counters for the bench ``rtfilter``
    block."""
    c = REGISTRY.counters("rtfilter.")
    rows_in = c.get("rtfilter.rows_in", 0)
    pruned = c.get("rtfilter.rows_pruned", 0)
    return {
        "decisions_apply": c.get("rtfilter.decision.apply", 0),
        "decisions_skip": c.get("rtfilter.decision.skip", 0),
        "observations": c.get("rtfilter.observations", 0),
        "builds": c.get("rtfilter.builds", 0),
        "build_us_p50": REGISTRY.histogram(
            "rtfilter.build_us").percentile(50),
        "rows_in": rows_in,
        "rows_pruned": pruned,
        "pass_frac": (rows_in - pruned) / rows_in if rows_in else None,
        "state_discarded": c.get("rtfilter.state_discarded", 0),
        "learned_signatures": len(_STORE.snapshot()),
    }
