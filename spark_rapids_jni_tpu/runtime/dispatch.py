"""Shape-bucketed dispatch & executable cache for the device op path.

The reference launches per-shape CUDA kernels, so a new batch size costs a
kernel *launch*; under XLA a new batch size costs a *retrace and recompile*
— orders of magnitude more. This layer closes that gap the way TPU serving
stacks do (pad ragged batches to a small set of canonical shapes): the
leading row dimension of every device-op input is padded up to a bucket
from a geometric schedule, an explicit ``row_valid`` mask (the ``n_valid``
scalar in vector form) keeps padded tail rows out of results and
reductions, and the compiled executable is memoized under
``(op, statics digest, leaf shapes/dtypes/shardings, backend)`` so every
batch size inside a bucket reuses one executable.

Compilation is explicit — ``jax.jit(fn).lower(args).compile()`` — rather
than delegated to jit's internal cache, so compiles and hits are exact,
countable events (telemetry counters ``dispatch.compile`` /
``dispatch.hit``; ``dispatch.padded_waste_bytes`` accounts the padding
tax). JAX's persistent compilation cache is wired from
``SPARK_RAPIDS_TPU_DISPATCH_CACHE`` (or ``dispatch.persistent_cache_dir``)
so steady-state runs start warm across processes.

Fail-safe posture: anything this layer cannot bucket or compile — tracer
inputs (the op is already inside a caller's trace), Arrow-layout strings,
nested columns, zero-row batches, lowering errors — falls back to calling
the op's implementation directly, with the reason counted. Dispatch must
never change what an op computes, only how often XLA compiles it.

Config knobs (utils/config.py): ``dispatch.enabled``,
``dispatch.bucket_base``, ``dispatch.max_waste_frac``,
``dispatch.persistent_cache_dir``.
"""

from __future__ import annotations

import math
import os
import threading
import warnings
from functools import partial
from typing import Any, Callable, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from spark_rapids_jni_tpu.columnar import Column, Table
from spark_rapids_jni_tpu.runtime import faults, resilience
from spark_rapids_jni_tpu.telemetry import spans
from spark_rapids_jni_tpu.telemetry.events import record_compile_cache
from spark_rapids_jni_tpu.telemetry.registry import REGISTRY
from spark_rapids_jni_tpu.types import TypeId
from spark_rapids_jni_tpu.utils.config import get_option

__all__ = [
    "Unbucketable",
    "bucket_config",
    "bucket_for",
    "quantize_capacity",
    "call",
    "rowwise",
    "sharded_call",
    "stats",
    "clear",
]

_ENV_CACHE_DIR = "SPARK_RAPIDS_TPU_DISPATCH_CACHE"

_lock = threading.RLock()
_EXEC_CACHE: dict = {}
# key -> threading.Event: a first-compile currently in flight. Concurrent
# callers of the same key park on the event and reuse the leader's
# executable instead of compiling it N times (single-flight).
_INFLIGHT: dict = {}
_persistent_initialized = False


class Unbucketable(Exception):
    """An input the bucketing pad cannot represent (Arrow-layout string,
    nested column, non-array leaf, mismatched leading dimension)."""


# ---------------------------------------------------------------------------
# bucket schedule
# ---------------------------------------------------------------------------


def bucket_config() -> tuple[bool, int, float]:
    """(enabled, bucket_base, max_waste_frac) — read per call, never baked
    into a trace. Callers that DO consume these at trace time (the shuffle
    capacity quantization) must carry this tuple in their dispatch key;
    ``sharded_call`` does so automatically."""
    return (
        bool(get_option("dispatch.enabled")),
        max(1, int(get_option("dispatch.bucket_base"))),
        max(0.0, float(get_option("dispatch.max_waste_frac"))),
    )


def bucket_for(n: int) -> int:
    """Smallest bucket >= n. Buckets are multiples of ``bucket_base``
    growing geometrically by ``min(1 + max_waste_frac, 2)`` — waste_frac
    1.0 gives power-of-two-style buckets (at most ~50% padded rows),
    0.0 degenerates to linear base-multiple rounding."""
    _, base, waste = bucket_config()
    n = max(int(n), 1)
    if n <= base:
        return base
    growth = min(1.0 + waste, 2.0)
    if growth <= 1.0:
        return ((n + base - 1) // base) * base
    b = base
    while b < n:
        nxt = ((int(b * growth) + base - 1) // base) * base
        b = max(nxt, b + base)
    return b


def quantize_capacity(capacity: int) -> int:
    """Bucket-quantize a derived output capacity (e.g. the shuffle's
    per-device slot count) so nearby batch sizes share one executable.
    Growing a capacity is always safe — extra slots are row_valid=False
    padding. Identity when dispatch is disabled."""
    enabled, _, _ = bucket_config()
    if not enabled:
        return int(capacity)
    return bucket_for(int(capacity))


# ---------------------------------------------------------------------------
# pytree pad / slice
# ---------------------------------------------------------------------------


def _is_array(x: Any) -> bool:
    return isinstance(x, (jax.Array, np.ndarray))


def _has_tracer(tree: Any) -> bool:
    return any(
        isinstance(leaf, jax.core.Tracer)
        for leaf in jax.tree_util.tree_leaves(tree)
    )


class _PadStats:
    __slots__ = ("padded_bytes", "total_bytes")

    def __init__(self) -> None:
        self.padded_bytes = 0
        self.total_bytes = 0


def _pad_array(x: Any, n: int, B: int, acc: _PadStats) -> Any:
    if not _is_array(x):
        raise Unbucketable(f"non-array leaf {type(x).__name__}")
    if x.ndim < 1 or x.shape[0] != n:
        raise Unbucketable(
            f"leading dim {x.shape} != row count {n}")
    row_bytes = int(np.dtype(x.dtype).itemsize) * int(
        math.prod(x.shape[1:]) if x.ndim > 1 else 1)
    acc.padded_bytes += (B - n) * row_bytes
    acc.total_bytes += B * row_bytes
    if B == n:
        return jnp.asarray(x)
    pad = jnp.zeros((B - n,) + tuple(x.shape[1:]), dtype=x.dtype)
    return jnp.concatenate([jnp.asarray(x), pad], axis=0)


def _pad_column(col: Column, n: int, B: int, acc: _PadStats) -> Column:
    if col.children is not None or col.dtype.type_id in (
            TypeId.LIST, TypeId.STRUCT):
        raise Unbucketable("nested (LIST/STRUCT) column")
    if col.dtype.is_string and not col.is_padded_string:
        raise Unbucketable("arrow-layout string column")
    if col.size != n:
        raise Unbucketable(f"column size {col.size} != row count {n}")
    data = _pad_array(col.data, n, B, acc)
    # padded tail rows are NULL rows: every op's null semantics already
    # neutralize them (sums add 0, min/max see sentinels, sorts rank them
    # by the row_valid key, counts skip them)
    validity = jnp.concatenate(
        [col.valid_mask(), jnp.zeros((B - n,), jnp.bool_)])
    chars = None
    if col.chars is not None:
        chars = _pad_array(col.chars, n, B, acc)
    return Column(col.dtype, data, validity, chars=chars)


def _pad_tree(x: Any, n: int, B: int, acc: _PadStats) -> Any:
    if x is None:
        return None
    if isinstance(x, Column):
        return _pad_column(x, n, B, acc)
    if isinstance(x, Table):
        return Table([_pad_column(c, n, B, acc) for c in x.columns])
    if _is_array(x):
        return _pad_array(x, n, B, acc)
    if isinstance(x, tuple):
        vals = [_pad_tree(v, n, B, acc) for v in x]
        return type(x)(*vals) if hasattr(x, "_fields") else tuple(vals)
    if isinstance(x, list):
        return [_pad_tree(v, n, B, acc) for v in x]
    if isinstance(x, dict):
        return {k: _pad_tree(v, n, B, acc) for k, v in x.items()}
    raise Unbucketable(f"non-array leaf {type(x).__name__}")


def _slice_column(col: Column, n: int, B: int) -> Column:
    data = col.data
    if _is_array(data) and data.ndim >= 1 and data.shape[0] == B:
        data = data[:n]
    validity = col.validity
    if _is_array(validity) and validity.shape[0] == B:
        validity = validity[:n]
    chars = col.chars
    if _is_array(chars) and chars.ndim >= 1 and chars.shape[0] == B:
        chars = chars[:n]
    return Column(col.dtype, data, validity, chars=chars,
                  children=col.children)


def _slice_tree(x: Any, n: int, B: int) -> Any:
    if B == n or x is None:
        return x
    if isinstance(x, Column):
        return _slice_column(x, n, B)
    if isinstance(x, Table):
        return Table([_slice_column(c, n, B) for c in x.columns])
    if _is_array(x):
        if x.ndim >= 1 and x.shape[0] == B:
            return x[:n]
        return x
    if isinstance(x, tuple):
        vals = [_slice_tree(v, n, B) for v in x]
        return type(x)(*vals) if hasattr(x, "_fields") else tuple(vals)
    if isinstance(x, list):
        return [_slice_tree(v, n, B) for v in x]
    if isinstance(x, dict):
        return {k: _slice_tree(v, n, B) for k, v in x.items()}
    return x


def _group_rows(group: Any) -> int:
    """The row count of one bucketing group (a pytree whose array leaves
    all share the leading row dimension)."""
    if isinstance(group, Table):
        return group.num_rows
    if isinstance(group, Column):
        return group.size
    for leaf in jax.tree_util.tree_leaves(group):
        if isinstance(leaf, Column):
            return leaf.size
        if _is_array(leaf):
            if leaf.ndim < 1:
                raise Unbucketable("scalar leaf has no row dimension")
            return int(leaf.shape[0])
    raise Unbucketable("group has no array leaves")


def _signature(tree: Any) -> tuple:
    """Hashable aval digest: treedef (carries Column dtypes as aux data —
    the reference's (typeId, scale) JNI marshaling) + per-leaf shape,
    dtype, and sharding."""
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    sig = []
    for leaf in leaves:
        shard = getattr(leaf, "sharding", None)
        sig.append((
            tuple(leaf.shape) if hasattr(leaf, "shape") else (),
            str(getattr(leaf, "dtype", type(leaf).__name__)),
            repr(shard) if shard is not None else "",
        ))
    return (treedef, tuple(sig))


# ---------------------------------------------------------------------------
# executable cache
# ---------------------------------------------------------------------------


def _refresh_cache_index(cache_dir: str) -> None:
    """Maintain the repo-owned ``index.json`` beside JAX's persistent
    cache entries: which jax version wrote them and how many processes
    have wired the directory. Written crash-safely (tmp + ``os.replace``
    + fsync, utils/atomic_io.py); a corrupt/truncated index from an
    earlier crash is DISCARDED with a telemetry event — warm start then
    costs one re-count, never a crash or a poisoned cache."""
    from spark_rapids_jni_tpu.telemetry.events import record_degrade
    from spark_rapids_jni_tpu.utils.atomic_io import (
        atomic_write_json,
        load_json,
    )

    index_path = os.path.join(cache_dir, "index.json")
    index, corrupt = load_json(index_path)
    if corrupt is not None:
        REGISTRY.counter("dispatch.persistent_cache_index_discarded").inc()
        record_degrade("dispatch.persistent_cache", "state_discarded",
                       tier="persistent", trigger="corrupt",
                       rung=0, path=index_path, reason=corrupt)
        index = None
    if not isinstance(index, dict):
        index = {}
    index["version"] = 1
    index["jax"] = str(jax.__version__)
    index["wired"] = int(index.get("wired", 0)) + 1
    atomic_write_json(index_path, index)


def _init_persistent_cache() -> None:
    """Wire JAX's cross-process compilation cache (idempotent). The short
    env var wins over the config option; thresholds are dropped to zero so
    the small CPU-test executables persist too."""
    global _persistent_initialized
    with _lock:
        if _persistent_initialized:
            return
        _persistent_initialized = True
    cache_dir = os.environ.get(_ENV_CACHE_DIR) or str(
        get_option("dispatch.persistent_cache_dir") or "")
    if not cache_dir:
        return
    try:
        os.makedirs(cache_dir, exist_ok=True)
        _refresh_cache_index(cache_dir)
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        for opt, val in (
                ("jax_persistent_cache_min_compile_time_secs", 0.0),
                ("jax_persistent_cache_min_entry_size_bytes", -1)):
            try:
                jax.config.update(opt, val)
            # knob names drift across jax versions; a miss only loses
            # tuning, never correctness, and the outer handler already
            # counts real failures
            # tpulint: disable=error-must-classify
            except Exception:
                pass
        # jax latches the cache as disabled at the FIRST compile in the
        # process; imports above us always compile something, so force a
        # re-read of the dir we just set
        try:
            from jax._src import compilation_cache as _cc
            _cc.reset_cache()
        # private-module probe, absent on some jax versions; the cache
        # still serves compiles after this point and the outer handler
        # counts real failures
        # tpulint: disable=error-must-classify
        except Exception:
            pass
        REGISTRY.gauge("dispatch.persistent_cache").set(1)
    except Exception:
        REGISTRY.counter("dispatch.persistent_cache_error").inc()


def _cache_lookup(key) -> tuple:
    """Single-flight cache lookup: ``(compiled, leader_event)``.

    ``compiled`` non-None means a cached executable (a hit — possibly
    after waiting out another thread's in-flight compile of the same
    key). ``compiled`` None means THIS caller is the compile leader for
    ``key`` and holds ``leader_event``; it MUST finish with
    ``_cache_store(key, compiled_or_None, leader_event)`` on every exit
    path, or waiters park forever. A leader that fails (stores None)
    wakes the waiters, and the first to re-loop becomes the new leader —
    a failed compile never wedges the key.
    """
    while True:
        with _lock:
            compiled = _EXEC_CACHE.get(key)
            if compiled is not None:
                return compiled, None
            ev = _INFLIGHT.get(key)
            if ev is None:
                ev = threading.Event()
                _INFLIGHT[key] = ev
                return None, ev
        ev.wait()


def _cache_store(key, compiled, ev: threading.Event) -> None:
    """Publish the leader's result (or its failure) and release waiters."""
    with _lock:
        if compiled is not None:
            _EXEC_CACHE[key] = compiled
        if _INFLIGHT.get(key) is ev:
            del _INFLIGHT[key]
    ev.set()


def _kernels_digest() -> tuple:
    """The Pallas kernel-tier configuration (ops/pallas) as a cache-key
    component. Tier selection happens at TRACE time inside ``fn``, so
    every executable must be keyed by the tier that traced it — flipping
    ``kernels.tier`` (or a per-op override) can never replay an
    executable traced under the other tier. Fused regions inherit this
    through their own dispatch.call, which is exactly how a Pallas
    kernel picks up shape bucketing, caching and donation like its XLA
    twin."""
    from spark_rapids_jni_tpu.ops import pallas as pallas_tier

    return pallas_tier.kernels_digest()


def _inline(op: str, reason: str, fn: Callable, row_args: tuple,
            aux_args: tuple) -> Any:
    REGISTRY.counter("dispatch.inline").inc()
    REGISTRY.counter(f"dispatch.inline.{reason}").inc()
    return fn(row_args, aux_args, None)


def call(
    op: str,
    fn: Callable,
    row_args: tuple,
    aux_args: tuple = (),
    *,
    statics: tuple = (),
    slice_rows: bool = True,
    bucket_rows: bool = True,
    donate_rows: bool = False,
) -> Any:
    """Dispatch ``fn`` through the bucketed executable cache.

    ``row_args`` is a tuple of bucketing GROUPS: each group is a pytree
    (Columns / Tables / arrays) whose leaves share one leading row
    dimension; each group is padded to its own bucket (a join has two
    groups). ``aux_args`` is a pytree of arrays traced but never padded
    (e.g. a DFA transition table — its shape still keys the cache).
    ``statics`` must capture every non-array value ``fn`` closes over that
    affects the trace (schemas, agg lists, config-derived flags).

    ``fn(row_args, aux_args, row_valids)`` — ``row_valids`` is one
    bool[bucket] mask per group (True = real row), or None on the inline
    path. ``slice_rows`` trims bucket-sized leading dimensions of the
    output back to group 0's true row count. ``bucket_rows=False`` keeps
    exact shapes (pure executable memoization, no padding) for ops whose
    semantics cannot absorb padded rows.

    ``donate_rows=True`` is the caller's declaration that every
    ``row_args`` buffer is DEAD after this call (an intermediate table it
    owns, a decoded chunk nothing else reads): the executable compiles
    with ``donate_argnums`` on the row param so XLA reuses those buffers
    for outputs instead of double-buffering. The flag keys the cache, so
    donating and non-donating call sites never share an executable; bytes
    handed over are counted under ``dispatch.donated_bytes``. Note that
    when the row count already sits on a bucket boundary the "padded"
    tree aliases the caller's arrays, so the declaration genuinely
    invalidates them — never set this for caller-visible inputs.

    Never raises on its own behalf: every failure mode falls back to
    ``fn(row_args, aux_args, None)`` with the reason counted under
    ``dispatch.inline.<reason>``.
    """
    REGISTRY.counter("dispatch.calls").inc()
    enabled, _, _ = bucket_config()
    if not enabled:
        return _inline(op, "disabled", fn, row_args, aux_args)
    if _has_tracer((row_args, aux_args)):
        return _inline(op, "tracer", fn, row_args, aux_args)
    try:
        ns = tuple(_group_rows(g) for g in row_args)
    except Unbucketable:
        return _inline(op, "unbucketable", fn, row_args, aux_args)
    if any(n == 0 for n in ns):
        return _inline(op, "empty", fn, row_args, aux_args)

    buckets = tuple(bucket_for(n) for n in ns) if bucket_rows else ns
    acc = _PadStats()
    try:
        padded = tuple(
            _pad_tree(g, n, B, acc)
            for g, n, B in zip(row_args, ns, buckets))
    except Unbucketable:
        return _inline(op, "unbucketable", fn, row_args, aux_args)
    row_valids = tuple(
        jnp.arange(B, dtype=jnp.int32) < jnp.int32(n)
        for n, B in zip(ns, buckets))

    key = (op, statics, donate_rows, _kernels_digest(),
           _signature((padded, aux_args, row_valids)),
           jax.default_backend())
    compiled, lead_ev = _cache_lookup(key)
    if compiled is None:
        _init_persistent_cache()

        def _compile():
            faults.fire("dispatch.compile", 0, op=op)
            jitted = (jax.jit(fn, donate_argnums=(0,)) if donate_rows
                      else jax.jit(fn))
            with spans.child("dispatch.compile", op=op), \
                    warnings.catch_warnings():
                # backends without donation support (CPU) warn per
                # donated buffer at lowering; the declaration is still
                # honored where the platform implements it
                warnings.filterwarnings(
                    "ignore", message="Some donated buffers were not usable")
                return jitted.lower(padded, aux_args, row_valids).compile()

        # transient device faults retry under the shared policy; genuine
        # compile errors (non-transient) give up on attempt 1 and take the
        # host_fallback ladder rung below — dispatch still never raises
        # on its own behalf
        exc = None
        try:
            compiled, exc = resilience.retry_or_none(
                op, _compile, seam="dispatch.compile", rung="host_fallback")
        finally:
            # publish (or publish the failure) on EVERY leader exit path:
            # a waiter parked on this key must never hang
            _cache_store(key, compiled, lead_ev)
        if compiled is None:
            if exc is not None and not isinstance(exc, Exception):
                raise exc  # KeyboardInterrupt etc: not dispatch's to absorb
            REGISTRY.counter("dispatch.compile_error").inc()
            return _inline(op, "compile_error", fn, row_args, aux_args)
        REGISTRY.counter("dispatch.compile").inc()
        REGISTRY.counter(f"dispatch.compile.{op}").inc()
        record_compile_cache(f"dispatch:{op}", hit=False)
    else:
        REGISTRY.counter("dispatch.hit").inc()
        REGISTRY.counter(f"dispatch.hit.{op}").inc()
        record_compile_cache(f"dispatch:{op}", hit=True)

    def _execute():
        faults.fire("dispatch.execute", 0, op=op)
        # host-side only: the span closes when the dispatch RETURNS (jax
        # is async); it never forces a device sync
        with spans.child("dispatch.execute", op=op):
            return compiled(padded, aux_args, row_valids)

    out, exc = resilience.retry_or_none(
        op, _execute, seam="dispatch.execute", rung="host_fallback")
    if out is None and exc is not None:
        if not isinstance(exc, Exception):
            raise exc
        # aval drift (weak types, sharding changes) — never take the op down
        REGISTRY.counter("dispatch.exec_error").inc()
        return _inline(op, "exec_error", fn, row_args, aux_args)

    REGISTRY.counter("dispatch.padded_rows").inc(
        sum(B - n for n, B in zip(ns, buckets)))
    REGISTRY.counter("dispatch.padded_waste_bytes").inc(acc.padded_bytes)
    REGISTRY.counter("dispatch.row_bytes_total").inc(acc.total_bytes)
    if donate_rows:
        REGISTRY.counter("dispatch.donated_bytes").inc(acc.total_bytes)
    if slice_rows:
        out = _slice_tree(out, ns[0], buckets[0])
    return out


def rowwise(
    op: str,
    fn: Callable,
    group: Any,
    aux_args: tuple = (),
    *,
    statics: tuple = (),
    slice_rows: bool = True,
) -> Any:
    """``call`` for the common single-row-group op."""
    return call(op, fn, (group,), aux_args, statics=statics,
                slice_rows=slice_rows)


def sharded_call(
    op: str,
    build: Callable[[], Callable],
    args: tuple,
    statics: tuple = (),
) -> Any:
    """Executable memoization (no row bucketing) for a shard_map/jit
    boundary: ``build()`` returns the per-call closure (a fresh
    ``jax.shard_map(step, ...)`` wrapper is fine — identity does not key
    the cache, ``(op, statics, signature)`` does). The bucket-schedule
    config rides the key because shuffle capacities consume it at trace
    time. Falls back to a direct call on any lower/compile failure."""
    REGISTRY.counter("dispatch.calls").inc()
    cfg = bucket_config()
    if not cfg[0]:
        REGISTRY.counter("dispatch.inline").inc()
        REGISTRY.counter("dispatch.inline.disabled").inc()
        return build()(*args)
    if _has_tracer(args):
        REGISTRY.counter("dispatch.inline").inc()
        REGISTRY.counter("dispatch.inline.tracer").inc()
        return build()(*args)
    key = (op, ("sharded", cfg) + tuple(statics), _kernels_digest(),
           _signature(args), jax.default_backend())
    compiled, lead_ev = _cache_lookup(key)
    if compiled is None:
        _init_persistent_cache()

        def _compile():
            faults.fire("dispatch.compile", 0, op=op)
            with spans.child("dispatch.compile", op=op):
                return jax.jit(build()).lower(*args).compile()

        exc = None
        try:
            compiled, exc = resilience.retry_or_none(
                op, _compile, seam="dispatch.compile", rung="host_fallback")
        finally:
            _cache_store(key, compiled, lead_ev)
        if compiled is None:
            if exc is not None and not isinstance(exc, Exception):
                raise exc
            REGISTRY.counter("dispatch.compile_error").inc()
            REGISTRY.counter("dispatch.inline").inc()
            REGISTRY.counter("dispatch.inline.compile_error").inc()
            return build()(*args)
        REGISTRY.counter("dispatch.compile").inc()
        REGISTRY.counter(f"dispatch.compile.{op}").inc()
        record_compile_cache(f"dispatch:{op}", hit=False)
    else:
        REGISTRY.counter("dispatch.hit").inc()
        REGISTRY.counter(f"dispatch.hit.{op}").inc()
        record_compile_cache(f"dispatch:{op}", hit=True)

    def _execute():
        faults.fire("dispatch.execute", 0, op=op)
        with spans.child("dispatch.execute", op=op):
            return compiled(*args)

    out, exc = resilience.retry_or_none(
        op, _execute, seam="dispatch.execute", rung="host_fallback")
    if out is None and exc is not None:
        if not isinstance(exc, Exception):
            raise exc
        REGISTRY.counter("dispatch.exec_error").inc()
        REGISTRY.counter("dispatch.inline").inc()
        REGISTRY.counter("dispatch.inline.exec_error").inc()
        return build()(*args)
    return out


# ---------------------------------------------------------------------------
# introspection
# ---------------------------------------------------------------------------


def stats() -> dict:
    """Aggregate dispatch counters for the bench ``dispatch`` block."""
    c = REGISTRY.counters("dispatch.")
    compiles = c.get("dispatch.compile", 0)
    hits = c.get("dispatch.hit", 0)
    total_bytes = c.get("dispatch.row_bytes_total", 0)
    waste = c.get("dispatch.padded_waste_bytes", 0)
    return {
        "calls": c.get("dispatch.calls", 0),
        "compiles": compiles,
        "hits": hits,
        "hit_rate": hits / max(1, hits + compiles),
        "inline": c.get("dispatch.inline", 0),
        "padded_waste_bytes": waste,
        "padded_waste_frac": (waste / total_bytes) if total_bytes else 0.0,
        "donated_bytes": c.get("dispatch.donated_bytes", 0),
        "executables": cache_size(),
    }


def cache_size() -> int:
    with _lock:
        return len(_EXEC_CACHE)


def clear() -> None:
    """Drop memoized executables (test isolation). Telemetry counters are
    owned by the registry and are NOT reset here."""
    with _lock:
        _EXEC_CACHE.clear()
