"""Device-runtime bridge: the Python entry points `libtpudf_rt.so` calls.

This is the TPU analogue of the reference's JNI->libcudf call path
(reference RowConversionJni.cpp:24-41: JVM -> JNI -> cudf device code).
Architecture decision, per SURVEY.md section 7 "hard parts": instead of a
from-scratch PJRT C-API client, `libtpudf_rt.so` EMBEDS a CPython
interpreter that owns the JAX runtime — one interpreter per process, the
single-controller model XLA wants. Every JVM/C thread funnels through the
GIL into this module, which serializes device work exactly the way the
reference funnels all Spark task threads into one CUDA context (per-thread
default streams notwithstanding, pom.xml:80).

Handles held by the C side map to the objects these functions return
(Column / Table / RowsColumn). All host<->device marshalling crosses as
raw little-endian bytes, matching the Java side's HostMemoryBuffer
convention (reference ParquetFooter.java:82-95).
"""

from __future__ import annotations

import numpy as np

from spark_rapids_jni_tpu.columnar import Column, Table
from spark_rapids_jni_tpu.ops.row_conversion import (
    RowsColumn,
    convert_from_rows as _convert_from_rows,
    convert_to_rows as _convert_to_rows,
)
from spark_rapids_jni_tpu.types import DType, TypeId


def init_platform(platform: str) -> None:
    """Pin the backend before first device touch. "" = default (TPU when
    present); "cpu" = host-only (tests, machines without an accelerator)."""
    if platform == "cpu":
        from spark_rapids_jni_tpu.utils.platform import force_cpu_platform

        force_cpu_platform()
    import jax

    jax.devices()  # fail fast if the backend cannot initialize


def column_from_host(
    type_id: int, scale: int, n: int, data: bytes, validity: bytes | None
) -> Column:
    """Build a device column from little-endian host bytes. ``validity`` is
    one byte per row (0 = null), or None for all-valid."""
    dt = DType(TypeId(type_id), scale)
    vmask = None
    if validity is not None:
        vmask = np.frombuffer(validity, dtype=np.uint8, count=n).astype(bool)
    if dt.is_decimal128:
        # 16 LE bytes per row = the int64[n, 2] limb pair (lo, hi)
        # directly — the same image column_to_host emits and the row
        # codecs pack
        import jax.numpy as jnp

        limbs = np.frombuffer(data, dtype=np.int64,
                              count=2 * n).reshape(n, 2)
        return Column(dt, jnp.asarray(limbs.copy()),
                      None if vmask is None else jnp.asarray(vmask))
    arr = np.frombuffer(data, dtype=dt.storage_dtype, count=n)
    return Column.from_numpy(arr.copy(), dt, validity=vmask)


def table_create(cols: list[Column]) -> Table:
    return Table(list(cols))


def table_num_columns(table: Table) -> int:
    return table.num_columns


def table_num_rows(table: Table) -> int:
    return table.num_rows


def table_column(table: Table, i: int) -> Column:
    return table.column(i)


def column_info(col: Column) -> tuple[int, int, int]:
    return int(col.dtype.type_id), col.dtype.scale, col.size


def column_to_host(col: Column) -> tuple[bytes, bytes]:
    """Device column -> (data bytes, one-byte-per-row validity)."""
    data, mask = col.to_numpy()
    if mask is None:
        mask = np.ones(col.size, dtype=bool)
    return data.tobytes(), mask.astype(np.uint8).tobytes()


def convert_to_rows(table: Table) -> list[RowsColumn]:
    return _convert_to_rows(table)


def convert_from_rows(
    rows: RowsColumn, type_ids: list[int], scales: list[int]
) -> Table:
    schema = [DType(TypeId(t), s) for t, s in zip(type_ids, scales)]
    return _convert_from_rows(rows, schema)


def rows_info(rows: RowsColumn) -> tuple[int, int]:
    return rows.num_rows, rows.row_size


def rows_to_host(rows: RowsColumn) -> bytes:
    return np.asarray(rows.data).tobytes()


def rows_from_host(num_rows: int, row_size: int, data: bytes) -> RowsColumn:
    import jax.numpy as jnp

    arr = np.frombuffer(data, dtype=np.uint8, count=num_rows * row_size)
    return RowsColumn(num_rows, row_size, jnp.asarray(arr.copy()))
